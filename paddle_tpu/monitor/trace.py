"""Chrome-trace-event JSON exporter (reference platform/profiler.cc
GenEventKernelCudaElapsedTime / DeviceTracer dump → chrome://tracing).

`TraceWriter` accumulates trace events host-side and serializes the
chrome trace-event format (the `{"traceEvents": [...]}` envelope) that
Perfetto / chrome://tracing / `tools/trace_report.py` load directly —
independent of jax.profiler's TensorBoard plugin, so it works on any
backend.

The module-level writer plus the `TRACING` gate are the recording
switch the hot paths check: `apply_op` and `RecordEvent` test
``TRACING[0]`` (one list index) before paying for any span bookkeeping,
so an idle process records nothing and allocates nothing.

Timestamps are `time.perf_counter()` seconds converted to the format's
microseconds — one monotonic clock for every producer keeps spans from
different layers aligned on the same timeline.
"""
from __future__ import annotations

import contextlib
import json
import os
import threading
import time

__all__ = ["TraceWriter", "TRACING", "FLIGHT", "is_tracing",
           "start_tracing", "stop_tracing", "get_writer", "span",
           "recording", "emit_complete", "emit_instant", "emit_flow"]

# shared mutable gate — hot paths read TRACING[0] directly
TRACING = [False]

# the armed flight recorder (monitor/flight.py) or None — a second
# consumer of span/instant events that stays on across a failure so the
# last seconds before a crash are dumpable even when full tracing is off.
# Kept here (not in flight.py) so span() pays ONE extra list index when
# nothing is armed and flight.py can import without a cycle.
FLIGHT = [None]


class TraceWriter:
    """Thread-safe collector of chrome trace events."""

    def __init__(self, pid: int | None = None):
        self.pid = os.getpid() if pid is None else pid
        self._events: list[dict] = []
        self._lock = threading.Lock()

    # -- event constructors -------------------------------------------------
    def add_complete(self, name: str, ts: float, dur: float,
                     tid: int | None = None, cat: str = "op",
                     args: dict | None = None) -> None:
        """One "X" (complete) event; ts/dur in seconds on the perf_counter
        timeline."""
        ev = {
            "name": name, "ph": "X", "cat": cat, "pid": self.pid,
            "tid": threading.get_ident() & 0x7FFFFFFF if tid is None else tid,
            "ts": int(ts * 1e6), "dur": int(dur * 1e6),
        }
        if args:
            ev["args"] = args
        with self._lock:
            self._events.append(ev)

    def add_begin(self, name: str, ts: float, tid: int | None = None,
                  cat: str = "op") -> None:
        self._add_mark("B", name, ts, tid, cat)

    def add_end(self, name: str, ts: float, tid: int | None = None,
                cat: str = "op") -> None:
        self._add_mark("E", name, ts, tid, cat)

    def add_instant(self, name: str, ts: float, cat: str = "instant") -> None:
        self._add_mark("i", name, ts, None, cat)

    def _add_mark(self, ph, name, ts, tid, cat):
        with self._lock:
            self._events.append({
                "name": name, "ph": ph, "cat": cat, "pid": self.pid,
                "tid": threading.get_ident() & 0x7FFFFFFF if tid is None
                else tid,
                "ts": int(ts * 1e6),
            })

    def add_counter(self, name: str, ts: float, values: dict) -> None:
        """One "C" (counter) event — e.g. the stat gauges over time."""
        with self._lock:
            self._events.append({
                "name": name, "ph": "C", "pid": self.pid, "tid": 0,
                "ts": int(ts * 1e6), "args": dict(values),
            })

    def add_flow(self, ph: str, flow_id: int, ts: float,
                 name: str = "request", cat: str = "trace") -> None:
        """One flow event ("s" start / "t" step / "f" finish) with
        ``id=flow_id``. Chrome/Perfetto draw an arrow chain through every
        flow event sharing an id, binding each to the enclosing slice on
        its thread — that chain is what turns per-layer spans into ONE
        connected per-request timeline (ISSUE 15 causal tracing)."""
        ev = {
            "name": name, "ph": ph, "cat": cat, "pid": self.pid,
            "tid": threading.get_ident() & 0x7FFFFFFF,
            "ts": int(ts * 1e6), "id": int(flow_id),
        }
        if ph == "f":
            ev["bp"] = "e"      # bind the finish to the enclosing slice
        with self._lock:
            self._events.append(ev)

    def extend(self, events) -> None:
        with self._lock:
            self._events.extend(events)

    # -- access / export ----------------------------------------------------
    def events(self) -> list:
        with self._lock:
            return list(self._events)

    def __len__(self):
        with self._lock:
            return len(self._events)

    def clear(self) -> None:
        with self._lock:
            self._events.clear()

    def to_json(self) -> str:
        return json.dumps({"traceEvents": self.events(),
                           "displayTimeUnit": "ms"})

    def write(self, path: str) -> str:
        d = os.path.dirname(os.path.abspath(path))
        if d:
            os.makedirs(d, exist_ok=True)
        with open(path, "w") as f:
            f.write(self.to_json())
        return path


_writer = TraceWriter()


def get_writer() -> TraceWriter:
    return _writer


def is_tracing() -> bool:
    return TRACING[0]


def start_tracing(clear: bool = True) -> TraceWriter:
    if clear:
        _writer.clear()
    TRACING[0] = True
    return _writer


def stop_tracing() -> TraceWriter:
    TRACING[0] = False
    return _writer


def recording() -> bool:
    """True when anything consumes events: full tracing OR an armed
    flight recorder. Hot paths that pre-compute span args should gate on
    this rather than ``TRACING[0]`` alone."""
    return TRACING[0] or FLIGHT[0] is not None


def emit_complete(name: str, ts: float, dur: float, cat: str = "op",
                  args: dict | None = None) -> None:
    """One complete event to every live consumer (trace writer when
    tracing, flight-recorder ring when armed)."""
    if TRACING[0]:
        _writer.add_complete(name, ts, dur, cat=cat, args=args)
    rec = FLIGHT[0]
    if rec is not None:
        rec.add_complete(name, ts, dur, cat=cat, args=args)


def emit_instant(name: str, ts: float, cat: str = "instant") -> None:
    if TRACING[0]:
        _writer.add_instant(name, ts, cat=cat)
    rec = FLIGHT[0]
    if rec is not None:
        rec.add_instant(name, ts, cat=cat)


def emit_flow(ph: str, flow_id: int, ts: float,
              name: str = "request") -> None:
    if TRACING[0]:
        _writer.add_flow(ph, flow_id, ts, name=name)
    rec = FLIGHT[0]
    if rec is not None:
        rec.add_flow(ph, flow_id, ts, name=name)


@contextlib.contextmanager
def span(name: str, cat: str = "op", args: dict | None = None,
         flow: int | None = None):
    """Record a span around a block — free when tracing is off (one list
    index) and the flight recorder is unarmed (a second list index).

    ``flow``: a trace/flow id to stamp a flow STEP event at span start,
    chaining this span into its request's causal timeline."""
    if not TRACING[0] and FLIGHT[0] is None:
        yield
        return
    t0 = time.perf_counter()
    if flow is not None:
        # flow events keep the constant "request" name: name-based event
        # filters (reports, tests) must see only the real span under the
        # span's name
        emit_flow("t", flow, t0)
    try:
        yield
    finally:
        emit_complete(name, t0, time.perf_counter() - t0,
                      cat=cat, args=args)
