"""Profiler (reference paddle/fluid/platform/profiler.h RecordEvent,
python/paddle/fluid/profiler.py, python/paddle/profiler).

TPU-native: three cooperating layers —
- ``RecordEvent`` named scopes feed (a) the host summary table, (b) the
  paddle_tpu.monitor chrome-trace writer when tracing is on, and (c)
  jax.profiler.TraceAnnotation so the spans also appear inside an XLA
  TensorBoard trace when one is being captured;
- ``start_profiler``/``stop_profiler``/``Profiler`` drive collection and
  write a Perfetto/chrome://tracing-loadable JSON via
  monitor.trace.TraceWriter — independent of jax.profiler, so trace
  export works on any backend;
- ``jax.profiler.start_trace`` (TensorBoard/XLA timeline) is opt-in and
  failure-tolerant: where the plugin is unavailable the chrome-trace file
  is still produced.
"""
from __future__ import annotations

import contextlib
import os
import time
from collections import defaultdict

import jax

from ..monitor import trace as _mtrace

__all__ = ["RecordEvent", "profiler", "start_profiler", "stop_profiler",
           "Profiler", "summary", "reset_profiler", "cuda_profiler", "npu_profiler",
]

_events = defaultdict(list)
_active = [False]
_trace_dir = [None]

_SORT_KEYS = ("total", "calls", "avg", "max", "min")


class RecordEvent:
    """RAII scope timer + device trace annotation."""

    def __init__(self, name, event_type="op"):
        self.name = name
        self._ann = None
        self._t0 = None
        self._native_cm = None

    def __enter__(self):
        self._t0 = time.perf_counter()
        try:
            self._ann = jax.profiler.TraceAnnotation(self.name)
            self._ann.__enter__()
        except Exception:
            self._ann = None
        # host-event recorder (native when built, py-fallback otherwise)
        try:
            from ..core import record_event as _record_event
            self._native_cm = _record_event(self.name)
            self._native_cm.__enter__()
        except Exception:
            self._native_cm = None
        return self

    def __exit__(self, *exc):
        if self._ann is not None:
            self._ann.__exit__(*exc)
        if self._native_cm is not None:
            self._native_cm.__exit__(*exc)
            self._native_cm = None
        dur = time.perf_counter() - self._t0
        if _active[0]:
            _events[self.name].append(dur)
        if _mtrace.TRACING[0]:
            _mtrace.get_writer().add_complete(self.name, self._t0, dur,
                                              cat="record_event")
        return False

    def begin(self):
        self.__enter__()

    def end(self):
        self.__exit__(None, None, None)


# -- jax trace, guarded (plugin may be unavailable / already running) -------

_jax_tracing = [False]


def _try_start_jax_trace(trace_dir) -> bool:
    if _jax_tracing[0] or not trace_dir:
        return False
    try:
        jax.profiler.start_trace(trace_dir)
        _jax_tracing[0] = True
        return True
    except Exception:
        return False


def _try_stop_jax_trace() -> None:
    if not _jax_tracing[0]:
        return
    try:
        jax.profiler.stop_trace()
    except Exception:
        pass
    _jax_tracing[0] = False


def start_profiler(state="All", tracer_option="Default", trace_dir=None,
                   use_jax_trace=True):
    _active[0] = True
    _events.clear()
    _mtrace.start_tracing()
    if trace_dir:
        _trace_dir[0] = trace_dir
        if use_jax_trace:
            _try_start_jax_trace(trace_dir)


def stop_profiler(sorted_key="total", profile_path=None):
    """Stop collection; print the summary table; when a trace dir was
    given, write the chrome-trace JSON there; when ``profile_path`` is
    given, write the summary table to that file (reference
    fluid/profiler.py stop_profiler semantics)."""
    _active[0] = False
    writer = _mtrace.stop_tracing()
    if _trace_dir[0]:
        writer.write(os.path.join(_trace_dir[0], "paddle_tpu_trace.json"))
        _try_stop_jax_trace()
        _trace_dir[0] = None
    rows = summary(sorted_key)
    if profile_path:
        d = os.path.dirname(os.path.abspath(profile_path))
        if d:
            os.makedirs(d, exist_ok=True)
        with open(profile_path, "w") as f:
            summary(sorted_key, file=f)
    return rows


def summary(sorted_key="total", file=None):
    """Aggregate RecordEvent timings; sort by ``sorted_key`` in
    total|calls|avg|max|min (reference fluid/profiler.py sorted_key),
    print the table to ``file`` (stdout by default), return the rows."""
    if sorted_key not in _SORT_KEYS:
        raise ValueError(
            f"summary: sorted_key must be one of {_SORT_KEYS}, "
            f"got {sorted_key!r}")
    rows = []
    for name, times in _events.items():
        rows.append({
            "name": name, "calls": len(times), "total": sum(times),
            "avg": sum(times) / len(times), "max": max(times), "min": min(times),
        })
    rows.sort(key=lambda r: -r[sorted_key])
    if rows:
        print(f"{'Event':<40}{'Calls':>8}{'Total(s)':>12}{'Avg(s)':>12}"
              f"{'Max(s)':>12}{'Min(s)':>12}", file=file)
        for r in rows:
            print(f"{r['name']:<40}{r['calls']:>8}{r['total']:>12.6f}"
                  f"{r['avg']:>12.6f}{r['max']:>12.6f}{r['min']:>12.6f}",
                  file=file)
    return rows


@contextlib.contextmanager
def profiler(state="All", sorted_key="total", profile_path="/tmp/profile", tracer_option="Default"):
    start_profiler(state, tracer_option)
    try:
        yield
    finally:
        stop_profiler(sorted_key, profile_path)


class Profiler:
    """paddle.profiler.Profiler-style API.

    - ``scheduler``: ``(wait, warmup, active)`` ints, or a callable
      ``step -> "wait"|"warmup"|"active"``; None records the whole
      start..stop window. During *wait* nothing is recorded; *warmup*
      records but its spans are discarded when the *active* window opens;
      after the last *active* step the trace is flushed: written under
      ``trace_dir`` and handed to ``on_trace_ready(self)``.
    - ``on_trace_ready``: callable(profiler) invoked at each flush;
      ``self.last_trace_path`` holds the file just written.
    - ``use_jax_trace``: also drive jax.profiler.start_trace for the XLA
      TensorBoard timeline (best-effort; the chrome-trace JSON is
      produced regardless).
    """

    def __init__(self, targets=None, scheduler=None, on_trace_ready=None,
                 trace_dir="/tmp/paddle_tpu_trace", timer_only=False,
                 use_jax_trace=False):
        self.trace_dir = trace_dir
        self.on_trace_ready = on_trace_ready
        self.timer_only = timer_only
        self.use_jax_trace = use_jax_trace
        self.last_trace_path = None
        if scheduler is None or callable(scheduler):
            self._sched = scheduler
        else:
            w, u, a = (int(x) for x in scheduler)
            if a <= 0:
                raise ValueError("scheduler active window must be positive")
            self._sched = self._make_window_fn(w, u, a)
        self._step_num = 0
        self._cycle_idx = 0
        self._recording = False

    @staticmethod
    def _make_window_fn(wait, warmup, active):
        cycle = wait + warmup + active

        def phase(step):
            pos = step % cycle
            if pos < wait:
                return "wait"
            if pos < wait + warmup:
                return "warmup"
            return "active"

        return phase

    def _phase(self, step):
        return self._sched(step) if self._sched is not None else "active"

    # -- lifecycle ----------------------------------------------------------
    def start(self):
        self._step_num = 0
        self._cycle_idx = 0
        self._recording = False
        _active[0] = True
        _events.clear()
        self._apply_phase(self._phase(0), prev=None)
        if self.use_jax_trace and not self.timer_only:
            _try_start_jax_trace(self.trace_dir)

    def step(self):
        prev = self._phase(self._step_num)
        self._step_num += 1
        cur = self._phase(self._step_num)
        if self._sched is not None and prev == "active" and cur != "active":
            self._flush()
        self._apply_phase(cur, prev)

    def stop(self):
        if self._recording and (self._sched is None
                                or self._phase(self._step_num) == "active"):
            self._flush()
        _mtrace.stop_tracing()
        self._recording = False
        _active[0] = False
        _try_stop_jax_trace()

    def _apply_phase(self, phase, prev):
        if phase == "wait":
            if self._recording:
                _mtrace.stop_tracing()
                self._recording = False
            return
        if not self._recording:
            _mtrace.start_tracing()
            self._recording = True
        if phase == "active" and prev == "warmup":
            # warmup spans exist only to stabilize caches — drop them
            _mtrace.get_writer().clear()

    def _flush(self):
        writer = _mtrace.get_writer()
        if self.trace_dir and not self.timer_only:
            name = (f"paddle_tpu_trace_{self._cycle_idx}.json"
                    if self._sched is not None else "paddle_tpu_trace.json")
            self.last_trace_path = writer.write(
                os.path.join(self.trace_dir, name))
        if self.on_trace_ready is not None:
            self.on_trace_ready(self)
        writer.clear()
        self._cycle_idx += 1

    def summary(self, sorted_key="total"):
        return summary(sorted_key)

    def __enter__(self):
        self.start()
        return self

    def __exit__(self, *exc):
        self.stop()
        return False


def reset_profiler():
    """Clear accumulated events without changing the collection state
    (reference fluid/profiler.py:168)."""
    from ..core import native

    native_reset = getattr(native, "profiler_reset", None)
    if native_reset is not None:
        native_reset()
    _events.clear()


@contextlib.contextmanager
def cuda_profiler(output_file=None, output_mode=None, config=None):
    """Reference fluid/profiler.py:39 wraps nvprof; the TPU analog is the
    host profiler already driven by start/stop_profiler, so this is a
    documented alias for porting scripts."""
    start_profiler()
    try:
        yield
    finally:
        stop_profiler()


npu_profiler = cuda_profiler
