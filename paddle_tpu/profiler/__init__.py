"""Profiler (reference paddle/fluid/platform/profiler.h RecordEvent,
python/paddle/fluid/profiler.py).

TPU-native: jax.profiler emits TensorBoard/perfetto traces (the
chrome-trace analog); RecordEvent maps to jax.profiler.TraceAnnotation named
scopes which show up inside the XLA trace timeline.
"""
from __future__ import annotations

import contextlib
import time
from collections import defaultdict

import jax

__all__ = ["RecordEvent", "profiler", "start_profiler", "stop_profiler",
           "Profiler", "summary", "reset_profiler", "cuda_profiler", "npu_profiler",
]

_events = defaultdict(list)
_active = [False]
_trace_dir = [None]


class RecordEvent:
    """RAII scope timer + device trace annotation."""

    def __init__(self, name, event_type="op"):
        self.name = name
        self._ann = None
        self._t0 = None
        self._native_cm = None

    def __enter__(self):
        self._t0 = time.perf_counter()
        try:
            self._ann = jax.profiler.TraceAnnotation(self.name)
            self._ann.__enter__()
        except Exception:
            self._ann = None
        # host-event recorder (native when built, py-fallback otherwise)
        try:
            from ..core import record_event as _record_event
            self._native_cm = _record_event(self.name)
            self._native_cm.__enter__()
        except Exception:
            self._native_cm = None
        return self

    def __exit__(self, *exc):
        if self._ann is not None:
            self._ann.__exit__(*exc)
        if self._native_cm is not None:
            self._native_cm.__exit__(*exc)
            self._native_cm = None
        if _active[0]:
            _events[self.name].append(time.perf_counter() - self._t0)
        return False

    def begin(self):
        self.__enter__()

    def end(self):
        self.__exit__(None, None, None)


def start_profiler(state="All", tracer_option="Default", trace_dir=None):
    _active[0] = True
    _events.clear()
    if trace_dir:
        _trace_dir[0] = trace_dir
        jax.profiler.start_trace(trace_dir)


def stop_profiler(sorted_key="total", profile_path=None):
    _active[0] = False
    if _trace_dir[0]:
        jax.profiler.stop_trace()
        _trace_dir[0] = None
    return summary(sorted_key)


def summary(sorted_key="total"):
    rows = []
    for name, times in _events.items():
        rows.append({
            "name": name, "calls": len(times), "total": sum(times),
            "avg": sum(times) / len(times), "max": max(times), "min": min(times),
        })
    rows.sort(key=lambda r: -r["total"])
    if rows:
        print(f"{'Event':<40}{'Calls':>8}{'Total(s)':>12}{'Avg(s)':>12}")
        for r in rows:
            print(f"{r['name']:<40}{r['calls']:>8}{r['total']:>12.6f}{r['avg']:>12.6f}")
    return rows


@contextlib.contextmanager
def profiler(state="All", sorted_key="total", profile_path="/tmp/profile", tracer_option="Default"):
    start_profiler(state, tracer_option)
    try:
        yield
    finally:
        stop_profiler(sorted_key, profile_path)


class Profiler:
    """paddle.profiler.Profiler-style API over jax.profiler."""

    def __init__(self, targets=None, scheduler=None, on_trace_ready=None, trace_dir="/tmp/paddle_tpu_trace"):
        self.trace_dir = trace_dir

    def start(self):
        start_profiler(trace_dir=self.trace_dir)

    def stop(self):
        stop_profiler()

    def step(self):
        pass

    def __enter__(self):
        self.start()
        return self

    def __exit__(self, *exc):
        self.stop()
        return False


def reset_profiler():
    """Clear accumulated events without changing the collection state
    (reference fluid/profiler.py:168)."""
    from ..core import native

    native_reset = getattr(native, "profiler_reset", None)
    if native_reset is not None:
        native_reset()
    _events.clear()


def cuda_profiler(output_file=None, output_mode=None, config=None):
    """Reference fluid/profiler.py:39 wraps nvprof; the TPU analog is the
    jax profiler trace already driven by start/stop_profiler, so this is a
    documented alias for porting scripts."""
    import contextlib

    @contextlib.contextmanager
    def _ctx():
        start_profiler()
        try:
            yield
        finally:
            stop_profiler()

    return _ctx()


npu_profiler = cuda_profiler
