"""Incubate fused operators (reference python/paddle/incubate/operators/):
softmax_mask_fuse, softmax_mask_fuse_upper_triangle — XLA fuses the mask
+ softmax into one kernel, so these are thin compositions, kept for API
parity with the reference's hand-fused CUDA ops
(operators/fused_softmax_mask_op.cu)."""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..framework.core import apply_op

__all__ = ["softmax_mask_fuse", "softmax_mask_fuse_bool",
           "softmax_mask_fuse_upper_triangle"]

_NEG = -1e30


def _mask_softmax(x, mask):
    # Additive mask, matching the reference
    # (incubate/operators/softmax_mask_fuse.py): callers pass 0 at kept
    # positions and a large negative value (e.g. -10000) at masked ones.
    s = x.astype(jnp.float32) + mask.astype(jnp.float32)
    return jax.nn.softmax(s, axis=-1).astype(x.dtype)


def _tri_softmax(x):
    q, k = x.shape[-2], x.shape[-1]
    tri = jnp.tril(jnp.ones((q, k), bool), k=k - q)
    s = jnp.where(tri, x.astype(jnp.float32), _NEG)
    return jax.nn.softmax(s, axis=-1).astype(x.dtype)


def softmax_mask_fuse(x, mask, name=None):
    """softmax(x + mask) over the last dim (additive mask, reference
    semantics: masked positions carry a large negative mask value)."""
    return apply_op(_mask_softmax, x, mask)


def _bool_mask_softmax(x, mask):
    s = jnp.where(mask.astype(bool), _NEG, x.astype(jnp.float32))
    return jax.nn.softmax(s, axis=-1).astype(x.dtype)


def softmax_mask_fuse_bool(x, mask, name=None):
    """Boolean-mask variant: mask 1/True = masked out (no reference
    counterpart; kept because it is the common jax calling convention)."""
    return apply_op(_bool_mask_softmax, x, mask)


def softmax_mask_fuse_upper_triangle(x, name=None):
    """Causal-masked softmax over the last dim (upper triangle masked)."""
    return apply_op(_tri_softmax, x)
