"""Incubate fused operators (reference python/paddle/incubate/operators/):
softmax_mask_fuse, softmax_mask_fuse_upper_triangle — XLA fuses the mask
+ softmax into one kernel, so these are thin compositions, kept for API
parity with the reference's hand-fused CUDA ops
(operators/fused_softmax_mask_op.cu)."""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..framework.core import apply_op

__all__ = ["softmax_mask_fuse", "softmax_mask_fuse_upper_triangle"]

_NEG = -1e30


def _mask_softmax(x, mask):
    s = x.astype(jnp.float32) + mask.astype(jnp.float32) * _NEG
    return jax.nn.softmax(s, axis=-1).astype(x.dtype)


def _tri_softmax(x):
    q, k = x.shape[-2], x.shape[-1]
    tri = jnp.tril(jnp.ones((q, k), bool), k=k - q)
    s = jnp.where(tri, x.astype(jnp.float32), _NEG)
    return jax.nn.softmax(s, axis=-1).astype(x.dtype)


def softmax_mask_fuse(x, mask, name=None):
    """softmax(x + mask*-inf) over the last dim; mask 1 = masked out."""
    return apply_op(_mask_softmax, x, mask)


def softmax_mask_fuse_upper_triangle(x, name=None):
    """Causal-masked softmax over the last dim (upper triangle masked)."""
    return apply_op(_tri_softmax, x)
