"""paddle.incubate parity surface (reference
python/paddle/incubate/__init__.py:17-27): experimental optimizers and
fused operators exported at the top level.
"""
from .optimizer import LookAhead, ModelAverage
from .operators import (
    softmax_mask_fuse,
    softmax_mask_fuse_bool,
    softmax_mask_fuse_upper_triangle,
)
from .tensor import segment_sum, segment_mean, segment_max, segment_min
from . import asp, operators, optimizer, tensor

__all__ = [
    "LookAhead",
    "ModelAverage",
    "softmax_mask_fuse",
    "softmax_mask_fuse_bool",
    "softmax_mask_fuse_upper_triangle",
    "segment_sum",
    "segment_mean",
    "segment_max",
    "segment_min",
]
