"""ASP — automatic structured (2:4) sparsity.

Parity: reference python/paddle/fluid/contrib/sparsity/ (calculate_mask
via MaskAlgo_MASK_2D/1D, prune_model, ASPHelper.decorate wrapping the
optimizer so masks are re-applied after every step) and fleet
asp_optimizer.py.

TPU-native: the mask computation is one vectorized jnp top-2-of-4 over the
reduction dim (no per-block python loops), masks live as buffers next to
the weights, and ``decorate`` wraps the optimizer's step with a masked
re-projection — the same semantics as the reference's
ASPHelper._insert_sparse_mask_ops.
"""
from __future__ import annotations

import weakref
from typing import Dict, List, Tuple

import jax.numpy as jnp
import numpy as np

from ..framework.core import Tensor
from ..nn.layer.layers import Layer

__all__ = ["calculate_mask", "check_sparsity", "prune_model", "decorate",
           "ASPHelper"]


def calculate_mask(weight, n=2, m=4):
    """n:m sparsity mask along the LAST dim (keep the n largest |w| in
    every group of m). Returns a 0/1 mask of weight's shape."""
    arr = weight._data if isinstance(weight, Tensor) else jnp.asarray(weight)
    if arr.shape[-1] % m != 0:
        raise ValueError(f"last dim {arr.shape[-1]} not divisible by m={m}")
    g = arr.reshape(arr.shape[:-1] + (arr.shape[-1] // m, m))
    # rank within each group; keep the top-n magnitudes
    order = jnp.argsort(jnp.abs(g), axis=-1)
    ranks = jnp.argsort(order, axis=-1)          # 0 = smallest
    mask = (ranks >= m - n).astype(arr.dtype)
    return mask.reshape(arr.shape)


def check_sparsity(weight, n=2, m=4) -> bool:
    """True if every m-group has at most n non-zeros."""
    arr = np.asarray(weight._data if isinstance(weight, Tensor) else weight)
    g = arr.reshape(arr.shape[:-1] + (arr.shape[-1] // m, m))
    return bool((np.count_nonzero(g, axis=-1) <= n).all())


def _prunable(model: Layer):
    for name, p in model.named_parameters():
        # weights of Linear-like layers: 2D with both dims >= 4 (reference
        # ASPHelper._is_supported_layer covers fc/linear/conv weights)
        if p.stop_gradient or len(p._data.shape) != 2:
            continue
        if p._data.shape[-1] % 4 != 0:
            continue
        yield name, p


class ASPHelper:
    # id(param) -> (weakref to the param, mask). The weakref does double
    # duty: its callback drops the entry when the param dies, and lookups
    # validate identity — a raw id() key alone can ALIAS a dead param's
    # recycled id to an unrelated new parameter (CPython reuses ids), which
    # would silently mask a never-pruned weight.
    _masks: Dict[int, Tuple[weakref.ref, jnp.ndarray]] = {}

    @classmethod
    def prune_model(cls, model: Layer, n=2, m=4):
        """Apply n:m masks to every prunable weight; masks are remembered
        for re-application by the decorated optimizer."""
        pruned = []
        for name, p in _prunable(model):
            mask = calculate_mask(p, n, m)
            p._data = p._data * mask
            key = id(p)
            cls._masks[key] = (
                weakref.ref(p, lambda _, k=key: cls._masks.pop(k, None)),
                mask)
            pruned.append(name)
        return pruned

    @classmethod
    def mask_for(cls, p):
        """The mask pruned onto THIS parameter object, else None."""
        entry = cls._masks.get(id(p))
        if entry is not None and entry[0]() is p:
            return entry[1]
        return None

    @classmethod
    def reapply(cls, params):
        for p in params:
            mask = cls.mask_for(p)
            if mask is not None:
                p._data = p._data * mask


def prune_model(model, n=2, m=4, mask_algo="mask_1d", with_mask=True):
    return ASPHelper.prune_model(model, n, m)


class _ASPOptimizer:
    """Optimizer wrapper re-applying masks after each step (reference
    ASPHelper decorate / fleet asp_optimizer)."""

    def __init__(self, inner):
        self._inner_opt = inner

    def step(self):
        self._inner_opt.step()
        ASPHelper.reapply(self._inner_opt._parameter_list or [])

    def clear_grad(self):
        self._inner_opt.clear_grad()

    clear_gradients = clear_grad

    def minimize(self, loss, startup_program=None, parameters=None,
                 no_grad_set=None):
        from ..framework.core import backward

        backward(loss)
        self.step()
        return None, []

    def __getattr__(self, item):
        return getattr(self._inner_opt, item)


def decorate(optimizer):
    return _ASPOptimizer(optimizer)
