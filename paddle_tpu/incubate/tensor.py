"""Incubate segment ops (reference python/paddle/incubate/tensor/math.py)
over jax.ops.segment_* — XLA lowers to sorted-segment reductions."""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..framework.core import apply_op

__all__ = ["segment_sum", "segment_mean", "segment_max", "segment_min"]


def _seg(data, ids, reduction, num):
    fn = {"sum": jax.ops.segment_sum, "max": jax.ops.segment_max,
          "min": jax.ops.segment_min}.get(reduction)
    if reduction == "mean":
        s = jax.ops.segment_sum(data, ids, num_segments=num)
        c = jax.ops.segment_sum(jnp.ones_like(data), ids, num_segments=num)
        return s / jnp.maximum(c, 1)
    return fn(data, ids, num_segments=num)


def _num_segments(segment_ids):
    # Segment count is a static shape parameter: resolve on host before
    # tracing (int() on a traced array would fail inside jax.vjp).
    arr = getattr(segment_ids, "_data", segment_ids)
    return int(jnp.max(arr)) + 1 if arr.size else 0


def segment_sum(data, segment_ids, name=None):
    return apply_op(_seg, data, segment_ids, reduction="sum",
                    num=_num_segments(segment_ids))


def segment_mean(data, segment_ids, name=None):
    return apply_op(_seg, data, segment_ids, reduction="mean",
                    num=_num_segments(segment_ids))


def segment_max(data, segment_ids, name=None):
    return apply_op(_seg, data, segment_ids, reduction="max",
                    num=_num_segments(segment_ids))


def segment_min(data, segment_ids, name=None):
    return apply_op(_seg, data, segment_ids, reduction="min",
                    num=_num_segments(segment_ids))
