"""Incubate optimizers: LookAhead, ModelAverage.

Parity: reference python/paddle/incubate/optimizer/{lookahead.py,
modelaverage.py} (and fluid LookaheadOptimizer, fluid/optimizer.py:6610).
TPU-native: both are wrappers over the inner optimizer's eager step; the
slow-weight / averaging math is a jitted pure update over each param.
"""
from __future__ import annotations

import jax.numpy as jnp

from ..framework.core import Tensor
from ..optimizer.optimizer import Optimizer

__all__ = ["LookAhead", "ModelAverage"]


class LookAhead(Optimizer):
    """Lookahead (https://arxiv.org/abs/1907.08610): the inner optimizer
    updates fast weights every step; every k steps the slow weights catch
    up: slow += alpha * (fast - slow); fast = slow."""

    def __init__(self, inner_optimizer, alpha=0.5, k=5, name=None):
        if inner_optimizer is None:
            raise ValueError("inner optimizer can not be None")
        if not 0.0 <= alpha <= 1.0:
            raise ValueError("alpha should be in [0, 1]")
        if not (isinstance(k, int) and k > 0):
            raise ValueError("k should be a positive integer")
        self.inner_optimizer = inner_optimizer
        self.alpha = float(alpha)
        self.k = int(k)
        self._k_count = 0
        self._slow = {}  # id(param) -> jnp array
        self._params = inner_optimizer._parameter_list or []
        self._name = name

    @property
    def _parameter_list(self):
        return self.inner_optimizer._parameter_list

    def get_lr(self):
        return self.inner_optimizer.get_lr()

    def step(self):
        for p in self._params:
            if id(p) not in self._slow:
                self._slow[id(p)] = p._data
        self.inner_optimizer.step()
        self._k_count += 1
        if self._k_count % self.k == 0:
            a = self.alpha
            for p in self._params:
                slow = self._slow[id(p)]
                slow = slow + a * (p._data - slow)
                self._slow[id(p)] = slow
                p._data = slow

    def clear_grad(self):
        self.inner_optimizer.clear_grad()

    def state_dict(self):
        sd = {"k_count": self._k_count}
        sd["inner"] = self.inner_optimizer.state_dict()
        return sd

    def set_state_dict(self, sd):
        self._k_count = int(sd.get("k_count", 0))
        if "inner" in sd:
            self.inner_optimizer.set_state_dict(sd["inner"])

    def minimize(self, loss, startup_program=None, parameters=None,
                 no_grad_set=None):
        from ..framework.core import backward

        backward(loss)
        self.step()
        return None, []


class ModelAverage(Optimizer):
    """Running average of parameters over a trailing window
    (reference incubate/optimizer/modelaverage.py): accumulates param sums;
    ``apply()`` swaps averaged weights in (optionally within a context),
    ``restore()`` swaps training weights back."""

    def __init__(self, average_window_rate, parameters=None,
                 min_average_window=10000, max_average_window=10000,
                 name=None):
        self.average_window = float(average_window_rate)
        self.min_average_window = int(min_average_window)
        self.max_average_window = int(max_average_window)
        self._params = list(parameters) if parameters is not None else []
        # per-param: sum_1 (current window), sum_2 (previous windows),
        # num_accumulates, old_num_accumulates, num_updates
        self._state = {}
        self._backup = {}
        self._name = name

    def _st(self, p):
        st = self._state.get(id(p))
        if st is None:
            z = jnp.zeros_like(p._data)
            st = {"sum_1": z, "sum_2": z, "num_acc": 0, "old_num_acc": 0,
                  "num_upd": 0}
            self._state[id(p)] = st
        return st

    def step(self):
        """Accumulate after the inner training step (call each iteration)."""
        for p in self._params:
            st = self._st(p)
            st["sum_1"] = st["sum_1"] + p._data
            st["num_acc"] += 1
            st["num_upd"] += 1
            window = min(self.max_average_window,
                         max(self.min_average_window,
                             int(st["num_upd"] * self.average_window)))
            if st["num_acc"] + st["old_num_acc"] >= window \
                    and st["num_acc"] >= self.min_average_window:
                st["sum_2"] = st["sum_1"]
                st["old_num_acc"] = st["num_acc"]
                st["sum_1"] = jnp.zeros_like(p._data)
                st["num_acc"] = 0

    def apply(self, executor=None, need_restore=True):
        """Swap averaged params in. Returns a context manager when used in
        ``with``-form via contextlib below."""
        for p in self._params:
            st = self._st(p)
            total = st["num_acc"] + st["old_num_acc"]
            if total == 0:
                continue
            self._backup[id(p)] = p._data
            avg = (st["sum_1"] + st["sum_2"]) / float(total)
            p._data = avg.astype(p._data.dtype)
        self._need_restore = need_restore
        return self

    def restore(self, executor=None):
        for p in self._params:
            if id(p) in self._backup:
                p._data = self._backup.pop(id(p))

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        if getattr(self, "_need_restore", True):
            self.restore()
        return False

    def clear_grad(self):
        for p in self._params:
            p.clear_grad()

    def minimize(self, loss, startup_program=None, parameters=None,
                 no_grad_set=None):
        self.step()
        return None, []
