"""Automatic mixed precision.

Parity: reference python/paddle/amp (auto_cast.py:21, grad_scaler.py:26) over
imperative/amp_auto_cast.cc. TPU-native: the low-precision dtype is bfloat16
(native MXU dtype, full fp32 range), so loss scaling is a no-op by default —
GradScaler keeps the fp16-era API for parity and for enable=True fp16 runs.

Mechanics: auto_cast flips a thread-local AMP state consulted by the layer
forward paths (Linear/Conv/Matmul cast inputs to the amp dtype; denylist ops
like softmax/log stay fp32) — same allow/deny structure as the reference's
AmpOperators lists (imperative/amp_auto_cast.cc:55).

Below bf16 there is an fp8 (e4m3) matmul path: ``amp.fp8`` carries the
per-tensor scaling state (just-in-time and delayed amax-history modes,
checkpointable like GradScaler) over the fused-dequant Pallas kernel in
``ops/fp8_matmul.py``; gate with ``FLAGS_fp8_matmul`` or
``GPTConfig(fp8=True)``.
"""
from __future__ import annotations

import threading
from contextlib import contextmanager

import jax.numpy as jnp

from ..framework import dtype as dtypes
from ..framework.core import Tensor

__all__ = ["auto_cast", "decorate", "GradScaler", "amp_guard", "amp_state",
           "white_list", "black_list"]

# mirror of the reference's default allow/deny lists (fp16_lists.py)
white_list = {"matmul", "matmul_v2", "conv2d", "conv1d", "conv3d", "linear", "einsum", "bmm", "mm"}
black_list = {"softmax", "log_softmax", "cross_entropy", "exp", "log", "mean",
              "sum", "norm", "layer_norm", "batch_norm", "softmax_with_cross_entropy"}


class _AmpState(threading.local):
    def __init__(self):
        self.enabled = False
        self.dtype = jnp.bfloat16
        self.level = "O1"


_amp_state = _AmpState()


def maybe_autocast(*tensors):
    """O1 white-list cast: when auto_cast is active, cast floating inputs
    of matmul/conv/linear-class ops to the AMP dtype (bf16 on TPU) so the
    MXU runs them at full rate. Non-float inputs and disabled state pass
    through untouched. Returns the inputs as a tuple.

    This is the funnel the reference implements in C++
    (imperative/amp_auto_cast.cc AmpAutoCasts): called by the compute-heavy
    functional entry points (linear, conv*, matmul family)."""
    if not _amp_state.enabled:
        return tensors
    dt = _amp_state.dtype
    out = []
    for t in tensors:
        if isinstance(t, Tensor) and jnp.issubdtype(t._data.dtype, jnp.floating) \
                and t._data.dtype != dt:
            out.append(_cast_tracked(t, dt))
        else:
            out.append(t)
    return tuple(out)


def _cast_tracked(t, dt):
    """Cast through the op funnel so the tape records the cast (grads come
    back in the original dtype)."""
    from ..tensor.manipulation import cast

    return cast(t, dt)


def amp_state():
    return _amp_state


def amp_active() -> bool:
    return _amp_state.enabled


def amp_dtype():
    return _amp_state.dtype


@contextmanager
def auto_cast(enable=True, custom_white_list=None, custom_black_list=None,
              level="O1", dtype="bfloat16"):
    prev = (_amp_state.enabled, _amp_state.dtype, _amp_state.level)
    _amp_state.enabled = bool(enable)
    _amp_state.dtype = dtypes.convert_dtype(dtype)
    _amp_state.level = level
    try:
        yield
    finally:
        _amp_state.enabled, _amp_state.dtype, _amp_state.level = prev


amp_guard = auto_cast


def maybe_cast_to_amp(x):
    """Called by matmul-class layer paths when amp is active."""
    if not _amp_state.enabled:
        return x
    if isinstance(x, Tensor) and dtypes.is_floating(x.dtype) and x.dtype != _amp_state.dtype:
        from ..tensor.manipulation import cast

        return cast(x, _amp_state.dtype)
    return x


def decorate(models, optimizers=None, level="O1", dtype="bfloat16",
             master_weight=None, save_dtype=None):
    """O2: cast model params to the amp dtype (keep norms fp32)."""
    from ..nn.layer.norm import LayerNorm, _BatchNormBase

    def _cast_model(m):
        if level == "O2":
            d = dtypes.convert_dtype(dtype)
            for layer in m.sublayers(include_self=True):
                if isinstance(layer, (_BatchNormBase, LayerNorm)):
                    continue
                for p in layer._parameters.values():
                    if p is not None and dtypes.is_floating(p.dtype):
                        p._data = p._data.astype(d)
        return m

    if isinstance(models, (list, tuple)):
        models = [_cast_model(m) for m in models]
    else:
        models = _cast_model(models)
    if optimizers is None:
        return models
    return models, optimizers


import jax as _jax


@_jax.jit
def _unscale_and_check(grads, scale):
    inv = 1.0 / scale
    new = tuple(g * inv for g in grads)
    finite = _jax.tree_util.tree_reduce(
        jnp.logical_and,
        tuple(jnp.isfinite(g).all() for g in new),
        jnp.bool_(True),
    )
    return new, finite


class GradScaler:
    """Loss scaling (reference python/paddle/amp/grad_scaler.py:26 over
    check_finite_and_unscale / update_loss_scaling ops).

    With bf16 (TPU default) scaling is unnecessary; kept functional for
    fp16-parity training runs.
    """

    def __init__(self, enable=True, init_loss_scaling=2.0 ** 15,
                 incr_ratio=2.0, decr_ratio=0.5, incr_every_n_steps=1000,
                 decr_every_n_nan_or_inf=2, use_dynamic_loss_scaling=True):
        self._enable = enable
        self._scale = float(init_loss_scaling) if enable else 1.0
        self._incr_ratio = incr_ratio
        self._decr_ratio = decr_ratio
        self._incr_every_n_steps = incr_every_n_steps
        self._decr_every_n = decr_every_n_nan_or_inf
        self._dynamic = use_dynamic_loss_scaling
        self._good_steps = 0
        self._bad_steps = 0
        self._found_inf = False
        # armed by FleetEngine.step: a deferred pull of the COMPILED
        # scaler counters, so the engine never blocks on float(scale) per
        # step; any observable read below materializes it first
        self._lazy_sync = None

    def _materialize(self):
        cb = self._lazy_sync
        if cb is not None:
            self._lazy_sync = None
            cb()

    def scale(self, var):
        if not self._enable:
            return var
        self._materialize()
        return var * self._scale

    def unscale_(self, optimizer):
        if not self._enable:
            return
        self._materialize()
        grads = [p.grad._data for p in optimizer._parameter_list or []
                 if p.grad is not None]
        if not grads:
            self._found_inf = False
            return
        # One fused XLA program: unscale every grad and reduce a single
        # scalar finite-flag — a single device→host sync per step (the
        # reference fuses the same way in check_finite_and_unscale_op.cu).
        new_grads, finite = _unscale_and_check(tuple(grads), self._scale)
        it = iter(new_grads)
        for p in optimizer._parameter_list or []:
            if p.grad is not None:
                p.grad = Tensor(next(it))
        self._found_inf = not bool(finite)

    def minimize(self, optimizer, scaled_loss):
        from ..framework.core import backward

        backward(scaled_loss)
        self.step(optimizer)

    def step(self, optimizer):
        if not self._enable:
            optimizer.step()
            return
        self.unscale_(optimizer)
        if not self._found_inf:
            optimizer.step()
        self.update()

    def update(self):
        if not (self._enable and self._dynamic):
            return
        self._materialize()
        if self._found_inf:
            self._bad_steps += 1
            self._good_steps = 0
            if self._bad_steps >= self._decr_every_n:
                self._scale = max(self._scale * self._decr_ratio, 1.0)
                self._bad_steps = 0
        else:
            self._good_steps += 1
            self._bad_steps = 0
            if self._good_steps >= self._incr_every_n_steps:
                self._scale *= self._incr_ratio
                self._good_steps = 0

    def is_enable(self):
        return self._enable

    def is_use_dynamic_loss_scaling(self):
        return self._dynamic

    def get_loss_scaling(self):
        self._materialize()
        return Tensor(jnp.asarray(self._scale))

    def set_init_loss_scaling(self, v):
        self._lazy_sync = None   # explicit override beats pending state
        self._scale = float(v)

    def state_dict(self):
        self._materialize()
        return {"scale": self._scale, "good_steps": self._good_steps,
                "bad_steps": self._bad_steps}

    def load_state_dict(self, d):
        self._lazy_sync = None   # explicit override beats pending state
        self._scale = d.get("scale", self._scale)
        self._good_steps = d.get("good_steps", 0)
        self._bad_steps = d.get("bad_steps", 0)
