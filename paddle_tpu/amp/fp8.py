"""fp8 (e4m3) training support: scaling state + differentiable matmul.

The scale-management half of the fp8 path (ISSUE 17); the Pallas kernel
lives in ``ops/fp8_matmul.py``. Two scaling modes, both per-tensor:

- **just-in-time** (:func:`fp8_linear`): scale = amax(tensor)/448
  computed on the spot. Stateless, so it drops into any forward (the
  GPT MLP wiring, ``GPTConfig(fp8=True)`` / ``FLAGS_fp8_matmul``) with
  no state threading; costs one extra reduction per operand.
- **delayed** (:func:`init_delayed_state` / :func:`delayed_scale` /
  :func:`update_delayed_state`): the standard fp8 recipe — quantize with
  a scale derived from a rolling amax HISTORY (max over the last
  ``window`` steps), then record the current step's amax. The state is a
  plain pytree ``{"amax_history": (window,) f32, "scale": () f32}``, so
  it rides inside jit like optimizer state; :class:`DelayedScaling`
  wraps a dict of named states with the same ``state_dict`` /
  ``load_state_dict`` surface as :class:`~paddle_tpu.amp.GradScaler`
  for checkpointing.

Gradients: :func:`fp8_linear` is a ``custom_vjp`` — the forward runs the
real fp8 kernel on the quantized operands; the backward differentiates
through the quantize-dequantize as a straight-through estimator (grads
computed against the DEQUANTIZED operands in bf16, zero cotangent into
the scales). That is the same STE contract as ``quantization.fake_quant``.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..ops.fp8_matmul import E4M3_MAX, fp8_matmul_arrays

__all__ = ["E4M3_MAX", "quantize_fp8", "fp8_linear", "init_delayed_state",
           "delayed_scale", "update_delayed_state", "fp8_linear_delayed",
           "DelayedScaling"]


def quantize_fp8(x, scale):
    """x / scale, saturated to the e4m3 range, cast to float8_e4m3fn."""
    s = jnp.maximum(jnp.asarray(scale, jnp.float32), 1e-12)
    q = jnp.clip(x.astype(jnp.float32) / s, -E4M3_MAX, E4M3_MAX)
    return q.astype(jnp.float8_e4m3fn)


def _jit_scale(t):
    """Just-in-time per-tensor scale: amax/448 (non-differentiable)."""
    amax = jax.lax.stop_gradient(jnp.max(jnp.abs(t.astype(jnp.float32))))
    return jnp.maximum(amax, 1e-12) / E4M3_MAX


@jax.custom_vjp
def _fp8_mm(x, w, sx, sw):
    xq = quantize_fp8(x, sx)
    wq = quantize_fp8(w, sw)
    return fp8_matmul_arrays(xq, wq, sx, sw, out_dtype=x.dtype)


def _fp8_mm_fwd(x, w, sx, sw):
    xq = quantize_fp8(x, sx)
    wq = quantize_fp8(w, sw)
    y = fp8_matmul_arrays(xq, wq, sx, sw, out_dtype=x.dtype)
    # zero-size sentinels carry the primal dtypes through the residuals
    # (raw dtypes are not valid pytree leaves)
    return y, (xq, wq, sx, sw, jnp.zeros((0,), x.dtype),
               jnp.zeros((0,), w.dtype))


def _fp8_mm_bwd(res, g):
    # STE: grads against the dequantized operands, bf16 dots, f32 accum —
    # what the compiled bwd of a bf16 matmul would run.
    xq, wq, sx, sw, xs, ws = res
    xdt, wdt = xs.dtype, ws.dtype
    xd = xq.astype(jnp.bfloat16)
    wd = wq.astype(jnp.bfloat16)
    g16 = g.astype(jnp.bfloat16)
    dx = jax.lax.dot_general(
        g16, wd, (((g.ndim - 1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32) * sw
    lead = tuple(range(g.ndim - 1))
    dw = jax.lax.dot_general(
        xd, g16, ((lead, lead), ((), ())),
        preferred_element_type=jnp.float32) * sx
    return (dx.astype(xdt), dw.astype(wdt),
            jnp.zeros_like(sx), jnp.zeros_like(sw))


_fp8_mm.defvjp(_fp8_mm_fwd, _fp8_mm_bwd)


def fp8_linear(x, w, bias=None):
    """``x @ w (+ bias)`` through the fp8 kernel, just-in-time per-tensor
    scaling, STE gradients. x [..., K] fp; w [K, N] fp."""
    y = _fp8_mm(x, w, _jit_scale(x), _jit_scale(w))
    if bias is not None:
        y = y + bias.astype(y.dtype)
    return y


# -- delayed scaling ---------------------------------------------------------

def init_delayed_state(window: int = 16):
    """Fresh per-tensor delayed-scaling state pytree."""
    return {"amax_history": jnp.zeros((int(window),), jnp.float32),
            "scale": jnp.asarray(1.0, jnp.float32)}


def delayed_scale(state):
    """The scale the CURRENT step should quantize with (history max)."""
    return state["scale"]


def update_delayed_state(state, t):
    """Record ``amax(t)`` and refresh the scale from the history max.
    Returns the new state; pure, jit-friendly."""
    amax = jax.lax.stop_gradient(jnp.max(jnp.abs(t.astype(jnp.float32))))
    hist = jnp.roll(state["amax_history"], 1).at[0].set(amax)
    scale = jnp.maximum(jnp.max(hist), 1e-12) / E4M3_MAX
    return {"amax_history": hist, "scale": scale}


def fp8_linear_delayed(x, w, x_state, w_state, bias=None):
    """Delayed-scaling fp8 linear: quantize with the HISTORY scales, then
    record this step's amaxes. Returns (y, new_x_state, new_w_state)."""
    y = _fp8_mm(x, w, delayed_scale(x_state), delayed_scale(w_state))
    if bias is not None:
        y = y + bias.astype(y.dtype)
    return y, update_delayed_state(x_state, x), update_delayed_state(w_state, w)


class DelayedScaling:
    """Host-side registry of named delayed-scaling states with the
    GradScaler checkpoint surface.

        fp8 = DelayedScaling(window=16)
        y, fp8["fc_x"], fp8["fc_w"] = fp8_linear_delayed(
            x, w, fp8["fc_x"], fp8["fc_w"])
        ckpt["fp8"] = fp8.state_dict()     # plain nested dict of arrays
        fp8.load_state_dict(ckpt["fp8"])   # exact round-trip
    """

    def __init__(self, window: int = 16):
        self._window = int(window)
        self._states: dict = {}

    def __getitem__(self, name):
        if name not in self._states:
            self._states[name] = init_delayed_state(self._window)
        return self._states[name]

    def __setitem__(self, name, state):
        self._states[name] = state

    def names(self):
        return sorted(self._states)

    def state_dict(self):
        import numpy as np

        return {name: {"amax_history": np.asarray(st["amax_history"]),
                       "scale": np.asarray(st["scale"])}
                for name, st in self._states.items()}

    def load_state_dict(self, d):
        for name, st in d.items():
            self._states[name] = {
                "amax_history": jnp.asarray(st["amax_history"],
                                            jnp.float32),
                "scale": jnp.asarray(st["scale"], jnp.float32)}
