"""paddle.onnx surface (reference python/paddle/onnx/export.py).

DECISION: the reference delegates to the external paddle2onnx package; this
environment is zero-egress and ships no onnx runtime, so export raises with
a pointer to the native serving path. The framework's own deployment format
is the versioned StableHLO artifact (static/export.py) served by
inference.Predictor — strictly more capable on TPU than an ONNX detour.
"""

__all__ = ["export"]


def export(layer, path, input_spec=None, opset_version=9, **configs):
    raise RuntimeError(
        "onnx export is not supported by this framework (the reference "
        "delegates to the external paddle2onnx converter, which cannot "
        "translate this runtime's programs); use paddle.jit.save + "
        "inference.Predictor (versioned StableHLO) for deployment")
