"""MoE fused permute/dispatch kernel — Pallas TPU, capacity-slot gather.

The MoE layer (nn/moe.py) routes each token to its top-k experts and
packs the survivors into a dense ``(E, C)`` capacity grid. The textbook
GShard formulation materializes a one-hot dispatch tensor ``(T, E, C)``
and contracts it with the tokens — ``O(T·E·C·H)`` FLOPs and a
``(T, E, C)`` buffer just to MOVE rows. This module replaces that with
the permutation it actually is:

- :func:`moe_dispatch_gather` — the routed entry. ``src`` (E·C,) int32
  names the token row filling each capacity slot (−1 = empty slot);
  the result is the ``(E·C, H)`` packed expert input, empty slots
  zeroed. On TPU with tileable shapes it runs the Pallas kernel;
  anywhere else (CPU/GPU, untileable H) the IDENTICAL composed jnp
  gather — the flash/paged fallback contract, pinned by interpret-mode
  parity tests (tests/test_moe.py, ``-m kernels``).

Kernel design:
- grid ``(E·C, H/hb)`` — one output row per major grid step, the hidden
  dim split at ``hb`` lanes (the autotune knob);
- ``src`` rides as SCALAR PREFETCH (pltpu.PrefetchScalarGridSpec): the
  token BlockSpec index_map reads ``src[i]`` (clamped to row 0 for
  empty slots) to DMA exactly the routed row — the permutation happens
  in the DMA engine, no ``(T, E, C)`` one-hot ever exists;
- empty slots (src[i] < 0) write zeros instead of the clamp row, so the
  packed grid matches the one-hot einsum bit-for-bit;
- backward is the transpose permutation: a scatter-add of the slot
  cotangents back to their source rows (dropped/empty slots contribute
  nothing), expressed as composed jnp — it is the same gather pattern
  mirrored, and XLA already emits a single dynamic-update stream for it.

Autotune family ``moe_dispatch`` (ops/autotune.py): candidates ladder
over the lane block ``hb`` ∈ {128, 256, 512, H} (legal divisors only).
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from . import autotune as _autotune
from .flash_attention import _compiler_params, _on_tpu

__all__ = ["moe_dispatch_gather", "moe_combine_scatter"]


def _gather_reference(x, src):
    """Composed jnp fallback: rows of ``x`` at ``src`` with empty
    (negative) slots zeroed. x (T, H); src (N,) int32 → (N, H)."""
    rows = x[jnp.maximum(src, 0)]
    return jnp.where((src >= 0)[:, None], rows, jnp.zeros_like(rows))


def _gather_kernel(src_ref, x_ref, o_ref):
    from jax.experimental import pallas as pl

    i = pl.program_id(0)
    row = x_ref[...]
    o_ref[...] = jnp.where(src_ref[i] >= 0, row, jnp.zeros_like(row))


@functools.partial(jax.jit, static_argnames=("hb", "interpret"))
def _gather_pallas(x, src, hb, interpret=False):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    T, H = x.shape
    N = src.shape[0]
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(N, H // hb),
        in_specs=[
            pl.BlockSpec((1, hb),
                         lambda i, j, src: (jnp.maximum(src[i], 0), j)),
        ],
        out_specs=pl.BlockSpec((1, hb), lambda i, j, src: (i, j)),
    )
    return pl.pallas_call(
        _gather_kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((N, H), x.dtype),
        compiler_params=_compiler_params(
            pltpu, vmem_limit_bytes=64 * 1024 * 1024),
        interpret=interpret,
    )(src, x)


def _pick_hb(N, T, H, dtype) -> int:
    """Hand-picked default lane block, overridable by the autotuner."""
    default = H if H % 512 else 512
    cfg = _autotune.get_config("moe_dispatch", (N, T, H), dtype,
                               {"hb": default})
    hb = int(cfg.get("hb", default))
    return hb if H % hb == 0 else default


def _gather_impl(x, src, interpret):
    T, H = x.shape
    N = src.shape[0]
    if interpret is None:
        interpret = False
        if not _on_tpu():
            return _gather_reference(x, src)
    if not interpret and H % 128 != 0:
        _autotune.note_fallback(
            "moe_dispatch", (N, T, H),
            "hidden=%d not a multiple of 128 lanes" % H)
        return _gather_reference(x, src)
    hb = _pick_hb(N, T, H, jnp.dtype(x.dtype).name)
    return _gather_pallas(x, src, hb=hb, interpret=bool(interpret))


@functools.partial(jax.custom_vjp, nondiff_argnums=(2,))
def _gather(x, src, interpret):
    return _gather_impl(x, src, interpret)


def _gather_fwd(x, src, interpret):
    return _gather_impl(x, src, interpret), (x.shape[0], src)


def _gather_bwd(interpret, res, dy):
    T, src = res
    # transpose of the permutation: scatter slot cotangents back to their
    # source rows; empty slots (clamped to row 0) add exact zeros there
    dy = jnp.where((src >= 0)[:, None], dy, jnp.zeros_like(dy))
    dx = jnp.zeros((T, dy.shape[1]), dy.dtype)
    return dx.at[jnp.maximum(src, 0)].add(dy), None


_gather.defvjp(_gather_fwd, _gather_bwd)


def moe_dispatch_gather(x, src, interpret=None):
    """Pack routed token rows into the dense (E·C, H) expert grid.

    x (T, H) — the token activations; src (E·C,) int32 — for capacity
    slot ``e*C + c``, the token row that fills it, or −1 for an empty
    slot (under-capacity expert or dropped assignment). Returns
    (E·C, H) in x.dtype with empty slots zeroed — bit-identical to the
    one-hot einsum ``einsum("tec,th->ech", dispatch, x)`` flattened,
    without ever building the (T, E, C) one-hot.

    Differentiable in ``x`` (custom VJP: the transpose scatter-add).
    Same routing contract as flash/paged attention: off-TPU (unless
    ``interpret=True`` forces the kernel) and on untileable hidden
    sizes this returns the identical composed jnp gather.
    """
    return _gather(x, jnp.asarray(src, jnp.int32), interpret)


def moe_combine_scatter(out, slot, gates):
    """Un-permute expert outputs back to token order and mix the top-k.

    out (E·C, H) — packed expert outputs; slot (T, k) int32 — the
    capacity slot ``e*C + c`` each token's rank-r assignment landed in
    (−1 = dropped); gates (T, k) f32 — the normalized router weights.
    Returns (T, H) in out.dtype: ``sum_r gates[t,r] * out[slot[t,r]]``
    with dropped ranks contributing zero (residual passthrough happens
    in the caller). The transpose of :func:`moe_dispatch_gather` — k
    gathers instead of a (T, E, C) combine einsum.
    """
    T, k = slot.shape
    y = jnp.zeros((T, out.shape[1]), out.dtype)
    for r in range(k):
        rows = _gather_reference(out, slot[:, r])
        y = y + rows * gates[:, r:r + 1].astype(out.dtype)
    return y


# -- autotune family (ISSUE 18) ---------------------------------------------
# Ladder over the lane block hb: small blocks pipeline more grid steps
# per row (better DMA overlap at huge H), H keeps one DMA per row.

def _dispatch_candidates(shape, dtype):
    N, T, H = (int(d) for d in shape)
    if H % 128 != 0:
        raise ValueError("hidden=%d not tileable (needs 128 lanes)" % H)
    # dict.fromkeys dedupes the H rung when H is already on the ladder
    return [{"hb": hb} for hb in dict.fromkeys((128, 256, 512, H))
            if hb <= H and H % hb == 0]


def _dispatch_bench(shape, dtype, config):
    import numpy as np

    N, T, H = (int(d) for d in shape)
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.standard_normal((T, H)).astype(dtype))
    src = jnp.asarray(rng.integers(-1, T, size=(N,)).astype(np.int32))
    out = _gather_pallas(x, src, hb=int(config["hb"]),
                         interpret=not _on_tpu())
    jax.block_until_ready(out)


_autotune.register_family("moe_dispatch", _dispatch_candidates,
                         _dispatch_bench)
