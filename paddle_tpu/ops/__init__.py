"""paddle_tpu.ops — Pallas TPU kernels and fused ops.

The analog of the reference's operators/fused/ (fused_transformer_op.cu,
fmha_ref.h) and the fusion_group runtime codegen — except on TPU, XLA
already fuses elementwise chains, so hand-written kernels are reserved for
the cases XLA can't do: flash attention (online softmax tiling) and
ring attention (overlapping ICI permutes with compute).
"""
from .flash_attention import flash_attention  # noqa: F401
from .fused import fused_multi_head_attention, fused_feedforward  # noqa: F401
