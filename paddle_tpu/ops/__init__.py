"""paddle_tpu.ops — Pallas TPU kernels and fused ops.

The analog of the reference's operators/fused/ (fused_transformer_op.cu,
fused_feedforward_op.cc, fused Adam) and the fusion_group runtime codegen.
Hand-written kernels are reserved for what XLA can't do by itself:

- flash attention (online-softmax tiling; ops/flash_attention.py)
- fused residual+layernorm and GeLU/SwiGLU MLP blocks with custom-VJP
  backward kernels (ops/fused_kernels.py, FLAGS_fused_kernels)
- one-pass flat-buffer AdamW/LAMB updates (ops/fused_optimizer.py,
  FLAGS_fused_optimizer)
- int8 weight-quantized matmul with in-epilogue per-channel dequant
  (ops/int8_matmul.py, routed through quantization.quantized_linear and
  the serving engine's int8 decode)

Every kernel follows the same contract: jnp reference math off-TPU,
``interpret=True`` for CPU parity tests (pytest -m kernels), a
FLAGS_benchmark row and a ``kernel.*`` trace span at its eager surface.
"""
from .flash_attention import flash_attention  # noqa: F401
from .fused import fused_multi_head_attention, fused_feedforward  # noqa: F401
from .fused_kernels import fused_ln_mlp, fused_add_layernorm  # noqa: F401
from .fused_optimizer import fused_adamw_update, fused_lamb_update  # noqa: F401
from .int8_matmul import int8_matmul_arrays, dynamic_int8_matmul  # noqa: F401
