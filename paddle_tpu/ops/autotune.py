"""Shape-keyed Pallas block-config autotuner (ISSUE 17).

Every kernel in the library ships hand-picked block sizes (`_auto_block`,
`_pick_block_b`, `_pick`, `_block_rows`). Those defaults are right at the
shapes they were tuned on and wrong elsewhere — the S=2048 flash cliff is
a single degenerate whole-sequence block chosen by `_auto_block`. This
module makes the choice measured instead of guessed:

- at the FIRST compile of a kernel family for a concrete
  ``(kernel, shape, dtype, backend)`` key, time 3-5 legal block configs
  on synthetic inputs and keep the winner;
- persist winners to a JSON cache (``tools/autotune_cache.json`` by
  default) keyed like the graftlint fingerprints
  (``kernel:shape:dtype:backend`` — line-free, host-portable,
  committable);
- consult the cache on every later compile (an O(1) dict hit at trace
  time).

Gated by ``FLAGS_autotune`` (default OFF: every kernel keeps its
hand-picked defaults bit-for-bit). The flag cell is mirrored here
through ``core.native.autotune_watchers`` so no jit-reachable function
reads the native cell directly (GL002). Trials run once per key on the
host at trace time, never inside a compiled program; timing therefore
uses a bare ``perf_counter`` and blocks only on locally-built synthetic
arrays.

Gauges: ``autotune_hits`` / ``autotune_misses`` / ``autotune_trials_ms``.
CLI: ``python -m tools.autotune`` (inspect / pre-populate / --check).
"""
from __future__ import annotations

import json
import os
import threading
import warnings
from time import perf_counter
from typing import Callable, Dict, List, Optional

from ..core import native as _native
from ..monitor import stats as _mstats

__all__ = ["enabled", "make_key", "get_config", "register_family",
           "families", "tune", "cache_entries", "stale_entries",
           "set_cache_path", "cache_path", "reset", "note_fallback"]

_DEFAULT_CACHE = os.path.normpath(os.path.join(
    os.path.dirname(os.path.abspath(__file__)), "..", "..", "tools",
    "autotune_cache.json"))

_lock = threading.RLock()
_cache_path = [os.environ.get("PADDLE_TPU_AUTOTUNE_CACHE", _DEFAULT_CACHE)]
_cache: list = [None]          # lazy {key: entry}; None = not loaded yet
_warned: set = set()           # corrupt keys already warned about

# Mirror of the FLAGS_autotune cell: module-local so jit-reachable
# consumers never subscript a core.native cell (GL002); set_flags keeps
# it in sync through the watcher list.
_enabled = [bool(_native.autotune[0])]
_native.autotune_watchers.append(
    lambda v: _enabled.__setitem__(0, bool(v)))

# kernel family -> {"candidates": fn(shape, dtype) -> [config, ...],
#                   "bench": fn(shape, dtype, config) -> None (one run,
#                            blocked on completion)}
_FAMILIES: Dict[str, dict] = {}


def enabled() -> bool:
    return _enabled[0]


def cache_path() -> str:
    return _cache_path[0]


def set_cache_path(path: str) -> None:
    """Point the autotuner at a different cache file (tests, CLI)."""
    with _lock:
        _cache_path[0] = path
        _cache[0] = None
        _warned.clear()


def reset() -> None:
    """Drop the in-memory cache so the next consult re-reads the file
    (simulates a process restart for the round-trip tests)."""
    with _lock:
        _cache[0] = None
        _warned.clear()


def register_family(name: str,
                    candidates: Callable[[tuple, str], List[dict]],
                    bench: Callable[[tuple, str, dict], None]) -> None:
    """Register a kernel family. ``candidates`` maps a concrete (shape,
    dtype) to the legal block configs worth trying (the hand-picked
    default should be among them); ``bench`` runs the kernel once with a
    given config on synthetic inputs and blocks until done."""
    _FAMILIES[name] = {"candidates": candidates, "bench": bench}


def families() -> List[str]:
    return sorted(_FAMILIES)


def _backend() -> str:
    try:
        import jax

        return jax.default_backend()
    except Exception:  # noqa: BLE001 — keyless host (CLI inspect)
        return "unknown"


def make_key(kernel: str, shape, dtype: str,
             backend: Optional[str] = None) -> str:
    """Cache key, graftlint-fingerprint style: kernel:shape:dtype:backend
    (e.g. ``flash:16x2048x2048x128:bfloat16:tpu``)."""
    dims = "x".join(str(int(d)) for d in shape)
    return "%s:%s:%s:%s" % (kernel, dims, dtype,
                            backend or _backend())


def parse_key(key: str):
    """Inverse of :func:`make_key`; raises ValueError on malformed keys."""
    kernel, dims, dtype, backend = key.split(":")
    shape = tuple(int(d) for d in dims.split("x"))
    return kernel, shape, dtype, backend


def _load() -> dict:
    if _cache[0] is not None:
        return _cache[0]
    entries: dict = {}
    path = _cache_path[0]
    if os.path.exists(path):
        try:
            with open(path) as f:
                raw = json.load(f)
            entries = dict(raw.get("entries", {}))
        except (OSError, ValueError) as e:
            warnings.warn("autotune cache %s unreadable (%s) — starting "
                          "empty" % (path, e), stacklevel=2)
    _cache[0] = entries
    return entries


def _save() -> None:
    path = _cache_path[0]
    try:
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump({"version": 1, "entries": _cache[0]}, f, indent=1,
                      sort_keys=True)
        os.replace(tmp, path)
    except OSError as e:
        warnings.warn("autotune cache %s not writable (%s) — winners kept "
                      "in-memory only" % (path, e), stacklevel=2)


def cache_entries() -> dict:
    with _lock:
        return dict(_load())


def _entry_config(key: str, entry) -> Optional[dict]:
    """Validate a cache entry; corrupt ones are skipped with a one-shot
    warning (the trial sweep then repairs the key)."""
    if isinstance(entry, dict) and isinstance(entry.get("config"), dict):
        return dict(entry["config"])
    if key not in _warned:
        _warned.add(key)
        warnings.warn("autotune cache entry %r is corrupt (%r) — ignoring "
                      "it and re-tuning" % (key, entry), stacklevel=3)
    return None


def _trial(bench: Callable, shape, dtype: str, config: dict,
           reps: int = 2) -> float:
    """Best-of-``reps`` wall ms for one config (first call warms the
    compile and is not timed)."""
    bench(shape, dtype, config)
    best = float("inf")
    for _ in range(max(1, reps)):
        t0 = perf_counter()
        bench(shape, dtype, config)
        best = min(best, perf_counter() - t0)
    return best * 1e3


def tune(kernel: str, shape, dtype: str, max_trials: int = 5,
         reps: int = 2) -> Optional[dict]:
    """Run the trial sweep for one key regardless of FLAGS_autotune and
    persist the winner (the CLI's pre-populate path). Returns the winning
    config, or None when the family is unknown or has no candidates."""
    fam = _FAMILIES.get(kernel)
    if fam is None:
        return None
    cands = list(fam["candidates"](tuple(shape), dtype))[:max_trials]
    if not cands:
        return None
    key = make_key(kernel, shape, dtype)
    trials: dict = {}
    t_begin = perf_counter()
    if len(cands) == 1:
        winner = dict(cands[0])
    else:
        winner, best_ms, t_spent = None, float("inf"), 0.0
        for config in cands:
            try:
                ms = _trial(fam["bench"], tuple(shape), dtype, config,
                            reps=reps)
            except Exception as e:  # noqa: BLE001 — an illegal candidate
                trials[_cfg_tag(config)] = "error: %s" % type(e).__name__
                continue
            t_spent += ms * (reps + 1)
            trials[_cfg_tag(config)] = round(ms, 4)
            if ms < best_ms:
                winner, best_ms = dict(config), ms
        _mstats.AUTOTUNE_TRIALS_MS.add(int(t_spent))
        if winner is None:
            return None
    with _lock:
        entries = _load()
        entries[key] = {"config": winner, "trials": trials}
        _save()
    from ..monitor import trace as _trace

    if _trace.is_tracing():
        # one span per trial sweep: the timeline shows WHERE compile time
        # went when FLAGS_autotune pays its one-time cost
        _trace.get_writer().add_complete(
            "autotune.tune", t_begin, perf_counter() - t_begin,
            cat="autotune",
            args={"key": key, "winner": _cfg_tag(winner),
                  "trials": trials})
    return winner


def get_config(kernel: str, shape, dtype: str, default: dict) -> dict:
    """The kernel-side entry: hand back the cached winner for this
    concrete key, trial-and-cache on a miss, or the hand-picked
    ``default`` untouched while FLAGS_autotune is off. Called at trace
    time (block sizes are static args), so the hot path is one dict
    lookup."""
    if not _enabled[0]:
        return default
    key = make_key(kernel, shape, dtype)
    with _lock:
        entries = _load()
        cached = entries.get(key)
    if cached is not None:
        config = _entry_config(key, cached)
        if config is not None:
            _mstats.AUTOTUNE_HITS.add()
            return config
    _mstats.AUTOTUNE_MISSES.add()
    winner = tune(kernel, shape, dtype)
    return winner if winner is not None else default


def _cfg_tag(config: dict) -> str:
    return "_".join("%s%s" % (k, v) for k, v in sorted(config.items()))


# -- fallback accounting (ISSUE 17 satellite) -------------------------------
# The kernel entries' untileable-shape escape hatches used to drop to
# composed jnp with NO signal — a model quietly losing its kernels looked
# identical to one using them. Every such branch now calls note_fallback.

_fallback_warned: set = set()


def note_fallback(kernel: str, shape, detail: str) -> None:
    """Count (``fused_kernel_fallbacks`` gauge) and warn ONCE per
    (kernel, shape) when a Pallas entry falls back to composed jnp,
    naming the kernel and the offending dimension. Called at trace time
    — once per compile, not per step."""
    _mstats.FUSED_KERNEL_FALLBACKS.add()
    from ..monitor import trace as _trace

    if _trace.is_tracing():
        _trace.get_writer().add_complete(
            "kernel.fallback", perf_counter(), 0.0, cat="autotune",
            args={"kernel": kernel,
                  "shape": "x".join(str(int(x)) for x in shape),
                  "detail": detail})
    key = (kernel, tuple(int(x) for x in shape))
    if key in _fallback_warned:
        return
    _fallback_warned.add(key)
    warnings.warn(
        "paddle_tpu.ops: %s falls back to composed jnp for shape %s — %s"
        % (kernel, tuple(int(x) for x in shape), detail), stacklevel=3)


def stale_entries() -> List[tuple]:
    """(key, reason) for every committed cache entry that no longer
    matches a legal config — unknown family, unparseable key, corrupt
    payload, or a config outside the family's current candidate set.
    ``python -m tools.autotune --check`` exits non-zero on any (the
    stale-fingerprint contract graftlint's baseline follows)."""
    out = []
    with _lock:
        entries = dict(_load())
    for key, entry in sorted(entries.items()):
        try:
            kernel, shape, dtype, _backend_name = parse_key(key)
        except (ValueError, TypeError):
            out.append((key, "unparseable key"))
            continue
        if not (isinstance(entry, dict)
                and isinstance(entry.get("config"), dict)):
            out.append((key, "corrupt entry payload"))
            continue
        fam = _FAMILIES.get(kernel)
        if fam is None:
            out.append((key, "unknown kernel family %r" % kernel))
            continue
        try:
            cands = [dict(c) for c in fam["candidates"](shape, dtype)]
        except Exception as e:  # noqa: BLE001 — shape no longer legal
            out.append((key, "shape rejected by family (%s)"
                        % type(e).__name__))
            continue
        if dict(entry["config"]) not in cands:
            out.append((key, "config %r no longer legal (candidates: %s)"
                        % (entry["config"],
                           [_cfg_tag(c) for c in cands] or "none")))
    return out
