"""fp8 (e4m3) matmul — Pallas dot kernel with fused dequant epilogue.

The fp8 leg of the quantized-matmul family (ISSUE 17): float8_e4m3fn
storage (max 448, 8x less HBM weight traffic than f32, 2x less than
bf16) with per-TENSOR scales, f32 accumulation, and the dequant
(``acc * sx * sw``) plus bias fused into the kernel epilogue — the same
shape as ops/int8_matmul.py, with the per-channel int8 rescale replaced
by the two scalar scales fp8 training uses.

The operands are upcast e4m3 -> bf16 inside the kernel before the dot:
e4m3 values are exactly representable in bf16, so the product is exact
and the MXU runs at its bf16 rate on hardware without a native fp8 dot.
The composed jnp fallback runs the SAME op sequence (bf16 dot, f32
accumulate, dequant, cast), so on/off-TPU numerics are identical.

Scale management (delayed amax-history scaling, checkpointable state)
lives in ``amp/fp8.py``; this module is pure kernel.

Fallback contract matches flash_attention: off-TPU (or on untileable
shapes) the identical XLA math runs; ``interpret=True`` forces the
Pallas kernel for CPU parity tests.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from ..monitor.stats import FP8_MATMUL_CALLS
from . import autotune as _autotune
from .flash_attention import _compiler_params, _on_tpu

__all__ = ["fp8_matmul_arrays", "E4M3_MAX"]

E4M3_MAX = 448.0


def _fp8_matmul_ref(xq, wq, sx, sw, bias, out_dtype):
    """jnp reference — the SAME op sequence the kernel runs."""
    acc = jax.lax.dot_general(
        xq.astype(jnp.bfloat16), wq.astype(jnp.bfloat16),
        (((xq.ndim - 1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)
    out = acc * (sx * sw)
    if bias is not None:
        out = out + bias.astype(jnp.float32)
    return out.astype(out_dtype)


def _fp8_kernel(sc_ref, xq_ref, wq_ref, b_ref, o_ref, acc_s, *,
                n_k, out_dtype):
    from jax.experimental import pallas as pl

    ki = pl.program_id(2)

    @pl.when(ki == 0)
    def _init():
        acc_s[...] = jnp.zeros_like(acc_s)

    acc_s[...] += jax.lax.dot_general(
        xq_ref[...].astype(jnp.bfloat16), wq_ref[...].astype(jnp.bfloat16),
        (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32)

    @pl.when(ki == n_k - 1)
    def _epilogue():
        out = acc_s[...] * (sc_ref[0] * sc_ref[1]) + b_ref[...]
        o_ref[...] = out.astype(out_dtype)


def _pick(n, cands):
    for c in cands:
        if n % c == 0 and c <= n:
            return c
    return None


@functools.partial(jax.jit, static_argnames=("out_dtype", "interpret",
                                             "bm", "bn", "bk"))
def _fp8_matmul_2d(xq, wq, sx, sw, bias, out_dtype, interpret=False,
                   bm=None, bn=None, bk=None):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    M, K = xq.shape
    N = wq.shape[1]
    # fp8 min tile is (32, 128): pad rows to 32 (decode batches are tiny)
    Mp = -(-M // 32) * 32
    if Mp != M:
        xq = jnp.pad(xq, ((0, Mp - M), (0, 0)))
    bm = bm or _pick(Mp, (256, 128, 64, 32))
    bn = bn or _pick(N, (512, 256, 128))
    bk = bk or _pick(K, (512, 256, 128))
    b2 = (bias.reshape(1, N).astype(jnp.float32) if bias is not None
          else jnp.zeros((1, N), jnp.float32))
    sc = jnp.stack([jnp.asarray(sx, jnp.float32).reshape(()),
                    jnp.asarray(sw, jnp.float32).reshape(())])
    out = pl.pallas_call(
        functools.partial(_fp8_kernel, n_k=K // bk, out_dtype=out_dtype),
        out_shape=jax.ShapeDtypeStruct((Mp, N), out_dtype),
        grid=(Mp // bm, N // bn, K // bk),
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.SMEM),
            pl.BlockSpec((bm, bk), lambda i, j, k: (i, k)),
            pl.BlockSpec((bk, bn), lambda i, j, k: (k, j)),
            pl.BlockSpec((1, bn), lambda i, j, k: (0, j)),
        ],
        out_specs=pl.BlockSpec((bm, bn), lambda i, j, k: (i, j)),
        scratch_shapes=[pltpu.VMEM((bm, bn), jnp.float32)],
        compiler_params=_compiler_params(
            pltpu, vmem_limit_bytes=64 * 1024 * 1024),
        interpret=interpret,
    )(sc, xq, wq, b2)
    return out[:M]


def fp8_matmul_arrays(xq, wq, sx, sw, bias=None, out_dtype=jnp.float32,
                      interpret=None):
    """``(xq @ wq) * sx * sw (+ bias)`` with the dequant fused in-epilogue.

    xq e4m3 [..., K]; wq e4m3 [K, N]; sx/sw f32 per-tensor scales (the
    values each operand was DIVIDED by at quantization; the epilogue
    multiplies them back). Falls back to the identical composed jnp math
    off-TPU or on untileable shapes."""
    sx = jnp.asarray(sx, jnp.float32)
    sw = jnp.asarray(sw, jnp.float32)
    if interpret is None:
        if not _on_tpu():
            return _fp8_matmul_ref(xq, wq, sx, sw, bias, out_dtype)
        interpret = False
    lead = xq.shape[:-1]
    K = xq.shape[-1]
    N = wq.shape[1]
    M = 1
    for d in lead:
        M *= int(d)
    if (_pick(N, (512, 256, 128)) is None
            or _pick(K, (512, 256, 128)) is None):
        _autotune.note_fallback(
            "fp8_matmul", (M, K, N),
            "K=%d or N=%d has no 128-divisible block" % (K, N))
        return _fp8_matmul_ref(xq, wq, sx, sw, bias, out_dtype)
    if not isinstance(xq, jax.core.Tracer):
        FP8_MATMUL_CALLS.add()
    blocks = {}
    if _autotune.enabled():
        Mp = -(-M // 32) * 32
        cfg = _autotune.get_config(
            "fp8_matmul", (M, K, N), "float8_e4m3fn",
            {"bm": _pick(Mp, (256, 128, 64, 32)),
             "bn": _pick(N, (512, 256, 128)),
             "bk": _pick(K, (512, 256, 128))})
        tm, tn, tk = (int(cfg.get(k, 0) or 0) for k in ("bm", "bn", "bk"))
        if (tm and Mp % tm == 0 and tn and N % tn == 0
                and tk and K % tk == 0):
            blocks = {"bm": tm, "bn": tn, "bk": tk}
    out = _fp8_matmul_2d(xq.reshape(M, K), wq, sx, sw, bias,
                         out_dtype=jnp.dtype(out_dtype).name,
                         interpret=interpret, **blocks)
    return out.reshape(*lead, N)


# -- autotune family (ISSUE 17) ---------------------------------------------

def _fp8_candidates(shape, dtype):
    M, K, N = shape
    Mp = -(-int(M) // 32) * 32
    bms = [c for c in (256, 128, 64, 32) if Mp % c == 0][:2]
    bns = [c for c in (512, 256, 128) if int(N) % c == 0][:2]
    bk = _pick(int(K), (512, 256, 128))
    if not bms or not bns or bk is None:
        return []
    out = []
    for bm in bms:
        for bn in bns:
            out.append({"bm": bm, "bn": bn, "bk": bk})
    return out[:5]


def _fp8_bench(shape, dtype, config):
    import numpy as np

    M, K, N = (int(d) for d in shape)
    rng = np.random.default_rng(0)
    xq = jnp.asarray(rng.standard_normal((M, K)).astype(np.float32)
                     ).astype(jnp.float8_e4m3fn)
    wq = jnp.asarray(rng.standard_normal((K, N)).astype(np.float32)
                     ).astype(jnp.float8_e4m3fn)
    out = _fp8_matmul_2d(xq, wq, jnp.float32(0.1), jnp.float32(0.1), None,
                         out_dtype="float32", interpret=not _on_tpu(),
                         **config)
    jax.block_until_ready(out)


_autotune.register_family("fp8_matmul", _fp8_candidates, _fp8_bench)
