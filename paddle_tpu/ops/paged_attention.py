"""Paged-attention decode kernel — Pallas TPU, block-table gather.

The serving engine's paged KV cache (ISSUE 7) keeps each layer's K/V in
a shared block pool ``(n_blocks, n_heads, block_size, head_dim)``; a
slot's tokens live in the blocks its block table names, in table order.
The batched one-token decode step then needs attention of a single query
per slot over that slot's *scattered* blocks — this module provides it:

- :func:`paged_attention_arrays` — the routed entry every caller uses.
  On TPU with tileable shapes it runs the Pallas kernel; anywhere else
  (CPU/GPU, or untileable shapes) it runs the IDENTICAL composed jnp
  math (gather blocks by table, mask, softmax) — the same fallback
  contract as ops/flash_attention.py, pinned by interpret-mode parity
  tests (tests/test_paged_attention.py, ``-m kernels``).

Kernel design (mirrors the flash forward):
- grid ``(batch, max_blocks_per_slot)``, kv-block innermost so the VMEM
  scratch (m, l, acc) carries across one slot's block sweep;
- the block table and per-slot lengths ride as SCALAR PREFETCH
  (pltpu.PrefetchScalarGridSpec): the K/V BlockSpec index_map reads
  ``tables[b, i]`` to DMA pool block ``tables[b, i]`` directly — no
  gather materialization, HBM traffic is exactly the live blocks;
- blocks past a slot's length are skipped with ``pl.when`` (their table
  entries point at reserved garbage block 0, so the dead DMA is safe);
- scores/softmax statistics in f32, accumulator f32, output cast back.

Ragged decode (ISSUE 17, ``FLAGS_ragged_decode``): the compute guard
skips dead blocks, but the K/V DMAs still sweep the PADDED table width —
a slot with 1 live block in a W=64 table pays 64 block fetches. With the
flag on, the K/V index map clamps dead iterations to the slot's LAST
live block (``tbl[b, min(i, max((len-1)//bs, 0))]``); consecutive grid
steps that name the same block elide the DMA on TPU, so HBM traffic
tracks live tokens instead of table width. Output is bit-identical: the
clamp only changes which block dead (compute-guarded) iterations would
have fetched, never what is computed.
"""
from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp

from ..core import native as _native
from . import autotune as _autotune
from .flash_attention import NEG_INF, _compiler_params, _on_tpu

__all__ = ["paged_attention_arrays"]

# Module-local mirror of FLAGS_ragged_decode (no core.native subscript in
# jit-reachable code); set_flags syncs it through the watcher list.
_ragged = [bool(_native.ragged_decode[0])]
_native.ragged_decode_watchers.append(
    lambda v: _ragged.__setitem__(0, bool(v)))


def _paged_attention_reference(q, kb, vb, tables, lengths, scale):
    """Composed jnp fallback: gather each slot's blocks into a contiguous
    (nh, W*bs, hd) view, mask positions >= length, softmax in f32.

    q (B, nh, hd); kb/vb (n_blocks, nh, bs, hd); tables (B, W) int32;
    lengths (B,) int32 — live tokens per slot (including the token whose
    K/V was just written). Returns (B, nh, hd) in q.dtype."""
    B, nh, hd = q.shape
    bs = kb.shape[2]
    W = tables.shape[1]
    k = kb[tables].transpose(0, 2, 1, 3, 4).reshape(B, nh, W * bs, hd)
    v = vb[tables].transpose(0, 2, 1, 3, 4).reshape(B, nh, W * bs, hd)
    s = jnp.einsum("bhd,bhkd->bhk", q, k.astype(q.dtype)) * scale
    live = jnp.arange(W * bs)[None, :] < lengths[:, None]
    s = jnp.where(live[:, None, :], s, NEG_INF)
    w = jax.nn.softmax(s.astype(jnp.float32), axis=-1).astype(q.dtype)
    return jnp.einsum("bhk,bhkd->bhd", w, v.astype(q.dtype))


def _decode_kernel(tables_ref, lengths_ref, q_ref, k_ref, v_ref, o_ref,
                   m_s, l_s, acc_s, *, block_size, n_blocks, scale):
    from jax.experimental import pallas as pl

    b = pl.program_id(0)
    i = pl.program_id(1)

    @pl.when(i == 0)
    def _init():
        m_s[:] = jnp.full_like(m_s, NEG_INF)
        l_s[:] = jnp.zeros_like(l_s)
        acc_s[:] = jnp.zeros_like(acc_s)

    ln = lengths_ref[b]

    @pl.when(i * block_size < ln)
    def _compute():
        q = q_ref[0]                                   # (nh, hd)
        k = k_ref[0]                                   # (nh, bs, hd)
        v = v_ref[0]
        s = jax.lax.dot_general(q, k, (((1,), (2,)), ((0,), (0,))),
                                preferred_element_type=jnp.float32) * scale
        pos = i * block_size + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
        s = jnp.where(pos < ln, s, NEG_INF)            # (nh, bs) f32
        m_prev = m_s[:, 0:1]
        l_prev = l_s[:, 0:1]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1, keepdims=True))
        alpha = jnp.exp(m_prev - m_new)
        p = jnp.exp(s - m_new)
        l_s[:] = jnp.broadcast_to(
            alpha * l_prev + jnp.sum(p, -1, keepdims=True), l_s.shape)
        m_s[:] = jnp.broadcast_to(m_new, m_s.shape)
        acc_s[:] = acc_s[:] * alpha + jax.lax.dot_general(
            p.astype(v.dtype), v, (((1,), (1,)), ((0,), (0,))),
            preferred_element_type=jnp.float32)

    @pl.when(i == n_blocks - 1)
    def _finalize():
        l = l_s[:, 0:1]
        l = jnp.where(l == 0.0, 1.0, l)
        o_ref[0] = (acc_s[:] / l).astype(o_ref.dtype)


@functools.partial(jax.jit,
                   static_argnames=("scale", "interpret", "ragged"))
def _paged_decode(q, kb, vb, tables, lengths, scale, interpret=False,
                  ragged=False):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    B, nh, hd = q.shape
    bs = kb.shape[2]
    W = tables.shape[1]
    if ragged:
        # Clamp dead sweep iterations to the slot's last LIVE block: the
        # index map then repeats that block index for every i past the
        # live range, and repeated consecutive indices elide the DMA —
        # decode HBM traffic tracks live tokens, not padded table width.
        # Compute stays guarded by pl.when(i*bs < len), so which block a
        # dead iteration names never affects the output.
        def _kv_idx(b, i, tbl, ln):
            last = jnp.maximum((ln[b] - 1) // bs, 0)
            return (tbl[b, jnp.minimum(i, last)], 0, 0, 0)
    else:
        def _kv_idx(b, i, tbl, ln):
            return (tbl[b, i], 0, 0, 0)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(B, W),
        in_specs=[
            pl.BlockSpec((1, nh, hd), lambda b, i, tbl, ln: (b, 0, 0)),
            pl.BlockSpec((1, nh, bs, hd), _kv_idx),
            pl.BlockSpec((1, nh, bs, hd), _kv_idx),
        ],
        out_specs=pl.BlockSpec((1, nh, hd), lambda b, i, tbl, ln: (b, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((nh, 128), jnp.float32),   # running max
            pltpu.VMEM((nh, 128), jnp.float32),   # running sum
            pltpu.VMEM((nh, hd), jnp.float32),    # output accumulator
        ],
    )
    kernel = functools.partial(_decode_kernel, block_size=bs, n_blocks=W,
                               scale=scale)
    return pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((B, nh, hd), q.dtype),
        compiler_params=_compiler_params(
            pltpu, vmem_limit_bytes=64 * 1024 * 1024),
        interpret=interpret,
    )(tables, lengths, q, kb, vb)


def paged_attention_arrays(q, kb, vb, tables, lengths, scale=None,
                           interpret=None, ragged=None):
    """Single-token paged attention over a block pool (routed entry).

    q (B, nh, hd) — one query per slot; kb/vb (n_blocks, nh, bs, hd) —
    one LAYER's slice of the pool; tables (B, W) int32 block tables
    (entries past a slot's live blocks must point at a safe block, the
    engine reserves pool block 0); lengths (B,) int32 live tokens.

    ``ragged=None`` follows ``FLAGS_ragged_decode``; True/False forces
    the live-length-clamped (resp. full-width) K/V sweep. Either way the
    result is bit-identical — ragged only changes DMA traffic.

    Same contract as flash_attention_arrays: off-TPU (unless
    ``interpret=True`` is forced) and on untileable shapes this returns
    the identical composed jnp math, so callers never branch.
    """
    B, nh, hd = q.shape
    bs = kb.shape[2]
    if scale is None:
        scale = 1.0 / math.sqrt(hd)
    if ragged is None:
        ragged = _ragged[0]
    if interpret is None:
        interpret = False
        if not _on_tpu():
            return _paged_attention_reference(q, kb, vb, tables, lengths,
                                              scale)
    if not interpret and ((hd % 128 != 0 and hd != 64) or bs % 8 != 0
                          or nh % 8 != 0):
        _autotune.note_fallback(
            "paged_attention", (B, nh, hd),
            "head_dim=%d (needs 64 or a multiple of 128) or "
            "block_size=%d / n_heads=%d not a multiple of 8"
            % (hd, bs, nh))
        return _paged_attention_reference(q, kb, vb, tables, lengths, scale)
    return _paged_decode(q, kb, vb, jnp.asarray(tables, jnp.int32),
                         jnp.asarray(lengths, jnp.int32), float(scale),
                         interpret=bool(interpret), ragged=bool(ragged))


# -- autotune family (ISSUE 17) ---------------------------------------------
# Single-candidate: the decode kernel has no free block knob (block_size
# is fixed by the pool layout). Registered so ``python -m tools.autotune``
# can pre-warm the key and --check covers committed entries.

def _paged_candidates(shape, dtype):
    return [{}]


def _paged_bench(shape, dtype, config):
    import numpy as np

    B, nh, hd, bs, W = (int(d) for d in shape)
    rng = np.random.default_rng(0)
    n_blocks = B * W + 1
    q = jnp.asarray(rng.standard_normal((B, nh, hd)).astype(dtype))
    kb = jnp.asarray(
        rng.standard_normal((n_blocks, nh, bs, hd)).astype(dtype))
    vb = jnp.asarray(
        rng.standard_normal((n_blocks, nh, bs, hd)).astype(dtype))
    tables = jnp.asarray(
        1 + np.arange(B * W, dtype=np.int32).reshape(B, W))
    lengths = jnp.full((B,), W * bs, jnp.int32)
    out = _paged_decode(q, kb, vb, tables, lengths,
                        1.0 / math.sqrt(hd), interpret=not _on_tpu(),
                        ragged=bool(_ragged[0]))
    jax.block_until_ready(out)


_autotune.register_family("paged_attention", _paged_candidates,
                          _paged_bench)
