"""Paged-attention decode kernel — Pallas TPU, block-table gather.

The serving engine's paged KV cache (ISSUE 7) keeps each layer's K/V in
a shared block pool ``(n_blocks, n_heads, block_size, head_dim)``; a
slot's tokens live in the blocks its block table names, in table order.
The batched one-token decode step then needs attention of a single query
per slot over that slot's *scattered* blocks — this module provides it:

- :func:`paged_attention_arrays` — the routed entry every caller uses.
  On TPU with tileable shapes it runs the Pallas kernel; anywhere else
  (CPU/GPU, or untileable shapes) it runs the IDENTICAL composed jnp
  math (gather blocks by table, mask, softmax) — the same fallback
  contract as ops/flash_attention.py, pinned by interpret-mode parity
  tests (tests/test_paged_attention.py, ``-m kernels``).

Kernel design (mirrors the flash forward):
- grid ``(batch, max_blocks_per_slot)``, kv-block innermost so the VMEM
  scratch (m, l, acc) carries across one slot's block sweep;
- the block table and per-slot lengths ride as SCALAR PREFETCH
  (pltpu.PrefetchScalarGridSpec): the K/V BlockSpec index_map reads
  ``tables[b, i]`` to DMA pool block ``tables[b, i]`` directly — no
  gather materialization, HBM traffic is exactly the live blocks;
- blocks past a slot's length are skipped with ``pl.when`` (their table
  entries point at reserved garbage block 0, so the dead DMA is safe);
- scores/softmax statistics in f32, accumulator f32, output cast back.
"""
from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp

from .flash_attention import NEG_INF, _compiler_params, _on_tpu

__all__ = ["paged_attention_arrays"]


def _paged_attention_reference(q, kb, vb, tables, lengths, scale):
    """Composed jnp fallback: gather each slot's blocks into a contiguous
    (nh, W*bs, hd) view, mask positions >= length, softmax in f32.

    q (B, nh, hd); kb/vb (n_blocks, nh, bs, hd); tables (B, W) int32;
    lengths (B,) int32 — live tokens per slot (including the token whose
    K/V was just written). Returns (B, nh, hd) in q.dtype."""
    B, nh, hd = q.shape
    bs = kb.shape[2]
    W = tables.shape[1]
    k = kb[tables].transpose(0, 2, 1, 3, 4).reshape(B, nh, W * bs, hd)
    v = vb[tables].transpose(0, 2, 1, 3, 4).reshape(B, nh, W * bs, hd)
    s = jnp.einsum("bhd,bhkd->bhk", q, k.astype(q.dtype)) * scale
    live = jnp.arange(W * bs)[None, :] < lengths[:, None]
    s = jnp.where(live[:, None, :], s, NEG_INF)
    w = jax.nn.softmax(s.astype(jnp.float32), axis=-1).astype(q.dtype)
    return jnp.einsum("bhk,bhkd->bhd", w, v.astype(q.dtype))


def _decode_kernel(tables_ref, lengths_ref, q_ref, k_ref, v_ref, o_ref,
                   m_s, l_s, acc_s, *, block_size, n_blocks, scale):
    from jax.experimental import pallas as pl

    b = pl.program_id(0)
    i = pl.program_id(1)

    @pl.when(i == 0)
    def _init():
        m_s[:] = jnp.full_like(m_s, NEG_INF)
        l_s[:] = jnp.zeros_like(l_s)
        acc_s[:] = jnp.zeros_like(acc_s)

    ln = lengths_ref[b]

    @pl.when(i * block_size < ln)
    def _compute():
        q = q_ref[0]                                   # (nh, hd)
        k = k_ref[0]                                   # (nh, bs, hd)
        v = v_ref[0]
        s = jax.lax.dot_general(q, k, (((1,), (2,)), ((0,), (0,))),
                                preferred_element_type=jnp.float32) * scale
        pos = i * block_size + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
        s = jnp.where(pos < ln, s, NEG_INF)            # (nh, bs) f32
        m_prev = m_s[:, 0:1]
        l_prev = l_s[:, 0:1]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1, keepdims=True))
        alpha = jnp.exp(m_prev - m_new)
        p = jnp.exp(s - m_new)
        l_s[:] = jnp.broadcast_to(
            alpha * l_prev + jnp.sum(p, -1, keepdims=True), l_s.shape)
        m_s[:] = jnp.broadcast_to(m_new, m_s.shape)
        acc_s[:] = acc_s[:] * alpha + jax.lax.dot_general(
            p.astype(v.dtype), v, (((1,), (1,)), ((0,), (0,))),
            preferred_element_type=jnp.float32)

    @pl.when(i == n_blocks - 1)
    def _finalize():
        l = l_s[:, 0:1]
        l = jnp.where(l == 0.0, 1.0, l)
        o_ref[0] = (acc_s[:] / l).astype(o_ref.dtype)


@functools.partial(jax.jit, static_argnames=("scale", "interpret"))
def _paged_decode(q, kb, vb, tables, lengths, scale, interpret=False):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    B, nh, hd = q.shape
    bs = kb.shape[2]
    W = tables.shape[1]
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(B, W),
        in_specs=[
            pl.BlockSpec((1, nh, hd), lambda b, i, tbl, ln: (b, 0, 0)),
            pl.BlockSpec((1, nh, bs, hd),
                         lambda b, i, tbl, ln: (tbl[b, i], 0, 0, 0)),
            pl.BlockSpec((1, nh, bs, hd),
                         lambda b, i, tbl, ln: (tbl[b, i], 0, 0, 0)),
        ],
        out_specs=pl.BlockSpec((1, nh, hd), lambda b, i, tbl, ln: (b, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((nh, 128), jnp.float32),   # running max
            pltpu.VMEM((nh, 128), jnp.float32),   # running sum
            pltpu.VMEM((nh, hd), jnp.float32),    # output accumulator
        ],
    )
    kernel = functools.partial(_decode_kernel, block_size=bs, n_blocks=W,
                               scale=scale)
    return pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((B, nh, hd), q.dtype),
        compiler_params=_compiler_params(
            pltpu, vmem_limit_bytes=64 * 1024 * 1024),
        interpret=interpret,
    )(tables, lengths, q, kb, vb)


def paged_attention_arrays(q, kb, vb, tables, lengths, scale=None,
                           interpret=None):
    """Single-token paged attention over a block pool (routed entry).

    q (B, nh, hd) — one query per slot; kb/vb (n_blocks, nh, bs, hd) —
    one LAYER's slice of the pool; tables (B, W) int32 block tables
    (entries past a slot's live blocks must point at a safe block, the
    engine reserves pool block 0); lengths (B,) int32 live tokens.

    Same contract as flash_attention_arrays: off-TPU (unless
    ``interpret=True`` is forced) and on untileable shapes this returns
    the identical composed jnp math, so callers never branch.
    """
    B, nh, hd = q.shape
    bs = kb.shape[2]
    if scale is None:
        scale = 1.0 / math.sqrt(hd)
    if interpret is None:
        interpret = False
        if not _on_tpu():
            return _paged_attention_reference(q, kb, vb, tables, lengths,
                                              scale)
    if not interpret and ((hd % 128 != 0 and hd != 64) or bs % 8 != 0
                          or nh % 8 != 0):
        return _paged_attention_reference(q, kb, vb, tables, lengths, scale)
    return _paged_decode(q, kb, vb, jnp.asarray(tables, jnp.int32),
                         jnp.asarray(lengths, jnp.int32), float(scale),
                         interpret=bool(interpret))
