"""Fused residual+layernorm and GeLU/SwiGLU-MLP Pallas kernels (fwd+bwd).

The TPU answer to the reference's operators/fused/fused_feedforward and
fused_bias_dropout_residual_layer_norm kernels: the transformer block's
non-attention half — ``y = x + act(LN(x) @ W1 + b1) @ W2 + b2`` — runs as
ONE Pallas kernel streaming the MLP hidden dim through VMEM in blocks,
with a custom-VJP backward kernel that recomputes z per block (flash-style
recompute; the [R, M] activation never round-trips HBM) and accumulates
dW1/dW2 in VMEM scratch across the row sweep.

Kernels:
- :func:`fused_ln_mlp` — pre-LN residual MLP (GeLU / ReLU / SwiGLU). LN
  optional (``ln_scale=None`` skips it), residual optional — this one
  shape covers the gpt/bert block MLP half and both fused_feedforward
  layouts.
- :func:`fused_add_layernorm` — LN(x + y), the post-LN residual pattern.

Both follow the flash-attention fallback contract: off-TPU the entry
points run the IDENTICAL composed jnp math (so ``FLAGS_fused_kernels``
flips nothing numerically on CPU), ``interpret=True`` forces the Pallas
kernels through the interpreter for CPU parity tests, and shapes the
kernel can't tile (H not a lane multiple, odd row counts) fall back to
the composed math automatically.
"""
from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp

from . import autotune as _autotune
from .flash_attention import _compiler_params, _on_tpu

__all__ = ["fused_ln_mlp", "fused_add_layernorm"]

_SQRT_2_PI = math.sqrt(2.0 / math.pi)


# --------------------------------------------------------------------------
# activations (closed-form derivatives: the backward kernel can't call AD)
# --------------------------------------------------------------------------

def _act(z, kind):
    if kind == "relu":
        return jnp.maximum(z, 0.0)
    # tanh-approx gelu (jax.nn.gelu default)
    u = _SQRT_2_PI * (z + 0.044715 * z * z * z)
    return 0.5 * z * (1.0 + jnp.tanh(u))


def _act_grad(z, kind):
    if kind == "relu":
        return (z > 0.0).astype(z.dtype)
    u = _SQRT_2_PI * (z + 0.044715 * z * z * z)
    t = jnp.tanh(u)
    du = _SQRT_2_PI * (1.0 + 3.0 * 0.044715 * z * z)
    return 0.5 * (1.0 + t) + 0.5 * z * (1.0 - t * t) * du


def _silu(z):
    return z * jax.nn.sigmoid(z)


def _silu_grad(z):
    s = jax.nn.sigmoid(z)
    return s * (1.0 + z * (1.0 - s))


# --------------------------------------------------------------------------
# composed references — EXACTLY the op sequence the unfused model code
# runs (models/gpt.py _block_kv, ops/fused.py _fused_ffn), so the
# off-TPU fallback is bit-identical to the flag-off path.
# --------------------------------------------------------------------------

def _layer_norm_ref(x, scale, bias, eps):
    x32 = x.astype(jnp.float32)
    mu = jnp.mean(x32, axis=-1, keepdims=True)
    var = jnp.mean(jnp.square(x32 - mu), axis=-1, keepdims=True)
    y = (x32 - mu) * jax.lax.rsqrt(var + eps)
    return (y * scale + bias).astype(x.dtype)


def _ln_mlp_reference(x, ln_scale, ln_bias, w1, b1, w2, b2, wg, bg,
                      act, residual, has_ln, eps):
    h = _layer_norm_ref(x, ln_scale, ln_bias, eps) if has_ln else x
    if act == "swiglu":
        a = _silu(h @ wg + bg) * (h @ w1 + b1)
    elif act == "relu":
        a = jax.nn.relu(h @ w1 + b1)
    else:
        a = jax.nn.gelu(h @ w1 + b1)
    out = a @ w2 + b2
    return x + out if residual else out


# --------------------------------------------------------------------------
# forward kernel: grid (row blocks, mlp blocks), mlp innermost; the
# LN'd input and the output accumulator live in VMEM scratch across the
# mlp sweep, so x is normalized once and y written once.
# --------------------------------------------------------------------------

def _fmlp_fwd_kernel(x_ref, lns_ref, lnb_ref, w1_ref, b1_ref, w2_ref,
                     b2_ref, wg_ref, bg_ref, y_ref, mu_ref, rs_ref,
                     lnx_s, acc_s, *, act, residual, has_ln, eps, n_j):
    from jax.experimental import pallas as pl

    ji = pl.program_id(1)

    @pl.when(ji == 0)
    def _init():
        x32 = x_ref[...].astype(jnp.float32)
        if has_ln:
            mu = jnp.mean(x32, axis=-1, keepdims=True)
            var = jnp.mean(jnp.square(x32 - mu), axis=-1, keepdims=True)
            rstd = jax.lax.rsqrt(var + eps)
            lnx = (x32 - mu) * rstd * lns_ref[...] + lnb_ref[...]
        else:
            mu = jnp.zeros((x32.shape[0], 1), jnp.float32)
            rstd = jnp.ones((x32.shape[0], 1), jnp.float32)
            lnx = x32
        mu_ref[...] = mu
        rs_ref[...] = rstd
        lnx_s[...] = lnx.astype(lnx_s.dtype)
        acc_s[...] = jnp.zeros_like(acc_s)

    lnx = lnx_s[...]
    z = jax.lax.dot(lnx, w1_ref[...],
                    preferred_element_type=jnp.float32) + b1_ref[...]
    if act == "swiglu":
        zg = jax.lax.dot(lnx, wg_ref[...],
                         preferred_element_type=jnp.float32) + bg_ref[...]
        a = _silu(zg) * z
    else:
        a = _act(z, act)
    acc_s[...] += jax.lax.dot(a.astype(lnx.dtype), w2_ref[...],
                              preferred_element_type=jnp.float32)

    @pl.when(ji == n_j - 1)
    def _finalize():
        out = acc_s[...] + b2_ref[...]
        if residual:
            out = out + x_ref[...].astype(jnp.float32)
        y_ref[...] = out.astype(y_ref.dtype)


# --------------------------------------------------------------------------
# backward kernel: grid (mlp blocks, row blocks), rows innermost; dW1/dW2
# accumulate in scratch over the row sweep; per-mlp-block d(lnx) partials
# go to HBM and are summed by XLA (the flash dQ-partials pattern). The
# LN backward + residual add + db2 are cheap row-local jnp afterwards.
# --------------------------------------------------------------------------

def _fmlp_bwd_kernel(x_ref, lns_ref, lnb_ref, w1_ref, b1_ref, w2_ref,
                     wg_ref, bg_ref, mu_ref, rs_ref, dy_ref,
                     dw1_ref, db1_ref, dwg_ref, dbg_ref, dlnxp_ref,
                     dw1_s, db1_s, dwg_s, dbg_s, *,
                     act, has_ln, eps, n_r):
    from jax.experimental import pallas as pl

    ri = pl.program_id(1)

    @pl.when(ri == 0)
    def _init():
        dw1_s[...] = jnp.zeros_like(dw1_s)
        db1_s[...] = jnp.zeros_like(db1_s)
        dwg_s[...] = jnp.zeros_like(dwg_s)
        dbg_s[...] = jnp.zeros_like(dbg_s)

    x32 = x_ref[...].astype(jnp.float32)
    if has_ln:
        lnx = ((x32 - mu_ref[...]) * rs_ref[...] * lns_ref[...]
               + lnb_ref[...])
    else:
        lnx = x32
    lnx = lnx.astype(x_ref.dtype)
    dy = dy_ref[...].astype(jnp.float32)

    dim = lambda lc, rc: (((lc,), (rc,)), ((), ()))
    z = jax.lax.dot(lnx, w1_ref[...],
                    preferred_element_type=jnp.float32) + b1_ref[...]
    # da = dy @ w2^T, contracting the H dims (no in-kernel transpose)
    da = jax.lax.dot_general(dy.astype(x_ref.dtype), w2_ref[...],
                             dim(1, 1), preferred_element_type=jnp.float32)
    if act == "swiglu":
        zg = jax.lax.dot(lnx, wg_ref[...],
                         preferred_element_type=jnp.float32) + bg_ref[...]
        sg = _silu(zg)
        dz = da * sg
        dzg = da * z * _silu_grad(zg)
        dwg_s[...] += jax.lax.dot_general(      # lnx^T @ dzg
            lnx, dzg.astype(x_ref.dtype), dim(0, 0),
            preferred_element_type=jnp.float32)
        dbg_s[...] += jnp.sum(dzg, axis=0, keepdims=True)
    else:
        dz = da * _act_grad(z, act)
        dzg = None
    db1_s[...] += jnp.sum(dz, axis=0, keepdims=True)
    dw1_s[...] += jax.lax.dot_general(          # lnx^T @ dz
        lnx, dz.astype(x_ref.dtype), dim(0, 0),
        preferred_element_type=jnp.float32)
    dlnx = jax.lax.dot_general(                 # dz @ w1^T
        dz.astype(x_ref.dtype), w1_ref[...], dim(1, 1),
        preferred_element_type=jnp.float32)
    if act == "swiglu":
        dlnx = dlnx + jax.lax.dot_general(
            dzg.astype(x_ref.dtype), wg_ref[...], dim(1, 1),
            preferred_element_type=jnp.float32)
    dlnxp_ref[0] = dlnx

    @pl.when(ri == n_r - 1)
    def _finalize():
        dw1_ref[...] = dw1_s[...]
        db1_ref[...] = db1_s[...]
        dwg_ref[...] = dwg_s[...]
        dbg_ref[...] = dbg_s[...]


def _fmlp_bwd_dw2_kernel(x_ref, lns_ref, lnb_ref, w1_ref, b1_ref, wg_ref,
                         bg_ref, mu_ref, rs_ref, dy_ref, dw2_ref, dw2_s, *,
                         act, has_ln, eps, n_r):
    """dW2 = a^T dy, recomputing a per (mlp block, row block); separate
    kernel so the main backward's scratch budget stays within VMEM at
    large H·bj."""
    from jax.experimental import pallas as pl

    ri = pl.program_id(1)

    @pl.when(ri == 0)
    def _init():
        dw2_s[...] = jnp.zeros_like(dw2_s)

    x32 = x_ref[...].astype(jnp.float32)
    if has_ln:
        lnx = ((x32 - mu_ref[...]) * rs_ref[...] * lns_ref[...]
               + lnb_ref[...])
    else:
        lnx = x32
    lnx = lnx.astype(x_ref.dtype)
    z = jax.lax.dot(lnx, w1_ref[...],
                    preferred_element_type=jnp.float32) + b1_ref[...]
    if act == "swiglu":
        zg = jax.lax.dot(lnx, wg_ref[...],
                         preferred_element_type=jnp.float32) + bg_ref[...]
        a = _silu(zg) * z
    else:
        a = _act(z, act)
    dw2_s[...] += jax.lax.dot_general(          # a^T @ dy
        a.astype(x_ref.dtype), dy_ref[...], (((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)

    @pl.when(ri == n_r - 1)
    def _finalize():
        dw2_ref[...] = dw2_s[...]


# --------------------------------------------------------------------------
# pallas_call plumbing
# --------------------------------------------------------------------------

def _pick(n, cands):
    for c in cands:
        if n % c == 0 and c <= n:
            return c
    return None


def _tileable(R, H, M, dtype):
    # bf16/int8 blocks need >=16 sublanes (min tile); f32 allows 8
    cands = ((256, 128, 64, 32, 16) if jnp.dtype(dtype).itemsize < 4
             else (256, 128, 64, 32, 16, 8))
    br = _pick(R, cands)
    bj = _pick(M, (512, 256, 128))
    if br is None or bj is None or H % 128 != 0:
        return None
    return br, bj


def _fmlp_forward(x2, lns, lnb, w1, b1, w2, b2, wg, bg, act, residual,
                  has_ln, eps, br, bj, interpret):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    R, H = x2.shape
    M = w1.shape[1]
    n_r, n_j = R // br, M // bj
    row = lambda: pl.BlockSpec((br, H), lambda i, j: (i, 0))
    kernel = functools.partial(_fmlp_fwd_kernel, act=act,
                               residual=residual, has_ln=has_ln,
                               eps=eps, n_j=n_j)
    y, mu, rstd = pl.pallas_call(
        kernel,
        out_shape=(jax.ShapeDtypeStruct((R, H), x2.dtype),
                   jax.ShapeDtypeStruct((R, 1), jnp.float32),
                   jax.ShapeDtypeStruct((R, 1), jnp.float32)),
        grid=(n_r, n_j),
        in_specs=[
            row(),
            pl.BlockSpec((1, H), lambda i, j: (0, 0)),
            pl.BlockSpec((1, H), lambda i, j: (0, 0)),
            pl.BlockSpec((H, bj), lambda i, j: (0, j)),
            pl.BlockSpec((1, bj), lambda i, j: (0, j)),
            pl.BlockSpec((bj, H), lambda i, j: (j, 0)),
            pl.BlockSpec((1, H), lambda i, j: (0, 0)),
            pl.BlockSpec((H, bj), lambda i, j: (0, j)),
            pl.BlockSpec((1, bj), lambda i, j: (0, j)),
        ],
        out_specs=(row(),
                   pl.BlockSpec((br, 1), lambda i, j: (i, 0)),
                   pl.BlockSpec((br, 1), lambda i, j: (i, 0))),
        scratch_shapes=[pltpu.VMEM((br, H), x2.dtype),
                        pltpu.VMEM((br, H), jnp.float32)],
        compiler_params=_compiler_params(
            pltpu, vmem_limit_bytes=64 * 1024 * 1024),
        interpret=interpret,
    )(x2, lns, lnb, w1, b1, w2, b2, wg, bg)
    return y, mu, rstd


def _fmlp_backward(x2, lns, lnb, w1, b1, w2, wg, bg, mu, rstd, dy2,
                   act, residual, has_ln, eps, br, bj, interpret):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    R, H = x2.shape
    M = w1.shape[1]
    n_r, n_j = R // br, M // bj
    dy2 = dy2.astype(x2.dtype)

    common = [
        pl.BlockSpec((br, H), lambda j, i: (i, 0)),          # x
        pl.BlockSpec((1, H), lambda j, i: (0, 0)),           # ln scale
        pl.BlockSpec((1, H), lambda j, i: (0, 0)),           # ln bias
        pl.BlockSpec((H, bj), lambda j, i: (0, j)),          # w1
        pl.BlockSpec((1, bj), lambda j, i: (0, j)),          # b1
    ]
    tail = [
        pl.BlockSpec((H, bj), lambda j, i: (0, j)),          # wg
        pl.BlockSpec((1, bj), lambda j, i: (0, j)),          # bg
        pl.BlockSpec((br, 1), lambda j, i: (i, 0)),          # mu
        pl.BlockSpec((br, 1), lambda j, i: (i, 0)),          # rstd
        pl.BlockSpec((br, H), lambda j, i: (i, 0)),          # dy
    ]
    kernel = functools.partial(_fmlp_bwd_kernel, act=act, has_ln=has_ln,
                               eps=eps, n_r=n_r)
    dw1, db1, dwg, dbg, dlnxp = pl.pallas_call(
        kernel,
        out_shape=(jax.ShapeDtypeStruct((H, M), jnp.float32),
                   jax.ShapeDtypeStruct((1, M), jnp.float32),
                   jax.ShapeDtypeStruct((H, M), jnp.float32),
                   jax.ShapeDtypeStruct((1, M), jnp.float32),
                   jax.ShapeDtypeStruct((n_j, R, H), jnp.float32)),
        grid=(n_j, n_r),
        in_specs=common
        + [pl.BlockSpec((bj, H), lambda j, i: (j, 0))]       # w2
        + tail,
        out_specs=(pl.BlockSpec((H, bj), lambda j, i: (0, j)),
                   pl.BlockSpec((1, bj), lambda j, i: (0, j)),
                   pl.BlockSpec((H, bj), lambda j, i: (0, j)),
                   pl.BlockSpec((1, bj), lambda j, i: (0, j)),
                   pl.BlockSpec((1, br, H), lambda j, i: (j, i, 0))),
        scratch_shapes=[pltpu.VMEM((H, bj), jnp.float32),
                        pltpu.VMEM((1, bj), jnp.float32),
                        pltpu.VMEM((H, bj), jnp.float32),
                        pltpu.VMEM((1, bj), jnp.float32)],
        compiler_params=_compiler_params(
            pltpu, vmem_limit_bytes=64 * 1024 * 1024),
        interpret=interpret,
    )(x2, lns, lnb, w1, b1, w2, wg, bg, mu, rstd, dy2)

    dw2 = pl.pallas_call(
        functools.partial(_fmlp_bwd_dw2_kernel, act=act, has_ln=has_ln,
                          eps=eps, n_r=n_r),
        out_shape=jax.ShapeDtypeStruct((M, H), jnp.float32),
        grid=(n_j, n_r),
        in_specs=common + tail,
        out_specs=pl.BlockSpec((bj, H), lambda j, i: (j, 0)),
        scratch_shapes=[pltpu.VMEM((bj, H), jnp.float32)],
        compiler_params=_compiler_params(
            pltpu, vmem_limit_bytes=64 * 1024 * 1024),
        interpret=interpret,
    )(x2, lns, lnb, w1, b1, wg, bg, mu, rstd, dy2)

    dy32 = dy2.astype(jnp.float32)
    db2 = jnp.sum(dy32, axis=0, keepdims=True)               # [1, H]
    dlnx = jnp.sum(dlnxp, axis=0)                            # [R, H] f32
    x32 = x2.astype(jnp.float32)
    if has_ln:
        xhat = (x32 - mu) * rstd
        dscale = jnp.sum(dlnx * xhat, axis=0)
        dbias = jnp.sum(dlnx, axis=0)
        dxhat = dlnx * lns.astype(jnp.float32)
        mean1 = jnp.mean(dxhat, axis=-1, keepdims=True)
        mean2 = jnp.mean(dxhat * xhat, axis=-1, keepdims=True)
        dx = rstd * (dxhat - mean1 - xhat * mean2)
    else:
        dscale = jnp.zeros((H,), jnp.float32)
        dbias = jnp.zeros((H,), jnp.float32)
        dx = dlnx
    if residual:
        dx = dx + dy32
    return (dx.astype(x2.dtype), dscale, dbias, dw1, db1, dw2, db2,
            dwg, dbg)


# -- differentiable entry ---------------------------------------------------

@functools.partial(jax.custom_vjp, nondiff_argnums=(9, 10, 11, 12, 13, 14,
                                                    15))
def _fmlp(x2, lns, lnb, w1, b1, w2, b2, wg, bg, act, residual, has_ln,
          eps, br, bj, interpret):
    y, _, _ = _fmlp_forward(x2, lns, lnb, w1, b1, w2, b2, wg, bg, act,
                            residual, has_ln, eps, br, bj, interpret)
    return y


def _fmlp_fwd_rule(x2, lns, lnb, w1, b1, w2, b2, wg, bg, act, residual,
                   has_ln, eps, br, bj, interpret):
    y, mu, rstd = _fmlp_forward(x2, lns, lnb, w1, b1, w2, b2, wg, bg, act,
                                residual, has_ln, eps, br, bj, interpret)
    return y, (x2, lns, lnb, w1, b1, w2, wg, bg, mu, rstd)


def _fmlp_bwd_rule(act, residual, has_ln, eps, br, bj, interpret, res, g):
    x2, lns, lnb, w1, b1, w2, wg, bg, mu, rstd = res
    dx, dscale, dbias, dw1, db1, dw2, db2, dwg, dbg = _fmlp_backward(
        x2, lns, lnb, w1, b1, w2, wg, bg, mu, rstd, g, act, residual,
        has_ln, eps, br, bj, interpret)
    return (dx, dscale.reshape(lns.shape).astype(lns.dtype),
            dbias.reshape(lnb.shape).astype(lnb.dtype),
            dw1.astype(w1.dtype), db1.reshape(b1.shape).astype(b1.dtype),
            dw2.astype(w2.dtype), db2.astype(w2.dtype),
            dwg.astype(wg.dtype), dbg.reshape(bg.shape).astype(bg.dtype))


_fmlp.defvjp(_fmlp_fwd_rule, _fmlp_bwd_rule)


def fused_ln_mlp(x, w1, b1, w2, b2, *, ln_scale=None, ln_bias=None,
                 residual=True, act="gelu", w_gate=None, b_gate=None,
                 eps=1e-5, interpret=None):
    """``(x if residual) + act(LN?(x) @ w1 + b1) @ w2 + b2`` — fused.

    x: [..., H]; w1 [H, M]; w2 [M, H]. ``act``: "gelu" | "relu" |
    "swiglu" (swiglu takes the gate projection via w_gate/b_gate:
    ``silu(h@w_gate+b_gate) * (h@w1+b1)``). ``ln_scale=None`` skips the
    input LayerNorm. Off-TPU (or on untileable shapes) this is the
    identical composed jnp math; ``interpret=True`` forces the Pallas
    kernels (parity tests)."""
    has_ln = ln_scale is not None
    H = x.shape[-1]
    lns = (jnp.asarray(ln_scale, jnp.float32).reshape(1, H) if has_ln
           else jnp.ones((1, H), jnp.float32))
    lnb = (jnp.asarray(ln_bias, jnp.float32).reshape(1, H) if has_ln
           else jnp.zeros((1, H), jnp.float32))
    swiglu = act == "swiglu"
    wg = w_gate if swiglu else jnp.zeros_like(w1)
    bg = (b_gate if (swiglu and b_gate is not None)
          else jnp.zeros((w1.shape[1],), w1.dtype))

    ref = lambda: _ln_mlp_reference(
        x, lns.reshape(H) if has_ln else None,
        lnb.reshape(H) if has_ln else None,
        w1, b1, w2, b2, wg, bg, act, residual, has_ln, eps)
    if interpret is None:
        if not _on_tpu():
            return ref()
        interpret = False
    lead = x.shape[:-1]
    R = 1
    for d in lead:
        R *= int(d)
    M = w1.shape[1]
    tiles = _tileable(R, H, M, x.dtype)
    if tiles is None:
        _autotune.note_fallback(
            "fused_ln_mlp", (R, H, M),
            "rows=%d / mlp=%d not tileable or hidden=%d %% 128 != 0"
            % (R, M, H))
        return ref()
    br, bj = tiles
    if _autotune.enabled():
        cfg = _autotune.get_config(
            "fused_ln_mlp", (R, H, M), str(jnp.dtype(x.dtype)),
            {"br": br, "bj": bj})
        tr, tj = int(cfg.get("br", br)), int(cfg.get("bj", bj))
        if R % tr == 0 and M % tj == 0:
            br, bj = tr, tj
    y = _fmlp(x.reshape(R, H), lns, lnb, w1, b1.reshape(1, -1), w2,
              b2.reshape(1, -1), wg, bg.reshape(1, -1), act,
              bool(residual), has_ln, float(eps), br, bj, bool(interpret))
    return y.reshape(*lead, H)


# --------------------------------------------------------------------------
# fused residual + layernorm: LN(x + y)
# --------------------------------------------------------------------------

def _addln_fwd_kernel(x_ref, y_ref, s_ref, b_ref, o_ref, mu_ref, rs_ref,
                      *, eps):
    t = x_ref[...].astype(jnp.float32) + y_ref[...].astype(jnp.float32)
    mu = jnp.mean(t, axis=-1, keepdims=True)
    var = jnp.mean(jnp.square(t - mu), axis=-1, keepdims=True)
    rstd = jax.lax.rsqrt(var + eps)
    mu_ref[...] = mu
    rs_ref[...] = rstd
    o_ref[...] = ((t - mu) * rstd * s_ref[...] + b_ref[...]).astype(
        o_ref.dtype)


def _addln_bwd_kernel(x_ref, y_ref, s_ref, mu_ref, rs_ref, do_ref,
                      dx_ref, ds_ref, db_ref, ds_s, db_s, *, eps, n_r):
    from jax.experimental import pallas as pl

    ri = pl.program_id(0)

    @pl.when(ri == 0)
    def _init():
        ds_s[...] = jnp.zeros_like(ds_s)
        db_s[...] = jnp.zeros_like(db_s)

    t = x_ref[...].astype(jnp.float32) + y_ref[...].astype(jnp.float32)
    mu = mu_ref[...]
    rstd = rs_ref[...]
    xhat = (t - mu) * rstd
    do = do_ref[...].astype(jnp.float32)
    ds_s[...] += jnp.sum(do * xhat, axis=0, keepdims=True)
    db_s[...] += jnp.sum(do, axis=0, keepdims=True)
    dxhat = do * s_ref[...]
    mean1 = jnp.mean(dxhat, axis=-1, keepdims=True)
    mean2 = jnp.mean(dxhat * xhat, axis=-1, keepdims=True)
    dx_ref[...] = (rstd * (dxhat - mean1 - xhat * mean2)).astype(
        dx_ref.dtype)

    @pl.when(ri == n_r - 1)
    def _finalize():
        ds_ref[...] = ds_s[...]
        db_ref[...] = db_s[...]


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6))
def _addln(x2, y2, s, b, eps, br, interpret):
    out, _, _ = _addln_forward(x2, y2, s, b, eps, br, interpret)
    return out


def _addln_forward(x2, y2, s, b, eps, br, interpret):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    R, H = x2.shape
    row = lambda: pl.BlockSpec((br, H), lambda i: (i, 0))
    return pl.pallas_call(
        functools.partial(_addln_fwd_kernel, eps=eps),
        out_shape=(jax.ShapeDtypeStruct((R, H), x2.dtype),
                   jax.ShapeDtypeStruct((R, 1), jnp.float32),
                   jax.ShapeDtypeStruct((R, 1), jnp.float32)),
        grid=(R // br,),
        in_specs=[row(), row(),
                  pl.BlockSpec((1, H), lambda i: (0, 0)),
                  pl.BlockSpec((1, H), lambda i: (0, 0))],
        out_specs=(row(),
                   pl.BlockSpec((br, 1), lambda i: (i, 0)),
                   pl.BlockSpec((br, 1), lambda i: (i, 0))),
        compiler_params=_compiler_params(
            pltpu, vmem_limit_bytes=64 * 1024 * 1024),
        interpret=interpret,
    )(x2, y2, s, b)


def _addln_fwd_rule(x2, y2, s, b, eps, br, interpret):
    out, mu, rstd = _addln_forward(x2, y2, s, b, eps, br, interpret)
    return out, (x2, y2, s, mu, rstd)


def _addln_bwd_rule(eps, br, interpret, res, g):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    x2, y2, s, mu, rstd = res
    R, H = x2.shape
    row = lambda: pl.BlockSpec((br, H), lambda i: (i, 0))
    dx, ds, db = pl.pallas_call(
        functools.partial(_addln_bwd_kernel, eps=eps, n_r=R // br),
        out_shape=(jax.ShapeDtypeStruct((R, H), x2.dtype),
                   jax.ShapeDtypeStruct((1, H), jnp.float32),
                   jax.ShapeDtypeStruct((1, H), jnp.float32)),
        grid=(R // br,),
        in_specs=[row(), row(),
                  pl.BlockSpec((1, H), lambda i: (0, 0)),
                  pl.BlockSpec((br, 1), lambda i: (i, 0)),
                  pl.BlockSpec((br, 1), lambda i: (i, 0)),
                  row()],
        out_specs=(row(),
                   pl.BlockSpec((1, H), lambda i: (0, 0)),
                   pl.BlockSpec((1, H), lambda i: (0, 0))),
        scratch_shapes=[pltpu.VMEM((1, H), jnp.float32),
                        pltpu.VMEM((1, H), jnp.float32)],
        compiler_params=_compiler_params(
            pltpu, vmem_limit_bytes=64 * 1024 * 1024),
        interpret=interpret,
    )(x2, y2, s, mu, rstd, g.astype(x2.dtype))
    return dx, dx, ds.reshape(s.shape).astype(s.dtype), \
        db.reshape(s.shape).astype(s.dtype)


_addln.defvjp(_addln_fwd_rule, _addln_bwd_rule)


def fused_add_layernorm(x, y, scale, bias, eps=1e-5, interpret=None):
    """LN(x + y) — the post-LN residual pattern, fused.

    Same fallback contract as :func:`fused_ln_mlp`: composed jnp off-TPU
    or on untileable shapes; ``interpret=True`` for parity tests."""
    H = x.shape[-1]
    # composed reference = the exact unfused pattern (residual add in the
    # compute dtype, then the fp32-stats LayerNorm)
    ref = lambda: _layer_norm_ref(x + y, scale, bias, eps)
    if interpret is None:
        if not _on_tpu():
            return ref()
        interpret = False
    lead = x.shape[:-1]
    R = 1
    for d in lead:
        R *= int(d)
    br = _pick(R, (256, 128, 64, 32, 16, 8))
    if br is None or H % 128 != 0:
        _autotune.note_fallback(
            "fused_add_ln", (R, H),
            "rows=%d has no legal row block or hidden=%d %% 128 != 0"
            % (R, H))
        return ref()
    if _autotune.enabled():
        cfg = _autotune.get_config("fused_add_ln", (R, H),
                                   str(jnp.dtype(x.dtype)), {"br": br})
        tr = int(cfg.get("br", br))
        if R % tr == 0:
            br = tr
    out = _addln(x.reshape(R, H), y.reshape(R, H),
                 jnp.asarray(scale, jnp.float32).reshape(1, H),
                 jnp.asarray(bias, jnp.float32).reshape(1, H),
                 float(eps), br, bool(interpret))
    return out.reshape(*lead, H)


# -- autotune families (ISSUE 17) ------------------------------------------

def _fmlp_candidates(shape, dtype):
    R, H, M = shape
    if _tileable(R, H, M, jnp.dtype(dtype)) is None:
        return []
    row_cands = ((256, 128, 64, 32, 16)
                 if jnp.dtype(dtype).itemsize < 4
                 else (256, 128, 64, 32, 16, 8))
    brs = [c for c in row_cands if R % c == 0][:2]
    bjs = [c for c in (512, 256, 128) if M % c == 0][:2]
    return [{"br": br, "bj": bj} for br in brs for bj in bjs][:5]


def _fmlp_bench(shape, dtype, config):
    import numpy as np

    R, H, M = shape
    rng = np.random.default_rng(0)
    dt = jnp.dtype(dtype)
    x = jnp.asarray(rng.standard_normal((R, H)), dt)
    ones = jnp.ones((1, H), jnp.float32)
    w1 = jnp.asarray(rng.standard_normal((H, M)) * 0.05, dt)
    w2 = jnp.asarray(rng.standard_normal((M, H)) * 0.05, dt)
    zb1 = jnp.zeros((1, M), dt)
    zb2 = jnp.zeros((1, H), dt)
    y, _, _ = _fmlp_forward(
        x, ones, jnp.zeros((1, H), jnp.float32), w1, zb1, w2, zb2,
        jnp.zeros_like(w1), zb1, "gelu", True, True, 1e-5,
        int(config["br"]), int(config["bj"]), not _on_tpu())
    jax.block_until_ready(y)


def _addln_candidates(shape, dtype):
    R, H = shape
    if H % 128 != 0:
        return []
    return [{"br": c} for c in (256, 128, 64, 32, 16, 8)
            if R % c == 0][:4]


def _addln_bench(shape, dtype, config):
    import numpy as np

    R, H = shape
    rng = np.random.default_rng(0)
    dt = jnp.dtype(dtype)
    x = jnp.asarray(rng.standard_normal((R, H)), dt)
    y = jnp.asarray(rng.standard_normal((R, H)), dt)
    out, _, _ = _addln_forward(
        x, y, jnp.ones((1, H), jnp.float32), jnp.zeros((1, H), jnp.float32),
        1e-5, int(config["br"]), not _on_tpu())
    jax.block_until_ready(out)


_autotune.register_family("fused_ln_mlp", _fmlp_candidates, _fmlp_bench)
_autotune.register_family("fused_add_ln", _addln_candidates, _addln_bench)
