"""Fused AdamW/LAMB update — one pass over flat param/moment buffers.

The analog of the reference's fused optimizer kernels
(operators/optimizers/adam_op.cu run once per parameter, and
operators/fused/fused_adam_op multi-tensor form): instead of an unfused
per-leaf ``tree_map`` — one XLA kernel launch per parameter, each reading
p/g/m/v and writing p/m/v with poor occupancy on small leaves — the
param/grad/moment pytrees are flattened into a few contiguous
dtype-homogeneous buffers ("buckets") and updated in ONE Pallas pass per
bucket (one HBM round-trip, full-width VPU blocks).

Two consumers, two shapes of the same math:

- **in-jit** (:func:`fused_adamw_update` / :func:`fused_lamb_update`):
  drop-in replacements for ``pure_adamw_update`` / ``pure_lamb_update``
  (parallel/train_step.py) with identical signatures AND identical state
  layout (m/v stay per-leaf trees, so checkpoints and ZeRO specs are
  unchanged); leaves are bucketed/concatenated inside the jit.
- **eager** (:func:`fused_eager_step`): replaces ``Optimizer.step``'s
  per-parameter jit-dispatch loop (N device round-trips per step) with
  ONE jitted dispatch over device-resident moments — the big win for
  eager training, where dispatch dominates.

Backend split (measured): on TPU each bucket runs the flat Pallas pass;
off-TPU the same formula stays per-leaf INSIDE the single program —
XLA CPU materializes every concat/split as a real copy (~8ms per
100-leaf round-trip vs ~2ms for the per-leaf math), so flattening there
would eat the dispatch win. Numerics are identical either way (the flat
reference is the per-leaf formula applied elementwise); the Pallas
kernels themselves are covered by interpret-mode parity tests
(tests/test_fused_kernels.py). ``FLAGS_fused_optimizer`` gates all
wiring; unset, every caller keeps the historical unfused path untouched.
"""
from __future__ import annotations

import functools
import time

import jax
import jax.numpy as jnp

from ..monitor import benchmark as _bench
from ..monitor.stats import FUSED_OPTIMIZER_STEPS
from ..monitor.trace import span as _trace_span
from . import autotune as _autotune
from .flash_attention import _compiler_params, _on_tpu

__all__ = ["adamw_flat", "lamb_moments_flat", "fused_adamw_update",
           "fused_lamb_update", "fused_update_from_slots",
           "fused_eager_step", "flatten_bucket", "unflatten_bucket"]

_LANE = 1024          # 8 f32 sublanes x 128 lanes
_SUB = 16             # row padding multiple (bf16 min tile sublanes)


# --------------------------------------------------------------------------
# flat buffer helpers
# --------------------------------------------------------------------------

def flatten_bucket(leaves):
    """Concat raveled leaves into one 1-D buffer (shared dtype)."""
    if len(leaves) == 1:
        return jnp.ravel(leaves[0])
    return jnp.concatenate([jnp.ravel(x) for x in leaves])


def unflatten_bucket(flat, shapes, dtype=None):
    """Split a flat buffer back into leaves of the given shapes."""
    out, off = [], 0
    for s in shapes:
        n = 1
        for d in s:
            n *= int(d)
        leaf = jax.lax.dynamic_slice_in_dim(flat, off, n).reshape(s)
        out.append(leaf if dtype is None else leaf.astype(dtype))
        off += n
    return out


def _pad_2d(flat):
    """1-D buffer → (R, 1024) with R a multiple of 16 (tile-aligned)."""
    n = flat.shape[0]
    rows = -(-n // _LANE)
    rows = -(-rows // _SUB) * _SUB
    pad = rows * _LANE - n
    if pad:
        flat = jnp.pad(flat, (0, pad))
    return flat.reshape(rows, _LANE), n


def _block_rows(rows: int) -> int:
    for bb in (512, 256, 128, 64, 32, 16):
        if rows % bb == 0:
            return bb
    return rows


# --------------------------------------------------------------------------
# AdamW flat update (Pallas kernel + identical jnp fallback)
# --------------------------------------------------------------------------
#
# Math (f32 regardless of storage dtype):
#   g' = g + l2*p                                  (classic-Adam L2)
#   m' = b1*m + (1-b1)*g' ;  v' = b2*v + (1-b2)*g'^2
#   step = (m'/bc1) / (sqrt(v'/bc2) + eps)         [pure form], or
#   step = sqrt(bc2)/bc1 * m' / (sqrt(v') + eps)   [eager form — matches
#                                                   Adam._pure_update's
#                                                   lr_t algebra exactly]
#   p' = p*(1 - lr*wd) - lr*step                   (decoupled decay first)
#
# Scalars (lr, bc1, bc2) ride in SMEM so schedules never recompile.

def _adamw_kernel(sc_ref, p_ref, g_ref, m_ref, v_ref,
                  np_ref, nm_ref, nv_ref, *, b1, b2, eps, wd, l2,
                  eager_form):
    lr, bc1, bc2 = sc_ref[0], sc_ref[1], sc_ref[2]
    p = p_ref[...].astype(jnp.float32)
    g = g_ref[...].astype(jnp.float32)
    m = m_ref[...].astype(jnp.float32)
    v = v_ref[...].astype(jnp.float32)
    if l2:
        g = g + l2 * p
    m = b1 * m + (1.0 - b1) * g
    v = b2 * v + (1.0 - b2) * (g * g)
    if eager_form:
        step = (jnp.sqrt(bc2) / bc1) * m / (jnp.sqrt(v) + eps)
    else:
        step = (m / bc1) / (jnp.sqrt(v / bc2) + eps)
    p = p * (1.0 - lr * wd) - lr * step
    np_ref[...] = p.astype(np_ref.dtype)
    nm_ref[...] = m.astype(nm_ref.dtype)
    nv_ref[...] = v.astype(nv_ref.dtype)


def _adamw_flat_ref(p, g, m, v, lr, bc1, bc2, *, b1, b2, eps, wd, l2,
                    eager_form):
    """jnp reference — the SAME op sequence the kernel runs."""
    p32 = p.astype(jnp.float32)
    g32 = g.astype(jnp.float32)
    m32 = m.astype(jnp.float32)
    v32 = v.astype(jnp.float32)
    if l2:
        g32 = g32 + l2 * p32
    m32 = b1 * m32 + (1.0 - b1) * g32
    v32 = b2 * v32 + (1.0 - b2) * (g32 * g32)
    if eager_form:
        step = (jnp.sqrt(bc2) / bc1) * m32 / (jnp.sqrt(v32) + eps)
    else:
        step = (m32 / bc1) / (jnp.sqrt(v32 / bc2) + eps)
    p32 = p32 * (1.0 - lr * wd) - lr * step
    return p32.astype(p.dtype), m32.astype(m.dtype), v32.astype(v.dtype)


def adamw_flat(p, g, m, v, lr, bc1, bc2, *, b1=0.9, b2=0.999, eps=1e-8,
               wd=0.0, l2=0.0, eager_form=False, interpret=None):
    """One-pass AdamW over a flat 1-D bucket → (new_p, new_m, new_v).

    ``interpret=None`` auto-selects: the Pallas kernel on TPU, the
    identical jnp math elsewhere; ``interpret=True`` forces the kernel
    through the Pallas interpreter (parity tests)."""
    kw = dict(b1=float(b1), b2=float(b2), eps=float(eps), wd=float(wd),
              l2=float(l2), eager_form=bool(eager_form))
    if interpret is None:
        if not _on_tpu():
            return _adamw_flat_ref(p, g, m, v, lr, bc1, bc2, **kw)
        interpret = False
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    n = p.shape[0]
    p2, _ = _pad_2d(p)
    g2, _ = _pad_2d(g)
    m2, _ = _pad_2d(m)
    v2, _ = _pad_2d(v)
    rows = p2.shape[0]
    bb = _block_rows(rows)
    if _autotune.enabled():
        cfg = _autotune.get_config("fused_adamw", (rows,),
                                   str(jnp.dtype(p.dtype)), {"bb": bb})
        tb = int(cfg.get("bb", 0) or 0)
        if tb and rows % tb == 0:
            bb = tb
    sc = jnp.stack([jnp.asarray(lr, jnp.float32),
                    jnp.asarray(bc1, jnp.float32),
                    jnp.asarray(bc2, jnp.float32)])
    blk = lambda: pl.BlockSpec((bb, _LANE), lambda i: (i, 0))
    np2, nm2, nv2 = pl.pallas_call(
        functools.partial(_adamw_kernel, **kw),
        out_shape=(jax.ShapeDtypeStruct(p2.shape, p.dtype),
                   jax.ShapeDtypeStruct(m2.shape, m.dtype),
                   jax.ShapeDtypeStruct(v2.shape, v.dtype)),
        grid=(rows // bb,),
        in_specs=[pl.BlockSpec(memory_space=pltpu.SMEM),
                  blk(), blk(), blk(), blk()],
        out_specs=(blk(), blk(), blk()),
        compiler_params=_compiler_params(
            pltpu, vmem_limit_bytes=64 * 1024 * 1024),
        interpret=interpret,
    )(sc, p2, g2, m2, v2)
    return (np2.reshape(-1)[:n], nm2.reshape(-1)[:n], nv2.reshape(-1)[:n])


# --------------------------------------------------------------------------
# LAMB: fused moment/trust-ratio-dividend pass; the per-parameter trust
# ratio (a per-leaf norm pair) is applied outside the kernel — still one
# HBM pass for the moment math, then cheap reductions.
# --------------------------------------------------------------------------

def _lamb_kernel(sc_ref, p_ref, g_ref, m_ref, v_ref,
                 nm_ref, nv_ref, r_ref, *, b1, b2, eps, wd):
    bc1, bc2 = sc_ref[1], sc_ref[2]
    p = p_ref[...].astype(jnp.float32)
    g = g_ref[...].astype(jnp.float32)
    m = m_ref[...].astype(jnp.float32)
    v = v_ref[...].astype(jnp.float32)
    m = b1 * m + (1.0 - b1) * g
    v = b2 * v + (1.0 - b2) * (g * g)
    r = (m / bc1) / (jnp.sqrt(v / bc2) + eps) + wd * p
    nm_ref[...] = m.astype(nm_ref.dtype)
    nv_ref[...] = v.astype(nv_ref.dtype)
    r_ref[...] = r


def _lamb_flat_ref(p, g, m, v, bc1, bc2, *, b1, b2, eps, wd):
    p32 = p.astype(jnp.float32)
    g32 = g.astype(jnp.float32)
    m32 = b1 * m.astype(jnp.float32) + (1.0 - b1) * g32
    v32 = b2 * v.astype(jnp.float32) + (1.0 - b2) * (g32 * g32)
    r = (m32 / bc1) / (jnp.sqrt(v32 / bc2) + eps) + wd * p32
    return m32.astype(m.dtype), v32.astype(v.dtype), r


def lamb_moments_flat(p, g, m, v, bc1, bc2, *, b1=0.9, b2=0.999, eps=1e-6,
                      wd=0.0, interpret=None):
    """Fused LAMB moment update → (new_m, new_v, trust_dividend r)."""
    kw = dict(b1=float(b1), b2=float(b2), eps=float(eps), wd=float(wd))
    if interpret is None:
        if not _on_tpu():
            return _lamb_flat_ref(p, g, m, v, bc1, bc2, **kw)
        interpret = False
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    n = p.shape[0]
    p2, _ = _pad_2d(p)
    g2, _ = _pad_2d(g)
    m2, _ = _pad_2d(m)
    v2, _ = _pad_2d(v)
    rows = p2.shape[0]
    bb = _block_rows(rows)
    sc = jnp.stack([jnp.float32(0.0), jnp.asarray(bc1, jnp.float32),
                    jnp.asarray(bc2, jnp.float32)])
    blk = lambda: pl.BlockSpec((bb, _LANE), lambda i: (i, 0))
    nm2, nv2, r2 = pl.pallas_call(
        functools.partial(_lamb_kernel, **kw),
        out_shape=(jax.ShapeDtypeStruct(m2.shape, m.dtype),
                   jax.ShapeDtypeStruct(v2.shape, v.dtype),
                   jax.ShapeDtypeStruct(p2.shape, jnp.float32)),
        grid=(rows // bb,),
        in_specs=[pl.BlockSpec(memory_space=pltpu.SMEM),
                  blk(), blk(), blk(), blk()],
        out_specs=(blk(), blk(), blk()),
        compiler_params=_compiler_params(
            pltpu, vmem_limit_bytes=64 * 1024 * 1024),
        interpret=interpret,
    )(sc, p2, g2, m2, v2)
    return (nm2.reshape(-1)[:n], nv2.reshape(-1)[:n], r2.reshape(-1)[:n])


# --------------------------------------------------------------------------
# bucket executors: ONE program either way, but the flat concat/kernel
# layout only on TPU — XLA CPU materializes every concat/split as a real
# copy (measured ~8ms per 100-leaf round-trip vs ~2ms for the same math
# left per-leaf inside one program), while on TPU the flat Pallas pass
# is the whole point. Numerics are identical: the flat reference IS the
# per-leaf formula applied elementwise.
# --------------------------------------------------------------------------

def _bucket_adamw(ps, gs, ms, vs, lr, bc1, bc2, *, b1, b2, eps, wd,
                  l2=0.0, eager_form=False, store=None):
    """AdamW over one bucket's leaf lists → (new_ps, new_ms, new_vs)."""
    kw = dict(b1=b1, b2=b2, eps=eps, wd=wd, l2=l2, eager_form=eager_form)
    if _on_tpu():
        sdt = store or ms[0].dtype
        npf, nmf, nvf = adamw_flat(
            flatten_bucket(ps), flatten_bucket(gs),
            flatten_bucket([m.astype(sdt) for m in ms]),
            flatten_bucket([v.astype(sdt) for v in vs]),
            lr, bc1, bc2, **kw)
        shapes = [p.shape for p in ps]
        return (unflatten_bucket(npf, shapes),
                unflatten_bucket(nmf, shapes),
                unflatten_bucket(nvf, shapes))
    out = [_adamw_flat_ref(p, g,
                           m if store is None else m.astype(store),
                           v if store is None else v.astype(store),
                           lr, bc1, bc2, **kw)
           for p, g, m, v in zip(ps, gs, ms, vs)]
    return ([o[0] for o in out], [o[1] for o in out],
            [o[2] for o in out])


def _bucket_lamb(ps, gs, ms, vs, bc1, bc2, *, b1, b2, eps, wd):
    """LAMB moments over one bucket → (new_ms, new_vs, rs)."""
    kw = dict(b1=b1, b2=b2, eps=eps, wd=wd)
    if _on_tpu():
        nmf, nvf, rf = lamb_moments_flat(
            flatten_bucket(ps), flatten_bucket(gs), flatten_bucket(ms),
            flatten_bucket(vs), bc1, bc2, **kw)
        shapes = [p.shape for p in ps]
        return (unflatten_bucket(nmf, shapes),
                unflatten_bucket(nvf, shapes),
                unflatten_bucket(rf, shapes))
    out = [_lamb_flat_ref(p, g, m, v, bc1, bc2, **kw)
           for p, g, m, v in zip(ps, gs, ms, vs)]
    return ([o[0] for o in out], [o[1] for o in out],
            [o[2] for o in out])


# --------------------------------------------------------------------------
# in-jit tree-level updates (pure_adamw_update / pure_lamb_update parity)
# --------------------------------------------------------------------------

def _bucket_indices(flat_p, flat_m, flat_wd):
    """Group leaf indices by (param dtype, moment dtype, decay coeff)."""
    buckets: dict = {}
    for i, (p, m, wd) in enumerate(zip(flat_p, flat_m, flat_wd)):
        buckets.setdefault(
            (jnp.dtype(p.dtype), jnp.dtype(m.dtype), float(wd)),
            []).append(i)
    return buckets


def fused_adamw_update(params, grads, state, lr, beta1=0.9, beta2=0.999,
                       eps=1e-8, weight_decay=0.01, l2_coeff=0.0,
                       mv_dtype=None, decay_mask=None):
    """pure_adamw_update drop-in: same signature, same state layout
    (per-leaf m/v trees), the math executed as one flat pass per
    (dtype, decay) bucket. FLAGS_fused_optimizer selects it inside
    jit.TrainStep / DistributedTrainStep."""
    count = state["count"] + 1
    c = count.astype(jnp.float32)
    bc1 = 1.0 - beta1 ** c
    bc2 = 1.0 - beta2 ** c

    flat_p, treedef = jax.tree_util.tree_flatten(params)
    flat_g = treedef.flatten_up_to(grads)
    flat_m = treedef.flatten_up_to(state["m"])
    flat_v = treedef.flatten_up_to(state["v"])
    flat_wd = ([weight_decay] * len(flat_p) if decay_mask is None else
               [weight_decay if dm else 0.0
                for dm in treedef.flatten_up_to(decay_mask)])
    store = [(m.dtype if mv_dtype is None else mv_dtype) for m in flat_m]

    new_p = [None] * len(flat_p)
    new_m = [None] * len(flat_p)
    new_v = [None] * len(flat_p)
    for (pdt, mdt, wd), idx in _bucket_indices(flat_p, flat_m,
                                               flat_wd).items():
        nps, nms, nvs = _bucket_adamw(
            [flat_p[i] for i in idx],
            [flat_g[i].astype(jnp.float32) for i in idx],
            [flat_m[i] for i in idx], [flat_v[i] for i in idx],
            lr, bc1, bc2, b1=beta1, b2=beta2, eps=eps, wd=wd,
            l2=l2_coeff, store=store[idx[0]])
        for i, pl_, ml_, vl_ in zip(idx, nps, nms, nvs):
            new_p[i], new_m[i], new_v[i] = pl_, ml_, vl_
    unflat = functools.partial(jax.tree_util.tree_unflatten, treedef)
    return unflat(new_p), {"m": unflat(new_m), "v": unflat(new_v),
                           "count": count}


def fused_lamb_update(params, grads, state, lr, beta1=0.9, beta2=0.999,
                      eps=1e-6, weight_decay=0.01, decay_mask=None, **_):
    """pure_lamb_update drop-in: fused moment/dividend pass per bucket,
    then the per-PARAMETER trust ratio ‖p‖/‖r‖ applied per leaf."""
    count = state["count"] + 1
    c = count.astype(jnp.float32)
    bc1 = 1.0 - beta1 ** c
    bc2 = 1.0 - beta2 ** c

    flat_p, treedef = jax.tree_util.tree_flatten(params)
    flat_g = treedef.flatten_up_to(grads)
    flat_m = treedef.flatten_up_to(state["m"])
    flat_v = treedef.flatten_up_to(state["v"])
    flat_wd = ([weight_decay] * len(flat_p) if decay_mask is None else
               [weight_decay if dm else 0.0
                for dm in treedef.flatten_up_to(decay_mask)])

    new_p = [None] * len(flat_p)
    new_m = [None] * len(flat_p)
    new_v = [None] * len(flat_p)
    for (pdt, mdt, wd), idx in _bucket_indices(flat_p, flat_m,
                                               flat_wd).items():
        ms, vs, rs = _bucket_lamb(
            [flat_p[i] for i in idx],
            [flat_g[i].astype(jnp.float32) for i in idx],
            [flat_m[i] for i in idx], [flat_v[i] for i in idx],
            bc1, bc2, b1=beta1, b2=beta2, eps=eps, wd=wd)
        for j, i in enumerate(idx):
            p32 = flat_p[i].astype(jnp.float32)
            r = rs[j]
            p_norm = jnp.sqrt(jnp.sum(jnp.square(p32)))
            r_norm = jnp.sqrt(jnp.sum(jnp.square(r)))
            trust = jnp.where((p_norm > 0) & (r_norm > 0),
                              p_norm / r_norm, 1.0)
            new_p[i] = (p32 - lr * trust * r).astype(flat_p[i].dtype)
            new_m[i], new_v[i] = ms[j], vs[j]
    unflat = functools.partial(jax.tree_util.tree_unflatten, treedef)
    return unflat(new_p), {"m": unflat(new_m), "v": unflat(new_v),
                           "count": count}


# --------------------------------------------------------------------------
# jit.TrainStep bridge: same per-param slot layout (m1, m2, b1p, b2p),
# fused execution. Slots are materialized together at TrainStep build, so
# every param's beta-pow pair advances in lockstep — the first leaf's pair
# is the bucket's bias correction.
# --------------------------------------------------------------------------

def fused_update_from_slots(opt, param_names, params, grads, slots, lr,
                            hyper):
    """Fused Adam/AdamW update over TrainStep's named state dicts.

    ``slots[k] = [m1, m2, b1p, b2p]``; returns (new_params, new_slots)
    with the identical layout. ``hyper[k]`` is the param's static hyper
    tuple (b1/b2/eps[/coeff]) — part of the bucket key, so AdamW's
    apply_decay_param_fun exclusions land in their own buckets."""
    k0 = param_names[0]
    b1p, b2p = slots[k0][2], slots[k0][3]
    h0 = dict(hyper[k0])
    b1, b2 = h0["b1"], h0["b2"]
    # slot convention (Adam._init_slot/_pure_update): b1p already holds
    # beta1^t when the step runs; the pow advances AFTER use
    bc1 = 1.0 - b1p
    bc2 = 1.0 - b2p

    buckets: dict = {}
    for k in param_names:
        h = dict(hyper[k])
        key = (jnp.dtype(params[k].dtype), float(h.get("coeff", 0.0)),
               float(h["eps"]))
        buckets.setdefault(key, []).append(k)

    new_params, new_slots = {}, {}
    for (pdt, wd, eps), keys in buckets.items():
        nps, nms, nvs = _bucket_adamw(
            [params[k] for k in keys],
            [grads[k].astype(jnp.float32) for k in keys],
            [slots[k][0] for k in keys], [slots[k][1] for k in keys],
            jnp.asarray(lr, jnp.float32), bc1, bc2,
            b1=b1, b2=b2, eps=eps, wd=wd, eager_form=True)
        for k, pl_, ml_, vl_ in zip(keys, nps, nms, nvs):
            new_params[k] = pl_
            new_slots[k] = [ml_, vl_, b1p * b1, b2p * b2]
    return new_params, new_slots


# --------------------------------------------------------------------------
# eager Optimizer.step fast path: ONE device dispatch per step over
# persistent flat moment buffers (vs N per-param jit calls).
# --------------------------------------------------------------------------

class _FusedEagerState:
    """Per-optimizer cache: bucket layout + device-resident moments.

    Built lazily from the optimizer's existing per-param slots (so a
    half-trained optimizer can switch the flag on mid-run), kept in
    lockstep afterwards; ``sync_slots`` writes the moments back into
    ``opt._accumulators`` for state_dict/checkpoint readers. The whole
    step is ONE jitted dispatch; inside it each bucket runs through
    :func:`_bucket_adamw`/:func:`_bucket_lamb` (flat Pallas pass on
    TPU, per-leaf math elsewhere)."""

    def __init__(self, opt, params_grads, kind):
        self.kind = kind                      # "adam" | "lamb"
        self.params = [p for p, _ in params_grads]
        self.sig = tuple((id(p), tuple(p._data.shape), str(p._data.dtype))
                         for p in self.params)
        buckets: dict = {}
        for i, p in enumerate(self.params):
            h = dict(opt._hyper(p))
            l2 = 0.0
            reg = (p.regularizer if p.regularizer is not None
                   else opt._weight_decay)
            from ..regularizer import L2Decay
            if isinstance(reg, L2Decay) and not opt._decoupled_wd():
                l2 = float(reg.coeff)
            lr_mult = float(p.optimize_attr.get("learning_rate", 1.0))
            slots = opt._get_slots(p)
            key = (str(p._data.dtype), str(slots[0].dtype),
                   float(h.get("coeff", h.get("wd", 0.0))),
                   float(h["eps"]), l2, lr_mult)
            buckets.setdefault(key, []).append(i)
        self.buckets = [(key, idx) for key, idx in buckets.items()]
        self.b1 = float(opt._beta1)
        self.b2 = float(opt._beta2)
        # device-resident moments per bucket (leaf lists, slot order)
        self.ms, self.vs = [], []
        for _, idx in self.buckets:
            ms, vs = [], []
            for i in idx:
                s = opt._get_slots(self.params[i])
                ms.append(s[0])
                vs.append(s[1])
            self.ms.append(ms)
            self.vs.append(vs)
        s0 = opt._get_slots(self.params[0])
        self.b1p, self.b2p = s0[2], s0[3]
        self._fn = None

    def _build(self):
        buckets, b1, b2, kind = self.buckets, self.b1, self.b2, self.kind

        def run(plist, glist, mlist, vlist, b1p, b2p, lr):
            # b1p/b2p already hold beta^t at use time (slot convention)
            bc1 = 1.0 - b1p
            bc2 = 1.0 - b2p
            new_p = list(plist)
            new_m, new_v = [], []
            for bi, (key, idx) in enumerate(buckets):
                _, _, wd, eps, l2, lr_mult = key
                ps = [plist[i] for i in idx]
                gs = [glist[i].astype(jnp.float32) for i in idx]
                blr = lr * lr_mult
                if kind == "adam":
                    nps, nms, nvs = _bucket_adamw(
                        ps, gs, mlist[bi], vlist[bi], blr, bc1, bc2,
                        b1=b1, b2=b2, eps=eps, wd=wd, l2=l2,
                        eager_form=True)
                    for i, leaf in zip(idx, nps):
                        new_p[i] = leaf
                else:
                    nms, nvs, rs = _bucket_lamb(
                        ps, gs, mlist[bi], vlist[bi], bc1, bc2,
                        b1=b1, b2=b2, eps=eps, wd=wd)
                    for i, r in zip(idx, rs):
                        p32 = plist[i].astype(jnp.float32)
                        p_norm = jnp.sqrt(jnp.sum(jnp.square(p32)))
                        r_norm = jnp.sqrt(jnp.sum(jnp.square(r)))
                        trust = jnp.where((p_norm > 0) & (r_norm > 0),
                                          p_norm / r_norm, 1.0)
                        new_p[i] = (p32 - blr * trust * r).astype(
                            plist[i].dtype)
                new_m.append(nms)
                new_v.append(nvs)
            return new_p, new_m, new_v, b1p * b1, b2p * b2

        self._fn = jax.jit(run, donate_argnums=(2, 3))

    def step(self, grads, lr):
        if self._fn is None:
            self._build()
        plist = [p._data for p in self.params]
        new_p, self.ms, self.vs, self.b1p, self.b2p = self._fn(
            plist, grads, self.ms, self.vs, self.b1p, self.b2p,
            jnp.asarray(lr, jnp.float32))
        for p, arr in zip(self.params, new_p):
            p._data = arr

    def sync_slots(self, opt):
        """Write the moments + beta-pows back into opt._accumulators."""
        names = opt._slot_names()
        for bi, (_, idx) in enumerate(self.buckets):
            for i, m, v in zip(idx, self.ms[bi], self.vs[bi]):
                p = self.params[i]
                vals = [m, v]
                if "beta1_pow" in names:
                    vals += [self.b1p, self.b2p]
                opt._set_slots(p, vals)


def fused_eager_step(opt, params_grads, lr) -> bool:
    """One-dispatch fused step for Adam/AdamW/Lamb eager ``step()``.

    Returns False (caller falls back to the unfused per-param loop) when
    the param set uses features the flat path doesn't cover (L1
    regularizers). On success: params updated in place, slot mirrors
    marked dirty (synced lazily by state_dict)."""
    from ..regularizer import L1Decay

    if not params_grads:
        return True
    for p, _ in params_grads:
        reg = p.regularizer if p.regularizer is not None else \
            opt._weight_decay
        if isinstance(reg, L1Decay):
            return False
    kind = "lamb" if type(opt).__name__ == "Lamb" else "adam"
    sig = tuple((id(p), tuple(p._data.shape), str(p._data.dtype))
                for p, _ in params_grads)
    st = getattr(opt, "_fused_state", None)
    if st is None or st.sig != sig:
        st = _FusedEagerState(opt, params_grads, kind)
        opt._fused_state = st
    grads = []
    for p, g in params_grads:
        garr = g._data if hasattr(g, "_data") else g
        grads.append(garr)
    t0 = time.perf_counter()
    with _trace_span("kernel.fused_%s" % kind, cat="kernel"):
        st.step(grads, lr)
    if _bench.enabled():
        _bench.record_op("fused_%s@step" % kind, time.perf_counter() - t0)
    FUSED_OPTIMIZER_STEPS.add()
    opt._slots_stale = True
    return True


# -- autotune family (ISSUE 17) ---------------------------------------------

def _adamw_candidates(shape, dtype):
    rows = int(shape[0])
    cands = [{"bb": c} for c in (512, 256, 128, 64, 32, 16)
             if rows % c == 0]
    return (cands or [{"bb": rows}])[:4]


def _adamw_bench(shape, dtype, config):
    import numpy as np

    rows = int(shape[0])
    n = rows * _LANE
    rng = np.random.default_rng(0)
    p = jnp.asarray(rng.standard_normal(n).astype(dtype))
    g = jnp.asarray(rng.standard_normal(n).astype(dtype) * 0.01)
    m = jnp.zeros((n,), dtype)
    v = jnp.zeros((n,), dtype)
    # bench through the padded 2-D kernel body directly at this block
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    p2, _ = _pad_2d(p)
    g2, _ = _pad_2d(g)
    m2, _ = _pad_2d(m)
    v2, _ = _pad_2d(v)
    bb = int(config["bb"])
    sc = jnp.stack([jnp.float32(1e-3), jnp.float32(0.9),
                    jnp.float32(0.999)])
    blk = lambda: pl.BlockSpec((bb, _LANE), lambda i: (i, 0))
    out = pl.pallas_call(
        functools.partial(_adamw_kernel, b1=0.9, b2=0.999, eps=1e-8,
                          wd=0.0, l2=0.0, eager_form=False),
        out_shape=(jax.ShapeDtypeStruct(p2.shape, p.dtype),
                   jax.ShapeDtypeStruct(m2.shape, m.dtype),
                   jax.ShapeDtypeStruct(v2.shape, v.dtype)),
        grid=(rows // bb,),
        in_specs=[pl.BlockSpec(memory_space=pltpu.SMEM),
                  blk(), blk(), blk(), blk()],
        out_specs=(blk(), blk(), blk()),
        compiler_params=_compiler_params(
            pltpu, vmem_limit_bytes=64 * 1024 * 1024),
        interpret=not _on_tpu(),
    )(sc, p2, g2, m2, v2)
    jax.block_until_ready(out)


_autotune.register_family("fused_adamw", _adamw_candidates, _adamw_bench)
