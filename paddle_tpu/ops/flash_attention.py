"""Flash attention — Pallas TPU kernel.

TPU-native answer to the reference's fused attention
(operators/fused/fused_transformer_op.cu, fmha_ref.h): instead of a cuda
fMHA, a Pallas kernel that tiles Q into VMEM blocks and computes softmax(QK^T)V
per block, so the [S, S] score matrix never hits HBM. The backward pass
recomputes attention inside jax.checkpoint (rematerialization is cheaper
than saving scores on TPU — HBM bandwidth is the bottleneck).

Layout: [batch, heads, seq, head_dim] (matches MultiHeadAttention internals).
"""
from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp

_INTERPRET_CACHE = {}


def _on_tpu() -> bool:
    try:
        return jax.devices()[0].platform in ("tpu", "axon")
    except Exception:
        return False


def _attention_reference(q, k, v, causal, scale):
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k) * scale
    if causal:
        qlen, klen = s.shape[-2], s.shape[-1]
        mask = jnp.tril(jnp.ones((qlen, klen), dtype=bool), k=klen - qlen)
        s = jnp.where(mask, s, -1e30)
    p = jax.nn.softmax(s.astype(jnp.float32), axis=-1).astype(q.dtype)
    return jnp.einsum("bhqk,bhkd->bhqd", p, v)


def _flash_kernel(q_ref, k_ref, v_ref, o_ref, *, scale, causal, block_q):
    from jax.experimental import pallas as pl

    qi = pl.program_id(1)
    q = q_ref[0].astype(jnp.float32)  # [block_q, d]
    k = k_ref[0].astype(jnp.float32)  # [S, d]
    v = v_ref[0].astype(jnp.float32)  # [S, d]
    s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                            preferred_element_type=jnp.float32) * scale  # [block_q, S]
    if causal:
        seq = k.shape[0]
        q_pos = qi * block_q + jax.lax.broadcasted_iota(jnp.int32, s.shape, 0)
        k_pos = jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
        s = jnp.where(q_pos >= k_pos, s, -1e30)
    m = jnp.max(s, axis=-1, keepdims=True)
    p = jnp.exp(s - m)
    l = jnp.sum(p, axis=-1, keepdims=True)
    o = jax.lax.dot_general(p, v, (((1,), (0,)), ((), ())),
                            preferred_element_type=jnp.float32) / l
    o_ref[0] = o.astype(o_ref.dtype)


@functools.partial(jax.jit, static_argnames=("causal", "scale", "block_q", "interpret"))
def _flash_forward(q, k, v, causal=False, scale=None, block_q=128, interpret=False):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    b, h, sq, d = q.shape
    sk = k.shape[2]
    if scale is None:
        scale = 1.0 / math.sqrt(d)
    bq = min(block_q, sq)
    if sq % bq != 0:
        return _attention_reference(q, k, v, causal, scale)
    qr = q.reshape(b * h, sq, d)
    kr = k.reshape(b * h, sk, d)
    vr = v.reshape(b * h, sk, d)
    grid = (b * h, sq // bq)
    kernel = functools.partial(_flash_kernel, scale=scale, causal=causal, block_q=bq)
    out = pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct((b * h, sq, d), q.dtype),
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, bq, d), lambda i, j: (i, j, 0)),
            pl.BlockSpec((1, sk, d), lambda i, j: (i, 0, 0)),
            pl.BlockSpec((1, sk, d), lambda i, j: (i, 0, 0)),
        ],
        out_specs=pl.BlockSpec((1, bq, d), lambda i, j: (i, j, 0)),
        interpret=interpret,
    )(qr, kr, vr)
    return out.reshape(b, h, sq, d)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5))
def _flash(q, k, v, causal, scale, block_q):
    return _flash_forward(q, k, v, causal=causal, scale=scale, block_q=block_q)


def _flash_fwd_rule(q, k, v, causal, scale, block_q):
    return _flash(q, k, v, causal, scale, block_q), (q, k, v)


def _flash_bwd_rule(causal, scale, block_q, res, g):
    # Backward recomputes attention through the XLA reference path (the
    # [S,S] score matrix exists only inside the bwd computation; a Pallas
    # flash-backward kernel replacing this is tracked work).
    q, k, v = res
    _, vjp = jax.vjp(
        lambda q_, k_, v_: _attention_reference(q_, k_, v_, causal, scale),
        q, k, v)
    return vjp(g)


_flash.defvjp(_flash_fwd_rule, _flash_bwd_rule)


def flash_attention_arrays(q, k, v, causal=False, scale=None, block_q=128):
    """Array-level entry (used inside jit traces / functional code).

    Differentiable: the Pallas kernel runs the forward; a custom_vjp
    recomputes the backward via the reference formula.
    """
    d = q.shape[-1]
    if scale is None:
        scale = 1.0 / math.sqrt(d)
    use_pallas = _on_tpu() and d in (64, 128, 256) and q.shape[-2] >= 128
    if use_pallas:
        return _flash(q, k, v, bool(causal), float(scale), int(block_q))
    return _attention_reference(q, k, v, causal, scale)


def flash_attention(query, key, value, dropout=0.0, causal=False,
                    return_softmax=False, training=True, name=None):
    """Tensor-level API, paddle.incubate.nn.functional.fused-attention-like.

    query/key/value: [batch, num_heads, seq, head_dim] Tensors.
    """
    from ..framework.core import Tensor, apply_op

    if return_softmax:
        raise NotImplementedError("flash_attention does not materialize softmax")
    out = apply_op(_flash_entry, query, key, value, causal=bool(causal))
    if dropout and training:
        from ..nn import functional as F

        out = F.dropout(out, dropout, training=training)
    return out


def _flash_entry(q, k, v, causal):
    return flash_attention_arrays(q, k, v, causal=causal)
