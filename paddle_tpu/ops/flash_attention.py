"""Flash attention — Pallas TPU kernels with KV blocking (fwd + bwd).

TPU-native answer to the reference's fused attention
(operators/fused/fused_transformer_op.cu, fmha_ref.h): instead of a CUDA
fMHA, Pallas kernels that stream K/V through VMEM in blocks with
online-softmax accumulation, so neither the [S, S] score matrix nor the
full K/V ever needs to sit in fast memory at once.

Design notes (tuned on a v5e chip):
- grid (bh/block_b, q blocks, kv blocks), kv innermost so the VMEM
  scratch (m, l, acc) carries across the kv sweep; block_b batches
  several batch*head rows per grid step to amortize per-step overhead
  at short sequence lengths.
- matmuls run at the input dtype's MXU rate (bf16 in training) with f32
  accumulation; softmax statistics stay f32.
- backward is ONE fused kernel: dK/dV accumulate in scratch over the
  inner q sweep, while dQ per-kv partials go to HBM and are summed by
  XLA — S and dP are computed once instead of twice (4 matmuls, the
  same count as XLA's saved-P backward, but without materializing P).
- lse/delta travel as [.., seq, 1] f32 — no lane-broadcast HBM waste.

Layout: [batch, heads, seq, head_dim] (matches MultiHeadAttention internals).
"""
from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp

from . import autotune as _autotune

NEG_INF = -1e30


def _compiler_params(pltpu, **kw):
    """pltpu.CompilerParams was TPUCompilerParams before jax 0.5."""
    cls = getattr(pltpu, "CompilerParams", None) or pltpu.TPUCompilerParams
    return cls(**kw)


def _on_tpu() -> bool:
    try:
        return jax.devices()[0].platform in ("tpu", "axon")
    except Exception:
        return False


def _attention_reference(q, k, v, causal, scale):
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k) * scale
    if causal:
        qlen, klen = s.shape[-2], s.shape[-1]
        mask = jnp.tril(jnp.ones((qlen, klen), dtype=bool), k=klen - qlen)
        s = jnp.where(mask, s, NEG_INF)
    p = jax.nn.softmax(s.astype(jnp.float32), axis=-1).astype(q.dtype)
    return jnp.einsum("bhqk,bhkd->bhqd", p, v)


def _pick_block_b(bh: int, bq: int, bk: int) -> int:
    """Largest divisor of bh keeping the f32 score block under ~8MB.
    The backward kernel holds ~3 score-sized f32 intermediates (s, p, dp)
    plus double-buffered input blocks inside the 64MB VMEM scoped limit;
    measured on v5e: bb=8 at 512x512 blocks beats bb=4 by ~7%."""
    budget = 8 * 1024 * 1024
    bb = 1
    for cand in (2, 4, 8, 16):
        if bh % cand == 0 and cand * bq * bk * 4 <= budget:
            bb = cand
    return bb


def _auto_block(s: int, cap: int = 2048) -> int:
    """Largest power-of-two block <= cap dividing s. Measured on v5e
    (BERT-base shapes): whole-sequence blocks win up to 2048 (41.0 vs 38.0
    sps at seq 2048) — the online-softmax streaming only pays once S*S
    no longer fits VMEM comfortably."""
    for b in (cap, cap // 2, cap // 4, cap // 8, 128):
        if b <= s and s % b == 0:
            return b
    return s


# --------------------------------------------------------------------------
# forward
# --------------------------------------------------------------------------

def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, m_s, l_s, acc_s, *,
                scale, causal, block_q, block_k, n_kv, off=0):
    from jax.experimental import pallas as pl

    qi = pl.program_id(1)
    ki = pl.program_id(2)

    @pl.when(ki == 0)
    def _init():
        m_s[:] = jnp.full_like(m_s, NEG_INF)
        l_s[:] = jnp.zeros_like(l_s)
        acc_s[:] = jnp.zeros_like(acc_s)

    # Causal: skip kv blocks strictly above this q block's diagonal.
    live = (qi * block_q + block_q - 1 + off >= ki * block_k) if causal else True

    @pl.when(live)
    def _compute():
        q = q_ref[...]                                  # [bb, bq, d]
        k = k_ref[...]
        v = v_ref[...]
        s = jax.lax.dot_general(q, k, (((2,), (2,)), ((0,), (0,))),
                                preferred_element_type=jnp.float32) * scale
        if causal:
            q_pos = qi * block_q + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
            k_pos = ki * block_k + jax.lax.broadcasted_iota(jnp.int32, s.shape, 2)
            s = jnp.where(q_pos + off >= k_pos, s, NEG_INF)
        m_prev = m_s[:, :, 0:1]                         # [bb, bq, 1]
        l_prev = l_s[:, :, 0:1]
        m_cur = jnp.max(s, axis=-1, keepdims=True)
        m_new = jnp.maximum(m_prev, m_cur)
        alpha = jnp.exp(m_prev - m_new)
        p = jnp.exp(s - m_new)                          # [bb, bq, bk] f32
        l_s[:] = jnp.broadcast_to(alpha * l_prev + jnp.sum(p, -1, keepdims=True),
                                  l_s.shape)
        m_s[:] = jnp.broadcast_to(m_new, m_s.shape)
        acc_s[:] = acc_s[:] * alpha + jax.lax.dot_general(
            p.astype(v.dtype), v, (((2,), (1,)), ((0,), (0,))),
            preferred_element_type=jnp.float32)

    @pl.when(ki == n_kv - 1)
    def _finalize():
        l = l_s[:, :, 0:1]
        l = jnp.where(l == 0.0, 1.0, l)
        o_ref[...] = (acc_s[:] / l).astype(o_ref.dtype)
        lse_ref[...] = m_s[:, :, 0:1] + jnp.log(l)      # [bb, bq, 1]


@functools.partial(jax.jit, static_argnames=(
    "causal", "scale", "block_q", "block_k", "block_b", "interpret"))
def _flash_forward(q, k, v, causal=False, scale=None, block_q=512,
                   block_k=1024, block_b=None, interpret=False):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    b, h, sq, d = q.shape
    if scale is None:
        scale = 1.0 / math.sqrt(d)
    sk = k.shape[2]
    bq = min(block_q, sq)
    bk = min(block_k, sk)
    n_q, n_kv = sq // bq, sk // bk
    bh = b * h
    bb = block_b if block_b else _pick_block_b(bh, bq, bk)
    qr = q.reshape(bh, sq, d)
    kr = k.reshape(bh, sk, d)
    vr = v.reshape(bh, sk, d)
    off = sk - sq
    kernel = functools.partial(_fwd_kernel, scale=scale, causal=causal,
                               block_q=bq, block_k=bk, n_kv=n_kv, off=off)
    if causal:
        # FlashAttention-2-style DMA clamp: kv blocks strictly above the
        # q block's diagonal are pl.when-skipped in the kernel, but the
        # plain (i, kk, 0) map still DMAs them. Clamping dead kk to the
        # last LIVE kv block makes consecutive dead steps re-reference
        # the same block, so the pipeline elides their copies — the
        # compute (and output) is bit-identical, only dead traffic goes.
        def _kv_idx(i, j, kk):
            return (i, jnp.minimum(
                kk, jnp.clip((j * bq + bq - 1 + off) // bk, 0, n_kv - 1)), 0)
    else:
        def _kv_idx(i, j, kk):
            return (i, kk, 0)
    out, lse = pl.pallas_call(
        kernel,
        out_shape=(jax.ShapeDtypeStruct((bh, sq, d), q.dtype),
                   jax.ShapeDtypeStruct((bh, sq, 1), jnp.float32)),
        grid=(bh // bb, n_q, n_kv),
        in_specs=[
            pl.BlockSpec((bb, bq, d), lambda i, j, kk: (i, j, 0)),
            pl.BlockSpec((bb, bk, d), _kv_idx),
            pl.BlockSpec((bb, bk, d), _kv_idx),
        ],
        out_specs=(pl.BlockSpec((bb, bq, d), lambda i, j, kk: (i, j, 0)),
                   pl.BlockSpec((bb, bq, 1), lambda i, j, kk: (i, j, 0))),
        scratch_shapes=[
            pltpu.VMEM((bb, bq, 128), jnp.float32),   # running max
            pltpu.VMEM((bb, bq, 128), jnp.float32),   # running sum
            pltpu.VMEM((bb, bq, d), jnp.float32),     # output accumulator
        ],
        compiler_params=_compiler_params(
            pltpu, vmem_limit_bytes=64 * 1024 * 1024),
        interpret=interpret,
    )(qr, kr, vr)
    return out.reshape(b, h, sq, d), lse


# --------------------------------------------------------------------------
# backward, one fused kernel (see module docstring). delta = rowsum(dO*O)
# is one fused XLA pass producing a tiny [bh, sq, 1] input.
# --------------------------------------------------------------------------

def _bwd_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                dk_ref, dv_ref, dqp_ref, dk_s, dv_s, *,
                scale, causal, block_q, block_k, n_q, off=0):
    from jax.experimental import pallas as pl

    ki = pl.program_id(1)
    qi = pl.program_id(2)

    @pl.when(qi == 0)
    def _init():
        dk_s[:] = jnp.zeros_like(dk_s)
        dv_s[:] = jnp.zeros_like(dv_s)

    live = (qi * block_q + block_q - 1 + off >= ki * block_k) if causal else True

    @pl.when(live)
    def _compute():
        q = q_ref[...]                                  # [bb, bq, d]
        k = k_ref[...]                                  # [bb, bk, d]
        v = v_ref[...]
        do = do_ref[...]
        lse = lse_ref[...]                              # [bb, bq, 1]
        delta = delta_ref[...]                          # [bb, bq, 1]
        s = jax.lax.dot_general(q, k, (((2,), (2,)), ((0,), (0,))),
                                preferred_element_type=jnp.float32) * scale
        p = jnp.exp(s - lse)                            # [bb, bq, bk] f32
        if causal:
            q_pos = qi * block_q + jax.lax.broadcasted_iota(jnp.int32, p.shape, 1)
            k_pos = ki * block_k + jax.lax.broadcasted_iota(jnp.int32, p.shape, 2)
            p = jnp.where(q_pos + off >= k_pos, p, 0.0)
        pb = p.astype(do.dtype)
        dv_s[:] += jax.lax.dot_general(pb, do, (((1,), (1,)), ((0,), (0,))),
                                       preferred_element_type=jnp.float32)
        dp = jax.lax.dot_general(do, v, (((2,), (2,)), ((0,), (0,))),
                                 preferred_element_type=jnp.float32)
        ds = (p * (dp - delta) * scale).astype(q.dtype)  # [bb, bq, bk]
        dk_s[:] += jax.lax.dot_general(ds, q, (((1,), (1,)), ((0,), (0,))),
                                       preferred_element_type=jnp.float32)
        dqp_ref[0] = jax.lax.dot_general(
            ds, k, (((2,), (1,)), ((0,), (0,))),
            preferred_element_type=jnp.float32).astype(dqp_ref.dtype)

    @pl.when(jnp.logical_not(live))
    def _dead():
        dqp_ref[0] = jnp.zeros_like(dqp_ref[0])

    @pl.when(qi == n_q - 1)
    def _finalize():
        dk_ref[...] = dk_s[:].astype(dk_ref.dtype)
        dv_ref[...] = dv_s[:].astype(dv_ref.dtype)


@functools.partial(jax.jit, static_argnames=(
    "causal", "scale", "block_q", "block_k", "block_b", "interpret"))
def _flash_backward(q, k, v, o, lse, g, causal=False, scale=None,
                    block_q=512, block_k=1024, block_b=None, interpret=False):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    b, h, sq, d = q.shape
    if scale is None:
        scale = 1.0 / math.sqrt(d)
    sk = k.shape[2]
    bq = min(block_q, sq)
    bk = min(block_k, sk)
    n_q, n_kv = sq // bq, sk // bk
    bh = b * h
    bb = block_b if block_b else _pick_block_b(bh, bq, bk)
    qr, kr, vr = (x.reshape(bh, -1, d) for x in (q, k, v))
    dor = g.reshape(bh, sq, d)
    # delta = rowsum(dO * O): one fused XLA pass, tiny [bh, sq, 1] output
    delta = jnp.sum(g.astype(jnp.float32) * o.astype(jnp.float32),
                    axis=-1).reshape(bh, sq, 1)
    dqp_dtype = q.dtype if n_kv == 1 else jnp.float32

    off = sk - sq
    if causal:
        # mirror of the forward DMA clamp: with q innermost, the dead
        # iterations are q blocks strictly BELOW this kv block's
        # diagonal (j < first live block ceil((kk*bk - off - bq + 1)/bq)
        # = (kk*bk - off) // bq); pin them to that first live block so
        # their q/do/lse/delta copies elide. Dead steps only write the
        # zero dqp block, so the outputs are bit-identical.
        def _q_idx(i, kk, j):
            return (i, jnp.maximum(
                j, jnp.clip((kk * bk - off) // bq, 0, n_q - 1)), 0)
    else:
        def _q_idx(i, kk, j):
            return (i, j, 0)
    dk, dv, dqp = pl.pallas_call(
        functools.partial(_bwd_kernel, scale=scale, causal=causal,
                          block_q=bq, block_k=bk, n_q=n_q, off=off),
        out_shape=(jax.ShapeDtypeStruct((bh, sk, d), k.dtype),
                   jax.ShapeDtypeStruct((bh, sk, d), v.dtype),
                   jax.ShapeDtypeStruct((n_kv, bh, sq, d), dqp_dtype)),
        grid=(bh // bb, n_kv, n_q),
        in_specs=[
            pl.BlockSpec((bb, bq, d), _q_idx),
            pl.BlockSpec((bb, bk, d), lambda i, kk, j: (i, kk, 0)),
            pl.BlockSpec((bb, bk, d), lambda i, kk, j: (i, kk, 0)),
            pl.BlockSpec((bb, bq, d), _q_idx),
            pl.BlockSpec((bb, bq, 1), _q_idx),
            pl.BlockSpec((bb, bq, 1), _q_idx),
        ],
        out_specs=(pl.BlockSpec((bb, bk, d), lambda i, kk, j: (i, kk, 0)),
                   pl.BlockSpec((bb, bk, d), lambda i, kk, j: (i, kk, 0)),
                   pl.BlockSpec((1, bb, bq, d),
                                lambda i, kk, j: (kk, i, j, 0))),
        scratch_shapes=[pltpu.VMEM((bb, bk, d), jnp.float32),
                        pltpu.VMEM((bb, bk, d), jnp.float32)],
        compiler_params=_compiler_params(
            pltpu, vmem_limit_bytes=64 * 1024 * 1024),
        interpret=interpret,
    )(qr, kr, vr, dor, lse, delta)

    dq = jnp.sum(dqp, axis=0).astype(q.dtype) if n_kv > 1 else dqp[0]
    return (dq.reshape(b, h, sq, d), dk.reshape(b, h, sk, d),
            dv.reshape(b, h, sk, d))


# --------------------------------------------------------------------------
# differentiable entry
# --------------------------------------------------------------------------

@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7, 8))
def _flash(q, k, v, causal, scale, block_q, block_k, block_b, interpret):
    out, _ = _flash_forward(q, k, v, causal=causal, scale=scale,
                            block_q=block_q, block_k=block_k,
                            block_b=block_b, interpret=interpret)
    return out


def _flash_fwd_rule(q, k, v, causal, scale, block_q, block_k, block_b,
                    interpret):
    out, lse = _flash_forward(q, k, v, causal=causal, scale=scale,
                              block_q=block_q, block_k=block_k,
                              block_b=block_b, interpret=interpret)
    return out, (q, k, v, out, lse)


def _flash_bwd_rule(causal, scale, block_q, block_k, block_b, interpret,
                    res, g):
    q, k, v, out, lse = res
    return _flash_backward(q, k, v, out, lse, g, causal=causal, scale=scale,
                           block_q=block_q, block_k=block_k,
                           block_b=block_b, interpret=interpret)


_flash.defvjp(_flash_fwd_rule, _flash_bwd_rule)


def flash_attention_arrays(q, k, v, causal=False, scale=None, block_q=None,
                           block_k=None, block_b=None, interpret=None):
    """Array-level entry (used inside jit traces / functional code).

    Differentiable end to end in Pallas: KV-blocked online-softmax forward,
    delta-trick fused backward. block_q/block_k default to the measured
    v5e auto policy (_auto_block); pass explicitly to override.

    head_dim handling: the MXU wants the minor dim in {64, k·128}. Other
    widths (e.g. 96 = 1536/16 in GPT-760M shapes) are zero-padded to the
    next multiple of 128 — zero columns change neither the q·k scores nor
    add output mass, the padded output columns are sliced off, and their
    cotangents are zero, so gradients match the unpadded math exactly.
    """
    d = q.shape[-1]
    if scale is None:
        scale = 1.0 / math.sqrt(d)
    auto = block_q is None and block_k is None and block_b is None
    if block_q is None:
        block_q = _auto_block(q.shape[2])
    if block_k is None:
        block_k = _auto_block(k.shape[2])
    if interpret is None:
        interpret = False
        if not _on_tpu():
            return _attention_reference(q, k, v, causal, scale)
    sq, sk = q.shape[2], k.shape[2]
    bq, bk = min(block_q, sq), min(block_k, sk)
    if not (sq % bq == 0 and sk % bk == 0 and sq >= 128 and sk >= 128):
        _autotune.note_fallback(
            "flash", q.shape,
            "seq_q=%d/seq_k=%d not tileable by block %dx%d (needs seq >= "
            "128 and block-divisible)" % (sq, sk, bq, bk))
        return _attention_reference(q, k, v, causal, scale)
    if d % 128 != 0 and d != 64:
        dp = -(-d // 128) * 128
        pad = ((0, 0), (0, 0), (0, 0), (0, dp - d))
        out = flash_attention_arrays(
            jnp.pad(q, pad), jnp.pad(k, pad), jnp.pad(v, pad), causal=causal,
            scale=scale,
            block_q=None if auto else block_q,
            block_k=None if auto else block_k,
            block_b=None if auto else block_b,
            interpret=interpret)
        return out[..., :d]
    if auto and _autotune.enabled():
        bh = q.shape[0] * q.shape[1]
        cfg = _autotune.get_config(
            "flash.causal" if causal else "flash", (bh, sq, sk, d),
            str(jnp.dtype(q.dtype)),
            {"block_q": bq, "block_k": bk,
             "block_b": _pick_block_b(bh, bq, bk)})
        tq, tk = int(cfg.get("block_q", bq)), int(cfg.get("block_k", bk))
        if sq % tq == 0 and sk % tk == 0:   # never trust a cache into
            bq, bk = tq, tk                  # an untileable config
            tb = cfg.get("block_b")
            block_b = int(tb) if tb and bh % int(tb) == 0 else None
    return _flash(q, k, v, bool(causal), float(scale), int(bq),
                  int(bk), block_b and int(block_b), bool(interpret))


def flash_attention(query, key, value, dropout=0.0, causal=False,
                    return_softmax=False, training=True, name=None):
    """Tensor-level API, paddle.incubate.nn.functional.fused-attention-like.

    query/key/value: [batch, num_heads, seq, head_dim] Tensors.
    """
    from ..framework.core import Tensor, apply_op

    if return_softmax:
        raise NotImplementedError("flash_attention does not materialize softmax")
    out = apply_op(_flash_entry, query, key, value, causal=bool(causal))
    if dropout and training:
        from ..nn import functional as F

        out = F.dropout(out, dropout, training=training)
    return out


def _flash_entry(q, k, v, causal):
    return flash_attention_arrays(q, k, v, causal=causal)


# -- autotune family (ISSUE 17) --------------------------------------------
# Candidates walk the power-of-two block ladder the hand policy picks
# from, so the hand-picked default is always in the trial set and the
# S=2048 whole-sequence degenerate block has to EARN its slot.

def _flash_candidates(shape, dtype):
    bh, sq, sk, d = shape
    out, seen = [], set()
    for cap in (2048, 1024, 512, 256, 128):
        bq = min(_auto_block(sq, cap), sq)
        bk = min(_auto_block(sk, cap), sk)
        if sq % bq or sk % bk or (bq, bk) in seen:
            continue
        seen.add((bq, bk))
        out.append({"block_q": bq, "block_k": bk,
                    "block_b": _pick_block_b(bh, bq, bk)})
    return out[:5]


def _flash_bench(causal):
    def bench(shape, dtype, config):
        import numpy as np

        bh, sq, sk, d = shape
        rng = np.random.default_rng(0)
        dt = jnp.dtype(dtype)
        q = jnp.asarray(rng.standard_normal((1, bh, sq, d)), dt)
        k = jnp.asarray(rng.standard_normal((1, bh, sk, d)), dt)
        v = jnp.asarray(rng.standard_normal((1, bh, sk, d)), dt)
        out, _ = _flash_forward(
            q, k, v, causal=causal, scale=1.0 / math.sqrt(d),
            block_q=int(config["block_q"]), block_k=int(config["block_k"]),
            block_b=int(config.get("block_b") or 0) or None,
            interpret=not _on_tpu())
        jax.block_until_ready(out)
    return bench


_autotune.register_family("flash", _flash_candidates, _flash_bench(False))
_autotune.register_family("flash.causal", _flash_candidates,
                          _flash_bench(True))
