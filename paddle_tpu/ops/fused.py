"""Fused transformer ops.

Reference surface: paddle.incubate.nn.functional fused_multi_head_attention /
fused_feedforward (operators/fused/fused_attention_op.cu,
fused_feedforward_op.cc). On TPU these are compositions that XLA fuses into
a handful of kernels; attention itself uses the Pallas flash kernel.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..core.native import fused_kernels as _fused_kernels_flag
from ..framework.core import Tensor, apply_op
from ..monitor.stats import FUSED_KERNEL_CALLS
from .flash_attention import flash_attention_arrays


def _fused_mha(x, qkv_w, qkv_b, out_w, out_b, ln_w, ln_b, num_heads,
               pre_ln, causal, eps):
    b, s, d = x.shape
    h = num_heads
    hd = d // h
    residual = x
    if pre_ln:
        mu = jnp.mean(x, -1, keepdims=True)
        var = jnp.var(x, -1, keepdims=True)
        x = (x - mu) * jax.lax.rsqrt(var + eps) * ln_w + ln_b
    qkv = x @ qkv_w + qkv_b  # [b, s, 3d]
    q, k, v = jnp.split(qkv, 3, axis=-1)

    def heads(t):
        return t.reshape(b, s, h, hd).transpose(0, 2, 1, 3)

    o = flash_attention_arrays(heads(q), heads(k), heads(v), causal=causal)
    o = o.transpose(0, 2, 1, 3).reshape(b, s, d)
    o = o @ out_w + out_b
    y = residual + o
    if not pre_ln:
        mu = jnp.mean(y, -1, keepdims=True)
        var = jnp.var(y, -1, keepdims=True)
        y = (y - mu) * jax.lax.rsqrt(var + eps) * ln_w + ln_b
    return y


def fused_multi_head_attention(x, qkv_weight, qkv_bias, linear_weight, linear_bias,
                               ln_scale, ln_bias, num_heads, pre_layer_norm=False,
                               causal=False, epsilon=1e-5, name=None):
    return apply_op(_fused_mha, x, qkv_weight, qkv_bias, linear_weight, linear_bias,
                    ln_scale, ln_bias, num_heads=int(num_heads),
                    pre_ln=bool(pre_layer_norm), causal=bool(causal), eps=float(epsilon))


def _fused_ffn(x, w1, b1, w2, b2, ln_w, ln_b, pre_ln, act, eps):
    if _fused_kernels_flag[0]:
        # FLAGS_fused_kernels: the Pallas fused LN/MLP library
        # (ops/fused_kernels.py). Off-TPU these entries run the identical
        # composed math below, so the flag is numerics-neutral on CPU.
        from .fused_kernels import fused_add_layernorm, fused_ln_mlp

        if pre_ln:
            return fused_ln_mlp(x, w1, b1, w2, b2, ln_scale=ln_w,
                                ln_bias=ln_b, residual=True, act=act,
                                eps=eps)
        mlp = fused_ln_mlp(x, w1, b1, w2, b2, ln_scale=None,
                           residual=False, act=act, eps=eps)
        return fused_add_layernorm(x, mlp, ln_w, ln_b, eps=eps)
    residual = x
    if pre_ln:
        mu = jnp.mean(x, -1, keepdims=True)
        var = jnp.var(x, -1, keepdims=True)
        x = (x - mu) * jax.lax.rsqrt(var + eps) * ln_w + ln_b
    hdn = x @ w1 + b1
    hdn = jax.nn.gelu(hdn) if act == "gelu" else jax.nn.relu(hdn)
    y = residual + (hdn @ w2 + b2)
    if not pre_ln:
        mu = jnp.mean(y, -1, keepdims=True)
        var = jnp.var(y, -1, keepdims=True)
        y = (y - mu) * jax.lax.rsqrt(var + eps) * ln_w + ln_b
    return y


def fused_feedforward(x, linear1_weight, linear1_bias, linear2_weight, linear2_bias,
                      ln_scale, ln_bias, pre_layer_norm=False, activation="relu",
                      epsilon=1e-5, name=None):
    if _fused_kernels_flag[0]:
        FUSED_KERNEL_CALLS.add()
    return apply_op(_fused_ffn, x, linear1_weight, linear1_bias, linear2_weight,
                    linear2_bias, ln_scale, ln_bias, pre_ln=bool(pre_layer_norm),
                    act=activation, eps=float(epsilon))
