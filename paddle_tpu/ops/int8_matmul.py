"""int8 weight-quantized matmul — Pallas dot kernel with fused dequant.

The kernel behind ``quantization.quantized_linear`` (the reference's slim
int8 inference path over cuDNN int8 convs): int8 activations x int8
weights on the MXU (v5e runs int8 at 2x the bf16 rate) with int32
accumulation, and the per-channel dequant (``acc * xscale * wscale[n]``)
plus bias fused into the kernel epilogue — the dequantized fp tensor is
written once, never the int32 accumulator.

Entry points:
- :func:`int8_matmul_arrays` — already-quantized operands
  ``(xq int8 [.., K], wq int8 [K, N], wscale [N], xscale scalar)``.
- :func:`dynamic_int8_matmul` — fp activations, per-tensor abs-max
  quantized on the fly (weight-only-quantized serving decode).

Fallback contract matches flash_attention: off-TPU (or on untileable
shapes) the identical XLA math runs (``lax.dot_general`` int8 path);
``interpret=True`` forces the Pallas kernel for CPU parity tests.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from ..monitor.stats import INT8_MATMUL_CALLS
from . import autotune as _autotune
from .flash_attention import _compiler_params, _on_tpu

__all__ = ["int8_matmul_arrays", "dynamic_int8_matmul"]


def _int8_matmul_ref(xq, wq, wscale, xscale, bias, out_dtype):
    acc = jax.lax.dot_general(
        xq, wq, (((xq.ndim - 1,), (0,)), ((), ())),
        preferred_element_type=jnp.int32)
    out = acc.astype(jnp.float32) * (xscale * wscale)
    if bias is not None:
        out = out + bias
    return out.astype(out_dtype)


def _int8_kernel(xs_ref, xq_ref, wq_ref, ws_ref, b_ref, o_ref, acc_s, *,
                 n_k, out_dtype):
    from jax.experimental import pallas as pl

    ki = pl.program_id(2)

    @pl.when(ki == 0)
    def _init():
        acc_s[...] = jnp.zeros_like(acc_s)

    acc_s[...] += jax.lax.dot_general(
        xq_ref[...], wq_ref[...], (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.int32)

    @pl.when(ki == n_k - 1)
    def _epilogue():
        out = acc_s[...].astype(jnp.float32) * (xs_ref[0] * ws_ref[...])
        out = out + b_ref[...]
        o_ref[...] = out.astype(out_dtype)


def _pick(n, cands):
    for c in cands:
        if n % c == 0 and c <= n:
            return c
    return None


@functools.partial(jax.jit, static_argnames=("out_dtype", "interpret",
                                             "bm", "bn", "bk"))
def _int8_matmul_2d(xq, wq, wscale, xscale, bias, out_dtype,
                    interpret=False, bm=None, bn=None, bk=None):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    M, K = xq.shape
    N = wq.shape[1]
    # int8 min tile is (32, 128): pad rows to 32 (decode batches are tiny)
    Mp = -(-M // 32) * 32
    if Mp != M:
        xq = jnp.pad(xq, ((0, Mp - M), (0, 0)))
    bm = bm or _pick(Mp, (256, 128, 64, 32))
    bn = bn or _pick(N, (512, 256, 128))
    bk = bk or _pick(K, (512, 256, 128))
    ws2 = wscale.reshape(1, N).astype(jnp.float32)
    b2 = (bias.reshape(1, N).astype(jnp.float32) if bias is not None
          else jnp.zeros((1, N), jnp.float32))
    xs = xscale.reshape(1).astype(jnp.float32)
    out = pl.pallas_call(
        functools.partial(_int8_kernel, n_k=K // bk, out_dtype=out_dtype),
        out_shape=jax.ShapeDtypeStruct((Mp, N), out_dtype),
        grid=(Mp // bm, N // bn, K // bk),
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.SMEM),
            pl.BlockSpec((bm, bk), lambda i, j, k: (i, k)),
            pl.BlockSpec((bk, bn), lambda i, j, k: (k, j)),
            pl.BlockSpec((1, bn), lambda i, j, k: (0, j)),
            pl.BlockSpec((1, bn), lambda i, j, k: (0, j)),
        ],
        out_specs=pl.BlockSpec((bm, bn), lambda i, j, k: (i, j)),
        scratch_shapes=[pltpu.VMEM((bm, bn), jnp.int32)],
        compiler_params=_compiler_params(
            pltpu, vmem_limit_bytes=64 * 1024 * 1024),
        interpret=interpret,
    )(xs, xq, wq, ws2, b2)
    return out[:M]


def int8_matmul_arrays(xq, wq, wscale, xscale, bias=None,
                       out_dtype=jnp.float32, interpret=None):
    """``dequant(xq @ wq)`` with per-channel dequant fused in-epilogue.

    xq int8 [..., K]; wq int8 [K, N]; wscale [N] (dequant multiplier,
    i.e. scale/qmax); xscale scalar. Falls back to the identical XLA
    int8 dot off-TPU or on untileable shapes."""
    xscale = jnp.asarray(xscale, jnp.float32)
    if interpret is None:
        if not _on_tpu():
            return _int8_matmul_ref(xq, wq, wscale, xscale, bias, out_dtype)
        interpret = False
    lead = xq.shape[:-1]
    K = xq.shape[-1]
    N = wq.shape[1]
    M = 1
    for d in lead:
        M *= int(d)
    if xscale.size != 1:
        # per-row activation scales: a design choice, not a fallback
        return _int8_matmul_ref(xq, wq, wscale, xscale, bias, out_dtype)
    if (_pick(N, (512, 256, 128)) is None
            or _pick(K, (512, 256, 128)) is None):
        _autotune.note_fallback(
            "int8_matmul", (M, K, N),
            "K=%d or N=%d has no 128-divisible block" % (K, N))
        return _int8_matmul_ref(xq, wq, wscale, xscale, bias, out_dtype)
    if not isinstance(xq, jax.core.Tracer):
        INT8_MATMUL_CALLS.add()
    blocks = {}
    if _autotune.enabled():
        Mp = -(-M // 32) * 32
        cfg = _autotune.get_config(
            "int8_matmul", (M, K, N), "int8",
            {"bm": _pick(Mp, (256, 128, 64, 32)),
             "bn": _pick(N, (512, 256, 128)),
             "bk": _pick(K, (512, 256, 128))})
        tm, tn, tk = (int(cfg.get(k, 0) or 0) for k in ("bm", "bn", "bk"))
        if (tm and Mp % tm == 0 and tn and N % tn == 0
                and tk and K % tk == 0):
            blocks = {"bm": tm, "bn": tn, "bk": tk}
    out = _int8_matmul_2d(xq.reshape(M, K), wq, wscale, xscale, bias,
                          out_dtype=jnp.dtype(out_dtype).name,
                          interpret=interpret, **blocks)
    return out.reshape(*lead, N)


def dynamic_int8_matmul(x, wq, wscale, bias=None, interpret=None):
    """Weight-only int8 matmul for fp activations: per-tensor abs-max
    dynamic activation quantization, then the fused dequant kernel.
    First consumer: the serving engine's int8 decode path
    (``InferenceEngine(int8_weights=True)``)."""
    xscale = jnp.maximum(jnp.max(jnp.abs(x.astype(jnp.float32))),
                         1e-8) / 127.0
    xq = jnp.clip(jnp.round(x.astype(jnp.float32) / xscale),
                  -127, 127).astype(jnp.int8)
    return int8_matmul_arrays(xq, wq, wscale, xscale, bias=bias,
                              out_dtype=x.dtype, interpret=interpret)


# -- autotune family (ISSUE 17) ---------------------------------------------

def _int8_candidates(shape, dtype):
    M, K, N = shape
    Mp = -(-int(M) // 32) * 32
    bms = [c for c in (256, 128, 64, 32) if Mp % c == 0][:2]
    bns = [c for c in (512, 256, 128) if int(N) % c == 0][:2]
    bk = _pick(int(K), (512, 256, 128))
    if not bms or not bns or bk is None:
        return []
    out = []
    for bm in bms:
        for bn in bns:
            out.append({"bm": bm, "bn": bn, "bk": bk})
    return out[:5]


def _int8_bench(shape, dtype, config):
    import numpy as np

    M, K, N = (int(d) for d in shape)
    rng = np.random.default_rng(0)
    xq = jnp.asarray(rng.integers(-127, 128, (M, K), dtype=np.int8))
    wq = jnp.asarray(rng.integers(-127, 128, (K, N), dtype=np.int8))
    ws = jnp.full((N,), 0.01, jnp.float32)
    xs = jnp.asarray(0.01, jnp.float32)
    out = _int8_matmul_2d(xq, wq, ws, xs, None, out_dtype="float32",
                          interpret=not _on_tpu(), **config)
    jax.block_until_ready(out)


_autotune.register_family("int8_matmul", _int8_candidates, _int8_bench)
