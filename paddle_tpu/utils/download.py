"""Weights cache lookup (reference python/paddle/utils/download.py).

The reference downloads pretrained weights over HTTP into
~/.cache/paddle/hapi/weights. This runtime is ZERO-EGRESS by policy: the
same cache-path contract is honored (plus PADDLE_TPU_WEIGHTS_DIR), files
already present are returned with md5 verification, and a missing file
raises UnavailableError telling the user where to place it — instead of
silently attempting network IO that the environment forbids.
"""
from __future__ import annotations

import hashlib
import os

__all__ = ["get_weights_path_from_url", "get_path_from_url", "WEIGHTS_HOME"]

WEIGHTS_HOME = os.path.expanduser("~/.cache/paddle/hapi/weights")


def _md5(path: str) -> str:
    h = hashlib.md5()
    with open(path, "rb") as f:
        for chunk in iter(lambda: f.read(1 << 20), b""):
            h.update(chunk)
    return h.hexdigest()


def get_path_from_url(url: str, root_dir: str, md5sum: str = None,
                      check_exist: bool = True):
    fname = os.path.basename(url)
    search = [os.path.join(root_dir, fname)]
    env_dir = os.environ.get("PADDLE_TPU_WEIGHTS_DIR")
    if env_dir:
        search.insert(0, os.path.join(env_dir, fname))
    for path in search:
        if os.path.isfile(path):
            if md5sum and _md5(path) != md5sum:
                from ..framework.enforce import PreconditionNotMetError

                raise PreconditionNotMetError(
                    f"Cached weights {path} fail md5 verification "
                    f"(want {md5sum}).",
                    hint="delete the file and re-place a good copy")
            return path
    from ..framework.enforce import UnavailableError

    raise UnavailableError(
        f"Pretrained weights {fname!r} are not in the local cache and this "
        f"runtime performs no network IO.",
        hint=f"place the file at {search[-1]} (or set "
             f"PADDLE_TPU_WEIGHTS_DIR); source URL: {url}")


def get_weights_path_from_url(url: str, md5sum: str = None):
    """reference download.py get_weights_path_from_url: resolve a weights
    URL to a local cache path."""
    return get_path_from_url(url, WEIGHTS_HOME, md5sum)
