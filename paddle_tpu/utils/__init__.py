"""paddle_tpu.utils (reference python/paddle/utils)."""
from __future__ import annotations

from . import download  # noqa: F401
from .download import get_weights_path_from_url  # noqa: F401

__all__ = ["deprecated", "try_import", "download", "require_version",
           "get_weights_path_from_url", "unique_name", "install_check"]


def deprecated(update_to="", since="", reason=""):
    def wrapper(fn):
        return fn

    return wrapper


def try_import(module_name, err_msg=None):
    import importlib

    try:
        return importlib.import_module(module_name)
    except ImportError:
        raise ImportError(err_msg or f"{module_name} is required")


class unique_name:
    _counters = {}

    @staticmethod
    def generate(key):
        n = unique_name._counters.get(key, 0)
        unique_name._counters[key] = n + 1
        return f"{key}_{n}"

    @staticmethod
    def guard(new_generator=None):
        from contextlib import nullcontext

        return nullcontext()


def install_check():
    import jax
    import jax.numpy as jnp

    x = jnp.ones((2, 2))
    y = (x @ x).sum()
    y.block_until_ready()
    print(f"paddle_tpu is installed successfully! devices: {jax.devices()}")


def run_check():
    install_check()


def require_version(min_version, max_version=None):
    """Check the installed framework version is within range (reference
    utils/install_check.py require_version)."""
    from .. import __version__

    def _key(v):
        parts = []
        for p in str(v).split(".")[:3]:
            digits = "".join(ch for ch in p if ch.isdigit())
            parts.append(int(digits) if digits else 0)
        while len(parts) < 3:  # pad so '0.1' == '0.1.0', like the reference
            parts.append(0)
        return tuple(parts)

    if _key(__version__) < _key(min_version):
        raise Exception(
            "installed version %s is below required %s"
            % (__version__, min_version))
    if max_version is not None and _key(__version__) > _key(max_version):
        raise Exception(
            "installed version %s is above supported %s"
            % (__version__, max_version))
