"""paddle_tpu.utils (reference python/paddle/utils)."""
from __future__ import annotations

from . import download  # noqa: F401
from .download import get_weights_path_from_url  # noqa: F401

__all__ = ["deprecated", "try_import", "download",
           "get_weights_path_from_url", "unique_name", "install_check"]


def deprecated(update_to="", since="", reason=""):
    def wrapper(fn):
        return fn

    return wrapper


def try_import(module_name, err_msg=None):
    import importlib

    try:
        return importlib.import_module(module_name)
    except ImportError:
        raise ImportError(err_msg or f"{module_name} is required")


class unique_name:
    _counters = {}

    @staticmethod
    def generate(key):
        n = unique_name._counters.get(key, 0)
        unique_name._counters[key] = n + 1
        return f"{key}_{n}"

    @staticmethod
    def guard(new_generator=None):
        from contextlib import nullcontext

        return nullcontext()


def install_check():
    import jax
    import jax.numpy as jnp

    x = jnp.ones((2, 2))
    y = (x @ x).sum()
    y.block_until_ready()
    print(f"paddle_tpu is installed successfully! devices: {jax.devices()}")


def run_check():
    install_check()
