"""Custom-op extension API.

Parity: reference ``PD_BUILD_OP`` (paddle/fluid/extension/ — user C++/CUDA
kernels compiled against installed headers, loaded by
framework/custom_operator.cc and exposed through
paddle.utils.cpp_extension.load).

TPU-native redesign: a user "kernel" is a jax-traceable function — most
usefully a Pallas TPU kernel — registered with an optional custom VJP.
Registration returns a Tensor-in/Tensor-out callable wired through the
eager autograd tape AND usable under jit/to_static (the function body is
pure jax), so one registration covers both worlds the reference needed
separate op + grad-op registrations for.

    import jax.numpy as jnp
    from paddle_tpu.utils.custom_op import register_op

    @register_op("my_scale")
    def my_scale(x, *, factor=2.0):
        return x * factor            # or a pl.pallas_call kernel

    # custom gradient (optional — default is jax autodiff through the body)
    @my_scale.def_vjp
    def my_scale_vjp(residuals, g, *, factor=2.0):
        (x,) = residuals
        return (g * factor,)
"""
from __future__ import annotations

import functools
from typing import Callable, Dict, Optional

import jax

from ..framework.core import Tensor, apply_op

__all__ = ["register_op", "get_op", "registered_ops", "CustomOp"]

_REGISTRY: Dict[str, "CustomOp"] = {}


class CustomOp:
    """A registered custom op: callable on Tensors, differentiable."""

    def __init__(self, name: str, fn: Callable):
        self.name = name
        self._raw_fn = fn
        self._fwd: Optional[Callable] = None
        self._vjp: Optional[Callable] = None
        self._impl = fn  # plain body until a custom vjp is attached
        functools.update_wrapper(self, fn)

    # -- optional custom gradient ------------------------------------------
    def def_fwd(self, fwd: Callable):
        """Forward returning (out, residuals) for the custom VJP."""
        self._fwd = fwd
        self._rebuild()
        return fwd

    def def_vjp(self, vjp: Callable):
        """``vjp(residuals, cotangent, **attrs) -> input cotangents``.

        Without def_fwd, residuals default to the primal inputs tuple.
        """
        self._vjp = vjp
        self._rebuild()
        return vjp

    def _rebuild(self):
        if self._vjp is None:
            self._impl = self._raw_fn
            return
        raw, fwd, vjp = self._raw_fn, self._fwd, self._vjp

        # attrs are static for the custom_vjp: build one wrapped fn per
        # attrs signature (cached) so jax.custom_vjp sees array-only args
        @functools.lru_cache(maxsize=None)
        def for_attrs(attr_items):
            attrs = dict(attr_items)

            @jax.custom_vjp
            def op(*arrays):
                return raw(*arrays, **attrs)

            def op_fwd(*arrays):
                if fwd is not None:
                    return fwd(*arrays, **attrs)
                return raw(*arrays, **attrs), arrays

            def op_bwd(residuals, g):
                return tuple(vjp(residuals, g, **attrs))

            op.defvjp(op_fwd, op_bwd)
            return op

        def impl(*arrays, **attrs):
            return for_attrs(tuple(sorted(attrs.items())))(*arrays)

        functools.update_wrapper(impl, raw)
        self._impl = impl

    # -- call ---------------------------------------------------------------
    def __call__(self, *args, **attrs):
        return apply_op(self._impl, *args, op_name=self.name, **attrs)


def register_op(name: str, fn: Optional[Callable] = None) -> CustomOp:
    """Register a custom op (decorator or direct call).

    Raises on duplicate names, like the reference's op registry
    (OpInfoMap::Insert PADDLE_ENFORCE on duplicates).
    """
    def do(f):
        if name in _REGISTRY:
            raise ValueError(f"custom op '{name}' already registered")
        op = CustomOp(name, f)
        _REGISTRY[name] = op
        return op

    if fn is not None:
        return do(fn)
    return do


def get_op(name: str) -> CustomOp:
    if name not in _REGISTRY:
        raise KeyError(f"no custom op named '{name}' "
                       f"(registered: {sorted(_REGISTRY)})")
    return _REGISTRY[name]


def registered_ops():
    return sorted(_REGISTRY)
