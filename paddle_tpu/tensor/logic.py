"""Comparison / logical / bitwise ops (reference python/paddle/tensor/logic.py)."""
from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from ..framework.core import Tensor, apply_op

__all__ = [
    "equal", "not_equal", "greater_than", "greater_equal", "less_than",
    "less_equal", "logical_and", "logical_or", "logical_xor", "logical_not",
    "bitwise_and", "bitwise_or", "bitwise_xor", "bitwise_not", "equal_all",
    "allclose", "isclose", "is_empty", "is_tensor",
]


def _w(x):
    return x if isinstance(x, Tensor) else Tensor(jnp.asarray(x))


def _mk(jfn, name):
    def op(x, y, name=None):
        return apply_op(jfn, _w(x), _w(y), op_name=name)

    op.__name__ = name
    return op


equal = _mk(jnp.equal, "equal")
not_equal = _mk(jnp.not_equal, "not_equal")
greater_than = _mk(jnp.greater, "greater_than")
greater_equal = _mk(jnp.greater_equal, "greater_equal")
less_than = _mk(jnp.less, "less_than")
less_equal = _mk(jnp.less_equal, "less_equal")
logical_and = _mk(jnp.logical_and, "logical_and")
logical_or = _mk(jnp.logical_or, "logical_or")
logical_xor = _mk(jnp.logical_xor, "logical_xor")
bitwise_and = _mk(jnp.bitwise_and, "bitwise_and")
bitwise_or = _mk(jnp.bitwise_or, "bitwise_or")
bitwise_xor = _mk(jnp.bitwise_xor, "bitwise_xor")


def logical_not(x, name=None):
    return apply_op(jnp.logical_not, _w(x))


def bitwise_not(x, name=None):
    return apply_op(jnp.bitwise_not, _w(x))


def _equal_all(x, y):
    return jnp.array_equal(x, y)


def equal_all(x, y, name=None):
    return apply_op(_equal_all, _w(x), _w(y))


def _allclose(x, y, rtol=1e-05, atol=1e-08, equal_nan=False):
    return jnp.allclose(x, y, rtol=rtol, atol=atol, equal_nan=equal_nan)


def allclose(x, y, rtol=1e-05, atol=1e-08, equal_nan=False, name=None):
    return apply_op(_allclose, _w(x), _w(y), rtol=float(rtol), atol=float(atol), equal_nan=bool(equal_nan))


def _isclose(x, y, rtol=1e-05, atol=1e-08, equal_nan=False):
    return jnp.isclose(x, y, rtol=rtol, atol=atol, equal_nan=equal_nan)


def isclose(x, y, rtol=1e-05, atol=1e-08, equal_nan=False, name=None):
    return apply_op(_isclose, _w(x), _w(y), rtol=float(rtol), atol=float(atol), equal_nan=bool(equal_nan))


def is_empty(x, name=None):
    return Tensor(jnp.asarray(x.size == 0))


def is_tensor(x):
    return isinstance(x, Tensor)
