"""Tensor creation ops.

Parity surface: reference python/paddle/tensor/creation.py. All creation is
eager jnp; values land on the default device (TPU) lazily via jax.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..framework import dtype as dtypes
from ..framework.core import Tensor, apply_op, to_tensor  # noqa: F401

__all__ = [
    "to_tensor", "zeros", "ones", "full", "zeros_like", "ones_like",
    "full_like", "empty", "empty_like", "arange", "linspace", "logspace",
    "eye", "tril", "triu", "diag", "diagflat", "meshgrid", "assign",
    "clone", "numel", "one_hot", "complex", "create_parameter",
    "check_shape",
]


def _dt(dtype, default=None):
    d = dtypes.convert_dtype(dtype)
    if d is None:
        d = default if default is not None else dtypes.default_float_dtype()
    return d


def _shape(shape):
    if isinstance(shape, Tensor):
        shape = shape.numpy().tolist()
    if isinstance(shape, (int, np.integer)):
        return (int(shape),)
    return tuple(int(s) for s in shape)


def zeros(shape, dtype=None, name=None):
    return Tensor(jnp.zeros(_shape(shape), _dt(dtype)))


def ones(shape, dtype=None, name=None):
    return Tensor(jnp.ones(_shape(shape), _dt(dtype)))


def full(shape, fill_value, dtype=None, name=None):
    if isinstance(fill_value, Tensor):
        fill_value = fill_value.item()
    return Tensor(jnp.full(_shape(shape), fill_value, _dt(dtype)))


def empty(shape, dtype=None, name=None):
    return zeros(shape, dtype)


def zeros_like(x, dtype=None, name=None):
    return Tensor(jnp.zeros_like(x._data if isinstance(x, Tensor) else x, dtype=dtypes.convert_dtype(dtype)))


def ones_like(x, dtype=None, name=None):
    return Tensor(jnp.ones_like(x._data if isinstance(x, Tensor) else x, dtype=dtypes.convert_dtype(dtype)))


def full_like(x, fill_value, dtype=None, name=None):
    return Tensor(jnp.full_like(x._data if isinstance(x, Tensor) else x, fill_value, dtype=dtypes.convert_dtype(dtype)))


def empty_like(x, dtype=None, name=None):
    return zeros_like(x, dtype)


def arange(start=0, end=None, step=1, dtype=None, name=None):
    for v in (start, end, step):
        pass
    start = start.item() if isinstance(start, Tensor) else start
    end = end.item() if isinstance(end, Tensor) else end
    step = step.item() if isinstance(step, Tensor) else step
    if end is None:
        start, end = 0, start
    if dtype is None:
        dtype = "int64" if all(
            isinstance(v, (int, np.integer)) for v in (start, end, step)
        ) else None
    return Tensor(jnp.arange(start, end, step, dtype=dtypes.convert_dtype(dtype)))


def linspace(start, stop, num, dtype=None, name=None):
    start = start.item() if isinstance(start, Tensor) else start
    stop = stop.item() if isinstance(stop, Tensor) else stop
    num = num.item() if isinstance(num, Tensor) else num
    return Tensor(jnp.linspace(start, stop, int(num), dtype=_dt(dtype)))


def logspace(start, stop, num, base=10.0, dtype=None, name=None):
    return Tensor(jnp.logspace(start, stop, int(num), base=base, dtype=_dt(dtype)))


def eye(num_rows, num_columns=None, dtype=None, name=None):
    return Tensor(jnp.eye(int(num_rows), num_columns if num_columns is None else int(num_columns), dtype=_dt(dtype)))


def _tril(x, diagonal=0):
    return jnp.tril(x, k=diagonal)


def _triu(x, diagonal=0):
    return jnp.triu(x, k=diagonal)


def tril(x, diagonal=0, name=None):
    return apply_op(_tril, x, diagonal=int(diagonal))


def triu(x, diagonal=0, name=None):
    return apply_op(_triu, x, diagonal=int(diagonal))


def _diag(x, offset=0, padding_value=0):
    if x.ndim == 1 and padding_value != 0:
        d = jnp.diag(x, k=offset)
        mask = jnp.diag(jnp.ones_like(x, dtype=bool), k=offset)
        return jnp.where(mask, d, jnp.asarray(padding_value, d.dtype))
    return jnp.diag(x, k=offset)


def diag(x, offset=0, padding_value=0, name=None):
    return apply_op(_diag, x, offset=int(offset), padding_value=padding_value)


def _diagflat(a, offset=0):
    return jnp.diagflat(a, k=offset)


def diagflat(x, offset=0, name=None):
    return apply_op(_diagflat, x, offset=int(offset))


def meshgrid(*args, **kwargs):
    if len(args) == 1 and isinstance(args[0], (list, tuple)):
        args = args[0]
    from ..framework.core import apply_op

    # through apply_op so the broadcasts stay on the tape (reference
    # meshgrid_op has a grad kernel; wrapping raw outputs severed it)
    out = apply_op(lambda *xs: tuple(jnp.meshgrid(*xs, indexing="ij")),
                   *args, op_name="meshgrid")
    return list(out) if isinstance(out, tuple) else [out]


def _identity(x):
    return x + 0 if jnp.issubdtype(x.dtype, jnp.number) else jnp.array(x)


def assign(x, output=None):
    src = x._data if isinstance(x, Tensor) else jnp.asarray(x)
    if output is not None:
        output.set_value(src)
        return output
    return apply_op(_identity, x if isinstance(x, Tensor) else Tensor(src))


def clone(x, name=None):
    return apply_op(_identity, x)


def numel(x, name=None):
    return Tensor(jnp.asarray(x.size if isinstance(x, Tensor) else np.size(x), dtype=jnp.int32))


def _one_hot(x, num_classes):
    return jax.nn.one_hot(x, num_classes, dtype=dtypes.default_float_dtype())


def one_hot(x, num_classes, name=None):
    return apply_op(_one_hot, x, num_classes=int(num_classes))


def _complex(r, i):
    return jax.lax.complex(r, i)


def complex(real, imag, name=None):  # noqa: A001
    return apply_op(_complex, real, imag)


def create_parameter(shape, dtype, name=None, attr=None, is_bias=False,
                     default_initializer=None):
    """Create a learnable Parameter directly
    (reference fluid/layers/tensor.py:97: Xavier default, Constant(0) for
    bias)."""
    from ..framework.core import Parameter
    from ..framework.param_attr import ParamAttr
    from ..nn import initializer as I

    init = default_initializer
    trainable = True
    attr = ParamAttr._to_attr(attr)
    if getattr(attr, "weight_norm_dim", None) is not None:
        raise NotImplementedError(
            "WeightNormParamAttr: apply nn.utils.weight_norm(layer) "
            "instead — the reparameterization is a layer hook here")
    if isinstance(attr, ParamAttr):
        if attr.initializer is not None:
            init = attr.initializer
        trainable = attr.trainable
        if name is None:
            name = attr.name
    if init is None:
        init = I._global_default(is_bias)
    if init is None:
        init = I.Constant(0.0) if is_bias else I.XavierUniform()
    dt = _dt(dtype)
    data = init(tuple(int(s) for s in shape), dt)
    return Parameter(data, name=name, trainable=trainable)


def check_shape(shape, op_name="create"):
    """Validate a shape argument (reference fluid/data_feeder.py:142)."""
    if isinstance(shape, Tensor):
        return
    if not isinstance(shape, (list, tuple)):
        raise TypeError("%s: shape must be a list/tuple/Tensor, got %r"
                        % (op_name, type(shape)))
    for s in shape:
        if not isinstance(s, (int, np.integer)) and not isinstance(s, Tensor):
            raise TypeError("%s: shape entries must be int or Tensor" % op_name)
        if isinstance(s, (int, np.integer)) and s < -1:
            raise ValueError("%s: shape entries must be >= -1" % op_name)
