"""paddle_tpu.tensor — the op namespace, plus Tensor method attachment.

Mirrors the reference's monkey-patching of math/manipulation/... methods
onto Tensor (python/paddle/tensor/__init__.py + fluid/dygraph/math_op_patch.py).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..framework.core import Tensor, Parameter, apply_op, to_tensor

from .creation import *  # noqa: F401,F403
from .math import *  # noqa: F401,F403
from .manipulation import *  # noqa: F401,F403
from .logic import *  # noqa: F401,F403
from .search import *  # noqa: F401,F403
from .random import *  # noqa: F401,F403
from .linalg import *  # noqa: F401,F403
from .signal import *  # noqa: F401,F403

from . import creation, math, manipulation, logic, search, random, linalg, signal  # noqa: F401


def _einsum_impl(*ops, equation):
    return jnp.einsum(equation, *ops)


def einsum(equation, *operands):
    if len(operands) == 1 and isinstance(operands[0], (list, tuple)):
        operands = tuple(operands[0])
    return apply_op(_einsum_impl, *operands, equation=equation)


# --------------------------------------------------------------------------
# index helpers for Tensor __getitem__/__setitem__
# --------------------------------------------------------------------------

def _norm_index(idx):
    """Normalize an index: Tensors -> numpy arrays (concrete), keep rest."""
    def conv(i):
        if isinstance(i, Tensor):
            d = i._data
            if isinstance(d, jax.core.Tracer):
                return d
            if d.dtype == jnp.bool_:
                return np.asarray(d)
            return np.asarray(d)
        if isinstance(i, (list, np.ndarray)):
            return np.asarray(i)
        return i

    if isinstance(idx, tuple):
        return tuple(conv(i) for i in idx)
    return conv(idx)


def _getitem_impl(x, idx):
    return x[idx]


def _tensor_getitem(self, idx):
    idx = _norm_index(idx)
    return apply_op(_getitem_impl, self, idx=idx)


def _setitem_impl(x, v, idx):
    return x.at[idx].set(v)


def _tensor_setitem(self, idx, value):
    from ..framework.core import inplace_apply

    idx = _norm_index(idx)
    if not isinstance(value, Tensor):
        value = Tensor(jnp.asarray(value, dtype=self._data.dtype))
    elif value.dtype != self.dtype:
        value = cast(value, self.dtype)
    # inplace_apply runs the op against an alias carrying the old tape node:
    # rebinding self directly would make the new node its own input and
    # sever the gradient history (see inplace_apply docstring).
    inplace_apply(self, lambda prev: apply_op(_setitem_impl, prev, value,
                                              idx=idx))
    if self._grad_node is not None:
        self.stop_gradient = False


# --------------------------------------------------------------------------
# dunders
# --------------------------------------------------------------------------

def _binop(fn):
    def op(self, other):
        return fn(self, other)

    return op


def _rbinop(fn):
    def op(self, other):
        return fn(other, self)

    return op


_DUNDERS = {
    "__add__": _binop(add), "__radd__": _rbinop(add),
    "__sub__": _binop(subtract), "__rsub__": _rbinop(subtract),
    "__mul__": _binop(multiply), "__rmul__": _rbinop(multiply),
    "__truediv__": _binop(divide), "__rtruediv__": _rbinop(divide),
    "__floordiv__": _binop(floor_divide), "__rfloordiv__": _rbinop(floor_divide),
    "__mod__": _binop(remainder), "__rmod__": _rbinop(remainder),
    "__pow__": _binop(pow), "__rpow__": _rbinop(pow),
    "__matmul__": _binop(matmul), "__rmatmul__": _rbinop(matmul),
    "__eq__": _binop(equal), "__ne__": _binop(not_equal),
    "__lt__": _binop(less_than), "__le__": _binop(less_equal),
    "__gt__": _binop(greater_than), "__ge__": _binop(greater_equal),
    "__and__": _binop(logical_and), "__or__": _binop(logical_or),
    "__xor__": _binop(logical_xor),
    "__getitem__": _tensor_getitem,
    "__setitem__": _tensor_setitem,
}


def _neg(self):
    return neg(self)


def _abs(self):
    return abs(self)


def _invert(self):
    return logical_not(self)


_DUNDERS["__neg__"] = _neg
_DUNDERS["__abs__"] = _abs
_DUNDERS["__invert__"] = _invert

for name, fn in _DUNDERS.items():
    setattr(Tensor, name, fn)

# keep identity-based hash (overridden by __eq__ definition above otherwise)
Tensor.__hash__ = lambda self: id(self)


# --------------------------------------------------------------------------
# method attachment: t.sum(), t.reshape(), ...
# --------------------------------------------------------------------------

_METHODS = [
    # math
    "add", "subtract", "multiply", "divide", "floor_divide", "remainder",
    "mod", "pow", "matmul", "mm", "bmm", "dot", "inner", "outer", "addmm",
    "maximum", "minimum", "exp", "expm1", "log", "log2", "log10", "log1p",
    "sqrt", "rsqrt", "square", "abs", "sign", "floor", "ceil", "round",
    "trunc", "sin", "cos", "tan", "asin", "acos", "atan", "atan2", "sinh",
    "cosh", "tanh", "asinh", "acosh", "atanh", "reciprocal", "sigmoid",
    "clip", "sum", "mean", "max", "min", "prod", "cumsum", "cumprod",
    "logsumexp", "std", "var", "median", "isnan", "isinf", "isfinite",
    "nan_to_num", "erf", "erfinv", "lgamma", "digamma", "neg", "scale",
    "all", "any", "trace", "lerp", "kron", "count_nonzero", "frac",
    # manipulation
    "reshape", "transpose", "concat", "split", "chunk", "squeeze",
    "unsqueeze", "flatten", "flip", "roll", "tile", "expand", "expand_as",
    "broadcast_to", "gather", "gather_nd", "scatter", "scatter_nd_add",
    "index_select", "masked_select", "unbind", "unique", "repeat_interleave",
    "take_along_axis", "put_along_axis", "moveaxis", "tolist", "where",
    "index_sample", "index_add", "pad",
    # logic
    "equal", "not_equal", "greater_than", "greater_equal", "less_than",
    "less_equal", "logical_and", "logical_or", "logical_xor", "logical_not",
    "bitwise_and", "bitwise_or", "bitwise_xor", "bitwise_not", "equal_all",
    "allclose", "isclose",
    # search
    "argmax", "argmin", "argsort", "sort", "topk", "nonzero", "kthvalue",
    "mode",
    # linalg
    "norm", "dist", "t", "cross", "cholesky", "inv", "matrix_power",
    # creation
    "tril", "triu", "diag",
]

_ns = globals()
for _m in _METHODS:
    if _m in _ns and not hasattr(Tensor, _m):
        setattr(Tensor, _m, _ns[_m])

# a couple of aliases paddle exposes as methods
Tensor.dim = lambda self: self.ndim
Tensor.rank = lambda self: Tensor(jnp.asarray(self.ndim))
Tensor.cpu = lambda self: self
Tensor.cuda = lambda self, *a, **k: self
Tensor.pin_memory = lambda self: self
