"""Signal ops: frame / overlap_add / stft / istft
(reference python/paddle/tensor/signal.py:34,155,238,392).

frame/overlap_add are pure gather/scatter-add reshapes, so XLA fuses them;
stft composes frame + rfft/fft and istft inverts it with the standard
window-envelope normalization. All differentiable through apply_op's vjp.
"""
from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from ..framework.core import Tensor, apply_op

__all__ = ["frame", "overlap_add", "stft", "istft"]


def _frame_impl(x, frame_length, hop_length, axis=-1):
    if axis not in (0, -1):
        raise ValueError("frame: axis must be 0 or -1, got %d" % axis)
    if axis == 0:
        # [seq, ...] -> operate on leading axis; move it last, recurse, undo
        y = _frame_impl(jnp.moveaxis(x, 0, -1), frame_length, hop_length, -1)
        # y: [..., frame_length, num_frames] -> [num_frames, frame_length, ...]
        return jnp.moveaxis(jnp.moveaxis(y, -1, 0), -1, 1)
    seq_len = x.shape[-1]
    num_frames = 1 + (seq_len - frame_length) // hop_length
    starts = jnp.arange(num_frames) * hop_length               # [F]
    offs = jnp.arange(frame_length)                            # [L]
    idx = starts[None, :] + offs[:, None]                      # [L, F]
    return x[..., idx]                                         # [..., L, F]


def frame(x, frame_length, hop_length, axis=-1, name=None):
    frame_length, hop_length = int(frame_length), int(hop_length)
    if frame_length <= 0 or hop_length <= 0:
        raise ValueError("frame_length and hop_length must be positive")
    seq_len = x.shape[0] if int(axis) == 0 else x.shape[-1]
    if seq_len < frame_length:
        raise ValueError(
            "frame: input length (%d) must be >= frame_length (%d)"
            % (seq_len, frame_length))
    return apply_op(_frame_impl, x, frame_length=frame_length,
                    hop_length=hop_length, axis=int(axis), op_name="frame")


def _overlap_add_impl(x, hop_length, axis=-1):
    if axis not in (0, -1):
        raise ValueError("overlap_add: axis must be 0 or -1, got %d" % axis)
    if axis == 0:
        # [num_frames, frame_length, ...] -> [..., frame_length, num_frames]
        y = _overlap_add_impl(
            jnp.moveaxis(jnp.moveaxis(x, 0, -1), 0, -2), hop_length, -1)
        return jnp.moveaxis(y, -1, 0)
    frame_length, num_frames = x.shape[-2], x.shape[-1]
    seq_len = (num_frames - 1) * hop_length + frame_length
    starts = jnp.arange(num_frames) * hop_length
    idx = (starts[None, :] + jnp.arange(frame_length)[:, None]).reshape(-1)
    flat = x.reshape(x.shape[:-2] + (frame_length * num_frames,))
    out = jnp.zeros(x.shape[:-2] + (seq_len,), dtype=x.dtype)
    return out.at[..., idx].add(flat)


def overlap_add(x, hop_length, axis=-1, name=None):
    return apply_op(_overlap_add_impl, x, hop_length=int(hop_length),
                    axis=int(axis), op_name="overlap_add")


def _prep_window(window, win_length, n_fft, op):
    """Validate win_length<=n_fft (reference signal.py asserts this) and
    center-pad the window to n_fft."""
    if win_length > n_fft:
        raise ValueError(
            "%s: win_length (%d) must be <= n_fft (%d)" % (op, win_length, n_fft))
    if window is not None:
        w = window.numpy() if isinstance(window, Tensor) else np.asarray(window)
        if w.ndim != 1 or len(w) != win_length:
            raise ValueError(
                "%s: window must be a 1-D tensor of length win_length (%d), "
                "got shape %r" % (op, win_length, tuple(w.shape)))
    else:
        w = np.ones(win_length, np.float32)
    if len(w) < n_fft:
        lpad = (n_fft - len(w)) // 2
        w = np.pad(w, (lpad, n_fft - len(w) - lpad))
    return Tensor(jnp.asarray(w))


def _stft_impl(x, window, n_fft, hop_length, center, pad_mode, normalized,
               onesided):
    if center:
        pad = [(0, 0)] * (x.ndim - 1) + [(n_fft // 2, n_fft // 2)]
        x = jnp.pad(x, pad, mode=pad_mode)
    frames = _frame_impl(x, n_fft, hop_length, -1)      # [..., n_fft, F]
    frames = frames * window[:, None]
    if onesided:
        out = jnp.fft.rfft(frames, axis=-2)
    else:
        out = jnp.fft.fft(frames, axis=-2)
    if normalized:
        out = out / jnp.sqrt(jnp.asarray(n_fft, out.real.dtype))
    return out


def stft(x, n_fft, hop_length=None, win_length=None, window=None,
         center=True, pad_mode="reflect", normalized=False, onesided=True,
         name=None):
    """Short-time Fourier transform (reference tensor/signal.py:238).

    x: [..., seq_length] real (or complex with onesided=False). Returns
    complex [..., n_fft//2+1 (or n_fft), num_frames].
    """
    n_fft = int(n_fft)
    hop_length = int(hop_length) if hop_length is not None else n_fft // 4
    win_length = int(win_length) if win_length is not None else n_fft
    w = _prep_window(window, win_length, n_fft, "stft")
    return apply_op(_stft_impl, x, w, n_fft=n_fft,
                    hop_length=hop_length, center=bool(center),
                    pad_mode=pad_mode, normalized=bool(normalized),
                    onesided=bool(onesided), op_name="stft")


def _istft_impl(x, window, n_fft, hop_length, center, normalized, onesided,
                length, return_complex):
    if normalized:
        x = x * jnp.sqrt(jnp.asarray(n_fft, x.real.dtype))
    if onesided:
        frames = jnp.fft.irfft(x, n=n_fft, axis=-2)     # [..., n_fft, F]
    else:
        frames = jnp.fft.ifft(x, axis=-2)
        if not return_complex:
            frames = frames.real
    frames = frames * window[:, None]
    y = _overlap_add_impl(frames, hop_length, -1)
    # window-envelope normalization: overlap-add of window^2
    wsq = jnp.broadcast_to((window ** 2)[:, None], (n_fft, x.shape[-1]))
    env = _overlap_add_impl(wsq, hop_length, -1)
    y = y / jnp.where(env > 1e-11, env, 1.0)
    if center:
        y = y[..., n_fft // 2:]
        if length is None:
            # all full frames minus the symmetric head padding
            y = y[..., : (x.shape[-1] - 1) * hop_length]
    if length is not None:
        y = y[..., :length]
    return y


def istft(x, n_fft, hop_length=None, win_length=None, window=None,
          center=True, normalized=False, onesided=True, length=None,
          return_complex=False, name=None):
    """Inverse STFT (reference tensor/signal.py:392)."""
    n_fft = int(n_fft)
    if onesided and return_complex:
        raise ValueError(
            "istft: onesided=True cannot produce a complex output "
            "(set onesided=False for return_complex=True)")
    hop_length = int(hop_length) if hop_length is not None else n_fft // 4
    win_length = int(win_length) if win_length is not None else n_fft
    w = _prep_window(window, win_length, n_fft, "istft")
    return apply_op(_istft_impl, x, w, n_fft=n_fft,
                    hop_length=hop_length, center=bool(center),
                    normalized=bool(normalized), onesided=bool(onesided),
                    length=None if length is None else int(length),
                    return_complex=bool(return_complex), op_name="istft")
