"""Shape/layout manipulation ops.

Parity surface: reference python/paddle/tensor/manipulation.py.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..framework import dtype as dtypes
from ..framework.core import Tensor, apply_op, inplace_apply

__all__ = [
    "reshape", "transpose", "concat", "stack", "split", "chunk", "squeeze",
    "unsqueeze", "flatten", "flip", "roll", "tile", "expand", "expand_as",
    "broadcast_to", "broadcast_tensors", "gather", "gather_nd", "scatter",
    "scatter_nd_add", "index_select", "masked_select", "where", "slice",
    "unbind", "unique", "unique_consecutive", "repeat_interleave",
    "take_along_axis", "put_along_axis", "moveaxis", "cast", "unstack",
    "strided_slice", "tensordot", "as_real", "as_complex", "crop", "pad",
    "index_sample", "index_add", "tolist", "split_sections", "shape",
    "rank", "reverse", "scatter_nd", "shard_index", "reshape_",
    "squeeze_", "unsqueeze_", "scatter_", "broadcast_shape",
]


def _ax(a):
    if isinstance(a, Tensor):
        a = a.item()
    return int(a)


def _reshape(x, shape):
    return jnp.reshape(x, shape)


def reshape(x, shape, name=None):
    from ..framework.enforce import InvalidArgumentError, check_type

    check_type(x, "x", Tensor, "reshape")
    if isinstance(shape, Tensor):
        shape = shape.numpy().tolist()
    shape = tuple(int(s.item()) if isinstance(s, Tensor) else int(s) for s in shape)
    n_unknown = sum(1 for s in shape if s == -1)
    if n_unknown > 1:
        raise InvalidArgumentError(
            f"Only one dimension value of 'shape' in reshape can be -1, "
            f"but received shape = {list(shape)}.")
    import numpy as _np

    known = int(_np.prod([s for s in shape if s != -1])) if shape else 1
    total = int(_np.prod(x.shape)) if x.shape else 1
    if (n_unknown == 0 and known != total) or             (n_unknown == 1 and (known == 0 or total % known != 0)):
        raise InvalidArgumentError(
            f"The 'shape' in reshape is invalid: input has {total} "
            f"elements, shape = {list(shape)}.",
            hint="the product of the target shape must equal the element "
                 "count")
    return apply_op(_reshape, x, shape=shape)


def _transpose(x, perm):
    return jnp.transpose(x, perm)


def transpose(x, perm, name=None):
    from ..framework.enforce import InvalidArgumentError

    perm = tuple(int(p) for p in perm)
    nd = x.ndim if hasattr(x, "ndim") else len(x.shape)
    if sorted(perm) != list(range(nd)):
        raise InvalidArgumentError(
            f"The 'perm' in transpose must be a permutation of "
            f"[0, ..., {nd - 1}], but received {list(perm)}.")
    return apply_op(_transpose, x, perm=perm)


def _concat_op(*xs, axis=0):
    return jnp.concatenate(xs, axis=axis)


def concat(x, axis=0, name=None):
    from ..framework.enforce import (InvalidArgumentError, check_axis,
                                     check_type)

    check_type(x, "x", (list, tuple), "concat")
    if not x:
        raise InvalidArgumentError("The input list of concat is empty.")
    ax = check_axis(_ax(axis), x[0].ndim, "concat")
    ref = list(x[0].shape)
    for i, t in enumerate(x[1:], 1):
        s = list(t.shape)
        if len(s) != len(ref) or any(
                a != b for d, (a, b) in enumerate(zip(s, ref)) if d != ax):
            raise InvalidArgumentError(
                f"The shapes of concat inputs must match except on the "
                f"concat axis {ax}, but input 0 has shape {ref} and input "
                f"{i} has shape {s}.")
    return apply_op(_concat_op, *x, axis=ax)


def _stack_op(*xs, axis=0):
    return jnp.stack(xs, axis=axis)


def stack(x, axis=0, name=None):
    return apply_op(_stack_op, *x, axis=_ax(axis))


def _split(x, indices, axis):
    return tuple(jnp.split(x, indices, axis=axis))


def split(x, num_or_sections, axis=0, name=None):
    axis = _ax(axis)
    dim = (x.shape if isinstance(x, Tensor) else list(x.shape))[axis]
    if isinstance(num_or_sections, int):
        n = num_or_sections
        if dim % n != 0:
            raise ValueError(f"split: {dim} not divisible by {n}")
        indices = n  # jnp.split supports int
    else:
        secs = [int(s.item()) if isinstance(s, Tensor) else int(s) for s in num_or_sections]
        n_neg = secs.count(-1)
        if n_neg:
            known = sum(s for s in secs if s != -1)
            secs = [dim - known if s == -1 else s for s in secs]
        indices = tuple(np.cumsum(secs)[:-1].tolist())
    out = apply_op(_split, x, indices=indices, axis=axis)
    return list(out)


split_sections = split


def chunk(x, chunks, axis=0, name=None):
    return split(x, chunks, axis)


def _squeeze(x, axis=None):
    if axis is None:
        return jnp.squeeze(x)
    axis = tuple(a for a in axis if x.shape[a] == 1)
    return jnp.squeeze(x, axis=axis) if axis else x


def squeeze(x, axis=None, name=None):
    if axis is not None:
        if isinstance(axis, (int, np.integer)):
            axis = (int(axis),)
        axis = tuple(int(a) % (x.ndim if isinstance(x, Tensor) else x.ndim) for a in axis)
    return apply_op(_squeeze, x, axis=axis)


def _unsqueeze(x, axis):
    return jnp.expand_dims(x, axis)


def unsqueeze(x, axis, name=None):
    if isinstance(axis, Tensor):
        axis = axis.numpy().tolist()
    if isinstance(axis, (int, np.integer)):
        axis = (int(axis),)
    return apply_op(_unsqueeze, x, axis=tuple(int(a) for a in axis))


def _flatten(x, start_axis=0, stop_axis=-1):
    nd = x.ndim
    if nd == 0:
        return x.reshape(1)
    sa, so = start_axis % nd, stop_axis % nd
    new_shape = x.shape[:sa] + (-1,) + x.shape[so + 1:]
    return x.reshape(new_shape)


def flatten(x, start_axis=0, stop_axis=-1, name=None):
    return apply_op(_flatten, x, start_axis=int(start_axis), stop_axis=int(stop_axis))


def _flip(x, axis):
    return jnp.flip(x, axis=axis)


def flip(x, axis, name=None):
    if isinstance(axis, (int, np.integer)):
        axis = (int(axis),)
    return apply_op(_flip, x, axis=tuple(int(a) for a in axis))


def _roll(x, shifts, axis=None):
    return jnp.roll(x, shifts, axis=axis)


def roll(x, shifts, axis=None, name=None):
    if isinstance(shifts, (list, tuple)):
        shifts = tuple(int(s) for s in shifts)
    else:
        shifts = int(shifts)
    if isinstance(axis, (list, tuple)):
        axis = tuple(int(a) for a in axis)
    elif axis is not None:
        axis = int(axis)
    return apply_op(_roll, x, shifts=shifts, axis=axis)


def _tile(x, reps):
    return jnp.tile(x, reps)


def tile(x, repeat_times, name=None):
    if isinstance(repeat_times, Tensor):
        repeat_times = repeat_times.numpy().tolist()
    return apply_op(_tile, x, reps=tuple(int(r.item()) if isinstance(r, Tensor) else int(r) for r in repeat_times))


def _expand(x, shape):
    offset = len(shape) - x.ndim
    shape = tuple(
        x.shape[i - offset] if (s == -1 and i >= offset) else s
        for i, s in enumerate(shape)
    )
    return jnp.broadcast_to(x, shape)


def expand(x, shape, name=None):
    if isinstance(shape, Tensor):
        shape = shape.numpy().tolist()
    shape = tuple(int(s.item()) if isinstance(s, Tensor) else int(s) for s in shape)
    return apply_op(_expand, x, shape=shape)


def expand_as(x, y, name=None):
    return expand(x, y.shape)


def broadcast_to(x, shape, name=None):
    return expand(x, shape)


def broadcast_tensors(inputs, name=None):
    arrs = [t._data if isinstance(t, Tensor) else jnp.asarray(t) for t in inputs]
    shape = jnp.broadcast_shapes(*[a.shape for a in arrs])
    return [expand(t if isinstance(t, Tensor) else Tensor(t), list(shape)) for t in inputs]


def _gather(x, index, axis=0):
    return jnp.take(x, index, axis=axis)


def gather(x, index, axis=0, name=None):
    if isinstance(index, Tensor) and index.ndim > 1:
        index = reshape(index, [-1])
    return apply_op(_gather, x, index, axis=_ax(axis))


def _gather_nd(x, index):
    idx = tuple(jnp.moveaxis(index, -1, 0))
    return x[idx]


def gather_nd(x, index, name=None):
    return apply_op(_gather_nd, x, index)


def _scatter(x, index, updates, overwrite=True):
    if index.ndim == 2:
        index = index[:, 0]
    if overwrite:
        return x.at[index].set(updates)
    # paddle scatter(overwrite=False): zero the rows then add
    zeroed = x.at[index].set(jnp.zeros_like(updates))
    return zeroed.at[index].add(updates)


def scatter(x, index, updates, overwrite=True, name=None):
    return apply_op(_scatter, x, index, updates, overwrite=bool(overwrite))


def _scatter_nd_add(x, index, updates):
    idx = tuple(jnp.moveaxis(index, -1, 0))
    return x.at[idx].add(updates)


def scatter_nd_add(x, index, updates, name=None):
    return apply_op(_scatter_nd_add, x, index, updates)


def index_select(x, index, axis=0, name=None):
    return apply_op(_gather, x, index, axis=_ax(axis))


def _index_add(x, index, value, axis):
    x_m = jnp.moveaxis(x, axis, 0)
    v_m = jnp.moveaxis(value, axis, 0)
    out = x_m.at[index].add(v_m)
    return jnp.moveaxis(out, 0, axis)


def index_add(x, index, axis, value, name=None):
    return apply_op(_index_add, x, index, value if isinstance(value, Tensor) else Tensor(jnp.asarray(value)), axis=_ax(axis))


def _index_sample(x, index):
    return jnp.take_along_axis(x, index, axis=1)


def index_sample(x, index, name=None):
    return apply_op(_index_sample, x, index)


def _masked_take(x, flat_idx):
    return jnp.take(x.reshape(-1), flat_idx)


def masked_select(x, mask, name=None):
    # dynamic-shaped: eager only (not jittable) — mirrors reference
    # semantics. The mask resolves to host indices eagerly; the gather
    # itself goes through apply_op so gradients flow back to x.
    import numpy as np

    xa = x._data if isinstance(x, Tensor) else jnp.asarray(x)
    ma = mask._data if isinstance(mask, Tensor) else jnp.asarray(mask)
    ma = np.broadcast_to(np.asarray(ma), xa.shape)
    flat_idx = jnp.asarray(np.nonzero(ma.reshape(-1))[0])
    return apply_op(_masked_take, x, flat_idx=flat_idx)


def _where(cond, x, y):
    return jnp.where(cond, x, y)


def where(condition, x=None, y=None, name=None):
    if x is None and y is None:
        from .search import nonzero

        return nonzero(condition, as_tuple=False)
    if not isinstance(x, Tensor):
        x = Tensor(jnp.asarray(x))
    if not isinstance(y, Tensor):
        y = Tensor(jnp.asarray(y))
    return apply_op(_where, condition, x, y)


_py_slice = slice  # the builtin — shadowed below by the paddle op


def _slice_op(x, axes, starts, ends):
    idx = [_py_slice(None)] * x.ndim
    for ax, st, en in zip(axes, starts, ends):
        idx[ax] = _py_slice(st, en)
    return x[tuple(idx)]


def slice(x, axes, starts, ends, name=None):  # noqa: A001
    starts = tuple(int(s.item()) if isinstance(s, Tensor) else int(s) for s in starts)
    ends = tuple(int(e.item()) if isinstance(e, Tensor) else int(e) for e in ends)
    return apply_op(_slice_op, x, axes=tuple(int(a) for a in axes), starts=starts, ends=ends)


def _strided_slice(x, axes, starts, ends, strides):
    idx = [_py_slice(None)] * x.ndim
    for ax, st, en, sd in zip(axes, starts, ends, strides):
        idx[ax] = _py_slice(st, en, sd)
    return x[tuple(idx)]


def strided_slice(x, axes, starts, ends, strides, name=None):
    return apply_op(
        _strided_slice, x,
        axes=tuple(int(a) for a in axes),
        starts=tuple(int(s) for s in starts),
        ends=tuple(int(e) for e in ends),
        strides=tuple(int(s) for s in strides),
    )


def _unbind(x, axis=0):
    return tuple(jnp.moveaxis(x, axis, 0))


def unbind(input, axis=0, name=None):  # noqa: A002
    return list(apply_op(_unbind, input, axis=_ax(axis)))


unstack = unbind


def unique(x, return_index=False, return_inverse=False, return_counts=False, axis=None, dtype="int64", name=None):
    xa = np.asarray(x._data if isinstance(x, Tensor) else x)
    res = np.unique(xa, return_index=return_index, return_inverse=return_inverse, return_counts=return_counts, axis=axis)
    if not isinstance(res, tuple):
        return Tensor(jnp.asarray(res))
    return tuple(Tensor(jnp.asarray(r)) for r in res)


def unique_consecutive(x, return_inverse=False, return_counts=False, axis=None, dtype="int64", name=None):
    xa = np.asarray(x._data if isinstance(x, Tensor) else x)
    if axis is not None or xa.ndim > 1 and axis is None:
        xa = xa.reshape(-1) if axis is None else xa
    keep = np.ones(xa.shape[0], dtype=bool)
    keep[1:] = xa[1:] != xa[:-1]
    out = [Tensor(jnp.asarray(xa[keep]))]
    if return_inverse:
        inv = np.cumsum(keep) - 1
        out.append(Tensor(jnp.asarray(inv)))
    if return_counts:
        idx = np.nonzero(keep)[0]
        counts = np.diff(np.append(idx, xa.shape[0]))
        out.append(Tensor(jnp.asarray(counts)))
    return out[0] if len(out) == 1 else tuple(out)


def _repeat_interleave(x, repeats, axis=None):
    return jnp.repeat(x, repeats, axis=axis)


def repeat_interleave(x, repeats, axis=None, name=None):
    if isinstance(repeats, Tensor):
        repeats = repeats._data
        return Tensor(jnp.repeat(x._data if isinstance(x, Tensor) else x, repeats, axis=axis))
    return apply_op(_repeat_interleave, x, repeats=int(repeats), axis=None if axis is None else int(axis))


def _take_along_axis(x, indices, axis):
    return jnp.take_along_axis(x, indices, axis=axis)


def take_along_axis(arr, indices, axis, name=None):
    return apply_op(_take_along_axis, arr, indices, axis=_ax(axis))


def _put_along_axis(x, indices, values, axis, reduce="assign"):  # noqa: A002
    if reduce == "assign":
        return jnp.put_along_axis(x, indices, values, axis=axis, inplace=False)
    dims = list(range(x.ndim))
    # build scatter via at[]
    idx = [jnp.arange(s).reshape([-1 if i == d else 1 for i in dims]) for d, s in enumerate(x.shape)]
    idx[axis] = indices
    idx = [jnp.broadcast_to(i, indices.shape) for i in idx]
    values = jnp.broadcast_to(values, indices.shape)
    if reduce == "add":
        return x.at[tuple(idx)].add(values)
    if reduce == "multiply" or reduce == "mul":
        return x.at[tuple(idx)].multiply(values)
    raise ValueError(f"unknown reduce {reduce}")


def put_along_axis(arr, indices, values, axis, reduce="assign", name=None):  # noqa: A002
    if not isinstance(values, Tensor):
        values = Tensor(jnp.asarray(values, dtype=(arr.dtype if isinstance(arr, Tensor) else None)))
    return apply_op(_put_along_axis, arr, indices, values, axis=_ax(axis), reduce=reduce)


def _moveaxis(x, source, destination):
    return jnp.moveaxis(x, source, destination)


def moveaxis(x, source, destination, name=None):
    if isinstance(source, (list, tuple)):
        source = tuple(int(s) for s in source)
        destination = tuple(int(d) for d in destination)
    else:
        source, destination = int(source), int(destination)
    return apply_op(_moveaxis, x, source=source, destination=destination)


def _cast(x, dtype):
    return x.astype(dtype)


def cast(x, dtype):
    d = dtypes.convert_dtype(dtype)
    return apply_op(_cast, x, dtype=d)


def _tensordot(x, y, axes):
    return jnp.tensordot(x, y, axes=axes)


def tensordot(x, y, axes=2, name=None):
    if isinstance(axes, (list, tuple)):
        axes = tuple(tuple(a) if isinstance(a, (list, tuple)) else a for a in axes)
    return apply_op(_tensordot, x, y, axes=axes)


def _as_real(x):
    return jnp.stack([jnp.real(x), jnp.imag(x)], axis=-1)


def as_real(x, name=None):
    return apply_op(_as_real, x)


def _as_complex(x):
    return jax.lax.complex(x[..., 0], x[..., 1])


def as_complex(x, name=None):
    return apply_op(_as_complex, x)


def _crop(x, offsets, shape):
    idx = tuple(_py_slice(o, o + s) for o, s in zip(offsets, shape))
    return x[idx]


def crop(x, shape=None, offsets=None, name=None):
    nd = x.ndim
    if offsets is None:
        offsets = [0] * nd
    shape = [x.shape[i] if s == -1 else int(s) for i, s in enumerate(shape)]
    return apply_op(_crop, x, offsets=tuple(int(o) for o in offsets), shape=tuple(shape))


def _pad_nd(x, pad_width, mode="constant", value=0.0):
    if mode == "constant":
        return jnp.pad(x, pad_width, mode="constant", constant_values=value)
    if mode == "replicate":
        return jnp.pad(x, pad_width, mode="edge")
    if mode == "reflect":
        return jnp.pad(x, pad_width, mode="reflect")
    if mode == "circular":
        return jnp.pad(x, pad_width, mode="wrap")
    raise ValueError(f"unknown pad mode {mode}")


def pad(x, pad, mode="constant", value=0.0, data_format=None, name=None):  # noqa: A002
    """paddle.nn.functional.pad-compatible N-d pad.

    ``pad`` is either len==2*ndim (applies to all dims, paddle "ND" form,
    reversed last-dim-first like the reference) or the conv-style 4/6-elem
    form with data_format.
    """
    nd = x.ndim
    if isinstance(pad, Tensor):
        pad = pad.numpy().tolist()
    pad = [int(p) for p in pad]
    if len(pad) == 2 * nd:
        # paddle semantic: pad is [d0_left, d0_right, d1_left, ...] over all dims
        pw = tuple((pad[2 * i], pad[2 * i + 1]) for i in range(nd))
    else:
        # partial spec applies to trailing spatial dims per data_format
        df = data_format or {3: "NCL", 4: "NCHW", 5: "NCDHW"}.get(nd)
        n_spatial = len(pad) // 2
        pw = [(0, 0)] * nd
        if df is None:
            # no channel layout (1-D/2-D tensors): pad the trailing dims
            spatial_dims = list(range(nd - n_spatial, nd))
        elif df.startswith("NC"):
            spatial_dims = list(range(2, 2 + n_spatial))
        else:
            spatial_dims = list(range(1, 1 + n_spatial))
        # like the reference (and torch): pad[0:2] applies to the LAST
        # spatial dim, pad[2:4] to the one before it, etc.
        for i, d in enumerate(reversed(spatial_dims)):
            pw[d] = (pad[2 * i], pad[2 * i + 1])
        pw = tuple(pw)
    return apply_op(_pad_nd, x, pad_width=pw, mode=mode, value=float(value))


def tolist(x):
    return np.asarray(x._data if isinstance(x, Tensor) else x).tolist()


def _shape_impl(x):
    return jnp.asarray(x.shape, jnp.int32)


def shape(input, name=None):  # noqa: A002
    """1-D int32 tensor of the runtime shape (reference paddle.shape)."""
    return apply_op(_shape_impl, input, op_name="shape")


def _rank_impl(x):
    return jnp.asarray(x.ndim, jnp.int32)


def rank(input, name=None):  # noqa: A002
    """0-D int32 tensor holding the number of dimensions."""
    return apply_op(_rank_impl, input, op_name="rank")


def reverse(x, axis, name=None):
    """Legacy alias of flip (reference fluid.layers.reverse)."""
    return flip(x, axis, name=name)


def scatter_nd(index, updates, shape, name=None):  # noqa: A002
    """Sum-scatter ``updates`` into zeros of ``shape``
    (reference scatter_nd_op.cc: scatter_nd = scatter_nd_add onto zeros)."""
    if isinstance(shape, Tensor):
        shape = shape.tolist()
    shape = tuple(int(s) for s in shape)
    updates_t = updates if isinstance(updates, Tensor) else Tensor(jnp.asarray(updates))
    zero = Tensor(jnp.zeros(shape, updates_t.dtype))
    return scatter_nd_add(zero, index, updates_t, name=name)


def _shard_index_impl(x, shard_size, shard_id, ignore_value):
    return jnp.where(x // shard_size == shard_id, x % shard_size,
                     jnp.asarray(ignore_value, x.dtype))


def shard_index(input, index_num, nshards, shard_id, ignore_value=-1, name=None):  # noqa: A002
    """Recompute indices relative to the shard that owns them
    (reference fluid/layers/nn.py:14904 shard_index)."""
    if shard_id < 0 or shard_id >= nshards:
        raise ValueError(
            "The shard_id(%d) should be in [0, %d)" % (shard_id, nshards))
    shard_size = (int(index_num) + int(nshards) - 1) // int(nshards)
    return apply_op(_shard_index_impl, input, shard_size=shard_size,
                    shard_id=int(shard_id), ignore_value=int(ignore_value),
                    op_name="shard_index")


# ---------------------------------------------------------------------------
# inplace variants (reference tensor/manipulation.py reshape_/squeeze_/...)
# ---------------------------------------------------------------------------

def reshape_(x, shape, name=None):  # noqa: A002
    return inplace_apply(x, reshape, shape, name=name)


def squeeze_(x, axis=None, name=None):
    return inplace_apply(x, squeeze, axis=axis, name=name)


def unsqueeze_(x, axis, name=None):
    return inplace_apply(x, unsqueeze, axis, name=name)


def scatter_(x, index, updates, overwrite=True, name=None):
    return inplace_apply(x, scatter, index, updates, overwrite=overwrite,
                         name=name)


def broadcast_shape(x_shape, y_shape):
    """Broadcast result shape of two shapes (reference paddle.broadcast_shape)."""
    return list(jnp.broadcast_shapes(tuple(int(s) for s in x_shape),
                                     tuple(int(s) for s in y_shape)))
