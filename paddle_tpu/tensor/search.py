"""Search / sort / index ops (reference python/paddle/tensor/search.py)."""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..framework import dtype as dtypes
from ..framework.core import Tensor, apply_op

__all__ = [
    "argmax", "argmin", "argsort", "sort", "topk", "nonzero", "searchsorted",
    "kthvalue", "mode", "masked_select", "index_select", "where",
]

from .manipulation import index_select, masked_select, where  # re-export


def _argmax(x, axis=None, keepdim=False, dtype=jnp.int64):
    out = jnp.argmax(x, axis=axis)
    if keepdim and axis is not None:
        out = jnp.expand_dims(out, axis)
    if axis is None and keepdim:
        out = out.reshape((1,) * x.ndim)
    return out.astype(dtype)


def argmax(x, axis=None, keepdim=False, dtype="int64", name=None):
    return apply_op(_argmax, x, axis=None if axis is None else int(axis), keepdim=bool(keepdim), dtype=dtypes.convert_dtype(dtype))


def _argmin(x, axis=None, keepdim=False, dtype=jnp.int64):
    out = jnp.argmin(x, axis=axis)
    if keepdim and axis is not None:
        out = jnp.expand_dims(out, axis)
    if axis is None and keepdim:
        out = out.reshape((1,) * x.ndim)
    return out.astype(dtype)


def argmin(x, axis=None, keepdim=False, dtype="int64", name=None):
    return apply_op(_argmin, x, axis=None if axis is None else int(axis), keepdim=bool(keepdim), dtype=dtypes.convert_dtype(dtype))


def _argsort(x, axis=-1, descending=False):
    out = jnp.argsort(x, axis=axis, descending=descending)
    return out


def argsort(x, axis=-1, descending=False, name=None):
    return apply_op(_argsort, x, axis=int(axis), descending=bool(descending))


def _sort(x, axis=-1, descending=False):
    out = jnp.sort(x, axis=axis, descending=descending)
    return out


def sort(x, axis=-1, descending=False, name=None):
    return apply_op(_sort, x, axis=int(axis), descending=bool(descending))


def _topk(x, k, axis=-1, largest=True, sorted=True):  # noqa: A002
    axis = axis % x.ndim
    xm = jnp.moveaxis(x, axis, -1)
    if largest:
        vals, idx = jax.lax.top_k(xm, k)
    else:
        vals, idx = jax.lax.top_k(-xm, k)
        vals = -vals
    return jnp.moveaxis(vals, -1, axis), jnp.moveaxis(idx.astype(jnp.int64), -1, axis)


def topk(x, k, axis=-1, largest=True, sorted=True, name=None):  # noqa: A002
    if isinstance(k, Tensor):
        k = int(k.item())
    return apply_op(_topk, x, k=int(k), axis=int(axis), largest=bool(largest), sorted=bool(sorted))


def nonzero(x, as_tuple=False):
    # dynamic output shape: eager-only, like reference's dygraph nonzero
    xa = np.asarray(x._data if isinstance(x, Tensor) else x)
    nz = np.nonzero(xa)
    if as_tuple:
        return tuple(Tensor(jnp.asarray(i).reshape(-1, 1)) for i in nz)
    return Tensor(jnp.asarray(np.stack(nz, axis=1)))


def _searchsorted(sorted_sequence, values, right=False):
    side = "right" if right else "left"
    if sorted_sequence.ndim == 1:
        return jnp.searchsorted(sorted_sequence, values, side=side)
    # batched: apply along last dim
    fn = lambda s, v: jnp.searchsorted(s, v, side=side)  # noqa: E731
    flat_s = sorted_sequence.reshape(-1, sorted_sequence.shape[-1])
    flat_v = values.reshape(-1, values.shape[-1])
    out = jax.vmap(fn)(flat_s, flat_v)
    return out.reshape(values.shape)


def searchsorted(sorted_sequence, values, out_int32=False, right=False, name=None):
    out = apply_op(_searchsorted, sorted_sequence, values, right=bool(right))
    return out.astype("int32") if out_int32 else out


def _kthvalue(x, k, axis=-1, keepdim=False):
    axis = axis % x.ndim
    xm = jnp.moveaxis(x, axis, -1)
    vals = jnp.sort(xm, axis=-1)[..., k - 1]
    idx = jnp.argsort(xm, axis=-1)[..., k - 1]
    if keepdim:
        vals = jnp.expand_dims(vals, axis)
        idx = jnp.expand_dims(idx, axis)
    return vals, idx.astype(jnp.int64)


def kthvalue(x, k, axis=-1, keepdim=False, name=None):
    return apply_op(_kthvalue, x, k=int(k), axis=int(axis), keepdim=bool(keepdim))


def mode(x, axis=-1, keepdim=False, name=None):
    xa = np.asarray(x._data if isinstance(x, Tensor) else x)
    axis_ = axis % xa.ndim
    xm = np.moveaxis(xa, axis_, -1)
    flat = xm.reshape(-1, xm.shape[-1])
    vals = np.empty(flat.shape[0], dtype=xa.dtype)
    idxs = np.empty(flat.shape[0], dtype=np.int64)
    for i, row in enumerate(flat):
        uv, counts = np.unique(row, return_counts=True)
        v = uv[np.argmax(counts)]
        vals[i] = v
        idxs[i] = np.where(row == v)[0][-1]
    vals = vals.reshape(xm.shape[:-1])
    idxs = idxs.reshape(xm.shape[:-1])
    if keepdim:
        vals = np.expand_dims(vals, axis_)
        idxs = np.expand_dims(idxs, axis_)
    return Tensor(jnp.asarray(vals)), Tensor(jnp.asarray(idxs))
