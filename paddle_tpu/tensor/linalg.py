"""Linear algebra ops (reference python/paddle/tensor/linalg.py).

XLA lowers these to TPU-friendly primitives where available; decompositions
that XLA:TPU lacks fall back to CPU via jax automatically.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..framework.core import Tensor, apply_op
from .math import matmul, dot  # noqa: F401

__all__ = [
    "norm", "dist", "t", "cross", "cholesky", "qr", "svd", "inv", "det",
    "slogdet", "solve", "triangular_solve", "matrix_power", "pinv",
    "multi_dot", "eig", "eigh", "eigvals", "eigvalsh", "matrix_rank",
    "histogram", "bincount", "mv", "lu", "lstsq", "cov", "corrcoef",
    "inverse",
]


def _norm(x, p=2, axis=None, keepdim=False):
    if p == "fro" or (p == 2 and axis is None):
        return jnp.sqrt(jnp.sum(jnp.square(x), axis=axis, keepdims=keepdim))
    if p == np.inf:
        return jnp.max(jnp.abs(x), axis=axis, keepdims=keepdim)
    if p == -np.inf:
        return jnp.min(jnp.abs(x), axis=axis, keepdims=keepdim)
    if p == 0:
        return jnp.sum((x != 0).astype(x.dtype), axis=axis, keepdims=keepdim)
    return jnp.power(jnp.sum(jnp.power(jnp.abs(x), p), axis=axis, keepdims=keepdim), 1.0 / p)


def norm(x, p=2, axis=None, keepdim=False, name=None):
    if isinstance(axis, (list, tuple)):
        axis = tuple(int(a) for a in axis)
    elif axis is not None:
        axis = int(axis)
    if isinstance(p, str) and p != "fro":
        raise ValueError(f"unsupported norm p={p}")
    return apply_op(_norm, x, p=p if isinstance(p, str) else float(p) if p not in (np.inf, -np.inf) else p, axis=axis, keepdim=bool(keepdim))


def _dist(x, y, p=2):
    return _norm(x - y, p=p)


def dist(x, y, p=2, name=None):
    return apply_op(_dist, x, y, p=float(p) if p not in (np.inf, -np.inf) else p)


def _t(x):
    if x.ndim < 2:
        return x
    return x.T


def t(input, name=None):  # noqa: A002
    return apply_op(_t, input)


def _cross(x, y, axis=9):
    ax = axis if axis != 9 else None
    if ax is None:
        for i, s in enumerate(x.shape):
            if s == 3:
                ax = i
                break
    return jnp.cross(x, y, axis=ax)


def cross(x, y, axis=9, name=None):
    return apply_op(_cross, x, y, axis=int(axis))


def cholesky(x, upper=False, name=None):
    return apply_op(_chol_impl, x, upper=bool(upper))


def _chol_impl(a, upper=False):
    L = jnp.linalg.cholesky(a)
    return jnp.swapaxes(L, -1, -2) if upper else L


def _qr(x, mode="reduced"):
    return jnp.linalg.qr(x, mode=mode)


def qr(x, mode="reduced", name=None):
    out = apply_op(_qr, x, mode=mode)
    return out


def _svd(x, full_matrices=False):
    return jnp.linalg.svd(x, full_matrices=full_matrices)


def svd(x, full_matrices=False, name=None):
    return apply_op(_svd, x, full_matrices=bool(full_matrices))


def inv(x, name=None):
    return apply_op(jnp.linalg.inv, x)


def det(x, name=None):
    return apply_op(jnp.linalg.det, x)


def _slogdet(x):
    sign, logdet = jnp.linalg.slogdet(x)
    return jnp.stack([sign, logdet])


def slogdet(x, name=None):
    return apply_op(_slogdet, x)


def solve(x, y, name=None):
    return apply_op(jnp.linalg.solve, x, y)


def _triangular_solve(x, y, upper=True, transpose=False, unitriangular=False):
    if transpose:
        x = jnp.swapaxes(x, -1, -2)
        upper = not upper
    return jax.scipy.linalg.solve_triangular(x, y, lower=not upper, unit_diagonal=unitriangular)


def triangular_solve(x, y, upper=True, transpose=False, unitriangular=False, name=None):
    return apply_op(_triangular_solve, x, y, upper=bool(upper), transpose=bool(transpose), unitriangular=bool(unitriangular))


def _matrix_power(x, n):
    return jnp.linalg.matrix_power(x, n)


def matrix_power(x, n, name=None):
    return apply_op(_matrix_power, x, n=int(n))


def _pinv(x, rcond=1e-15, hermitian=False):
    return jnp.linalg.pinv(x, rtol=rcond, hermitian=hermitian)


def pinv(x, rcond=1e-15, hermitian=False, name=None):
    return apply_op(_pinv, x, rcond=float(rcond), hermitian=bool(hermitian))


def _multi_dot(*mats):
    out = mats[0]
    for m in mats[1:]:
        out = out @ m
    return out


def multi_dot(x, name=None):
    return apply_op(_multi_dot, *x)


def eig(x, name=None):
    xa = np.asarray(x._data if isinstance(x, Tensor) else x)
    w, v = np.linalg.eig(xa)
    return Tensor(jnp.asarray(w)), Tensor(jnp.asarray(v))


def eigvals(x, name=None):
    xa = np.asarray(x._data if isinstance(x, Tensor) else x)
    return Tensor(jnp.asarray(np.linalg.eigvals(xa)))


def _eigh(x, UPLO="L"):
    return jnp.linalg.eigh(x, UPLO=UPLO)


def eigh(x, UPLO="L", name=None):
    return apply_op(_eigh, x, UPLO=UPLO)


def _eigvalsh(x, UPLO="L"):
    return jnp.linalg.eigvalsh(x, UPLO=UPLO)


def eigvalsh(x, UPLO="L", name=None):
    return apply_op(_eigvalsh, x, UPLO=UPLO)


def matrix_rank(x, tol=None, hermitian=False, name=None):
    xa = x._data if isinstance(x, Tensor) else jnp.asarray(x)
    return Tensor(jnp.linalg.matrix_rank(xa, rtol=tol))


def histogram(input, bins=100, min=0, max=0, name=None):  # noqa: A002
    xa = np.asarray(input._data if isinstance(input, Tensor) else input)
    if min == 0 and max == 0:
        min, max = xa.min(), xa.max()  # noqa: A001
    h, _ = np.histogram(xa, bins=bins, range=(min, max))
    return Tensor(jnp.asarray(h.astype(np.int64)))


def bincount(x, weights=None, minlength=0, name=None):
    xa = x._data if isinstance(x, Tensor) else jnp.asarray(x)
    wa = weights._data if isinstance(weights, Tensor) else weights
    n = int(jnp.max(xa)) + 1 if xa.size else 0
    length = builtins_max(n, int(minlength))
    return Tensor(jnp.bincount(xa, weights=wa, length=length))


def builtins_max(a, b):
    return a if a > b else b


def mv(x, vec, name=None):
    return apply_op(jnp.matmul, x, vec)


def lu(x, pivot=True, get_infos=False, name=None):
    import jax.scipy.linalg as jsl

    xa = x._data if isinstance(x, Tensor) else jnp.asarray(x)
    lu_, piv = jsl.lu_factor(xa)
    if get_infos:
        return Tensor(lu_), Tensor(piv.astype(jnp.int32)), Tensor(jnp.zeros((), jnp.int32))
    return Tensor(lu_), Tensor(piv.astype(jnp.int32))


def lstsq(x, y, rcond=None, driver=None, name=None):
    xa = np.asarray(x._data if isinstance(x, Tensor) else x)
    ya = np.asarray(y._data if isinstance(y, Tensor) else y)
    sol, res, rank, sv = np.linalg.lstsq(xa, ya, rcond=rcond)
    return (Tensor(jnp.asarray(sol)), Tensor(jnp.asarray(res)), Tensor(jnp.asarray(rank)), Tensor(jnp.asarray(sv)))


def _cov(x, rowvar=True, ddof=True):
    return jnp.cov(x, rowvar=rowvar, ddof=1 if ddof else 0)


def cov(x, rowvar=True, ddof=True, fweights=None, aweights=None, name=None):
    return apply_op(_cov, x, rowvar=bool(rowvar), ddof=bool(ddof))


def corrcoef(x, rowvar=True, name=None):
    return apply_op(_corrcoef, x, rowvar=bool(rowvar))


def _corrcoef(x, rowvar=True):
    return jnp.corrcoef(x, rowvar=rowvar)


inverse = inv  # reference paddle.inverse (tensor/math.py) == linalg.inv
