"""Math ops (parity surface: reference python/paddle/tensor/math.py).

Every op is a thin wrapper over a module-level pure jnp function dispatched
through apply_op, so the eager path gets op-level jit caching and the tape
gets a jax.vjp closure.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..framework import dtype as dtypes
from ..framework.core import Tensor, apply_op

__all__ = [
    "add", "subtract", "multiply", "divide", "floor_divide", "remainder",
    "mod", "pow", "matmul", "mm", "bmm", "dot", "inner", "outer", "addmm",
    "maximum", "minimum", "fmax", "fmin", "exp", "expm1", "log", "log2",
    "log10", "log1p", "sqrt", "rsqrt", "square", "abs", "sign", "floor",
    "ceil", "round", "trunc", "sin", "cos", "tan", "asin", "acos", "atan",
    "atan2", "sinh", "cosh", "tanh", "asinh", "acosh", "atanh", "reciprocal",
    "sigmoid", "clip", "sum", "mean", "max", "min", "amax", "amin", "prod",
    "cumsum", "cumprod", "logsumexp", "logcumsumexp", "std", "var", "median",
    "kron", "isnan", "isinf", "isfinite", "nan_to_num", "erf", "erfinv",
    "lgamma", "digamma", "neg", "increment", "scale", "stanh", "multiplex",
    "all", "any", "deg2rad", "rad2deg", "angle", "conj", "real", "imag",
    "trace", "diff", "heaviside", "frac", "count_nonzero", "nansum",
    "nanmean", "gcd", "lcm", "lerp", "rot90", "add_n", "diagonal",
    "floor_mod", "tanh_",
]


def _w(x):
    """Wrap plain python/np scalars so binary ops accept them."""
    if isinstance(x, Tensor):
        return x
    return Tensor(jnp.asarray(x))


def _make_unary(jfn, name):
    def op(x, name=None):
        return apply_op(jfn, _w(x), op_name=name)

    op.__name__ = name
    op.__qualname__ = name
    return op


def _make_binary(jfn, name):
    def op(x, y, name=None):
        return apply_op(jfn, _w(x), _w(y), op_name=name)

    op.__name__ = name
    op.__qualname__ = name
    return op


exp = _make_unary(jnp.exp, "exp")
expm1 = _make_unary(jnp.expm1, "expm1")
log = _make_unary(jnp.log, "log")
log2 = _make_unary(jnp.log2, "log2")
log10 = _make_unary(jnp.log10, "log10")
log1p = _make_unary(jnp.log1p, "log1p")
sqrt = _make_unary(jnp.sqrt, "sqrt")
square = _make_unary(jnp.square, "square")
sign = _make_unary(jnp.sign, "sign")
floor = _make_unary(jnp.floor, "floor")
ceil = _make_unary(jnp.ceil, "ceil")
round = _make_unary(jnp.round, "round")  # noqa: A001
trunc = _make_unary(jnp.trunc, "trunc")
sin = _make_unary(jnp.sin, "sin")
cos = _make_unary(jnp.cos, "cos")
tan = _make_unary(jnp.tan, "tan")
asin = _make_unary(jnp.arcsin, "asin")
acos = _make_unary(jnp.arccos, "acos")
atan = _make_unary(jnp.arctan, "atan")
sinh = _make_unary(jnp.sinh, "sinh")
cosh = _make_unary(jnp.cosh, "cosh")
tanh = _make_unary(jnp.tanh, "tanh")
asinh = _make_unary(jnp.arcsinh, "asinh")
acosh = _make_unary(jnp.arccosh, "acosh")
atanh = _make_unary(jnp.arctanh, "atanh")
abs = _make_unary(jnp.abs, "abs")  # noqa: A001
neg = _make_unary(jnp.negative, "neg")
erf = _make_unary(jax.scipy.special.erf, "erf")
erfinv = _make_unary(jax.scipy.special.erfinv, "erfinv")
lgamma = _make_unary(jax.scipy.special.gammaln, "lgamma")
digamma = _make_unary(jax.scipy.special.digamma, "digamma")
isnan = _make_unary(jnp.isnan, "isnan")
isinf = _make_unary(jnp.isinf, "isinf")
isfinite = _make_unary(jnp.isfinite, "isfinite")
deg2rad = _make_unary(jnp.deg2rad, "deg2rad")
rad2deg = _make_unary(jnp.rad2deg, "rad2deg")
angle = _make_unary(jnp.angle, "angle")
conj = _make_unary(jnp.conj, "conj")
real = _make_unary(jnp.real, "real")
imag = _make_unary(jnp.imag, "imag")
frac = _make_unary(lambda x: x - jnp.trunc(x), "frac")

add = _make_binary(jnp.add, "add")
subtract = _make_binary(jnp.subtract, "subtract")
multiply = _make_binary(jnp.multiply, "multiply")
divide = _make_binary(jnp.divide, "divide")
floor_divide = _make_binary(jnp.floor_divide, "floor_divide")
remainder = _make_binary(jnp.remainder, "remainder")
mod = remainder
maximum = _make_binary(jnp.maximum, "maximum")
minimum = _make_binary(jnp.minimum, "minimum")
fmax = _make_binary(jnp.fmax, "fmax")
fmin = _make_binary(jnp.fmin, "fmin")
atan2 = _make_binary(jnp.arctan2, "atan2")
kron = _make_binary(jnp.kron, "kron")
heaviside = _make_binary(jnp.heaviside, "heaviside")
gcd = _make_binary(jnp.gcd, "gcd")
lcm = _make_binary(jnp.lcm, "lcm")
inner = _make_binary(jnp.inner, "inner")
outer = _make_binary(jnp.outer, "outer")
dot = _make_binary(jnp.dot, "dot")


def _rsqrt(x):
    return jax.lax.rsqrt(x)


rsqrt = _make_unary(_rsqrt, "rsqrt")


def _reciprocal(x):
    return 1.0 / x


reciprocal = _make_unary(_reciprocal, "reciprocal")
sigmoid = _make_unary(jax.nn.sigmoid, "sigmoid")


def _pow(x, y):
    return jnp.power(x, y)


def pow(x, y, name=None):  # noqa: A001
    return apply_op(_pow, _w(x), _w(y))


def _matmul(x, y, transpose_x=False, transpose_y=False):
    if transpose_x:
        axes = list(range(x.ndim))
        axes[-1], axes[-2] = axes[-2], axes[-1]
        x = jnp.transpose(x, axes)
    if transpose_y:
        axes = list(range(y.ndim))
        axes[-1], axes[-2] = axes[-2], axes[-1]
        y = jnp.transpose(y, axes)
    return jnp.matmul(x, y)


def matmul(x, y, transpose_x=False, transpose_y=False, name=None):
    from ..amp import maybe_autocast
    from ..framework.enforce import InvalidArgumentError

    if getattr(x, "ndim", 0) >= 2 and getattr(y, "ndim", 0) >= 2:
        k1 = x.shape[-1] if not transpose_x else x.shape[-2]
        k2 = y.shape[-2] if not transpose_y else y.shape[-1]
        if int(k1) != int(k2):
            raise InvalidArgumentError(
                f"Input shapes of matmul are incompatible: "
                f"x {list(x.shape)} (transpose_x={bool(transpose_x)}) and "
                f"y {list(y.shape)} (transpose_y={bool(transpose_y)}) — "
                f"contracted dims {int(k1)} vs {int(k2)}.")
    x, y = maybe_autocast(x, y)
    return apply_op(_matmul, x, y, transpose_x=bool(transpose_x), transpose_y=bool(transpose_y))


mm = matmul


def bmm(x, y, name=None):
    from ..amp import maybe_autocast

    x, y = maybe_autocast(x, y)
    return apply_op(jnp.matmul, x, y)


def _addmm(input, x, y, beta=1.0, alpha=1.0):  # noqa: A002
    return beta * input + alpha * jnp.matmul(x, y)


def addmm(input, x, y, beta=1.0, alpha=1.0, name=None):  # noqa: A002
    return apply_op(_addmm, input, x, y, beta=float(beta), alpha=float(alpha))


def _clip(x, min=None, max=None):  # noqa: A002
    return jnp.clip(x, min, max)


def clip(x, min=None, max=None, name=None):  # noqa: A002
    if isinstance(min, Tensor):
        min = min.item()  # noqa: A001
    if isinstance(max, Tensor):
        max = max.item()  # noqa: A001
    return apply_op(_clip, x, min=min, max=max)


def _axis(axis):
    if axis is None:
        return None
    if isinstance(axis, Tensor):
        axis = axis.numpy().tolist()
    if isinstance(axis, (list, tuple)):
        return tuple(int(a) for a in axis)
    return int(axis)


def _sum(x, axis=None, keepdim=False, dtype=None):
    return jnp.sum(x, axis=axis, keepdims=keepdim, dtype=dtype)


def sum(x, axis=None, dtype=None, keepdim=False, name=None):  # noqa: A001
    return apply_op(_sum, x, axis=_axis(axis), keepdim=bool(keepdim), dtype=dtypes.convert_dtype(dtype))


def _nansum(x, axis=None, keepdim=False):
    return jnp.nansum(x, axis=axis, keepdims=keepdim)


def nansum(x, axis=None, dtype=None, keepdim=False, name=None):
    return apply_op(_nansum, x, axis=_axis(axis), keepdim=bool(keepdim))


def _nanmean(x, axis=None, keepdim=False):
    return jnp.nanmean(x, axis=axis, keepdims=keepdim)


def nanmean(x, axis=None, keepdim=False, name=None):
    return apply_op(_nanmean, x, axis=_axis(axis), keepdim=bool(keepdim))


def _mean(x, axis=None, keepdim=False):
    return jnp.mean(x, axis=axis, keepdims=keepdim)


def mean(x, axis=None, keepdim=False, name=None):
    return apply_op(_mean, x, axis=_axis(axis), keepdim=bool(keepdim))


def _max(x, axis=None, keepdim=False):
    return jnp.max(x, axis=axis, keepdims=keepdim)


def max(x, axis=None, keepdim=False, name=None):  # noqa: A001
    return apply_op(_max, x, axis=_axis(axis), keepdim=bool(keepdim))


def _min(x, axis=None, keepdim=False):
    return jnp.min(x, axis=axis, keepdims=keepdim)


def min(x, axis=None, keepdim=False, name=None):  # noqa: A001
    return apply_op(_min, x, axis=_axis(axis), keepdim=bool(keepdim))


amax = max
amin = min


def _prod(x, axis=None, keepdim=False, dtype=None):
    return jnp.prod(x, axis=axis, keepdims=keepdim, dtype=dtype)


def prod(x, axis=None, keepdim=False, dtype=None, name=None):
    return apply_op(_prod, x, axis=_axis(axis), keepdim=bool(keepdim), dtype=dtypes.convert_dtype(dtype))


def _cumsum(x, axis=None, dtype=None):
    if axis is None:
        x = x.reshape(-1)
        axis = 0
    return jnp.cumsum(x, axis=axis, dtype=dtype)


def cumsum(x, axis=None, dtype=None, name=None):
    return apply_op(_cumsum, x, axis=_axis(axis), dtype=dtypes.convert_dtype(dtype))


def _cumprod(x, dim=None, dtype=None):
    return jnp.cumprod(x, axis=dim, dtype=dtype)


def cumprod(x, dim=None, dtype=None, name=None):
    return apply_op(_cumprod, x, dim=_axis(dim), dtype=dtypes.convert_dtype(dtype))


def _logsumexp(x, axis=None, keepdim=False):
    return jax.scipy.special.logsumexp(x, axis=axis, keepdims=keepdim)


def logsumexp(x, axis=None, keepdim=False, name=None):
    return apply_op(_logsumexp, x, axis=_axis(axis), keepdim=bool(keepdim))


def _logcumsumexp(x, axis=None):
    if axis is None:
        x = x.reshape(-1)
        axis = 0
    # subtract the GLOBAL max along the axis: a running (cummax) shift is
    # inconsistent across the cumsum — exp(x_i - m_j) terms with different
    # m_j cannot be summed directly (caught by the op-output sweep)
    m = jnp.max(x, axis=axis, keepdims=True)
    return jnp.log(jnp.cumsum(jnp.exp(x - m), axis=axis)) + m


def logcumsumexp(x, axis=None, name=None):
    return apply_op(_logcumsumexp, x, axis=_axis(axis))


def _std(x, axis=None, unbiased=True, keepdim=False):
    return jnp.std(x, axis=axis, ddof=1 if unbiased else 0, keepdims=keepdim)


def std(x, axis=None, unbiased=True, keepdim=False, name=None):
    return apply_op(_std, x, axis=_axis(axis), unbiased=bool(unbiased), keepdim=bool(keepdim))


def _var(x, axis=None, unbiased=True, keepdim=False):
    return jnp.var(x, axis=axis, ddof=1 if unbiased else 0, keepdims=keepdim)


def var(x, axis=None, unbiased=True, keepdim=False, name=None):
    return apply_op(_var, x, axis=_axis(axis), unbiased=bool(unbiased), keepdim=bool(keepdim))


def _median(x, axis=None, keepdim=False):
    return jnp.median(x, axis=axis, keepdims=keepdim)


def median(x, axis=None, keepdim=False, name=None):
    return apply_op(_median, x, axis=_axis(axis), keepdim=bool(keepdim))


def _nan_to_num(x, nan=0.0, posinf=None, neginf=None):
    return jnp.nan_to_num(x, nan=nan, posinf=posinf, neginf=neginf)


def nan_to_num(x, nan=0.0, posinf=None, neginf=None, name=None):
    return apply_op(_nan_to_num, x, nan=float(nan), posinf=posinf, neginf=neginf)


def increment(x, value=1.0, name=None):
    out = apply_op(jnp.add, x, Tensor(jnp.asarray(value, x.dtype)))
    x._data = out._data
    x._grad_node = out._grad_node
    x._out_index = out._out_index
    return x


def _scale(x, scale=1.0, bias=0.0, bias_after_scale=True):
    if bias_after_scale:
        return x * scale + bias
    return (x + bias) * scale


def scale(x, scale=1.0, bias=0.0, bias_after_scale=True, act=None, name=None):  # noqa: A002
    if isinstance(scale, Tensor):
        scale = scale.item()  # noqa: A001
    out = apply_op(_scale, x, scale=float(scale), bias=float(bias), bias_after_scale=bool(bias_after_scale))
    if act is not None:
        from ..nn import functional as F

        out = getattr(F, act)(out)
    return out


def _stanh(x, scale_a=0.67, scale_b=1.7159):
    return scale_b * jnp.tanh(scale_a * x)


def stanh(x, scale_a=0.67, scale_b=1.7159, name=None):
    return apply_op(_stanh, x, scale_a=float(scale_a), scale_b=float(scale_b))


def _multiplex(*args):
    index, cands = args[-1], jnp.stack(args[:-1])
    index = index.reshape(-1)
    return cands[index, jnp.arange(index.shape[0])]


def multiplex(inputs, index, name=None):
    idx = index if isinstance(index, Tensor) else Tensor(jnp.asarray(index))
    return apply_op(_multiplex, *inputs, idx)


def _all(x, axis=None, keepdim=False):
    return jnp.all(x, axis=axis, keepdims=keepdim)


def all(x, axis=None, keepdim=False, name=None):  # noqa: A001
    return apply_op(_all, x, axis=_axis(axis), keepdim=bool(keepdim))


def _any(x, axis=None, keepdim=False):
    return jnp.any(x, axis=axis, keepdims=keepdim)


def any(x, axis=None, keepdim=False, name=None):  # noqa: A001
    return apply_op(_any, x, axis=_axis(axis), keepdim=bool(keepdim))


def _trace(x, offset=0, axis1=0, axis2=1):
    return jnp.trace(x, offset=offset, axis1=axis1, axis2=axis2)


def trace(x, offset=0, axis1=0, axis2=1, name=None):
    return apply_op(_trace, x, offset=int(offset), axis1=int(axis1), axis2=int(axis2))


def _diff(x, n=1, axis=-1):
    return jnp.diff(x, n=n, axis=axis)


def diff(x, n=1, axis=-1, prepend=None, append=None, name=None):
    if prepend is not None or append is not None:
        parts = []
        if prepend is not None:
            parts.append(prepend)
        parts.append(x)
        if append is not None:
            parts.append(append)
        from .manipulation import concat

        x = concat(parts, axis=axis)
    return apply_op(_diff, x, n=int(n), axis=int(axis))


def _count_nonzero(x, axis=None, keepdim=False):
    return jnp.count_nonzero(x, axis=axis, keepdims=keepdim)


def count_nonzero(x, axis=None, keepdim=False, name=None):
    return apply_op(_count_nonzero, x, axis=_axis(axis), keepdim=bool(keepdim))


def _lerp(x, y, weight):
    return x + weight * (y - x)


def lerp(x, y, weight, name=None):
    if not isinstance(weight, Tensor):
        weight = Tensor(jnp.asarray(weight, dtype=(x.dtype if isinstance(x, Tensor) else None)))
    return apply_op(_lerp, _w(x), _w(y), weight)


def _rot90(x, k=1, axes=(0, 1)):
    return jnp.rot90(x, k=k, axes=axes)


def rot90(x, k=1, axes=(0, 1), name=None):
    return apply_op(_rot90, x, k=int(k), axes=tuple(axes))


def _add_n_impl(*xs):
    out = xs[0]
    for a in xs[1:]:
        out = out + a
    return out


def add_n(inputs, name=None):
    """Elementwise sum of a list of tensors (reference sum_op / paddle.add_n).

    A single Tensor still goes through apply_op so the result is a fresh
    Tensor, never an alias of the input (inplace ops on the result must not
    mutate the input)."""
    if isinstance(inputs, Tensor):
        inputs = [inputs]
    if not inputs:
        raise ValueError("add_n expects at least one input tensor")
    return apply_op(_add_n_impl, *[_w(x) for x in inputs], op_name="add_n")


def _diagonal_impl(x, offset=0, axis1=0, axis2=1):
    return jnp.diagonal(x, offset=offset, axis1=axis1, axis2=axis2)


def diagonal(x, offset=0, axis1=0, axis2=1, name=None):
    return apply_op(_diagonal_impl, _w(x), offset=int(offset),
                    axis1=int(axis1), axis2=int(axis2), op_name="diagonal")


floor_mod = remainder  # reference alias (paddle.floor_mod == paddle.remainder)


def tanh_(x, name=None):
    from ..framework.core import inplace_apply

    return inplace_apply(x, tanh)
