"""Random sampling ops over the global stateful PRNG.

Reference surface: python/paddle/tensor/random.py; seeding semantics from
framework/generator.cc (see paddle_tpu.framework.random).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..framework import dtype as dtypes
from ..framework import random as grandom
from ..framework.core import Tensor

__all__ = [
    "rand", "randn", "randint", "randint_like", "randperm", "uniform",
    "normal", "standard_normal", "multinomial", "bernoulli", "poisson",
    "uniform_", "normal_", "exponential_",
]


def _shape(shape):
    if isinstance(shape, Tensor):
        shape = shape.numpy().tolist()
    if isinstance(shape, (int, np.integer)):
        return (int(shape),)
    return tuple(int(s.item()) if isinstance(s, Tensor) else int(s) for s in shape)


def _dt(dtype):
    d = dtypes.convert_dtype(dtype)
    return d if d is not None else dtypes.default_float_dtype()


def rand(shape, dtype=None, name=None):
    return Tensor(jax.random.uniform(grandom.next_key(), _shape(shape), dtype=_dt(dtype)))


def randn(shape, dtype=None, name=None):
    return Tensor(jax.random.normal(grandom.next_key(), _shape(shape), dtype=_dt(dtype)))


standard_normal = randn


def randint(low=0, high=None, shape=(1,), dtype="int64", name=None):
    if high is None:
        low, high = 0, low
    d = dtypes.convert_dtype(dtype)
    return Tensor(jax.random.randint(grandom.next_key(), _shape(shape), int(low), int(high), dtype=d))


def randint_like(x, low=0, high=None, dtype=None, name=None):
    return randint(low, high, x.shape, dtype or "int64")


def randperm(n, dtype="int64", name=None):
    return Tensor(jax.random.permutation(grandom.next_key(), int(n)).astype(dtypes.convert_dtype(dtype)))


def uniform(shape, dtype=None, min=-1.0, max=1.0, seed=0, name=None):  # noqa: A002
    key = jax.random.key(seed) if seed else grandom.next_key()
    return Tensor(jax.random.uniform(key, _shape(shape), dtype=_dt(dtype), minval=float(min), maxval=float(max)))


def normal(mean=0.0, std=1.0, shape=None, name=None):
    if isinstance(mean, Tensor) or isinstance(std, Tensor):
        m = mean._data if isinstance(mean, Tensor) else mean
        s = std._data if isinstance(std, Tensor) else std
        shp = jnp.broadcast_shapes(jnp.shape(m), jnp.shape(s))
        return Tensor(jax.random.normal(grandom.next_key(), shp) * s + m)
    return Tensor(jax.random.normal(grandom.next_key(), _shape(shape)) * std + mean)


def multinomial(x, num_samples=1, replacement=False, name=None):
    xa = x._data if isinstance(x, Tensor) else jnp.asarray(x)
    logits = jnp.log(jnp.maximum(xa, 1e-30))
    key = grandom.next_key()
    if replacement:
        out = jax.random.categorical(key, logits, axis=-1, shape=(
            (num_samples,) + xa.shape[:-1] if xa.ndim > 1 else (num_samples,)
        ))
        out = jnp.moveaxis(out, 0, -1) if xa.ndim > 1 else out
    else:
        # Gumbel top-k trick for sampling without replacement
        g = jax.random.gumbel(key, xa.shape)
        _, out = jax.lax.top_k(logits + g, num_samples)
    return Tensor(out.astype(jnp.int64))


def bernoulli(x, name=None):
    xa = x._data if isinstance(x, Tensor) else jnp.asarray(x)
    u = jax.random.uniform(grandom.next_key(), xa.shape)
    return Tensor((u < xa).astype(xa.dtype))


def poisson(x, name=None):
    xa = x._data if isinstance(x, Tensor) else jnp.asarray(x)
    return Tensor(jax.random.poisson(grandom.next_key(), xa).astype(xa.dtype))


# in-place variants used by initializers
def uniform_(x, min=-1.0, max=1.0, name=None):  # noqa: A002
    x._data = jax.random.uniform(grandom.next_key(), tuple(x._data.shape), dtype=x._data.dtype, minval=min, maxval=max)
    return x


def normal_(x, mean=0.0, std=1.0, name=None):
    x._data = jax.random.normal(grandom.next_key(), tuple(x._data.shape), dtype=x._data.dtype) * std + mean
    return x


def exponential_(x, lam=1.0, name=None):
    u = jax.random.uniform(grandom.next_key(), tuple(x._data.shape), dtype=x._data.dtype)
    x._data = -jnp.log(1.0 - u) / lam
    return x
