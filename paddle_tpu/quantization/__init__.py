"""Quantization: QAT (fake-quant training) and PTQ (post-training).

Parity: reference slim quantization
(python/paddle/fluid/contrib/slim/quantization/ — ImperativeQuantAware
:imperative/qat.py wraps Linear/Conv2D with fake-quant layers;
PostTrainingQuantization calibrates abs-max ranges; QuantizationTransformPass
rewrites static programs).

TPU-native redesign:
- fake_quant is a jax custom-vjp op (straight-through estimator) — one
  registration serves eager, to_static and the compiled train step; the
  reference needed separate fake_quantize_* CUDA ops + grad ops.
- int8 inference is REAL int8: v5e's MXU runs int8 at 2x the bf16 rate
  (394 vs 197 TOPS), so ``quantized_linear`` lowers to an int8 dot with
  int32 accumulation and per-channel rescale — the analog of the
  reference's cuDNN int8 conv path.
- on TPU that dot runs through the Pallas fused int8 kernel
  (``ops/int8_matmul.py``): the per-channel dequant and bias add execute
  in the kernel epilogue, so the int32 accumulator never round-trips
  HBM. Off-TPU the identical XLA math runs. The serving engine's
  weight-only int8 decode (``serving.InferenceEngine(int8_weights=True)``
  over ``models.gpt.quantize_gpt_weights``) is the first consumer.
"""
from __future__ import annotations

import functools
from typing import Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..framework.core import Tensor, apply_op
from ..nn.layer.layers import Layer

__all__ = [
    "fake_quant", "quant_absmax_scale", "quantize_weight",
    "quantized_linear", "quantize_weight_fp8", "fp8_quantized_linear",
    "QuantizedLinear", "ImperativeQuantAware",
    "PostTrainingQuantization",
]


# -- fake quant (QAT) -------------------------------------------------------

@functools.partial(jax.custom_vjp, nondiff_argnums=(2,))
def _fake_quant(x, scale, bits):
    qmax = 2.0 ** (bits - 1) - 1
    s = jnp.maximum(scale, 1e-8)
    q = jnp.clip(jnp.round(x / s * qmax), -qmax, qmax)
    return q * s / qmax


def _fq_fwd(x, scale, bits):
    return _fake_quant(x, scale, bits), (x, scale)


def _fq_bwd(bits, res, g):
    # straight-through estimator: pass grads inside the clip range
    x, scale = res
    s = jnp.maximum(scale, 1e-8)
    inside = (jnp.abs(x) <= s).astype(g.dtype)
    return g * inside, jnp.zeros_like(scale)


_fake_quant.defvjp(_fq_fwd, _fq_bwd)


def fake_quant(x, scale, bits=8):
    """Quantize-dequantize with STE gradients (reference
    fake_quantize_dequantize_moving_average_abs_max op)."""
    return apply_op(lambda a, s: _fake_quant(a, s, bits), x,
                    scale if isinstance(scale, Tensor) else Tensor(jnp.asarray(scale, jnp.float32)))


def quant_absmax_scale(w, per_channel_axis: Optional[int] = None):
    """abs-max scale; per-channel along the given axis when set."""
    arr = w._data if isinstance(w, Tensor) else jnp.asarray(w)
    if per_channel_axis is None:
        return jnp.max(jnp.abs(arr))
    axes = tuple(i for i in range(arr.ndim) if i != per_channel_axis)
    return jnp.max(jnp.abs(arr), axis=axes)


# -- real int8 (PTQ inference) ---------------------------------------------

def quantize_weight(w, bits=8, per_channel_axis=1):
    """fp weight → (int8 weight, fp32 per-channel scale)."""
    arr = w._data if isinstance(w, Tensor) else jnp.asarray(w)
    qmax = 2.0 ** (bits - 1) - 1
    scale = quant_absmax_scale(arr, per_channel_axis)
    s = jnp.maximum(scale, 1e-8)
    shape = [1] * arr.ndim
    if per_channel_axis is not None:
        shape[per_channel_axis] = -1
    q = jnp.clip(jnp.round(arr / s.reshape(shape) * qmax), -qmax, qmax)
    return q.astype(jnp.int8), (s / qmax).astype(jnp.float32)


def _int8_linear(x, wq, wscale, xscale, bias):
    # quantize activation with the calibrated scale, int8 matmul with
    # int32 accumulation (MXU int8 path), dequantize with the product of
    # scales (wscale broadcasts over the trailing out-features dim). On
    # TPU the dot + per-channel dequant + bias run as one Pallas kernel
    # (ops/int8_matmul.py, dequant fused into the epilogue); elsewhere
    # the identical XLA dot_general math.
    from ..ops.int8_matmul import int8_matmul_arrays

    xq = jnp.clip(jnp.round(x / xscale), -127, 127).astype(jnp.int8)
    return int8_matmul_arrays(xq, wq, wscale, xscale, bias=bias,
                              out_dtype=x.dtype)


def quantized_linear(x, wq, wscale, xscale, bias=None):
    """y = dequant(int8(x) @ int8 W) — real int8 on the MXU."""
    args = (x, wq, wscale, xscale) + ((bias,) if bias is not None else ())
    if bias is not None:
        return apply_op(lambda a, w, ws, xs, b: _int8_linear(a, w, ws, xs, b),
                        *args)
    return apply_op(lambda a, w, ws, xs: _int8_linear(a, w, ws, xs, None),
                    *args)


# -- real fp8 (e4m3 weight storage, ISSUE 17) -------------------------------

def quantize_weight_fp8(w):
    """fp weight → (e4m3 weight, f32 per-tensor scale). The fp8 analog
    of :func:`quantize_weight`; dequant is ``wq.astype(f) * scale``."""
    from ..amp.fp8 import E4M3_MAX, quantize_fp8

    arr = w._data if isinstance(w, Tensor) else jnp.asarray(w)
    scale = jnp.maximum(jnp.max(jnp.abs(arr.astype(jnp.float32))),
                        1e-12) / E4M3_MAX
    return quantize_fp8(arr, scale), scale.astype(jnp.float32)


def _fp8_linear(x, wq, wscale, bias):
    # dynamic per-tensor activation scaling, then the fused-dequant fp8
    # kernel (ops/fp8_matmul.py) — same routing contract as _int8_linear.
    from ..amp.fp8 import E4M3_MAX, quantize_fp8
    from ..ops.fp8_matmul import fp8_matmul_arrays

    xscale = jnp.maximum(jnp.max(jnp.abs(x.astype(jnp.float32))),
                         1e-12) / E4M3_MAX
    xq = quantize_fp8(x, xscale)
    return fp8_matmul_arrays(xq, wq, xscale, wscale, bias=bias,
                             out_dtype=x.dtype)


def fp8_quantized_linear(x, wq, wscale, bias=None):
    """y = dequant(e4m3(x) @ e4m3 W) — fp8 storage, bf16-exact dot."""
    args = (x, wq, wscale) + ((bias,) if bias is not None else ())
    if bias is not None:
        return apply_op(lambda a, w, ws, b: _fp8_linear(a, w, ws, b), *args)
    return apply_op(lambda a, w, ws: _fp8_linear(a, w, ws, None), *args)


# -- QAT layer wrappers -----------------------------------------------------

class QuantizedLinear(Layer):
    """Linear with fake-quantized weight + activation (reference
    imperative/qat.py QuantizedLinear). Weight scale: per-channel abs-max,
    recomputed per step; activation scale: moving-average abs-max buffer."""

    def __init__(self, layer, weight_bits=8, activation_bits=8,
                 moving_rate=0.9):
        super().__init__()
        self.weight = layer.weight
        if getattr(layer, "bias", None) is not None:
            self.bias = layer.bias
        self._wbits = weight_bits
        self._abits = activation_bits
        self._rate = moving_rate
        self.register_buffer(
            "act_scale", Tensor(jnp.asarray(0.0, jnp.float32)))

    def forward(self, x):
        from ..nn import functional as NF

        cur = jnp.max(jnp.abs(x._data)).astype(jnp.float32)
        if self.training:
            # buffer mutation: under TrainStep/to_static/FleetEngine the
            # functional_call buffer threading captures this (the moving
            # average calibrates inside the compiled step); in eager it
            # updates in place. Either way no tracer leaks — functional_call
            # snapshots and restores all buffers.
            new = jnp.where(self.act_scale._data == 0.0, cur,
                            self._rate * self.act_scale._data
                            + (1 - self._rate) * cur)
            self.act_scale._data = jax.lax.stop_gradient(new)
        x = fake_quant(x, Tensor(jnp.maximum(self.act_scale._data, 1e-8)),
                       self._abits)
        wscale = quant_absmax_scale(self.weight, per_channel_axis=1)
        w = fake_quant(self.weight, Tensor(wscale[None, :]), self._wbits)
        return NF.linear(x, w, getattr(self, "bias", None))


_QUANTIZABLE = {"Linear": QuantizedLinear}


class ImperativeQuantAware:
    """Dygraph QAT driver (reference imperative/qat.py ImperativeQuantAware):
    ``quantize(model)`` swaps quantizable sublayers in place."""

    def __init__(self, quantizable_layer_type=("Linear",),
                 weight_bits=8, activation_bits=8, moving_rate=0.9,
                 weight_quantize_type="channel_wise_abs_max",
                 activation_quantize_type="moving_average_abs_max"):
        self._types = tuple(quantizable_layer_type)
        self._wbits = weight_bits
        self._abits = activation_bits
        self._rate = moving_rate

    def quantize(self, model: Layer) -> Layer:
        for parent in model.sublayers(include_self=True):
            for name, child in list(parent._sub_layers.items()):
                tn = type(child).__name__
                if tn in self._types and tn in _QUANTIZABLE:
                    parent._sub_layers[name] = _QUANTIZABLE[tn](
                        child, self._wbits, self._abits, self._rate)
        return model


# -- PTQ --------------------------------------------------------------------

class PostTrainingQuantization:
    """Post-training quantization (reference slim
    PostTrainingQuantization, simplified to the dygraph path):

        ptq = PostTrainingQuantization(model)
        for batch in calib_loader: ptq.collect(batch)   # abs-max ranges
        qmodel = ptq.convert()                          # int8 weights

    ``convert`` replaces Linear layers with frozen int8 layers running
    :func:`quantized_linear`.
    """

    def __init__(self, model: Layer, quantizable_layer_type=("Linear",)):
        self._model = model
        self._types = tuple(quantizable_layer_type)
        self._ranges: Dict[int, float] = {}
        self._hooks = []
        for layer in model.sublayers(include_self=True):
            if type(layer).__name__ in self._types:
                self._hooks.append(layer.register_forward_pre_hook(
                    self._make_hook(layer)))

    def _make_hook(self, layer):
        def hook(lyr, inputs):
            x = inputs[0]
            cur = float(jnp.max(jnp.abs(x._data)))
            self._ranges[id(lyr)] = max(self._ranges.get(id(lyr), 0.0), cur)

        return hook

    def collect(self, *inputs):
        self._model.eval()
        return self._model(*inputs)

    def convert(self) -> Layer:
        for h in self._hooks:
            h.remove()
        for parent in self._model.sublayers(include_self=True):
            for name, child in list(parent._sub_layers.items()):
                if type(child).__name__ in self._types and \
                        id(child) in self._ranges:
                    parent._sub_layers[name] = _FrozenInt8Linear(
                        child, self._ranges[id(child)])
        return self._model


class _FrozenInt8Linear(Layer):
    def __init__(self, layer, act_absmax):
        super().__init__()
        wq, wscale = quantize_weight(layer.weight, per_channel_axis=1)
        self.register_buffer("wq", Tensor(wq))
        self.register_buffer("wscale", Tensor(wscale))
        self.register_buffer(
            "xscale", Tensor(jnp.asarray(max(act_absmax, 1e-8) / 127.0,
                                         jnp.float32)))
        # keep the bias as a registered parameter so state_dict/save carry it
        if getattr(layer, "bias", None) is not None:
            self.bias = layer.bias

    def forward(self, x):
        return quantized_linear(x, self.wq, self.wscale, self.xscale,
                                getattr(self, "bias", None))
