"""Vision transforms (reference python/paddle/vision/transforms/).

Numpy/host-side preprocessing — the DataLoader applies these before batches
hit the device. HWC uint8 numpy in, CHW float out (paddle convention via
ToTensor).
"""
from __future__ import annotations

import numbers

import numpy as np

from ..framework.core import Tensor

__all__ = [
    "Compose", "ToTensor", "Resize", "Normalize", "CenterCrop", "RandomCrop",
    "RandomHorizontalFlip", "RandomVerticalFlip", "Transpose", "Pad",
    "RandomResizedCrop", "BrightnessTransform", "ContrastTransform",
    "SaturationTransform", "HueTransform", "ColorJitter", "Grayscale",
    "to_tensor", "normalize", "resize", "hflip", "vflip", "center_crop", "crop",
    "RandomRotation", "adjust_brightness", "adjust_contrast", "adjust_hue",
    "pad", "rotate", "to_grayscale",
]


def _as_hwc(img):
    img = np.asarray(img)
    if img.ndim == 2:
        img = img[:, :, None]
    return img


def resize(img, size, interpolation="bilinear"):
    img = _as_hwc(img)
    h, w = img.shape[:2]
    if isinstance(size, int):
        if h < w:
            nh, nw = size, int(size * w / h)
        else:
            nh, nw = int(size * h / w), size
    else:
        nh, nw = size
    if (nh, nw) == (h, w):
        return img
    # bilinear resize in numpy (host-side; device path uses jax.image)
    ys = np.linspace(0, h - 1, nh)
    xs = np.linspace(0, w - 1, nw)
    if interpolation == "nearest":
        out = img[np.round(ys).astype(int)[:, None], np.round(xs).astype(int)[None, :]]
        return out
    y0 = np.floor(ys).astype(int)
    x0 = np.floor(xs).astype(int)
    y1 = np.minimum(y0 + 1, h - 1)
    x1 = np.minimum(x0 + 1, w - 1)
    wy = (ys - y0)[:, None, None]
    wx = (xs - x0)[None, :, None]
    f = img.astype(np.float32)
    out = (f[y0][:, x0] * (1 - wy) * (1 - wx) + f[y1][:, x0] * wy * (1 - wx)
           + f[y0][:, x1] * (1 - wy) * wx + f[y1][:, x1] * wy * wx)
    if img.dtype == np.uint8:
        out = np.clip(out, 0, 255).astype(np.uint8)
    return out


def crop(img, top, left, height, width):
    return _as_hwc(img)[top:top + height, left:left + width]


def center_crop(img, output_size):
    img = _as_hwc(img)
    if isinstance(output_size, numbers.Number):
        output_size = (int(output_size), int(output_size))
    h, w = img.shape[:2]
    th, tw = output_size
    i = int(round((h - th) / 2.0))
    j = int(round((w - tw) / 2.0))
    return crop(img, i, j, th, tw)


def hflip(img):
    return _as_hwc(img)[:, ::-1]


def vflip(img):
    return _as_hwc(img)[::-1]


def to_tensor(pic, data_format="CHW"):
    img = _as_hwc(pic).astype(np.float32)
    if img.dtype == np.float32 and np.asarray(pic).dtype == np.uint8:
        img = img / 255.0
    elif np.asarray(pic).dtype == np.uint8:
        img = img / 255.0
    if data_format == "CHW":
        img = img.transpose(2, 0, 1)
    return Tensor(img)


def normalize(img, mean, std, data_format="CHW", to_rgb=False):
    if isinstance(img, Tensor):
        arr = np.asarray(img._data)
    else:
        arr = np.asarray(img, dtype=np.float32)
    mean = np.asarray(mean, dtype=np.float32)
    std = np.asarray(std, dtype=np.float32)
    if data_format == "CHW":
        arr = (arr - mean[:, None, None]) / std[:, None, None]
    else:
        arr = (arr - mean) / std
    return Tensor(arr) if isinstance(img, Tensor) else arr


class BaseTransform:
    def __call__(self, img):
        return self._apply_image(img)


class Compose:
    def __init__(self, transforms):
        self.transforms = transforms

    def __call__(self, data):
        for t in self.transforms:
            data = t(data)
        return data


class ToTensor(BaseTransform):
    def __init__(self, data_format="CHW", keys=None):
        self.data_format = data_format

    def _apply_image(self, img):
        return to_tensor(img, self.data_format)


class Resize(BaseTransform):
    def __init__(self, size, interpolation="bilinear", keys=None):
        self.size = size
        self.interpolation = interpolation

    def _apply_image(self, img):
        return resize(img, self.size, self.interpolation)


class Normalize(BaseTransform):
    def __init__(self, mean=0.0, std=1.0, data_format="CHW", to_rgb=False, keys=None):
        if isinstance(mean, numbers.Number):
            mean = [mean, mean, mean]
        if isinstance(std, numbers.Number):
            std = [std, std, std]
        self.mean, self.std = mean, std
        self.data_format = data_format

    def _apply_image(self, img):
        return normalize(img, self.mean, self.std, self.data_format)


class CenterCrop(BaseTransform):
    def __init__(self, size, keys=None):
        self.size = size

    def _apply_image(self, img):
        return center_crop(img, self.size)


class RandomCrop(BaseTransform):
    def __init__(self, size, padding=None, pad_if_needed=False, fill=0,
                 padding_mode="constant", keys=None):
        if isinstance(size, numbers.Number):
            size = (int(size), int(size))
        self.size = size
        self.padding = padding

    def _apply_image(self, img):
        img = _as_hwc(img)
        if self.padding:
            p = self.padding if not isinstance(self.padding, numbers.Number) else [self.padding] * 4
            img = np.pad(img, ((p[1], p[3]), (p[0], p[2]), (0, 0)))
        h, w = img.shape[:2]
        th, tw = self.size
        if h == th and w == tw:
            return img
        i = np.random.randint(0, h - th + 1)
        j = np.random.randint(0, w - tw + 1)
        return crop(img, i, j, th, tw)


class RandomResizedCrop(BaseTransform):
    def __init__(self, size, scale=(0.08, 1.0), ratio=(3.0 / 4, 4.0 / 3),
                 interpolation="bilinear", keys=None):
        if isinstance(size, numbers.Number):
            size = (int(size), int(size))
        self.size = size
        self.scale = scale
        self.ratio = ratio
        self.interpolation = interpolation

    def _apply_image(self, img):
        img = _as_hwc(img)
        h, w = img.shape[:2]
        area = h * w
        for _ in range(10):
            target_area = np.random.uniform(*self.scale) * area
            ar = np.exp(np.random.uniform(np.log(self.ratio[0]), np.log(self.ratio[1])))
            nw = int(round(np.sqrt(target_area * ar)))
            nh = int(round(np.sqrt(target_area / ar)))
            if 0 < nw <= w and 0 < nh <= h:
                i = np.random.randint(0, h - nh + 1)
                j = np.random.randint(0, w - nw + 1)
                return resize(crop(img, i, j, nh, nw), self.size, self.interpolation)
        return resize(center_crop(img, min(h, w)), self.size, self.interpolation)


class RandomHorizontalFlip(BaseTransform):
    def __init__(self, prob=0.5, keys=None):
        self.prob = prob

    def _apply_image(self, img):
        if np.random.rand() < self.prob:
            return hflip(img)
        return img


class RandomVerticalFlip(BaseTransform):
    def __init__(self, prob=0.5, keys=None):
        self.prob = prob

    def _apply_image(self, img):
        if np.random.rand() < self.prob:
            return vflip(img)
        return img


class Transpose(BaseTransform):
    def __init__(self, order=(2, 0, 1), keys=None):
        self.order = order

    def _apply_image(self, img):
        return _as_hwc(img).transpose(self.order)


class Pad(BaseTransform):
    def __init__(self, padding, fill=0, padding_mode="constant", keys=None):
        if isinstance(padding, numbers.Number):
            padding = [padding] * 4
        elif len(padding) == 2:
            padding = [padding[0], padding[1], padding[0], padding[1]]
        self.padding = padding
        self.fill = fill

    def _apply_image(self, img):
        img = _as_hwc(img)
        p = self.padding
        return np.pad(img, ((p[1], p[3]), (p[0], p[2]), (0, 0)), constant_values=self.fill)


class BrightnessTransform(BaseTransform):
    def __init__(self, value, keys=None):
        self.value = value

    def _apply_image(self, img):
        if self.value == 0:
            return img
        alpha = np.random.uniform(max(0, 1 - self.value), 1 + self.value)
        img = _as_hwc(img).astype(np.float32) * alpha
        return np.clip(img, 0, 255).astype(np.uint8)


class ContrastTransform(BaseTransform):
    def __init__(self, value, keys=None):
        self.value = value

    def _apply_image(self, img):
        if self.value == 0:
            return img
        alpha = np.random.uniform(max(0, 1 - self.value), 1 + self.value)
        img = _as_hwc(img).astype(np.float32)
        mean = img.mean()
        out = img * alpha + mean * (1 - alpha)
        return np.clip(out, 0, 255).astype(np.uint8)


class SaturationTransform(BaseTransform):
    def __init__(self, value, keys=None):
        self.value = value

    def _apply_image(self, img):
        if self.value == 0:
            return img
        alpha = np.random.uniform(max(0, 1 - self.value), 1 + self.value)
        img = _as_hwc(img).astype(np.float32)
        gray = img.mean(axis=2, keepdims=True)
        out = img * alpha + gray * (1 - alpha)
        return np.clip(out, 0, 255).astype(np.uint8)


class HueTransform(BaseTransform):
    def __init__(self, value, keys=None):
        if isinstance(value, numbers.Number):
            if not 0 <= value <= 0.5:
                raise ValueError("hue value must be in [0, 0.5]")
            self.value = (-float(value), float(value))
        else:
            lo, hi = float(value[0]), float(value[1])
            if not -0.5 <= lo <= hi <= 0.5:
                raise ValueError("hue range must lie within [-0.5, 0.5]")
            self.value = (lo, hi)

    def _apply_image(self, img):
        if self.value == (0.0, 0.0):
            return img
        factor = np.random.uniform(self.value[0], self.value[1])
        return adjust_hue(img, factor)


class ColorJitter(BaseTransform):
    def __init__(self, brightness=0, contrast=0, saturation=0, hue=0, keys=None):
        self.transforms = [
            BrightnessTransform(brightness), ContrastTransform(contrast),
            SaturationTransform(saturation), HueTransform(hue),
        ]

    def _apply_image(self, img):
        order = np.random.permutation(len(self.transforms))
        for i in order:
            img = self.transforms[i](img)
        return img


class Grayscale(BaseTransform):
    def __init__(self, num_output_channels=1, keys=None):
        self.num_output_channels = num_output_channels

    def _apply_image(self, img):
        img = _as_hwc(img).astype(np.float32)
        gray = (img[..., 0] * 0.299 + img[..., 1] * 0.587 + img[..., 2] * 0.114)
        gray = gray[..., None]
        if self.num_output_channels == 3:
            gray = np.repeat(gray, 3, axis=2)
        return gray.astype(np.uint8)


# --- functional color/geometry ops (reference vision/transforms/functional.py)

def adjust_brightness(img, brightness_factor):
    """img * factor, clipped (reference functional.adjust_brightness)."""
    arr = _as_hwc(img).astype(np.float32) * float(brightness_factor)
    return np.clip(arr, 0, 255).astype(np.uint8)


def adjust_contrast(img, contrast_factor):
    """Blend with the mean gray level (reference functional.adjust_contrast)."""
    arr = _as_hwc(img).astype(np.float32)
    gray = (arr[..., 0] * 0.299 + arr[..., 1] * 0.587
            + arr[..., 2] * 0.114) if arr.shape[-1] == 3 else arr[..., 0]
    mean = gray.mean()
    out = mean + float(contrast_factor) * (arr - mean)
    return np.clip(out, 0, 255).astype(np.uint8)


def _rgb_to_hsv(rgb):
    r, g, b = rgb[..., 0], rgb[..., 1], rgb[..., 2]
    maxc = np.maximum(np.maximum(r, g), b)
    minc = np.minimum(np.minimum(r, g), b)
    v = maxc
    delta = maxc - minc
    s = np.where(maxc > 0, delta / np.maximum(maxc, 1e-12), 0.0)
    dz = np.maximum(delta, 1e-12)
    rc, gc, bc = (maxc - r) / dz, (maxc - g) / dz, (maxc - b) / dz
    h = np.where(maxc == r, bc - gc,
                 np.where(maxc == g, 2.0 + rc - bc, 4.0 + gc - rc))
    h = np.where(delta > 0, (h / 6.0) % 1.0, 0.0)
    return np.stack([h, s, v], axis=-1)


def _hsv_to_rgb(hsv):
    h, s, v = hsv[..., 0], hsv[..., 1], hsv[..., 2]
    i = np.floor(h * 6.0)
    f = h * 6.0 - i
    p = v * (1.0 - s)
    q = v * (1.0 - s * f)
    t = v * (1.0 - s * (1.0 - f))
    i = i.astype(np.int32) % 6
    out = np.choose(i[..., None], [
        np.stack([v, t, p], -1), np.stack([q, v, p], -1),
        np.stack([p, v, t], -1), np.stack([p, q, v], -1),
        np.stack([t, p, v], -1), np.stack([v, p, q], -1)])
    return out


def adjust_hue(img, hue_factor):
    """Shift hue by hue_factor ∈ [-0.5, 0.5] via HSV
    (reference functional.adjust_hue)."""
    if not -0.5 <= hue_factor <= 0.5:
        raise ValueError("hue_factor must be in [-0.5, 0.5]")
    arr = _as_hwc(img).astype(np.float32) / 255.0
    if arr.shape[-1] == 1:
        return _as_hwc(img)
    hsv = _rgb_to_hsv(arr)
    hsv[..., 0] = (hsv[..., 0] + hue_factor) % 1.0
    out = _hsv_to_rgb(hsv)
    return np.clip(out * 255.0, 0, 255).astype(np.uint8)


def to_grayscale(img, num_output_channels=1):
    return Grayscale(num_output_channels)._apply_image(img)


def pad(img, padding, fill=0, padding_mode="constant"):
    """Pad H/W (reference functional.pad): padding int | (lr, tb) |
    (l, t, r, b)."""
    arr = _as_hwc(img)
    if isinstance(padding, numbers.Number):
        l = t = r = b = int(padding)
    elif len(padding) == 2:
        l = r = int(padding[0])
        t = b = int(padding[1])
    else:
        l, t, r, b = (int(v) for v in padding)
    width = [(t, b), (l, r), (0, 0)]
    if padding_mode == "constant":
        return np.pad(arr, width, mode="constant", constant_values=fill)
    mode = {"reflect": "reflect", "edge": "edge",
            "symmetric": "symmetric"}.get(padding_mode)
    if mode is None:
        raise ValueError("unknown padding_mode %r" % (padding_mode,))
    return np.pad(arr, width, mode=mode)


def rotate(img, angle, interpolation="nearest", expand=False, center=None,
           fill=0):
    """Rotate counter-clockwise by ``angle`` degrees via PIL
    (reference functional.rotate)."""
    from PIL import Image

    arr = _as_hwc(img)
    squeeze = arr.shape[-1] == 1
    pil = Image.fromarray(arr[..., 0] if squeeze else arr)
    resample = {"nearest": Image.NEAREST, "bilinear": Image.BILINEAR,
                "bicubic": Image.BICUBIC}[interpolation]
    out = np.asarray(pil.rotate(angle, resample=resample, expand=expand,
                                center=center, fillcolor=fill))
    return out[..., None] if squeeze else out


class RandomRotation(BaseTransform):
    """Rotate by a random angle from [-degrees, degrees] (reference
    transforms.RandomRotation)."""

    def __init__(self, degrees, interpolation="nearest", expand=False,
                 center=None, fill=0, keys=None):
        if isinstance(degrees, numbers.Number):
            if degrees < 0:
                raise ValueError("degrees must be non-negative")
            self.degrees = (-float(degrees), float(degrees))
        else:
            self.degrees = (float(degrees[0]), float(degrees[1]))
        self.args = (interpolation, expand, center, fill)

    def _apply_image(self, img):
        angle = np.random.uniform(self.degrees[0], self.degrees[1])
        interpolation, expand, center, fill = self.args
        return rotate(img, angle, interpolation, expand, center, fill)
