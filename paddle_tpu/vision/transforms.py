"""Vision transforms (reference python/paddle/vision/transforms/).

Numpy/host-side preprocessing — the DataLoader applies these before batches
hit the device. HWC uint8 numpy in, CHW float out (paddle convention via
ToTensor).
"""
from __future__ import annotations

import numbers

import numpy as np

from ..framework.core import Tensor

__all__ = [
    "Compose", "ToTensor", "Resize", "Normalize", "CenterCrop", "RandomCrop",
    "RandomHorizontalFlip", "RandomVerticalFlip", "Transpose", "Pad",
    "RandomResizedCrop", "BrightnessTransform", "ContrastTransform",
    "SaturationTransform", "HueTransform", "ColorJitter", "Grayscale",
    "to_tensor", "normalize", "resize", "hflip", "vflip", "center_crop", "crop",
]


def _as_hwc(img):
    img = np.asarray(img)
    if img.ndim == 2:
        img = img[:, :, None]
    return img


def resize(img, size, interpolation="bilinear"):
    img = _as_hwc(img)
    h, w = img.shape[:2]
    if isinstance(size, int):
        if h < w:
            nh, nw = size, int(size * w / h)
        else:
            nh, nw = int(size * h / w), size
    else:
        nh, nw = size
    if (nh, nw) == (h, w):
        return img
    # bilinear resize in numpy (host-side; device path uses jax.image)
    ys = np.linspace(0, h - 1, nh)
    xs = np.linspace(0, w - 1, nw)
    if interpolation == "nearest":
        out = img[np.round(ys).astype(int)[:, None], np.round(xs).astype(int)[None, :]]
        return out
    y0 = np.floor(ys).astype(int)
    x0 = np.floor(xs).astype(int)
    y1 = np.minimum(y0 + 1, h - 1)
    x1 = np.minimum(x0 + 1, w - 1)
    wy = (ys - y0)[:, None, None]
    wx = (xs - x0)[None, :, None]
    f = img.astype(np.float32)
    out = (f[y0][:, x0] * (1 - wy) * (1 - wx) + f[y1][:, x0] * wy * (1 - wx)
           + f[y0][:, x1] * (1 - wy) * wx + f[y1][:, x1] * wy * wx)
    if img.dtype == np.uint8:
        out = np.clip(out, 0, 255).astype(np.uint8)
    return out


def crop(img, top, left, height, width):
    return _as_hwc(img)[top:top + height, left:left + width]


def center_crop(img, output_size):
    img = _as_hwc(img)
    if isinstance(output_size, numbers.Number):
        output_size = (int(output_size), int(output_size))
    h, w = img.shape[:2]
    th, tw = output_size
    i = int(round((h - th) / 2.0))
    j = int(round((w - tw) / 2.0))
    return crop(img, i, j, th, tw)


def hflip(img):
    return _as_hwc(img)[:, ::-1]


def vflip(img):
    return _as_hwc(img)[::-1]


def to_tensor(pic, data_format="CHW"):
    img = _as_hwc(pic).astype(np.float32)
    if img.dtype == np.float32 and np.asarray(pic).dtype == np.uint8:
        img = img / 255.0
    elif np.asarray(pic).dtype == np.uint8:
        img = img / 255.0
    if data_format == "CHW":
        img = img.transpose(2, 0, 1)
    return Tensor(img)


def normalize(img, mean, std, data_format="CHW", to_rgb=False):
    if isinstance(img, Tensor):
        arr = np.asarray(img._data)
    else:
        arr = np.asarray(img, dtype=np.float32)
    mean = np.asarray(mean, dtype=np.float32)
    std = np.asarray(std, dtype=np.float32)
    if data_format == "CHW":
        arr = (arr - mean[:, None, None]) / std[:, None, None]
    else:
        arr = (arr - mean) / std
    return Tensor(arr) if isinstance(img, Tensor) else arr


class BaseTransform:
    def __call__(self, img):
        return self._apply_image(img)


class Compose:
    def __init__(self, transforms):
        self.transforms = transforms

    def __call__(self, data):
        for t in self.transforms:
            data = t(data)
        return data


class ToTensor(BaseTransform):
    def __init__(self, data_format="CHW", keys=None):
        self.data_format = data_format

    def _apply_image(self, img):
        return to_tensor(img, self.data_format)


class Resize(BaseTransform):
    def __init__(self, size, interpolation="bilinear", keys=None):
        self.size = size
        self.interpolation = interpolation

    def _apply_image(self, img):
        return resize(img, self.size, self.interpolation)


class Normalize(BaseTransform):
    def __init__(self, mean=0.0, std=1.0, data_format="CHW", to_rgb=False, keys=None):
        if isinstance(mean, numbers.Number):
            mean = [mean, mean, mean]
        if isinstance(std, numbers.Number):
            std = [std, std, std]
        self.mean, self.std = mean, std
        self.data_format = data_format

    def _apply_image(self, img):
        return normalize(img, self.mean, self.std, self.data_format)


class CenterCrop(BaseTransform):
    def __init__(self, size, keys=None):
        self.size = size

    def _apply_image(self, img):
        return center_crop(img, self.size)


class RandomCrop(BaseTransform):
    def __init__(self, size, padding=None, pad_if_needed=False, fill=0,
                 padding_mode="constant", keys=None):
        if isinstance(size, numbers.Number):
            size = (int(size), int(size))
        self.size = size
        self.padding = padding

    def _apply_image(self, img):
        img = _as_hwc(img)
        if self.padding:
            p = self.padding if not isinstance(self.padding, numbers.Number) else [self.padding] * 4
            img = np.pad(img, ((p[1], p[3]), (p[0], p[2]), (0, 0)))
        h, w = img.shape[:2]
        th, tw = self.size
        if h == th and w == tw:
            return img
        i = np.random.randint(0, h - th + 1)
        j = np.random.randint(0, w - tw + 1)
        return crop(img, i, j, th, tw)


class RandomResizedCrop(BaseTransform):
    def __init__(self, size, scale=(0.08, 1.0), ratio=(3.0 / 4, 4.0 / 3),
                 interpolation="bilinear", keys=None):
        if isinstance(size, numbers.Number):
            size = (int(size), int(size))
        self.size = size
        self.scale = scale
        self.ratio = ratio
        self.interpolation = interpolation

    def _apply_image(self, img):
        img = _as_hwc(img)
        h, w = img.shape[:2]
        area = h * w
        for _ in range(10):
            target_area = np.random.uniform(*self.scale) * area
            ar = np.exp(np.random.uniform(np.log(self.ratio[0]), np.log(self.ratio[1])))
            nw = int(round(np.sqrt(target_area * ar)))
            nh = int(round(np.sqrt(target_area / ar)))
            if 0 < nw <= w and 0 < nh <= h:
                i = np.random.randint(0, h - nh + 1)
                j = np.random.randint(0, w - nw + 1)
                return resize(crop(img, i, j, nh, nw), self.size, self.interpolation)
        return resize(center_crop(img, min(h, w)), self.size, self.interpolation)


class RandomHorizontalFlip(BaseTransform):
    def __init__(self, prob=0.5, keys=None):
        self.prob = prob

    def _apply_image(self, img):
        if np.random.rand() < self.prob:
            return hflip(img)
        return img


class RandomVerticalFlip(BaseTransform):
    def __init__(self, prob=0.5, keys=None):
        self.prob = prob

    def _apply_image(self, img):
        if np.random.rand() < self.prob:
            return vflip(img)
        return img


class Transpose(BaseTransform):
    def __init__(self, order=(2, 0, 1), keys=None):
        self.order = order

    def _apply_image(self, img):
        return _as_hwc(img).transpose(self.order)


class Pad(BaseTransform):
    def __init__(self, padding, fill=0, padding_mode="constant", keys=None):
        if isinstance(padding, numbers.Number):
            padding = [padding] * 4
        elif len(padding) == 2:
            padding = [padding[0], padding[1], padding[0], padding[1]]
        self.padding = padding
        self.fill = fill

    def _apply_image(self, img):
        img = _as_hwc(img)
        p = self.padding
        return np.pad(img, ((p[1], p[3]), (p[0], p[2]), (0, 0)), constant_values=self.fill)


class BrightnessTransform(BaseTransform):
    def __init__(self, value, keys=None):
        self.value = value

    def _apply_image(self, img):
        if self.value == 0:
            return img
        alpha = np.random.uniform(max(0, 1 - self.value), 1 + self.value)
        img = _as_hwc(img).astype(np.float32) * alpha
        return np.clip(img, 0, 255).astype(np.uint8)


class ContrastTransform(BaseTransform):
    def __init__(self, value, keys=None):
        self.value = value

    def _apply_image(self, img):
        if self.value == 0:
            return img
        alpha = np.random.uniform(max(0, 1 - self.value), 1 + self.value)
        img = _as_hwc(img).astype(np.float32)
        mean = img.mean()
        out = img * alpha + mean * (1 - alpha)
        return np.clip(out, 0, 255).astype(np.uint8)


class SaturationTransform(BaseTransform):
    def __init__(self, value, keys=None):
        self.value = value

    def _apply_image(self, img):
        if self.value == 0:
            return img
        alpha = np.random.uniform(max(0, 1 - self.value), 1 + self.value)
        img = _as_hwc(img).astype(np.float32)
        gray = img.mean(axis=2, keepdims=True)
        out = img * alpha + gray * (1 - alpha)
        return np.clip(out, 0, 255).astype(np.uint8)


class HueTransform(BaseTransform):
    def __init__(self, value, keys=None):
        self.value = value

    def _apply_image(self, img):
        return img  # full HSV hue rotation: host-side nicety, not on hot path


class ColorJitter(BaseTransform):
    def __init__(self, brightness=0, contrast=0, saturation=0, hue=0, keys=None):
        self.transforms = [
            BrightnessTransform(brightness), ContrastTransform(contrast),
            SaturationTransform(saturation), HueTransform(hue),
        ]

    def _apply_image(self, img):
        order = np.random.permutation(len(self.transforms))
        for i in order:
            img = self.transforms[i](img)
        return img


class Grayscale(BaseTransform):
    def __init__(self, num_output_channels=1, keys=None):
        self.num_output_channels = num_output_channels

    def _apply_image(self, img):
        img = _as_hwc(img).astype(np.float32)
        gray = (img[..., 0] * 0.299 + img[..., 1] * 0.587 + img[..., 2] * 0.114)
        gray = gray[..., None]
        if self.num_output_channels == 3:
            gray = np.repeat(gray, 3, axis=2)
        return gray.astype(np.uint8)
