"""ResNeXt (reference python/paddle/vision/models/resnext.py:129).

Aggregated residual transformations = the ResNet bottleneck with grouped
3x3 convs; on TPU the grouped conv lowers to a feature-group XLA
convolution that tiles onto the MXU, so this reuses the ResNet trunk with
(groups=cardinality, width=group width) rather than a parallel tower copy.
"""
from __future__ import annotations

from .resnet import BottleneckBlock, ResNet

__all__ = ["ResNeXt", "resnext50_32x4d", "resnext50_64x4d",
           "resnext101_32x4d", "resnext101_64x4d", "resnext152_32x4d",
           "resnext152_64x4d"]


class ResNeXt(ResNet):
    """Reference ResNeXt class surface (depth, cardinality, num_classes,
    with_pool); 152 uses [3, 8, 36, 3] like the reference."""

    def __init__(self, depth=50, cardinality=32, num_classes=1000,
                 with_pool=True):
        self.cardinality = cardinality
        # reference uses 4-wide groups for 32-card, 64-card models alike
        super().__init__(BottleneckBlock, depth, width=4,
                         num_classes=num_classes, with_pool=with_pool,
                         groups=cardinality)


def _resnext(arch, depth, cardinality, pretrained, **kwargs):
    model = ResNeXt(depth=depth, cardinality=cardinality, **kwargs)
    if pretrained:
        raise RuntimeError(
            "zero-egress environment: pretrained weights unavailable")
    return model


def resnext50_32x4d(pretrained=False, **kwargs):
    return _resnext("resnext50_32x4d", 50, 32, pretrained, **kwargs)


def resnext50_64x4d(pretrained=False, **kwargs):
    return _resnext("resnext50_64x4d", 50, 64, pretrained, **kwargs)


def resnext101_32x4d(pretrained=False, **kwargs):
    return _resnext("resnext101_32x4d", 101, 32, pretrained, **kwargs)


def resnext101_64x4d(pretrained=False, **kwargs):
    return _resnext("resnext101_64x4d", 101, 64, pretrained, **kwargs)


def resnext152_32x4d(pretrained=False, **kwargs):
    return _resnext("resnext152_32x4d", 152, 32, pretrained, **kwargs)


def resnext152_64x4d(pretrained=False, **kwargs):
    return _resnext("resnext152_64x4d", 152, 64, pretrained, **kwargs)
