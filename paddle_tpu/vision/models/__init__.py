"""paddle_tpu.vision.models (reference python/paddle/vision/models)."""
from .lenet import LeNet  # noqa: F401
from .alexnet import AlexNet, alexnet  # noqa: F401
from .vgg import VGG, vgg11, vgg13, vgg16, vgg19  # noqa: F401
from .resnet import (  # noqa: F401
    ResNet, resnet18, resnet34, resnet50, resnet101, resnet152,
    wide_resnet50_2, wide_resnet101_2,
)
from .mobilenet import MobileNetV1, MobileNetV2, mobilenet_v1, mobilenet_v2  # noqa: F401
from .resnext import (  # noqa: F401
    ResNeXt, resnext50_32x4d, resnext50_64x4d, resnext101_32x4d,
    resnext101_64x4d, resnext152_32x4d, resnext152_64x4d,
)
