"""Detection ops (reference python/paddle/vision/ops.py + the detection op
family, paddle/fluid/operators/detection/).

TPU-native: every op is pure jnp/lax (vmapped bilinear sampling instead of
per-ROI CUDA kernels; sigmoid/exp decode as fused elementwise). NMS keeps
its data-dependent loop on host via a fixed-iteration lax.while formulation
when traced sizes allow, else eager numpy — dynamic output shapes are
inherently host-side, as in the reference's CPU kernel.

deform_conv2d / read_file / decode_jpeg are intentionally absent: modulated
deformable sampling is a gather-heavy op with no TPU-efficient layout (the
reference only ships CUDA kernels), and file IO ops belong to the input
pipeline (paddle_tpu.io + PIL/numpy), not the graph.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..framework.core import Tensor, apply_op
from ..nn.layer.layers import Layer

__all__ = ["yolo_box", "roi_align", "roi_pool", "nms", "box_iou",
           "RoIAlign", "RoIPool"]


def _arr(x):
    return x._data if isinstance(x, Tensor) else jnp.asarray(x)


# -- yolo_box ---------------------------------------------------------------

def _yolo_box(x, img_size, anchors, class_num, conf_thresh,
              downsample_ratio, clip_bbox, scale_x_y):
    n, c, h, w = x.shape
    s = len(anchors) // 2
    an = jnp.asarray(anchors, jnp.float32).reshape(s, 2)
    x = x.reshape(n, s, 5 + class_num, h, w)

    grid_x = jnp.arange(w, dtype=jnp.float32)[None, :]
    grid_y = jnp.arange(h, dtype=jnp.float32)[:, None]
    alpha = scale_x_y
    beta = -0.5 * (scale_x_y - 1.0)
    bx = (jax.nn.sigmoid(x[:, :, 0]) * alpha + beta + grid_x) / w
    by = (jax.nn.sigmoid(x[:, :, 1]) * alpha + beta + grid_y) / h
    bw = jnp.exp(x[:, :, 2]) * an[None, :, 0, None, None] / (
        downsample_ratio * w)
    bh = jnp.exp(x[:, :, 3]) * an[None, :, 1, None, None] / (
        downsample_ratio * h)
    conf = jax.nn.sigmoid(x[:, :, 4])
    probs = jax.nn.sigmoid(x[:, :, 5:])

    img_h = img_size[:, 0].astype(jnp.float32).reshape(n, 1, 1, 1)
    img_w = img_size[:, 1].astype(jnp.float32).reshape(n, 1, 1, 1)
    x1 = (bx - bw / 2) * img_w
    y1 = (by - bh / 2) * img_h
    x2 = (bx + bw / 2) * img_w
    y2 = (by + bh / 2) * img_h
    if clip_bbox:
        x1 = jnp.clip(x1, 0, img_w - 1)
        y1 = jnp.clip(y1, 0, img_h - 1)
        x2 = jnp.clip(x2, 0, img_w - 1)
        y2 = jnp.clip(y2, 0, img_h - 1)
    boxes = jnp.stack([x1, y1, x2, y2], axis=-1).reshape(n, s * h * w, 4)
    score = conf[:, :, None] * probs                      # [n,s,cls,h,w]
    keep = (conf > conf_thresh).astype(score.dtype)[:, :, None]
    score = (score * keep).transpose(0, 1, 3, 4, 2).reshape(
        n, s * h * w, class_num)
    return boxes, score


def yolo_box(x, img_size, anchors, class_num, conf_thresh,
             downsample_ratio, clip_bbox=True, name=None, scale_x_y=1.0,
             iou_aware=False, iou_aware_factor=0.5):
    """Decode YOLOv3 head output → (boxes [N,S·H·W,4], scores
    [N,S·H·W,class_num]) (reference vision/ops.py yolo_box over
    detection/yolo_box_op)."""
    if iou_aware:
        raise NotImplementedError("yolo_box: iou_aware not supported")
    return apply_op(_yolo_box, x, img_size,
                    anchors=tuple(int(a) for a in anchors),
                    class_num=int(class_num),
                    conf_thresh=float(conf_thresh),
                    downsample_ratio=int(downsample_ratio),
                    clip_bbox=bool(clip_bbox), scale_x_y=float(scale_x_y))


# -- roi align / pool -------------------------------------------------------

def _bilinear(feat, y, x):
    """feat [C,H,W]; y/x scalar float coords → [C]."""
    H, W = feat.shape[1], feat.shape[2]
    y0 = jnp.floor(y)
    x0 = jnp.floor(x)
    y1, x1 = y0 + 1, x0 + 1
    wy1, wx1 = y - y0, x - x0
    wy0, wx0 = 1 - wy1, 1 - wx1

    def at(yy, xx):
        yi = jnp.clip(yy, 0, H - 1).astype(jnp.int32)
        xi = jnp.clip(xx, 0, W - 1).astype(jnp.int32)
        v = feat[:, yi, xi]
        ok = (yy >= -1) & (yy <= H) & (xx >= -1) & (xx <= W)
        return jnp.where(ok, v, 0.0)

    return (at(y0, x0) * wy0 * wx0 + at(y0, x1) * wy0 * wx1 +
            at(y1, x0) * wy1 * wx0 + at(y1, x1) * wy1 * wx1)


def _roi_align(x, boxes, box_image, output_size, spatial_scale,
               sampling_ratio, aligned, sr_max):
    oh, ow = output_size
    off = 0.5 if aligned else 0.0
    adaptive = sampling_ratio <= 0
    sr = sr_max if adaptive else sampling_ratio

    def one_roi(img_idx, box):
        feat = x[img_idx]
        x1, y1, x2, y2 = box * spatial_scale - off
        rw = jnp.maximum(x2 - x1, 1e-6 if aligned else 1.0)
        rh = jnp.maximum(y2 - y1, 1e-6 if aligned else 1.0)
        bin_h, bin_w = rh / oh, rw / ow
        if adaptive:
            # reference roi_align_op: ceil(roi_size / pooled_size) samples
            # per bin, per ROI. Counts are traced; the grid is padded to
            # the static sr_max and masked, so shapes stay XLA-static.
            sry = jnp.clip(jnp.ceil(bin_h), 1, sr).astype(jnp.float32)
            srx = jnp.clip(jnp.ceil(bin_w), 1, sr).astype(jnp.float32)
        else:
            sry = srx = jnp.float32(sr)
        j = jnp.arange(sr, dtype=jnp.float32)
        iy, my = (j + 0.5) / sry, j < sry
        ix, mx = (j + 0.5) / srx, j < srx
        gy = y1 + (jnp.arange(oh)[:, None] + iy[None, :]) * bin_h  # [oh,sr]
        gx = x1 + (jnp.arange(ow)[:, None] + ix[None, :]) * bin_w  # [ow,sr]
        sample = jax.vmap(lambda yy: jax.vmap(
            lambda xx: _bilinear(feat, yy, xx))(gx.reshape(-1)))(
                gy.reshape(-1))                      # [oh*sr, ow*sr, C]
        sample = sample.reshape(oh, sr, ow, sr, -1)
        w = (my.astype(sample.dtype)[None, :, None, None, None]
             * mx.astype(sample.dtype)[None, None, None, :, None])
        return (jnp.sum(sample * w, axis=(1, 3)) / (sry * srx)
                ).transpose(2, 0, 1)                 # [C,oh,ow]

    return jax.vmap(one_roi)(box_image, boxes)


def roi_align(x, boxes, boxes_num, output_size, spatial_scale=1.0,
              sampling_ratio=-1, aligned=True, name=None):
    """RoIAlign (reference vision/ops.py roi_align over roi_align_op):
    x [N,C,H,W]; boxes [R,4] (x1,y1,x2,y2); boxes_num [N] rois per image.
    Returns [R, C, output_size, output_size]. sampling_ratio<=0 uses the
    reference's adaptive ceil(roi_size/output_size) per-ROI sample count
    (grid padded to the batch max so shapes stay static)."""
    if isinstance(output_size, int):
        output_size = (output_size, output_size)
    bn = np.asarray(_arr(boxes_num))
    box_image = jnp.asarray(np.repeat(np.arange(len(bn)), bn).astype(np.int32))
    sr_max = int(sampling_ratio)
    if sampling_ratio <= 0:
        b = np.asarray(_arr(boxes), dtype=np.float64)
        oh, ow = output_size
        floor = 1e-6 if aligned else 1.0
        rw = np.maximum((b[:, 2] - b[:, 0]) * spatial_scale, floor)
        rh = np.maximum((b[:, 3] - b[:, 1]) * spatial_scale, floor)
        sr_max = int(max(1, np.max(np.ceil(np.concatenate(
            [rh / oh, rw / ow]))))) if len(b) else 1
    return apply_op(_roi_align, x, boxes, box_image,
                    output_size=tuple(int(s) for s in output_size),
                    spatial_scale=float(spatial_scale),
                    sampling_ratio=int(sampling_ratio), aligned=bool(aligned),
                    sr_max=sr_max)


def _roi_pool(x, boxes, box_image, output_size, spatial_scale):
    oh, ow = output_size

    def one_roi(img_idx, box):
        feat = x[img_idx]
        C, H, W = feat.shape
        x1 = jnp.round(box[0] * spatial_scale)
        y1 = jnp.round(box[1] * spatial_scale)
        x2 = jnp.round(box[2] * spatial_scale)
        y2 = jnp.round(box[3] * spatial_scale)
        rw = jnp.maximum(x2 - x1 + 1, 1.0)
        rh = jnp.maximum(y2 - y1 + 1, 1.0)
        # max over each bin via masked reduction (dense, static-shaped)
        ys = jnp.arange(H, dtype=jnp.float32)
        xs = jnp.arange(W, dtype=jnp.float32)
        ybin = jnp.clip(jnp.floor((ys - y1) / (rh / oh)), -1, oh).astype(jnp.int32)
        xbin = jnp.clip(jnp.floor((xs - x1) / (rw / ow)), -1, ow).astype(jnp.int32)
        inside_y = (ys >= y1) & (ys <= y2)
        inside_x = (xs >= x1) & (xs <= x2)

        out = jnp.full((C, oh, ow), -jnp.inf, feat.dtype)
        ymask = (ybin[None, :] == jnp.arange(oh)[:, None]) & inside_y[None, :]
        xmask = (xbin[None, :] == jnp.arange(ow)[:, None]) & inside_x[None, :]
        # [oh, H] x [ow, W] masks → per-bin max: einsum-style masked max
        big_neg = jnp.asarray(-1e30, feat.dtype)
        f = feat[None, None]                      # [1,1,C,H,W]
        m = (ymask[:, None, None, :, None] & xmask[None, :, None, None, :])
        vals = jnp.where(m, f, big_neg)           # [oh,ow,C,H,W]
        out = jnp.max(vals, axis=(3, 4)).transpose(2, 0, 1)
        empty = ~(m.any(axis=(3, 4)))             # [oh,ow,C]
        return jnp.where(empty.transpose(2, 0, 1), 0.0, out)

    return jax.vmap(one_roi)(box_image, boxes)


def roi_pool(x, boxes, boxes_num, output_size, spatial_scale=1.0, name=None):
    """RoIPool (reference roi_pool_op): max-pool each ROI bin."""
    if isinstance(output_size, int):
        output_size = (output_size, output_size)
    bn = np.asarray(_arr(boxes_num))
    box_image = jnp.asarray(np.repeat(np.arange(len(bn)), bn).astype(np.int32))
    return apply_op(_roi_pool, x, boxes, box_image,
                    output_size=tuple(int(s) for s in output_size),
                    spatial_scale=float(spatial_scale))


class RoIAlign(Layer):
    def __init__(self, output_size, spatial_scale=1.0):
        super().__init__()
        self._output_size = output_size
        self._spatial_scale = spatial_scale

    def forward(self, x, boxes, boxes_num):
        return roi_align(x, boxes, boxes_num, self._output_size,
                         self._spatial_scale)


class RoIPool(Layer):
    def __init__(self, output_size, spatial_scale=1.0):
        super().__init__()
        self._output_size = output_size
        self._spatial_scale = spatial_scale

    def forward(self, x, boxes, boxes_num):
        return roi_pool(x, boxes, boxes_num, self._output_size,
                        self._spatial_scale)


# -- box utilities ----------------------------------------------------------

def box_iou(boxes1, boxes2):
    """Pairwise IoU [R1, R2] for (x1,y1,x2,y2) boxes."""
    a = _arr(boxes1).astype(jnp.float32)
    b = _arr(boxes2).astype(jnp.float32)
    area1 = (a[:, 2] - a[:, 0]) * (a[:, 3] - a[:, 1])
    area2 = (b[:, 2] - b[:, 0]) * (b[:, 3] - b[:, 1])
    lt = jnp.maximum(a[:, None, :2], b[None, :, :2])
    rb = jnp.minimum(a[:, None, 2:], b[None, :, 2:])
    wh = jnp.clip(rb - lt, 0)
    inter = wh[..., 0] * wh[..., 1]
    return Tensor(inter / (area1[:, None] + area2[None, :] - inter + 1e-10))


def nms(boxes, iou_threshold=0.3, scores=None, category_idxs=None,
        categories=None, top_k=None):
    """Hard NMS (reference vision/ops.py nms, eager host semantics —
    dynamic output size). With category_idxs, NMS is per category."""
    b = np.asarray(_arr(boxes), np.float32)
    n = len(b)
    sc = (np.asarray(_arr(scores), np.float32) if scores is not None
          else np.arange(n, 0, -1, dtype=np.float32))

    def nms_one(idxs):
        order = idxs[np.argsort(-sc[idxs])]
        keep = []
        iou = np.asarray(box_iou(b, b)._data)
        alive = list(order)
        while alive:
            i = alive.pop(0)
            keep.append(i)
            alive = [j for j in alive if iou[i, j] <= iou_threshold]
        return keep

    if category_idxs is None:
        keep = nms_one(np.arange(n))
    else:
        cats = np.asarray(_arr(category_idxs))
        keep = []
        for c in (categories if categories is not None else np.unique(cats)):
            keep.extend(nms_one(np.where(cats == c)[0]))
        keep = sorted(keep, key=lambda i: -sc[i])
    if top_k is not None:
        keep = keep[:top_k]
    return Tensor(jnp.asarray(np.asarray(keep, np.int64)))
