"""Detection ops (reference python/paddle/vision/ops.py + the detection op
family, paddle/fluid/operators/detection/).

TPU-native: every op is pure jnp/lax (vmapped bilinear sampling instead of
per-ROI CUDA kernels; sigmoid/exp decode as fused elementwise). NMS keeps
its data-dependent loop on host via a fixed-iteration lax.while formulation
when traced sizes allow, else eager numpy — dynamic output shapes are
inherently host-side, as in the reference's CPU kernel.

read_file / decode_jpeg run host-side (PIL): image IO is input-pipeline
work that never belongs on the TPU.
deform_conv2d is implemented as vectorized bilinear gathers + grouped
einsum — gather-heavy (VPU-bound, not MXU-peak) but numerically exact vs
the reference's modulated im2col.
"""
from __future__ import annotations

import math

import jax
import jax.numpy as jnp
import numpy as np

from ..framework.core import Tensor, apply_op
from ..nn.functional.common import _bilinear_batch
from ..nn.layer.layers import Layer

__all__ = ["yolo_box", "roi_align", "roi_pool", "psroi_pool", "nms",
           "box_iou", "prior_box", "box_coder", "bipartite_match",
           "multiclass_nms", "matrix_nms", "deform_conv2d", "iou_similarity",
           "box_clip", "anchor_generator", "generate_proposals",
           "distribute_fpn_proposals", "collect_fpn_proposals",
           "RoIAlign", "RoIPool", "yolo_loss", "DeformConv2D", "PSRoIPool",
           "read_file", "decode_jpeg", "ssd_loss", "target_assign",
           "density_prior_box", "rpn_target_assign",
           "generate_proposal_labels", "retinanet_target_assign",
           "retinanet_detection_output", "polygon_box_transform",
           "locality_aware_nms"]


def _arr(x):
    return x._data if isinstance(x, Tensor) else jnp.asarray(x)


# -- yolo_box ---------------------------------------------------------------

def _yolo_box(x, img_size, anchors, class_num, conf_thresh,
              downsample_ratio, clip_bbox, scale_x_y):
    n, c, h, w = x.shape
    s = len(anchors) // 2
    an = jnp.asarray(anchors, jnp.float32).reshape(s, 2)
    x = x.reshape(n, s, 5 + class_num, h, w)

    grid_x = jnp.arange(w, dtype=jnp.float32)[None, :]
    grid_y = jnp.arange(h, dtype=jnp.float32)[:, None]
    alpha = scale_x_y
    beta = -0.5 * (scale_x_y - 1.0)
    bx = (jax.nn.sigmoid(x[:, :, 0]) * alpha + beta + grid_x) / w
    by = (jax.nn.sigmoid(x[:, :, 1]) * alpha + beta + grid_y) / h
    bw = jnp.exp(x[:, :, 2]) * an[None, :, 0, None, None] / (
        downsample_ratio * w)
    bh = jnp.exp(x[:, :, 3]) * an[None, :, 1, None, None] / (
        downsample_ratio * h)
    conf = jax.nn.sigmoid(x[:, :, 4])
    probs = jax.nn.sigmoid(x[:, :, 5:])

    img_h = img_size[:, 0].astype(jnp.float32).reshape(n, 1, 1, 1)
    img_w = img_size[:, 1].astype(jnp.float32).reshape(n, 1, 1, 1)
    x1 = (bx - bw / 2) * img_w
    y1 = (by - bh / 2) * img_h
    x2 = (bx + bw / 2) * img_w
    y2 = (by + bh / 2) * img_h
    if clip_bbox:
        x1 = jnp.clip(x1, 0, img_w - 1)
        y1 = jnp.clip(y1, 0, img_h - 1)
        x2 = jnp.clip(x2, 0, img_w - 1)
        y2 = jnp.clip(y2, 0, img_h - 1)
    boxes = jnp.stack([x1, y1, x2, y2], axis=-1).reshape(n, s * h * w, 4)
    score = conf[:, :, None] * probs                      # [n,s,cls,h,w]
    keep = (conf > conf_thresh).astype(score.dtype)[:, :, None]
    score = (score * keep).transpose(0, 1, 3, 4, 2).reshape(
        n, s * h * w, class_num)
    return boxes, score


def yolo_box(x, img_size, anchors, class_num, conf_thresh,
             downsample_ratio, clip_bbox=True, name=None, scale_x_y=1.0,
             iou_aware=False, iou_aware_factor=0.5):
    """Decode YOLOv3 head output → (boxes [N,S·H·W,4], scores
    [N,S·H·W,class_num]) (reference vision/ops.py yolo_box over
    detection/yolo_box_op)."""
    if iou_aware:
        raise NotImplementedError("yolo_box: iou_aware not supported")
    return apply_op(_yolo_box, x, img_size,
                    anchors=tuple(int(a) for a in anchors),
                    class_num=int(class_num),
                    conf_thresh=float(conf_thresh),
                    downsample_ratio=int(downsample_ratio),
                    clip_bbox=bool(clip_bbox), scale_x_y=float(scale_x_y))


# -- roi align / pool -------------------------------------------------------
# bilinear gathers share one implementation: nn/functional/common.py
# _bilinear_batch (bounds="clamp_sample" here — roi_align edge semantics)


def _roi_align(x, boxes, box_image, output_size, spatial_scale,
               sampling_ratio, aligned, sr_max):
    oh, ow = output_size
    off = 0.5 if aligned else 0.0
    adaptive = sampling_ratio <= 0
    sr = sr_max if adaptive else sampling_ratio

    def one_roi(img_idx, box):
        feat = x[img_idx]
        x1, y1, x2, y2 = box * spatial_scale - off
        rw = jnp.maximum(x2 - x1, 1e-6 if aligned else 1.0)
        rh = jnp.maximum(y2 - y1, 1e-6 if aligned else 1.0)
        bin_h, bin_w = rh / oh, rw / ow
        if adaptive:
            # reference roi_align_op: ceil(roi_size / pooled_size) samples
            # per bin, per ROI. Counts are traced; the grid is padded to
            # the static sr_max and masked, so shapes stay XLA-static.
            sry = jnp.clip(jnp.ceil(bin_h), 1, sr).astype(jnp.float32)
            srx = jnp.clip(jnp.ceil(bin_w), 1, sr).astype(jnp.float32)
        else:
            sry = srx = jnp.float32(sr)
        j = jnp.arange(sr, dtype=jnp.float32)
        iy, my = (j + 0.5) / sry, j < sry
        ix, mx = (j + 0.5) / srx, j < srx
        gy = y1 + (jnp.arange(oh)[:, None] + iy[None, :]) * bin_h  # [oh,sr]
        gx = x1 + (jnp.arange(ow)[:, None] + ix[None, :]) * bin_w  # [ow,sr]
        ys = jnp.broadcast_to(gy.reshape(-1)[:, None],
                              (oh * sr, ow * sr))
        xs = jnp.broadcast_to(gx.reshape(-1)[None, :],
                              (oh * sr, ow * sr))
        sample = _bilinear_batch(feat, ys, xs, bounds="clamp_sample")
        sample = sample.reshape(-1, oh, sr, ow, sr)   # [C,oh,sr,ow,sr]
        w = (my.astype(sample.dtype)[None, None, :, None, None]
             * mx.astype(sample.dtype)[None, None, None, None, :])
        return jnp.sum(sample * w, axis=(2, 4)) / (sry * srx)  # [C,oh,ow]

    return jax.vmap(one_roi)(box_image, boxes)


def roi_align(x, boxes, boxes_num, output_size, spatial_scale=1.0,
              sampling_ratio=-1, aligned=True, name=None):
    """RoIAlign (reference vision/ops.py roi_align over roi_align_op):
    x [N,C,H,W]; boxes [R,4] (x1,y1,x2,y2); boxes_num [N] rois per image.
    Returns [R, C, output_size, output_size]. sampling_ratio<=0 uses the
    reference's adaptive ceil(roi_size/output_size) per-ROI sample count
    (grid padded to the batch max so shapes stay static)."""
    if isinstance(output_size, int):
        output_size = (output_size, output_size)
    bn = np.asarray(_arr(boxes_num))
    box_image = jnp.asarray(np.repeat(np.arange(len(bn)), bn).astype(np.int32))
    sr_max = int(sampling_ratio)
    if sampling_ratio <= 0:
        b = np.asarray(_arr(boxes), dtype=np.float64)
        oh, ow = output_size
        floor = 1e-6 if aligned else 1.0
        rw = np.maximum((b[:, 2] - b[:, 0]) * spatial_scale, floor)
        rh = np.maximum((b[:, 3] - b[:, 1]) * spatial_scale, floor)
        sr_max = int(max(1, np.max(np.ceil(np.concatenate(
            [rh / oh, rw / ow]))))) if len(b) else 1
    return apply_op(_roi_align, x, boxes, box_image,
                    output_size=tuple(int(s) for s in output_size),
                    spatial_scale=float(spatial_scale),
                    sampling_ratio=int(sampling_ratio), aligned=bool(aligned),
                    sr_max=sr_max)


def _roi_pool(x, boxes, box_image, output_size, spatial_scale):
    oh, ow = output_size

    def one_roi(img_idx, box):
        feat = x[img_idx]
        C, H, W = feat.shape
        x1 = jnp.round(box[0] * spatial_scale)
        y1 = jnp.round(box[1] * spatial_scale)
        x2 = jnp.round(box[2] * spatial_scale)
        y2 = jnp.round(box[3] * spatial_scale)
        rw = jnp.maximum(x2 - x1 + 1, 1.0)
        rh = jnp.maximum(y2 - y1 + 1, 1.0)
        # max over each bin via masked reduction (dense, static-shaped)
        ys = jnp.arange(H, dtype=jnp.float32)
        xs = jnp.arange(W, dtype=jnp.float32)
        ybin = jnp.clip(jnp.floor((ys - y1) / (rh / oh)), -1, oh).astype(jnp.int32)
        xbin = jnp.clip(jnp.floor((xs - x1) / (rw / ow)), -1, ow).astype(jnp.int32)
        inside_y = (ys >= y1) & (ys <= y2)
        inside_x = (xs >= x1) & (xs <= x2)

        out = jnp.full((C, oh, ow), -jnp.inf, feat.dtype)
        ymask = (ybin[None, :] == jnp.arange(oh)[:, None]) & inside_y[None, :]
        xmask = (xbin[None, :] == jnp.arange(ow)[:, None]) & inside_x[None, :]
        # [oh, H] x [ow, W] masks → per-bin max: einsum-style masked max
        big_neg = jnp.asarray(-1e30, feat.dtype)
        f = feat[None, None]                      # [1,1,C,H,W]
        m = (ymask[:, None, None, :, None] & xmask[None, :, None, None, :])
        vals = jnp.where(m, f, big_neg)           # [oh,ow,C,H,W]
        out = jnp.max(vals, axis=(3, 4)).transpose(2, 0, 1)
        empty = ~(m.any(axis=(3, 4)))             # [oh,ow,C]
        return jnp.where(empty.transpose(2, 0, 1), 0.0, out)

    return jax.vmap(one_roi)(box_image, boxes)


def roi_pool(x, boxes, boxes_num, output_size, spatial_scale=1.0, name=None):
    """RoIPool (reference roi_pool_op): max-pool each ROI bin."""
    if isinstance(output_size, int):
        output_size = (output_size, output_size)
    bn = np.asarray(_arr(boxes_num))
    box_image = jnp.asarray(np.repeat(np.arange(len(bn)), bn).astype(np.int32))
    return apply_op(_roi_pool, x, boxes, box_image,
                    output_size=tuple(int(s) for s in output_size),
                    spatial_scale=float(spatial_scale))


class RoIAlign(Layer):
    def __init__(self, output_size, spatial_scale=1.0):
        super().__init__()
        self._output_size = output_size
        self._spatial_scale = spatial_scale

    def forward(self, x, boxes, boxes_num):
        return roi_align(x, boxes, boxes_num, self._output_size,
                         self._spatial_scale)


class RoIPool(Layer):
    def __init__(self, output_size, spatial_scale=1.0):
        super().__init__()
        self._output_size = output_size
        self._spatial_scale = spatial_scale

    def forward(self, x, boxes, boxes_num):
        return roi_pool(x, boxes, boxes_num, self._output_size,
                        self._spatial_scale)


# -- box utilities ----------------------------------------------------------

def box_iou(boxes1, boxes2):
    """Pairwise IoU [R1, R2] for (x1,y1,x2,y2) boxes."""
    a = _arr(boxes1).astype(jnp.float32)
    b = _arr(boxes2).astype(jnp.float32)
    area1 = (a[:, 2] - a[:, 0]) * (a[:, 3] - a[:, 1])
    area2 = (b[:, 2] - b[:, 0]) * (b[:, 3] - b[:, 1])
    lt = jnp.maximum(a[:, None, :2], b[None, :, :2])
    rb = jnp.minimum(a[:, None, 2:], b[None, :, 2:])
    wh = jnp.clip(rb - lt, 0)
    inter = wh[..., 0] * wh[..., 1]
    return Tensor(inter / (area1[:, None] + area2[None, :] - inter + 1e-10))


def nms(boxes, iou_threshold=0.3, scores=None, category_idxs=None,
        categories=None, top_k=None):
    """Hard NMS (reference vision/ops.py nms, eager host semantics —
    dynamic output size). With category_idxs, NMS is per category."""
    b = np.asarray(_arr(boxes), np.float32)
    n = len(b)
    sc = (np.asarray(_arr(scores), np.float32) if scores is not None
          else np.arange(n, 0, -1, dtype=np.float32))

    def nms_one(idxs):
        order = idxs[np.argsort(-sc[idxs])]
        keep = []
        iou = np.asarray(box_iou(b, b)._data)
        alive = list(order)
        while alive:
            i = alive.pop(0)
            keep.append(i)
            alive = [j for j in alive if iou[i, j] <= iou_threshold]
        return keep

    if category_idxs is None:
        keep = nms_one(np.arange(n))
    else:
        cats = np.asarray(_arr(category_idxs))
        keep = []
        for c in (categories if categories is not None else np.unique(cats)):
            keep.extend(nms_one(np.where(cats == c)[0]))
        keep = sorted(keep, key=lambda i: -sc[i])
    if top_k is not None:
        keep = keep[:top_k]
    return Tensor(jnp.asarray(np.asarray(keep, np.int64)))


# -- SSD detection family (reference paddle/fluid/operators/detection/) -----

def prior_box(input, image, min_sizes, max_sizes=None, aspect_ratios=(1.0,),
              variance=(0.1, 0.1, 0.2, 0.2), flip=False, clip=False,
              steps=(0.0, 0.0), offset=0.5, min_max_aspect_ratios_order=False,
              name=None):
    """SSD prior boxes (reference detection/prior_box_op.h:52 — same box
    emission order, incl. the min_max_aspect_ratios_order switch).

    input [N,C,H,W] feature map, image [N,C,Him,Wim]. Returns
    (boxes [H,W,num_priors,4], variances [H,W,num_priors,4]) — pure
    host-side geometry (static given shapes), no device compute.
    """
    fh, fw = int(input.shape[2]), int(input.shape[3])
    ih, iw = int(image.shape[2]), int(image.shape[3])
    min_sizes = [float(s) for s in np.atleast_1d(min_sizes)]
    max_sizes = [float(s) for s in np.atleast_1d(max_sizes)] if max_sizes else []
    if max_sizes:
        assert len(max_sizes) == len(min_sizes), \
            "max_sizes must pair with min_sizes"
    # ExpandAspectRatios (prior_box_op.h:27): dedup, keep 1.0 first, flip
    ars = [1.0]
    for ar in aspect_ratios:
        ar = float(ar)
        if any(abs(ar - e) < 1e-6 for e in ars):
            continue
        ars.append(ar)
        if flip:
            ars.append(1.0 / ar)
    step_w = float(steps[0]) or iw / fw
    step_h = float(steps[1]) or ih / fh

    boxes_per_pos = []

    def emit(cx, cy, bw, bh):
        boxes_per_pos.append(((cx - bw) / iw, (cy - bh) / ih,
                              (cx + bw) / iw, (cy + bh) / ih))

    rows = []
    for h in range(fh):
        row = []
        for w in range(fw):
            boxes_per_pos = []
            cx = (w + offset) * step_w
            cy = (h + offset) * step_h
            for s, ms in enumerate(min_sizes):
                if min_max_aspect_ratios_order:
                    emit(cx, cy, ms / 2.0, ms / 2.0)
                    if max_sizes:
                        r = math.sqrt(ms * max_sizes[s]) / 2.0
                        emit(cx, cy, r, r)
                    for ar in ars:
                        if abs(ar - 1.0) < 1e-6:
                            continue
                        emit(cx, cy, ms * math.sqrt(ar) / 2.0,
                             ms / math.sqrt(ar) / 2.0)
                else:
                    for ar in ars:
                        emit(cx, cy, ms * math.sqrt(ar) / 2.0,
                             ms / math.sqrt(ar) / 2.0)
                    if max_sizes:
                        r = math.sqrt(ms * max_sizes[s]) / 2.0
                        emit(cx, cy, r, r)
            row.append(boxes_per_pos)
        rows.append(row)
    out = np.asarray(rows, np.float32)                 # [H,W,P,4]
    if clip:
        out = np.clip(out, 0.0, 1.0)
    var = np.broadcast_to(np.asarray(variance, np.float32), out.shape).copy()
    return Tensor(jnp.asarray(out)), Tensor(jnp.asarray(var))


def box_coder(prior_box, prior_box_var, target_box, code_type="encode_center_size",
              box_normalized=True, axis=0, name=None):
    """Encode/decode boxes against priors (reference
    detection/box_coder_op.h:41 EncodeCenterSize / :118 DecodeCenterSize,
    same variance and +1-for-unnormalized conventions)."""
    pb = _arr(prior_box).astype(jnp.float32)
    tb = _arr(target_box).astype(jnp.float32)
    norm = bool(box_normalized)
    off = 0.0 if norm else 1.0

    var_arr = None
    var_list = None
    if prior_box_var is not None:
        if isinstance(prior_box_var, (list, tuple)):
            var_list = jnp.asarray(prior_box_var, jnp.float32)
        else:
            var_arr = _arr(prior_box_var).astype(jnp.float32)

    pw = pb[:, 2] - pb[:, 0] + off
    ph = pb[:, 3] - pb[:, 1] + off
    pcx = pb[:, 0] + pw / 2
    pcy = pb[:, 1] + ph / 2

    if code_type == "encode_center_size":
        # tb [N,4] targets x pb [M,4] priors -> [N,M,4]
        tw = tb[:, 2] - tb[:, 0] + off
        th = tb[:, 3] - tb[:, 1] + off
        tcx = (tb[:, 0] + tb[:, 2]) / 2
        tcy = (tb[:, 1] + tb[:, 3]) / 2
        out = jnp.stack([
            (tcx[:, None] - pcx[None, :]) / pw[None, :],
            (tcy[:, None] - pcy[None, :]) / ph[None, :],
            jnp.log(jnp.abs(tw[:, None] / pw[None, :])),
            jnp.log(jnp.abs(th[:, None] / ph[None, :])),
        ], axis=-1)
        if var_arr is not None:
            out = out / var_arr[None, :, :]
        elif var_list is not None:
            out = out / var_list[None, None, :]
        return Tensor(out)

    if code_type != "decode_center_size":
        raise ValueError(f"unknown code_type {code_type!r}")
    # tb [N,M,4] deltas; priors broadcast along axis (0: per column j,
    # 1: per row i)
    exp = (lambda a: a[None, :]) if axis == 0 else (lambda a: a[:, None])
    if var_arr is not None:
        v = var_arr[None, :, :] if axis == 0 else var_arr[:, None, :]
    elif var_list is not None:
        v = var_list[None, None, :]
    else:
        v = jnp.ones((1, 1, 4), jnp.float32)
    cx = v[..., 0] * tb[..., 0] * exp(pw) + exp(pcx)
    cy = v[..., 1] * tb[..., 1] * exp(ph) + exp(pcy)
    w = jnp.exp(v[..., 2] * tb[..., 2]) * exp(pw)
    h = jnp.exp(v[..., 3] * tb[..., 3]) * exp(ph)
    out = jnp.stack([cx - w / 2, cy - h / 2,
                     cx + w / 2 - off, cy + h / 2 - off], axis=-1)
    return Tensor(out)


def bipartite_match(dist_matrix, match_type=None, dist_threshold=None,
                    name=None):
    """Greedy bipartite matching (reference detection/bipartite_match_op.cc
    BipartiteMatch): rows = entities (gt boxes), cols = candidates
    (priors). Returns (match_indices [1,M] int32 row-per-column with -1
    unmatched, match_dist [1,M]). match_type='per_prediction' additionally
    assigns any unmatched column whose best row distance > dist_threshold
    (argmax match, reference :118)."""
    d = np.asarray(_arr(dist_matrix), np.float32)
    assert d.ndim == 2, "bipartite_match expects a 2-D distance matrix"
    rows, cols = d.shape
    match_idx = np.full((cols,), -1, np.int32)
    match_dist = np.zeros((cols,), np.float32)
    work = d.copy()
    for _ in range(min(rows, cols)):
        i, j = np.unravel_index(np.argmax(work), work.shape)
        if work[i, j] <= 0:
            break
        match_idx[j] = i
        match_dist[j] = d[i, j]
        work[i, :] = -1.0
        work[:, j] = -1.0
    if match_type == "per_prediction":
        thr = float(dist_threshold if dist_threshold is not None else 0.5)
        best_row = d.argmax(axis=0)
        best = d.max(axis=0)
        extra = (match_idx == -1) & (best > thr)
        match_idx[extra] = best_row[extra]
        match_dist[extra] = best[extra]
    return (Tensor(jnp.asarray(match_idx[None, :])),
            Tensor(jnp.asarray(match_dist[None, :])))


def multiclass_nms(bboxes, scores, score_threshold=0.0, nms_top_k=-1,
                   keep_top_k=-1, nms_threshold=0.3, normalized=True,
                   nms_eta=1.0, background_label=0, return_index=False,
                   rois_num=None, name=None):
    """Multi-class NMS (reference detection/multiclass_nms_op.cc, host
    semantics — dynamic output): bboxes [N,M,4], scores [N,C,M] — or the
    LoD-style form bboxes [M_total,4], scores [C,M_total] with ``rois_num``
    [N] giving per-image box counts (reference multiclass_nms3). Returns
    (out [K,6] rows (label, score, x1,y1,x2,y2), rois_num [N]) and the
    kept flat indices when return_index."""
    b = np.asarray(_arr(bboxes), np.float32)
    s = np.asarray(_arr(scores), np.float32)
    if s.ndim == 2:
        if rois_num is None:
            raise ValueError(
                "multiclass_nms with 2-D scores needs rois_num (per-image "
                "box counts, reference multiclass_nms3 RoisNum input)")
        counts = [int(v) for v in np.asarray(_arr(rois_num))]
        bounds = np.cumsum([0] + counts)
        outs = []
        for n in range(len(counts)):
            lo, hi = bounds[n], bounds[n + 1]
            outs.append(multiclass_nms(
                b[None, lo:hi], s[None, :, lo:hi], score_threshold,
                nms_top_k, keep_top_k, nms_threshold, normalized, nms_eta,
                background_label, return_index=True))
        out = np.concatenate([np.asarray(o[0]._data) for o in outs]) \
            if outs else np.zeros((0, 6), np.float32)
        nums = np.concatenate([np.asarray(o[1]._data) for o in outs]) \
            if outs else np.zeros((0,), np.int32)
        idx = np.concatenate(
            [np.asarray(o[2]._data) + bounds[n] for n, o in enumerate(outs)]
        ) if outs else np.zeros((0,), np.int64)
        res = (Tensor(jnp.asarray(out)), Tensor(jnp.asarray(nums)))
        if return_index:
            return res + (Tensor(jnp.asarray(idx)),)
        return res
    if rois_num is not None:
        raise ValueError("rois_num only applies to the 2-D LoD-style "
                         "inputs; batched [N,C,M] scores already carry the "
                         "image grouping")
    N, C, M = s.shape

    def area_iou(bb):
        off = 0.0 if normalized else 1.0
        x1, y1, x2, y2 = bb[:, 0], bb[:, 1], bb[:, 2], bb[:, 3]
        area = (x2 - x1 + off) * (y2 - y1 + off)
        ix1 = np.maximum(x1[:, None], x1[None, :])
        iy1 = np.maximum(y1[:, None], y1[None, :])
        ix2 = np.minimum(x2[:, None], x2[None, :])
        iy2 = np.minimum(y2[:, None], y2[None, :])
        iw = np.clip(ix2 - ix1 + off, 0, None)
        ih = np.clip(iy2 - iy1 + off, 0, None)
        inter = iw * ih
        return inter / (area[:, None] + area[None, :] - inter + 1e-10)

    all_rows, all_idx, per_img = [], [], []
    for n in range(N):
        iou = area_iou(b[n])
        kept = []  # (label, score, box_idx)
        for c in range(C):
            if c == background_label:
                continue
            cand = np.where(s[n, c] > score_threshold)[0]
            cand = cand[np.argsort(-s[n, c][cand], kind="stable")]
            if nms_top_k > 0:
                cand = cand[:nms_top_k]
            alive = list(cand)
            thr = nms_threshold
            while alive:
                i = alive.pop(0)
                kept.append((c, s[n, c, i], i))
                alive = [j for j in alive if iou[i, j] <= thr]
                if nms_eta < 1.0 and thr > 0.5:
                    thr *= nms_eta
        kept.sort(key=lambda t: -t[1])
        if keep_top_k > 0:
            kept = kept[:keep_top_k]
        for c, sc, i in kept:
            all_rows.append([float(c), float(sc)] + list(b[n, i]))
            all_idx.append(n * M + i)
        per_img.append(len(kept))
    out = (np.asarray(all_rows, np.float32) if all_rows
           else np.zeros((0, 6), np.float32))
    res = (Tensor(jnp.asarray(out)),
           Tensor(jnp.asarray(np.asarray(per_img, np.int32))))
    if return_index:
        return res + (Tensor(jnp.asarray(np.asarray(all_idx, np.int64))),)
    return res


# -- psroi_pool -------------------------------------------------------------

def _psroi_pool(x, boxes, box_image, output_size, spatial_scale, out_channels):
    oh, ow = output_size

    def one_roi(img_idx, box):
        feat = x[img_idx]                            # [C, H, W]
        C, H, W = feat.shape
        x1 = jnp.round(box[0] * spatial_scale)
        y1 = jnp.round(box[1] * spatial_scale)
        x2 = jnp.round(box[2] * spatial_scale)
        y2 = jnp.round(box[3] * spatial_scale)
        rw = jnp.maximum(x2 - x1, 0.1)
        rh = jnp.maximum(y2 - y1, 0.1)
        bin_w, bin_h = rw / ow, rh / oh
        ys = jnp.arange(H, dtype=jnp.float32)
        xs = jnp.arange(W, dtype=jnp.float32)
        # bin index of each pixel (floor) with inside-bin masks
        ybin = jnp.floor((ys - y1) / bin_h).astype(jnp.int32)
        xbin = jnp.floor((xs - x1) / bin_w).astype(jnp.int32)
        in_y = (ys >= y1) & (ys < y2)
        in_x = (xs >= x1) & (xs < x2)
        # mask [oh, H] / [ow, W]
        my = ((ybin[None, :] == jnp.arange(oh)[:, None]) & in_y[None, :])
        mx = ((xbin[None, :] == jnp.arange(ow)[:, None]) & in_x[None, :])
        myf = my.astype(feat.dtype)
        mxf = mx.astype(feat.dtype)
        # position-sensitive: output channel c, bin (i,j) pools input
        # channel c*oh*ow + i*ow + j — contract each channel against ITS
        # bin's masks only (an unrestricted chw,ih,jw->cij einsum would
        # compute the full cross product and keep 1/(oh*ow) of it)
        featp = feat.reshape(out_channels, oh, ow, H, W)
        sums = jnp.einsum("cijhw,ih,jw->cij", featp, myf, mxf)
        counts = jnp.einsum("ih,jw->ij", myf, mxf)
        return sums / jnp.maximum(counts, 1.0)[None, :, :]

    return jax.vmap(one_roi)(box_image, boxes)


def psroi_pool(x, boxes, boxes_num, output_size, spatial_scale=1.0,
               name=None):
    """Position-sensitive RoI pooling (reference detection R-FCN op
    operators/psroi_pool_op.h): x [N, out_c*oh*ow, H, W]; returns
    [R, out_c, oh, ow] where bin (i,j) averages input channel
    c*oh*ow + i*ow + j over the bin region."""
    if isinstance(output_size, int):
        output_size = (output_size, output_size)
    oh, ow = output_size
    C = int(x.shape[1])
    if C % (oh * ow) != 0:
        raise ValueError(
            f"psroi_pool needs channels ({C}) divisible by "
            f"output_size^2 ({oh * ow})")
    bn = np.asarray(_arr(boxes_num))
    box_image = jnp.asarray(np.repeat(np.arange(len(bn)), bn).astype(np.int32))
    return apply_op(_psroi_pool, x, boxes, box_image,
                    output_size=(oh, ow), spatial_scale=float(spatial_scale),
                    out_channels=C // (oh * ow))


# -- deformable conv --------------------------------------------------------

def _deform_conv2d(x, offset, mask, weight, bias, stride, padding, dilation,
                   deformable_groups, groups):
    N, Cin, H, W = x.shape
    Cout, Cin_g, kh, kw = weight.shape
    sh, sw = stride
    ph, pw = padding
    dh, dw = dilation
    Ho = (H + 2 * ph - (dh * (kh - 1) + 1)) // sh + 1
    Wo = (W + 2 * pw - (dw * (kw - 1) + 1)) // sw + 1
    K = kh * kw
    dg = deformable_groups
    cpg = Cin // dg  # channels per deformable group

    # base sampling grid [K, Ho, Wo]
    oy = jnp.arange(Ho) * sh - ph
    ox = jnp.arange(Wo) * sw - pw
    ky, kx = jnp.meshgrid(jnp.arange(kh) * dh, jnp.arange(kw) * dw,
                          indexing="ij")
    base_y = ky.reshape(K, 1, 1) + oy[None, :, None]
    base_x = kx.reshape(K, 1, 1) + ox[None, None, :]

    def one_image(img, off, msk):
        # off [2*dg*K, Ho, Wo] layout (dg, K, 2:(y,x)) per reference
        off = off.reshape(dg, K, 2, Ho, Wo)
        cols = []
        for g in range(dg):
            ys = base_y + off[g, :, 0]
            xs = base_x + off[g, :, 1]
            sampled = _bilinear_batch(img[g * cpg:(g + 1) * cpg], ys, xs,
                                      bounds="zero_corner")
            if msk is not None:
                sampled = sampled * msk.reshape(dg, K, Ho, Wo)[g][None]
            cols.append(sampled)                     # [cpg, K, Ho, Wo]
        return jnp.concatenate(cols, axis=0)         # [Cin, K, Ho, Wo]

    cols = jax.vmap(one_image)(x, offset,
                               mask if mask is not None else
                               jnp.ones((N, dg * K, Ho, Wo), x.dtype))
    # grouped conv as einsum over (Cin_g, K)
    cols = cols.reshape(N, groups, Cin_g, K, Ho, Wo)
    wg = weight.reshape(groups, Cout // groups, Cin_g, kh * kw)
    out = jnp.einsum("ngckyz,gock->ngoyz", cols, wg)
    out = out.reshape(N, Cout, Ho, Wo)
    if bias is not None:
        out = out + bias.reshape(1, Cout, 1, 1)
    return out


def deform_conv2d(x, offset, weight, bias=None, stride=1, padding=0,
                  dilation=1, deformable_groups=1, groups=1, mask=None,
                  name=None):
    """Deformable conv v1/v2 (reference operators/deformable_conv_op.h
    modulated im2col + GEMM): per-kernel-point learned (dy, dx) offsets,
    optional modulation mask (v2). offset [N, 2*dg*kh*kw, Ho, Wo] with
    (y, x) interleaved per point; mask [N, dg*kh*kw, Ho, Wo]."""
    def norm2(v):
        return (v, v) if isinstance(v, int) else tuple(v)

    args = [x, offset, weight] + ([bias] if bias is not None else []) + \
        ([mask] if mask is not None else [])

    def impl(x_, off_, w_, *rest):
        b_ = rest[0] if bias is not None else None
        m_ = rest[-1] if mask is not None else None
        return _deform_conv2d(x_, off_, m_, w_, b_, norm2(stride),
                              norm2(padding), norm2(dilation),
                              int(deformable_groups), int(groups))

    return apply_op(impl, *args)


# -- detection batch 2 ------------------------------------------------------

def iou_similarity(x, y, box_normalized=True, name=None):
    """Pairwise IoU [N, M] (reference detection/iou_similarity_op.h;
    box_normalized=False adds the +1 pixel convention)."""
    a = _arr(x).astype(jnp.float32)
    b = _arr(y).astype(jnp.float32)
    off = 0.0 if box_normalized else 1.0
    area1 = (a[:, 2] - a[:, 0] + off) * (a[:, 3] - a[:, 1] + off)
    area2 = (b[:, 2] - b[:, 0] + off) * (b[:, 3] - b[:, 1] + off)
    lt = jnp.maximum(a[:, None, :2], b[None, :, :2])
    rb = jnp.minimum(a[:, None, 2:], b[None, :, 2:])
    wh = jnp.clip(rb - lt + off, 0)
    inter = wh[..., 0] * wh[..., 1]
    return Tensor(inter / (area1[:, None] + area2[None, :] - inter + 1e-10))


def _box_clip(boxes, im_info, is_scale, pixel_offset):
    off = 1.0 if pixel_offset else 0.0
    h, w, scale = im_info[0], im_info[1], im_info[2]
    im_w = jnp.round(w / scale) if is_scale else w
    im_h = jnp.round(h / scale) if is_scale else h
    x_hi, y_hi = im_w - off, im_h - off
    x1 = jnp.clip(boxes[..., 0], 0, x_hi)
    y1 = jnp.clip(boxes[..., 1], 0, y_hi)
    x2 = jnp.clip(boxes[..., 2], 0, x_hi)
    y2 = jnp.clip(boxes[..., 3], 0, y_hi)
    return jnp.stack([x1, y1, x2, y2], axis=-1)


def box_clip(input, im_info, name=None):  # noqa: A002
    """Clip boxes to the image (reference detection/box_clip_op over
    bbox_util.h ClipTiledBoxes): input [N, 4] (or [B, N, 4] with im_info
    [B, 3]); im_info rows are (height, width, scale) — bounds are
    round(size/scale) - 1."""
    b = _arr(input)
    info = _arr(im_info).astype(jnp.float32)
    if b.ndim == 3:
        return apply_op(
            lambda bb, ii: jax.vmap(
                lambda r, i: _box_clip(r, i, True, True))(bb, ii),
            input, im_info)
    return apply_op(lambda bb, ii: _box_clip(bb, ii, True, True),
                    input, im_info if info.ndim == 1 else Tensor(info[0]))


def anchor_generator(input, anchor_sizes, aspect_ratios=(1.0,),
                     variances=(0.1, 0.1, 0.2, 0.2), stride=(16.0, 16.0),
                     offset=0.5, name=None):
    """Faster R-CNN anchors (reference detection/anchor_generator_op.h:40 —
    same rounding and emission order: aspect_ratios outer, anchor_sizes
    inner, pixel-center convention). Returns (anchors [H,W,A,4],
    variances [H,W,A,4]) in absolute pixels."""
    fh, fw = int(input.shape[2]), int(input.shape[3])
    sw, sh = float(stride[0]), float(stride[1])
    rows = []
    for h in range(fh):
        row = []
        for w in range(fw):
            x_ctr = w * sw + offset * (sw - 1)
            y_ctr = h * sh + offset * (sh - 1)
            cell = []
            for ar in aspect_ratios:
                for size in anchor_sizes:
                    area = sw * sh
                    base_w = round(math.sqrt(area / ar))
                    base_h = round(base_w * ar)
                    aw = (size / sw) * base_w
                    ah = (size / sh) * base_h
                    cell.append((x_ctr - 0.5 * (aw - 1),
                                 y_ctr - 0.5 * (ah - 1),
                                 x_ctr + 0.5 * (aw - 1),
                                 y_ctr + 0.5 * (ah - 1)))
            row.append(cell)
        rows.append(row)
    anchors = np.asarray(rows, np.float32)
    var = np.broadcast_to(np.asarray(variances, np.float32),
                          anchors.shape).copy()
    return Tensor(jnp.asarray(anchors)), Tensor(jnp.asarray(var))


def matrix_nms(bboxes, scores, score_threshold, post_threshold=0.0,
               nms_top_k=-1, keep_top_k=-1, use_gaussian=False,
               gaussian_sigma=2.0, background_label=0, normalized=True,
               return_index=False, return_rois_num=True, name=None):
    """Matrix NMS (reference detection/matrix_nms_op.cc NMSMatrix — SOLOv2
    decay: each candidate's score is multiplied by the min over
    higher-scored overlaps of decay(iou, max_iou)). bboxes [N,M,4], scores
    [N,C,M]; returns (out [K,6], rois_num [N][, index])."""
    b = np.asarray(_arr(bboxes), np.float32)
    s = np.asarray(_arr(scores), np.float32)
    N, C, M = s.shape
    off = 0.0 if normalized else 1.0

    def iou(b1, b2):
        a1 = (b1[2] - b1[0] + off) * (b1[3] - b1[1] + off)
        a2 = (b2[2] - b2[0] + off) * (b2[3] - b2[1] + off)
        iw = min(b1[2], b2[2]) - max(b1[0], b2[0]) + off
        ih = min(b1[3], b2[3]) - max(b1[1], b2[1]) + off
        if iw <= 0 or ih <= 0:
            return 0.0
        return iw * ih / (a1 + a2 - iw * ih)

    all_rows, all_idx, per_img = [], [], []
    for n in range(N):
        kept = []  # (decayed_score, label, box_idx)
        for c in range(C):
            if c == background_label:
                continue
            cand = np.where(s[n, c] > score_threshold)[0]
            cand = cand[np.argsort(-s[n, c][cand], kind="stable")]
            if nms_top_k > -1:
                cand = cand[:nms_top_k]
            if not len(cand):
                continue
            iou_mat = np.zeros((len(cand), len(cand)), np.float32)
            iou_max = np.zeros(len(cand), np.float32)
            for i in range(1, len(cand)):
                for j in range(i):
                    iou_mat[i, j] = iou(b[n, cand[i]], b[n, cand[j]])
                iou_max[i] = iou_mat[i, :i].max()
            if s[n, c, cand[0]] > post_threshold:
                kept.append((s[n, c, cand[0]], c, cand[0]))
            for i in range(1, len(cand)):
                decays = []
                for j in range(i):
                    if use_gaussian:
                        d = math.exp((iou_max[j] ** 2 - iou_mat[i, j] ** 2)
                                     * gaussian_sigma)
                    else:
                        d = (1.0 - iou_mat[i, j]) / (1.0 - iou_max[j])
                    decays.append(d)
                ds = min(decays) * s[n, c, cand[i]]
                if ds > post_threshold:
                    kept.append((ds, c, cand[i]))
        kept.sort(key=lambda t: -t[0])
        if keep_top_k > -1:
            kept = kept[:keep_top_k]
        for sc, c, i in kept:
            all_rows.append([float(c), float(sc)] + list(b[n, i]))
            all_idx.append(n * M + i)
        per_img.append(len(kept))
    out = (np.asarray(all_rows, np.float32) if all_rows
           else np.zeros((0, 6), np.float32))
    res = [Tensor(jnp.asarray(out))]
    if return_rois_num:
        res.append(Tensor(jnp.asarray(np.asarray(per_img, np.int32))))
    if return_index:
        res.append(Tensor(jnp.asarray(np.asarray(all_idx, np.int64))))
    return tuple(res) if len(res) > 1 else res[0]


# -- RPN / FPN proposal pipeline --------------------------------------------

_BBOX_CLIP = math.log(1000.0 / 16.0)


def generate_proposals(scores, bbox_deltas, img_size, anchors, variances=None,
                       pre_nms_top_n=6000, post_nms_top_n=1000,
                       nms_thresh=0.5, min_size=0.1, eta=1.0,
                       return_rois_num=True, name=None):
    """RPN proposal generation (reference
    detection/generate_proposals_op.cc ProposalForOneImage + bbox_util.h
    BoxCoder/FilterBoxes): per image, take pre_nms_top_n scores, decode
    deltas against anchors (variance-scaled, w/h delta clipped at
    log(1000/16)), clip to the image (pixel convention), drop boxes
    smaller than min_size (scale-corrected) or with centers outside, NMS,
    keep post_nms_top_n. Host semantics (dynamic output), like the
    reference CPU kernel.

    scores [N,A,H,W]; bbox_deltas [N,4A,H,W]; img_size/im_info [N,3]
    (h, w, scale); anchors [H,W,A,4] or [M,4]; variances same shape as
    anchors or None. Returns (rois [K,4], roi_probs [K,1], rois_num [N]).
    """
    s = np.asarray(_arr(scores), np.float32)
    d = np.asarray(_arr(bbox_deltas), np.float32)
    info = np.asarray(_arr(img_size), np.float32)
    anc = np.asarray(_arr(anchors), np.float32).reshape(-1, 4)
    var = (np.asarray(_arr(variances), np.float32).reshape(-1, 4)
           if variances is not None else None)
    N, A, H, W = s.shape

    def decode(anchor, vr, delta):
        off = 1.0
        aw = anchor[:, 2] - anchor[:, 0] + off
        ah = anchor[:, 3] - anchor[:, 1] + off
        acx = anchor[:, 0] + 0.5 * aw
        acy = anchor[:, 1] + 0.5 * ah
        dx, dy, dw, dh = delta[:, 0], delta[:, 1], delta[:, 2], delta[:, 3]
        if vr is not None:
            dx, dy = vr[:, 0] * dx, vr[:, 1] * dy
            dw, dh = vr[:, 2] * dw, vr[:, 3] * dh
        cx = dx * aw + acx
        cy = dy * ah + acy
        w = np.exp(np.minimum(dw, _BBOX_CLIP)) * aw
        h = np.exp(np.minimum(dh, _BBOX_CLIP)) * ah
        return np.stack([cx - w / 2, cy - h / 2,
                         cx + w / 2 - off, cy + h / 2 - off], axis=1)

    all_rois, all_probs, per_img = [], [], []
    for n in range(N):
        # [A,H,W] -> [H,W,A] flat, matching the anchors' [H,W,A,4] order
        sc = s[n].transpose(1, 2, 0).reshape(-1)
        dl = d[n].reshape(A, 4, H, W).transpose(2, 3, 0, 1).reshape(-1, 4)
        order = np.argsort(-sc, kind="stable")
        if 0 < pre_nms_top_n < len(order):
            order = order[:pre_nms_top_n]
        props = decode(anc[order], var[order] if var is not None else None,
                       dl[order])
        im_h, im_w, im_scale = info[n]
        props[:, 0] = np.clip(props[:, 0], 0, im_w - 1)
        props[:, 1] = np.clip(props[:, 1], 0, im_h - 1)
        props[:, 2] = np.clip(props[:, 2], 0, im_w - 1)
        props[:, 3] = np.clip(props[:, 3], 0, im_h - 1)
        # FilterBoxes (bbox_util.h:190): min_size in ORIGINAL image scale,
        # centers inside the image
        ms = max(float(min_size), 1.0)
        ws = props[:, 2] - props[:, 0] + 1
        hs = props[:, 3] - props[:, 1] + 1
        ws_orig = (props[:, 2] - props[:, 0]) / im_scale + 1
        hs_orig = (props[:, 3] - props[:, 1]) / im_scale + 1
        cx = props[:, 0] + ws / 2
        cy = props[:, 1] + hs / 2
        keep = np.where((ws_orig >= ms) & (hs_orig >= ms)
                        & (cx <= im_w) & (cy <= im_h))[0]
        props, psc = props[keep], sc[order][keep]
        # NMS with eta-adaptive threshold (nms_util.h NMSFast), rejection
        # vectorized per round so pre_nms_top_n=6000 stays tractable
        areas = (props[:, 2] - props[:, 0] + 1) * (props[:, 3] - props[:, 1] + 1)
        alive_idx = np.arange(len(props))
        sel = []
        thr = nms_thresh
        while alive_idx.size:
            i = alive_idx[0]
            sel.append(i)
            if 0 < post_nms_top_n <= len(sel):
                break
            rest = alive_idx[1:]
            iw = (np.minimum(props[i, 2], props[rest, 2])
                  - np.maximum(props[i, 0], props[rest, 0]) + 1)
            ih = (np.minimum(props[i, 3], props[rest, 3])
                  - np.maximum(props[i, 1], props[rest, 1]) + 1)
            inter = np.clip(iw, 0, None) * np.clip(ih, 0, None)
            iou = inter / (areas[i] + areas[rest] - inter)
            alive_idx = rest[iou <= thr]
            if eta < 1.0 and thr > 0.5:
                thr *= eta
        all_rois.append(props[sel])
        all_probs.append(psc[sel])
        per_img.append(len(sel))
    rois = (np.concatenate(all_rois) if all_rois
            else np.zeros((0, 4), np.float32))
    probs = (np.concatenate(all_probs)[:, None] if all_probs
             else np.zeros((0, 1), np.float32))
    out = (Tensor(jnp.asarray(rois)), Tensor(jnp.asarray(probs)))
    if return_rois_num:
        out += (Tensor(jnp.asarray(np.asarray(per_img, np.int32))),)
    return out


def distribute_fpn_proposals(fpn_rois, min_level, max_level, refer_level,
                             refer_scale, pixel_offset=True, rois_num=None,
                             name=None):
    """Route RoIs to FPN levels (reference
    detection/distribute_fpn_proposals_op.h:113: tgt_lvl =
    floor(log2(sqrt(area)/refer_scale + 1e-6) + refer_level), clipped).
    Returns (multi_rois list low→high level, restore_index [R,1]
    mapping concat(multi_rois) rows back to input order[, rois_num list])."""
    r = np.asarray(_arr(fpn_rois), np.float32)
    off = 1.0 if pixel_offset else 0.0
    w = r[:, 2] - r[:, 0] + off
    h = r[:, 3] - r[:, 1] + off
    area = np.where((w > 0) & (h > 0), w * h, 0.0)
    lvl = np.floor(np.log2(np.sqrt(area) / refer_scale + 1e-6) + refer_level)
    lvl = np.clip(lvl, min_level, max_level).astype(np.int64)
    multi, order = [], []
    for L in range(min_level, max_level + 1):
        idx = np.where(lvl == L)[0]
        multi.append(Tensor(jnp.asarray(r[idx])))
        order.append(idx)
    order = np.concatenate(order) if order else np.zeros((0,), np.int64)
    restore = np.empty_like(order)
    restore[order] = np.arange(len(order))
    out = ([t for t in multi],
           Tensor(jnp.asarray(restore[:, None].astype(np.int32))))
    if rois_num is not None:
        # per-level PER-IMAGE counts [N], matching the reference's
        # MultiLevelRoIsNum output (distribute_fpn_proposals_op.h:180)
        rn = np.asarray(_arr(rois_num), np.int64).reshape(-1)
        img_id = np.repeat(np.arange(len(rn)), rn)
        counts = [Tensor(jnp.asarray(np.bincount(
            img_id[lvl == L], minlength=len(rn)).astype(np.int32)))
            for L in range(min_level, max_level + 1)]
        return out + (counts,)
    return out


def collect_fpn_proposals(multi_rois, multi_scores, min_level, max_level,
                          post_nms_top_n, rois_num_per_level=None, name=None):
    """Merge per-level RoIs and keep the post_nms_top_n best by score
    (reference detection/collect_fpn_proposals_op.h)."""
    rois = np.concatenate([np.asarray(_arr(r), np.float32)
                           for r in multi_rois]) \
        if multi_rois else np.zeros((0, 4), np.float32)
    scores = np.concatenate([np.asarray(_arr(s), np.float32).reshape(-1)
                             for s in multi_scores]) \
        if multi_scores else np.zeros((0,), np.float32)
    order = np.argsort(-scores, kind="stable")[:post_nms_top_n]
    if rois_num_per_level is None:
        return Tensor(jnp.asarray(rois[order]))
    # reference collect_fpn_proposals_op.h: select top-K globally by score,
    # then regroup by image (stable, so within-image score order is kept)
    # and also emit per-image counts.
    per_level = [np.asarray(_arr(c), np.int64).reshape(-1)
                 for c in rois_num_per_level]
    n_img = len(per_level[0]) if per_level else 0
    img_id = np.concatenate([np.repeat(np.arange(len(c)), c)
                             for c in per_level]) \
        if per_level else np.zeros((0,), np.int64)
    sel_img = img_id[order]
    regroup = np.argsort(sel_img, kind="stable")
    out_rois = rois[order][regroup]
    rois_num = np.bincount(sel_img, minlength=n_img).astype(np.int32)
    return (Tensor(jnp.asarray(out_rois)),
            Tensor(jnp.asarray(rois_num)))


# -- YOLOv3 loss + layer wrappers + image IO --------------------------------

def _bce_logits_soft(x, t):
    # SigmoidCrossEntropy (yolov3_loss_op.h:35) with soft targets
    return jnp.maximum(x, 0.0) - x * t + jnp.log1p(jnp.exp(-jnp.abs(x)))


def _yolo_loss_impl(x, gt_box, gt_label, gt_score, anchors, anchor_mask,
                    class_num, ignore_thresh, downsample_ratio,
                    use_label_smooth, scale_x_y):
    N, C, H, W = x.shape
    mask_num = len(anchor_mask)
    an_num = len(anchors) // 2
    input_size = downsample_ratio * H
    anc = jnp.asarray(anchors, jnp.float32).reshape(an_num, 2)
    amask = jnp.asarray(anchor_mask, jnp.int32)
    p = x.reshape(N, mask_num, 5 + class_num, H, W)
    scale, bias = scale_x_y, -0.5 * (scale_x_y - 1.0)

    label_pos, label_neg = 1.0, 0.0
    if use_label_smooth:
        sw = min(1.0 / class_num, 1.0 / 40)
        label_pos, label_neg = 1.0 - sw, sw

    # --- ignore mask: best IoU of each prediction vs any valid gt
    gx = jnp.arange(W, dtype=jnp.float32)[None, :]
    gy = jnp.arange(H, dtype=jnp.float32)[:, None]
    masked_anc = anc[amask]                                    # [m, 2]
    px = (jax.nn.sigmoid(p[:, :, 0]) * scale + bias + gx) / W  # [N,m,H,W]
    py = (jax.nn.sigmoid(p[:, :, 1]) * scale + bias + gy) / H
    pw = jnp.exp(p[:, :, 2]) * masked_anc[:, 0][None, :, None, None] / input_size
    ph = jnp.exp(p[:, :, 3]) * masked_anc[:, 1][None, :, None, None] / input_size

    gt_valid = (gt_box[:, :, 2] > 0) & (gt_box[:, :, 3] > 0)   # [N,B]

    def iou_cwh(x1, y1, w1, h1, x2, y2, w2, h2):
        li = jnp.maximum(x1 - w1 / 2, x2 - w2 / 2)
        ri = jnp.minimum(x1 + w1 / 2, x2 + w2 / 2)
        ti = jnp.maximum(y1 - h1 / 2, y2 - h2 / 2)
        bi = jnp.minimum(y1 + h1 / 2, y2 + h2 / 2)
        inter = jnp.maximum(ri - li, 0) * jnp.maximum(bi - ti, 0)
        return inter / (w1 * h1 + w2 * h2 - inter + 1e-10)

    ious = iou_cwh(px[..., None], py[..., None], pw[..., None], ph[..., None],
                   gt_box[:, None, None, None, :, 0],
                   gt_box[:, None, None, None, :, 1],
                   gt_box[:, None, None, None, :, 2],
                   gt_box[:, None, None, None, :, 3])          # [N,m,H,W,B]
    ious = jnp.where(gt_valid[:, None, None, None, :], ious, 0.0)
    best_iou = jnp.max(ious, axis=-1)
    obj = jnp.where(best_iou > ignore_thresh, -1.0, 0.0)       # [N,m,H,W]

    # --- per-gt best anchor (wh IoU at origin) over ALL anchors
    aw = anc[:, 0] / input_size
    ah = anc[:, 1] / input_size
    inter = (jnp.minimum(gt_box[..., 2][..., None], aw)
             * jnp.minimum(gt_box[..., 3][..., None], ah))     # [N,B,an]
    a_iou = inter / (gt_box[..., 2][..., None] * gt_box[..., 3][..., None]
                     + aw * ah - inter + 1e-10)
    best_n = jnp.argmax(a_iou, axis=-1)                        # [N,B]
    mask_idx = jnp.argmax(best_n[..., None] == amask, axis=-1)
    in_mask = jnp.any(best_n[..., None] == amask, axis=-1) & gt_valid

    gi = jnp.clip((gt_box[..., 0] * W).astype(jnp.int32), 0, W - 1)
    gj = jnp.clip((gt_box[..., 1] * H).astype(jnp.int32), 0, H - 1)

    # gather responsible predictions per gt: [N,B,5+cls]
    ni = jnp.arange(N)[:, None]
    pred_at = p[ni, mask_idx, :, gj, gi]
    tx = gt_box[..., 0] * W - gi
    ty = gt_box[..., 1] * H - gj
    tw = jnp.log(jnp.maximum(gt_box[..., 2], 1e-9) * input_size
                 / anc[best_n, 0])
    th = jnp.log(jnp.maximum(gt_box[..., 3], 1e-9) * input_size
                 / anc[best_n, 1])
    loc_scale = (2.0 - gt_box[..., 2] * gt_box[..., 3]) * gt_score
    loc = (_bce_logits_soft(pred_at[..., 0], tx)
           + _bce_logits_soft(pred_at[..., 1], ty)
           + jnp.abs(pred_at[..., 2] - tw)
           + jnp.abs(pred_at[..., 3] - th)) * loc_scale
    cls_t = jnp.where(jnp.arange(class_num)[None, None, :]
                      == gt_label[..., None], label_pos, label_neg)
    cls = jnp.sum(_bce_logits_soft(pred_at[..., 5:], cls_t), -1) * gt_score
    per_gt = jnp.where(in_mask, loc + cls, 0.0)
    loss = jnp.sum(per_gt, axis=1)                             # [N]

    # positive cells override the ignore mask with the gt score
    flat_obj = obj.reshape(N, -1)
    pos_flat = (mask_idx * H + gj) * W + gi                    # [N,B]
    safe_idx = jnp.where(in_mask, pos_flat, mask_num * H * W)
    grown = jnp.concatenate([flat_obj, jnp.zeros((N, 1))], axis=1)
    grown = grown.at[ni, safe_idx].set(
        jnp.where(in_mask, gt_score, 0.0))
    obj = grown[:, :-1].reshape(N, mask_num, H, W)

    conf = p[:, :, 4]
    obj_loss = jnp.where(
        obj > 1e-5, _bce_logits_soft(conf, 1.0) * obj,
        jnp.where(obj > -0.5, _bce_logits_soft(conf, 0.0), 0.0))
    return loss + jnp.sum(obj_loss, axis=(1, 2, 3))


def yolo_loss(x, gt_box, gt_label, anchors, anchor_mask, class_num,
              ignore_thresh, downsample_ratio, gt_score=None,
              use_label_smooth=True, name=None, scale_x_y=1.0):
    """YOLOv3 loss (reference detection/yolov3_loss_op.h: per-gt best-anchor
    assignment, soft-target sigmoid CE on x/y, L1 on w/h scaled by
    (2 - w*h), objectness with IoU>thresh ignore zone, label smoothing).
    Returns per-image loss [N]."""
    from ..framework.core import Tensor, apply_op

    if gt_score is None:
        gt_score = Tensor(jnp.ones(tuple(gt_label.shape), jnp.float32))
    return apply_op(
        _yolo_loss_impl, x, gt_box, gt_label, gt_score,
        anchors=tuple(int(a) for a in anchors),
        anchor_mask=tuple(int(a) for a in anchor_mask),
        class_num=int(class_num), ignore_thresh=float(ignore_thresh),
        downsample_ratio=int(downsample_ratio),
        use_label_smooth=bool(use_label_smooth),
        scale_x_y=float(scale_x_y), op_name="yolo_loss")


class DeformConv2D(Layer):
    """Deformable conv layer (reference vision/ops.py:626 DeformConv2D)
    over the deform_conv2d functional."""

    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, dilation=1, deformable_groups=1, groups=1,
                 weight_attr=None, bias_attr=None):
        super().__init__()
        k = kernel_size if isinstance(kernel_size, (list, tuple)) \
            else (kernel_size, kernel_size)
        self._attrs = (stride, padding, dilation, deformable_groups, groups)
        from ..nn import initializer as I

        self.weight = self.create_parameter(
            shape=[out_channels, in_channels // groups, k[0], k[1]],
            attr=weight_attr, default_initializer=I.XavierNormal())
        self.bias = self.create_parameter(
            shape=[out_channels], attr=bias_attr, is_bias=True)

    def forward(self, x, offset, mask=None):
        s, p, d, dg, g = self._attrs
        return deform_conv2d(x, offset, self.weight, bias=self.bias,
                             stride=s, padding=p, dilation=d,
                             deformable_groups=dg, groups=g, mask=mask)


class PSRoIPool(Layer):
    """Position-sensitive RoI pooling layer (reference vision/ops.py:978)."""

    def __init__(self, output_size, spatial_scale=1.0):
        super().__init__()
        self.output_size = output_size
        self.spatial_scale = spatial_scale

    def forward(self, x, boxes, boxes_num):
        return psroi_pool(x, boxes, boxes_num, self.output_size,
                          self.spatial_scale)


def read_file(filename, name=None):
    """File bytes as a uint8 tensor (reference vision/ops.py:819)."""
    from ..framework.core import Tensor

    with open(filename, "rb") as f:
        data = f.read()
    return Tensor(jnp.asarray(np.frombuffer(data, np.uint8)))


def decode_jpeg(x, mode="unchanged", name=None):
    """Decode a JPEG byte tensor to CHW uint8 (reference vision/ops.py:864,
    decode_jpeg op over nvjpeg). Host-side decode via PIL — image IO is
    input-pipeline work that belongs on CPU, not the TPU."""
    import io

    from PIL import Image

    from ..framework.core import Tensor

    raw = bytes(np.asarray(x._data if hasattr(x, "_data") else x,
                           np.uint8).tobytes())
    img = Image.open(io.BytesIO(raw))
    if mode == "gray":
        img = img.convert("L")
    elif mode == "rgb":
        img = img.convert("RGB")
    arr = np.asarray(img, np.uint8)
    if arr.ndim == 2:
        arr = arr[None]
    else:
        arr = arr.transpose(2, 0, 1)
    return Tensor(jnp.asarray(arr))


# -- SSD training losses ----------------------------------------------------

def _softmax_ce_rows(logits, labels):
    lse = jax.nn.logsumexp(logits, axis=-1)
    picked = jnp.take_along_axis(logits, labels[..., None], axis=-1)[..., 0]
    return lse - picked


def _ssd_loss_impl(loc, conf, loc_t, conf_t, pos_mask, sel_mask,
                   loc_loss_weight, conf_loss_weight, normalizer):
    d = loc - loc_t
    ad = jnp.abs(d)
    sl1 = jnp.sum(jnp.where(ad < 1.0, 0.5 * d * d, ad - 0.5), axis=-1)
    loc_l = sl1 * pos_mask * loc_loss_weight
    conf_l = _softmax_ce_rows(conf, conf_t) * sel_mask * conf_loss_weight
    out = (loc_l + conf_l) / normalizer
    return out.reshape(-1, 1)


def ssd_loss(location, confidence, gt_box, gt_label, prior_box,
             prior_box_var=None, background_label=0, overlap_threshold=0.5,
             neg_pos_ratio=3.0, neg_overlap=0.5, loc_loss_weight=1.0,
             conf_loss_weight=1.0, match_type="per_prediction",
             mining_type="max_negative", normalize=True, sample_size=None):
    """SSD multibox loss (reference fluid/layers/detection.py:1520 —
    bipartite/per-prediction matching, encode targets against priors,
    max-negative hard mining at neg_pos_ratio, smooth-L1 + softmax CE,
    normalized by the matched count).

    Padded-dense gt convention (README LoD decision): gt_box [N, B, 4]
    with invalid rows w<=0, gt_label [N, B] or [N, B, 1]. location
    [N, M, 4]; confidence [N, M, C]; prior_box [M, 4]. Returns the
    per-prior weighted loss [N*M, 1] (matching the reference's output
    shape), differentiable w.r.t. location/confidence.
    """
    if mining_type != "max_negative":
        raise NotImplementedError("ssd_loss: only max_negative mining")
    from ..framework.core import Tensor, apply_op

    loc_a = np.asarray(_arr(location), np.float32)
    conf_a = np.asarray(_arr(confidence), np.float32)
    gtb = np.asarray(_arr(gt_box), np.float32)
    gtl = np.asarray(_arr(gt_label)).reshape(gtb.shape[0], -1)
    pb = np.asarray(_arr(prior_box), np.float32)
    pbv = (np.asarray(_arr(prior_box_var), np.float32)
           if prior_box_var is not None
           else np.tile(np.asarray([0.1, 0.1, 0.2, 0.2], np.float32),
                        (len(pb), 1)))
    N, M, _ = loc_a.shape

    loc_t = np.zeros((N, M, 4), np.float32)
    conf_t = np.zeros((N, M), np.int64)
    pos_mask = np.zeros((N, M), np.float32)
    sel_mask = np.zeros((N, M), np.float32)
    n_matched = 0
    for n in range(N):
        valid = (gtb[n, :, 2] - gtb[n, :, 0]) > 0
        g = gtb[n][valid]
        gl = gtl[n][valid]
        if len(g) == 0:
            continue
        iou = _np_iou(g, pb)
        match, _dist = bipartite_match(Tensor(jnp.asarray(iou)),
                                       match_type=match_type,
                                       dist_threshold=overlap_threshold)
        match = np.asarray(_arr(match)).reshape(-1)       # [M], -1 unmatched
        pos = match >= 0
        n_pos = int(pos.sum())
        n_matched += n_pos
        if n_pos:
            # elementwise EncodeCenterSize per matched pair, NOT the
            # pairwise grid (shared helper, box_coder_op.h:41 semantics)
            loc_t[n][pos] = _encode_pairs(pb[pos], g[match[pos]], pbv[pos])
            conf_t[n][pos] = gl[match[pos]]
        # hard negative mining by conf loss on the background class
        best_iou = iou.max(axis=0) if len(g) else np.zeros(M)
        neg_cand = (~pos) & (best_iou < neg_overlap)
        z = conf_a[n] - conf_a[n].max(-1, keepdims=True)
        ce_bg = (np.log(np.exp(z).sum(-1))
                 - z[:, background_label])                 # bg CE per prior
        n_neg = int(min(neg_pos_ratio * max(n_pos, 1),
                        neg_cand.sum()))
        if sample_size is not None:
            n_neg = min(n_neg, int(sample_size))
        if n_neg > 0:
            cand_idx = np.where(neg_cand)[0]
            hard = cand_idx[np.argsort(-ce_bg[cand_idx])[:n_neg]]
            sel_mask[n][hard] = 1.0
            conf_t[n][hard] = background_label
        sel_mask[n][pos] = 1.0
        pos_mask[n][pos] = 1.0

    normalizer = float(n_matched) if (normalize and n_matched) else 1.0
    return apply_op(
        _ssd_loss_impl, location, confidence,
        Tensor(jnp.asarray(loc_t)), Tensor(jnp.asarray(conf_t)),
        Tensor(jnp.asarray(pos_mask)), Tensor(jnp.asarray(sel_mask)),
        loc_loss_weight=float(loc_loss_weight),
        conf_loss_weight=float(conf_loss_weight), normalizer=normalizer,
        op_name="ssd_loss")


def target_assign(input, matched_indices, negative_indices=None,  # noqa: A002
                  mismatch_value=0, name=None):
    """Assign per-column targets by match indices (reference
    detection/target_assign_op.h): out[j] = input[matched[j]] where
    matched[j] >= 0 else mismatch_value; weight 1 for matched (and listed
    negatives), 0 otherwise. input [B, 4] rows (padded-dense gt rows),
    matched_indices [1, M] or [M]."""
    from ..framework.core import Tensor

    rows = np.asarray(_arr(input))
    match = np.asarray(_arr(matched_indices)).reshape(-1)
    M = len(match)
    feat = rows.shape[-1] if rows.ndim > 1 else 1
    out = np.full((M, feat), mismatch_value, rows.dtype)
    w = np.zeros((M, 1), np.float32)
    pos = match >= 0
    out[pos] = rows.reshape(-1, feat)[match[pos]]
    w[pos] = 1.0
    if negative_indices is not None:
        neg = np.asarray(_arr(negative_indices)).reshape(-1).astype(np.int64)
        w[neg] = 1.0
    return Tensor(jnp.asarray(out)), Tensor(jnp.asarray(w))


def density_prior_box(input, image, densities, fixed_sizes, fixed_ratios,  # noqa: A002
                      variance=(0.1, 0.1, 0.2, 0.2), clip=False,
                      steps=(0.0, 0.0), offset=0.5, flatten_to_2d=False,
                      name=None):
    """Density prior boxes (reference detection/density_prior_box_op.h):
    per cell, for each (density, fixed_size, fixed_ratio) emit a density x
    density shifted grid of boxes of size fixed_size*sqrt(ratio)."""
    from ..framework.core import Tensor

    feat = np.asarray(_arr(input))
    img = np.asarray(_arr(image))
    H, W = feat.shape[2], feat.shape[3]
    img_h, img_w = img.shape[2], img.shape[3]
    step_h = steps[1] if steps[1] else img_h / H
    step_w = steps[0] if steps[0] else img_w / W
    boxes = []
    for y in range(H):
        for x in range(W):
            cx = (x + offset) * step_w
            cy = (y + offset) * step_h
            cell = []
            step_average = int((step_w + step_h) * 0.5)  # density_prior_box_op.h
            for dens, fs in zip(densities, fixed_sizes):
                for ratio in fixed_ratios:
                    bw = fs * math.sqrt(ratio)
                    bh = fs / math.sqrt(ratio)
                    shift = int(step_average / dens)
                    for dy in range(dens):
                        for dx in range(dens):
                            ccx = (cx - step_average / 2.0 + shift / 2.0
                                   + dx * shift)
                            ccy = (cy - step_average / 2.0 + shift / 2.0
                                   + dy * shift)
                            cell.append([(ccx - bw / 2.0) / img_w,
                                         (ccy - bh / 2.0) / img_h,
                                         (ccx + bw / 2.0) / img_w,
                                         (ccy + bh / 2.0) / img_h])
            boxes.append(cell)
    out = np.asarray(boxes, np.float32).reshape(H, W, -1, 4)
    if clip:
        out = np.clip(out, 0.0, 1.0)
    nprior = out.shape[2]
    var = np.tile(np.asarray(variance, np.float32)[None, None, None, :],
                  (H, W, nprior, 1))
    if flatten_to_2d:
        out = out.reshape(-1, 4)
        var = var.reshape(-1, 4)
    return Tensor(jnp.asarray(out)), Tensor(jnp.asarray(var))


# -- RPN / RCNN training target assignment ----------------------------------

# advancing sampler shared by the assign ops: the reference draws a NEW
# random subset each training step; a per-call fixed seed would freeze it.
# Seeded from the framework global seed and folded with the distributed
# rank so data-parallel workers and reseeded runs decorrelate while
# staying reproducible under paddle.seed (ADVICE r4).
_DET_RNG_STATE = {"key": None, "rng": None}


def _det_rng():
    from ..framework.random import _global_rng
    try:
        from ..distributed.env import get_rank
        rank = get_rank()
    except Exception:  # noqa: BLE001 — env without launch wiring
        rank = 0
    # seed_epoch distinguishes two paddle.seed(k) calls with the SAME k:
    # each reseed must restart the sampling stream (reproducibility means
    # seed(7)-run-A == seed(7)-run-B, not run-B continuing run-A's draws)
    key = (_global_rng._seed, getattr(_global_rng, "seed_epoch", 0), rank)
    if _DET_RNG_STATE["key"] != key:
        _DET_RNG_STATE["key"] = key
        _DET_RNG_STATE["rng"] = np.random.default_rng(
            np.random.SeedSequence(spawn_key=(rank,), entropy=key[0]))
    return _DET_RNG_STATE["rng"]


def _np_iou_off(a, b, off):
    """Pairwise IoU with the unnormalized +off pixel convention."""
    lt = np.maximum(a[:, None, :2], b[None, :, :2])
    rb = np.minimum(a[:, None, 2:], b[None, :, 2:])
    wh = np.clip(rb - lt + off, 0, None)
    inter = wh[..., 0] * wh[..., 1]
    ar_a = (a[:, 2] - a[:, 0] + off) * (a[:, 3] - a[:, 1] + off)
    ar_b = (b[:, 2] - b[:, 0] + off) * (b[:, 3] - b[:, 1] + off)
    return inter / np.maximum(ar_a[:, None] + ar_b[None, :] - inter, 1e-10)


def _np_iou(a, b):
    """Pairwise IoU of [n,4] x [m,4] normalized/absolute corner boxes."""
    lt = np.maximum(a[:, None, :2], b[None, :, :2])
    rb = np.minimum(a[:, None, 2:], b[None, :, 2:])
    wh = np.clip(rb - lt, 0, None)
    inter = wh[..., 0] * wh[..., 1]
    ar_a = (a[:, 2] - a[:, 0]) * (a[:, 3] - a[:, 1])
    ar_b = (b[:, 2] - b[:, 0]) * (b[:, 3] - b[:, 1])
    return inter / np.maximum(ar_a[:, None] + ar_b[None, :] - inter, 1e-10)


def _encode_pairs(priors, gts, var):
    pw = priors[:, 2] - priors[:, 0]
    ph = priors[:, 3] - priors[:, 1]
    pcx = (priors[:, 0] + priors[:, 2]) / 2
    pcy = (priors[:, 1] + priors[:, 3]) / 2
    gw = gts[:, 2] - gts[:, 0]
    gh = gts[:, 3] - gts[:, 1]
    gcx = (gts[:, 0] + gts[:, 2]) / 2
    gcy = (gts[:, 1] + gts[:, 3]) / 2
    return np.stack(
        [(gcx - pcx) / pw / var[:, 0], (gcy - pcy) / ph / var[:, 1],
         np.log(np.maximum(gw / pw, 1e-10)) / var[:, 2],
         np.log(np.maximum(gh / ph, 1e-10)) / var[:, 3]], axis=1)


def rpn_target_assign(bbox_pred, cls_logits, anchor_box, anchor_var,
                      gt_boxes, is_crowd, im_info,
                      rpn_batch_size_per_im=256, rpn_straddle_thresh=0.0,
                      rpn_fg_fraction=0.5, rpn_positive_overlap=0.7,
                      rpn_negative_overlap=0.3, use_random=True):
    """RPN anchor sampling (reference detection/rpn_target_assign_op.cc):
    straddle filter, force-match each gt's best anchor, IoU thresholds,
    sample rpn_batch_size_per_im at rpn_fg_fraction. Host-side sampling
    (data-dependent output size) like the reference CPU kernel.

    Padded-dense gts (rows with w<=0 invalid). Returns (score_pred,
    loc_pred, score_target, loc_target, bbox_inside_weight) gathered over
    the sampled anchors, concatenated across the batch.
    """
    from ..framework.core import Tensor

    bp = np.asarray(_arr(bbox_pred), np.float32)
    cl = np.asarray(_arr(cls_logits), np.float32)
    anchors = np.asarray(_arr(anchor_box), np.float32).reshape(-1, 4)
    avar = np.asarray(_arr(anchor_var), np.float32).reshape(-1, 4)
    gtb = np.asarray(_arr(gt_boxes), np.float32)
    crowd = (np.asarray(_arr(is_crowd)).reshape(gtb.shape[0], -1)
             if is_crowd is not None else np.zeros(gtb.shape[:2], np.int64))
    info = np.asarray(_arr(im_info), np.float32)
    N = bp.shape[0]
    rng = _det_rng()

    sp, lp, st, lt, iw = [], [], [], [], []
    for n in range(N):
        im_h, im_w = float(info[n, 0]), float(info[n, 1])
        if rpn_straddle_thresh >= 0:
            inside = ((anchors[:, 0] >= -rpn_straddle_thresh)
                      & (anchors[:, 1] >= -rpn_straddle_thresh)
                      & (anchors[:, 2] < im_w + rpn_straddle_thresh)
                      & (anchors[:, 3] < im_h + rpn_straddle_thresh))
        else:
            inside = np.ones(len(anchors), bool)
        idx_in = np.where(inside)[0]
        valid = ((gtb[n, :, 2] - gtb[n, :, 0]) > 0) & (crowd[n] == 0)
        g = gtb[n][valid]
        if len(idx_in) == 0:
            continue
        if len(g):
            iou = _np_iou(anchors[idx_in], g)          # [A, G]
            max_iou = iou.max(axis=1)
            argmax_g = iou.argmax(axis=1)
            labels = -np.ones(len(idx_in), np.int64)
            labels[max_iou < rpn_negative_overlap] = 0
            # force-match: each gt's best anchor is positive
            labels[iou.argmax(axis=0)] = 1
            labels[max_iou >= rpn_positive_overlap] = 1
        else:
            # negative-only image: all inside anchors are background and
            # still contribute sampled negatives (the reference assigns
            # background everywhere rather than skipping the image)
            labels = np.zeros(len(idx_in), np.int64)
            argmax_g = np.zeros(len(idx_in), np.int64)

        fg_idx = np.where(labels == 1)[0]
        bg_idx = np.where(labels == 0)[0]
        n_fg = int(min(len(fg_idx), rpn_fg_fraction * rpn_batch_size_per_im))
        if len(fg_idx) > n_fg:
            fg_idx = rng.permutation(fg_idx)[:n_fg] if use_random \
                else fg_idx[:n_fg]
        n_bg = int(min(len(bg_idx), rpn_batch_size_per_im - n_fg))
        if len(bg_idx) > n_bg:
            bg_idx = rng.permutation(bg_idx)[:n_bg] if use_random \
                else bg_idx[:n_bg]

        sel = np.concatenate([fg_idx, bg_idx])
        gidx = idx_in[sel]
        sp.append(cl[n].reshape(-1)[gidx])
        lp.append(bp[n].reshape(-1, 4)[gidx])
        st.append(np.concatenate([np.ones(len(fg_idx), np.int32),
                                  np.zeros(len(bg_idx), np.int32)]))
        tgt = np.zeros((len(sel), 4), np.float32)
        if len(fg_idx):
            fa = idx_in[fg_idx]
            tgt[: len(fg_idx)] = _encode_pairs(
                anchors[fa], g[argmax_g[fg_idx]], avar[fa])
        lt.append(tgt)
        w = np.zeros((len(sel), 4), np.float32)
        w[: len(fg_idx)] = 1.0
        iw.append(w)

    cat = (lambda xs, sh: np.concatenate(xs)
           if xs else np.zeros(sh, np.float32))
    return (Tensor(jnp.asarray(cat(sp, (0,))[:, None])),
            Tensor(jnp.asarray(cat(lp, (0, 4)))),
            Tensor(jnp.asarray(cat(st, (0,)).astype(np.int32)[:, None])),
            Tensor(jnp.asarray(cat(lt, (0, 4)))),
            Tensor(jnp.asarray(cat(iw, (0, 4)))))


def generate_proposal_labels(rpn_rois, gt_classes, is_crowd, gt_boxes,
                             im_info, batch_size_per_im=256,
                             fg_fraction=0.25, fg_thresh=0.25,
                             bg_thresh_hi=0.5, bg_thresh_lo=0.0,
                             bbox_reg_weights=(0.1, 0.1, 0.2, 0.2),
                             class_nums=None, use_random=True,
                             is_cls_agnostic=False, is_cascade_rcnn=False,
                             *, rois_num=None):
    """RCNN proposal sampling (reference
    detection/generate_proposal_labels_op.cc SampleRoisForOneImage):
    append gts to rois, split fg (iou>=fg_thresh) / bg
    (bg_thresh_lo<=iou<bg_thresh_hi), sample at fg_fraction, emit
    per-class box targets. rois are grouped per image via ``rois_num``
    (the padded-dense stand-in for the reference's LoD).

    Returns (rois, labels_int32, bbox_targets, bbox_inside_weights,
    bbox_outside_weights, rois_num_out).
    """
    from ..framework.core import Tensor

    rois = np.asarray(_arr(rpn_rois), np.float32)
    rn = (np.asarray(_arr(rois_num)).reshape(-1).astype(np.int64)
          if rois_num is not None else np.asarray([len(rois)], np.int64))
    gtb = np.asarray(_arr(gt_boxes), np.float32)
    gtc = np.asarray(_arr(gt_classes)).reshape(gtb.shape[0], -1)
    crowd = (np.asarray(_arr(is_crowd)).reshape(gtb.shape[0], -1)
             if is_crowd is not None else np.zeros(gtb.shape[:2], np.int64))
    C = int(class_nums) if class_nums else int(gtc.max()) + 1
    wts = np.asarray(bbox_reg_weights, np.float32)
    rng = _det_rng()

    info = np.asarray(_arr(im_info), np.float32)
    out_rois, out_lab, out_tgt, out_in, out_num = [], [], [], [], []
    off = 0
    for n in range(len(rn)):
        r = rois[off: off + int(rn[n])]
        off += int(rn[n])
        # reference op maps rpn_rois back to the ORIGINAL image frame
        # (divides by im_info[2]) so they match the gt coordinates
        scale = float(info[n, 2]) if info.shape[1] > 2 else 1.0
        if scale != 1.0:
            r = r / scale
        valid = ((gtb[n, :, 2] - gtb[n, :, 0]) > 0) & (crowd[n] == 0)
        g = gtb[n][valid]
        gcls = gtc[n][valid]
        cand = np.concatenate([r, g]) if len(g) and not is_cascade_rcnn \
            else r
        if len(cand) == 0 or len(g) == 0:
            out_num.append(0)
            continue
        iou = _np_iou(cand, g)
        max_iou = iou.max(axis=1)
        gt_of = iou.argmax(axis=1)
        fg = np.where(max_iou >= fg_thresh)[0]
        bg = np.where((max_iou < bg_thresh_hi)
                      & (max_iou >= bg_thresh_lo))[0]
        n_fg = int(min(len(fg), fg_fraction * batch_size_per_im))
        if len(fg) > n_fg:
            fg = rng.permutation(fg)[:n_fg] if use_random else fg[:n_fg]
        n_bg = int(min(len(bg), batch_size_per_im - n_fg))
        if len(bg) > n_bg:
            bg = rng.permutation(bg)[:n_bg] if use_random else bg[:n_bg]
        sel = np.concatenate([fg, bg]).astype(np.int64)
        labels = np.concatenate([gcls[gt_of[fg]],
                                 np.zeros(len(bg), np.int64)])
        enc = np.zeros((len(sel), 4), np.float32)
        if len(fg):
            # reference BoxToDelta divides each delta BY its weight
            # (0.1 -> delta*10): _encode_pairs' var IS that weight
            enc[: len(fg)] = _encode_pairs(
                cand[fg], g[gt_of[fg]], np.tile(wts, (len(fg), 1)))
        ncls = 1 if is_cls_agnostic else C
        tgt = np.zeros((len(sel), 4 * ncls), np.float32)
        inw = np.zeros_like(tgt)
        for i in range(len(fg)):
            c = 0 if is_cls_agnostic else int(labels[i])
            tgt[i, 4 * c: 4 * c + 4] = enc[i]
            inw[i, 4 * c: 4 * c + 4] = 1.0
        out_rois.append(cand[sel])
        out_lab.append(labels)
        out_tgt.append(tgt)
        out_in.append(inw)
        out_num.append(len(sel))

    ncls = 1 if is_cls_agnostic else C
    cat = (lambda xs, sh: np.concatenate(xs)
           if xs else np.zeros(sh, np.float32))
    tgt_all = cat(out_tgt, (0, 4 * ncls))
    inw_all = cat(out_in, (0, 4 * ncls))
    outs = (Tensor(jnp.asarray(cat(out_rois, (0, 4)))),
            Tensor(jnp.asarray(cat(out_lab, (0,)).astype(np.int32)[:, None])),
            Tensor(jnp.asarray(tgt_all)),
            Tensor(jnp.asarray(inw_all)),
            Tensor(jnp.asarray(inw_all.copy())))
    if rois_num is None:
        # the reference's 5-output contract (fluid positional unpacking)
        return outs
    return outs + (Tensor(jnp.asarray(np.asarray(out_num, np.int32))),)


def retinanet_target_assign(bbox_pred, cls_logits, anchor_box, anchor_var,
                            gt_boxes, gt_labels, is_crowd, im_info,
                            num_classes=1, positive_overlap=0.5,
                            negative_overlap=0.4):
    """RetinaNet anchor assignment (reference
    detection/retinanet_target_assign): like rpn_target_assign but with NO
    subsampling (focal loss consumes every anchor), per-class one-hot
    score targets (-1 = ignore band), and the foreground count output.

    Returns (score_pred [K, num_classes], loc_pred, score_target [K, 1],
    loc_target, bbox_inside_weight, fg_num [1, 1])."""
    from ..framework.core import Tensor

    bp = np.asarray(_arr(bbox_pred), np.float32)
    cl = np.asarray(_arr(cls_logits), np.float32)
    anchors = np.asarray(_arr(anchor_box), np.float32).reshape(-1, 4)
    avar = np.asarray(_arr(anchor_var), np.float32).reshape(-1, 4)
    gtb = np.asarray(_arr(gt_boxes), np.float32)
    gtl = np.asarray(_arr(gt_labels)).reshape(gtb.shape[0], -1)
    crowd = (np.asarray(_arr(is_crowd)).reshape(gtb.shape[0], -1)
             if is_crowd is not None else np.zeros(gtb.shape[:2], np.int64))
    N = bp.shape[0]

    sp, lp, st, lt, iw = [], [], [], [], []
    fg_total = 0
    for n in range(N):
        valid = ((gtb[n, :, 2] - gtb[n, :, 0]) > 0) & (crowd[n] == 0)
        g = gtb[n][valid]
        gl = gtl[n][valid]
        if len(g):
            iou = _np_iou(anchors, g)
            max_iou = iou.max(axis=1)
            argmax_g = iou.argmax(axis=1)
            labels = -np.ones(len(anchors), np.int64)  # ignore band
            labels[max_iou < negative_overlap] = 0
            labels[iou.argmax(axis=0)] = 1
            labels[max_iou >= positive_overlap] = 1
        else:
            # negative-only image: every anchor is a background sample
            # (reference behavior — the image is not skipped)
            labels = np.zeros(len(anchors), np.int64)
            argmax_g = np.zeros(len(anchors), np.int64)
        keep = labels >= 0                            # all non-ignored
        fg = labels == 1
        fg_total += int(fg.sum())
        sel = np.where(keep)[0]
        sp.append(cl[n].reshape(len(anchors), -1)[sel])
        lp.append(bp[n].reshape(-1, 4)[sel])
        # score target: gt CLASS for fg (1-based like the reference,
        # 0 = background), 0 for bg
        tgt_lab = np.zeros(len(sel), np.int32)
        fg_sel = fg[sel]
        tgt_lab[fg_sel] = gl[argmax_g[sel][fg_sel]].astype(np.int32)
        st.append(tgt_lab)
        enc = np.zeros((len(sel), 4), np.float32)
        if fg_sel.any():
            fa = sel[fg_sel]
            enc[fg_sel] = _encode_pairs(anchors[fa], g[argmax_g[fa]],
                                        avar[fa])
        lt.append(enc)
        w = np.zeros((len(sel), 4), np.float32)
        w[fg_sel] = 1.0
        iw.append(w)

    cat = (lambda xs, sh: np.concatenate(xs)
           if xs else np.zeros(sh, np.float32))
    return (Tensor(jnp.asarray(cat(sp, (0, max(num_classes, 1))))),
            Tensor(jnp.asarray(cat(lp, (0, 4)))),
            Tensor(jnp.asarray(cat(st, (0,)).astype(np.int32)[:, None])),
            Tensor(jnp.asarray(cat(lt, (0, 4)))),
            Tensor(jnp.asarray(cat(iw, (0, 4)))),
            Tensor(jnp.asarray(np.asarray([[max(fg_total, 1)]], np.int32))))


def _nms_fast_off(dets, nms_threshold, eta):
    """Greedy NMS over [K, 5] (box4 + score) rows with the reference's
    non-normalized (+1 pixel) IoU and adaptive eta threshold
    (retinanet_detection_output_op.cc NMSFast). Returns kept row indices
    in selection order. The full pairwise IoU matrix is precomputed once
    (like multiclass_nms's area_iou); only the greedy keep-loop is
    sequential."""
    order = np.argsort(-dets[:, 4], kind="stable")
    iou = _np_iou_off(dets[:, :4], dets[:, :4], 1.0)
    kept: list = []
    adaptive = nms_threshold
    for i in order:
        i = int(i)
        if kept and (iou[i, kept] > adaptive).any():
            continue
        kept.append(i)
        if eta < 1 and adaptive > 0.5:
            adaptive *= eta
    return kept


def retinanet_detection_output(bboxes, scores, anchors, im_info,
                               score_threshold=0.05, nms_top_k=1000,
                               keep_top_k=100, nms_threshold=0.3,
                               nms_eta=1.0):
    """RetinaNet inference (reference detection/retinanet_detection_output_op.cc):
    per FPN level, the nms_top_k best per-(anchor, class) scores above
    score_threshold (threshold 0.0 for the HIGHEST level, :409) are decoded
    against that level's anchors with the +1 pixel convention
    (DeltaScoreToPrediction :267), then class-wise NMS with non-normalized
    IoU merges across levels and keep_top_k caps per image; output labels
    are class+1 (MultiClassOutput :430). Lists are per level."""
    from ..framework.core import Tensor

    info = np.asarray(_arr(im_info), np.float32)
    N = info.shape[0]
    L = len(list(scores))
    all_det = []
    for n in range(N):
        preds = {}                      # class -> [xmin,ymin,xmax,ymax,score]
        for lvl, (bb, sc, an) in enumerate(zip(bboxes, scores, anchors)):
            b = np.asarray(_arr(bb), np.float32)[n]        # [M, 4] deltas
            s = np.asarray(_arr(sc), np.float32)[n]        # [M, C] sigmoid
            a = np.asarray(_arr(an), np.float32).reshape(-1, 4)
            C = s.shape[1]
            # flattened per-(anchor, class) selection; the highest FPN
            # level uses threshold 0.0 (reference :409)
            thresh = score_threshold if lvl < L - 1 else 0.0
            flat = s.reshape(-1)
            ok = np.where(flat > thresh)[0]
            order = ok[np.argsort(-flat[ok], kind="stable")]
            if nms_top_k > -1:
                order = order[:nms_top_k]
            if len(order) == 0:
                continue
            aidx = order // C
            cidx = order % C
            # decode with the +1 pixel convention (variance-free deltas)
            aw = a[aidx, 2] - a[aidx, 0] + 1
            ah = a[aidx, 3] - a[aidx, 1] + 1
            acx = a[aidx, 0] + aw / 2
            acy = a[aidx, 1] + ah / 2
            d = b[aidx]
            cx = d[:, 0] * aw + acx
            cy = d[:, 1] * ah + acy
            w = np.exp(d[:, 2]) * aw
            h = np.exp(d[:, 3]) * ah
            scale = float(info[n, 2]) if info.shape[1] > 2 else 1.0
            im_h = np.round(info[n, 0] / scale)
            im_w = np.round(info[n, 1] / scale)
            x1 = np.clip((cx - w / 2) / scale, 0, im_w - 1)
            y1 = np.clip((cy - h / 2) / scale, 0, im_h - 1)
            x2 = np.clip((cx + w / 2 - 1) / scale, 0, im_w - 1)
            y2 = np.clip((cy + h / 2 - 1) / scale, 0, im_h - 1)
            rows = np.stack([x1, y1, x2, y2, flat[order]], axis=1)
            for c in np.unique(cidx):
                preds.setdefault(int(c), []).append(rows[cidx == c])
        dets = []                       # (score, label, box4)
        for c, chunks in preds.items():
            cls = np.concatenate(chunks)
            for i in _nms_fast_off(cls, nms_threshold, nms_eta):
                dets.append((cls[i, 4], c, cls[i, :4]))
        dets.sort(key=lambda t: -t[0])
        if keep_top_k > 0:
            dets = dets[:keep_top_k]
        out = np.asarray(
            [[c + 1, sc_, *box] for sc_, c, box in dets], np.float32)
        all_det.append(out.reshape(-1, 6))
    out = np.concatenate(all_det) if all_det else np.zeros((0, 6), np.float32)
    nums = np.asarray([len(d) for d in all_det], np.int32)
    return Tensor(jnp.asarray(out)), Tensor(jnp.asarray(nums))


def polygon_box_transform(input, name=None):  # noqa: A002
    """detection/polygon_box_transform_op.cc: offsets → absolute quad
    coords per 4x-downsampled cell: even channels 4*j - in, odd 4*i - in."""
    from ..framework.core import apply_op

    def _impl(x):
        n, c, h, w = x.shape
        jj = jnp.arange(w, dtype=x.dtype)[None, None, None, :]
        ii = jnp.arange(h, dtype=x.dtype)[None, None, :, None]
        even = jnp.arange(c)[None, :, None, None] % 2 == 0
        return jnp.where(even, 4.0 * jj - x, 4.0 * ii - x)

    return apply_op(_impl, input, op_name="polygon_box_transform")


def locality_aware_nms(bboxes, scores, score_threshold, nms_top_k,
                       keep_top_k, nms_threshold=0.3, normalized=True,
                       nms_eta=1.0, background_label=-1, name=None):
    """EAST-style NMS (reference detection/locality_aware_nms_op.cc):
    first a sequential pass score-weight-merges CONSECUTIVE boxes whose
    IoU with the running box exceeds nms_threshold (scores add), then
    standard class-wise NMS runs on the merged set. Rectangle boxes
    (box_size 4); the quad PolyIoU variants raise."""
    from ..framework.core import Tensor

    bx = np.asarray(_arr(bboxes), np.float32)
    sc = np.asarray(_arr(scores), np.float32)
    if bx.shape[-1] != 4:
        raise NotImplementedError(
            "locality_aware_nms: quad boxes (PolyIoU) not supported; "
            "rectangles only")
    off = 0.0 if normalized else 1.0
    N = bx.shape[0]
    outs, nums = [], []
    for n in range(N):
        b = bx[n]
        s = sc[n]                              # [C, M]
        dets = []
        for c in range(s.shape[0]):
            if c == background_label:
                continue
            box_c = b.copy()
            s_c = s[c].copy()
            skip = np.ones(len(box_c), bool)
            idx = -1
            for i in range(len(box_c)):
                if idx > -1:
                    ov = _np_iou_off(box_c[i][None], box_c[idx][None],
                                     off)[0, 0]
                    if ov > nms_threshold:
                        tot = s_c[i] + s_c[idx]
                        box_c[idx] = (box_c[i] * s_c[i]
                                      + box_c[idx] * s_c[idx]) / tot
                        s_c[idx] = tot
                    else:
                        skip[idx] = False
                        idx = i
                else:
                    idx = i
            if idx > -1:
                skip[idx] = False
            keep = (~skip) & (s_c > score_threshold)
            if not keep.any():
                continue
            # second pass: delegate the class suppression to multiclass_nms
            # (same sort/top-k/adaptive-eta path, offset handled there)
            det_c, _cn = multiclass_nms(
                Tensor(jnp.asarray(box_c[keep][None])),
                Tensor(jnp.asarray(s_c[keep][None, None, :])),
                score_threshold=0.0, nms_top_k=nms_top_k,
                keep_top_k=-1, nms_threshold=nms_threshold,
                normalized=normalized, nms_eta=nms_eta,
                background_label=-1)
            for row in np.asarray(_arr(det_c)).reshape(-1, 6):
                dets.append([float(c), *row[1:]])
        dets.sort(key=lambda r: -r[1])
        if keep_top_k > 0:
            dets = dets[:keep_top_k]
        outs.append(np.asarray(dets, np.float32).reshape(-1, 6))
        nums.append(len(dets))
    return (Tensor(jnp.asarray(np.concatenate(outs))),
            Tensor(jnp.asarray(np.asarray(nums, np.int32))))
