"""paddle_tpu.vision (mirrors paddle.vision)."""
from . import models  # noqa: F401
from . import transforms  # noqa: F401
from . import datasets  # noqa: F401
from . import ops  # noqa: F401
from .datasets import MNIST, FashionMNIST, Cifar10, Cifar100  # noqa: F401
from .models import LeNet, ResNet, resnet18, resnet50  # noqa: F401


def set_image_backend(backend):
    pass


def get_image_backend():
    return "numpy"
