"""Vision datasets (reference python/paddle/vision/datasets/).

Zero-egress environment: datasets load from local files when present
(standard IDX/cifar formats) and otherwise generate deterministic synthetic
data with the right shapes — tests and benches rely on shapes/dtypes, not
on the actual corpus.
"""
from __future__ import annotations

import gzip
import os
import struct

import numpy as np

from ..io import Dataset

__all__ = ["MNIST", "FashionMNIST", "Cifar10", "Cifar100", "Flowers", "ImageFolder", "DatasetFolder"]


class MNIST(Dataset):
    NUM_CLASSES = 10
    IMAGE_SHAPE = (1, 28, 28)

    def __init__(self, image_path=None, label_path=None, mode="train",
                 transform=None, download=True, backend=None):
        self.mode = mode
        self.transform = transform
        self.images, self.labels = self._load(image_path, label_path)

    def _load(self, image_path, label_path):
        if image_path and os.path.exists(image_path):
            with gzip.open(image_path, "rb") as f:
                magic, n, rows, cols = struct.unpack(">IIII", f.read(16))
                images = np.frombuffer(f.read(), dtype=np.uint8).reshape(n, rows, cols)
            with gzip.open(label_path, "rb") as f:
                struct.unpack(">II", f.read(8))
                labels = np.frombuffer(f.read(), dtype=np.uint8)
            return images, labels.astype(np.int64)
        # synthetic fallback (deterministic)
        n = 60000 if self.mode == "train" else 10000
        rng = np.random.RandomState(0 if self.mode == "train" else 1)
        images = rng.randint(0, 256, size=(n, 28, 28), dtype=np.uint8)
        labels = rng.randint(0, 10, size=(n,)).astype(np.int64)
        return images, labels

    def __getitem__(self, idx):
        img = self.images[idx]
        label = self.labels[idx]
        if self.transform is not None:
            img = self.transform(img)
        else:
            img = img.astype(np.float32)[None, :, :] / 255.0
        return img, np.asarray([label], dtype=np.int64)

    def __len__(self):
        return len(self.images)


class FashionMNIST(MNIST):
    pass


class Cifar10(Dataset):
    NUM_CLASSES = 10
    IMAGE_SHAPE = (3, 32, 32)

    def __init__(self, data_file=None, mode="train", transform=None,
                 download=True, backend=None):
        self.mode = mode
        self.transform = transform
        n = 50000 if mode == "train" else 10000
        rng = np.random.RandomState(0 if mode == "train" else 1)
        self.images = rng.randint(0, 256, size=(n, 3, 32, 32), dtype=np.uint8)
        self.labels = rng.randint(0, self.NUM_CLASSES, size=(n,)).astype(np.int64)

    def __getitem__(self, idx):
        img = self.images[idx]
        label = self.labels[idx]
        if self.transform is not None:
            img = self.transform(img.transpose(1, 2, 0))
        else:
            img = img.astype(np.float32) / 255.0
        return img, np.asarray([label], dtype=np.int64)

    def __len__(self):
        return len(self.images)


class Cifar100(Cifar10):
    NUM_CLASSES = 100


class Flowers(Cifar10):
    NUM_CLASSES = 102


class DatasetFolder(Dataset):
    def __init__(self, root, loader=None, extensions=None, transform=None,
                 is_valid_file=None):
        self.root = root
        self.transform = transform
        self.samples = []
        classes = sorted(d for d in os.listdir(root) if os.path.isdir(os.path.join(root, d)))
        self.class_to_idx = {c: i for i, c in enumerate(classes)}
        exts = extensions or (".png", ".jpg", ".jpeg", ".bmp", ".npy")
        for c in classes:
            d = os.path.join(root, c)
            for fname in sorted(os.listdir(d)):
                if fname.lower().endswith(tuple(exts)):
                    self.samples.append((os.path.join(d, fname), self.class_to_idx[c]))
        self.loader = loader or self._default_loader

    @staticmethod
    def _default_loader(path):
        if path.endswith(".npy"):
            return np.load(path)
        raise RuntimeError("image decoding requires a loader (no PIL in env)")

    def __getitem__(self, idx):
        path, target = self.samples[idx]
        sample = self.loader(path)
        if self.transform is not None:
            sample = self.transform(sample)
        return sample, target

    def __len__(self):
        return len(self.samples)


class ImageFolder(DatasetFolder):
    def __init__(self, root, loader=None, extensions=None, transform=None,
                 is_valid_file=None):
        self.root = root
        self.transform = transform
        self.loader = loader or DatasetFolder._default_loader
        exts = extensions or (".png", ".jpg", ".jpeg", ".bmp", ".npy")
        self.samples = [os.path.join(root, f) for f in sorted(os.listdir(root))
                        if f.lower().endswith(tuple(exts))]

    def __getitem__(self, idx):
        sample = self.loader(self.samples[idx])
        if self.transform is not None:
            sample = self.transform(sample)
        return [sample]

    def __len__(self):
        return len(self.samples)
