"""paddle_tpu.metric (reference python/paddle/metric/metrics.py)."""
from __future__ import annotations

import numpy as np

from ..framework.core import Tensor

__all__ = ["Metric", "Accuracy", "Precision", "Recall", "Auc", "accuracy"]


def _np(x):
    return np.asarray(x._data) if isinstance(x, Tensor) else np.asarray(x)


class Metric:
    def __init__(self):
        pass

    def reset(self):
        raise NotImplementedError

    def update(self, *args):
        raise NotImplementedError

    def accumulate(self):
        raise NotImplementedError

    def name(self):
        raise NotImplementedError

    def compute(self, *args):
        return args


class Accuracy(Metric):
    def __init__(self, topk=(1,), name=None, *args, **kwargs):
        super().__init__()
        self.topk = (topk,) if isinstance(topk, int) else tuple(topk)
        self.maxk = max(self.topk)
        self._name = name or "acc"
        self.reset()

    def compute(self, pred, label, *args):
        pred_np = _np(pred)
        label_np = _np(label)
        idx = np.argsort(-pred_np, axis=-1)[..., : self.maxk]
        if label_np.ndim == pred_np.ndim and label_np.shape[-1] == 1:
            label_np = label_np[..., 0]
        correct = idx == label_np[..., None]
        return Tensor(correct.astype(np.float32))

    def update(self, correct, *args):
        c = _np(correct)
        num_samples = c.shape[0]
        accs = []
        for k in self.topk:
            num_corrects = c[..., :k].sum()
            accs.append(float(num_corrects) / num_samples)
            self.total[self.topk.index(k)] += float(num_corrects)
            self.count[self.topk.index(k)] += num_samples
        return accs[0] if len(accs) == 1 else accs

    def reset(self):
        self.total = [0.0] * len(self.topk)
        self.count = [0] * len(self.topk)

    def accumulate(self):
        res = [t / c if c > 0 else 0.0 for t, c in zip(self.total, self.count)]
        return res[0] if len(res) == 1 else res

    def name(self):
        if len(self.topk) == 1:
            return [self._name]
        return [f"{self._name}_top{k}" for k in self.topk]


class Precision(Metric):
    def __init__(self, name="precision", *args, **kwargs):
        super().__init__()
        self._name = name
        self.reset()

    def update(self, preds, labels):
        p = (_np(preds) > 0.5).astype(np.int32).reshape(-1)
        l = _np(labels).astype(np.int32).reshape(-1)
        self.tp += int(((p == 1) & (l == 1)).sum())
        self.fp += int(((p == 1) & (l == 0)).sum())

    def reset(self):
        self.tp = 0
        self.fp = 0

    def accumulate(self):
        denom = self.tp + self.fp
        return self.tp / denom if denom else 0.0

    def name(self):
        return self._name


class Recall(Metric):
    def __init__(self, name="recall", *args, **kwargs):
        super().__init__()
        self._name = name
        self.reset()

    def update(self, preds, labels):
        p = (_np(preds) > 0.5).astype(np.int32).reshape(-1)
        l = _np(labels).astype(np.int32).reshape(-1)
        self.tp += int(((p == 1) & (l == 1)).sum())
        self.fn += int(((p == 0) & (l == 1)).sum())

    def reset(self):
        self.tp = 0
        self.fn = 0

    def accumulate(self):
        denom = self.tp + self.fn
        return self.tp / denom if denom else 0.0

    def name(self):
        return self._name


class Auc(Metric):
    def __init__(self, curve="ROC", num_thresholds=4095, name="auc", *args, **kwargs):
        super().__init__()
        self._num_thresholds = num_thresholds
        self._name = name
        self.reset()

    def update(self, preds, labels):
        p = _np(preds)
        if p.ndim == 2:
            p = p[:, 1]
        l = _np(labels).reshape(-1)
        bins = np.minimum((p * self._num_thresholds).astype(np.int64), self._num_thresholds - 1)
        for b, lab in zip(bins, l):
            if lab:
                self._stat_pos[b] += 1
            else:
                self._stat_neg[b] += 1

    def reset(self):
        self._stat_pos = np.zeros(self._num_thresholds, dtype=np.int64)
        self._stat_neg = np.zeros(self._num_thresholds, dtype=np.int64)

    def accumulate(self):
        tot_pos = 0.0
        tot_neg = 0.0
        auc = 0.0
        for i in range(self._num_thresholds - 1, -1, -1):
            pos = float(self._stat_pos[i])
            neg = float(self._stat_neg[i])
            auc += neg * (tot_pos + pos / 2.0)
            tot_pos += pos
            tot_neg += neg
        return auc / (tot_pos * tot_neg) if tot_pos and tot_neg else 0.0

    def name(self):
        return self._name


def accuracy(input, label, k=1, correct=None, total=None, name=None):  # noqa: A002
    pred_np = _np(input)
    label_np = _np(label)
    idx = np.argsort(-pred_np, axis=-1)[..., :k]
    if label_np.ndim == pred_np.ndim and label_np.shape[-1] == 1:
        label_np = label_np[..., 0]
    corr = (idx == label_np[..., None]).any(axis=-1)
    return Tensor(np.asarray(corr.mean(), dtype=np.float32))
