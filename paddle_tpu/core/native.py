"""ctypes bindings for the native runtime core (csrc/ptpu_core.cc).

The reference binds its C++ core with pybind11 (paddle/fluid/pybind/
pybind.cc); this environment has no pybind11, so the native library exports
a C ABI consumed here via ctypes. The .so is lazy-built with the Makefile
on first import; if the toolchain is unavailable the pure-Python fallbacks
below keep the API working (slower, same semantics).
"""
from __future__ import annotations

import ctypes
import os
import subprocess
import threading
import time
from contextlib import contextmanager
from typing import Optional

_DIR = os.path.dirname(os.path.abspath(__file__))
_LIB_PATH = os.path.join(_DIR, "lib", "libptpu_core.so")

_lib: Optional[ctypes.CDLL] = None


def _build_and_load() -> Optional[ctypes.CDLL]:
    if not os.path.exists(_LIB_PATH):
        try:
            subprocess.run(["make", "-C", _DIR, "-s"], check=True,
                           capture_output=True, timeout=120)
        except Exception:
            return None
    try:
        lib = ctypes.CDLL(_LIB_PATH)
    except OSError:
        return None
    # signatures
    lib.ptpu_last_error.restype = ctypes.c_char_p
    lib.ptpu_flag_set.argtypes = [ctypes.c_char_p, ctypes.c_char_p]
    lib.ptpu_flag_get.argtypes = [ctypes.c_char_p, ctypes.c_char_p, ctypes.c_int]
    lib.ptpu_flag_get.restype = ctypes.c_int
    lib.ptpu_stat_add.argtypes = [ctypes.c_char_p, ctypes.c_int64]
    lib.ptpu_stat_get.argtypes = [ctypes.c_char_p]
    lib.ptpu_stat_get.restype = ctypes.c_int64
    lib.ptpu_stat_reset.argtypes = [ctypes.c_char_p]
    lib.ptpu_profiler_enable.argtypes = [ctypes.c_int]
    lib.ptpu_event_begin.restype = ctypes.c_int64
    lib.ptpu_event_end.argtypes = [ctypes.c_char_p, ctypes.c_int64]
    lib.ptpu_profiler_dump.argtypes = [ctypes.c_char_p, ctypes.c_int64]
    lib.ptpu_profiler_dump.restype = ctypes.c_int64
    lib.ptpu_profiler_event_count.restype = ctypes.c_int
    lib.ptpu_queue_create.argtypes = [ctypes.c_int]
    lib.ptpu_queue_create.restype = ctypes.c_void_p
    lib.ptpu_queue_push.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                    ctypes.c_int64, ctypes.c_int]
    lib.ptpu_queue_push.restype = ctypes.c_int
    lib.ptpu_queue_pop.argtypes = [ctypes.c_void_p,
                                   ctypes.POINTER(ctypes.POINTER(ctypes.c_char)),
                                   ctypes.POINTER(ctypes.c_int64), ctypes.c_int]
    lib.ptpu_queue_pop.restype = ctypes.c_int
    lib.ptpu_buffer_free.argtypes = [ctypes.POINTER(ctypes.c_char)]
    lib.ptpu_queue_size.argtypes = [ctypes.c_void_p]
    lib.ptpu_queue_size.restype = ctypes.c_int
    lib.ptpu_queue_close.argtypes = [ctypes.c_void_p]
    lib.ptpu_queue_destroy.argtypes = [ctypes.c_void_p]
    lib.ptpu_arena_create.argtypes = [ctypes.c_int64]
    lib.ptpu_arena_create.restype = ctypes.c_void_p
    lib.ptpu_arena_alloc.argtypes = [ctypes.c_void_p, ctypes.c_int64]
    lib.ptpu_arena_alloc.restype = ctypes.c_void_p
    lib.ptpu_arena_free.argtypes = [ctypes.c_void_p, ctypes.c_void_p]
    lib.ptpu_arena_free.restype = ctypes.c_int
    lib.ptpu_arena_stat.argtypes = [ctypes.c_void_p, ctypes.c_int]
    lib.ptpu_arena_stat.restype = ctypes.c_int64
    lib.ptpu_arena_destroy.argtypes = [ctypes.c_void_p]
    return lib


_lib = _build_and_load()
NATIVE_AVAILABLE = _lib is not None


# -- flags ------------------------------------------------------------------

_py_flags = {}
_py_flags_lock = threading.Lock()


# Fast-path mirror of FLAGS_check_nan_inf, read per-op by apply_op (the
# analog of the reference's post-kernel CheckOpHasNanOrInf gate,
# operator.cc:1199); a list so importers share the mutable cell.
check_nan_inf = [False]

# Fast-path mirror of FLAGS_benchmark (reference imperative/flags.cc):
# while on, apply_op accumulates per-op wall time into
# paddle_tpu.monitor.benchmark.
benchmark = [False]

# Fast-path mirror of FLAGS_eager_grad_jit (ISSUE 2): gates the cached
# jitted-VJP fast path on grad-enabled eager dispatch (the training-side
# PreparedOp-cache analog in framework.core). Default ON; flip with
# `paddle.set_flags({"FLAGS_eager_grad_jit": 0})` to fall back to raw
# per-call jax.vjp closures.
eager_grad_jit = [True]


def _truthy(value) -> bool:
    return str(value).lower() in ("1", "true", "yes", "on")


# Fast-path mirror of FLAGS_use_shared_memory (ISSUE 3 — the reference's
# fluid/dataloader flags.use_shared_memory): multiprocess DataLoader
# workers ship batches through a shared-memory ring instead of pickling
# them over pipes. Default ON; the pipe path stays the automatic fallback
# for non-numpy payloads and platform errors.
use_shared_memory = [_truthy(os.environ.get("FLAGS_use_shared_memory", "1"))]

# Fast-path mirror of FLAGS_fast_step (ISSUE 3): donated async train-step
# fast path — params/opt-state stay device-resident across steps with
# buffer donation, the step is dispatched without blocking, and reading
# the loss is the only sync point (counted by the step_async_syncs gauge).
# `paddle.set_flags({"FLAGS_fast_step": 0})` restores the per-step
# writeback + per-step host scalar paths.
fast_step = [_truthy(os.environ.get("FLAGS_fast_step", "1"))]

# Fast-path mirror of FLAGS_serving_jit (ISSUE 4): the serving engine's
# jit-compiled KV-cache prefill/decode programs. Default ON;
# `paddle.set_flags({"FLAGS_serving_jit": 0})` drops the engine to an
# un-jitted full-recompute reference decode (same scheduler, same
# sampling) — the numerics escape hatch for debugging cache bugs.
serving_jit = [_truthy(os.environ.get("FLAGS_serving_jit", "1"))]

# Fast-path mirror of FLAGS_fused_optimizer (ISSUE 6 — the reference's
# operators/fused/ fused Adam/LAMB kernels): flatten the param/moment/grad
# pytrees into a few contiguous dtype-homogeneous buffers and run the
# whole optimizer update as ONE pass (a Pallas kernel on TPU, a single
# fused XLA program elsewhere) instead of a per-leaf tree_map. Opt-in on
# Adam/AdamW/Lamb eager ``step()`` and on jit.TrainStep /
# DistributedTrainStep. Default OFF; the unfused path is pinned
# bit-for-bit while unset.
fused_optimizer = [_truthy(os.environ.get("FLAGS_fused_optimizer", "0"))]

# Fast-path mirror of FLAGS_fused_kernels (ISSUE 6): fused
# residual+layernorm and GeLU/SwiGLU-MLP Pallas kernels in the
# transformer block hot path (ops/fused_kernels.py, wired through
# ops/fused.py and models/gpt.py). Off-TPU the "fused" entry points fall
# back to the identical composed jnp math, so flipping the flag on CPU
# changes nothing; interpret-mode parity tests cover the kernels
# themselves. Default OFF.
fused_kernels = [_truthy(os.environ.get("FLAGS_fused_kernels", "0"))]

# Fast-path mirror of FLAGS_overlap_grads (ISSUE 6): latency-hiding
# gradient collectives — DistributedTrainStep computes grads under
# shard_map with a per-bucket pmean issued INSIDE the backward (a
# custom-vjp identity on each param bucket), so the dp-grad all-reduce
# for layer N overlaps the backward compute of layers < N instead of
# serializing after the full backward. Default OFF; requires a pure
# data/sharding mesh (model/pipe degree 1) and replicated params — other
# topologies keep the GSPMD path.
overlap_grads = [_truthy(os.environ.get("FLAGS_overlap_grads", "0"))]

# Fast-path mirror of FLAGS_paged_kv (ISSUE 7): the serving engine's
# paged KV cache — a block pool (n_blocks, layers, heads, block_size,
# head_dim) with per-slot block tables instead of one contiguous
# max_len buffer per slot, chunked prefill interleaved with decode
# ticks, and the Pallas paged-attention decode kernel
# (ops/paged_attention.py) on TPU. Default OFF; the PR-4 fixed-slot
# path is pinned bit-for-bit while unset.
paged_kv = [_truthy(os.environ.get("FLAGS_paged_kv", "0"))]

# FLAGS_fault_inject (ISSUE 5): deterministic fault-injection spec string
# (e.g. "nan_grad@step=50:repeat=3,crash@step=120"); empty = no faults.
# The resilience.faults registry registers a watcher here so set_flags
# reconfigures it immediately; the cell holds the raw spec text.
fault_inject = [os.environ.get("FLAGS_fault_inject", "")]
fault_inject_watchers: list = []

# FLAGS_sanitize (ISSUE 8): opt-in runtime sanitizers
# (paddle_tpu.analysis.sanitizers) — the jit-boundary recompile explainer
# (a cache miss diffs its aval signature against the nearest cached entry
# and emits a `sanitize.recompile` span naming the differing leaf) and
# the donation-after-use guard (buffers donated to a compiled step are
# tombstoned; a later host read raises with the donating call site).
# Default OFF; the unset path is pinned bit-for-bit — each hook is one
# list-index check.
sanitize = [_truthy(os.environ.get("FLAGS_sanitize", "0"))]


# FLAGS_shardy (ISSUE 9): lower shardings through the Shardy (sdy)
# partitioner dialect instead of legacy GSPMD mhlo.sharding strings —
# axis NAMES survive into the lowered module (`sdy.sharding_constraint
# <@mesh, [{"data"}, {"model"}]>`), which is what fleet.auto.explain
# debugging and the assert-on-HLO tests read. Default ON; flip to 0 to
# fall back to the legacy partitioner (the compiled HLO is equivalent —
# partitioning happens at compile time either way).
shardy = [_truthy(os.environ.get("FLAGS_shardy", "1"))]


def apply_shardy_flag() -> None:
    """Push the cell value into jax's global lowering config (called at
    paddle_tpu import and from set_flags)."""
    try:
        import jax

        jax.config.update("jax_use_shardy_partitioner", bool(shardy[0]))
    except Exception:  # noqa: BLE001 — older jax without the option
        pass


@contextmanager
def shardy_disabled():
    """Trace/lower with the legacy GSPMD partitioner regardless of
    FLAGS_shardy. Needed around host-callback ops (jax.pure_callback /
    jax.debug.print): jax 0.4.x's callback lowering predates Shardy and
    dies with `'OpSharding' object has no attribute 'build'` when the sdy
    dialect is active."""
    try:
        import jax

        prev = bool(jax.config.jax_use_shardy_partitioner)
    except Exception:  # noqa: BLE001
        yield
        return
    try:
        jax.config.update("jax_use_shardy_partitioner", False)
        yield
    finally:
        jax.config.update("jax_use_shardy_partitioner", prev)


def _int_or_zero(value) -> int:
    try:
        return int(str(value))
    except (TypeError, ValueError):
        return 0


# FLAGS_shm_slot_bytes (ISSUE 3 transport, cell added by ISSUE 8's
# env-flag lint): manual override of the shared-memory ring's per-slot
# byte size; 0 = size from the probed sample. Going through a cell keeps
# `paddle.set_flags({"FLAGS_shm_slot_bytes": n})` working — the env var
# alone would be unreachable after import.
shm_slot_bytes = [_int_or_zero(os.environ.get("FLAGS_shm_slot_bytes", "0"))]


# FLAGS_serving_mesh (ISSUE 10): multi-chip sharded decode for the
# serving engine — an integer DATA degree: decode slots shard over the
# mesh "data" axis, the remaining devices become the "model" axis over
# which weights shard Megatron-style via gpt_param_specs (GSPMD derives
# the collectives). 0 (default) keeps the single-chip engine bit-for-bit;
# an explicit ``InferenceEngine(mesh=...)`` overrides the flag either way.
serving_mesh = [_int_or_zero(os.environ.get("FLAGS_serving_mesh", "0"))]


# FLAGS_prefix_cache (ISSUE 11): radix-tree prefix sharing over the
# paged KV block pool — admission walks a host-side radix tree of
# cached prompt prefixes, splices matched (refcounted, copy-on-write)
# blocks into the new slot's table and only prefills the uncached tail,
# so a shared system prompt prefills ONCE and fans out across streams.
# Requires FLAGS_paged_kv=1 (or InferenceEngine(paged=True)). Default
# OFF; the cache-cold engine is pinned token-identical while unset, and
# greedy output with the cache ON is pinned token-identical to cold.
prefix_cache = [_truthy(os.environ.get("FLAGS_prefix_cache", "0"))]


# FLAGS_autotune (ISSUE 17): shape-keyed Pallas block autotuning — at the
# first compile of a kernel family for a concrete (kernel, shape, dtype,
# backend) key, time a handful of legal block configs and persist the
# winner to tools/autotune_cache.json (ops/autotune.py); later compiles
# consult the cache. Default OFF; unset, every kernel keeps its
# hand-picked `_auto_block` defaults bit-for-bit. Kernel modules mirror
# the cell via `autotune_watchers` so no jit-reachable code reads it.
autotune = [_truthy(os.environ.get("FLAGS_autotune", "0"))]
autotune_watchers: list = []

# FLAGS_fp8_matmul (ISSUE 17): fp8 (e4m3) matmul path for the block
# projections — delayed-scaling amax history through paddle_tpu.amp.fp8,
# dequant fused into the kernel epilogue (ops/fp8_matmul.py, the int8
# epilogue pattern). Default OFF; the bf16 path is pinned bit-for-bit
# while unset. `GPTConfig(fp8=True)` opts a model in explicitly.
fp8_matmul = [_truthy(os.environ.get("FLAGS_fp8_matmul", "0"))]
fp8_matmul_watchers: list = []

# FLAGS_ragged_decode (ISSUE 17): ragged paged-attention decode — the
# paged kernel's K/V index map clamps dead table iterations (past the
# slot's live length) to the last live block, so consecutive grid steps
# re-reference the same block and the DMA is elided; decode cost tracks
# live tokens instead of padded table width. Compute is already guarded
# per-iteration, so ON is bit-identical to OFF by construction; default
# OFF keeps the PR-7 index map verbatim. Mirrored via watchers
# (ragged_decode_watchers) — the decode wrapper is jit-reachable.
ragged_decode = [_truthy(os.environ.get("FLAGS_ragged_decode", "0"))]
ragged_decode_watchers: list = []

# FLAGS_overlap_zero2 (ISSUE 17): extend FLAGS_overlap_grads' in-backward
# gradient collective from pmean to the ZeRO-2 reduce-scatter — sharded
# grad buckets issue psum_scatter INSIDE the backward so the scatter of
# layer N overlaps the backward compute of layers < N, and each device
# only ever materializes its grad shard. Requires FLAGS_overlap_grads=1
# and zero level >= 2. Default OFF; the post-backward GSPMD
# reduce-scatter path is pinned bit-for-bit while unset.
overlap_zero2 = [_truthy(os.environ.get("FLAGS_overlap_zero2", "0"))]


def set_flag(name: str, value) -> None:
    if name.endswith("check_nan_inf"):
        check_nan_inf[0] = _truthy(value)
    elif name.endswith("benchmark"):
        benchmark[0] = _truthy(value)
    elif name.endswith("eager_grad_jit"):
        eager_grad_jit[0] = _truthy(value)
    elif name.endswith("use_shared_memory"):
        use_shared_memory[0] = _truthy(value)
    elif name.endswith("fast_step"):
        fast_step[0] = _truthy(value)
    elif name.endswith("serving_jit"):
        serving_jit[0] = _truthy(value)
    elif name.endswith("fused_optimizer"):
        fused_optimizer[0] = _truthy(value)
    elif name.endswith("fused_kernels"):
        fused_kernels[0] = _truthy(value)
    elif name.endswith("overlap_grads"):
        overlap_grads[0] = _truthy(value)
    elif name.endswith("paged_kv"):
        paged_kv[0] = _truthy(value)
    elif name.endswith("fault_inject"):
        fault_inject[0] = str(value)
        for watcher in fault_inject_watchers:
            watcher(fault_inject[0])
    elif name.endswith("sanitize"):
        sanitize[0] = _truthy(value)
    elif name.endswith("shardy"):
        shardy[0] = _truthy(value)
        apply_shardy_flag()
    elif name.endswith("shm_slot_bytes"):
        shm_slot_bytes[0] = _int_or_zero(value)
    elif name.endswith("serving_mesh"):
        serving_mesh[0] = _int_or_zero(value)
    elif name.endswith("prefix_cache"):
        prefix_cache[0] = _truthy(value)
    elif name.endswith("autotune"):
        autotune[0] = _truthy(value)
        for watcher in autotune_watchers:
            watcher(autotune[0])
    elif name.endswith("fp8_matmul"):
        fp8_matmul[0] = _truthy(value)
        for watcher in fp8_matmul_watchers:
            watcher(fp8_matmul[0])
    elif name.endswith("ragged_decode"):
        ragged_decode[0] = _truthy(value)
        for watcher in ragged_decode_watchers:
            watcher(ragged_decode[0])
    elif name.endswith("overlap_zero2"):
        overlap_zero2[0] = _truthy(value)
    if _lib is not None:
        _lib.ptpu_flag_set(name.encode(), str(value).encode())
    else:
        with _py_flags_lock:
            _py_flags[name] = str(value)


def get_flag(name: str, default=None):
    if _lib is not None:
        buf = ctypes.create_string_buffer(4096)
        if _lib.ptpu_flag_get(name.encode(), buf, 4096):
            return buf.value.decode()
        return default
    with _py_flags_lock:
        if name in _py_flags:
            return _py_flags[name]
    return os.environ.get(name, default)


# -- stats ------------------------------------------------------------------

_py_stats = {}


def stat_add(name: str, delta: int = 1) -> None:
    if _lib is not None:
        _lib.ptpu_stat_add(name.encode(), int(delta))
    else:
        with _py_flags_lock:
            _py_stats[name] = _py_stats.get(name, 0) + int(delta)


def stat_get(name: str) -> int:
    if _lib is not None:
        return int(_lib.ptpu_stat_get(name.encode()))
    with _py_flags_lock:
        return _py_stats.get(name, 0)


def stat_reset(name: str) -> None:
    if _lib is not None:
        _lib.ptpu_stat_reset(name.encode())
    else:
        with _py_flags_lock:
            _py_stats[name] = 0


# -- profiler ---------------------------------------------------------------

_py_events = []
_py_prof_enabled = [False]


def profiler_enable(on: bool = True) -> None:
    if _lib is not None:
        _lib.ptpu_profiler_enable(1 if on else 0)
    else:
        _py_prof_enabled[0] = bool(on)


def profiler_clear() -> None:
    if _lib is not None:
        _lib.ptpu_profiler_clear()
    else:
        _py_events.clear()


def profiler_dump() -> str:
    """Chrome-trace JSON of recorded events."""
    if _lib is not None:
        n = _lib.ptpu_profiler_dump(None, 0)
        buf = ctypes.create_string_buffer(int(n) + 1)
        _lib.ptpu_profiler_dump(buf, n)
        return buf.raw[:n].decode()
    import json
    return json.dumps({"traceEvents": [
        {"name": name, "ph": "X", "pid": 0, "tid": 0,
         "ts": int(ts * 1e6), "dur": int(dur * 1e6)}
        for name, ts, dur in _py_events]})


@contextmanager
def record_event(name: str):
    """RAII event scope (reference platform/profiler.h:130 RecordEvent)."""
    if _lib is not None:
        t0 = _lib.ptpu_event_begin()
        try:
            yield
        finally:
            _lib.ptpu_event_end(name.encode(), t0)
    else:
        t0 = time.perf_counter()
        try:
            yield
        finally:
            if _py_prof_enabled[0]:
                _py_events.append((name, t0, time.perf_counter() - t0))


# -- blocking queue ---------------------------------------------------------

class BlockingQueue:
    """Bounded byte-buffer queue backed by the native impl (pure-Python
    fallback uses queue.Queue). Payloads are bytes; producers block when
    full, consumers when empty; close() releases both sides."""

    def __init__(self, capacity: int = 8):
        self._native = _lib is not None
        if self._native:
            self._h = _lib.ptpu_queue_create(int(capacity))
        else:
            import queue
            self._q = queue.Queue(maxsize=capacity)
            self._closed = threading.Event()

    def push(self, data: bytes, timeout_ms: int = -1) -> bool:
        if self._native:
            r = _lib.ptpu_queue_push(self._h, data, len(data), timeout_ms)
            if r == -1:
                raise TimeoutError("queue push timed out")
            return r == 1
        # fallback: poll in short slices so close() wakes blocked pushers
        # (matching the native close semantics)
        import queue as _q
        deadline = None if timeout_ms < 0 else time.monotonic() + timeout_ms / 1e3
        while True:
            if self._closed.is_set():
                return False
            try:
                self._q.put(data, timeout=0.05)
                return True
            except _q.Full:
                if deadline is not None and time.monotonic() > deadline:
                    raise TimeoutError("queue push timed out")

    def pop(self, timeout_ms: int = -1) -> Optional[bytes]:
        """None means closed-and-drained."""
        if self._native:
            pdata = ctypes.POINTER(ctypes.c_char)()
            plen = ctypes.c_int64()
            r = _lib.ptpu_queue_pop(self._h, ctypes.byref(pdata),
                                    ctypes.byref(plen), timeout_ms)
            if r == -1:
                raise TimeoutError("queue pop timed out")
            if r == 0:
                return None
            out = ctypes.string_at(pdata, plen.value)
            _lib.ptpu_buffer_free(pdata)
            return out
        import queue as _q
        deadline = None if timeout_ms < 0 else time.monotonic() + timeout_ms / 1e3
        while True:
            try:
                return self._q.get(timeout=0.05)
            except _q.Empty:
                if self._closed.is_set() and self._q.empty():
                    return None
                if deadline is not None and time.monotonic() > deadline:
                    raise TimeoutError("queue pop timed out")

    def __len__(self):
        if self._native:
            return _lib.ptpu_queue_size(self._h)
        return self._q.qsize()

    def close(self):
        if self._native:
            _lib.ptpu_queue_close(self._h)
        else:
            self._closed.set()

    def __del__(self):
        try:
            if self._native and _lib is not None:
                _lib.ptpu_queue_destroy(self._h)
        except Exception:
            pass


# -- arena allocator --------------------------------------------------------

class ArenaAllocator:
    """Host staging arena with best-fit + coalescing and stats.

    Stats indices: 0=allocated bytes, 1=peak bytes, 2=alloc count,
    3=free-block count (fragmentation signal).
    """

    def __init__(self, nbytes: int):
        if _lib is None:
            raise RuntimeError("native core unavailable — ArenaAllocator "
                               "requires the compiled runtime")
        self._h = _lib.ptpu_arena_create(int(nbytes))
        if not self._h:
            raise MemoryError(_lib.ptpu_last_error().decode())

    def alloc(self, nbytes: int) -> int:
        p = _lib.ptpu_arena_alloc(self._h, int(nbytes))
        if not p:
            raise MemoryError(_lib.ptpu_last_error().decode())
        return p

    def free(self, ptr: int) -> None:
        if not _lib.ptpu_arena_free(self._h, ptr):
            raise ValueError(_lib.ptpu_last_error().decode())

    def stat(self, which: int) -> int:
        return int(_lib.ptpu_arena_stat(self._h, which))

    @property
    def allocated(self):
        return self.stat(0)

    @property
    def peak(self):
        return self.stat(1)

    def __del__(self):
        try:
            if _lib is not None and getattr(self, "_h", None):
                _lib.ptpu_arena_destroy(self._h)
        except Exception:
            pass
