// paddle_tpu native runtime core.
//
// TPU-native counterparts of the reference's C++ platform layer, exposed as
// a C ABI for ctypes (this environment has no pybind11):
//   - flags registry        (reference paddle/fluid/platform/flags.cc,
//                            pybind/global_value_getter_setter.cc)
//   - stat monitor          (reference paddle/fluid/platform/monitor.h:77
//                            StatRegistry / STAT_ADD)
//   - profiler events       (reference paddle/fluid/platform/profiler.h:130
//                            RecordEvent -> chrome trace)
//   - blocking queue        (reference paddle/fluid/operators/reader/
//                            lod_tensor_blocking_queue.h, the DataLoader's
//                            C++ half)
//   - host arena allocator  (reference paddle/fluid/memory/allocation/
//                            auto_growth_best_fit_allocator.cc — host-side
//                            staging analog; device memory belongs to PJRT)
//
// Build: make -C paddle_tpu/core (g++ -shared -fPIC). Loaded via ctypes by
// paddle_tpu/core/native.py with a pure-Python fallback.

#include <atomic>
#include <chrono>
#include <condition_variable>
#include <cstdint>
#include <cstdlib>
#include <cstring>
#include <deque>
#include <map>
#include <mutex>
#include <string>
#include <thread>
#include <vector>

#define PTPU_API extern "C" __attribute__((visibility("default")))

// ---------------------------------------------------------------------------
// error reporting (enforce analog): last error string per thread
// ---------------------------------------------------------------------------
static thread_local std::string g_last_error;

PTPU_API const char* ptpu_last_error() { return g_last_error.c_str(); }

static void set_error(const std::string& msg) { g_last_error = msg; }

// ---------------------------------------------------------------------------
// flags registry
// ---------------------------------------------------------------------------
extern char** environ;

namespace {
struct FlagsRegistry {
  std::mutex mu;
  std::map<std::string, std::string> flags;

  FlagsRegistry() {
    // adopt FLAGS_* environment variables, as the reference does for its
    // exported gflags (platform/flags.cc)
    for (char** e = environ; e && *e; ++e) {
      const char* kv = *e;
      if (std::strncmp(kv, "FLAGS_", 6) == 0) {
        const char* eq = std::strchr(kv, '=');
        if (eq) flags.emplace(std::string(kv, eq - kv), std::string(eq + 1));
      }
    }
  }
};
FlagsRegistry& flags_registry() {
  static FlagsRegistry r;
  return r;
}
}  // namespace

PTPU_API void ptpu_flag_set(const char* name, const char* value) {
  auto& r = flags_registry();
  std::lock_guard<std::mutex> lk(r.mu);
  r.flags[name] = value;
}

// returns 1 if found; copies up to cap-1 bytes into out
PTPU_API int ptpu_flag_get(const char* name, char* out, int cap) {
  auto& r = flags_registry();
  std::lock_guard<std::mutex> lk(r.mu);
  auto it = r.flags.find(name);
  if (it == r.flags.end()) return 0;
  std::strncpy(out, it->second.c_str(), cap - 1);
  out[cap - 1] = '\0';
  return 1;
}

PTPU_API int ptpu_flag_count() {
  auto& r = flags_registry();
  std::lock_guard<std::mutex> lk(r.mu);
  return (int)r.flags.size();
}

// ---------------------------------------------------------------------------
// stat monitor
// ---------------------------------------------------------------------------
namespace {
struct StatRegistry {
  std::mutex mu;
  std::map<std::string, std::atomic<int64_t>> stats;
};
StatRegistry& stat_registry() {
  static StatRegistry r;
  return r;
}
}  // namespace

PTPU_API void ptpu_stat_add(const char* name, int64_t delta) {
  auto& r = stat_registry();
  std::lock_guard<std::mutex> lk(r.mu);
  r.stats[name] += delta;
}

PTPU_API int64_t ptpu_stat_get(const char* name) {
  auto& r = stat_registry();
  std::lock_guard<std::mutex> lk(r.mu);
  auto it = r.stats.find(name);
  return it == r.stats.end() ? 0 : it->second.load();
}

PTPU_API void ptpu_stat_reset(const char* name) {
  auto& r = stat_registry();
  std::lock_guard<std::mutex> lk(r.mu);
  r.stats[name] = 0;
}

// ---------------------------------------------------------------------------
// profiler: RecordEvent ring buffer -> chrome trace JSON
// ---------------------------------------------------------------------------
namespace {
struct ProfEvent {
  std::string name;
  int64_t ts_ns;
  int64_t dur_ns;
  int64_t tid;
};
struct Profiler {
  std::mutex mu;
  std::vector<ProfEvent> events;
  std::atomic<bool> enabled{false};
};
Profiler& profiler() {
  static Profiler p;
  return p;
}
int64_t now_ns() {
  return std::chrono::duration_cast<std::chrono::nanoseconds>(
             std::chrono::steady_clock::now().time_since_epoch())
      .count();
}
}  // namespace

PTPU_API void ptpu_profiler_enable(int on) { profiler().enabled = on != 0; }

PTPU_API int64_t ptpu_event_begin() { return now_ns(); }

PTPU_API void ptpu_event_end(const char* name, int64_t begin_ns) {
  auto& p = profiler();
  if (!p.enabled) return;
  int64_t tid = (int64_t)std::hash<std::thread::id>{}(std::this_thread::get_id());
  std::lock_guard<std::mutex> lk(p.mu);
  p.events.push_back({name, begin_ns, now_ns() - begin_ns, tid & 0xffff});
}

PTPU_API int ptpu_profiler_event_count() {
  auto& p = profiler();
  std::lock_guard<std::mutex> lk(p.mu);
  return (int)p.events.size();
}

// serialize chrome-trace JSON; returns bytes written (or required size if
// out==nullptr), truncates at cap
PTPU_API int64_t ptpu_profiler_dump(char* out, int64_t cap) {
  auto& p = profiler();
  std::lock_guard<std::mutex> lk(p.mu);
  std::string json = "{\"traceEvents\":[";
  bool first = true;
  for (auto& e : p.events) {
    if (!first) json += ",";
    first = false;
    json += "{\"name\":\"" + e.name + "\",\"ph\":\"X\",\"pid\":0,\"tid\":" +
            std::to_string(e.tid) + ",\"ts\":" + std::to_string(e.ts_ns / 1000) +
            ",\"dur\":" + std::to_string(e.dur_ns / 1000) + "}";
  }
  json += "]}";
  if (out == nullptr) return (int64_t)json.size();
  int64_t n = (int64_t)json.size() < cap ? (int64_t)json.size() : cap;
  std::memcpy(out, json.data(), n);
  return n;
}

PTPU_API void ptpu_profiler_clear() {
  auto& p = profiler();
  std::lock_guard<std::mutex> lk(p.mu);
  p.events.clear();
}

// ---------------------------------------------------------------------------
// blocking queue of byte buffers
// ---------------------------------------------------------------------------
namespace {
struct ByteBuf {
  char* data;
  int64_t len;
};
struct BlockingQueue {
  std::mutex mu;
  std::condition_variable cv_push, cv_pop;
  std::deque<ByteBuf> q;
  size_t capacity;
  bool closed = false;
};
}  // namespace

PTPU_API void* ptpu_queue_create(int capacity) {
  auto* q = new BlockingQueue();
  q->capacity = capacity > 0 ? (size_t)capacity : 1;
  return q;
}

// returns 1 on success, 0 if closed, -1 on timeout (timeout_ms < 0 = block)
PTPU_API int ptpu_queue_push(void* h, const char* data, int64_t len,
                             int timeout_ms) {
  auto* q = (BlockingQueue*)h;
  std::unique_lock<std::mutex> lk(q->mu);
  auto ready = [&] { return q->closed || q->q.size() < q->capacity; };
  if (timeout_ms < 0) {
    q->cv_push.wait(lk, ready);
  } else if (!q->cv_push.wait_for(lk, std::chrono::milliseconds(timeout_ms),
                                  ready)) {
    return -1;
  }
  if (q->closed) return 0;
  char* copy = (char*)std::malloc(len);
  std::memcpy(copy, data, len);
  q->q.push_back({copy, len});
  q->cv_pop.notify_one();
  return 1;
}

// returns length >=0 on success (caller then calls ptpu_queue_take to copy
// out + free), 0-with-closed semantics via status: 1 ok, 0 closed+empty,
// -1 timeout
PTPU_API int ptpu_queue_pop(void* h, char** out_data, int64_t* out_len,
                            int timeout_ms) {
  auto* q = (BlockingQueue*)h;
  std::unique_lock<std::mutex> lk(q->mu);
  auto ready = [&] { return q->closed || !q->q.empty(); };
  if (timeout_ms < 0) {
    q->cv_pop.wait(lk, ready);
  } else if (!q->cv_pop.wait_for(lk, std::chrono::milliseconds(timeout_ms),
                                 ready)) {
    return -1;
  }
  if (q->q.empty()) return 0;  // closed and drained
  ByteBuf b = q->q.front();
  q->q.pop_front();
  q->cv_push.notify_one();
  *out_data = b.data;
  *out_len = b.len;
  return 1;
}

PTPU_API void ptpu_buffer_free(char* data) { std::free(data); }

PTPU_API int ptpu_queue_size(void* h) {
  auto* q = (BlockingQueue*)h;
  std::lock_guard<std::mutex> lk(q->mu);
  return (int)q->q.size();
}

PTPU_API void ptpu_queue_close(void* h) {
  auto* q = (BlockingQueue*)h;
  std::lock_guard<std::mutex> lk(q->mu);
  q->closed = true;
  q->cv_pop.notify_all();
  q->cv_push.notify_all();
}

PTPU_API void ptpu_queue_destroy(void* h) {
  auto* q = (BlockingQueue*)h;
  {
    std::lock_guard<std::mutex> lk(q->mu);
    for (auto& b : q->q) std::free(b.data);
    q->q.clear();
    q->closed = true;
  }
  delete q;
}

// ---------------------------------------------------------------------------
// host arena allocator (best-fit with coalescing) + stats
// ---------------------------------------------------------------------------
namespace {
struct Arena {
  std::mutex mu;
  char* base = nullptr;
  size_t size = 0;
  // offset -> length, free blocks
  std::map<size_t, size_t> free_blocks;
  std::map<size_t, size_t> used_blocks;
  int64_t allocated = 0, peak = 0, alloc_count = 0;
};
}  // namespace

PTPU_API void* ptpu_arena_create(int64_t bytes) {
  auto* a = new Arena();
  a->base = (char*)std::malloc(bytes);
  if (!a->base) {
    delete a;
    set_error("arena: malloc failed");
    return nullptr;
  }
  a->size = bytes;
  a->free_blocks[0] = bytes;
  return a;
}

PTPU_API void* ptpu_arena_alloc(void* h, int64_t bytes) {
  auto* a = (Arena*)h;
  std::lock_guard<std::mutex> lk(a->mu);
  if (bytes < 0) {
    set_error("arena: negative allocation size");
    return nullptr;
  }
  // Round 0-byte requests up to one aligned unit: need==0 would re-insert
  // the chosen free block at its own offset while also recording it in
  // used_blocks — a double-tracked region that corrupts later coalescing.
  if (bytes == 0) bytes = 1;
  size_t need = (size_t)((bytes + 63) & ~63LL);  // 64B aligned
  // best fit
  auto best = a->free_blocks.end();
  for (auto it = a->free_blocks.begin(); it != a->free_blocks.end(); ++it) {
    if (it->second >= need &&
        (best == a->free_blocks.end() || it->second < best->second)) {
      best = it;
    }
  }
  if (best == a->free_blocks.end()) {
    set_error("arena: out of memory");
    return nullptr;
  }
  size_t off = best->first, len = best->second;
  a->free_blocks.erase(best);
  if (len > need) a->free_blocks[off + need] = len - need;
  a->used_blocks[off] = need;
  a->allocated += (int64_t)need;
  a->alloc_count += 1;
  if (a->allocated > a->peak) a->peak = a->allocated;
  return a->base + off;
}

PTPU_API int ptpu_arena_free(void* h, void* ptr) {
  auto* a = (Arena*)h;
  std::lock_guard<std::mutex> lk(a->mu);
  size_t off = (char*)ptr - a->base;
  auto it = a->used_blocks.find(off);
  if (it == a->used_blocks.end()) {
    set_error("arena: free of unknown pointer");
    return 0;
  }
  size_t len = it->second;
  a->used_blocks.erase(it);
  a->allocated -= (int64_t)len;
  // insert + coalesce with neighbors
  auto ins = a->free_blocks.emplace(off, len).first;
  if (ins != a->free_blocks.begin()) {
    auto prev = std::prev(ins);
    if (prev->first + prev->second == ins->first) {
      prev->second += ins->second;
      a->free_blocks.erase(ins);
      ins = prev;
    }
  }
  auto next = std::next(ins);
  if (next != a->free_blocks.end() &&
      ins->first + ins->second == next->first) {
    ins->second += next->second;
    a->free_blocks.erase(next);
  }
  return 1;
}

PTPU_API int64_t ptpu_arena_stat(void* h, int which) {
  auto* a = (Arena*)h;
  std::lock_guard<std::mutex> lk(a->mu);
  switch (which) {
    case 0: return a->allocated;
    case 1: return a->peak;
    case 2: return a->alloc_count;
    case 3: return (int64_t)a->free_blocks.size();
    default: return -1;
  }
}

PTPU_API void ptpu_arena_destroy(void* h) {
  auto* a = (Arena*)h;
  std::free(a->base);
  delete a;
}
