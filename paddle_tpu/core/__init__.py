"""paddle_tpu.core — native (C++) runtime core.

The TPU build's counterpart of the reference's C++ platform layer
(``paddle.fluid.core``): flags, stat monitor, profiler event recorder,
blocking queue, host arena allocator. See csrc/ptpu_core.cc and native.py.
"""
from .native import (
    NATIVE_AVAILABLE,
    ArenaAllocator,
    BlockingQueue,
    get_flag,
    set_flag,
    stat_add,
    stat_get,
    stat_reset,
    profiler_enable,
    profiler_dump,
    profiler_clear,
    record_event,
)

__all__ = [
    "NATIVE_AVAILABLE", "ArenaAllocator", "BlockingQueue",
    "get_flag", "set_flag", "stat_add", "stat_get", "stat_reset",
    "profiler_enable", "profiler_dump", "profiler_clear", "record_event",
]
