"""paddle_tpu.models — flagship model families (functional, shardable).

These are the models the reference ships training configs for (driver
BASELINE.json: LeNet/ResNet-50 in paddle.vision, BERT/ERNIE/GPT via Fleet).
Vision models live in paddle_tpu.vision.models (Layer API); the language
models here are written functionally — pure ``forward(params, batch)`` over
a param pytree with PartitionSpec tables — because that is the shape the
compiled hybrid-parallel path (paddle_tpu.parallel) consumes directly.
"""
from .gpt import (
    GPTConfig,
    gpt_init,
    gpt_forward,
    gpt_loss,
    gpt_param_specs,
    gpt_prefill,
    gpt_prefill_chunk,
    gpt_decode_step,
    gpt_decode_step_paged,
    gpt_verify_step,
    gpt_verify_step_paged,
    gpt_truncate,
    gpt_tiny,
    gpt_small,
    gpt_1p3b,
    gpt_nano,
    bert_base_config,
)
from .dlrm import (
    DLRMConfig,
    dlrm_init,
    dlrm_forward,
    dlrm_forward_from_emb,
    dlrm_loss,
    dlrm_loss_from_emb,
    dlrm_param_specs,
    dlrm_score_fn,
    dlrm_tiny,
    synthetic_ctr_batches,
)

__all__ = [
    "GPTConfig", "gpt_init", "gpt_forward", "gpt_loss", "gpt_param_specs",
    "gpt_prefill", "gpt_prefill_chunk",
    "gpt_decode_step", "gpt_decode_step_paged",
    "gpt_verify_step", "gpt_verify_step_paged", "gpt_truncate",
    "gpt_tiny", "gpt_small", "gpt_1p3b", "gpt_nano", "bert_base_config",
    "DLRMConfig", "dlrm_init", "dlrm_forward", "dlrm_forward_from_emb",
    "dlrm_loss", "dlrm_loss_from_emb", "dlrm_param_specs", "dlrm_score_fn",
    "dlrm_tiny", "synthetic_ctr_batches",
]
