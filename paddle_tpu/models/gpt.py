"""GPT-family decoder LM, TPU-first.

Capability parity with the reference's Fleet GPT path (driver BASELINE
config 5: "GPT-3 1.3B Fleet hybrid-parallel mp×pp×dp") and its parallel
layers (reference fleet/meta_parallel/parallel_layers/mp_layers.py:30
VocabParallelEmbedding, :97 ColumnParallelLinear, :170 RowParallelLinear)
— but instead of hand-written collectives, the model is a pure function
over a param pytree plus a PartitionSpec table (:func:`gpt_param_specs`);
GSPMD derives the identity/allreduce pattern the reference codes by hand.

Design notes (TPU):
- blocks are STACKED (leading layer dim) and applied with lax.scan — one
  compiled block body regardless of depth; with pipeline stages the leading
  dim reshapes to (n_stages, layers_per_stage) and shards over "pipe"
  (paddle_tpu.parallel.pipeline).
- matmul dims padded to MXU-friendly multiples (vocab 50304 = 128·393).
- compute dtype bf16, params fp32 (master weights — reference AMP O2
  semantics, contrib/mixed_precision/fp16_utils.py), softmax/loss in fp32.
- attention uses the Pallas flash kernel on TPU (ops/flash_attention.py),
  jnp reference path elsewhere.
- remat (jax.checkpoint) per block — the reference's RecomputeOptimizer /
  recompute_interval (fleet/utils/recompute.py:63) as a one-flag rematerialisation.
"""
from __future__ import annotations

import dataclasses
import math
from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from ..core import native as _native
from ..ops.flash_attention import NEG_INF, _attention_reference, _on_tpu

__all__ = ["GPTConfig", "gpt_init", "gpt_forward", "gpt_loss",
           "gpt_param_specs", "gpt_tiny", "gpt_small", "gpt_1p3b",
           "gpt_nano", "gpt_truncate", "bert_base_config", "gpt_prefill",
           "gpt_decode_step", "gpt_decode_step_paged", "gpt_prefill_chunk",
           "gpt_prefill_prefix", "gpt_verify_step", "gpt_verify_step_paged",
           "quantize_gpt_weights"]

# Module-local mirror of FLAGS_fp8_matmul (no core.native subscript in
# jit-reachable code); set_flags syncs it through the watcher list.
_fp8 = [bool(_native.fp8_matmul[0])]
_native.fp8_matmul_watchers.append(
    lambda v: _fp8.__setitem__(0, bool(v)))


@dataclasses.dataclass
class GPTConfig:
    vocab_size: int = 50304
    hidden: int = 768
    n_layers: int = 12
    n_heads: int = 12
    seq_len: int = 1024
    mlp_ratio: int = 4
    dtype: Any = jnp.bfloat16        # compute dtype
    param_dtype: Any = jnp.float32   # master weights
    n_stages: int = 1                # pipeline depth (mesh "pipe")
    remat: bool = False
    use_flash: Optional[bool] = None  # None = auto (TPU only)
    # lax.scan unroll over the layer dim. None = FULL unroll: XLA then
    # fuses/pipelines across layer boundaries — measured on v5e (bf16,
    # remat on): BERT-base 234->242 sps, ERNIE-large 73->88 sps (+19%),
    # GPT-1.3B MFU 0.54->0.60. Costs compile time (~3x); 1 keeps the
    # rolled one-body scan (fastest compile, e.g. for tests).
    scan_unroll: Optional[int] = None
    # long-context: ring attention with the seq dim sharded over seq_axis
    # (context parallelism — new capability vs the reference, SURVEY.md §5)
    ring_attention: bool = False
    seq_axis: str = "sharding"
    # fused residual+LN+MLP block half (ops/fused_kernels.py Pallas
    # kernels with custom-VJP backward). None = follow
    # FLAGS_fused_kernels at trace time; off-TPU the fused entry runs the
    # identical composed math, so this is numerics-neutral on CPU.
    fused_mlp: Optional[bool] = None
    # fp8 (e4m3) MLP matmuls (ops/fp8_matmul.py kernel, amp/fp8.py
    # just-in-time per-tensor scaling, STE gradients). None = follow
    # FLAGS_fp8_matmul at trace time. NOT numerics-neutral (that is the
    # point); takes the unfused MLP path when both fp8 and fused are on.
    fp8: Optional[bool] = None
    # mixture of experts (ISSUE 18): moe_experts=E routes every
    # moe_every-th block's MLP through an E-expert top-k MoE (nn/moe.py)
    # — ~moe_every·E/(moe_every-1+E)x the MLP parameters at near-dense
    # step FLOPs. The default moe_experts=0 keeps the dense model
    # BIT-IDENTICAL: params, forward, loss and every serving path take
    # the exact pre-MoE code (pinned by tests/test_moe.py).
    moe_experts: int = 0
    moe_top_k: int = 2
    moe_every: int = 2
    # training dispatch capacity (C = ceil(cf·k·T/E), overflow dropped
    # with residual passthrough); inference paths are always DROPLESS
    # so decode quality never depends on batch composition
    moe_capacity_factor: float = 1.25
    moe_aux_weight: float = 1e-2     # load-balance loss weight in gpt_loss
    moe_z_weight: float = 1e-3       # router z-loss weight in gpt_loss
    # mesh axis carrying expert parallelism (fleet.auto plans ep onto
    # "model"). Set → the one-hot einsum dispatch with the expert dim
    # constraint-pinned there (GSPMD lowers it to an AllToAll pair);
    # None → the fused Pallas permute kernel (ops/moe_dispatch.py).
    moe_axis: Optional[str] = None

    @property
    def head_dim(self):
        return self.hidden // self.n_heads

    @property
    def mlp_hidden(self):
        return self.hidden * self.mlp_ratio

    @property
    def moe_layer_ids(self):
        """Indices of MoE blocks: every moe_every-th layer (1-based), so
        moe_every=2 → layers 1, 3, 5, ...; moe_every=1 → all layers."""
        if self.moe_experts <= 0:
            return ()
        n = max(1, int(self.moe_every))
        return tuple(i for i in range(self.n_layers) if i % n == n - 1)


def gpt_tiny(**kw):
    d = dict(vocab_size=512, hidden=64, n_layers=4, n_heads=4, seq_len=64)
    d.update(kw)
    return GPTConfig(**d)


def gpt_small(**kw):
    d = dict(hidden=768, n_layers=12, n_heads=12, seq_len=1024)
    d.update(kw)
    return GPTConfig(**d)


def gpt_1p3b(**kw):
    # GPT-3 1.3B: the reference Fleet hybrid benchmark config
    d = dict(hidden=2048, n_layers=24, n_heads=16, seq_len=2048)
    d.update(kw)
    return GPTConfig(**d)


def gpt_nano(**kw):
    # draft-model scale for speculative decoding (ISSUE 10): small enough
    # that k draft steps cost less than the one target pass they save
    d = dict(vocab_size=512, hidden=64, n_layers=2, n_heads=4, seq_len=64)
    d.update(kw)
    return GPTConfig(**d)


def gpt_truncate(cfg: GPTConfig, params, n_layers: int):
    """Layer-truncated draft model: the first ``n_layers`` blocks of
    ``params`` with the embeddings/final-LN/tied head SHARED with the
    target. Returns ``(draft_cfg, draft_params)`` ready for
    ``serving.InferenceEngine(draft=...)``.

    Sharing wte/wpe/lnf keeps the truncated model's logits correlated
    with the target's without any extra training — the cheapest useful
    speculative-decoding draft (a separately trained gpt_nano-class
    model slots into the same contract). ``params`` must be the plain
    gpt_init layout (quantize AFTER truncation, not before)."""
    if not 1 <= n_layers <= cfg.n_layers:
        raise ValueError(
            f"n_layers={n_layers} outside [1, {cfg.n_layers}]")
    if cfg.moe_layer_ids:
        raise ValueError(
            "gpt_truncate does not support MoE configs: the dense-MLP "
            "and expert subtrees stack over different layer subsets, so "
            "a [:n_layers] slice has no single meaning")
    draft = dict(params)
    draft["blocks"] = {name: leaf[:n_layers]
                      for name, leaf in params["blocks"].items()}
    return dataclasses.replace(cfg, n_layers=n_layers), draft


def bert_base_config(**kw):
    # BERT-base shapes (used by bench.py config 3 as an encoder-sized LM)
    d = dict(vocab_size=30592, hidden=768, n_layers=12, n_heads=12,
             seq_len=512)
    d.update(kw)
    return GPTConfig(**d)


# --------------------------------------------------------------------------
# params
# --------------------------------------------------------------------------

def gpt_init(cfg: GPTConfig, seed: int = 0) -> Dict[str, Any]:
    """Init a param pytree; block leaves carry a leading layer dim.

    With ``moe_experts=E``: the dense MLP leaves shrink to the non-MoE
    layer count and a ``params["moe"]`` subtree (leading MoE-layer dim)
    holds the router + expert weights — attention/LN leaves keep the
    full layer stack either way. ``moe_experts=0`` draws the exact
    pre-MoE tree bit-for-bit (the dense key schedule is untouched)."""
    key = jax.random.key(seed)
    H, L, M, V, S = cfg.hidden, cfg.n_layers, cfg.mlp_hidden, cfg.vocab_size, cfg.seq_len
    pd = cfg.param_dtype
    std = 0.02
    ks = jax.random.split(key, 8)

    def nrm(k, shape, scale=std):
        return (scale * jax.random.normal(k, shape)).astype(pd)

    moe_ids = cfg.moe_layer_ids
    Ld = L - len(moe_ids)                 # dense-MLP layer count (== L
    #                                       when MoE is off: bit-identical)
    blocks = {
        "ln1_s": jnp.ones((L, H), pd),
        "ln1_b": jnp.zeros((L, H), pd),
        "qkv_w": nrm(ks[0], (L, H, 3 * H)),
        "qkv_b": jnp.zeros((L, 3 * H), pd),
        "proj_w": nrm(ks[1], (L, H, H), std / math.sqrt(2 * L)),
        "proj_b": jnp.zeros((L, H), pd),
        "ln2_s": jnp.ones((L, H), pd),
        "ln2_b": jnp.zeros((L, H), pd),
        "fc_w": nrm(ks[2], (Ld, H, M)),
        "fc_b": jnp.zeros((Ld, M), pd),
        "out_w": nrm(ks[3], (Ld, M, H), std / math.sqrt(2 * L)),
        "out_b": jnp.zeros((Ld, H), pd),
    }
    out = {
        "wte": nrm(ks[4], (V, H)),
        "wpe": nrm(ks[5], (S, H), 0.01),
        "blocks": blocks,
        "lnf_s": jnp.ones((H,), pd),
        "lnf_b": jnp.zeros((H,), pd),
    }
    if moe_ids:
        # moe keys derive from ks[6] (dense path never consumes it, so
        # the dense leaves above match the moe_experts=0 tree exactly)
        Lm, E = len(moe_ids), cfg.moe_experts
        mks = jax.random.split(ks[6], 3)
        out["moe"] = {
            "router_w": nrm(mks[0], (Lm, H, E)),
            "w_in": nrm(mks[1], (Lm, E, H, M)),
            "b_in": jnp.zeros((Lm, E, M), pd),
            "w_out": nrm(mks[2], (Lm, E, M, H), std / math.sqrt(2 * L)),
            "b_out": jnp.zeros((Lm, E, H), pd),
        }
    return out


def gpt_param_specs(cfg: GPTConfig) -> Dict[str, Any]:
    """PartitionSpec table: Megatron-style TP over "model", stages over
    "pipe". Mirrors what reference mp_layers + PipelineLayer produce.
    MoE expert leaves shard their EXPERT dim over "model" (expert
    parallelism — each shard holds E/ep whole experts, the layout the
    fleet.auto ``ep`` plans and the serving mesh decode assume)."""
    pipe = ("pipe",) if cfg.n_stages > 1 else ()
    b = lambda *rest: P(*(pipe + (None,) + rest))  # (stage?, layer, ...)
    out = {
        "wte": P("model", None),            # vocab-parallel embedding
        "wpe": P(),
        "blocks": {
            "ln1_s": b(None), "ln1_b": b(None),
            "qkv_w": b(None, "model"),      # column-parallel
            "qkv_b": b("model"),
            "proj_w": b("model", None),     # row-parallel
            "proj_b": b(None),
            "ln2_s": b(None), "ln2_b": b(None),
            "fc_w": b(None, "model"),       # column-parallel
            "fc_b": b("model"),
            "out_w": b("model", None),      # row-parallel
            "out_b": b(None),
        },
        "lnf_s": P(), "lnf_b": P(),
    }
    if cfg.moe_layer_ids:
        if len(cfg.moe_layer_ids) == cfg.n_layers:
            # every MLP routed: the dense leaves are zero-length stubs
            # (leading dim 0) and XLA pins zero-sized outputs replicated
            # — the TP spec would trip the out-sharding check
            for k in ("fc_w", "fc_b", "out_w", "out_b"):
                out["blocks"][k] = P()
        out["moe"] = {
            "router_w": P(),                       # tiny, replicated
            "w_in": P(None, "model", None, None),  # expert-parallel
            "b_in": P(None, "model", None),
            "w_out": P(None, "model", None, None),
            "b_out": P(None, "model", None),
        }
    return out


# --------------------------------------------------------------------------
# forward
# --------------------------------------------------------------------------

def _layer_norm(x, scale, bias, eps=1e-5):
    x32 = x.astype(jnp.float32)
    mu = jnp.mean(x32, axis=-1, keepdims=True)
    var = jnp.mean(jnp.square(x32 - mu), axis=-1, keepdims=True)
    y = (x32 - mu) * jax.lax.rsqrt(var + eps)
    return (y * scale + bias).astype(x.dtype)


def _attention(cfg: GPTConfig, q, k, v):
    scale = 1.0 / math.sqrt(cfg.head_dim)
    if cfg.ring_attention:
        # ring+flash: per-hop block compute is the Pallas kernel
        # (parallel/ring_flash.py); jnp blockwise reference off-TPU
        from ..parallel.ring_flash import ring_flash_attention_sharded
        return ring_flash_attention_sharded(q, k, v, causal=True, scale=scale,
                                      seq_axis=cfg.seq_axis,
                                      batch_axis="data", head_axis="model")
    # auto: measured on v5e — flash wins at seq >= 1024 always, and at 512
    # whenever remat is off (278 vs 260 sps BERT-base; the 512 loss only
    # appears under remat, which recomputes the fused kernel in the
    # backward); see bench.py flash_ab + tools/exp_bert.py
    use_flash = (cfg.use_flash if cfg.use_flash is not None
                 else (_on_tpu() and (q.shape[2] >= 1024
                                      or (q.shape[2] >= 512
                                          and not cfg.remat))))
    if use_flash:
        from ..ops.flash_attention import flash_attention_arrays
        return flash_attention_arrays(q, k, v, causal=True, scale=scale)
    return _attention_reference(q, k, v, causal=True, scale=scale)


def _attn_half(cfg: GPTConfig, p, x):
    """Attention half of a block (LN1 → QKV → attention → proj +
    residual); p leaves have no layer dim. Returns (x, (kh, vh))."""
    B, S, H = x.shape
    nh, hd = cfg.n_heads, cfg.head_dim
    cd = cfg.dtype

    h = _layer_norm(x, p["ln1_s"], p["ln1_b"])
    qkv = h @ p["qkv_w"].astype(cd) + p["qkv_b"].astype(cd)
    q, k, v = jnp.split(qkv, 3, axis=-1)
    to_heads = lambda t: t.reshape(B, S, nh, hd).transpose(0, 2, 1, 3)
    kh, vh = to_heads(k), to_heads(v)
    o = _attention(cfg, to_heads(q), kh, vh)
    o = o.transpose(0, 2, 1, 3).reshape(B, S, H)
    return x + o @ p["proj_w"].astype(cd) + p["proj_b"].astype(cd), (kh, vh)


def _mlp_half(cfg: GPTConfig, p, x):
    """Dense MLP half of a block (LN2 → gelu MLP + residual)."""
    cd = cfg.dtype
    fused = (cfg.fused_mlp if cfg.fused_mlp is not None
             else _native.fused_kernels[0])
    fp8 = cfg.fp8 if cfg.fp8 is not None else _fp8[0]
    if fp8:
        from ..amp.fp8 import fp8_linear

        h = _layer_norm(x, p["ln2_s"], p["ln2_b"])
        h = jax.nn.gelu(fp8_linear(h, p["fc_w"].astype(cd),
                                   p["fc_b"].astype(cd)))
        x = x + fp8_linear(h, p["out_w"].astype(cd), p["out_b"].astype(cd))
    elif fused:
        from ..ops.fused_kernels import fused_ln_mlp

        x = fused_ln_mlp(x, p["fc_w"].astype(cd), p["fc_b"].astype(cd),
                         p["out_w"].astype(cd), p["out_b"].astype(cd),
                         ln_scale=p["ln2_s"], ln_bias=p["ln2_b"],
                         residual=True, act="gelu")
    else:
        h = _layer_norm(x, p["ln2_s"], p["ln2_b"])
        h = jax.nn.gelu(h @ p["fc_w"].astype(cd) + p["fc_b"].astype(cd))
        x = x + h @ p["out_w"].astype(cd) + p["out_b"].astype(cd)
    return x


def _block_kv(cfg: GPTConfig, p, x):
    """One transformer block; p leaves have no layer dim. Also returns the
    per-head K/V ((B, nh, S, hd) each) so the prefill path can seed a KV
    cache; gpt_forward discards them (XLA DCEs the dead outputs)."""
    x, (kh, vh) = _attn_half(cfg, p, x)
    return _mlp_half(cfg, p, x), (kh, vh)


def _block(cfg: GPTConfig, p, x):
    """One transformer block; p leaves have no layer dim."""
    return _block_kv(cfg, p, x)[0]


def _block_stack(cfg: GPTConfig, blocks, x):
    """lax.scan over the leading layer dim (unrolled per cfg.scan_unroll)."""
    body = _block
    if cfg.remat:
        # keep non-batch matmul results (weights-only dots), recompute the
        # rest: measured equal to full remat at batch 16 and ~10% faster at
        # batch 32 on v5e (BERT-base)
        body = jax.checkpoint(
            body, static_argnums=(0,),
            policy=jax.checkpoint_policies.dots_with_no_batch_dims_saveable)

    def step(h, layer_p):
        return body(cfg, layer_p, h), None

    n_layers = jax.tree_util.tree_leaves(blocks)[0].shape[0]
    unroll = n_layers if cfg.scan_unroll is None \
        else max(1, min(int(cfg.scan_unroll), n_layers))
    x, _ = jax.lax.scan(step, x, blocks, unroll=unroll)
    return x


# -- mixture-of-experts blocks (ISSUE 18) -----------------------------------
# MoE layers break the homogeneous lax.scan stack (their MLP params live
# in a separate subtree with a different leading dim), so the MoE forward
# is a Python loop over per-layer leaves: one compiled body per layer.

_ATTN_KEYS = ("ln1_s", "ln1_b", "qkv_w", "qkv_b", "proj_w", "proj_b",
              "ln2_s", "ln2_b")
_MLP_KEYS = ("fc_w", "fc_b", "out_w", "out_b")
_MOE_KEYS = ("router_w", "w_in", "b_in", "w_out", "b_out")


def _layer_params(tree, i, keys):
    return {k: tree[k][i] for k in keys}


def _moe_mlp_half(cfg: GPTConfig, p, pm, x, capacity_factor):
    """MoE MLP half (LN2 → routed expert FFN + residual). x (B, S, H);
    returns (x, aux, z, counts (E,), dropped). Dropped assignments
    contribute nothing to y, so the residual passes those tokens
    through unchanged."""
    from ..nn.moe import moe_ffn

    B, S, H = x.shape
    h = _layer_norm(x, p["ln2_s"], p["ln2_b"])
    y, aux, z, counts, dropped = moe_ffn(
        pm, h.reshape(B * S, H), top_k=cfg.moe_top_k,
        capacity_factor=capacity_factor, expert_axis=cfg.moe_axis)
    return x + y.reshape(B, S, H), aux, z, counts, dropped


def _block_moe(cfg: GPTConfig, p, pm, x, capacity_factor):
    """One MoE transformer block (attention half + routed MLP half)."""
    x, _ = _attn_half(cfg, p, x)
    return _moe_mlp_half(cfg, p, pm, x, capacity_factor)


def _hidden_moe(cfg: GPTConfig, params, x, capacity_factor):
    """Block stack with MoE layers interleaved (Python loop — see module
    note above). Returns (x, aux_sum, z_sum, counts, dropped); aux/z
    are SUMS over the MoE layers, callers average by len(moe_layer_ids).
    ``capacity_factor=None`` routes droplessly (the inference mode)."""
    moe_ids = set(cfg.moe_layer_ids)
    blocks = params["blocks"]
    aux = jnp.float32(0.0)
    zl = jnp.float32(0.0)
    counts = jnp.zeros((cfg.moe_experts,), jnp.int32)
    dropped = jnp.int32(0)
    dense = _block
    moe = _block_moe
    if cfg.remat:
        policy = jax.checkpoint_policies.dots_with_no_batch_dims_saveable
        dense = jax.checkpoint(dense, static_argnums=(0,), policy=policy)
        moe = jax.checkpoint(moe, static_argnums=(0, 4), policy=policy)
    di = mi = 0
    for i in range(cfg.n_layers):
        pa = _layer_params(blocks, i, _ATTN_KEYS)
        if i in moe_ids:
            pm = _layer_params(params["moe"], mi, _MOE_KEYS)
            mi += 1
            x, a, z, c, d = moe(cfg, pa, pm, x, capacity_factor)
            aux, zl = aux + a, zl + z
            counts, dropped = counts + c, dropped + d
        else:
            pd = _layer_params(blocks, di, _MLP_KEYS)
            di += 1
            x = dense(cfg, {**pa, **pd}, x)
    return x, aux, zl, counts, dropped


def _embed(cfg: GPTConfig, params, tokens):
    emb = params["wte"].astype(cfg.dtype)[tokens]
    pos = params["wpe"].astype(cfg.dtype)[: tokens.shape[1]]
    return emb + pos[None, :, :]


def _logits(params, x, compute_dtype=jnp.bfloat16):
    # tied head. The matmul runs in bf16 on the MXU with fp32 ACCUMULATION
    # (preferred_element_type) — fp32 operands would run at 1/4 the MXU
    # rate for the single biggest matmul in the model (B·S×H×V), while the
    # fp32 accumulator keeps the softmax numerically stable. The returned
    # logits are fp32.
    return jnp.einsum("bsh,vh->bsv", x.astype(compute_dtype),
                      params["wte"].astype(compute_dtype),
                      preferred_element_type=jnp.float32)


def _head(cfg: GPTConfig, params, x):
    x = _layer_norm(x, params["lnf_s"], params["lnf_b"])
    return _logits(params, x)


def gpt_forward(cfg: GPTConfig, params, tokens):
    """tokens (B, S) int32 → logits (B, S, V).

    With cfg.n_stages > 1 the caller is expected to reshape the batch into
    microbatches and use parallel.pipeline_forward (see gpt_loss).
    MoE blocks route DROPLESSLY here (inference semantics — identical
    routing to every serving path regardless of batch composition).
    """
    x = _embed(cfg, params, tokens)
    if cfg.moe_layer_ids:
        x = _hidden_moe(cfg, params, x, None)[0]
    else:
        x = _block_stack(cfg, params["blocks"], x)
    return _head(cfg, params, x)


def _pipeline_hidden(cfg: GPTConfig, params, tokens, n_micro):
    """Embed → SPMD pipeline over stage-stacked blocks → hidden states."""
    from ..parallel.pipeline import pipeline_forward, stack_stages

    B, S = tokens.shape
    if B % n_micro != 0:
        raise ValueError(f"batch {B} not divisible by n_micro {n_micro}")
    x = _embed(cfg, params, tokens)
    # microbatch index on the INNER dim (x[i] interleaves the batch):
    # the batch's data/sharding tiling stays on the major dim through the
    # reshape, so forward and backward layouts cross the pipeline scan
    # without the SPMD partitioner's replicate-and-repartition fallback.
    mb = B // n_micro
    x_micro = x.reshape(mb, n_micro, S, cfg.hidden).transpose(1, 0, 2, 3)
    stage_params = params["blocks"]
    if stage_params["qkv_w"].ndim == 3:  # flat (L, H, 3H) — not yet staged
        stage_params = stack_stages(stage_params, cfg.n_stages)

    def stage_fn(sp, h):
        return _block_stack(cfg, sp, h)

    h = pipeline_forward(stage_fn, stage_params, x_micro, cfg.n_stages)
    return h.transpose(1, 0, 2, 3).reshape(B, S, cfg.hidden)


def _chunked_ce(params, x, labels, chunk: int):
    """Cross entropy without materializing (B, S, V) logits: the final LN'd
    hiddens are processed in sequence chunks; each chunk's logits live only
    inside its scan iteration (remat'd), so peak memory is (B, chunk, V) —
    the (B,S,V) fp32 logits buffer (~1GB at BERT-base/batch16) never
    exists. HBM-bound loss → big memory headroom for larger batch."""
    B, S, H = x.shape
    n_chunks = S // chunk
    xc = x.reshape(B, n_chunks, chunk, H).transpose(1, 0, 2, 3)
    lc = labels.reshape(B, n_chunks, chunk).transpose(1, 0, 2)

    @jax.checkpoint
    def one(args):
        xs, ls = args
        logp = jax.nn.log_softmax(_logits(params, xs), axis=-1)
        return -jnp.sum(jnp.take_along_axis(logp, ls[..., None], axis=-1))

    total = jnp.sum(jax.lax.map(one, (xc, lc)))
    return total / (B * S)


def gpt_loss(cfg: GPTConfig, params, batch, n_micro: int = 1,
             loss_chunk: Optional[int] = None):
    """Causal-LM cross entropy. batch = (tokens, labels), both (B, S).

    ``loss_chunk``: sequence-chunked CE — peak-memory saver for huge vocab
    or long seq (full (B,S,V) fp32 logits never materialize); measured
    ~10% slower than the fused full-logits path at BERT-base scale, so off
    by default.

    MoE configs add the router regularizers to the CE:
    ``moe_aux_weight · mean-layer aux + moe_z_weight · mean-layer z``,
    with capacity-factor dispatch (drops + residual passthrough)."""
    tokens, labels = batch
    moe_ids = cfg.moe_layer_ids
    aux = zl = None
    if cfg.n_stages > 1:
        if moe_ids:
            raise ValueError(
                "MoE (moe_experts>0) and pipeline stages (n_stages>1) "
                "are not combinable yet — the MoE subtree has no stage "
                "stacking")
        if n_micro < cfg.n_stages:
            raise ValueError(
                f"n_micro={n_micro} must be >= n_stages={cfg.n_stages} "
                "(fewer microbatches than stages leaves the pipeline idle)")
        x = _pipeline_hidden(cfg, params, tokens, n_micro)
    else:
        x = _embed(cfg, params, tokens)
        if moe_ids:
            x, aux, zl, _, _ = _hidden_moe(cfg, params, x,
                                           cfg.moe_capacity_factor)
        else:
            x = _block_stack(cfg, params["blocks"], x)
    x = _layer_norm(x, params["lnf_s"], params["lnf_b"])
    if loss_chunk and tokens.shape[1] > loss_chunk:
        if tokens.shape[1] % loss_chunk != 0:
            raise ValueError(
                f"loss_chunk={loss_chunk} must divide seq_len="
                f"{tokens.shape[1]} (the memory saver would otherwise be "
                "silently disabled)")
        ce = _chunked_ce(params, x, labels, loss_chunk)
    else:
        logp = jax.nn.log_softmax(_logits(params, x), axis=-1)
        ll = jnp.take_along_axis(logp, labels[..., None], axis=-1)[..., 0]
        ce = -jnp.mean(ll)
    if aux is not None:
        n = len(moe_ids)
        ce = ce + cfg.moe_aux_weight * (aux / n) \
            + cfg.moe_z_weight * (zl / n)
    return ce


# --------------------------------------------------------------------------
# KV-cache autoregressive serving path (paddle_tpu.serving, ISSUE 4)
# --------------------------------------------------------------------------
#
# The reference's inference stack recomputes nothing either — its
# AnalysisPredictor serves a compiled program; generation loops over it.
# Here the generation loop gets its own pair of pure functions so the
# serving engine can jit them once:
#
# - gpt_prefill: one causal pass over the whole prompt that ALSO emits the
#   per-layer K/V it computed, so a cache slot can be seeded in the same
#   program (causality makes those K/V exact: hidden state at position s
#   never sees positions > s, so end-padding a prompt is safe).
# - gpt_decode_step: batched one-token step — each sequence's new K/V is
#   scattered into its cache slot at ``positions`` and the single query
#   attends over the slot masked to ``pos <= positions``. O(S·H) per token
#   instead of gpt_forward's O(S·H² + S²·H) full recompute.
#
# Both run over the cache layout paddle_tpu.serving.KVCache owns:
# (slots, layers, heads, max_len, head_dim). Stage-stacked (n_stages > 1)
# param trees are a training layout; serving expects the flat (L, ...)
# blocks gpt_init produces.

def quantize_gpt_weights(params, names=("qkv_w", "proj_w", "fc_w",
                                        "out_w")):
    """Per-channel int8 weight quantization of the block matmuls.

    Each named (L, K, N) block weight becomes ``{"q": int8 (L, K, N),
    "s": f32 (L, N)}`` (s is the dequant multiplier absmax/127, reduced
    over the contraction dim). The resulting tree feeds
    :func:`gpt_decode_step` — ``_block_decode`` routes dict-typed
    weights through the Pallas int8 matmul with dynamic per-tensor
    activation quantization (ops/int8_matmul.py). Embedding/logits stay
    fp (the tied wte doubles as the lookup table). First consumer:
    ``serving.InferenceEngine(int8_weights=True)``."""
    out = dict(params)
    blocks = dict(params["blocks"])
    for name in names:
        w = jnp.asarray(blocks[name], jnp.float32)
        s = jnp.maximum(jnp.max(jnp.abs(w), axis=1), 1e-8) / 127.0
        q = jnp.clip(jnp.round(w / s[:, None, :]), -127, 127)
        blocks[name] = {"q": q.astype(jnp.int8), "s": s}
    out["blocks"] = blocks
    return out


def _dec_mm(x, w, cd):
    """x @ w for a maybe-int8-quantized decode weight (see
    quantize_gpt_weights)."""
    if isinstance(w, dict):
        from ..ops.int8_matmul import dynamic_int8_matmul

        return dynamic_int8_matmul(x, w["q"], w["s"]).astype(cd)
    return x @ w.astype(cd)


def _dec_attn(cfg: GPTConfig, p, x, kc_l, vc_l, positions):
    """Attention half of the one-token block step (cache write + attend
    + proj residual). x (B, 1, H); kc_l/vc_l (B, nh, max_len, hd);
    positions (B,) int32. Returns (x, updated kc_l, updated vc_l)."""
    B = x.shape[0]
    nh, hd = cfg.n_heads, cfg.head_dim
    cd = cfg.dtype

    h = _layer_norm(x, p["ln1_s"], p["ln1_b"])
    qkv = _dec_mm(h, p["qkv_w"], cd) + p["qkv_b"].astype(cd)
    q, k, v = jnp.split(qkv, 3, axis=-1)         # each (B, 1, H)
    to_heads = lambda t: t.reshape(B, nh, hd)
    q, k, v = to_heads(q), to_heads(k), to_heads(v)

    def write(c, new, pos):  # c (nh, max_len, hd), new (nh, hd)
        return jax.lax.dynamic_update_slice(c, new[:, None, :], (0, pos, 0))

    kc_l = jax.vmap(write)(kc_l, k, positions)
    vc_l = jax.vmap(write)(vc_l, v, positions)

    # same numerics as _attention_reference: scores in compute dtype,
    # softmax in fp32; padded/garbage cache positions are masked off
    scale = 1.0 / math.sqrt(hd)
    s = jnp.einsum("bhd,bhkd->bhk", q, kc_l) * scale
    live = jnp.arange(kc_l.shape[2])[None, :] <= positions[:, None]
    s = jnp.where(live[:, None, :], s, NEG_INF)
    w = jax.nn.softmax(s.astype(jnp.float32), axis=-1).astype(q.dtype)
    o = jnp.einsum("bhk,bhkd->bhd", w, vc_l).reshape(B, 1, nh * hd)

    x = x + _dec_mm(o, p["proj_w"], cd) + p["proj_b"].astype(cd)
    return x, kc_l, vc_l


def _dec_mlp(cfg: GPTConfig, p, x):
    """Dense MLP half of the one-token block step (LN2 → gelu MLP +
    residual; weights may be int8-quantized dicts)."""
    cd = cfg.dtype
    h = _layer_norm(x, p["ln2_s"], p["ln2_b"])
    h = jax.nn.gelu(_dec_mm(h, p["fc_w"], cd) + p["fc_b"].astype(cd))
    return x + _dec_mm(h, p["out_w"], cd) + p["out_b"].astype(cd)


def _dec_moe_mlp(cfg: GPTConfig, pa, pm, x):
    """MoE MLP half of the one-token block step — DROPLESS, so decode
    quality never depends on which requests share the tick. x (B, 1, H);
    returns (x, counts (E,) i32, dropped i32)."""
    from ..nn.moe import moe_ffn

    B = x.shape[0]
    h = _layer_norm(x, pa["ln2_s"], pa["ln2_b"])
    y, _, _, counts, dropped = moe_ffn(
        pm, h.reshape(B, -1), top_k=cfg.moe_top_k, capacity_factor=None,
        expert_axis=cfg.moe_axis)
    return x + y.reshape(x.shape), counts, dropped


def _block_decode(cfg: GPTConfig, p, x, kc_l, vc_l, positions):
    """One-token block step against one layer's cache slice.

    x (B, 1, H); kc_l/vc_l (B, nh, max_len, hd) — this layer's cache for
    every slot; positions (B,) int32 — where each slot's incoming token
    lands. Block weights may be int8-quantized dicts (see
    quantize_gpt_weights). Returns (x, updated kc_l, updated vc_l)."""
    x, kc_l, vc_l = _dec_attn(cfg, p, x, kc_l, vc_l, positions)
    return _dec_mlp(cfg, p, x), kc_l, vc_l


def gpt_prefill(cfg: GPTConfig, params, tokens):
    """tokens (B, S) int32 → (logits (B, S, V) fp32, cache_entries).

    cache_entries = (k, v), each (B, L, nh, S, hd) in cfg.dtype — exactly
    the K/V gpt_forward computes for those positions, slot-major so a
    whole prompt drops into a KVCache slot with one dynamic_update_slice
    (serving.kv_cache.cache_insert)."""
    x = _embed(cfg, params, tokens)

    if cfg.moe_layer_ids:
        # MoE stacks are heterogeneous (see _hidden_moe) — Python loop,
        # dropless routing, K/V collected per layer then stacked
        moe_ids = set(cfg.moe_layer_ids)
        blocks = params["blocks"]
        ks, vs = [], []
        di = mi = 0
        for i in range(cfg.n_layers):
            pa = _layer_params(blocks, i, _ATTN_KEYS)
            x, (kh, vh) = _attn_half(cfg, pa, x)
            ks.append(kh)
            vs.append(vh)
            if i in moe_ids:
                pm = _layer_params(params["moe"], mi, _MOE_KEYS)
                mi += 1
                x = _moe_mlp_half(cfg, pa, pm, x, None)[0]
            else:
                pd = _layer_params(blocks, di, _MLP_KEYS)
                di += 1
                x = _mlp_half(cfg, {**pa, **pd}, x)
        return _head(cfg, params, x), (jnp.stack(ks, axis=1),
                                       jnp.stack(vs, axis=1))

    def step(h, layer_p):
        h, kv = _block_kv(cfg, layer_p, h)
        return h, kv

    x, (ks, vs) = jax.lax.scan(step, x, params["blocks"])
    # (L, B, nh, S, hd) → (B, L, nh, S, hd)
    return _head(cfg, params, x), (jnp.moveaxis(ks, 0, 1),
                                   jnp.moveaxis(vs, 0, 1))


def gpt_decode_step(cfg: GPTConfig, params, cache, positions, tokens):
    """Batched one-token decode against a slotted KV cache.

    cache = (k, v), each (B, L, nh, max_len, hd); positions (B,) int32 —
    the index each incoming token occupies (== tokens already cached in
    that slot); tokens (B,) int32. Returns (logits (B, V) fp32, new cache)
    with the new tokens' K/V written at ``positions``. Slots whose
    position/token are stale (unoccupied engine slots) compute garbage
    that later prefills overwrite — callers mask host-side.

    MoE configs return a THIRD element ``(counts (E,) i32, dropped i32)``
    — per-tick router load for the serving gauges (dropless routing, so
    dropped stays 0 by construction; the counter is a guard)."""
    k_cache, v_cache = cache
    cd = cfg.dtype
    L = k_cache.shape[1]
    x = (params["wte"].astype(cd)[tokens]
         + params["wpe"].astype(cd)[positions])[:, None, :]   # (B, 1, H)

    if cfg.moe_layer_ids:
        moe_ids = set(cfg.moe_layer_ids)
        blocks = params["blocks"]
        counts = jnp.zeros((cfg.moe_experts,), jnp.int32)
        dropped = jnp.int32(0)
        di = mi = 0
        for i in range(cfg.n_layers):
            pa = _layer_params(blocks, i, _ATTN_KEYS)
            x, kc_l, vc_l = _dec_attn(cfg, pa, x, k_cache[:, i],
                                      v_cache[:, i], positions)
            k_cache = k_cache.at[:, i].set(kc_l)
            v_cache = v_cache.at[:, i].set(vc_l)
            if i in moe_ids:
                pm = _layer_params(params["moe"], mi, _MOE_KEYS)
                mi += 1
                x, c, d = _dec_moe_mlp(cfg, pa, pm, x)
                counts, dropped = counts + c, dropped + d
            else:
                pd = _layer_params(blocks, di, _MLP_KEYS)
                di += 1
                x = _dec_mlp(cfg, {**pa, **pd}, x)
        return (_head(cfg, params, x)[:, 0], (k_cache, v_cache),
                (counts, dropped))

    def step(carry, inp):
        x, kc, vc = carry
        layer_p, li = inp
        kc_l = jnp.take(kc, li, axis=1)
        vc_l = jnp.take(vc, li, axis=1)
        x, kc_l, vc_l = _block_decode(cfg, layer_p, x, kc_l, vc_l, positions)
        kc = jax.lax.dynamic_update_index_in_dim(kc, kc_l, li, 1)
        vc = jax.lax.dynamic_update_index_in_dim(vc, vc_l, li, 1)
        return (x, kc, vc), None

    (x, k_cache, v_cache), _ = jax.lax.scan(
        step, (x, k_cache, v_cache), (params["blocks"], jnp.arange(L)))
    return _head(cfg, params, x)[:, 0], (k_cache, v_cache)


def _block_verify(cfg: GPTConfig, p, x, kc_l, vc_l, positions):
    """C-token block step against one layer's cache slice (ISSUE 10 —
    the speculative-decoding verify shape, also a batched chunk append).

    x (B, C, H); kc_l/vc_l (B, nh, max_len, hd); positions (B,) int32 —
    the index the FIRST incoming token occupies; token j of a row lands
    at ``positions + j``. The C new K/V rows are one contiguous span, so
    ONE dynamic_update_slice per slot writes them all; each query j then
    attends over the slot masked to ``pos <= positions + j`` — the math
    per query equals :func:`_block_decode` run token-by-token."""
    B, C, _ = x.shape
    nh, hd = cfg.n_heads, cfg.head_dim
    cd = cfg.dtype

    h = _layer_norm(x, p["ln1_s"], p["ln1_b"])
    qkv = _dec_mm(h, p["qkv_w"], cd) + p["qkv_b"].astype(cd)
    q, k, v = jnp.split(qkv, 3, axis=-1)          # each (B, C, H)
    to_heads = lambda t: t.reshape(B, C, nh, hd).transpose(0, 2, 1, 3)
    q, k, v = to_heads(q), to_heads(k), to_heads(v)   # (B, nh, C, hd)

    def write(c, new, pos):  # c (nh, max_len, hd), new (nh, C, hd)
        return jax.lax.dynamic_update_slice(c, new, (0, pos, 0))

    kc_l = jax.vmap(write)(kc_l, k.astype(kc_l.dtype), positions)
    vc_l = jax.vmap(write)(vc_l, v.astype(vc_l.dtype), positions)

    scale = 1.0 / math.sqrt(hd)
    s = jnp.einsum("bhqd,bhkd->bhqk", q, kc_l.astype(q.dtype)) * scale
    qpos = positions[:, None] + jnp.arange(C)[None, :]        # (B, C)
    live = jnp.arange(kc_l.shape[2])[None, None, :] <= qpos[:, :, None]
    s = jnp.where(live[:, None], s, NEG_INF)
    w = jax.nn.softmax(s.astype(jnp.float32), axis=-1).astype(q.dtype)
    o = jnp.einsum("bhqk,bhkd->bhqd", w, vc_l.astype(q.dtype))
    o = o.transpose(0, 2, 1, 3).reshape(B, C, nh * hd)

    x = x + _dec_mm(o, p["proj_w"], cd) + p["proj_b"].astype(cd)
    h = _layer_norm(x, p["ln2_s"], p["ln2_b"])
    h = jax.nn.gelu(_dec_mm(h, p["fc_w"], cd) + p["fc_b"].astype(cd))
    x = x + _dec_mm(h, p["out_w"], cd) + p["out_b"].astype(cd)
    return x, kc_l, vc_l


def gpt_verify_step(cfg: GPTConfig, params, cache, positions, tokens):
    """Batched MULTI-token decode against a slotted KV cache (ISSUE 10).

    cache = (k, v), each (B, L, nh, max_len, hd); positions (B,) int32 —
    where each row's FIRST token lands (token j at ``positions + j``);
    tokens (B, C) int32. Returns (logits (B, C, V) fp32, new cache):
    logits[:, j] is the next-token distribution after consuming tokens
    ``[..j]`` — exactly what gpt_decode_step would return fed the same
    tokens one at a time, in ONE program. This is the
    speculative-decoding verify pass: the target model scores a draft's
    k proposals plus the bonus position in a single dispatch. The caller
    must guarantee ``positions + C <= max_len`` (the engine's headroom
    check); rows whose later entries are rejected leave stale K/V past
    the accepted length, which the position mask hides until the next
    step overwrites them."""
    if cfg.moe_layer_ids:
        raise ValueError(
            "gpt_verify_step does not support MoE configs (the engine "
            "rejects speculative decoding with moe_experts > 0)")
    k_cache, v_cache = cache
    cd = cfg.dtype
    L = k_cache.shape[1]
    C = tokens.shape[1]
    qpos = positions[:, None] + jnp.arange(C)[None, :]
    x = params["wte"].astype(cd)[tokens] + params["wpe"].astype(cd)[qpos]

    def step(carry, inp):
        x, kc, vc = carry
        layer_p, li = inp
        kc_l = jnp.take(kc, li, axis=1)
        vc_l = jnp.take(vc, li, axis=1)
        x, kc_l, vc_l = _block_verify(cfg, layer_p, x, kc_l, vc_l, positions)
        kc = jax.lax.dynamic_update_index_in_dim(kc, kc_l, li, 1)
        vc = jax.lax.dynamic_update_index_in_dim(vc, vc_l, li, 1)
        return (x, kc, vc), None

    (x, k_cache, v_cache), _ = jax.lax.scan(
        step, (x, k_cache, v_cache), (params["blocks"], jnp.arange(L)))
    return _head(cfg, params, x), (k_cache, v_cache)


# --------------------------------------------------------------------------
# Paged KV cache variants (serving.PagedKVCache, ISSUE 7)
# --------------------------------------------------------------------------
#
# Same contract as gpt_prefill/gpt_decode_step, but the cache is a shared
# BLOCK POOL (n_blocks, L, nh, block_size, hd) addressed through per-slot
# block tables instead of one contiguous max_len strip per slot, so cache
# memory is proportional to live tokens. Pool block 0 is reserved as the
# garbage sink: table padding (and whole tables of unoccupied slots)
# point at it, so stale batch lanes scatter their garbage K/V somewhere
# no live slot ever reads.

def _dec_attn_paged(cfg: GPTConfig, p, x, kb_l, vb_l, tables, positions):
    """Attention half of the paged one-token block step (pool write +
    paged attention + proj residual). Returns (x, kb_l, vb_l)."""
    B = x.shape[0]
    nh, hd = cfg.n_heads, cfg.head_dim
    bs = kb_l.shape[2]
    cd = cfg.dtype

    h = _layer_norm(x, p["ln1_s"], p["ln1_b"])
    qkv = _dec_mm(h, p["qkv_w"], cd) + p["qkv_b"].astype(cd)
    q, k, v = jnp.split(qkv, 3, axis=-1)         # each (B, 1, H)
    to_heads = lambda t: t.reshape(B, nh, hd)
    q, k, v = to_heads(q), to_heads(k), to_heads(v)

    # scatter each slot's new K/V into (its block, its offset); slots own
    # their blocks exclusively so the only index collisions are stale
    # lanes colliding on garbage block 0
    blk = jnp.take_along_axis(tables, (positions // bs)[:, None], axis=1)[:, 0]
    off = positions % bs
    kb_l = kb_l.at[blk, :, off, :].set(k.astype(kb_l.dtype))
    vb_l = vb_l.at[blk, :, off, :].set(v.astype(vb_l.dtype))

    from ..ops.paged_attention import paged_attention_arrays
    o = paged_attention_arrays(q, kb_l, vb_l, tables, positions + 1,
                               scale=1.0 / math.sqrt(hd))
    o = o.reshape(B, 1, nh * hd)

    x = x + _dec_mm(o, p["proj_w"], cd) + p["proj_b"].astype(cd)
    return x, kb_l, vb_l


def _block_decode_paged(cfg: GPTConfig, p, x, kb_l, vb_l, tables, positions):
    """One-token block step against one layer's slice of the block pool.

    x (B, 1, H); kb_l/vb_l (n_blocks, nh, block_size, hd); tables (B, W)
    int32; positions (B,) int32 — where each slot's incoming token
    lands. Attention routes through ops.paged_attention (Pallas kernel
    on TPU, identical composed gather elsewhere)."""
    x, kb_l, vb_l = _dec_attn_paged(cfg, p, x, kb_l, vb_l, tables,
                                    positions)
    return _dec_mlp(cfg, p, x), kb_l, vb_l


def gpt_decode_step_paged(cfg: GPTConfig, params, pool, tables, positions,
                          tokens):
    """Batched one-token decode against a paged block pool.

    pool = (kb, vb), each (n_blocks, L, nh, block_size, hd); tables
    (B, W) int32 per-slot block tables (padding/stale rows point at
    reserved block 0); positions/tokens (B,) int32. Returns
    (logits (B, V) fp32, new pool) with the new tokens' K/V written at
    block ``tables[b, positions[b] // block_size]``, offset
    ``positions[b] % block_size``. Numerics match gpt_decode_step over
    the same live positions; MoE configs return the same third
    ``(counts, dropped)`` element gpt_decode_step does."""
    kb, vb = pool
    cd = cfg.dtype
    L = kb.shape[1]
    x = (params["wte"].astype(cd)[tokens]
         + params["wpe"].astype(cd)[positions])[:, None, :]   # (B, 1, H)

    if cfg.moe_layer_ids:
        moe_ids = set(cfg.moe_layer_ids)
        blocks = params["blocks"]
        counts = jnp.zeros((cfg.moe_experts,), jnp.int32)
        dropped = jnp.int32(0)
        di = mi = 0
        for i in range(cfg.n_layers):
            pa = _layer_params(blocks, i, _ATTN_KEYS)
            x, kb_l, vb_l = _dec_attn_paged(cfg, pa, x, kb[:, i], vb[:, i],
                                            tables, positions)
            kb = kb.at[:, i].set(kb_l)
            vb = vb.at[:, i].set(vb_l)
            if i in moe_ids:
                pm = _layer_params(params["moe"], mi, _MOE_KEYS)
                mi += 1
                x, c, d = _dec_moe_mlp(cfg, pa, pm, x)
                counts, dropped = counts + c, dropped + d
            else:
                pd = _layer_params(blocks, di, _MLP_KEYS)
                di += 1
                x = _dec_mlp(cfg, {**pa, **pd}, x)
        return (_head(cfg, params, x)[:, 0], (kb, vb), (counts, dropped))

    def step(carry, inp):
        x, kb, vb = carry
        layer_p, li = inp
        kb_l = jnp.take(kb, li, axis=1)
        vb_l = jnp.take(vb, li, axis=1)
        x, kb_l, vb_l = _block_decode_paged(cfg, layer_p, x, kb_l, vb_l,
                                            tables, positions)
        kb = jax.lax.dynamic_update_index_in_dim(kb, kb_l, li, 1)
        vb = jax.lax.dynamic_update_index_in_dim(vb, vb_l, li, 1)
        return (x, kb, vb), None

    (x, kb, vb), _ = jax.lax.scan(
        step, (x, kb, vb), (params["blocks"], jnp.arange(L)))
    return _head(cfg, params, x)[:, 0], (kb, vb)


def _block_verify_paged(cfg: GPTConfig, p, x, kb_l, vb_l, tables,
                        positions):
    """C-token block step against one layer's slice of the block pool.

    x (B, C, H); kb_l/vb_l (n_blocks, nh, block_size, hd); tables (B, W)
    int32; positions (B,) int32 — token j of row b lands at block
    ``tables[b, (positions[b]+j) // bs]``, offset ``(positions[b]+j) %
    bs``. Attention is the composed table gather (the multi-query shape
    the Pallas decode kernel does not cover); the table width W is
    already bucketed by the engine, so gather work tracks live tokens."""
    B, C, _ = x.shape
    nh, hd = cfg.n_heads, cfg.head_dim
    bs = kb_l.shape[2]
    cd = cfg.dtype
    W = tables.shape[1]

    h = _layer_norm(x, p["ln1_s"], p["ln1_b"])
    qkv = _dec_mm(h, p["qkv_w"], cd) + p["qkv_b"].astype(cd)
    q, k, v = jnp.split(qkv, 3, axis=-1)          # each (B, C, H)
    qh = q.reshape(B, C, nh, hd).transpose(0, 2, 1, 3)   # (B, nh, C, hd)
    kh = k.reshape(B, C, nh, hd)
    vh = v.reshape(B, C, nh, hd)

    # scatter the C new K/V of every row; live slots own their blocks
    # exclusively (positions contiguous), so the only index collisions
    # are stale lanes piling onto their garbage sink
    qpos = positions[:, None] + jnp.arange(C)[None, :]        # (B, C)
    blk = jnp.take_along_axis(tables, qpos // bs, axis=1)
    off = qpos % bs
    kb_l = kb_l.at[blk, :, off, :].set(kh.astype(kb_l.dtype))
    vb_l = vb_l.at[blk, :, off, :].set(vh.astype(vb_l.dtype))

    kg = kb_l[tables].transpose(0, 2, 1, 3, 4).reshape(B, nh, W * bs, hd)
    vg = vb_l[tables].transpose(0, 2, 1, 3, 4).reshape(B, nh, W * bs, hd)
    s = jnp.einsum("bhqd,bhkd->bhqk", qh, kg.astype(qh.dtype)) \
        * (1.0 / math.sqrt(hd))
    live = jnp.arange(W * bs)[None, None, :] <= qpos[:, :, None]
    s = jnp.where(live[:, None], s, NEG_INF)
    w = jax.nn.softmax(s.astype(jnp.float32), axis=-1).astype(qh.dtype)
    o = jnp.einsum("bhqk,bhkd->bhqd", w, vg.astype(qh.dtype))
    o = o.transpose(0, 2, 1, 3).reshape(B, C, nh * hd)

    x = x + _dec_mm(o, p["proj_w"], cd) + p["proj_b"].astype(cd)
    h = _layer_norm(x, p["ln2_s"], p["ln2_b"])
    h = jax.nn.gelu(_dec_mm(h, p["fc_w"], cd) + p["fc_b"].astype(cd))
    x = x + _dec_mm(h, p["out_w"], cd) + p["out_b"].astype(cd)
    return x, kb_l, vb_l


def gpt_verify_step_paged(cfg: GPTConfig, params, pool, tables, positions,
                          tokens):
    """Batched multi-token decode against a paged block pool (ISSUE 10).

    pool = (kb, vb), each (n_blocks, L, nh, block_size, hd); tables
    (B, W) int32; positions (B,) int32 — the first token's index per
    row; tokens (B, C) int32. Returns (logits (B, C, V) fp32, new pool).
    Same per-query math as gpt_decode_step_paged; the caller must have
    grown each live row's table to cover ``positions + C`` tokens (the
    engine's speculative grow), and stale lanes scatter onto their
    garbage sink exactly like the single-token step."""
    if cfg.moe_layer_ids:
        raise ValueError(
            "gpt_verify_step_paged does not support MoE configs (the "
            "engine rejects speculative decoding and prefix caching "
            "with moe_experts > 0)")
    kb, vb = pool
    L = kb.shape[1]

    def step(carry, inp):
        x, kb, vb = carry
        layer_p, li = inp
        kb_l = jnp.take(kb, li, axis=1)
        vb_l = jnp.take(vb, li, axis=1)
        x, kb_l, vb_l = _block_verify_paged(cfg, layer_p, x, kb_l, vb_l,
                                            tables, positions)
        kb = jax.lax.dynamic_update_index_in_dim(kb, kb_l, li, 1)
        vb = jax.lax.dynamic_update_index_in_dim(vb, vb_l, li, 1)
        return (x, kb, vb), None

    cd = cfg.dtype
    C = tokens.shape[1]
    qpos = positions[:, None] + jnp.arange(C)[None, :]
    x = params["wte"].astype(cd)[tokens] + params["wpe"].astype(cd)[qpos]
    (x, kb, vb), _ = jax.lax.scan(
        step, (x, kb, vb), (params["blocks"], jnp.arange(L)))
    return _head(cfg, params, x), (kb, vb)


def gpt_prefill_prefix(cfg: GPTConfig, params, pool, table_row, tokens,
                       start):
    """Prefill continuing from an arbitrary cached prefix (ISSUE 11 —
    the radix prefix cache's tail entry point).

    Like :func:`gpt_prefill_chunk`, but ``start`` (tokens already cached
    for this slot) need NOT be block-aligned: a prefix-cache match ends
    wherever the shared prompt diverges, often mid-block (the engine has
    already copy-on-write-duplicated that block, so the scatter below
    writes a private copy). Routes through the batched verify math
    (:func:`gpt_verify_step_paged` at B=1): token j of ``tokens``
    (1, C) lands at position ``start + j`` through ``table_row``'s
    block/offset lookup, and each query attends over the WHOLE cached
    prefix — matched blocks included — masked to ``pos <= start + j``,
    so logits at chunk position i equal :func:`gpt_prefill`'s at global
    position ``start + i`` over the same tokens. Padded tail positions
    scatter garbage through sink-padded table entries nobody reads.
    Returns (logits (1, C, V) fp32, updated pool)."""
    return gpt_verify_step_paged(cfg, params, pool, table_row[None, :],
                                 jnp.reshape(start, (1,)).astype(jnp.int32),
                                 tokens)


def _chunk_attn(cfg: GPTConfig, p, x, kb_l, vb_l, table_row, start):
    """Attention half of the chunked-prefill block step (pool write +
    full-prefix attention + proj residual). Returns (x, kb_l, vb_l)."""
    _, C, H = x.shape
    nh, hd = cfg.n_heads, cfg.head_dim
    bs = kb_l.shape[2]
    cd = cfg.dtype
    W = table_row.shape[0]

    h = _layer_norm(x, p["ln1_s"], p["ln1_b"])
    qkv = h @ p["qkv_w"].astype(cd) + p["qkv_b"].astype(cd)
    q, k, v = jnp.split(qkv, 3, axis=-1)
    to_heads = lambda t: t[0].reshape(C, nh, hd).transpose(1, 0, 2)
    q, k, v = to_heads(q), to_heads(k), to_heads(v)   # (nh, C, hd)

    for j in range(C // bs):
        bid = jnp.take(table_row, start // bs + j)
        kb_l = jax.lax.dynamic_update_slice(
            kb_l, k[None, :, j * bs:(j + 1) * bs].astype(kb_l.dtype),
            (bid, 0, 0, 0))
        vb_l = jax.lax.dynamic_update_slice(
            vb_l, v[None, :, j * bs:(j + 1) * bs].astype(vb_l.dtype),
            (bid, 0, 0, 0))

    kg = kb_l[table_row].transpose(1, 0, 2, 3).reshape(nh, W * bs, hd)
    vg = vb_l[table_row].transpose(1, 0, 2, 3).reshape(nh, W * bs, hd)
    s = jnp.einsum("hqd,hkd->hqk", q, kg.astype(q.dtype)) \
        * (1.0 / math.sqrt(hd))
    live = jnp.arange(W * bs)[None, :] <= (start + jnp.arange(C))[:, None]
    s = jnp.where(live[None], s, NEG_INF)
    w = jax.nn.softmax(s.astype(jnp.float32), axis=-1).astype(q.dtype)
    o = jnp.einsum("hqk,hkd->hqd", w, vg.astype(q.dtype))
    o = o.transpose(1, 0, 2).reshape(1, C, H)

    return x + o @ p["proj_w"].astype(cd) + p["proj_b"].astype(cd), \
        kb_l, vb_l


def _block_chunk(cfg: GPTConfig, p, x, kb_l, vb_l, table_row, start):
    """One transformer block over one prefill CHUNK against the pool.

    x (1, C, H) — C is the block_size-padded chunk length; kb_l/vb_l
    (n_blocks, nh, block_size, hd); table_row (W,) int32 — this slot's
    table; start — tokens already cached (block-aligned, traced). The
    chunk's K/V are written into the pool FIRST, then chunk queries
    attend over every cached position (previous chunks + the chunk
    itself) under the global causal mask, so the math equals one whole
    causal pass over the same prefix."""
    cd = cfg.dtype
    x, kb_l, vb_l = _chunk_attn(cfg, p, x, kb_l, vb_l, table_row, start)
    h = _layer_norm(x, p["ln2_s"], p["ln2_b"])
    h = jax.nn.gelu(h @ p["fc_w"].astype(cd) + p["fc_b"].astype(cd))
    x = x + h @ p["out_w"].astype(cd) + p["out_b"].astype(cd)
    return x, kb_l, vb_l


def gpt_prefill_chunk(cfg: GPTConfig, params, pool, table_row, tokens,
                      start):
    """One chunk of a paged, chunked prefill.

    tokens (1, C) int32 — the next C prompt tokens, end-padded to a
    multiple of block_size (one compile per padded chunk length); start
    — tokens already cached for this slot, a block_size multiple (the
    engine chunks at prefill_chunk % block_size == 0 boundaries);
    table_row (W,) int32 must already cover positions < start + C.
    Returns (logits (1, C, V) fp32, updated pool): logits at position i
    equal gpt_prefill's at global position start + i, because every
    chunk attends over the full cached prefix (padded tail positions
    produce garbage nobody reads — decode overwrites them before ever
    attending)."""
    kb, vb = pool
    cd = cfg.dtype
    C = tokens.shape[1]
    L = kb.shape[1]

    pos_emb = jax.lax.dynamic_slice(
        params["wpe"], (start, 0), (C, params["wpe"].shape[1]))
    x = params["wte"].astype(cd)[tokens] + pos_emb.astype(cd)[None]

    if cfg.moe_layer_ids:
        moe_ids = set(cfg.moe_layer_ids)
        blocks = params["blocks"]
        di = mi = 0
        for i in range(cfg.n_layers):
            pa = _layer_params(blocks, i, _ATTN_KEYS)
            x, kb_l, vb_l = _chunk_attn(cfg, pa, x, kb[:, i], vb[:, i],
                                        table_row, start)
            kb = kb.at[:, i].set(kb_l)
            vb = vb.at[:, i].set(vb_l)
            if i in moe_ids:
                pm = _layer_params(params["moe"], mi, _MOE_KEYS)
                mi += 1
                x = _moe_mlp_half(cfg, pa, pm, x, None)[0]
            else:
                pd = _layer_params(blocks, di, _MLP_KEYS)
                di += 1
                h = _layer_norm(x, pa["ln2_s"], pa["ln2_b"])
                h = jax.nn.gelu(h @ pd["fc_w"].astype(cd)
                                + pd["fc_b"].astype(cd))
                x = x + h @ pd["out_w"].astype(cd) + pd["out_b"].astype(cd)
        return _head(cfg, params, x), (kb, vb)

    def step(carry, inp):
        x, kb, vb = carry
        layer_p, li = inp
        kb_l = jnp.take(kb, li, axis=1)
        vb_l = jnp.take(vb, li, axis=1)
        x, kb_l, vb_l = _block_chunk(cfg, layer_p, x, kb_l, vb_l, table_row,
                                     start)
        kb = jax.lax.dynamic_update_index_in_dim(kb, kb_l, li, 1)
        vb = jax.lax.dynamic_update_index_in_dim(vb, vb_l, li, 1)
        return (x, kb, vb), None

    (x, kb, vb), _ = jax.lax.scan(
        step, (x, kb, vb), (params["blocks"], jnp.arange(L)))
    return _head(cfg, params, x), (kb, vb)
