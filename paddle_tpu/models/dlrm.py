"""DLRM / DeepFM — the CTR ranking models of the recommender stack.

Functional like models/gpt.py: a config dataclass, an init returning a
param pytree split into ``{"dense": ..., "table": ...}`` (the split the
sparse training path consumes — tables update via SelectedRows, dense
via the pure optimizers), a PartitionSpec table, and a synthetic CTR
stream with planted logistic structure so loss curves are meaningful.

Architecture (Naumov et al. DLRM): dense features → bottom MLP → one
vector; each categorical slot → embedding vector from ONE shared
mod-sharded table (slot-hashed id space — the reference's
``sparse_embedding`` is likewise one logical id space per PS table);
pairwise-dot feature interaction over all vectors; concat with the
bottom vector → top MLP → 1 logit. ``arch="deepfm"`` swaps the
interaction for the FM second-order term + flattened embeddings. Both
MLPs run through the fused LN+MLP kernel (ops/fused_kernels.py) —
Pallas on TPU, identical composed jnp math on CPU.
"""
from __future__ import annotations

import dataclasses
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from ..ops.fused_kernels import fused_ln_mlp

__all__ = ["DLRMConfig", "dlrm_tiny", "dlrm_init", "dlrm_param_specs",
           "dlrm_forward_from_emb", "dlrm_forward", "dlrm_loss",
           "dlrm_loss_from_emb", "dlrm_score_fn", "synthetic_ctr_batches"]


@dataclasses.dataclass(frozen=True)
class DLRMConfig:
    n_dense: int = 13          # continuous features (Criteo layout)
    n_slots: int = 8           # categorical slots, one id each
    table_rows: int = 100_000  # shared (slot-hashed) id space
    table_dim: int = 16        # embedding width D == bottom MLP output
    mlp_hidden: int = 64       # top MLP width
    mlp_mult: int = 4          # fused-block expansion factor
    arch: str = "dlrm"         # "dlrm" | "deepfm"
    dtype: str = "float32"

    @property
    def interact_dim(self) -> int:
        n = self.n_slots + 1   # slots + bottom vector
        if self.arch == "deepfm":
            return self.table_dim * n
        return self.table_dim + n * (n - 1) // 2

    @property
    def table_bytes(self) -> int:
        return self.table_rows * self.table_dim * \
            jnp.dtype(self.dtype).itemsize


def dlrm_tiny(**kw) -> DLRMConfig:
    """Test-sized config (fits the 8-dev virtual CPU mesh)."""
    base = dict(n_dense=4, n_slots=4, table_rows=1000, table_dim=8,
                mlp_hidden=16)
    base.update(kw)
    return DLRMConfig(**base)


def _linear(key, n_in, n_out, dtype):
    scale = (2.0 / (n_in + n_out)) ** 0.5
    return {"w": (scale * jax.random.normal(
        key, (n_in, n_out))).astype(dtype),
        "b": jnp.zeros((n_out,), dtype)}


def _block(key, width, mult, dtype):
    k1, k2 = jax.random.split(key)
    m = width * mult
    return {"w1": (0.02 * jax.random.normal(k1, (width, m))).astype(dtype),
            "b1": jnp.zeros((m,), dtype),
            "w2": (0.02 * jax.random.normal(k2, (m, width))).astype(dtype),
            "b2": jnp.zeros((width,), dtype),
            "ln_s": jnp.ones((width,), dtype),
            "ln_b": jnp.zeros((width,), dtype)}


def dlrm_init(cfg: DLRMConfig, seed: int = 0):
    """``{"table": (rows, D) logical, "dense": {...}}`` — feed
    ``tables={"table": p["table"]}`` and ``p["dense"]`` to
    SparseTrainStep."""
    dt = jnp.dtype(cfg.dtype)
    keys = jax.random.split(jax.random.key(seed), 6)
    table = (0.01 * jax.random.normal(
        keys[0], (cfg.table_rows, cfg.table_dim))).astype(dt)
    dense = {
        "bot_in": _linear(keys[1], cfg.n_dense, cfg.table_dim, dt),
        "bot_blk": _block(keys[2], cfg.table_dim, cfg.mlp_mult, dt),
        "top_in": _linear(keys[3], cfg.interact_dim, cfg.mlp_hidden, dt),
        "top_blk": _block(keys[4], cfg.mlp_hidden, cfg.mlp_mult, dt),
        "top_out": _linear(keys[5], cfg.mlp_hidden, 1, dt),
    }
    return {"table": table, "dense": dense}


def dlrm_param_specs(cfg: DLRMConfig):
    """Table rows shard over "model"; the MLPs replicate (they are tiny
    next to the table — DLRM is embedding-bound by construction)."""
    lin = {"w": P(), "b": P()}
    blk = {k: P() for k in ("w1", "b1", "w2", "b2", "ln_s", "ln_b")}
    return {"table": P("model", None),
            "dense": {"bot_in": dict(lin), "bot_blk": dict(blk),
                      "top_in": dict(lin), "top_blk": dict(blk),
                      "top_out": dict(lin)}}


def _apply_block(blk, x):
    return fused_ln_mlp(x, blk["w1"], blk["b1"], blk["w2"], blk["b2"],
                        ln_scale=blk["ln_s"], ln_bias=blk["ln_b"],
                        residual=True, act="relu")


def dlrm_forward_from_emb(cfg: DLRMConfig, dense_params, dense_x, emb):
    """Logits from already-gathered slot vectors.

    ``dense_x``: (B, n_dense); ``emb``: (B, n_slots, D) — the gathered
    vectors (differentiable leaf in the sparse train step). Returns
    (B,) logits.
    """
    d = dense_params
    bot = jnp.tanh(dense_x @ d["bot_in"]["w"] + d["bot_in"]["b"])
    bot = _apply_block(d["bot_blk"], bot)                   # (B, D)
    vecs = jnp.concatenate([bot[:, None, :], emb], axis=1)  # (B, n+1, D)
    if cfg.arch == "deepfm":
        # FM second-order term + flattened embeddings through the MLP
        s = vecs.sum(axis=1)
        fm = 0.5 * (jnp.square(s) - jnp.square(vecs).sum(axis=1)).sum(-1)
        feats = vecs.reshape(vecs.shape[0], -1)
    else:
        # pairwise dots, upper triangle (the DLRM dot interaction)
        dots = jnp.einsum("bnd,bmd->bnm", vecs, vecs)
        n = vecs.shape[1]
        iu, ju = jnp.triu_indices(n, k=1)
        feats = jnp.concatenate([bot, dots[:, iu, ju]], axis=1)
        fm = 0.0
    top = jnp.tanh(feats @ d["top_in"]["w"] + d["top_in"]["b"])
    top = _apply_block(d["top_blk"], top)
    logit = (top @ d["top_out"]["w"] + d["top_out"]["b"])[:, 0]
    return logit + fm


def dlrm_forward(cfg: DLRMConfig, params, batch):
    """Convenience single-array path: plain dense gather (no sharding,
    no sparse grads) — the reference the sparse trajectory pins against."""
    emb = jnp.take(params["table"], batch["slots"], axis=0)
    return dlrm_forward_from_emb(cfg, params["dense"], batch["dense"], emb)


def _bce(logit, y):
    # stable binary cross-entropy with logits
    return jnp.mean(jnp.maximum(logit, 0) - logit * y +
                    jnp.log1p(jnp.exp(-jnp.abs(logit))))


def dlrm_loss(cfg: DLRMConfig, params, batch):
    return _bce(dlrm_forward(cfg, params, batch), batch["y"])


def dlrm_loss_from_emb(cfg: DLRMConfig, dense_params, emb, batch):
    """``loss_fn`` shape for SparseTrainStep (emb dict keyed "table")."""
    logit = dlrm_forward_from_emb(cfg, dense_params, batch["dense"],
                                  emb["table"])
    return _bce(logit, batch["y"])


def dlrm_score_fn(cfg: DLRMConfig, dense_params):
    """``score_fn`` for serving's EmbeddingRanker: emb dict in, (B,)
    sigmoid CTR scores out."""
    def score(emb, dense):
        logit = dlrm_forward_from_emb(cfg, dense_params, dense,
                                      emb["table"])
        return jax.nn.sigmoid(logit)
    return score


def synthetic_ctr_batches(cfg: DLRMConfig, batch_size: int, n_batches: int,
                          seed: int = 0, ragged: bool = False,
                          max_multi_hot: int = 4):
    """Synthetic CTR stream with planted logistic structure: labels are
    Bernoulli in a fixed random linear model over the dense features
    and per-slot id hashes, so a learner beats chance and loss curves
    slope. ``ragged=True`` adds ``"multi_hot"`` — a list of n_slots
    variable-length id arrays per batch (the shm-ring ragged payload).
    Yields dict batches of numpy arrays (shm-ring shardable).
    """
    rng = np.random.default_rng(seed)
    w_dense = rng.normal(size=cfg.n_dense).astype(np.float32)
    w_slot = rng.normal(size=cfg.n_slots).astype(np.float32)
    for _ in range(n_batches):
        dense = rng.normal(size=(batch_size, cfg.n_dense)).astype(
            np.float32)
        # zipf-ish skew: hot ids dominate, like real CTR id traffic
        slots = np.minimum(
            rng.zipf(1.3, size=(batch_size, cfg.n_slots)) - 1,
            cfg.table_rows - 1).astype(np.int32)
        planted = dense @ w_dense + \
            (np.sin(slots * 0.1) * w_slot).sum(axis=1)
        y = (rng.uniform(size=batch_size) <
             1 / (1 + np.exp(-planted))).astype(np.float32)
        batch = {"dense": dense, "slots": slots, "y": y}
        if ragged:
            batch["multi_hot"] = [
                rng.integers(0, cfg.table_rows,
                             rng.integers(1, max_multi_hot + 1)
                             ).astype(np.int64)
                for _ in range(cfg.n_slots)]
        yield batch
