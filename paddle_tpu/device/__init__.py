"""Device management — the Place/DeviceContext analog.

Reference: paddle/fluid/platform/place.h:150 (Place variant) and
device_context.h:818 (DeviceContextPool). On TPU the PJRT client owns
streams and contexts, so this reduces to device selection + queries;
the multi-device story is the jax.sharding Mesh (see paddle_tpu.distributed).
"""
from __future__ import annotations

import jax

__all__ = [
    "set_device", "get_device", "get_all_devices", "device_count",
    "TPUPlace", "CPUPlace", "CUDAPlace", "XPUPlace", "NPUPlace",
    "CUDAPinnedPlace", "is_compiled_with_cuda", "is_compiled_with_xpu",
    "is_compiled_with_npu", "is_compiled_with_tpu", "synchronize",
]


class _Place:
    device_type = "unknown"

    def __init__(self, device_id: int = 0):
        self.device_id = int(device_id)

    def __repr__(self):
        return f"{self.device_type}:{self.device_id}"

    def __eq__(self, other):
        return (
            isinstance(other, _Place)
            and self.device_type == other.device_type
            and self.device_id == other.device_id
        )

    def __hash__(self):
        return hash((self.device_type, self.device_id))

    def get_device_id(self):
        return self.device_id

    @property
    def jax_device(self):
        devs = [d for d in jax.devices() if _platform_of(d) == self.device_type]
        if not devs:
            devs = jax.devices()
        return devs[self.device_id % len(devs)]


def _platform_of(d):
    p = d.platform
    return "tpu" if p in ("tpu", "axon") else p


class TPUPlace(_Place):
    device_type = "tpu"


class CPUPlace(_Place):
    device_type = "cpu"


class CUDAPlace(TPUPlace):
    """Accepted for API parity; maps to the accelerator (TPU) device."""

    device_type = "tpu"


class XPUPlace(TPUPlace):
    device_type = "tpu"


class NPUPlace(TPUPlace):
    device_type = "tpu"


class CUDAPinnedPlace(CPUPlace):
    device_type = "cpu"


_current_device = [None]


def _default_place():
    d = jax.devices()[0]
    return TPUPlace(0) if _platform_of(d) == "tpu" else CPUPlace(0)


def set_device(device):
    """paddle.set_device parity: 'tpu', 'tpu:0', 'cpu', 'gpu:0' (→ tpu)."""
    if isinstance(device, _Place):
        _current_device[0] = device
        return device
    name, _, idx = str(device).partition(":")
    idx = int(idx) if idx else 0
    if name in ("gpu", "cuda", "tpu", "xpu", "npu"):
        place = TPUPlace(idx)
    else:
        place = CPUPlace(idx)
    _current_device[0] = place
    try:
        jax.config.update("jax_default_device", place.jax_device)
    except Exception:
        pass
    return place


def get_device() -> str:
    p = _current_device[0] or _default_place()
    return f"{p.device_type}:{p.device_id}"


def current_place():
    return _current_device[0] or _default_place()


def get_all_devices():
    return [f"{_platform_of(d)}:{i}" for i, d in enumerate(jax.devices())]


def device_count():
    return jax.device_count()


def is_compiled_with_cuda() -> bool:
    return False


def is_compiled_with_rocm() -> bool:
    return False


def is_compiled_with_xpu() -> bool:
    return False


def is_compiled_with_npu() -> bool:
    return False


def is_compiled_with_tpu() -> bool:
    return any(_platform_of(d) == "tpu" for d in jax.devices())


def synchronize(device=None):
    """Block until all dispatched work on the device completes."""
    (jax.device_put(0) + 0).block_until_ready()
