"""Probability distributions (reference python/paddle/distribution.py:42).

Uniform/Normal/Categorical with sample/log_prob/probs/entropy/kl_divergence.
Sampling draws keys from the global framework PRNG (framework/random.py) so
``paddle.seed`` governs reproducibility, mirroring the reference's use of
the global generator.
"""
from __future__ import annotations

import math

import jax
import jax.numpy as jnp
import numpy as np

from .framework.core import Tensor
from .framework.random import next_key

__all__ = ["Distribution", "Uniform", "Normal", "Categorical"]


def _arr(v):
    if isinstance(v, Tensor):
        return v._data
    return jnp.asarray(v, jnp.float32)


class Distribution:
    """Abstract base (reference distribution.py:42)."""

    def sample(self, shape):
        raise NotImplementedError

    def entropy(self):
        raise NotImplementedError

    def kl_divergence(self, other):
        raise NotImplementedError

    def log_prob(self, value):
        raise NotImplementedError

    def probs(self, value):
        raise NotImplementedError


class Uniform(Distribution):
    """U[low, high) (reference distribution.py:169)."""

    def __init__(self, low, high, name=None):
        self.low = _arr(low)
        self.high = _arr(high)

    def sample(self, shape, seed=0):
        key = jax.random.PRNGKey(seed) if seed else next_key()
        shape = tuple(int(s) for s in shape) + jnp.broadcast_shapes(
            self.low.shape, self.high.shape)
        u = jax.random.uniform(key, shape, jnp.float32)
        return Tensor(self.low + u * (self.high - self.low))

    def log_prob(self, value):
        v = _arr(value)
        inside = (v >= self.low) & (v < self.high)
        dens = jnp.where(inside, 1.0 / (self.high - self.low), 0.0)
        return Tensor(jnp.log(dens))

    def probs(self, value):
        v = _arr(value)
        inside = (v >= self.low) & (v < self.high)
        return Tensor(jnp.where(inside, 1.0 / (self.high - self.low), 0.0))

    def entropy(self):
        return Tensor(jnp.log(self.high - self.low))


class Normal(Distribution):
    """N(loc, scale^2) (reference distribution.py:391)."""

    def __init__(self, loc, scale, name=None):
        self.loc = _arr(loc)
        self.scale = _arr(scale)

    def sample(self, shape, seed=0):
        key = jax.random.PRNGKey(seed) if seed else next_key()
        shape = tuple(int(s) for s in shape) + jnp.broadcast_shapes(
            self.loc.shape, self.scale.shape)
        z = jax.random.normal(key, shape, jnp.float32)
        return Tensor(self.loc + z * self.scale)

    def log_prob(self, value):
        v = _arr(value)
        var = self.scale ** 2
        return Tensor(-((v - self.loc) ** 2) / (2 * var)
                      - jnp.log(self.scale) - 0.5 * math.log(2 * math.pi))

    def probs(self, value):
        return Tensor(jnp.exp(self.log_prob(value)._data))

    def entropy(self):
        return Tensor(0.5 + 0.5 * math.log(2 * math.pi)
                      + jnp.log(self.scale)
                      + jnp.zeros_like(self.loc))

    def kl_divergence(self, other):
        if not isinstance(other, Normal):
            raise TypeError("kl_divergence expects another Normal")
        var_ratio = (self.scale / other.scale) ** 2
        t1 = ((self.loc - other.loc) / other.scale) ** 2
        return Tensor(0.5 * (var_ratio + t1 - 1.0 - jnp.log(var_ratio)))


class Categorical(Distribution):
    """Categorical over unnormalized logits (reference distribution.py:641,
    which softmax-normalizes: prob = exp(logits - max) / sum)."""

    def __init__(self, logits, name=None):
        self.logits = _arr(logits)

    def _p(self):
        z = self.logits - jnp.max(self.logits, axis=-1, keepdims=True)
        e = jnp.exp(z)
        return e / jnp.sum(e, axis=-1, keepdims=True)

    def sample(self, shape):
        key = next_key()
        p = self._p()
        shape = tuple(int(s) for s in shape)
        idx = jax.random.categorical(key, jnp.log(p),
                                     shape=shape + p.shape[:-1])
        # leave the native integer dtype: an int64 astype under the default
        # x64-disabled config only emits a truncation warning
        return Tensor(idx)

    def probs(self, value):
        p = self._p()
        v = _arr(value).astype(jnp.int32)
        if p.ndim == 1:
            return Tensor(p[v])
        if v.ndim == p.ndim - 1:
            # per-row category index (batched logits): gather one per row
            return Tensor(jnp.take_along_axis(p, v[..., None],
                                              axis=-1)[..., 0])
        return Tensor(jnp.take_along_axis(p, v, axis=-1))

    def log_prob(self, value):
        return Tensor(jnp.log(self.probs(value)._data))

    def entropy(self):
        p = self._p()
        return Tensor(-jnp.sum(p * jnp.log(p), axis=-1))

    def kl_divergence(self, other):
        if not isinstance(other, Categorical):
            raise TypeError("kl_divergence expects another Categorical")
        p, q = self._p(), other._p()
        return Tensor(jnp.sum(p * (jnp.log(p) - jnp.log(q)), axis=-1))
