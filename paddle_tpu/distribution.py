"""Probability distributions (reference python/paddle/distribution.py:42).

Uniform/Normal/Categorical with sample/log_prob/probs/entropy/kl_divergence.
Sampling draws keys from the global framework PRNG (framework/random.py) so
``paddle.seed`` governs reproducibility, mirroring the reference's use of
the global generator.

DIFFERENTIABLE: every density/statistic routes through ``apply_op`` with
the constructor's parameter Tensors as live inputs, so log_prob/entropy/kl
participate in the autograd tape (the reference builds these from regular
ops for the same reason — policy-gradient and VAE losses must train
through them). ``sample`` additionally keeps the reparameterization path
live for Uniform/Normal: loc + z * scale with z a constant draw.

The op bodies are MODULE-LEVEL functions taking the evaluation point as a
positional argument (not per-call closures): apply_op's eager jit cache
keys on function identity, so closures would recompile and leak one cache
entry per call.
"""
from __future__ import annotations

import math

import jax
import jax.numpy as jnp
import numpy as np

from .framework.core import Tensor, apply_op
from .framework.random import next_key

__all__ = ["Distribution", "Uniform", "Normal", "Categorical"]


def _arr(v):
    if isinstance(v, Tensor):
        return v._data
    return jnp.asarray(v, jnp.float32)


def _keep(v):
    """Keep a Tensor (tape-live) as is; wrap raw values."""
    return v if isinstance(v, Tensor) else Tensor(_arr(v))


def _val(v):
    """Evaluation-point arg for apply_op: a Tensor passes through LIVE so
    d log_prob / d value flows (unwrapping with _arr would sever the
    tape); raw values become plain arrays. Categorical keeps _arr — its
    value is an integer index with no gradient."""
    return v if isinstance(v, Tensor) else _arr(v)


class Distribution:
    """Abstract base (reference distribution.py:42)."""

    def sample(self, shape):
        raise NotImplementedError

    def entropy(self):
        raise NotImplementedError

    def kl_divergence(self, other):
        raise NotImplementedError

    def log_prob(self, value):
        raise NotImplementedError

    def probs(self, value):
        raise NotImplementedError


# -- uniform op bodies ------------------------------------------------------

def _uniform_sample_op(lo, hi, u):
    return lo + u * (hi - lo)


def _uniform_log_prob_op(lo, hi, v):
    inside = (v >= lo) & (v < hi)
    return jnp.log(jnp.where(inside, 1.0 / (hi - lo), 0.0))


def _uniform_probs_op(lo, hi, v):
    inside = (v >= lo) & (v < hi)
    return jnp.where(inside, 1.0 / (hi - lo), 0.0)


def _uniform_entropy_op(lo, hi):
    return jnp.log(hi - lo)


class Uniform(Distribution):
    """U[low, high) (reference distribution.py:169)."""

    def __init__(self, low, high, name=None):
        self.low = _keep(low)
        self.high = _keep(high)

    def sample(self, shape, seed=0):
        key = jax.random.PRNGKey(seed) if seed else next_key()
        shape = tuple(int(s) for s in shape) + jnp.broadcast_shapes(
            self.low._data.shape, self.high._data.shape)
        u = jax.random.uniform(key, shape, jnp.float32)
        # reparameterized: low + u * (high - low) stays on the tape
        return apply_op(_uniform_sample_op, self.low, self.high, u,
                        op_name="uniform_sample")

    def log_prob(self, value):
        return apply_op(_uniform_log_prob_op, self.low, self.high,
                        _val(value), op_name="uniform_log_prob")

    def probs(self, value):
        return apply_op(_uniform_probs_op, self.low, self.high,
                        _val(value), op_name="uniform_probs")

    def entropy(self):
        return apply_op(_uniform_entropy_op, self.low, self.high,
                        op_name="uniform_entropy")


# -- normal op bodies -------------------------------------------------------

def _normal_sample_op(lo, sc, z):
    return lo + z * sc


def _normal_log_prob_op(lo, sc, v):
    return (-((v - lo) ** 2) / (2 * sc ** 2) - jnp.log(sc)
            - 0.5 * math.log(2 * math.pi))


def _normal_probs_op(lo, sc, v):
    return jnp.exp(-((v - lo) ** 2) / (2 * sc ** 2)) \
        / (sc * math.sqrt(2 * math.pi))


def _normal_entropy_op(lo, sc):
    return (0.5 + 0.5 * math.log(2 * math.pi) + jnp.log(sc)
            + jnp.zeros_like(lo))


def _normal_kl_op(lo, sc, olo, osc):
    var_ratio = (sc / osc) ** 2
    t1 = ((lo - olo) / osc) ** 2
    return 0.5 * (var_ratio + t1 - 1.0 - jnp.log(var_ratio))


class Normal(Distribution):
    """N(loc, scale^2) (reference distribution.py:391)."""

    def __init__(self, loc, scale, name=None):
        self.loc = _keep(loc)
        self.scale = _keep(scale)

    def sample(self, shape, seed=0):
        key = jax.random.PRNGKey(seed) if seed else next_key()
        shape = tuple(int(s) for s in shape) + jnp.broadcast_shapes(
            self.loc._data.shape, self.scale._data.shape)
        z = jax.random.normal(key, shape, jnp.float32)
        # reparameterization trick: grads flow to loc/scale through z
        return apply_op(_normal_sample_op, self.loc, self.scale, z,
                        op_name="normal_sample")

    def log_prob(self, value):
        return apply_op(_normal_log_prob_op, self.loc, self.scale,
                        _val(value), op_name="normal_log_prob")

    def probs(self, value):
        return apply_op(_normal_probs_op, self.loc, self.scale,
                        _val(value), op_name="normal_probs")

    def entropy(self):
        return apply_op(_normal_entropy_op, self.loc, self.scale,
                        op_name="normal_entropy")

    def kl_divergence(self, other):
        if not isinstance(other, Normal):
            raise TypeError("kl_divergence expects another Normal")
        return apply_op(_normal_kl_op, self.loc, self.scale, other.loc,
                        other.scale, op_name="normal_kl")


# -- categorical op bodies --------------------------------------------------

def _gather_cat(p, v):
    if p.ndim == 1:
        return p[v]
    if v.ndim == p.ndim - 1:
        # per-row category index (batched logits): gather one per row
        return jnp.take_along_axis(p, v[..., None], axis=-1)[..., 0]
    return jnp.take_along_axis(p, v, axis=-1)


def _categorical_probs_op(lg, v):
    return _gather_cat(jax.nn.softmax(lg, axis=-1), v)


def _categorical_log_prob_op(lg, v):
    # log-softmax gather (NOT log of the gathered prob): numerically
    # stable and differentiable at small probabilities
    return _gather_cat(jax.nn.log_softmax(lg, axis=-1), v)


def _categorical_entropy_op(lg):
    # from log_softmax with a where(p>0, lp, 0) guard: p * log(p) at
    # p == 0 is 0 * -inf = NaN under the naive jnp.log(p) form (extreme
    # logit gaps underflow the softmax to exactly 0). Guarding lp ITSELF
    # (not the product) keeps both the 0*log0=0 convention and a NaN-free
    # gradient — where() grads still multiply by the untaken branch's
    # cotangent, so a -inf must never reach the product
    lp = jax.nn.log_softmax(lg, axis=-1)
    p = jnp.exp(lp)
    return -jnp.sum(p * jnp.where(p > 0, lp, 0.0), axis=-1)


def _categorical_kl_op(lg, olg):
    # same where(p>0, ., 0) guard as entropy: a zero-probability category
    # contributes 0 to the KL sum, not 0 * (-inf - lp') = NaN
    lp = jax.nn.log_softmax(lg, axis=-1)
    olp = jax.nn.log_softmax(olg, axis=-1)
    p = jnp.exp(lp)
    return jnp.sum(p * jnp.where(p > 0, lp - olp, 0.0), axis=-1)


class Categorical(Distribution):
    """Categorical over unnormalized logits (reference distribution.py:641,
    which softmax-normalizes: prob = exp(logits - max) / sum)."""

    def __init__(self, logits, name=None):
        self.logits = _keep(logits)

    def sample(self, shape):
        key = next_key()
        lg = self.logits._data
        shape = tuple(int(s) for s in shape)
        # jax.random.categorical takes unnormalized logits directly — no
        # softmax/log round-trip (which underflows for extreme gaps)
        idx = jax.random.categorical(key, lg, shape=shape + lg.shape[:-1])
        # leave the native integer dtype: an int64 astype under the default
        # x64-disabled config only emits a truncation warning
        return Tensor(idx)

    def probs(self, value):
        return apply_op(_categorical_probs_op, self.logits,
                        _arr(value).astype(jnp.int32),
                        op_name="categorical_probs")

    def log_prob(self, value):
        return apply_op(_categorical_log_prob_op, self.logits,
                        _arr(value).astype(jnp.int32),
                        op_name="categorical_log_prob")

    def entropy(self):
        return apply_op(_categorical_entropy_op, self.logits,
                        op_name="categorical_entropy")

    def kl_divergence(self, other):
        if not isinstance(other, Categorical):
            raise TypeError("kl_divergence expects another Categorical")
        return apply_op(_categorical_kl_op, self.logits, other.logits,
                        op_name="categorical_kl")
