"""Functionalise an eager Layer: run its forward with parameters taken from
an external pytree instead of the layer's own storage.

This is the bridge between the Paddle-style stateful ``nn.Layer`` world and
the pure-function world jit/pjit compile (the reference never needs this —
its executor interprets ops against mutable Scopes; under XLA the training
step must be a pure function of (params, batch)).

Used by the Fleet engine (distributed/fleet/engine.py) to compile
facade-built models into one sharded XLA program.
"""
from __future__ import annotations

from typing import Any, Dict

import jax

from .core import Tensor, no_grad

__all__ = ["layer_params", "layer_buffers", "functional_call"]


def layer_params(layer, trainable_only: bool = True) -> Dict[str, Any]:
    """Named parameter arrays of a Layer as a flat {name: jax.Array} dict."""
    out = {}
    for name, p in layer.named_parameters():
        if trainable_only and p.stop_gradient:
            continue
        out[name] = p._data
    return out


def _wrap(x):
    if isinstance(x, Tensor):
        return x
    if isinstance(x, (tuple, list)):
        return type(x)(_wrap(v) for v in x)
    return Tensor(x)


def _unwrap_out(x):
    if isinstance(x, Tensor):
        return x._data
    if isinstance(x, (tuple, list)):
        return type(x)(_unwrap_out(v) for v in x)
    return x


def layer_buffers(layer) -> Dict[str, Any]:
    """Named buffer arrays of a Layer as a flat {name: jax.Array} dict."""
    return {n: b._data for n, b in layer.named_buffers() if b is not None}


def functional_call(layer, params: Dict[str, Any], *args,
                    buffers: Dict[str, Any] = None, **kwargs):
    """Call ``layer(*args)`` with its parameters substituted by ``params``.

    ``params`` maps named_parameters() names to (possibly traced) arrays.
    The layer's own parameter AND buffer storage is restored on exit, so
    this is safe to trace with jax.jit/grad: traced arrays never leak into
    eager state even when the forward mutates buffers in place (BatchNorm
    running stats). Inputs may be raw arrays or Tensors; the output is
    unwrapped to raw arrays (matching how jit-able code consumes it).

    When ``buffers`` is given (a {name: array} dict like
    :func:`layer_buffers`), those arrays are substituted before the call
    and the post-forward values are returned alongside the output as
    ``(out, new_buffers)`` — the functional analog of the reference's
    in-place persistable-variable updates.
    """
    named = dict(layer.named_parameters())
    named_buf = {n: b for n, b in layer.named_buffers() if b is not None}
    saved = {}
    saved_buf = {n: b._data for n, b in named_buf.items()}
    try:
        for name, arr in params.items():
            p = named[name]
            saved[name] = p._data
            p._data = arr
        if buffers:
            for name, arr in buffers.items():
                named_buf[name]._data = arr
        with no_grad():
            out = layer(*_wrap(args), **{k: _wrap(v) for k, v in kwargs.items()})
        if buffers is not None:
            new_buffers = {name: named_buf[name]._data for name in buffers}
            return _unwrap_out(out), new_buffers
        return _unwrap_out(out)
    finally:
        for name, old in saved.items():
            named[name]._data = old
        for name, old in saved_buf.items():
            named_buf[name]._data = old
