"""Global stateful RNG over JAX's functional PRNG.

The reference keeps per-device seeded generator state
(/root/reference/paddle/fluid/framework/generator.h:118) plus a
tensor-parallel-aware RNG-state tracker
(/root/reference/python/paddle/distributed/fleet/meta_parallel/parallel_layers/random.py:32).

TPU-native design: one global `jax.random.key` threaded through a split
counter. `paddle_tpu.seed(n)` resets it. Inside a jit trace the stateful
path would bake the key into the compiled program, so traced code should
use `rng_key()` explicitly (our functional layers thread keys); the
eager path splits the global key on every draw.

The TP-aware `RNGStatesTracker` lives in
paddle_tpu.distributed.fleet.meta_parallel.random and reuses this module.
"""
from __future__ import annotations

import threading

import jax


class _GlobalRNG:
    """Key creation is lazy: importing paddle_tpu must never initialize an
    XLA backend (DataLoader spawn/forkserver children import the package in
    environments where the parent's device plugin is unavailable)."""

    def __init__(self, seed: int = 0):
        self._lock = threading.Lock()
        self._seed = int(seed)
        self._key = None
        # bumped on every seed() call — consumers caching derived
        # generators (e.g. the detection samplers) key on (seed, epoch)
        # so reseeding with the SAME value still restarts their streams
        self.seed_epoch = 0

    def seed(self, s: int):
        with self._lock:
            self._seed = int(s)
            self._key = jax.random.key(int(s))
            self.seed_epoch += 1

    def _ensure(self):
        if self._key is None:
            self._key = jax.random.key(self._seed)

    def next_key(self):
        """Split the global key; returns a fresh subkey (eager use)."""
        with self._lock:
            self._ensure()
            self._key, sub = jax.random.split(self._key)
            return sub

    def get_state(self):
        with self._lock:
            self._ensure()
            return self._key

    def set_state(self, key):
        with self._lock:
            self._key = key


_global_rng = _GlobalRNG(0)


def seed(s: int):
    """paddle.seed parity: seed the global generator."""
    _global_rng.seed(s)
    return _global_rng


def next_key():
    return _global_rng.next_key()


def get_rng_state():
    return _global_rng.get_state()


def set_rng_state(state):
    _global_rng.set_state(state)
