"""paddle.save / paddle.load parity.

Reference: python/paddle/framework/io.py:553 (save) / :769 (load) — pickled
state_dicts. We store numpy-converted pytrees via pickle; Tensors round-trip
as Tensors. For large sharded checkpoints use paddle_tpu.distributed.checkpoint
(orbax-backed async sharded save — the AutoCheckpoint/HDFS analog).
"""
from __future__ import annotations

import os
import pickle

import numpy as np

from .core import Tensor


class _TensorPickle:
    """Pickle wrapper marking arrays that should be restored as Tensors."""

    def __init__(self, array):
        self.array = array


def _to_savable(obj):
    if isinstance(obj, Tensor):
        return _TensorPickle(np.asarray(obj._data))
    if isinstance(obj, dict):
        return {k: _to_savable(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        t = [ _to_savable(v) for v in obj]
        return t if isinstance(obj, list) else tuple(t)
    return obj


def _from_savable(obj, return_numpy=False):
    if isinstance(obj, _TensorPickle):
        return obj.array if return_numpy else Tensor(obj.array)
    if isinstance(obj, dict):
        return {k: _from_savable(v, return_numpy) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        t = [_from_savable(v, return_numpy) for v in obj]
        return t if isinstance(obj, list) else tuple(t)
    return obj


def save(obj, path, protocol=4, **configs):
    d = os.path.dirname(path)
    if d:
        os.makedirs(d, exist_ok=True)
    with open(path, "wb") as f:
        pickle.dump(_to_savable(obj), f, protocol=protocol)


def load(path, **configs):
    return_numpy = configs.get("return_numpy", False)
    with open(path, "rb") as f:
        obj = pickle.load(f)
    return _from_savable(obj, return_numpy)
