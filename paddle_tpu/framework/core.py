"""Eager Tensor, op dispatch, and the autograd tape.

This is the TPU-native analog of the reference's dygraph runtime:

- ``Tracer::TraceOp`` (/root/reference/paddle/fluid/imperative/tracer.cc:146)
  becomes :func:`apply_op` — every eager op funnels through it. Instead of
  building per-op ``GradOpNode``s from a grad-op registry, we call
  ``jax.vjp`` on the op's pure function: the returned closure *is* the grad
  node (it holds the residuals the reference would stash in the grad op's
  inputs).
- ``BasicEngine::Execute`` (/root/reference/paddle/fluid/imperative/
  basic_engine.cc:379) becomes :func:`backward` — a reverse-topological walk
  over :class:`GradNode` with cotangent accumulation (the reference's
  ``GradientAccumulator``).
- The ``core.ops`` generated fast path
  (/root/reference/paddle/fluid/pybind/op_function_generator.cc) is replaced
  by op-level ``jax.jit`` caching keyed on (fn, static attrs) — XLA's trace
  cache plays the role of the reference's ``PreparedOp`` kernel cache
  (/root/reference/paddle/fluid/imperative/prepared_operator.cc:92).
  The same PreparedOp treatment covers the TRAINING path: grad-enabled
  dispatches and the backward walk's vjp applications + cotangent adds go
  through the (fn, attrs, avals)-keyed grad-jit cache (``_grad_jit_cache``
  below; gauges grad_jit_hit/miss/compile in paddle_tpu.monitor; disable
  with ``FLAGS_eager_grad_jit=0``).

Inside a ``jax.jit``/``jax.grad`` trace (our "static"/functional mode) the
tape is bypassed: differentiation is handled by JAX's own machinery, so
:func:`apply_op` just calls the pure function on the tracers.
"""
from __future__ import annotations

import functools
import threading
import time
from typing import Any, Callable, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from . import dtype as dtypes

__all__ = [
    "Tensor",
    "Parameter",
    "AsyncLoss",
    "apply_op",
    "backward",
    "grad",
    "no_grad",
    "is_grad_enabled",
    "set_grad_enabled",
    "to_tensor",
]


# --------------------------------------------------------------------------
# grad mode
# --------------------------------------------------------------------------

class _GradState(threading.local):
    def __init__(self):
        self.enabled = True


_grad_state = _GradState()


def is_grad_enabled() -> bool:
    return _grad_state.enabled


def set_grad_enabled(enabled: bool):
    _grad_state.enabled = bool(enabled)


class no_grad:
    """Context manager & decorator disabling the autograd tape.

    Parity: ``paddle.no_grad`` (reference python/paddle/fluid/dygraph/base.py).
    """

    def __enter__(self):
        self._prev = _grad_state.enabled
        _grad_state.enabled = False
        return self

    def __exit__(self, *exc):
        _grad_state.enabled = self._prev
        return False

    def __call__(self, fn):
        @functools.wraps(fn)
        def wrapper(*a, **k):
            with no_grad():
                return fn(*a, **k)

        return wrapper


class enable_grad:
    def __enter__(self):
        self._prev = _grad_state.enabled
        _grad_state.enabled = True
        return self

    def __exit__(self, *exc):
        _grad_state.enabled = self._prev
        return False


# --------------------------------------------------------------------------
# Tensor
# --------------------------------------------------------------------------

def _unwrap(x):
    return x._data if isinstance(x, Tensor) else x


def _is_tracer(x) -> bool:
    return isinstance(x, jax.core.Tracer)


class Tensor:
    """Eager tensor: a named wrapper over ``jax.Array``.

    Mirrors the user surface of ``paddle.Tensor`` (reference VarBase,
    /root/reference/paddle/fluid/imperative/layer.cc). ``stop_gradient``
    defaults to True like the reference; ``Parameter`` flips it.
    """

    __slots__ = (
        "_data",
        "stop_gradient",
        "grad",
        "_grad_node",
        "_out_index",
        "name",
        "persistable",
        "sharding",  # optional jax.sharding.PartitionSpec for pjit placement
        "__weakref__",
    )

    # let Tensor win in  np_array * tensor  and similar
    __array_priority__ = 100

    def __init__(self, data, stop_gradient: bool = True, name: Optional[str] = None):
        if isinstance(data, Tensor):
            data = data._data
        if not isinstance(data, jax.Array) and not _is_tracer(data):
            data = jnp.asarray(data)
        self._data = data
        self.stop_gradient = stop_gradient
        self.grad: Optional[Tensor] = None
        self._grad_node: Optional[GradNode] = None
        self._out_index: int = 0
        self.name = name
        self.persistable = False
        self.sharding = None

    # -- basic properties ---------------------------------------------------
    @property
    def shape(self):
        return list(self._data.shape)

    @property
    def dtype(self):
        return self._data.dtype

    @property
    def ndim(self):
        return self._data.ndim

    @property
    def size(self):
        return int(np.prod(self._data.shape)) if self._data.shape else 1

    @property
    def place(self):
        devs = getattr(self._data, "devices", None)
        if devs is None:
            return "tracer"
        try:
            return next(iter(self._data.devices()))
        except Exception:
            return "unknown"

    @property
    def is_leaf(self):
        return self._grad_node is None

    @property
    def T(self):
        from .. import tensor as T

        return T.transpose(self, list(range(self.ndim))[::-1])

    def numpy(self):
        if _sanitize[0]:
            _san_check_read(self._data)
        return np.asarray(self._data)

    def item(self, *args):
        if _sanitize[0]:
            _san_check_read(self._data)
        if args:
            return np.asarray(self._data).item(*args)
        return np.asarray(self._data).item()

    def tolist(self):
        if _sanitize[0]:
            _san_check_read(self._data)
        return np.asarray(self._data).tolist()

    def detach(self):
        t = Tensor(self._data, stop_gradient=True, name=self.name)
        return t

    def clone(self):
        from .. import tensor as T

        return T.clone(self)

    def numel(self):
        return self.size

    def element_size(self):
        return self._data.dtype.itemsize

    def astype(self, dtype):
        from .. import tensor as T

        return T.cast(self, dtype)

    cast = astype

    def clear_grad(self):
        self.grad = None

    clear_gradient = clear_grad

    def block_until_ready(self):
        if hasattr(self._data, "block_until_ready"):
            self._data.block_until_ready()
        return self

    def backward(self, grad_tensor=None, retain_graph: bool = False):
        backward(self, grad_tensor, retain_graph=retain_graph)

    # value mutation (optimizers, state loading)
    def set_value(self, value):
        if isinstance(value, Tensor):
            value = value._data
        value = jnp.asarray(value, dtype=self._data.dtype)
        if tuple(value.shape) != tuple(self._data.shape):
            raise ValueError(
                f"set_value shape mismatch: {value.shape} vs {self._data.shape}"
            )
        self._data = value

    def copy_(self, other, *a):
        self.set_value(other)
        return self

    def fill_(self, v):
        self._data = jnp.full_like(self._data, v)
        return self

    def zero_(self):
        self._data = jnp.zeros_like(self._data)
        return self

    # -- repr ---------------------------------------------------------------
    def __repr__(self):
        if _is_tracer(self._data):
            return f"Tensor(shape={self.shape}, dtype={self.dtype.name}, <traced>)"
        sg = self.stop_gradient
        return (
            f"Tensor(shape={self.shape}, dtype={self.dtype.name}, "
            f"stop_gradient={sg},\n       {np.asarray(self._data)})"
        )

    def __len__(self):
        if self.ndim == 0:
            raise TypeError("len() of a 0-d tensor")
        return self._data.shape[0]

    def __iter__(self):
        for i in range(len(self)):
            yield self[i]

    def __bool__(self):
        if _sanitize[0]:
            _san_check_read(self._data)
        return bool(np.asarray(self._data))

    def __int__(self):
        if _sanitize[0]:
            _san_check_read(self._data)
        return int(np.asarray(self._data))

    def __float__(self):
        if _sanitize[0]:
            _san_check_read(self._data)
        return float(np.asarray(self._data))

    def __format__(self, spec):
        if self.ndim == 0:
            return format(np.asarray(self._data).item(), spec)
        return repr(self)

    def __hash__(self):
        return id(self)

    # arithmetic dunders are attached in paddle_tpu/tensor/__init__.py
    # (to avoid a circular import with the op modules).

    def __jax_array__(self):
        # lets jnp.* consume Tensor directly (loses tape; used in no-grad
        # utility code only).
        return self._data


class Parameter(Tensor):
    """Trainable tensor: ``stop_gradient=False``, persistable.

    Parity: reference Parameter (python/paddle/fluid/framework.py:5932).
    """

    __slots__ = ("trainable", "optimize_attr", "regularizer", "need_clip", "is_distributed")

    def __init__(self, data, name=None, trainable=True):
        super().__init__(data, stop_gradient=not trainable, name=name)
        self.persistable = True
        self.trainable = trainable
        self.optimize_attr = {"learning_rate": 1.0}
        self.regularizer = None
        self.need_clip = True
        self.is_distributed = False


class AsyncLoss(Tensor):
    """Loss handle from an async (FLAGS_fast_step) train step.

    The step's XLA program is dispatched but NOT awaited; the handle
    behaves like any scalar Tensor, and the first host read (float()/
    numpy()/item()/bool()) is the sync point — counted once per handle by
    the ``step_async_syncs`` gauge, so a training loop that accidentally
    materializes every step shows up as step_async_syncs == train_steps.

    When the step carries an in-jit health sentinel
    (paddle_tpu.resilience), ``health`` holds its un-awaited device
    scalars ({"trip", "trips"}) — reading THEM is also a sync, so the
    guardian controls when (and whether) the verdict costs a host
    round-trip.
    """

    __slots__ = ("_synced", "health")

    def __init__(self, data, name=None):
        super().__init__(data, stop_gradient=True, name=name)
        self._synced = False
        self.health = None

    def _materialize(self):
        if not self._synced:
            self._synced = True
            _mstats.STEP_ASYNC_SYNCS.add()

    def numpy(self):
        self._materialize()
        return super().numpy()

    def item(self, *args):
        self._materialize()
        return super().item(*args)

    def tolist(self):
        self._materialize()
        return super().tolist()

    def __float__(self):
        self._materialize()
        return super().__float__()

    def __int__(self):
        self._materialize()
        return super().__int__()

    def __bool__(self):
        self._materialize()
        return super().__bool__()

    def __array__(self, dtype=None):
        # unlike base Tensor, the loss handle cooperates with np.asarray /
        # np.testing directly (it is a read-only scalar result)
        self._materialize()
        a = np.asarray(self._data)
        return a.astype(dtype) if dtype is not None else a


def to_tensor(data, dtype=None, place=None, stop_gradient=True):
    """paddle.to_tensor parity."""
    del place  # device placement is managed by jax; kept for API parity
    if isinstance(data, Tensor):
        arr = data._data
    elif isinstance(data, (list, tuple)) and any(
        isinstance(x, Tensor) for x in jax.tree_util.tree_leaves(data)
    ):
        arr = jnp.asarray(
            jax.tree_util.tree_map(
                lambda x: x._data if isinstance(x, Tensor) else x, data
            )
        )
    else:
        arr = data
    d = dtypes.convert_dtype(dtype)
    if not isinstance(arr, jax.Array) and not _is_tracer(arr):
        np_arr = np.asarray(arr)
        if d is None and np_arr.dtype == np.float64:
            d = dtypes.default_float_dtype()  # match paddle: python floats -> fp32
        arr = jnp.asarray(np_arr, dtype=d)
    elif d is not None and arr.dtype != d:
        arr = arr.astype(d)
    return Tensor(arr, stop_gradient=stop_gradient)


# --------------------------------------------------------------------------
# dispatch
# --------------------------------------------------------------------------

class GradNode:
    """One recorded op on the tape.

    ``vjp_fn`` closes over the op's residuals — the analog of a reference
    ``GradOpNode`` + its saved inputs
    (/root/reference/paddle/fluid/imperative/op_base.h). ``fn`` and
    ``arg_arrays`` keep the op's pure function + primal args so the
    double-grad path (create_graph=True, reference
    imperative/partial_grad_engine.cc) can re-run the vjp THROUGH
    apply_op — recording the grad computation itself on the tape.
    """

    __slots__ = ("vjp_fn", "inputs", "out_avals", "multi_out", "name",
                 "fn", "arg_arrays")

    def __init__(self, vjp_fn, inputs, out_avals, multi_out, name,
                 fn=None, arg_arrays=None):
        self.vjp_fn = vjp_fn
        self.inputs = inputs  # tuple[Tensor | None] (None for non-diff args)
        self.out_avals = out_avals  # [(shape, dtype)]
        self.multi_out = multi_out
        self.name = name
        self.fn = fn                  # pure fn with attrs bound
        self.arg_arrays = arg_arrays  # primal args (raw arrays)


_jit_cache: dict = {}
# Eager op-level jit: the analog of the reference's PreparedOp kernel cache.
EAGER_JIT = True


def _hashable_attrs(attrs):
    """Attrs as a canonical hashable tuple for cache keys. List/dict
    values are normalized (conv strides/paddings arrive as lists — they
    would otherwise force the raw fallback on every conv dispatch);
    genuinely unhashable values (arrays) raise TypeError."""
    def norm(v):
        if isinstance(v, (list, tuple)):
            return ("#seq",) + tuple(norm(x) for x in v)
        if isinstance(v, dict):
            return ("#map",) + tuple(
                sorted((k, norm(x)) for k, x in v.items()))
        hash(v)
        return v

    return tuple(sorted((k, norm(v)) for k, v in attrs.items()))


def _jitted(fn, attrs):
    try:
        key = (fn, _hashable_attrs(attrs))
        hash(key)
    except TypeError:
        return None
    j = _jit_cache.get(key)
    if j is None:
        _mstats.JIT_CACHE_MISS.add()
        _mstats.JIT_COMPILE.add()
        j = jax.jit(functools.partial(fn, **attrs))
        _jit_cache[key] = j
    else:
        _mstats.JIT_CACHE_HIT.add()
    return j


# -- grad-jit cache: the PreparedOp analog for the TRAINING path -----------
#
# The no-grad dispatch above amortizes trace+compile through _jit_cache,
# but a grad-enabled dispatch used to pay jax.vjp(f, *arrays) — a full
# un-jitted trace-and-execute — on EVERY call, and the backward walk then
# replayed raw Python vjp closures node by node. This cache extends the
# compile-once-dispatch-many model to training: keyed on
# (fn, sorted(attrs), input avals) it holds a jitted forward plus a jitted
# vjp-apply companion ``bwd(primals, cotangents)`` that re-derives the vjp
# INSIDE the compiled program (XLA dead-code-eliminates whatever part of
# the forward the residuals don't need — for matmul the recompute vanishes
# entirely; for tanh-like ops it is the standard remat trade). Residuals
# are therefore just the primal args the node already keeps for double
# grad — nothing extra is stored. Aval keying makes shape-churn recompile
# storms visible: every new (fn, attrs, avals) combination is a
# GRAD_JIT_MISS + GRAD_JIT_COMPILE, steady-state training is pure
# GRAD_JIT_HIT. Unhashable attrs or non-array args fall back to the raw
# per-call jax.vjp path; `set_flags({"FLAGS_eager_grad_jit": 0})` disables
# the cache entirely.

_grad_jit_cache: dict = {}
# (fn, attrs) -> list of aval sigs already compiled, in insertion order —
# the recompile explainer (FLAGS_sanitize) diffs a new miss against these
# to name the leaf whose shape/dtype churned
_grad_jit_groups: dict = {}


def _san_sig(sig):
    """Grad-jit aval sig -> sanitizers leaf-signature format
    ((name, shape, dtype, weak) per leaf)."""
    out = []
    for i, e in enumerate(sig):
        if isinstance(e, tuple):
            out.append((str(i), e[0], e[1], False))
        else:                       # python-scalar arg signed by type name
            out.append((str(i), e, "", True))
    return tuple(out)


class _GradJitEntry:
    __slots__ = ("f", "fwd", "bwd", "name", "fwd_primed", "bwd_primed")

    def __init__(self, fn, attrs, name):
        f_raw = functools.partial(fn, **attrs) if attrs else fn

        # normalize multi-output structure to a PLAIN tuple (NamedTuple
        # outputs of jnp.linalg ops reject plain-tuple cotangents — see
        # the raw-vjp path below)
        def f(*a, _f=f_raw):
            o = _f(*a)
            return tuple(o) if isinstance(o, (tuple, list)) else o

        def bwd(primals, cts, _f=f):
            _, vjp = jax.vjp(_f, *primals)
            return vjp(cts)

        self.f = f
        self.fwd = jax.jit(f)
        self.bwd = jax.jit(bwd)
        self.name = name
        self.fwd_primed = False
        self.bwd_primed = False


def _grad_aval_sig(arrays):
    """Aval cache key: (shape, dtype) per array arg, python-scalar args by
    type (they trace weak-typed, so same type => same aval). Raises
    TypeError for anything else — the caller falls back to raw jax.vjp."""
    sig = []
    for a in arrays:
        sh = getattr(a, "shape", None)
        if sh is None:
            if not isinstance(a, (int, float, complex)):
                raise TypeError("non-array positional arg")
            sig.append(type(a).__name__)
        else:
            dt = getattr(a, "dtype", None)
            if dt is None:
                raise TypeError("shaped arg without dtype")
            sig.append((tuple(sh), str(dt)))
    return tuple(sig)


def _grad_jitted(fn, attrs, arrays, name=None):
    """Cache lookup for the grad-enabled fast path; None => raw fallback."""
    try:
        key = (fn, _hashable_attrs(attrs) if attrs else (),
               _grad_aval_sig(arrays))
        hash(key)
    except TypeError:
        return None
    e = _grad_jit_cache.get(key)
    if e is None:
        _mstats.GRAD_JIT_MISS.add()
        _mstats.GRAD_JIT_COMPILE.add()
        e = _GradJitEntry(fn, attrs, name or getattr(fn, "__name__", "op"))
        group = _grad_jit_groups.setdefault(key[:2], [])
        if _sanitize[0] and group:
            # recompile explainer: name the leaf whose aval churned vs
            # the nearest already-compiled signature
            _san_note_recompile(f"grad_jit:{e.name}", _san_sig(key[2]),
                                [_san_sig(s) for s in group])
        group.append(key[2])
        _grad_jit_cache[key] = e
    else:
        _mstats.GRAD_JIT_HIT.add()
    return e


def _grad_jit_fwd(entry, arrays):
    if not entry.fwd_primed:
        entry.fwd_primed = True
        if _benchmark[0]:
            # first call pays trace+compile: surface it in the
            # FLAGS_benchmark table so recompile storms are attributable
            t0 = time.perf_counter()
            out = entry.fwd(*arrays)
            _bench_record(entry.name + "@grad_jit_compile",
                          time.perf_counter() - t0)
            return out
    return entry.fwd(*arrays)


def _grad_jit_bwd(entry, primals, cts):
    if not entry.bwd_primed:
        entry.bwd_primed = True
        if _benchmark[0]:
            t0 = time.perf_counter()
            out = entry.bwd(primals, cts)
            _bench_record(entry.name + "@grad_jit_bwd_compile",
                          time.perf_counter() - t0)
            return out
    return entry.bwd(primals, cts)


def _ct_add_op(a, b):
    return a + b


def _ct_accum(a, b):
    """Cotangent accumulation through the grad-jit cache: the backward
    walk's adds (the reference's GradientAccumulator) hit the same
    compiled-once path as the vjp applications, so a steady-state train
    step executes only cache hits."""
    if _eager_grad_jit[0]:
        e = _grad_jitted(_ct_add_op, {}, (a, b))
        if e is not None:
            return _grad_jit_fwd(e, (a, b))
    return a + b


_symbolic_dispatch_hook = [None]


def set_symbolic_dispatch(fn):
    """Install the static-mode recorder (paddle_tpu.static.graph): called
    with (fn, args, attrs, op_name); returns NotImplemented to fall
    through to eager execution."""
    _symbolic_dispatch_hook[0] = fn


# FLAGS_check_nan_inf post-op sanitizer (reference operator.cc:1199-1200 →
# CheckOpHasNanOrInf after every kernel run). The shared cell lives in
# core.native so `paddle.set_flags({"FLAGS_check_nan_inf": 1})` flips it.
from ..core.native import check_nan_inf as _nan_check  # noqa: E402
from ..core.native import benchmark as _benchmark  # noqa: E402
from ..core.native import eager_grad_jit as _eager_grad_jit  # noqa: E402
# FLAGS_sanitize (ISSUE 8): donation-after-use guard on Tensor host reads
# + recompile explainer on grad-jit cache misses; one list-index check
# per hook while unset
from ..core.native import sanitize as _sanitize  # noqa: E402
from ..analysis.sanitizers import check_host_read as _san_check_read  # noqa: E402
from ..analysis.sanitizers import note_recompile as _san_note_recompile  # noqa: E402
# Observability hooks (paddle_tpu.monitor): stat handles are pre-created
# module attributes so the idle dispatch path pays one counter add; span
# timing and FLAGS_benchmark accumulation are gated on shared cells.
from ..monitor import stats as _mstats  # noqa: E402
from ..monitor.benchmark import record_op as _bench_record  # noqa: E402
from ..monitor.trace import TRACING as _TRACING  # noqa: E402
from ..monitor.trace import get_writer as _trace_writer  # noqa: E402


def _check_finite(op_name, outs):
    for i, o in enumerate(outs):
        if hasattr(o, "dtype") and jnp.issubdtype(o.dtype, jnp.floating):
            if not bool(jnp.isfinite(o).all()):
                _mstats.NAN_INF_TRIPS.add()
                raise FloatingPointError(
                    f"FLAGS_check_nan_inf: output {i} of op '{op_name}' "
                    "contains NaN/Inf")


def apply_op(fn: Callable, *args, op_name: Optional[str] = None, **attrs):
    """Run pure function ``fn(*arrays, **attrs)`` on Tensor/array args.

    Records a GradNode when grad is enabled, we are not inside a jax trace,
    and at least one input requires grad. Returns Tensor (or tuple of
    Tensors mirroring fn's output structure). When static mode has
    installed a symbolic dispatcher and an arg is symbolic, the op is
    recorded into the active Program instead of executed.

    Instrumentation (paddle_tpu.monitor): every eager dispatch bumps the
    ``op_dispatch`` stat; while tracing is on each dispatch lands as a
    chrome-trace span, and while FLAGS_benchmark is set its wall time is
    accumulated per op. With both off the extra cost is the counter add —
    no span objects, no clock reads.
    """
    hook = _symbolic_dispatch_hook[0]
    if hook is not None:
        res = hook(fn, args, attrs, op_name)
        if res is not NotImplemented:
            return res
    _mstats.OP_DISPATCH.add()
    if _benchmark[0] or _TRACING[0]:
        name = op_name or getattr(fn, "__name__", "op")
        t0 = time.perf_counter()
        try:
            return _apply_op_eager(fn, args, attrs, op_name)
        finally:
            dt = time.perf_counter() - t0
            if _benchmark[0]:
                _bench_record(name, dt)
            if _TRACING[0]:
                _trace_writer().add_complete(name, t0, dt)
    return _apply_op_eager(fn, args, attrs, op_name)


def _apply_op_eager(fn, args, attrs, op_name):
    arrays = tuple(_unwrap(a) for a in args)
    tracing = any(_is_tracer(a) for a in arrays)
    input_tensors = tuple(a if isinstance(a, Tensor) else None for a in args)
    needs_grad = (
        not tracing
        and _grad_state.enabled
        and any(
            t is not None and (not t.stop_gradient or t._grad_node is not None)
            for t in input_tensors
        )
    )

    if needs_grad:
        entry = (_grad_jitted(fn, attrs, arrays,
                              op_name or getattr(fn, "__name__", "op"))
                 if _eager_grad_jit[0] else None)
        if entry is not None:
            # fast path: compiled forward; the grad node's "vjp closure"
            # is the cached jitted bwd bound to the primal args (which
            # double as the residuals — see the cache's module comment)
            f = entry.f
            out = _grad_jit_fwd(entry, arrays)
            vjp_fn = functools.partial(_grad_jit_bwd, entry, arrays)
        else:
            f_raw = functools.partial(fn, **attrs) if attrs else fn

            # normalize multi-output structure to a PLAIN tuple before
            # vjp: ops built on jnp.linalg (svd/qr/eigh) return
            # NamedTuples, and a vjp built on that structure rejects the
            # plain-tuple cotangents the backward walk supplies (found by
            # the decomposition grad sweep)
            def f(*a, _f=f_raw):
                o = _f(*a)
                return tuple(o) if isinstance(o, (tuple, list)) else o

            out, vjp_fn = jax.vjp(f, *arrays)
        multi = isinstance(out, (tuple, list))
        outs = tuple(out) if multi else (out,)
        if _nan_check[0]:
            _check_finite(op_name or getattr(fn, "__name__", "op"), outs)
        node = GradNode(
            vjp_fn,
            input_tensors,
            [(o.shape, o.dtype) for o in outs],
            multi,
            op_name or getattr(fn, "__name__", "op"),
            fn=f,
            arg_arrays=arrays,
        )
        result = []
        for i, o in enumerate(outs):
            t = Tensor(o, stop_gradient=False)
            t._grad_node = node
            t._out_index = i
            result.append(t)
        return tuple(result) if multi else result[0]

    if tracing:
        out = fn(*arrays, **attrs)
    else:
        j = _jitted(fn, attrs) if EAGER_JIT else None
        out = j(*arrays) if j is not None else fn(*arrays, **attrs)
        if _nan_check[0]:
            _check_finite(op_name or getattr(fn, "__name__", "op"),
                          out if isinstance(out, (tuple, list)) else (out,))
    if isinstance(out, (tuple, list)):
        return tuple(Tensor(o) for o in out)
    return Tensor(out)


# --------------------------------------------------------------------------
# backward engine
# --------------------------------------------------------------------------

def _topo_order(root: GradNode):
    order, seen = [], set()
    stack = [(root, False)]
    while stack:
        node, processed = stack.pop()
        if processed:
            order.append(node)
            continue
        if id(node) in seen:
            continue
        seen.add(id(node))
        stack.append((node, True))
        for t in node.inputs:
            if t is not None and t._grad_node is not None and id(t._grad_node) not in seen:
                stack.append((t._grad_node, False))
    return order  # leaves-first; reverse for backward


def _is_float0(ct) -> bool:
    return getattr(ct, "dtype", None) == jax.dtypes.float0


def backward(tensor: Tensor, grad_tensor=None, retain_graph: bool = False):
    """Reverse-mode walk of the tape (BasicEngine::Execute analog).

    The walk is a single coalesced pass over the reversed topo order:
    each node's vjp application is the cached jitted ``bwd`` the forward
    dispatch installed (grad-jit fast path) or its raw vjp closure
    (fallback), and cotangent accumulation routes through the same cache
    (:func:`_ct_accum`) — in steady state a train step's backward
    executes nothing but compiled-cache hits.
    """
    if tensor._grad_node is None:
        if not tensor.stop_gradient:
            g = (
                _unwrap(grad_tensor)
                if grad_tensor is not None
                else jnp.ones_like(tensor._data)
            )
            _accum_leaf(tensor, g)
        return

    if grad_tensor is None:
        seed_ct = jnp.ones_like(tensor._data)
    else:
        seed_ct = jnp.asarray(_unwrap(grad_tensor), dtype=tensor._data.dtype)

    node_cts: dict = {}  # id(node) -> list of cotangents per output
    root = tensor._grad_node
    node_cts[id(root)] = [None] * len(root.out_avals)
    node_cts[id(root)][tensor._out_index] = seed_ct

    pop = node_cts.pop
    for node in reversed(_topo_order(root)):
        cts = pop(id(node), None)
        if cts is None:
            continue
        if node.vjp_fn is None:
            raise RuntimeError(
                "Trying to backward through the graph a second time; "
                "pass retain_graph=True."
            )
        if node.multi_out:
            arg = tuple(
                c if c is not None else jnp.zeros(sh, dt)
                for c, (sh, dt) in zip(cts, node.out_avals)
            )
        else:
            arg = cts[0]
            if arg is None:
                sh, dt = node.out_avals[0]
                arg = jnp.zeros(sh, dt)
        in_cts = node.vjp_fn(arg)
        if not retain_graph:
            node.vjp_fn = None
        for t, ct in zip(node.inputs, in_cts):
            if t is None or ct is None or _is_float0(ct):
                continue
            gn = t._grad_node
            if gn is not None:
                slot = node_cts.setdefault(id(gn), [None] * len(gn.out_avals))
                i = t._out_index
                slot[i] = ct if slot[i] is None else _ct_accum(slot[i], ct)
            elif not t.stop_gradient:
                _accum_leaf(t, ct)


def _accum_leaf(t: Tensor, ct):
    if t.grad is None:
        t.grad = Tensor(ct)
    else:
        t.grad = Tensor(_ct_accum(t.grad._data, ct))
    for hook in _leaf_hooks.get(id(t), ()):
        hook(t)


# -- double grad (create_graph=True) ---------------------------------------
#
# The normal walk calls each node's saved vjp closure on raw arrays — fast,
# but the closure hides how the grad depends on the PRIMAL inputs (its
# residuals are baked in), so the result is a dead end for a second
# differentiation. The create_graph walk instead re-derives each node's vjp
# THROUGH apply_op with both the cotangents and the node's live primal
# inputs as explicit tensor arguments: the grad computation lands on the
# tape as ordinary ops, and paddle.grad composes to any order — the eager
# analog of the reference's partial_grad_engine double-grad
# (/root/reference/paddle/fluid/imperative/partial_grad_engine.cc:1).

def _node_vjp_recorded(node: GradNode, full_cts):
    """Run node's vjp through apply_op. full_cts: Tensor per output.
    Returns (live_positions, cotangent Tensors for those positions)."""
    if node.fn is None:
        raise RuntimeError(
            f"create_graph=True: op '{node.name}' has no re-runnable primal "
            "(PyLayer custom ops record only their backward closure, so "
            "second-order grads cannot flow through them — reformulate the "
            "PyLayer body with regular ops to use double grad)")
    live = [i for i, t in enumerate(node.inputs)
            if t is not None and jnp.issubdtype(
                jnp.asarray(t._data).dtype, jnp.inexact)]
    n_out = len(node.out_avals)
    arg_arrays = node.arg_arrays
    fn = node.fn
    multi = node.multi_out

    def gradop(*ins):
        cts, primals = ins[:n_out], ins[n_out:]
        args = list(arg_arrays)
        for j, i in enumerate(live):
            args[i] = primals[j]
        _, vjp = jax.vjp(fn, *args)
        in_cts = vjp(tuple(cts) if multi else cts[0])
        return tuple(in_cts[i] for i in live)

    out = apply_op(gradop, *full_cts,
                   *[node.inputs[i] for i in live],
                   op_name=f"{node.name}_grad")
    return live, (out if isinstance(out, tuple) else (out,))


def _backward_create_graph(tensor: Tensor, grad_tensor=None):
    """Tape-recording backward: like :func:`backward` but cotangents are
    Tensors and every vjp is an apply_op — leaf ``.grad``s come back
    graph-connected for higher-order differentiation."""
    if tensor._grad_node is None:
        if not tensor.stop_gradient:
            g = (grad_tensor if isinstance(grad_tensor, Tensor)
                 else Tensor(_unwrap(grad_tensor))
                 if grad_tensor is not None
                 else Tensor(jnp.ones_like(tensor._data)))
            t0 = tensor
            t0.grad = g if t0.grad is None else t0.grad + g
        return
    if grad_tensor is None:
        seed = Tensor(jnp.ones_like(tensor._data))
    elif isinstance(grad_tensor, Tensor):
        seed = grad_tensor
    else:
        seed = Tensor(jnp.asarray(_unwrap(grad_tensor),
                                  dtype=tensor._data.dtype))

    node_cts: dict = {}
    root = tensor._grad_node
    node_cts[id(root)] = [None] * len(root.out_avals)
    node_cts[id(root)][tensor._out_index] = seed

    order = _topo_order(root)
    for node in reversed(order):
        cts = node_cts.get(id(node))
        if cts is None:
            continue
        full = [c if c is not None else Tensor(jnp.zeros(sh, dt))
                for c, (sh, dt) in zip(cts, node.out_avals)]
        live, in_cts = _node_vjp_recorded(node, full)
        for i, ct in zip(live, in_cts):
            t = node.inputs[i]
            if t._grad_node is not None:
                slot = node_cts.setdefault(
                    id(t._grad_node), [None] * len(t._grad_node.out_avals))
                j = t._out_index
                slot[j] = ct if slot[j] is None else slot[j] + ct
            elif not t.stop_gradient:
                t.grad = ct if t.grad is None else t.grad + ct
                for hook in _leaf_hooks.get(id(t), ()):
                    hook(t)
        node_cts.pop(id(node), None)


def inplace_apply(x: "Tensor", fn, *args, **kwargs) -> "Tensor":
    """Inplace-API helper for the reference's trailing-underscore ops
    (tanh_/reshape_/scatter_ ...). XLA arrays are immutable, so "inplace"
    means: run the out-of-place op against an alias carrying x's tape node,
    then rebind x's buffer and node to the result. The alias (not x itself)
    is what the new GradNode records as input — rebinding x directly would
    make its node list x as its own input, a cycle that severs the tape.
    """
    if (_grad_state.enabled and not x.stop_gradient
            and x._grad_node is None):
        raise ValueError(
            "in-place operation on a leaf Tensor that requires grad is not "
            "supported (matches reference dygraph inplace semantics)")
    prev = Tensor(x._data, stop_gradient=x.stop_gradient)
    prev._grad_node = x._grad_node
    prev._out_index = x._out_index
    out = fn(prev, *args, **kwargs)
    x._data = out._data
    x._grad_node = out._grad_node
    x._out_index = out._out_index
    return x


# grad-accumulation hooks keyed by tensor id (DDP reducer uses these)
_leaf_hooks: dict = {}


def register_grad_hook(t: Tensor, hook):
    _leaf_hooks.setdefault(id(t), []).append(hook)
    return lambda: _leaf_hooks.get(id(t), []).remove(hook)


def grad(
    outputs,
    inputs,
    grad_outputs=None,
    retain_graph=None,
    create_graph=False,
    only_inputs=True,
    allow_unused=False,
    no_grad_vars=None,
):
    """paddle.grad parity (partial_grad_engine analog).

    Computes grads of outputs wrt inputs without writing ``.grad``.
    ``create_graph=True`` records the grad computation itself on the tape
    (see :func:`_backward_create_graph`) so the returned grads are
    differentiable — reference double-grad
    (imperative/partial_grad_engine.cc, dygraph/base.py grad()).
    ``no_grad_vars``: tensors treated as constants during this walk.
    """
    outs = outputs if isinstance(outputs, (list, tuple)) else [outputs]
    ins = inputs if isinstance(inputs, (list, tuple)) else [inputs]
    if grad_outputs is None:
        grad_outputs = [None] * len(outs)
    elif not isinstance(grad_outputs, (list, tuple)):
        grad_outputs = [grad_outputs]
    if retain_graph is None:
        # reference semantics: retain defaults to create_graph (the graph
        # must survive for the second-order backward to walk it)
        retain_graph = bool(create_graph)

    # no_grad_vars become temporary constant leaves: their node link is
    # unhooked so the walk neither descends past them nor accumulates.
    # Dedup by identity — a duplicated entry would snapshot the already-
    # frozen (None) state and the restore would leave the tensor severed.
    frozen = []
    if no_grad_vars:
        seen_ng = set()
        for t in (no_grad_vars if isinstance(no_grad_vars, (list, tuple))
                  else [no_grad_vars]):
            if id(t) in seen_ng:
                continue
            seen_ng.add(id(t))
            frozen.append((t, t._grad_node, t._out_index, t.stop_gradient))
            t._grad_node = None
            t.stop_gradient = True

    # Save/restore .grad of leaves so paddle.grad stays side-effect free.
    saved = {}

    def collect(t):
        saved[id(t)] = (t, t.grad)
        t.grad = None

    seen_nodes = set()
    for o in outs:
        if o._grad_node is None:
            continue
        for node in _topo_order(o._grad_node):
            if id(node) in seen_nodes:
                continue
            seen_nodes.add(id(node))
            for t in node.inputs:
                if t is not None and t._grad_node is None and not t.stop_gradient:
                    if id(t) not in saved:
                        collect(t)
    for t in ins:
        if id(t) not in saved:
            collect(t)

    try:
        for o, go in zip(outs, grad_outputs):
            if create_graph:
                _backward_create_graph(o, go)
            else:
                # always retain here: freeing (when retain_graph=False)
                # happens once in the finally block after ALL outputs
                # walked — per-output freeing would break multi-output grad
                backward(o, go, retain_graph=True)
        results = []
        for t in ins:
            g = t.grad
            if g is None and not allow_unused:
                raise RuntimeError(
                    "One of the differentiated tensors appears unused; "
                    "pass allow_unused=True."
                )
            results.append(g)
    finally:
        if not retain_graph:
            for o in outs:
                if o._grad_node is not None:
                    for node in _topo_order(o._grad_node):
                        node.vjp_fn = None
        for t, old in saved.values():
            t.grad = old
        for t, node, idx, sg in frozen:
            t._grad_node = node
            t._out_index = idx
            t.stop_gradient = sg
    return results
