"""ParamAttr — parameter attribute spec.

Parity: reference python/paddle/fluid/param_attr.py (ParamAttr, WeightNormParamAttr).
"""
from __future__ import annotations


class ParamAttr:
    def __init__(
        self,
        name=None,
        initializer=None,
        learning_rate=1.0,
        regularizer=None,
        trainable=True,
        do_model_average=True,
        need_clip=True,
    ):
        self.name = name
        self.initializer = initializer
        self.learning_rate = learning_rate
        self.regularizer = regularizer
        self.trainable = trainable
        self.do_model_average = do_model_average
        self.need_clip = need_clip

    @staticmethod
    def _to_attr(attr):
        """Normalize: None -> default, False -> no parameter, str -> named,
        Initializer -> wrap, ParamAttr -> itself."""
        if attr is None:
            return ParamAttr()
        if attr is False:
            return False
        if isinstance(attr, ParamAttr):
            return attr
        if isinstance(attr, str):
            return ParamAttr(name=attr)
        # assume an initializer instance
        return ParamAttr(initializer=attr)


class WeightNormParamAttr(ParamAttr):
    """reference WeightNormParamAttr (fluid/param_attr.py:216): requests
    the weight-norm reparameterization (g * v/||v||). DECISION: the
    static-graph reparameterization is served by the dygraph hook API
    (nn.utils.weight_norm); parameter creation with this attr raises and
    directs users there rather than silently training unnormalized.
    """

    def __init__(self, dim=None, **kwargs):
        super().__init__(**kwargs)
        self.weight_norm_dim = dim
