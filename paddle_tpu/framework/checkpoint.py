"""Sharded, async checkpoint / resume.

Parity surface (reference):
- ``paddle.save/load`` pickled state dicts — kept as-is in framework/io.py
  (reference python/paddle/framework/io.py:553/769).
- Fleet/persistables + pipeline-sharded per-stage checkpoints (reference
  fleet_base.py:701-828, pp_layers.py:381-416) → here ONE sharded tree:
  each host writes only its shards, orbax/tensorstore handles layout.
- **AutoCheckpoint** (reference fluid/incubate/checkpoint/
  auto_checkpoint.py:71 — periodic snapshots keyed by job env, auto-resume
  on restart) → :class:`CheckpointManager` with save_interval_steps +
  ``latest_step()`` resume.

TPU-native: checkpoints are orbax-backed — async (device→host copy happens
immediately, serialization in background threads so the train step is not
blocked), sharding-aware (restore places each shard on its mesh device
directly), format-stable across mesh reshapes (restoring on a different
mesh layout works because orbax stores the global array + metadata).
"""
from __future__ import annotations

import os
from typing import Any, Optional

import jax

__all__ = ["save_checkpoint", "load_checkpoint", "CheckpointManager"]


def _checkpointer():
    import orbax.checkpoint as ocp

    return ocp.StandardCheckpointer()


def _abstract_tree(tree):
    """Pytree of arrays → matching ShapeDtypeStructs (with shardings) used
    to direct a placement-aware restore."""
    return jax.tree_util.tree_map(
        lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype,
                                       sharding=getattr(x, "sharding", None))
        if hasattr(x, "shape") else x,
        tree)


def save_checkpoint(path: str, state: Any, force: bool = True) -> None:
    """Write a sharded checkpoint of a pytree of jax arrays."""
    path = os.path.abspath(path)
    ckptr = _checkpointer()
    try:
        ckptr.save(path, state, force=force)
        ckptr.wait_until_finished()
    finally:
        ckptr.close()


def load_checkpoint(path: str, template: Optional[Any] = None) -> Any:
    """Restore a checkpoint. ``template`` (pytree of arrays or
    ShapeDtypeStruct with shardings) directs placement: each shard is
    restored straight onto its mesh device."""
    path = os.path.abspath(path)
    ckptr = _checkpointer()
    try:
        if template is not None:
            return ckptr.restore(path, _abstract_tree(template))
        return ckptr.restore(path)
    finally:
        ckptr.close()


class CheckpointManager:
    """Periodic snapshots + retention + resume (AutoCheckpoint analog).

    Usage::

        mgr = CheckpointManager(dir, save_interval_steps=100, max_to_keep=3)
        start = mgr.restore_latest(step_obj) or 0     # auto-resume
        for step_i in range(start, n_steps):
            loss = step_obj(batch)
            mgr.maybe_save(step_i, step_obj)
    """

    def __init__(self, directory: str, save_interval_steps: int = 1,
                 max_to_keep: Optional[int] = 3, async_save: bool = True):
        import orbax.checkpoint as ocp

        self.directory = os.path.abspath(directory)
        self.save_interval_steps = max(1, int(save_interval_steps))
        options = ocp.CheckpointManagerOptions(
            max_to_keep=max_to_keep,
            save_interval_steps=self.save_interval_steps,
            enable_async_checkpointing=async_save,
        )
        self._mgr = ocp.CheckpointManager(self.directory, options=options)

    # -- state extraction ---------------------------------------------------

    @staticmethod
    def _state_of(obj):
        """Accepts a DistributedTrainStep (params/opt_state/step plus the
        global eager RNG state, so resume replays dropout identically) or a
        raw pytree."""
        if hasattr(obj, "params") and hasattr(obj, "opt_state"):
            from .random import get_rng_state

            key_data = jax.random.key_data(get_rng_state())
            return {"params": obj.params, "opt_state": obj.opt_state,
                    "step_count": obj._step_count,
                    "rng_key_data": key_data}
        return obj

    @staticmethod
    def _install(obj, state):
        if hasattr(obj, "params") and hasattr(obj, "opt_state") \
                and isinstance(state, dict) and "params" in state:
            obj.params = state["params"]
            obj.opt_state = state["opt_state"]
            obj._step_count = int(state.get("step_count", 0))
            if "rng_key_data" in state:
                from .random import set_rng_state

                set_rng_state(jax.random.wrap_key_data(state["rng_key_data"]))
            return obj
        return state

    # -- save/restore -------------------------------------------------------

    def maybe_save(self, step: int, obj) -> bool:
        """Interval-gated snapshot; returns False when skipped."""
        import orbax.checkpoint as ocp

        state = self._state_of(obj)
        return self._mgr.save(step, args=ocp.args.StandardSave(state))

    def save(self, step: int, obj) -> bool:
        """Unconditional snapshot (bypasses save_interval_steps) — for the
        final checkpoint before shutdown."""
        import orbax.checkpoint as ocp

        state = self._state_of(obj)
        return self._mgr.save(step, args=ocp.args.StandardSave(state),
                              force=True)

    def latest_step(self) -> Optional[int]:
        return self._mgr.latest_step()

    def restore(self, step: int, obj):
        """Restore snapshot ``step``. Train-step-like objects are updated
        in place (and returned); raw pytrees are templates — the restored
        tree is the RETURN VALUE (jax arrays are immutable)."""
        import orbax.checkpoint as ocp

        state = self._state_of(obj)
        restored = self._mgr.restore(
            step, args=ocp.args.StandardRestore(_abstract_tree(state)))
        return self._install(obj, restored)

    def restore_latest(self, obj) -> Optional[int]:
        """Auto-resume: restore the newest snapshot into ``obj``; returns
        the step to continue FROM (restored step + 1) or None if no
        checkpoint exists (reference AutoCheckpointChecker semantics).

        Only in-place-restorable objects (DistributedTrainStep-like) are
        accepted — a raw pytree could not receive the restored arrays, so
        it is rejected rather than silently resuming from stale weights;
        use ``restore(step, template)`` for raw trees."""
        step = self.latest_step()
        if step is None:
            return None
        out = self.restore(step, obj)
        if out is not obj:
            raise TypeError(
                "restore_latest needs an object with .params/.opt_state to "
                "install into; for a raw pytree use "
                "mgr.restore(mgr.latest_step(), template) and keep the "
                "returned tree")
        return step + 1

    def wait_until_finished(self):
        self._mgr.wait_until_finished()

    def close(self):
        self._mgr.close()
