"""Sharded, async checkpoint / resume.

Parity surface (reference):
- ``paddle.save/load`` pickled state dicts — kept as-is in framework/io.py
  (reference python/paddle/framework/io.py:553/769).
- Fleet/persistables + pipeline-sharded per-stage checkpoints (reference
  fleet_base.py:701-828, pp_layers.py:381-416) → here ONE sharded tree:
  each host writes only its shards, orbax/tensorstore handles layout.
- **AutoCheckpoint** (reference fluid/incubate/checkpoint/
  auto_checkpoint.py:71 — periodic snapshots keyed by job env, auto-resume
  on restart) → :class:`CheckpointManager` with save_interval_steps +
  ``latest_step()`` resume.

TPU-native: checkpoints are orbax-backed — async (device→host copy happens
immediately, serialization in background threads so the train step is not
blocked), sharding-aware (restore places each shard on its mesh device
directly), format-stable across mesh reshapes (restoring on a different
mesh layout works because orbax stores the global array + metadata).
"""
from __future__ import annotations

import os
import shutil
import time
import warnings
from typing import Any, Optional, Tuple

import jax

__all__ = ["save_checkpoint", "load_checkpoint", "CheckpointManager"]


def _checkpointer():
    import orbax.checkpoint as ocp

    return ocp.StandardCheckpointer()


def _save_attempt_hook() -> None:
    """ckpt_io_error fault-injection point (resilience.faults) — raises a
    transient OSError exactly where a flaky NFS/GCS-fuse mount would."""
    from ..resilience.faults import ENABLED, FAULTS

    if ENABLED[0]:
        FAULTS.on_ckpt_io()


def _with_io_retry(fn, what: str, retries: int = 3, backoff: float = 0.05):
    """Run ``fn`` retrying transient OSErrors with exponential backoff —
    checkpoint storage on real pods is NFS/GCS-fuse, where EIO/ESTALE
    blips are routine and a retry is the correct first response."""
    for attempt in range(retries + 1):
        try:
            _save_attempt_hook()
            return fn()
        except OSError as e:
            if attempt >= retries:
                raise
            delay = backoff * (2 ** attempt)
            warnings.warn(f"transient OSError during {what} "
                          f"(attempt {attempt + 1}/{retries + 1}): {e}; "
                          f"retrying in {delay:.2f}s")
            time.sleep(delay)


def _abstract_tree(tree):
    """Pytree of arrays → matching ShapeDtypeStructs (with shardings) used
    to direct a placement-aware restore."""
    return jax.tree_util.tree_map(
        lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype,
                                       sharding=getattr(x, "sharding", None))
        if hasattr(x, "shape") else x,
        tree)


def save_checkpoint(path: str, state: Any, force: bool = True,
                    retries: int = 3) -> None:
    """Write a sharded checkpoint of a pytree of jax arrays.

    Crash-safe: the tree is written to a sibling tmp dir and
    atomic-renamed into place, so a reader never observes a
    half-written checkpoint at ``path`` — a crash mid-save leaves either
    the previous complete checkpoint or a ``.tmp-*`` leftover that
    ``load_checkpoint`` ignores. Transient OSErrors are retried with
    exponential backoff."""
    path = os.path.abspath(path)
    tmp = f"{path}.tmp-{os.getpid()}-{time.monotonic_ns()}"

    def attempt():
        if os.path.exists(tmp):
            shutil.rmtree(tmp, ignore_errors=True)
        ckptr = _checkpointer()
        try:
            ckptr.save(tmp, state, force=True)
            ckptr.wait_until_finished()
        finally:
            ckptr.close()
        if os.path.exists(path):
            if not force:
                shutil.rmtree(tmp, ignore_errors=True)
                raise ValueError(f"checkpoint {path} exists (force=False)")
            shutil.rmtree(path)
        os.rename(tmp, path)

    try:
        _with_io_retry(attempt, f"checkpoint save to {path}",
                       retries=retries)
    finally:
        shutil.rmtree(tmp, ignore_errors=True)


def load_checkpoint(path: str, template: Optional[Any] = None) -> Any:
    """Restore a checkpoint. ``template`` (pytree of arrays or
    ShapeDtypeStruct with shardings) directs placement: each shard is
    restored straight onto its mesh device."""
    path = os.path.abspath(path)
    ckptr = _checkpointer()
    try:
        if template is not None:
            return ckptr.restore(path, _abstract_tree(template))
        return ckptr.restore(path)
    finally:
        ckptr.close()


class CheckpointManager:
    """Periodic snapshots + retention + resume (AutoCheckpoint analog).

    Usage::

        mgr = CheckpointManager(dir, save_interval_steps=100, max_to_keep=3)
        start = mgr.restore_latest(step_obj) or 0     # auto-resume
        for step_i in range(start, n_steps):
            loss = step_obj(batch)
            mgr.maybe_save(step_i, step_obj)
    """

    def __init__(self, directory: str, save_interval_steps: int = 1,
                 max_to_keep: Optional[int] = 3, async_save: bool = True,
                 save_retries: int = 3, keep_last: Optional[int] = None):
        import orbax.checkpoint as ocp

        self.directory = os.path.abspath(directory)
        self.save_interval_steps = max(1, int(save_interval_steps))
        self.save_retries = int(save_retries)
        # keep_last: OUR retention sweep over the step dirs on disk, on
        # top of orbax's max_to_keep. Orbax only garbage-collects steps
        # it tracks — a crash mid-write, a force-save retry, or a step
        # dir corrupted after the fact leaves directories all_steps()
        # never lists, and a long resilient run (rollbacks, preemption
        # relaunches) accumulates them without bound. The sweep removes
        # every step dir and stale ``*.tmp-*`` leftover older than the
        # newest ``keep_last`` steps; the tmp+atomic-rename discipline is
        # untouched (renames happen first, the sweep only ever deletes).
        self.keep_last = int(keep_last) if keep_last else None
        options = ocp.CheckpointManagerOptions(
            max_to_keep=max_to_keep,
            save_interval_steps=self.save_interval_steps,
            enable_async_checkpointing=async_save,
        )
        self._mgr = ocp.CheckpointManager(self.directory, options=options)

    # -- state extraction ---------------------------------------------------

    @staticmethod
    def _state_of(obj):
        """Accepts a DistributedTrainStep (params/opt_state/step plus the
        global eager RNG state, so resume replays dropout identically) or a
        raw pytree."""
        if hasattr(obj, "params") and hasattr(obj, "opt_state"):
            from .random import get_rng_state

            key_data = jax.random.key_data(get_rng_state())
            return {"params": obj.params, "opt_state": obj.opt_state,
                    "step_count": obj._step_count,
                    "rng_key_data": key_data}
        return obj

    @staticmethod
    def _install(obj, state):
        if hasattr(obj, "params") and hasattr(obj, "opt_state") \
                and isinstance(state, dict) and "params" in state:
            obj.params = state["params"]
            obj.opt_state = state["opt_state"]
            obj._step_count = int(state.get("step_count", 0))
            if "rng_key_data" in state:
                from .random import set_rng_state

                set_rng_state(jax.random.wrap_key_data(state["rng_key_data"]))
            return obj
        return state

    # -- save/restore -------------------------------------------------------

    def _save(self, step: int, obj, force: bool) -> bool:
        import orbax.checkpoint as ocp

        state = self._state_of(obj)
        out = _with_io_retry(
            lambda: self._mgr.save(step, args=ocp.args.StandardSave(state),
                                   force=force),
            f"checkpoint save (step {step})", retries=self.save_retries)
        if self.keep_last is not None:
            self._gc(just_saved=step)
        return out

    def should_save(self, step: int) -> bool:
        """Whether :meth:`maybe_save` would write at this step (public so
        an async caller can gate BEFORE paying for the host offload)."""
        return bool(self._mgr.should_save(step))

    def maybe_save(self, step: int, obj) -> bool:
        """Interval-gated snapshot; returns False when skipped. Transient
        OSErrors (flaky NFS/GCS-fuse) are retried with backoff."""
        # gate BEFORE touching storage so skipped intervals cost nothing
        # (and the fault-injection hook only fires on real save attempts)
        if not self._mgr.should_save(step):
            return False
        return self._save(step, obj, force=False)

    # -- retention ----------------------------------------------------------
    def _gc(self, just_saved: Optional[int] = None) -> None:
        """The ``keep_last`` sweep: delete every numeric step dir older
        than the newest ``keep_last`` (corrupt ones included — age is the
        step NUMBER, so a garbage-filled old dir cannot pin itself by
        mtime) plus any stale ``*.tmp-*`` leftovers. The dir just saved
        is never deleted even if retention math would pick it."""
        try:
            names = os.listdir(self.directory)
        except OSError:
            return
        steps = []
        for n in names:
            p = os.path.join(self.directory, n)
            if ".tmp-" in n or n.startswith("tmp"):
                shutil.rmtree(p, ignore_errors=True)
                continue
            if n.isdigit() and os.path.isdir(p):
                steps.append(int(n))
        keep = set(sorted(steps)[-self.keep_last:])
        if just_saved is not None:
            keep.add(int(just_saved))
        for s in steps:
            if s not in keep:
                shutil.rmtree(os.path.join(self.directory, str(s)),
                              ignore_errors=True)

    def save(self, step: int, obj) -> bool:
        """Unconditional snapshot (bypasses save_interval_steps) — for the
        final checkpoint before shutdown."""
        return self._save(step, obj, force=True)

    def latest_step(self) -> Optional[int]:
        return self._mgr.latest_step()

    def restore(self, step: int, obj):
        """Restore snapshot ``step``. Train-step-like objects are updated
        in place (and returned); raw pytrees are templates — the restored
        tree is the RETURN VALUE (jax arrays are immutable)."""
        import orbax.checkpoint as ocp

        state = self._state_of(obj)
        restored = self._mgr.restore(
            step, args=ocp.args.StandardRestore(_abstract_tree(state)))
        return self._install(obj, restored)

    def restore_latest(self, obj) -> Optional[int]:
        """Auto-resume: restore the newest INTACT snapshot into ``obj``;
        returns the step to continue FROM (restored step + 1) or None if
        nothing is restorable (reference AutoCheckpointChecker semantics).

        A crash mid-save can leave the newest step dir incomplete or
        corrupt; rather than wedging the relaunch, such steps are skipped
        with a warning and the next-newest one is tried.

        Only in-place-restorable objects (DistributedTrainStep-like) are
        accepted — a raw pytree could not receive the restored arrays, so
        it is rejected rather than silently resuming from stale weights;
        use ``restore_latest_tree(template)`` for raw trees."""
        for step in sorted(self._mgr.all_steps(), reverse=True):
            try:
                out = self.restore(step, obj)
            except Exception as e:  # noqa: BLE001 — skip corrupt, keep looking
                warnings.warn(
                    f"skipping unreadable checkpoint step {step} in "
                    f"{self.directory}: {type(e).__name__}: {e}")
                continue
            if out is not obj:
                raise TypeError(
                    "restore_latest needs an object with .params/.opt_state "
                    "to install into; for a raw pytree use "
                    "restore_latest_tree(template) and keep the returned "
                    "tree")
            return step + 1
        return None

    def restore_latest_tree(self, template) -> Optional[Tuple[int, Any]]:
        """Raw-pytree twin of :meth:`restore_latest`: returns
        ``(step, restored_tree)`` from the newest intact snapshot, or
        None. Corrupt/incomplete step dirs are skipped with a warning."""
        for step in sorted(self._mgr.all_steps(), reverse=True):
            try:
                return step, self.restore(step, template)
            except Exception as e:  # noqa: BLE001
                warnings.warn(
                    f"skipping unreadable checkpoint step {step} in "
                    f"{self.directory}: {type(e).__name__}: {e}")
        return None

    def wait_until_finished(self):
        self._mgr.wait_until_finished()

    def close(self):
        self._mgr.close()
