"""Dtype system.

TPU-native analog of the reference's VarType dtype enum
(/root/reference/paddle/fluid/framework/framework.proto:117) and the
python-side conversion helpers
(/root/reference/python/paddle/fluid/data_feeder.py convert_dtype).

We map Paddle dtype names onto jax/numpy dtypes. bfloat16 is first-class
(it is the native TPU matmul dtype) rather than an afterthought.
"""
from __future__ import annotations

import jax.numpy as jnp
import numpy as np

# Canonical name -> jnp dtype
_NAME_TO_DTYPE = {
    "bool": jnp.bool_,
    "uint8": jnp.uint8,
    "int8": jnp.int8,
    "int16": jnp.int16,
    "int32": jnp.int32,
    "int64": jnp.int64,
    "float16": jnp.float16,
    "bfloat16": jnp.bfloat16,
    "float32": jnp.float32,
    "float64": jnp.float64,
    "complex64": jnp.complex64,
    "complex128": jnp.complex128,
}

bool = jnp.bool_  # noqa: A001 - mirrors paddle.bool
uint8 = jnp.uint8
int8 = jnp.int8
int16 = jnp.int16
int32 = jnp.int32
int64 = jnp.int64
float16 = jnp.float16
bfloat16 = jnp.bfloat16
float32 = jnp.float32
float64 = jnp.float64
complex64 = jnp.complex64
complex128 = jnp.complex128

_DEFAULT_DTYPE = [jnp.float32]


def set_default_dtype(d):
    """paddle.set_default_dtype parity."""
    _DEFAULT_DTYPE[0] = convert_dtype(d)


def get_default_dtype():
    return np.dtype(_DEFAULT_DTYPE[0]).name


def default_float_dtype():
    return _DEFAULT_DTYPE[0]


def convert_dtype(dtype):
    """Normalize any user-supplied dtype spec to a numpy/jnp dtype."""
    if dtype is None:
        return None
    if isinstance(dtype, str):
        if dtype not in _NAME_TO_DTYPE:
            raise ValueError(f"Unknown dtype {dtype!r}")
        return jnp.dtype(_NAME_TO_DTYPE[dtype])
    # Accept numpy dtypes, jnp scalar types, python types
    try:
        return jnp.dtype(dtype)
    except TypeError:
        raise ValueError(f"Cannot convert {dtype!r} to a dtype")


def dtype_name(dtype) -> str:
    d = jnp.dtype(dtype)
    return d.name


def is_floating(dtype) -> bool:
    return jnp.issubdtype(jnp.dtype(dtype), jnp.floating)


def is_integer(dtype) -> bool:
    return jnp.issubdtype(jnp.dtype(dtype), jnp.integer)


# paddle.dtype parity: the reference aliases VarDesc.VarType as paddle.dtype
# (reference python/paddle/framework/dtype.py:17); here dtypes ARE numpy
# dtypes, so the class users construct/compare with is np.dtype itself.
dtype = np.dtype
