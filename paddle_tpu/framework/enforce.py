"""Enforce/error machinery — structured input validation for the public API.

Parity: reference PADDLE_ENFORCE (paddle/fluid/platform/enforce.h) and its
Python surface (`check_variable_and_dtype`/`check_type`/`check_dtype` in
python/paddle/fluid/data_feeder.py): every public op validates its inputs
and raises a rich, categorized error with the op name and a hint — instead
of letting a raw jax/XLA traceback surface three layers down.

Error categories mirror paddle/fluid/platform/errors.h; each class also
subclasses the natural Python builtin (TypeError/ValueError) so generic
`except ValueError` handling and existing tests keep working.
"""
from __future__ import annotations

from typing import Any, Iterable, Optional, Sequence

__all__ = [
    "EnforceNotMet", "InvalidArgumentError", "NotFoundError",
    "OutOfRangeError", "AlreadyExistsError", "PreconditionNotMetError",
    "UnimplementedError", "UnavailableError", "enforce", "check_type",
    "check_dtype", "check_axis", "check_shape_broadcast",
]


class EnforceNotMet(Exception):
    """Base of all enforce failures (reference platform::EnforceNotMet)."""

    category = "Error"

    def __init__(self, message: str, hint: Optional[str] = None):
        text = f"{self.category}: {message}"
        if hint:
            text += f"\n  [Hint: {hint}]"
        super().__init__(text)


class InvalidArgumentError(EnforceNotMet, ValueError):
    category = "InvalidArgumentError"


class NotFoundError(EnforceNotMet, KeyError):
    category = "NotFoundError"


class OutOfRangeError(EnforceNotMet, IndexError):
    category = "OutOfRangeError"


class AlreadyExistsError(EnforceNotMet, ValueError):
    category = "AlreadyExistsError"


class PreconditionNotMetError(EnforceNotMet, RuntimeError):
    category = "PreconditionNotMetError"


class UnimplementedError(EnforceNotMet, NotImplementedError):
    category = "UnimplementedError"


class UnavailableError(EnforceNotMet, RuntimeError):
    category = "UnavailableError"


class TypeEnforceError(EnforceNotMet, TypeError):
    category = "InvalidArgumentError"


def enforce(condition: Any, message: str, hint: Optional[str] = None,
            exc=InvalidArgumentError) -> None:
    """PADDLE_ENFORCE analog: raise a categorized error when falsy."""
    if not condition:
        raise exc(message, hint)


def check_type(x, name: str, expected_types, op_name: str) -> None:
    """reference data_feeder.check_type: typed argument validation."""
    if not isinstance(expected_types, tuple):
        expected_types = (expected_types,)
    if not isinstance(x, expected_types):
        want = "/".join(t.__name__ for t in expected_types)
        raise TypeEnforceError(
            f"The type of '{name}' in {op_name} must be {want}, but "
            f"received {type(x).__name__}.")


def check_dtype(dtype, name: str, expected: Iterable[str],
                op_name: str) -> None:
    """reference data_feeder.check_dtype: dtype whitelist validation."""
    d = str(dtype)
    for pref in ("paddle.", "jax.numpy.", "numpy."):
        if d.startswith(pref):
            d = d[len(pref):]
    expected = list(expected)
    if d not in expected:
        raise InvalidArgumentError(
            f"The data type of '{name}' in {op_name} must be one of "
            f"{expected}, but received {d}.")


def check_axis(axis: int, ndim: int, op_name: str) -> int:
    """Validate and normalize a dim index (reference enforce pattern in
    every axis-taking op): axis in [-ndim, ndim)."""
    if not isinstance(axis, int):
        raise TypeEnforceError(
            f"The type of 'axis' in {op_name} must be int, but received "
            f"{type(axis).__name__}.")
    if not (-ndim <= axis < max(ndim, 1)):
        raise OutOfRangeError(
            f"The axis of {op_name} is expected in range [{-ndim}, "
            f"{ndim}), but received {axis}.",
            hint=f"the input has {ndim} dimensions")
    return axis + ndim if axis < 0 else axis


def check_shape_broadcast(s1: Sequence[int], s2: Sequence[int],
                          op_name: str) -> None:
    """Validate numpy-style broadcastability with an actionable message."""
    a, b = list(s1)[::-1], list(s2)[::-1]
    for i in range(min(len(a), len(b))):
        if a[i] != b[i] and a[i] != 1 and b[i] != 1:
            raise InvalidArgumentError(
                f"Broadcast dimension mismatch in {op_name}: operand "
                f"shapes {list(s1)} and {list(s2)} are incompatible at "
                f"dim {len(a) - 1 - i if len(a) >= len(b) else len(b) - 1 - i}.",
                hint="each trailing dimension must match or be 1")
