from . import dtype
from .core import (
    Tensor,
    Parameter,
    apply_op,
    backward,
    grad,
    no_grad,
    enable_grad,
    is_grad_enabled,
    set_grad_enabled,
    to_tensor,
)
from .random import seed, get_rng_state, set_rng_state
from .checkpoint import CheckpointManager, load_checkpoint, save_checkpoint
