from .gradient_merge import GradientMergeOptimizer  # noqa: F401
