"""GradientMerge optimizer wrapper.

Parity: reference GradientMergeOptimizer (python/paddle/fluid/
optimizer.py:6782 and fleet/meta_optimizers/gradient_merge_optimizer.py):
accumulate gradients for k steps, apply the (optionally averaged) sum on
the k-th, zero the accumulators. The reference rewrites the static program
with conditional blocks; here the accumulation is an eager wrapper — the
per-step add is one fused XLA op per parameter, and the inner optimizer is
untouched between boundaries.

Consumed by fleet.distributed_optimizer when
``strategy.gradient_merge=True`` (gradient_merge_configs: k_steps, avg).
"""
from __future__ import annotations

from ....framework.core import Tensor

__all__ = ["GradientMergeOptimizer"]


class GradientMergeOptimizer:
    def __init__(self, inner_optimizer, k_steps=1, avg=True):
        if k_steps < 1:
            raise ValueError("k_steps must be >= 1")
        self._inner_opt = inner_optimizer
        self.k_steps = int(k_steps)
        self.avg = bool(avg)
        self._step_count = 0
        self._acc = {}  # id(param) -> accumulated grad array

    @property
    def _parameter_list(self):
        return self._inner_opt._parameter_list

    def step(self):
        self._step_count += 1
        params = self._inner_opt._parameter_list or []
        boundary = self._step_count % self.k_steps == 0
        for p in params:
            if p.grad is None:
                continue
            acc = self._acc.get(id(p))
            g = p.grad._data
            acc = g if acc is None else acc + g
            if boundary:
                if self.avg:
                    acc = acc / self.k_steps
                p.grad = Tensor(acc)
                self._acc.pop(id(p), None)
            else:
                self._acc[id(p)] = acc
        if boundary:
            self._inner_opt.step()

    def clear_grad(self):
        self._inner_opt.clear_grad()

    clear_gradients = clear_grad

    def minimize(self, loss, startup_program=None, parameters=None,
                 no_grad_set=None):
        from ....framework.core import backward

        backward(loss)
        self.step()
        return None, []

    def get_lr(self):
        return self._inner_opt.get_lr()

    def state_dict(self):
        return {"inner": self._inner_opt.state_dict(),
                "step_count": self._step_count}

    def set_state_dict(self, sd):
        self._step_count = int(sd.get("step_count", 0))
        if "inner" in sd:
            self._inner_opt.set_state_dict(sd["inner"])

    def __getattr__(self, item):
        return getattr(self._inner_opt, item)
