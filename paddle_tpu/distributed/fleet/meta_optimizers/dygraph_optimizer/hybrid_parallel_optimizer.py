"""Hybrid-parallel optimizer wrapper.

Parity: reference fleet/meta_optimizers/dygraph_optimizer/
hybrid_parallel_optimizer.py:173 (HybridParallelOptimizer: step =
sharding_reduce_gradients → fused_allreduce_gradients(dp) → inner step) and
:45 (HybridParallelClipGrad — global norm allreduced across mp+pp groups).

TPU-native: grad reduction across dp/mp happens inside the compiled step
(psum emitted by GSPMD); the eager wrapper therefore focuses on the clip
semantics and pass-through, keeping the reference API.
"""
from __future__ import annotations

from .....nn.clip import ClipGradByGlobalNorm
from ....env import get_state

__all__ = ["HybridParallelOptimizer", "HybridParallelClipGrad"]


class HybridParallelClipGrad:
    """Global-norm clip; on TPU the norm is already global once grads are
    reduced in the compiled step, so this reduces to ClipGradByGlobalNorm."""

    def __init__(self, clip, hcg):
        self._clip = clip
        self._hcg = hcg

    def __call__(self, params_grads):
        return self._clip(params_grads)


class HybridParallelOptimizer:
    def __init__(self, optimizer, hcg, strategy):
        self._inner_opt = optimizer
        self._hcg = hcg
        self._strategy = strategy
        if isinstance(optimizer._grad_clip, ClipGradByGlobalNorm):
            optimizer._grad_clip = HybridParallelClipGrad(optimizer._grad_clip, hcg)

    def step(self):
        self._inner_opt.step()

    def minimize(self, loss, startup_program=None, parameters=None, no_grad_set=None):
        return self._inner_opt.minimize(loss, startup_program, parameters, no_grad_set)

    def clear_grad(self):
        self._inner_opt.clear_grad()

    clear_gradients = clear_grad

    def get_lr(self):
        return self._inner_opt.get_lr()

    def set_lr(self, v):
        return self._inner_opt.set_lr(v)

    def state_dict(self):
        return self._inner_opt.state_dict()

    def set_state_dict(self, sd):
        return self._inner_opt.set_state_dict(sd)

    def __getattr__(self, item):
        return getattr(self._inner_opt, item)
