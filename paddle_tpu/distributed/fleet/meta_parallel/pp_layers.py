"""Pipeline layer decomposition.

Parity: reference fleet/meta_parallel/parallel_layers/pp_layers.py:132
(PipelineLayer, LayerDesc, SharedLayerDesc, SegmentLayers).

TPU-native: one process owns every stage (devices are mesh columns, not
processes), so PipelineLayer keeps the full layer list plus the
stage-segmentation metadata. Schedulers consume that metadata:
- PipelineParallel.train_batch: microbatch accumulation (exact semantics);
- paddle_tpu.parallel.pipeline: shard_map + ppermute schedule that places
  stage s's weights on mesh "pipe" coordinate s for true pipelined
  execution of uniform stages.
"""
from __future__ import annotations

import math
import re
from functools import partial
from typing import Callable, List, Optional, Union

from ....nn.layer.layers import Layer

__all__ = ["LayerDesc", "SharedLayerDesc", "PipelineLayer", "SegmentLayers"]


class LayerDesc:
    def __init__(self, layer_func, *inputs, **kwargs):
        self.layer_func = layer_func
        self.inputs = inputs
        self.kwargs = kwargs
        if not issubclass(layer_func, Layer):
            raise TypeError("layer_func must be a Layer subclass")

    def build_layer(self):
        return self.layer_func(*self.inputs, **self.kwargs)

    def __repr__(self):
        return f"LayerDesc({self.layer_func.__name__})"


class SharedLayerDesc(LayerDesc):
    def __init__(self, key, layer_func, forward_func=None, shared_weight_attr="weight",
                 *inputs, **kwargs):
        super().__init__(layer_func, *inputs, **kwargs)
        self.layer_name = key
        self.forward_func = forward_func
        self.shared_weight_attr = shared_weight_attr


class SegmentLayers:
    """reference pp_layers.py:63 — uniform / param-weighted segmentation."""

    def __init__(self, layers_desc, num_parts, method="uniform"):
        self._layers_desc = layers_desc
        self.method = method
        self.num_parts = num_parts
        self.num_items = len(layers_desc)
        assert self.num_items >= self.num_parts

    def do_segment(self):
        if self.method == "uniform":
            return self.uniform(self.num_items, self.num_parts)
        if self.method.startswith("layer:"):
            # segment by layer class name occurrences
            name = self.method.split(":", 1)[1]
            weights = [0] * len(self._layers_desc)
            for i, d in enumerate(self._layers_desc):
                cls = d.layer_func if isinstance(d, LayerDesc) else type(d)
                if re.search(name, cls.__name__):
                    weights[i] = 1
            actual = sum(weights)
            assert actual >= self.num_parts, (
                f"only {actual} layers match {name}, need >= {self.num_parts}")
            return self.segment_by_weights(weights)
        raise ValueError(f"unknown segment method {self.method}")

    @staticmethod
    def uniform(num_items, num_parts):
        result = [0] * (num_parts + 1)
        part_size = math.floor(num_items / num_parts)
        extra = num_items % num_parts
        for i in range(1, num_parts + 1):
            result[i] = result[i - 1] + part_size + (1 if i <= extra else 0)
        return result

    def segment_by_weights(self, weights):
        total = sum(weights)
        per = total / self.num_parts
        result = [0] * (self.num_parts + 1)
        acc, part = 0, 1
        for i, w in enumerate(weights):
            acc += w
            if acc >= per * part and part < self.num_parts:
                result[part] = i + 1
                part += 1
        result[self.num_parts] = len(weights)
        return result


class PipelineLayer(Layer):
    def __init__(self, layers, num_stages=None, topology=None, loss_fn=None,
                 seg_method="uniform", recompute_interval=0, recompute_offload=False,
                 recompute_partition=False):
        super().__init__()
        from ... import env

        self._layers_desc = list(layers)
        self._loss_fn = loss_fn
        self._recompute_interval = recompute_interval
        self._topo = topology
        hcg = env.get_state().get("hcg")
        if num_stages is None:
            num_stages = hcg.get_pipe_parallel_world_size() if hcg else 1
        self._num_stages = num_stages
        self._stage_id = hcg.get_stage_id() if hcg else 0

        seg = SegmentLayers(self._layers_desc, num_parts=num_stages, method=seg_method)
        self.segment_parts = seg.do_segment()

        # build ALL layers (single process owns the full model on TPU);
        # record stage boundaries for the schedulers
        self._shared_layers = {}
        self.run_function: List = []
        self._stage_of_layer = []
        for stage in range(num_stages):
            for i in range(self.segment_parts[stage], self.segment_parts[stage + 1]):
                desc = self._layers_desc[i]
                layer = self._build_one(desc, i)
                self.run_function.append(layer)
                self._stage_of_layer.append(stage)

    def _build_one(self, desc, idx):
        if isinstance(desc, SharedLayerDesc):
            if desc.layer_name not in self._shared_layers:
                built = desc.build_layer()
                self._shared_layers[desc.layer_name] = built
                self.add_sublayer(f"shared_{desc.layer_name}", built)
            layer = self._shared_layers[desc.layer_name]
            if desc.forward_func is not None:
                return partial(desc.forward_func, layer)
            return layer
        if isinstance(desc, LayerDesc):
            built = desc.build_layer()
            self.add_sublayer(str(idx), built)
            return built
        if isinstance(desc, Layer):
            self.add_sublayer(str(idx), desc)
            return desc
        if callable(desc):
            return desc
        raise TypeError(f"bad layer desc {desc}")

    def get_num_stages(self):
        return self._num_stages

    def get_stage_layers(self, stage_id):
        return [f for f, s in zip(self.run_function, self._stage_of_layer) if s == stage_id]

    def forward(self, input):  # noqa: A002
        from ..utils.recompute import recompute

        x = input
        for i, fn in enumerate(self.run_function):
            if self._recompute_interval > 0 and i % self._recompute_interval == 0 and not isinstance(x, tuple):
                x = recompute(fn, x)
            else:
                x = fn(*x) if isinstance(x, tuple) else fn(x)
        return x

    def save_state_dict(self, path):
        """Per-stage sharded checkpoint dirs (reference pp_layers.py:381)."""
        import os

        from ....framework.io import save

        os.makedirs(path, exist_ok=True)
        for stage in range(self._num_stages):
            sd = {}
            for i, (fn, s) in enumerate(zip(self.run_function, self._stage_of_layer)):
                if s != stage or not isinstance(fn, Layer):
                    continue
                for k, v in fn.state_dict().items():
                    sd[f"layer_{i}.{k}"] = v
            save(sd, os.path.join(path, f"stage_{stage}.pdparams"))

    def load_state_dict_from(self, path):
        import os

        from ....framework.io import load

        for stage in range(self._num_stages):
            f = os.path.join(path, f"stage_{stage}.pdparams")
            if not os.path.exists(f):
                continue
            sd = load(f)
            for i, (fn, s) in enumerate(zip(self.run_function, self._stage_of_layer)):
                if s != stage or not isinstance(fn, Layer):
                    continue
                prefix = f"layer_{i}."
                sub = {k[len(prefix):]: v for k, v in sd.items() if k.startswith(prefix)}
                if sub:
                    fn.set_state_dict(sub)
