"""TP-aware RNG state tracking.

Parity: reference fleet/meta_parallel/parallel_layers/random.py:32
(RNGStatesTracker, model_parallel_random_seed, get_rng_state_tracker):
dropout must DIFFER across model-parallel ranks (they hold different
activation shards) but MATCH across data-parallel replicas.

TPU-native: seeds derive jax PRNG keys; inside compiled code the "local"
dropout key is folded with the mesh "model" axis index, which reproduces
the per-mp-rank streams without per-process state.
"""
from __future__ import annotations

import contextlib

import jax

from .....framework import random as grandom

__all__ = ["RNGStatesTracker", "get_rng_state_tracker", "model_parallel_random_seed",
           "MODEL_PARALLEL_RNG"]

MODEL_PARALLEL_RNG = "model_parallel_rng"


class RNGStatesTracker:
    def __init__(self):
        self.states_ = {}
        self.seeds_ = set()

    def reset(self):
        self.states_ = {}
        self.seeds_ = set()

    def add(self, name, seed):
        if seed in self.seeds_:
            raise ValueError(f"seed {seed} already exists")
        self.seeds_.add(seed)
        if name in self.states_:
            raise ValueError(f"state {name} already exists")
        self.states_[name] = jax.random.key(seed)

    @contextlib.contextmanager
    def rng_state(self, name=MODEL_PARALLEL_RNG):
        if name not in self.states_:
            raise ValueError(f"state {name} does not exist")
        orig = grandom.get_rng_state()
        grandom.set_rng_state(self.states_[name])
        try:
            yield
        finally:
            self.states_[name] = grandom.get_rng_state()
            grandom.set_rng_state(orig)


_RNG_STATE_TRACKER = RNGStatesTracker()


def get_rng_state_tracker():
    return _RNG_STATE_TRACKER


def model_parallel_random_seed(seed=None):
    import random as pyrandom

    from .... import env

    hcg = env.get_state().get("hcg")
    mp_rank = hcg.get_model_parallel_rank() if hcg else 0
    if seed:
        global_seed = seed
        local_seed = seed * 1024 + mp_rank * 100
    else:
        global_seed = pyrandom.randint(0, 100000)
        local_seed = global_seed * 1024 + mp_rank * 100
    _RNG_STATE_TRACKER.reset()
    _RNG_STATE_TRACKER.add(MODEL_PARALLEL_RNG, local_seed)
    grandom.seed(global_seed)
