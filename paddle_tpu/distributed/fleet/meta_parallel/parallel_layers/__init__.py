"""Parallel layers (reference fleet/meta_parallel/parallel_layers)."""
from .mp_layers import (  # noqa: F401
    ColumnParallelLinear,
    ParallelCrossEntropy,
    RowParallelLinear,
    VocabParallelEmbedding,
)
from .random import (  # noqa: F401
    RNGStatesTracker,
    get_rng_state_tracker,
    model_parallel_random_seed,
)
