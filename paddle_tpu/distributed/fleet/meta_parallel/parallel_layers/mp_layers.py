"""Tensor-parallel layers.

Parity: reference fleet/meta_parallel/parallel_layers/mp_layers.py:30-300
(VocabParallelEmbedding, ColumnParallelLinear, RowParallelLinear,
ParallelCrossEntropy).

TPU-native redesign (GSPMD-first): the reference shards weights manually per
rank and inserts explicit collectives (_c_identity / _c_concat / _c_split /
_mp_allreduce). Here each layer holds the FULL logical weight annotated with
a PartitionSpec on the "model" mesh axis; forward applies sharding
constraints and XLA/GSPMD inserts the all-gathers/reduce-scatters over ICI.
Same math, same memory per device once jit'd over the mesh, no ring plumbing.
"""
from __future__ import annotations

import jax.numpy as jnp

from .....framework.core import Tensor
from .....nn import functional as F
from .....nn import initializer as I
from .....nn.layer.layers import Layer
from .... import env
from ....sharding_utils import P, shard_constraint

__all__ = ["VocabParallelEmbedding", "ColumnParallelLinear", "RowParallelLinear",
           "ParallelCrossEntropy"]


def _mp_degree():
    hcg = env.get_state().get("hcg")
    return hcg.get_model_parallel_world_size() if hcg else 1


class VocabParallelEmbedding(Layer):
    """Vocab-sharded embedding (reference mp_layers.py:30; c_embedding op)."""

    def __init__(self, num_embeddings, embedding_dim, weight_attr=None, name=None):
        super().__init__()
        self._num_embeddings = num_embeddings
        self._embedding_dim = embedding_dim
        self.weight = self.create_parameter(
            shape=[num_embeddings, embedding_dim], attr=weight_attr,
            default_initializer=I.XavierNormal())
        self.weight.is_distributed = _mp_degree() > 1
        self.weight.sharding = P("model", None)  # rows sharded over mp

    def forward(self, x):
        out = F.embedding(x, self.weight)
        # output replicated across mp (XLA all-gathers the sharded rows)
        return shard_constraint(out, "data")


class ColumnParallelLinear(Layer):
    """Output-dim sharded linear (reference mp_layers.py:97)."""

    def __init__(self, in_features, out_features, weight_attr=None,
                 has_bias=None, gather_output=True, fuse_matmul_bias=False,
                 name=None):
        super().__init__()
        self._in_features = in_features
        self._out_features = out_features
        self.gather_output = gather_output
        self.weight = self.create_parameter(
            shape=[in_features, out_features], attr=weight_attr,
            default_initializer=I.XavierNormal())
        self.weight.sharding = P(None, "model")
        if has_bias is None or has_bias:
            self.bias = self.create_parameter(shape=[out_features], is_bias=True)
            self.bias.sharding = P("model")
        else:
            self.bias = None

    def forward(self, x):
        y = F.linear(x, self.weight, self.bias)
        if self.gather_output:
            # replicate (all-gather over mp)
            y = shard_constraint(y, "data")
        else:
            y = shard_constraint(y, "data", *([None] * (y.ndim - 2)), "model")
        return y


class RowParallelLinear(Layer):
    """Input-dim sharded linear (reference mp_layers.py:170)."""

    def __init__(self, in_features, out_features, weight_attr=None,
                 has_bias=True, input_is_parallel=False, fuse_matmul_bias=False,
                 name=None):
        super().__init__()
        self._in_features = in_features
        self._out_features = out_features
        self.input_is_parallel = input_is_parallel
        self.weight = self.create_parameter(
            shape=[in_features, out_features], attr=weight_attr,
            default_initializer=I.XavierNormal())
        self.weight.sharding = P("model", None)
        if has_bias:
            self.bias = self.create_parameter(shape=[out_features], is_bias=True)
            self.bias.sharding = None  # replicated; added after reduction
        else:
            self.bias = None

    def forward(self, x):
        if not self.input_is_parallel:
            x = shard_constraint(x, "data", *([None] * (x.ndim - 2)), "model")
        y = F.linear(x, self.weight, None)
        # partial-sum contraction over the sharded axis: constrain output
        # replicated; GSPMD inserts the reduce (the _mp_allreduce analog)
        y = shard_constraint(y, "data")
        if self.bias is not None:
            y = y + self.bias
        return y


class ParallelCrossEntropy(Layer):
    """Vocab-sharded softmax CE (reference mp_layers.py:249).

    GSPMD computes the log-softmax reduction over the sharded class dim with
    a cross-mp all-reduce automatically when logits are model-sharded.
    """

    def __init__(self, mp_group=None, name=None):
        super().__init__()

    def forward(self, input, label):  # noqa: A002
        return F.cross_entropy(input, label, reduction="none")
