"""Pipeline-parallel training driver.

Parity: reference fleet/meta_parallel/pipeline_parallel.py:30
(PipelineParallel.train_batch → forward_backward_pipeline, Megatron 1F1B).

TPU-native semantics: ``train_batch`` routes to the compiled SPMD engine
(fleet/engine.py → parallel.DistributedTrainStep): one jitted program in
which stage params ride the "pipe" mesh axis and the microbatch rotation is
a CollectivePermute (parallel/pipeline.py). The eager path below —
sequential microbatch grad accumulation, exact 1F1B math but zero
cross-device overlap — is kept as a DEBUG MODE, selected with
``use_eager=True`` (or automatically when a GradScaler with dynamic loss
scaling is passed, whose host-side control flow cannot live in the jit).
"""
from __future__ import annotations

from typing import Optional

from ....framework.core import Tensor, backward
from ....nn.layer.layers import Layer
from ....tensor import concat, split
from .pp_layers import PipelineLayer

__all__ = ["PipelineParallel"]


class PipelineParallel(Layer):
    def __init__(self, layers, hcg, strategy):
        super().__init__()
        if not isinstance(layers, PipelineLayer):
            raise TypeError("PipelineParallel expects a PipelineLayer")
        self._layers = layers
        self._hcg = hcg
        self._strategy = strategy
        cfg = strategy.pipeline_configs if strategy is not None else {}
        self.accumulate_steps = int(cfg.get("accumulate_steps", 1))
        self.micro_batch_size = int(cfg.get("micro_batch_size", 1))
        self.total_loss = None
        self._engine = None
        self._engine_opt_id = None
        self._engine_scaler = None

    def forward(self, *args, **kwargs):
        return self._layers(*args, **kwargs)

    def _split_micro(self, data):
        if isinstance(data, (tuple, list)):
            parts = [self._split_micro(d) for d in data]
            return list(zip(*parts))
        n = data.shape[0]
        m = self.accumulate_steps
        if n % m != 0:
            raise ValueError(f"batch {n} not divisible by accumulate_steps {m}")
        return split(data, m, axis=0)

    def forward_backward_pipeline(self, data, scaler=None):
        """Microbatched fwd/bwd with grad accumulation (math of 1F1B)."""
        inputs, labels = data
        micro_inputs = self._split_micro(inputs)
        micro_labels = self._split_micro(labels)
        total_loss = None
        for mi, ml in zip(micro_inputs, micro_labels):
            out = self._layers(mi)
            assert self._layers._loss_fn is not None, "PipelineLayer needs loss_fn"
            loss = self._layers._loss_fn(out, ml)
            loss = loss / self.accumulate_steps
            if scaler is not None:
                backward(scaler.scale(loss))
            else:
                backward(loss)
            total_loss = loss if total_loss is None else total_loss + loss.detach()
        self.total_loss = total_loss
        return total_loss

    def _get_engine(self, optimizer, global_batch=None):
        from ..engine import FleetEngine
        from ....parallel.mesh import get_mesh

        if get_mesh() is None and \
                not getattr(self._strategy, "auto", False):
            return None
        if self._engine is None or self._engine_opt_id != id(optimizer):
            self._engine = FleetEngine(self._layers, optimizer,
                                       self._strategy, hcg=self._hcg,
                                       scaler=self._engine_scaler,
                                       global_batch=global_batch)
            self._engine_opt_id = id(optimizer)
        return self._engine

    def train_batch(self, data, optimizer, lr_scheduler=None, scaler=None,
                    use_eager=False):
        self._layers.train()
        # dynamic loss scaling is COMPILED into the engine step (pure
        # unscale + finite-gate + where-updated scale — the reference's
        # check_finite_and_unscale/update_loss_scaling op pair); only an
        # explicit use_eager drops to the sequential debug path
        if getattr(self, "_engine_scaler", None) is not scaler:
            self._engine_scaler = scaler
            self._engine = None
        eager = use_eager
        gb = None
        if not eager:
            x0 = data[0]
            gb = int(getattr(x0, "shape", [0])[0])
        engine = None if eager else self._get_engine(optimizer, gb)
        if engine is not None:
            loss = Tensor(engine.step(data))
        else:
            # debug mode: sequential microbatch grad accumulation
            loss = self.forward_backward_pipeline(data, scaler)
            if scaler is not None:
                scaler.step(optimizer)
            else:
                optimizer.step()
            optimizer.clear_grad()
        if lr_scheduler is not None:
            lr_scheduler.step()
        return loss

    def eval_batch(self, data, compute_loss=True):
        self._layers.eval()
        inputs, labels = data
        out = self._layers(inputs)
        if compute_loss:
            return self._layers._loss_fn(out, labels)
        return out
