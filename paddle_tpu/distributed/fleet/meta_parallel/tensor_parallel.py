"""TensorParallel model wrapper (reference meta_parallel/tensor_parallel.py:24).

The reference broadcasts mp/dp params and input data across rings at wrap
time. TPU-native: params are already consistent (single process or
deterministic per-process init via shared seed); wrapping is bookkeeping +
ensuring mp-sharded params carry their PartitionSpecs.
"""
from __future__ import annotations

from ....nn.layer.layers import Layer

__all__ = ["TensorParallel", "ShardingParallel", "MetaParallelBase"]


class MetaParallelBase(Layer):
    def __init__(self, layers, hcg, strategy):
        super().__init__()
        self._layers = layers
        self._hcg = hcg
        self._strategy = strategy
        self._engine = None
        self._engine_key = None
        self._prepare_for_model()

    def _prepare_for_model(self):
        pass

    def forward(self, *inputs, **kwargs):
        return self._layers(*inputs, **kwargs)

    def train_batch(self, data, optimizer, lr_scheduler=None, loss_fn=None):
        """Run one compiled SPMD training step (fleet/engine.py): forward +
        backward + clip + sharded optimizer update in a single jit. The
        eager forward()/backward()/opt.step() flow stays available for
        debugging; this is the engine path the facade promises."""
        from ..engine import FleetEngine
        from ....framework.core import Tensor

        key = (id(optimizer), id(loss_fn))
        if self._engine is None or self._engine_key != key:
            x = data[0]
            gb = int((x._data if isinstance(x, Tensor) else x).shape[0])
            self._engine = FleetEngine(self._layers, optimizer,
                                       self._strategy, hcg=self._hcg,
                                       loss_fn=loss_fn, global_batch=gb)
            self._engine_key = key
        loss = self._engine.step(data)
        if lr_scheduler is not None:
            lr_scheduler.step()
        return Tensor(loss)

    def state_dict(self, *args, **kwargs):
        return self._layers.state_dict(*args, **kwargs)

    def set_state_dict(self, sd, *args, **kwargs):
        return self._layers.set_state_dict(sd, *args, **kwargs)


class TensorParallel(MetaParallelBase):
    pass


class ShardingParallel(MetaParallelBase):
    """ZeRO wrapper (reference meta_parallel/sharding_parallel.py): under
    GSPMD the param broadcast is unnecessary; train_batch compiles the step
    with optimizer state sharded along the "sharding" axis (ZeRO-1)."""
    pass


class SemiAutoParallel(MetaParallelBase):
    """strategy.semi_auto wrapper: the model's shard_tensor annotations
    (distributed/auto_parallel) carry the placement; train_batch compiles
    one GSPMD step where every unannotated tensor's layout is completed by
    the partitioner — the TPU analog of the reference's
    completion.py + partitioner.py + reshard.py pipeline."""
    pass
