"""TensorParallel model wrapper (reference meta_parallel/tensor_parallel.py:24).

The reference broadcasts mp/dp params and input data across rings at wrap
time. TPU-native: params are already consistent (single process or
deterministic per-process init via shared seed); wrapping is bookkeeping +
ensuring mp-sharded params carry their PartitionSpecs.
"""
from __future__ import annotations

from ....nn.layer.layers import Layer

__all__ = ["TensorParallel", "ShardingParallel", "MetaParallelBase"]


class MetaParallelBase(Layer):
    def __init__(self, layers, hcg, strategy):
        super().__init__()
        self._layers = layers
        self._hcg = hcg
        self._strategy = strategy
        self._prepare_for_model()

    def _prepare_for_model(self):
        pass

    def forward(self, *inputs, **kwargs):
        return self._layers(*inputs, **kwargs)

    def state_dict(self, *args, **kwargs):
        return self._layers.state_dict(*args, **kwargs)

    def set_state_dict(self, sd, *args, **kwargs):
        return self._layers.set_state_dict(sd, *args, **kwargs)


class TensorParallel(MetaParallelBase):
    pass


class ShardingParallel(MetaParallelBase):
    pass
