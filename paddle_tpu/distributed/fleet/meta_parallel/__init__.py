from .parallel_layers.mp_layers import (  # noqa: F401
    VocabParallelEmbedding, ColumnParallelLinear, RowParallelLinear,
    ParallelCrossEntropy,
)
from .parallel_layers.random import (  # noqa: F401
    RNGStatesTracker, get_rng_state_tracker, model_parallel_random_seed,
)
from .pp_layers import LayerDesc, SharedLayerDesc, PipelineLayer, SegmentLayers  # noqa: F401
from .pipeline_parallel import PipelineParallel  # noqa: F401
from .tensor_parallel import TensorParallel, ShardingParallel, SemiAutoParallel, MetaParallelBase  # noqa: F401
