"""Fleet engine: routes facade-built eager models onto the compiled SPMD
training step.

Reference parity: fleet.distributed_model
(python/paddle/distributed/fleet/base/fleet_base.py:883) hands back a model
whose train_batch actually executes the selected parallelism. Here that
means building a :class:`paddle_tpu.parallel.DistributedTrainStep` — one
jitted sharded XLA program for forward + backward + clip + optimizer — from
the eager Layer, the eager optimizer's hyperparameters, and the strategy's
pipeline/sharding configuration.

Pipeline models: when every stage of a PipelineLayer holds a structurally
identical stack of sublayers, the engine stacks their params with a leading
stage dim sharded over the "pipe" mesh axis and runs the real SPMD pipeline
schedule (parallel.pipeline.pipeline_forward — CollectivePermute microbatch
rotation). Non-uniform stage stacks fall back to a scan over microbatches
with params replicated along "pipe" (same math, no cross-stage overlap) —
the compiled analog of the reference's grad-accumulation debug path.
"""
from __future__ import annotations

import warnings
from typing import Any, Callable, Dict, List, Optional

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from ...framework.core import Tensor
from ...framework.functional import functional_call, layer_buffers
from ...monitor import trace as _mtrace
from ...nn.clip import ClipGradByGlobalNorm
from ...nn.layer.layers import Layer
from ...parallel.mesh import get_mesh, mesh_shape
from ...parallel.train_step import DistributedTrainStep
from ...resilience import faults as _faults

__all__ = ["FleetEngine", "build_engine"]


def _optimizer_config(optimizer) -> Dict[str, Any]:
    """Extract (kind, lr, clip_norm, opt_kwargs) from an eager Optimizer.

    Unwraps meta-optimizer wrappers recursively (HybridParallelOptimizer,
    GradientMergeOptimizer, ...) to the leaf optimizer. GradientMerge
    k_steps/avg are surfaced so the engine can fold them into its
    microbatch accumulation (same math: the engine's batch IS the k merged
    micro-steps). Unsupported leaf kinds raise — silently training with
    different math than the user's optimizer is worse than an error."""
    inner = optimizer
    merge_k, merge_avg = 1, True
    seen = set()
    while hasattr(inner, "_inner_opt") and id(inner) not in seen:
        seen.add(id(inner))
        if type(inner).__name__ == "GradientMergeOptimizer":
            merge_k = int(getattr(inner, "k_steps", 1))
            merge_avg = bool(getattr(inner, "avg", True))
        inner = inner._inner_opt
    from ...regularizer import L1Decay, L2Decay

    def _l2_coeff(o):
        """Grad-side L2 coefficient of a non-decoupled optimizer."""
        wd = getattr(o, "_weight_decay", None)
        if wd is None:
            return 0.0
        if isinstance(wd, L2Decay):
            return float(wd.coeff)
        if isinstance(wd, L1Decay):
            raise NotImplementedError(
                "FleetEngine does not compile L1Decay regularization; "
                "use the eager train loop.")
        return float(wd)

    kind = type(inner).__name__.lower()
    decay_mask_of = None  # callable(Parameter) -> decay this param?
    if kind == "lamb":
        opt = "lamb"
        kwargs = {
            "beta1": float(getattr(inner, "_beta1", 0.9)),
            "beta2": float(getattr(inner, "_beta2", 0.999)),
            "eps": float(getattr(inner, "_epsilon", 1e-6)),
            "weight_decay": float(getattr(inner, "_lamb_wd", 0.01)),
        }
        ex_fn = getattr(inner, "_exclude_fn", None)
        if ex_fn is not None:
            decay_mask_of = lambda p: not ex_fn(p)  # noqa: E731
    elif "adamw" in kind or "adam" in kind:
        opt = "adamw"
        kwargs = {
            "beta1": float(getattr(inner, "_beta1", 0.9)),
            "beta2": float(getattr(inner, "_beta2", 0.999)),
            "eps": float(getattr(inner, "_epsilon", 1e-8)),
            # AdamW: decoupled decay lives in _coeff (optimizer.py:291);
            # Adam: L2Decay folds into the grad before the moments
            "weight_decay": float(getattr(inner, "_coeff", 0.0) or 0.0)
            if "adamw" in kind else 0.0,
            "l2_coeff": 0.0 if "adamw" in kind else _l2_coeff(inner),
        }
        decay_fn = getattr(inner, "_apply_decay_param_fun", None)
        if decay_fn is not None:
            # reference adamw.py _append_decoupled_weight_decay: the fn
            # sees the parameter NAME; the engine turns it into a
            # per-leaf decay mask inside the compiled step
            decay_mask_of = lambda p: bool(decay_fn(p.name or ""))  # noqa: E731
    elif type(inner).__name__ == "LarsMomentum":
        opt = "lars"
        kwargs = {
            "momentum": float(getattr(inner, "_momentum", 0.9)),
            "lars_coeff": float(getattr(inner, "_lars_coeff", 0.001)),
            "lars_weight_decay": float(getattr(inner, "_lars_wd", 0.0005)),
            "epsilon": float(getattr(inner, "_epsilon", 0.0)),
        }
    elif "momentum" in kind:
        opt = "momentum"
        kwargs = {
            "momentum": float(getattr(inner, "_momentum", 0.9)),
            "use_nesterov": bool(getattr(inner, "_use_nesterov", False)),
            "weight_decay": _l2_coeff(inner),
        }
    elif kind == "sgd":
        opt = "sgd"
        kwargs = {"weight_decay": _l2_coeff(inner)}
    else:
        raise NotImplementedError(
            f"FleetEngine cannot faithfully compile optimizer "
            f"{type(inner).__name__}; supported: SGD, Momentum, "
            f"LarsMomentum, Adam, AdamW, Lamb (optionally wrapped in "
            f"HybridParallelOptimizer/GradientMergeOptimizer). Use the "
            f"eager train loop for others.")
    clip = getattr(inner, "_grad_clip", None)
    # unwrap HybridParallelClipGrad
    clip = getattr(clip, "_clip", clip)
    clip_norm = float(clip.clip_norm) if isinstance(clip, ClipGradByGlobalNorm) else None
    return {"opt": opt, "opt_kwargs": kwargs, "clip_norm": clip_norm,
            "lr": lambda _step: float(inner.get_lr()), "inner": inner,
            "merge_k": merge_k, "merge_avg": merge_avg,
            "decay_mask_of": decay_mask_of}


def _named_trainable(layer: Layer):
    return [(n, p) for n, p in layer.named_parameters() if not p.stop_gradient]


def _spec_of(p) -> P:
    s = getattr(p, "sharding", None)
    return s if isinstance(s, P) else P()


def _stage_layer_lists(pp_layer) -> List[list]:
    """Per-stage unit lists. Units are Layers or bare callables (e.g.
    SharedLayerDesc forward_func partials); the uniformity analysis
    decides what can ride the SPMD pipeline."""
    stages: List[list] = [[] for _ in range(pp_layer.get_num_stages())]
    for fn, s in zip(pp_layer.run_function, pp_layer._stage_of_layer):
        stages[s].append(fn)
    return stages


def _underlying_layer(unit) -> Optional[Layer]:
    """The Layer carrying a unit's params (the unit itself, or the layer
    captured by a SharedLayerDesc forward_func partial)."""
    from functools import partial as _partial

    if isinstance(unit, Layer):
        return unit
    if isinstance(unit, _partial):
        for a in list(unit.args) + list(unit.keywords.values()):
            if isinstance(a, Layer):
                return a
    return None


def _unit_params(unit):
    layer = _underlying_layer(unit)
    if layer is None:
        return {}
    return {n: p for n, p in layer.named_parameters() if not p.stop_gradient}


def _unit_signature(unit):
    """Structural signature for middle-stage matching; None = cannot sit in
    the vmapped middle (bare callable)."""
    if not isinstance(unit, Layer):
        return None
    return tuple(sorted(
        (n, tuple(p._data.shape), str(p._data.dtype), str(_spec_of(p)))
        for n, p in unit.named_parameters() if not p.stop_gradient))


def _split_stages(stages: List[list]):
    """Decompose stages into (prologue, middle_per_stage, epilogue).

    The SPMD pipeline vmaps one stage body over the stage dim, which needs
    structurally identical per-stage unit stacks. Real models break that
    only at the edges — embedding on stage 0, tied-head/loss prep on the
    last stage (reference SharedLayerDesc, pp_layers.py:208-280). Those
    edge units are peeled off: the prologue runs on the full batch before
    microbatching, the epilogue per microbatch after the drain, and a
    weight shared between them appears ONCE in the param tree so autodiff
    sums its gradient contributions — the same math as the reference's
    allreduce over the tied stages' grads (pp_layers.py:268-281).

    Returns None when no uniform middle exists (engine falls back to the
    microbatch-scan compile).
    """
    n = len(stages)

    def match(a_units, b_units):
        if len(a_units) != len(b_units):
            return False
        for a, b in zip(a_units, b_units):
            sa, sb = _unit_signature(a), _unit_signature(b)
            if sa is None or sb is None or sa != sb:
                return False
        return True

    def try_m(m):
        if m < 1 or len(stages[0]) < m or len(stages[-1]) < m:
            return None
        mids = [stages[0][len(stages[0]) - m:]] + \
            [stages[s] for s in range(1, n - 1)] + [stages[-1][:m]]
        ref = mids[0]
        if any(_unit_signature(u) is None for u in ref):
            return None
        for other in mids[1:]:
            if not match(ref, other):
                return None
        # tied weights must not touch the middle (a weight shared between
        # a middle stage and anything else cannot be stage-stacked)
        mid_ids = set()
        for st in mids:
            for u in st:
                for p in _unit_params(u).values():
                    if id(p) in mid_ids:
                        return None
                    mid_ids.add(id(p))
        prologue = stages[0][:len(stages[0]) - m]
        epilogue = stages[-1][m:]
        for u in list(prologue) + list(epilogue):
            for p in _unit_params(u).values():
                if id(p) in mid_ids:
                    return None
        return prologue, mids, epilogue

    if n > 2:
        # middle stages fix m
        inner_lens = {len(stages[s]) for s in range(1, n - 1)}
        if len(inner_lens) != 1:
            return None
        return try_m(inner_lens.pop())
    for m in range(min(len(stages[0]), len(stages[-1])), 0, -1):
        got = try_m(m)
        if got is not None:
            return got
    return None


def _split_stages_padded(stages: List[list]):
    """Non-uniform fallback with REAL pipelining (VERDICT r4 item 8; the
    reference handles arbitrary segmentation, pp_layers.py:63-130): when
    every unit in every stage is the same Layer type with one structural
    signature but stage COUNTS differ, shorter stages are padded with
    dead units to the max count. Dead slots hold zero params and are
    masked out per stage inside the vmapped body (lax.axis_index over the
    vmap stage axis), so the stacked representation — and the
    CollectivePermute schedule — still applies. Cost: padded stages
    compute `max-L_s` dead units; gain: cross-stage overlap instead of
    the zero-overlap microbatch-scan fallback.

    Returns (stages, max_len) or None.
    """
    sig = None
    klass = None
    for st in stages:
        if not st:
            return None
        for u in st:
            if not isinstance(u, Layer):
                return None
            s = _unit_signature(u)
            if s is None or not s:
                return None
            if sig is None:
                sig, klass = s, type(u)
            elif s != sig or type(u) is not klass:
                return None
    seen = set()
    for st in stages:
        for u in st:
            for p in _unit_params(u).values():
                if id(p) in seen:
                    return None  # tied weights cannot be stage-stacked
                seen.add(id(p))
    lens = [len(st) for st in stages]
    if len(set(lens)) == 1:
        return None  # uniform — the exact path handles it
    return stages, max(lens)


class FleetEngine:
    """Compiled training step for a facade-built model.

    step((x, y)) -> loss (host float-able jax scalar). Parameters are
    written back into the eager Layer after every step (reference-count
    swap, no host transfer), so state_dict/save keep working.
    """

    def __init__(self, model: Layer, optimizer, strategy, hcg=None,
                 loss_fn: Optional[Callable] = None, mesh=None, scaler=None,
                 sentinel=None, global_batch: Optional[int] = None):
        from .meta_parallel.pp_layers import PipelineLayer

        self._model = model

        inner_model = model
        # unwrap facade wrappers holding the real layers at ._layers
        while not isinstance(inner_model, PipelineLayer) and \
                hasattr(inner_model, "_layers") and \
                isinstance(getattr(inner_model, "_layers"), Layer):
            inner_model = inner_model._layers
        self._inner_model = inner_model

        # strategy.auto (ISSUE 9): the fleet.auto planner picks the whole
        # hybrid plan — mesh dims, ZeRO level, microbatch count, schedule
        # — from the model + batch + device count, then installs the mesh
        # it chose (fleet.init deferred it for exactly this moment)
        self.plan = None
        if getattr(strategy, "auto", False):
            self.plan = self._make_plan(inner_model, strategy, global_batch)
            self.mesh = self.plan.create_mesh()
        else:
            self.mesh = mesh or get_mesh()
        if self.mesh is None:
            raise RuntimeError("FleetEngine needs a mesh (fleet.init first)")
        shape = mesh_shape(self.mesh)

        cfg = _optimizer_config(optimizer)
        pipe_deg = shape.get("pipe", 1)
        shard_deg = shape.get("sharding", 1)

        # strategy.lamb / strategy.lars replace the user optimizer's update
        # rule, like the reference meta-optimizers (fleet_base.py:1432-1470
        # via meta_optimizer_factory LambOptimizer/LarsOptimizer): moments
        # carry over hyper-for-hyper, exclude lists become decay masks.
        if getattr(strategy, "lamb", False):
            lc = getattr(strategy, "lamb_configs", {}) or {}
            cfg["opt"] = "lamb"
            cfg["opt_kwargs"] = {
                "beta1": cfg["opt_kwargs"].get("beta1", 0.9),
                "beta2": cfg["opt_kwargs"].get("beta2", 0.999),
                "eps": cfg["opt_kwargs"].get("eps", 1e-6),
                "weight_decay": float(lc.get("lamb_weight_decay", 0.01)),
            }
            excl = list(lc.get("exclude_from_weight_decay", []) or [])
            if excl:
                cfg["decay_mask_of"] = (
                    lambda p: not any(s in (p.name or "") for s in excl))
        elif getattr(strategy, "lars", False):
            lc = getattr(strategy, "lars_configs", {}) or {}
            cfg["opt"] = "lars"
            cfg["opt_kwargs"] = {
                "momentum": cfg["opt_kwargs"].get("momentum", 0.9),
                "lars_coeff": float(lc.get("lars_coeff", 0.001)),
                "lars_weight_decay": float(lc.get("lars_weight_decay",
                                                  0.0005)),
                "epsilon": float(lc.get("epsilon", 0.0)),
            }
            cfg["decay_mask_of"] = None

        pcfg = getattr(strategy, "pipeline_configs", {}) or {}
        # GradientMerge folds into microbatch accumulation: the engine's
        # batch is the k merged micro-steps, applied in one compiled step.
        # Composition with pipeline accumulation is multiplicative, like
        # the eager nesting (k merge boundaries × acc microbatches each).
        self.accumulate_steps = int(pcfg.get("accumulate_steps", 1)) * \
            cfg["merge_k"]
        # microbatch schedule: "FThenB" (the fill/drain scan, backward by
        # autodiff) or "1F1B" (parallel.pipeline.pipeline_1f1b — the
        # interleaved schedule computing grads inside one scan). The
        # planner picks 1F1B; manual configs opt in via
        # pipeline_configs={"schedule": "1F1B"}.
        sched = str(pcfg.get("schedule", "FThenB"))
        if self.plan is not None:
            self.accumulate_steps = self.plan.n_micro * cfg["merge_k"]
            if self.plan.pp > 1:
                sched = self.plan.schedule
        self._schedule = sched.lower().replace("-", "").replace("_", "")
        self._merge_avg = cfg["merge_avg"]
        self._pipe_sched_info = None  # (schedule, n_stages, n_micro)

        loss_layer = loss_fn
        if loss_layer is None and isinstance(inner_model, PipelineLayer):
            loss_layer = inner_model._loss_fn
        if loss_layer is None:
            raise ValueError("FleetEngine needs a loss_fn (PipelineLayer "
                             "loss_fn or explicit argument)")

        def loss_arrays(out, y):
            r = loss_layer(Tensor(out) if not isinstance(out, Tensor) else out,
                           Tensor(y) if not isinstance(y, Tensor) else y)
            return r._data if isinstance(r, Tensor) else r

        built = None
        if pipe_deg > 1:
            if isinstance(inner_model, PipelineLayer):
                stages = _stage_layer_lists(inner_model)
            elif self.plan is not None:
                # planner-chosen pipe over a plain model: segment its
                # top-level children into contiguous stages (the implicit
                # SegmentLayers an unmodified hapi script never wrote)
                stages = self._auto_stages(inner_model, pipe_deg)
            else:
                stages = None
            if stages is not None:
                built = self._build_pipelined(stages, inner_model,
                                              loss_arrays, pipe_deg)
            if built is None:
                warnings.warn(
                    "PipelineLayer stages are not structurally uniform; "
                    "compiling as microbatch-scan with pipe-replicated "
                    "params (no cross-stage overlap). Make stages uniform "
                    "for true SPMD pipelining.")
        if built is None:
            built = self._build_flat(inner_model, loss_arrays)
        params, specs, step_loss, buffers = built

        # strategy.recompute: rematerialize the whole forward in the
        # backward (reference RecomputeOptimizer / recompute meta-optimizer,
        # fleet_base.py:1432). Segment boundaries are the compiled step's
        # internal scans (microbatch/pipeline bodies are already
        # checkpointed); the flag adds the outer jax.checkpoint so saved
        # activations drop to the step inputs. The reference's
        # ``checkpoints`` name list does not transfer (XLA picks the
        # boundaries) — documented in README.
        if getattr(strategy, "recompute", False):
            step_loss = jax.checkpoint(step_loss)

        # strategy.amp: autocast the compiled forward (reference AMP
        # meta-optimizer → OptimizerWithMixedPrecision). On TPU the amp
        # dtype is bf16 (fp32 exponent range — loss scaling unnecessary);
        # fp16 requests additionally get the compiled dynamic loss scaler
        # seeded from amp_configs, matching reference
        # update_loss_scaling_op defaults.
        amp_cfgs = getattr(strategy, "amp_configs", {}) or {}
        self._amp_on = bool(getattr(strategy, "amp", False))
        if self._amp_on:
            from ...amp import auto_cast as _auto_cast

            amp_dtype = str(amp_cfgs.get("dtype", "bfloat16"))
            amp_level = "O2" if amp_cfgs.get("use_pure_fp16") else "O1"
            base_step_loss = step_loss

            def step_loss(params, buffers, batch,
                          _f=base_step_loss):  # noqa: F811
                with _auto_cast(enable=True, level=amp_level,
                                dtype=amp_dtype):
                    return _f(params, buffers, batch)

        self._scaler = scaler if (scaler is not None
                                  and getattr(scaler, "_enable", False)) \
            else None
        dynamic_scale = None
        if self._scaler is not None:
            s = self._scaler
            dynamic_scale = {
                "init_scale": float(s._scale),
                "incr_ratio": float(s._incr_ratio),
                "decr_ratio": float(s._decr_ratio),
                "incr_every_n_steps": int(s._incr_every_n_steps),
                "decr_every_n": int(s._decr_every_n),
            }
        elif (self._amp_on
              and str(amp_cfgs.get("dtype", "bfloat16")) in
              ("float16", "fp16")
              and amp_cfgs.get("use_dynamic_loss_scaling", True)):
            dynamic_scale = {
                "init_scale": float(amp_cfgs.get("init_loss_scaling",
                                                 32768.0)),
                "incr_ratio": float(amp_cfgs.get("incr_ratio", 2.0)),
                "decr_ratio": float(amp_cfgs.get("decr_ratio", 0.5)),
                "incr_every_n_steps": int(amp_cfgs.get("incr_every_n_steps",
                                                       1000)),
                "decr_every_n": int(amp_cfgs.get("decr_every_n_nan_or_inf",
                                                 2)),
            }

        self._write_back_names = list(params)
        self._step_loss = step_loss  # introspection (tests assert remat)
        opt_kwargs = dict(cfg["opt_kwargs"])
        if cfg.get("decay_mask_of") is not None:
            opt_kwargs["decay_mask"] = {
                k: bool(cfg["decay_mask_of"](p))
                for k, p in self._param_objs.items()}

        # strategy.asp: re-project pruned weights onto their 2:4 masks
        # after every optimizer update INSIDE the compiled step (reference
        # asp_optimizer.py → ASPHelper._insert_sparse_mask_ops appends
        # masking ops after the optimizer ops). Masks come from a prior
        # incubate.asp.prune_model call.
        optimizer_arg: Any = cfg["opt"]
        if getattr(strategy, "asp", False):
            from ...incubate.asp import ASPHelper
            from ...parallel.train_step import _OPTS

            if hasattr(self, "_pp_assign"):
                # stage-stacked build: each stage has its OWN 2:4 mask —
                # stack them per key (a donor-only mask would corrupt the
                # other stages' patterns); unpruned or padded slots stay
                # dense (all-ones)
                from collections import defaultdict

                by_key: dict = defaultdict(dict)
                for key, p, s in self._pp_assign:
                    by_key[key][s] = p
                asp_masks = {}
                for key, stage_of in by_key.items():
                    if not any(ASPHelper.mask_for(p) is not None
                               for p in stage_of.values()):
                        continue
                    rows = []
                    for s in range(params[key].shape[0]):
                        p = stage_of.get(s)
                        m = (ASPHelper.mask_for(p)
                             if p is not None else None)
                        rows.append(m if m is not None else
                                    jnp.ones(params[key].shape[1:],
                                             params[key].dtype))
                    asp_masks[key] = jnp.stack(rows)
                for key, p in getattr(self, "_pp_outer", {}).items():
                    m = ASPHelper.mask_for(p)
                    if m is not None:
                        asp_masks[key] = m
            else:
                asp_masks = {k: m for k, p in self._param_objs.items()
                             if (m := ASPHelper.mask_for(p)) is not None}
            if not asp_masks:
                warnings.warn(
                    "strategy.asp=True but no ASP masks found — call "
                    "paddle_tpu.incubate.asp.prune_model(model) before "
                    "building the engine; training proceeds dense.")
            else:
                base_init, base_upd = _OPTS[cfg["opt"]]

                def masked_update(p, g, s, lr, _u=base_upd, **kw):
                    new_p, new_s = _u(p, g, s, lr, **kw)
                    new_p = {k: (v * asp_masks[k].astype(v.dtype)
                                 if k in asp_masks else v)
                             for k, v in new_p.items()}
                    return new_p, new_s

                optimizer_arg = (base_init, masked_update)

        # ZeRO stage: planner-chosen, or strategy.sharding stage (the
        # reference sharding_configs {"stage": 1|2|3}), else the
        # historical default (stage 1 whenever a sharding axis exists)
        if self.plan is not None:
            zero_arg = self.plan.zero
        elif getattr(strategy, "sharding", False):
            zero_arg = int((getattr(strategy, "sharding_configs", {}) or
                            {}).get("stage", 1))
        else:
            zero_arg = shard_deg > 1
        acfg = getattr(strategy, "auto_configs", {}) or {}
        self._step = DistributedTrainStep(
            step_loss, params, specs, optimizer=optimizer_arg, lr=cfg["lr"],
            clip_norm=cfg["clip_norm"], zero=zero_arg, mesh=self.mesh,
            opt_kwargs=opt_kwargs, aux=buffers,
            dynamic_scale=dynamic_scale, sentinel=sentinel,
            zero_min_size=int(acfg.get("zero_min_size", 2 ** 12)))
        if self._scaler is not None:
            # start from the eager scaler's live counters (pull any state a
            # previous engine left pending on the mirror first)
            getattr(self._scaler, "_materialize", lambda: None)()
            self._step.scaler_state = {
                "scale": jnp.float32(self._scaler._scale),
                "good": jnp.int32(self._scaler._good_steps),
                "bad": jnp.int32(self._scaler._bad_steps),
            }
        self._scaler_dirty = False

    # -- builders ------------------------------------------------------------
    def _micro_loss(self, one_loss: Callable):
        """Wrap a per-batch loss into the accumulate_steps scan (identical
        math to eager PipelineParallel.forward_backward_pipeline: mean of
        per-microbatch mean losses; sum when GradientMerge avg=False).
        Buffers (BatchNorm stats) are carried through the scan so each
        microbatch sees the previous one's updates — eager-loop order."""
        acc = self.accumulate_steps
        avg = self._merge_avg

        if acc <= 1:
            return one_loss

        def scan_loss(params, buffers, batch):
            from ...parallel.sharding import constraint

            x, y = batch
            xm = x.reshape(acc, x.shape[0] // acc, *x.shape[1:])
            ym = y.reshape(acc, y.shape[0] // acc, *y.shape[1:])
            # pin the microbatch layout (same scan-xs miscompile hazard as
            # the pipelined build — see _build_pipelined.step_loss)
            xm = constraint(xm, P(None, ("data", "sharding"),
                                  *(None,) * (xm.ndim - 2)))
            ym = constraint(ym, P(None, ("data", "sharding"),
                                  *(None,) * (ym.ndim - 2)))

            def body(carry, xy):
                total, buf = carry
                loss, new_buf = one_loss(params, buf, xy)
                return (total + loss, new_buf), None

            (total, buf), _ = jax.lax.scan(
                jax.checkpoint(body), (jnp.float32(0.0), buffers), (xm, ym))
            return (total / acc if avg else total), buf

        return scan_loss

    def _build_flat(self, model: Layer, loss_arrays):
        named = _named_trainable(model)
        params = {n: p._data for n, p in named}
        specs = {n: _spec_of(p) for n, p in named}
        self._param_objs = {n: p for n, p in named}
        buffers = layer_buffers(model)
        self._write_back = lambda new: self._assign(model, new)
        self._write_back_buffers = lambda new: self._assign_buffers(model, new)

        def one_loss(params, buffers, batch):
            x, y = batch
            out, new_buf = functional_call(model, params, x, buffers=buffers)
            return loss_arrays(out, y), new_buf

        return params, specs, self._micro_loss(one_loss), buffers

    def _make_plan(self, inner_model, strategy, global_batch):
        """Run the fleet.auto planner over the model's trainable params."""
        import jax as _jax

        from . import auto as fleet_auto

        if global_batch is None:
            raise ValueError(
                "strategy.auto needs the global batch size to plan "
                "microbatching — pass global_batch to FleetEngine (the "
                "facade wrappers forward it from the first train_batch)")
        acfg = dict(getattr(strategy, "auto_configs", {}) or {})
        named = _named_trainable(inner_model)

        def nbytes(p):
            arr = p._data
            return int(arr.size) * int(arr.dtype.itemsize)

        total = sum(nbytes(p) for _, p in named)
        n_params = sum(int(p._data.size) for _, p in named)
        tp_bytes = sum(nbytes(p) for _, p in named
                       if "model" in str(_spec_of(p)))
        # pipeline-stackable depth + bytes: the structurally uniform
        # middle run of the unit list (edges peel into prologue/epilogue
        # at build time), measured on the MODEL's own structure rather
        # than inferred from leaf shapes
        if hasattr(inner_model, "run_function"):  # PipelineLayer
            units = [u for u in inner_model.run_function
                     if isinstance(u, Layer)]
        else:
            units = [u for u in self._auto_units(inner_model)
                     if isinstance(u, Layer)]
        sigs = [_unit_signature(u) for u in units]
        mid_sigs = [s for s in sigs if s]
        modal = max(set(mid_sigs), key=mid_sigs.count) if mid_sigs else None
        layers = mid_sigs.count(modal) if modal else 1
        layer_bytes = sum(
            sum(nbytes(p) for p in _unit_params(u).values())
            for u, s in zip(units, sigs) if s == modal) if modal else 0
        hidden = int(acfg.get("hidden", 0))
        if not hidden:
            cand = [p._data.shape[-1] for _, p in named
                    if p._data.ndim >= 2]
            hidden = max(cand) if cand else 0
        stats = fleet_auto.ModelStats(
            param_bytes=total, n_params=n_params, layer_bytes=layer_bytes,
            tp_bytes=tp_bytes, layers=int(layers), hidden=hidden,
            seq_len=int(acfg.get("seq_len", 1)))
        constraints = {k: int(acfg[k]) for k in
                       ("dp", "sharding", "pp", "mp", "n_micro", "zero")
                       if k in acfg}
        hw = fleet_auto.HardwareSpec()
        if "hbm_bytes_per_device" in acfg:
            hw = fleet_auto.HardwareSpec(
                hbm_bytes=int(acfg["hbm_bytes_per_device"]))
        return fleet_auto.plan(
            stats=stats, global_batch=int(global_batch),
            n_devices=len(_jax.devices()), hardware=hw,
            allow_mp=tp_bytes > 0,
            max_micro=int(acfg.get("max_micro", 16)),
            constraints=constraints,
            schedule=str(acfg.get("schedule", "1f1b")))

    @staticmethod
    def _auto_units(model: Layer) -> List[Layer]:
        """Top-level unit list of a plain model (descending through
        single-child wrappers) — the implicit LayerDesc sequence."""
        units = [c for c in model.children()]
        while len(units) == 1 and isinstance(units[0], Layer):
            inner = [c for c in units[0].children()]
            if not inner:
                break
            units = inner
        return units

    def _auto_stages(self, model: Layer, pipe_deg: int):
        """Segment a plain model's units into pipe_deg contiguous stages
        (uniform-count middle, like SegmentLayers); None when the model
        has fewer units than stages."""
        units = self._auto_units(model)
        if len(units) < pipe_deg:
            return None
        base, rem = divmod(len(units), pipe_deg)
        stages: List[list] = []
        i = 0
        for s in range(pipe_deg):
            k = base + (1 if s < rem else 0)
            stages.append(units[i:i + k])
            i += k
        return stages

    def _build_pipelined(self, stages, root_layer, loss_arrays, pipe_deg):
        from ...parallel.pipeline import pipeline_1f1b, pipeline_forward

        split = _split_stages(stages)
        padded_lens = None
        if split is None:
            got = _split_stages_padded(stages)
            if got is None:
                return None
            mids, max_m = got
            prologue, epilogue = [], []
            padded_lens = [len(st) for st in mids]
        else:
            prologue, mids, epilogue = split

        n_stages = len(stages)
        per_stage = [[_unit_params(u) for u in st] for st in mids]
        layer_count = max_m if padded_lens else len(per_stage[0])
        mid0 = mids[0]

        # stack middle stage s's params along a new leading "pipe" dim;
        # padded mode fills a short stage's missing slot with zeros (the
        # slot is masked dead in stage_fn, so zeros only have to be
        # finite)
        stacked: Dict[str, Any] = {}
        specs: Dict[str, Any] = {}
        self._pp_assign: List[tuple] = []  # (key, Parameter, stage|None)
        for li in range(layer_count):
            donor = next(s for s in range(n_stages)
                         if li < len(per_stage[s]))
            for pname in per_stage[donor][li]:
                key = f"stage.{li}.{pname}"
                rows = []
                for s in range(n_stages):
                    if li < len(per_stage[s]):
                        rows.append(per_stage[s][li][pname]._data)
                        self._pp_assign.append(
                            (key, per_stage[s][li][pname], s))
                    else:
                        rows.append(jnp.zeros_like(
                            per_stage[donor][li][pname]._data))
                stacked[key] = jnp.stack(rows)
                specs[key] = P("pipe",
                               *_spec_of(per_stage[donor][li][pname]))

        # edge (prologue/epilogue) params: one entry per PARAM OBJECT, so a
        # weight tied across the edges (SharedLayerDesc) appears once and
        # its gradient contributions sum through autodiff
        outer_key_of: Dict[int, str] = {}
        outer_params_t: Dict[str, Any] = {}
        for ui, unit in enumerate(list(prologue) + list(epilogue)):
            for pname, p in _unit_params(unit).items():
                if id(p) not in outer_key_of:
                    key = f"edge.{ui}.{pname}"
                    outer_key_of[id(p)] = key
                    outer_params_t[key] = p
        for key, p in outer_params_t.items():
            stacked[key] = p._data
            specs[key] = _spec_of(p)

        self._pp_outer = outer_params_t
        # decay-mask lookup: stage-stacked keys answer with the donor
        # stage's param (name patterns like bias/LayerNorm agree across
        # stages)
        self._param_objs = {}
        for li in range(layer_count):
            donor = next(s for s in range(n_stages)
                         if li < len(per_stage[s]))
            for pname, p in per_stage[donor][li].items():
                self._param_objs[f"stage.{li}.{pname}"] = p
        self._param_objs.update(outer_params_t)
        self._write_back = self._assign_pipelined
        self._write_back_buffers = lambda new: None

        buffers = layer_buffers(root_layer)
        if buffers:
            warnings.warn(
                "PipelineLayer stages carry buffers (e.g. BatchNorm running "
                "stats); the SPMD pipeline runs them frozen — updates inside "
                "the schedule are discarded (fill/drain ticks would pollute "
                "them). Use LayerNorm/GroupNorm in pipelined models.")
        buffers = {}

        def apply_edge(units, params, h):
            for unit in units:
                layer = _underlying_layer(unit)
                if layer is None:
                    out = unit(Tensor(h))
                    h = out._data if isinstance(out, Tensor) else out
                    continue
                pdict = {pn: params[outer_key_of[id(p)]]
                         for pn, p in _unit_params(unit).items()}
                if isinstance(unit, Layer):
                    h = functional_call(unit, pdict, h)
                else:
                    # SharedLayerDesc forward_func partial: bind the shared
                    # layer's params, then call the partial
                    named = dict(layer.named_parameters())
                    saved = {}
                    try:
                        for pn, arr in pdict.items():
                            saved[pn] = named[pn]._data
                            named[pn]._data = arr
                        from ...framework.core import no_grad

                        with no_grad():
                            out = unit(Tensor(h))
                        h = out._data if isinstance(out, Tensor) else out
                    finally:
                        for pn, old in saved.items():
                            named[pn]._data = old
            return h

        if padded_lens is None:
            def stage_fn(sp, h):
                for li, unit in enumerate(mid0):
                    lp = {pn: sp[f"stage.{li}.{pn}"]
                          for pn in per_stage[0][li]}
                    h = functional_call(unit, lp, h)
                return h
        else:
            # padded mode: one template unit (all units share class +
            # signature); each stage masks its dead trailing slots via
            # its vmap index. Both where-branches are computed (select
            # under vmap) — the cost of regaining CollectivePermute
            # overlap; dead-slot params are zeros, get zero grads.
            template = mid0[0]
            lens_arr = jnp.asarray(padded_lens, jnp.int32)
            tmpl_pnames = list(per_stage[0][0])

            def stage_fn(sp, h):
                n_live = lens_arr[jax.lax.axis_index("pipe_stage")]
                for li in range(layer_count):
                    lp = {pn: sp[f"stage.{li}.{pn}"] for pn in tmpl_pnames}
                    h2 = functional_call(template, lp, h)
                    h = jnp.where(li < n_live, h2, h)
                return h

        acc = max(self.accumulate_steps, n_stages)
        self._pipe_sched_info = (self._schedule, n_stages, acc)

        if self._schedule == "1f1b" and n_stages > 1:
            # 1F1B: epilogue + loss fold into the schedule's last-stage
            # loss head; gradients come out of the SAME scan
            # (parallel.pipeline.pipeline_1f1b — custom_vjp, so the
            # DistributedTrainStep's value_and_grad composes unchanged).
            # The prologue stays outside: its backward is driven by the
            # schedule's x_micro cotangent through ordinary autodiff,
            # which also sums a tied (SharedLayerDesc) weight's prologue
            # and head contributions at the params-dict level.
            epi_keys = sorted({outer_key_of[id(p)] for u in epilogue
                               for p in _unit_params(u).values()})

            def loss_head(hp, act, yt):
                o = apply_edge(epilogue, hp, act)
                return loss_arrays(o, yt)

            fb = pipeline_1f1b(stage_fn, loss_head, n_stages,
                               mean=self._merge_avg)

            def step_loss(params, buffers, batch):
                from ...parallel.sharding import constraint

                x, y = batch
                h = apply_edge(prologue, params, x)
                xm = h.reshape(acc, h.shape[0] // acc, *h.shape[1:])
                ym = y.reshape(acc, y.shape[0] // acc, *y.shape[1:])
                # same microbatch-layout pins as the fill/drain build
                xm = constraint(xm, P(None, ("data", "sharding"),
                                      *(None,) * (xm.ndim - 2)))
                ym = constraint(ym, P(None, ("data", "sharding"),
                                      *(None,) * (ym.ndim - 2)))
                mid_params = {k: v for k, v in params.items()
                              if k.startswith("stage.")}
                head_params = {k: params[k] for k in epi_keys}
                return fb(mid_params, head_params, xm, ym), buffers

            return stacked, specs, step_loss, buffers

        def step_loss(params, buffers, batch):
            from ...parallel.sharding import constraint

            x, y = batch
            h = apply_edge(prologue, params, x)
            xm = h.reshape(acc, h.shape[0] // acc, *h.shape[1:])
            ym = y.reshape(acc, y.shape[0] // acc, *y.shape[1:])
            # pin BOTH microbatched streams to the batch layout: the
            # batch->microbatch reshape leaves the data/sharding tiling on
            # the time axis. For ym the unpinned layout merely costs the
            # partitioner's replicate-and-repartition fallback per slice;
            # for xm (the pipeline scan's xs) the propagated split-on-
            # microbatch-dim sharding MISCOMPILES the scan on CPU GSPMD
            # (values read with a stride — seed failures
            # test_compiled_matches_eager_debug_mode & co), so the pin is
            # a correctness fix, not an optimisation.
            xm = constraint(xm, P(None, ("data", "sharding"),
                                  *(None,) * (xm.ndim - 2)))
            ym = constraint(ym, P(None, ("data", "sharding"),
                                  *(None,) * (ym.ndim - 2)))
            mid_params = {k: v for k, v in params.items()
                          if k.startswith("stage.")}
            ys = pipeline_forward(stage_fn, mid_params, xm, n_stages)
            # epilogue + loss per microbatch, sequenced (lax.map) with
            # remat so one microbatch of head activations is live at a
            # time — then mean over microbatches, identical math to eager
            # train_batch accumulation (sum when GradientMerge avg=False)
            if epilogue:
                @jax.checkpoint
                def per_micro(args):
                    o, t = args
                    o = apply_edge(epilogue, params, o)
                    return loss_arrays(o, t)

                losses = jax.lax.map(per_micro, (ys, ym))
            else:
                losses = jax.vmap(lambda o, t: loss_arrays(o, t))(ys, ym)
            return (jnp.mean(losses) if self._merge_avg
                    else jnp.sum(losses)), buffers

        return stacked, specs, step_loss, buffers

    # -- write-back ----------------------------------------------------------
    @staticmethod
    def _assign(model: Layer, new_params: Dict[str, Any]):
        named = dict(model.named_parameters())
        for n, arr in new_params.items():
            named[n]._data = arr

    @staticmethod
    def _assign_buffers(model: Layer, new_buffers: Dict[str, Any]):
        named = {n: b for n, b in model.named_buffers() if b is not None}
        for n, arr in (new_buffers or {}).items():
            named[n]._data = arr

    def _assign_pipelined(self, new_params: Dict[str, Any]):
        # triples were recorded at stacking time, so padded (dead) slots
        # are naturally skipped
        for key, p, s in self._pp_assign:
            p._data = new_params[key][s]
        for key, p in self._pp_outer.items():
            p._data = new_params[key]

    # -- public --------------------------------------------------------------
    @property
    def train_step(self) -> DistributedTrainStep:
        return self._step

    def adopt_train_step(self, step: DistributedTrainStep) -> None:
        """Swap in a rebuilt inner step (TrainGuardian elastic resize:
        the pod lost a host, fleet.auto re-planned over the survivors and
        a fresh DistributedTrainStep was built on the new mesh). The
        eager Layer mirrors the adopted device params immediately, so
        state_dict/save readers never see the dead mesh's arrays."""
        self._step = step
        self._write_back(step.params)
        self._write_back_buffers(step.aux)

    def _emit_pipeline_ticks(self):
        """One ``pipeline.tick`` span per schedule tick with the stage
        occupancy of the STATIC schedule actually compiled (the in-jit
        scan never returns to the host mid-step, so occupancy is emitted
        from the schedule's closed form). tools/trace_report.py's
        pipeline_report turns Σbusy/Σslots into the measured bubble
        fraction and diffs it against the cost model's prediction."""
        import time as _time

        sched, S, n = self._pipe_sched_info
        writer = _mtrace.get_writer()
        now = _time.perf_counter()
        one_f1b = sched == "1f1b" and S > 1
        T = n + (2 * (S - 1) if one_f1b else S - 1)
        slots = 2 * S if one_f1b else S
        for t in range(T):
            busy = sum(1 for s in range(S) if 0 <= t - s < n)
            if one_f1b:
                busy += sum(1 for s in range(S)
                            if 0 <= t - 2 * (S - 1) + s < n)
            writer.add_complete(
                "pipeline.tick", now, 1e-6, cat="pipeline",
                args={"t": t, "busy": busy, "slots": slots, "stages": S,
                      "n_micro": n, "schedule": sched})

    def step(self, batch):
        if _faults.ENABLED[0]:
            # fault-injection hook (FLAGS_fault_inject): the registry
            # evaluates each step index once, so the inner
            # DistributedTrainStep hook seeing the same index is a no-op
            batch = _faults.FAULTS.on_train_step(
                self._step._step_count, batch)
        x, y = batch
        x = x._data if isinstance(x, Tensor) else jnp.asarray(x)
        y = y._data if isinstance(y, Tensor) else jnp.asarray(y)
        loss = self._step((x, y))
        if self._pipe_sched_info is not None and _mtrace.is_tracing():
            self._emit_pipeline_ticks()
        self._write_back(self._step.params)
        self._write_back_buffers(self._step.aux)
        if self._scaler is not None:
            # LAZY mirror sync (ROADMAP PR-3 follow-up): float(scale) here
            # was a blocking device read every step — the one sync the
            # async fast path had left. Instead the eager GradScaler is
            # armed with a deferred pull; its next observable read
            # (get_loss_scaling / state_dict / scale) materializes the
            # compiled counters, i.e. sync happens at log/checkpoint
            # cadence rather than step cadence.
            self._scaler_dirty = True
            self._scaler._lazy_sync = self.sync_scaler
        return loss

    def sync_scaler(self) -> None:
        """Materialize the compiled scaler counters into the eager
        GradScaler mirror (no-op when already in sync)."""
        if self._scaler is None or not self._scaler_dirty:
            return
        st = self._step.scaler_state
        self._scaler._scale = float(st["scale"])
        self._scaler._good_steps = int(st["good"])
        self._scaler._bad_steps = int(st["bad"])
        self._scaler_dirty = False


def build_engine(model, optimizer, strategy, hcg=None, loss_fn=None,
                 mesh=None, sentinel=None,
                 global_batch=None) -> FleetEngine:
    return FleetEngine(model, optimizer, strategy, hcg=hcg, loss_fn=loss_fn,
                       mesh=mesh, sentinel=sentinel,
                       global_batch=global_batch)
