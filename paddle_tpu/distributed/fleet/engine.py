"""Fleet engine: routes facade-built eager models onto the compiled SPMD
training step.

Reference parity: fleet.distributed_model
(python/paddle/distributed/fleet/base/fleet_base.py:883) hands back a model
whose train_batch actually executes the selected parallelism. Here that
means building a :class:`paddle_tpu.parallel.DistributedTrainStep` — one
jitted sharded XLA program for forward + backward + clip + optimizer — from
the eager Layer, the eager optimizer's hyperparameters, and the strategy's
pipeline/sharding configuration.

Pipeline models: when every stage of a PipelineLayer holds a structurally
identical stack of sublayers, the engine stacks their params with a leading
stage dim sharded over the "pipe" mesh axis and runs the real SPMD pipeline
schedule (parallel.pipeline.pipeline_forward — CollectivePermute microbatch
rotation). Non-uniform stage stacks fall back to a scan over microbatches
with params replicated along "pipe" (same math, no cross-stage overlap) —
the compiled analog of the reference's grad-accumulation debug path.
"""
from __future__ import annotations

import warnings
from typing import Any, Callable, Dict, List, Optional

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from ...framework.core import Tensor
from ...framework.functional import functional_call
from ...nn.clip import ClipGradByGlobalNorm
from ...nn.layer.layers import Layer
from ...parallel.mesh import get_mesh, mesh_shape
from ...parallel.train_step import DistributedTrainStep

__all__ = ["FleetEngine", "build_engine"]


def _optimizer_config(optimizer) -> Dict[str, Any]:
    """Extract (kind, lr, clip_norm, opt_kwargs) from an eager Optimizer."""
    inner = getattr(optimizer, "_inner_opt", optimizer)
    kind = type(inner).__name__.lower()
    if "adamw" in kind or "adam" in kind:
        opt = "adamw"
        kwargs = {
            "beta1": float(getattr(inner, "_beta1", 0.9)),
            "beta2": float(getattr(inner, "_beta2", 0.999)),
            "eps": float(getattr(inner, "_epsilon", 1e-8)),
            "weight_decay": float(getattr(inner, "_weight_decay", 0.01) or 0.0)
            if "adamw" in kind else 0.0,
        }
    else:
        opt = "sgd"
        kwargs = {}
    clip = getattr(inner, "_grad_clip", None)
    # unwrap HybridParallelClipGrad
    clip = getattr(clip, "_clip", clip)
    clip_norm = float(clip.clip_norm) if isinstance(clip, ClipGradByGlobalNorm) else None
    return {"opt": opt, "opt_kwargs": kwargs, "clip_norm": clip_norm,
            "lr": lambda _step: float(inner.get_lr()), "inner": inner}


def _named_trainable(layer: Layer):
    return [(n, p) for n, p in layer.named_parameters() if not p.stop_gradient]


def _spec_of(p) -> P:
    s = getattr(p, "sharding", None)
    return s if isinstance(s, P) else P()


def _stage_layer_lists(pp_layer) -> Optional[List[List[Layer]]]:
    """Per-stage sublayer lists, or None if any stage holds a bare callable
    (no parameters to stack)."""
    stages: List[List[Layer]] = [[] for _ in range(pp_layer.get_num_stages())]
    for fn, s in zip(pp_layer.run_function, pp_layer._stage_of_layer):
        if not isinstance(fn, Layer):
            return None
        stages[s].append(fn)
    return stages


def _uniform_stages(stages: List[List[Layer]]):
    """If every stage's param tree matches stage 0 structurally, return
    (per_stage_param_lists, shapes_ok). Shared layers (tied weights across
    stages) break uniformity — their params appear in several stages."""
    seen = set()
    per_stage = []
    for st in stages:
        trees = []
        for layer in st:
            d = {}
            for n, p in layer.named_parameters():
                if p.stop_gradient:
                    continue
                if id(p) in seen:
                    return None  # tied weight spans stages
                d[n] = p
            trees.append(d)
        for d in trees:
            seen.update(id(p) for p in d.values())
        per_stage.append(trees)
    ref = per_stage[0]
    for other in per_stage[1:]:
        if len(other) != len(ref):
            return None
        for a, b in zip(ref, other):
            if sorted(a) != sorted(b):
                return None
            for k in a:
                if tuple(a[k]._data.shape) != tuple(b[k]._data.shape) or \
                        a[k]._data.dtype != b[k]._data.dtype:
                    return None
                if _spec_of(a[k]) != _spec_of(b[k]):
                    return None
    return per_stage


class FleetEngine:
    """Compiled training step for a facade-built model.

    step((x, y)) -> loss (host float-able jax scalar). Parameters are
    written back into the eager Layer after every step (reference-count
    swap, no host transfer), so state_dict/save keep working.
    """

    def __init__(self, model: Layer, optimizer, strategy, hcg=None,
                 loss_fn: Optional[Callable] = None, mesh=None):
        from .meta_parallel.pp_layers import PipelineLayer

        self.mesh = mesh or get_mesh()
        if self.mesh is None:
            raise RuntimeError("FleetEngine needs a mesh (fleet.init first)")
        shape = mesh_shape(self.mesh)
        self._model = model

        inner_model = model
        # unwrap facade wrappers holding the real layers at ._layers
        while not isinstance(inner_model, PipelineLayer) and \
                hasattr(inner_model, "_layers") and \
                isinstance(getattr(inner_model, "_layers"), Layer):
            inner_model = inner_model._layers
        self._inner_model = inner_model

        cfg = _optimizer_config(optimizer)
        pipe_deg = shape.get("pipe", 1)
        shard_deg = shape.get("sharding", 1)

        pcfg = getattr(strategy, "pipeline_configs", {}) or {}
        self.accumulate_steps = int(pcfg.get("accumulate_steps", 1))

        loss_layer = loss_fn
        if loss_layer is None and isinstance(inner_model, PipelineLayer):
            loss_layer = inner_model._loss_fn
        if loss_layer is None:
            raise ValueError("FleetEngine needs a loss_fn (PipelineLayer "
                             "loss_fn or explicit argument)")

        def loss_arrays(out, y):
            r = loss_layer(Tensor(out) if not isinstance(out, Tensor) else out,
                           Tensor(y) if not isinstance(y, Tensor) else y)
            return r._data if isinstance(r, Tensor) else r

        built = None
        if isinstance(inner_model, PipelineLayer) and pipe_deg > 1:
            built = self._build_pipelined(inner_model, loss_arrays, pipe_deg)
            if built is None:
                warnings.warn(
                    "PipelineLayer stages are not structurally uniform; "
                    "compiling as microbatch-scan with pipe-replicated "
                    "params (no cross-stage overlap). Make stages uniform "
                    "for true SPMD pipelining.")
        if built is None:
            built = self._build_flat(inner_model, loss_arrays)
        params, specs, step_loss = built

        self._write_back_names = list(params)
        self._step = DistributedTrainStep(
            step_loss, params, specs, optimizer=cfg["opt"], lr=cfg["lr"],
            clip_norm=cfg["clip_norm"], zero=shard_deg > 1, mesh=self.mesh,
            opt_kwargs=cfg["opt_kwargs"])

    # -- builders ------------------------------------------------------------
    def _micro_loss(self, one_loss: Callable):
        """Wrap a per-batch loss into the accumulate_steps scan (identical
        math to eager PipelineParallel.forward_backward_pipeline: mean of
        per-microbatch mean losses)."""
        acc = self.accumulate_steps

        if acc <= 1:
            return one_loss

        def scan_loss(params, batch):
            x, y = batch
            xm = x.reshape(acc, x.shape[0] // acc, *x.shape[1:])
            ym = y.reshape(acc, y.shape[0] // acc, *y.shape[1:])

            def body(total, xy):
                return total + one_loss(params, xy), None

            total, _ = jax.lax.scan(jax.checkpoint(body), jnp.float32(0.0),
                                    (xm, ym))
            return total / acc

        return scan_loss

    def _build_flat(self, model: Layer, loss_arrays):
        named = _named_trainable(model)
        params = {n: p._data for n, p in named}
        specs = {n: _spec_of(p) for n, p in named}
        self._write_back = lambda new: self._assign(model, new)

        def one_loss(params, batch):
            x, y = batch
            out = functional_call(model, params, x)
            return loss_arrays(out, y)

        return params, specs, self._micro_loss(one_loss)

    def _build_pipelined(self, pp_layer, loss_arrays, pipe_deg):
        from ...parallel.pipeline import pipeline_forward

        stages = _stage_layer_lists(pp_layer)
        if stages is None:
            return None
        per_stage = _uniform_stages(stages)
        if per_stage is None:
            return None

        n_stages = len(stages)
        # stack stage s's params along a new leading "pipe" dim
        stacked: Dict[str, Any] = {}
        specs: Dict[str, Any] = {}
        layer_count = len(per_stage[0])
        stage0 = stages[0]
        for li in range(layer_count):
            for pname in per_stage[0][li]:
                key = f"stage.{li}.{pname}"
                stacked[key] = jnp.stack(
                    [per_stage[s][li][pname]._data for s in range(n_stages)])
                specs[key] = P("pipe", *_spec_of(per_stage[0][li][pname]))

        self._pp_meta = (stages, per_stage, layer_count)
        self._write_back = self._assign_pipelined

        def stage_fn(sp, h):
            for li, layer in enumerate(stage0):
                lp = {pn: sp[f"stage.{li}.{pn}"] for pn in per_stage[0][li]}
                h = functional_call(layer, lp, h)
            return h

        acc = max(self.accumulate_steps, n_stages)

        def step_loss(params, batch):
            x, y = batch
            xm = x.reshape(acc, x.shape[0] // acc, *x.shape[1:])
            ym = y.reshape(acc, y.shape[0] // acc, *y.shape[1:])
            ys = pipeline_forward(stage_fn, params, xm, n_stages)
            # mean over microbatches of the per-micro loss — identical math
            # to eager train_batch's accumulation
            losses = jax.vmap(lambda o, t: loss_arrays(o, t))(ys, ym)
            return jnp.mean(losses)

        return stacked, specs, step_loss

    # -- write-back ----------------------------------------------------------
    @staticmethod
    def _assign(model: Layer, new_params: Dict[str, Any]):
        named = dict(model.named_parameters())
        for n, arr in new_params.items():
            named[n]._data = arr

    def _assign_pipelined(self, new_params: Dict[str, Any]):
        stages, per_stage, layer_count = self._pp_meta
        for li in range(layer_count):
            for pname in per_stage[0][li]:
                arr = new_params[f"stage.{li}.{pname}"]
                for s in range(len(stages)):
                    per_stage[s][li][pname]._data = arr[s]

    # -- public --------------------------------------------------------------
    @property
    def train_step(self) -> DistributedTrainStep:
        return self._step

    def step(self, batch):
        x, y = batch
        x = x._data if isinstance(x, Tensor) else jnp.asarray(x)
        y = y._data if isinstance(y, Tensor) else jnp.asarray(y)
        loss = self._step((x, y))
        self._write_back(self._step.params)
        return loss


def build_engine(model, optimizer, strategy, hcg=None, loss_fn=None,
                 mesh=None) -> FleetEngine:
    return FleetEngine(model, optimizer, strategy, hcg=hcg, loss_fn=loss_fn,
                       mesh=mesh)
