"""MultiSlot data generators (reference
python/paddle/distributed/fleet/data_generator/data_generator.py):
user subclasses implement generate_sample; the generator formats samples
into the slot text protocol that the Dataset pipe consumes."""
from __future__ import annotations

import sys


class DataGenerator:
    def __init__(self):
        self._line_processor = None
        self.batch_size_ = 1

    def set_batch(self, batch_size):
        self.batch_size_ = batch_size

    def generate_sample(self, line):
        raise NotImplementedError(
            "subclasses implement generate_sample(line) returning a "
            "CALLABLE (a generator function) whose iteration yields "
            "samples of [(slot_name, [values...]), ...]")

    def generate_batch(self, samples):
        def local_iter():
            for sample in samples:
                yield sample

        return local_iter

    def _gen_str(self, line):
        raise NotImplementedError

    def _flush(self, buf):
        # samples flow through the generate_batch hook per batch_size_
        # (reference data_generator.py: subclasses override it for
        # in-batch shuffling/padding)
        for sample in self.generate_batch(buf)():
            if sample is not None:
                sys.stdout.write(self._gen_str(sample))

    def run_from_stdin(self):
        buf = []
        for line in sys.stdin:
            for user_parsed_line in self.generate_sample(line)():
                if user_parsed_line is None:
                    continue
                buf.append(user_parsed_line)
                if len(buf) == self.batch_size_:
                    self._flush(buf)
                    buf = []
        if buf:
            self._flush(buf)

    def run_from_memory(self):
        buf = []
        for line in self.generate_sample(None)():
            if line is None:
                continue
            buf.append(line)
            if len(buf) == self.batch_size_:
                self._flush(buf)
                buf = []
        if buf:
            self._flush(buf)


class MultiSlotDataGenerator(DataGenerator):
    """Slot protocol: `<n> <v1> ... <vn>` per slot, space-joined
    (reference _gen_str; the slot ORDER carries the schema)."""

    def _gen_str(self, line):
        parts = []
        for _name, values in line:
            parts.append(str(len(values)))
            parts.extend(str(v) for v in values)
        return " ".join(parts) + "\n"


class MultiSlotStringDataGenerator(MultiSlotDataGenerator):
    """Same slot protocol; the reference variant only skips type checks."""
