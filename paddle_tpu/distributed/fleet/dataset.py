"""Dataset shims: InMemoryDataset / QueueDataset / sparse-table entries
(reference python/paddle/distributed/fleet/dataset/dataset.py:24,324 and
distributed/entry_attr.py).

The reference backs these with the C++ MultiSlotDataFeed + channel stack
feeding parameter-server trainers (SURVEY §2.1 #24/#25). Per the README
trainer/DataFeed and parameter-server decisions, the TPU build's
high-throughput path is io.DataLoader (+ the native prefetcher); these
classes keep the file-list API working single-process: text files, one
sample per line, parsed by ``pipe_command`` (run through the shell exactly
like the reference) or a user ``parse_fn``.
"""
from __future__ import annotations

import random as _random
import subprocess

__all__ = ["DatasetBase", "InMemoryDataset", "QueueDataset",
           "CountFilterEntry", "ProbabilityEntry"]


class DatasetBase:
    def __init__(self):
        self.batch_size = 1
        self.thread_num = 1
        self.filelist = []
        self.pipe_command = None
        self.parse_fn = None
        self.use_var = []

    def init(self, batch_size=1, thread_num=1, use_var=None,
             pipe_command=None, input_type=0, fs_name="", fs_ugi="",
             download_cmd="cat", **kwargs):
        self.batch_size = int(batch_size)
        self.thread_num = int(thread_num)
        self.use_var = list(use_var or [])
        self.pipe_command = pipe_command
        return self

    def set_filelist(self, filelist):
        self.filelist = list(filelist)

    def _read_one_file(self, path):
        with open(path, "r", encoding="utf-8", errors="replace") as f:
            data = f.read()
        if self.pipe_command:
            data = subprocess.run(
                self.pipe_command, shell=True, input=data,
                capture_output=True, text=True, check=True).stdout
        return [self.parse_fn(line) if self.parse_fn else line
                for line in data.splitlines() if line]

    def _read_lines(self):
        """thread_num > 1 processes FILES concurrently — each file's
        ``pipe_command`` is its own subprocess, so the heavy parsing runs
        genuinely in parallel (the analog of the reference's
        ``thread_num`` reader channels, framework/data_feed.cc
        MultiSlotDataFeed); results stream in filelist order (the
        reference's channels do not even guarantee that)."""
        n = min(int(self.thread_num or 1), len(self.filelist))
        if n > 1:
            # bounded read-ahead: at most n parsed files in flight — a
            # slow consumer throttles submission instead of the pool
            # racing ahead and buffering the whole parsed dataset
            from collections import deque
            from concurrent.futures import ThreadPoolExecutor

            with ThreadPoolExecutor(max_workers=n) as ex:
                files = iter(self.filelist)
                pending: deque = deque()
                for path in self.filelist[:n]:
                    pending.append(ex.submit(self._read_one_file, path))
                    next(files)
                while pending:
                    fut = pending.popleft()
                    nxt = next(files, None)
                    if nxt is not None:
                        pending.append(ex.submit(self._read_one_file, nxt))
                    yield from fut.result()
            return
        for path in self.filelist:
            yield from self._read_one_file(path)

    def _batches(self, lines):
        buf = []
        for item in lines:
            buf.append(item)
            if len(buf) == self.batch_size:
                yield buf
                buf = []
        if buf:
            yield buf


class InMemoryDataset(DatasetBase):
    """Load-then-shuffle dataset (reference dataset.py:324)."""

    def __init__(self):
        super().__init__()
        self._memory = []

    def load_into_memory(self, is_shuffle=False):
        self._memory = list(self._read_lines())
        if is_shuffle:
            self.local_shuffle()

    def local_shuffle(self):
        _random.shuffle(self._memory)

    def global_shuffle(self, fleet=None, thread_num=12):
        # single-host: global == local (multi-host shuffle belongs to the
        # PS runtime, see the README parameter-server decision)
        self.local_shuffle()

    def get_memory_data_size(self, fleet=None):
        return len(self._memory)

    def get_shuffle_data_size(self, fleet=None):
        return len(self._memory)

    def release_memory(self):
        self._memory = []

    def __iter__(self):
        return self._batches(iter(self._memory))


class QueueDataset(DatasetBase):
    """Streaming dataset: no in-memory staging (reference dataset.py
    QueueDataset)."""

    def __iter__(self):
        return self._batches(self._read_lines())


class ProbabilityEntry:
    """Sparse-table entry admission by probability (reference
    distributed/entry_attr.py). Config-only here: the sparse table lives in
    the parameter server the README documents out of the TPU critical
    path."""

    def __init__(self, probability):
        if not 0 < probability <= 1:
            raise ValueError("probability must be in (0, 1]")
        self.probability = float(probability)

    def _to_attr(self):
        return "probability_entry:%f" % self.probability


class CountFilterEntry:
    """Sparse-table entry admission by show count (reference
    distributed/entry_attr.py)."""

    def __init__(self, count_filter):
        if count_filter < 0:
            raise ValueError("count_filter must be >= 0")
        self.count_filter = int(count_filter)

    def _to_attr(self):
        return "count_filter_entry:%d" % self.count_filter
