"""DistributedStrategy.

Parity: reference python/paddle/distributed/fleet/base/distributed_strategy.py
(proto-backed, framework/distributed_strategy.proto:176). Here a plain
attribute bag with the same feature switches; features map to mesh axes and
jit options instead of program rewrites.
"""
from __future__ import annotations

__all__ = ["DistributedStrategy"]


class DistributedStrategy:
    def __init__(self):
        # feature switches (reference proto fields)
        self.amp = False
        self.amp_configs = {
            "init_loss_scaling": 32768.0, "incr_every_n_steps": 1000,
            "decr_every_n_nan_or_inf": 2, "incr_ratio": 2.0, "decr_ratio": 0.5,
            "use_dynamic_loss_scaling": True, "custom_white_list": [],
            "custom_black_list": [], "use_pure_fp16": False,
            "use_fp16_guard": True, "dtype": "bfloat16",
        }
        self.recompute = False
        self.recompute_configs = {"checkpoints": [], "enable_offload": False}
        self.pipeline = False
        self.pipeline_configs = {"accumulate_steps": 1, "micro_batch_size": 1}
        self.gradient_merge = False
        self.gradient_merge_configs = {"k_steps": 1, "avg": True}
        self.sharding = False
        self.sharding_configs = {
            "sharding_degree": 1, "stage": 1, "mp_degree": 1, "pp_degree": 1,
            "dp_degree": 1, "gradient_merge_acc_step": 1, "offload": False,
            "segment_broadcast_MB": 32.0,
        }
        self.lamb = False
        self.lamb_configs = {"lamb_weight_decay": 0.01, "exclude_from_weight_decay": []}
        self.lars = False
        self.lars_configs = {"lars_coeff": 0.001, "lars_weight_decay": 0.0005,
                             "epsilon": 0, "exclude_from_weight_decay": []}
        self.dgc = False
        self.localsgd = False
        self.fp16_allreduce = False
        self.a_sync = False
        self.a_sync_configs = {"k_steps": -1}
        self.semi_auto = False
        self.auto = False
        # fleet.auto planner knobs (ISSUE 9): hbm_bytes_per_device,
        # seq_len/hidden hints, max_micro, zero_min_size, schedule, and
        # dp/sharding/pp/mp/n_micro/zero pins
        self.auto_configs = {}
        self.asp = False
        self.heter_ccl_mode = False
        self.hybrid_configs = {
            "dp_degree": 1, "mp_degree": 1, "pp_degree": 1, "sharding_degree": 1,
        }
        self.tensor_parallel = False
        self.tensor_parallel_configs = {"tensor_parallel_degree": 1}
        self.without_graph_optimization = False
        self.fuse_all_reduce_ops = True
        self.fuse_grad_size_in_MB = 32
        self.nccl_comm_num = 1
        self.sync_nccl_allreduce = True
        self.gradient_scale_configs = {"scale_strategy": "avg"}
        self.find_unused_parameters = False
        self.last_comm_group_size_MB = 1
        # execution/build strategy placeholders
        self.execution_strategy = None
        self.build_strategy = None

    def __repr__(self):
        on = [k for k, v in self.__dict__.items()
              if isinstance(v, bool) and v]
        return f"DistributedStrategy(enabled={on})"
