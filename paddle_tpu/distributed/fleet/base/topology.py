"""Hybrid-parallel topology.

Parity: reference python/paddle/distributed/fleet/base/topology.py:36
(CommunicateTopology) / :117 (HybridCommunicateGroup). The 4-axis cartesian
rank mesh ["data","pipe","sharding","model"] maps 1:1 onto a
jax.sharding.Mesh with those axis names — mesh coordinates replace ranks,
named axes replace ring_ids.
"""
from __future__ import annotations

import itertools
from functools import reduce
from typing import Dict, List, Sequence

import numpy as np

__all__ = ["CommunicateTopology", "HybridCommunicateGroup", "ParallelMode"]


class ParallelMode:
    DATA_PARALLEL = 0
    TENSOR_PARALLEL = 1
    PIPELINE_PARALLEL = 2
    SHARDING_PARALLEL = 3


class CommunicateTopology:
    def __init__(self, hybrid_group_names=("data", "pipe", "sharding", "model"),
                 dims=(1, 1, 1, 1)):
        self._parallel_names = list(hybrid_group_names)
        self._dims = list(dims)
        self.coordinate = list(itertools.product(*(range(d) for d in dims)))
        self._world_size = int(np.prod(dims))
        self._coord2rank = {c: i for i, c in enumerate(self.coordinate)}
        self._rank2coord = {i: c for c, i in self._coord2rank.items()}

    def get_hybrid_group_names(self):
        return self._parallel_names

    def get_dim(self, axis_name):
        return self._dims[self._parallel_names.index(axis_name)]

    get_dim_size = get_dim

    def world_size(self):
        return self._world_size

    def get_rank(self, **kwargs):
        coord = tuple(kwargs[name] for name in self._parallel_names)
        return self._coord2rank[coord]

    def get_coord(self, rank):
        return self._rank2coord[rank]

    def get_axis_list(self, axis_name, index):
        """All ranks whose coordinate on axis_name == index."""
        axis = self._parallel_names.index(axis_name)
        return sorted(self._coord2rank[c] for c in self.coordinate if c[axis] == index)

    def get_comm_list(self, axis_name):
        """List of rank-groups along axis_name (reference get_comm_list)."""
        axis = self._parallel_names.index(axis_name)
        other_axes = [i for i in range(len(self._dims)) if i != axis]
        groups = []
        for other in itertools.product(*(range(self._dims[i]) for i in other_axes)):
            ranks = []
            for v in range(self._dims[axis]):
                coord = [0] * len(self._dims)
                for i, o in zip(other_axes, other):
                    coord[i] = o
                coord[axis] = v
                ranks.append(self._coord2rank[tuple(coord)])
            groups.append(ranks)
        return groups

    def get_rank_from_stage(self, global_rank, **kwargs):
        coord = self.get_coord(global_rank)
        tf = dict(zip(self._parallel_names, coord))
        tf.update(kwargs)
        return self.get_rank(**tf)


class HybridCommunicateGroup:
    """Reference topology.py:117 — carves dp/mp/pp/sharding sub-groups.

    TPU-native: instead of creating NCCL rings per group, we record the axis
    names; collectives inside compiled code reference axes directly.
    """

    def __init__(self, topology: CommunicateTopology, global_rank: int = 0):
        self._topo = topology
        self.global_rank = global_rank
        self.nranks = topology.world_size()

        self._dp_degree = topology.get_dim("data")
        self._mp_degree = topology.get_dim("model")
        self._pp_degree = topology.get_dim("pipe")
        self._sharding_degree = topology.get_dim("sharding")

        from ... import collective as C

        coord = topology.get_coord(global_rank)
        names = topology.get_hybrid_group_names()
        self._coord = dict(zip(names, coord))

        def mk(axis):
            ranks_groups = topology.get_comm_list(axis)
            my = next(g for g in ranks_groups if global_rank in g)
            return C.Group(my.index(global_rank), len(my), id=hash(axis) % 100000,
                           ranks=my, axis_name=axis)

        self._dp_group = mk("data")
        self._mp_group = mk("model")
        self._pp_group = mk("pipe")
        self._sharding_group = mk("sharding")

    # parallel mode checks (reference api)
    def get_parallel_mode(self):
        if self._mp_degree > 1:
            return ParallelMode.TENSOR_PARALLEL
        if self._pp_degree > 1:
            return ParallelMode.PIPELINE_PARALLEL
        if self._sharding_degree > 1:
            return ParallelMode.SHARDING_PARALLEL
        return ParallelMode.DATA_PARALLEL

    def topology(self):
        return self._topo

    def get_global_rank(self):
        return self.global_rank

    # data parallel
    def get_data_parallel_rank(self):
        return self._coord["data"]

    def get_data_parallel_world_size(self):
        return self._dp_degree

    def get_data_parallel_group(self):
        return self._dp_group

    def get_data_parallel_group_src_rank(self):
        return self._dp_group.ranks[0]

    # model (tensor) parallel
    def get_model_parallel_rank(self):
        return self._coord["model"]

    def get_model_parallel_world_size(self):
        return self._mp_degree

    def get_model_parallel_group(self):
        return self._mp_group

    def get_model_parallel_group_src_rank(self):
        return self._mp_group.ranks[0]

    # pipeline parallel
    def get_stage_id(self):
        return self._coord["pipe"]

    def get_pipe_parallel_world_size(self):
        return self._pp_degree

    def get_pipe_parallel_group(self):
        return self._pp_group

    def is_first_stage(self):
        return self.get_stage_id() == 0

    def is_last_stage(self):
        return self.get_stage_id() == self._pp_degree - 1

    # sharding
    def get_sharding_parallel_rank(self):
        return self._coord["sharding"]

    def get_sharding_parallel_world_size(self):
        return self._sharding_degree

    def get_sharding_parallel_group(self):
        return self._sharding_group

    def get_sharding_parallel_group_src_rank(self):
        return self._sharding_group.ranks[0]

    # p2p neighbors (reference topology.py:225)
    def get_p2p_groups(self):
        prev = (self.get_stage_id() - 1) % self._pp_degree
        nxt = (self.get_stage_id() + 1) % self._pp_degree
        return prev, nxt

    def get_rank_from_stage(self, stage_id, **kwargs):
        return self._topo.get_rank_from_stage(self.global_rank, pipe=stage_id, **kwargs)
