"""Fleet facade.

Parity: reference python/paddle/distributed/fleet/base/fleet_base.py:103
(Fleet.init/distributed_model/distributed_optimizer/minimize). TPU-native:
``init`` with hybrid_configs builds ONE global jax.sharding.Mesh with axes
["data","pipe","sharding","model"]; distributed_model/optimizer select
wrappers that annotate shardings for pjit rather than rewriting programs.
"""
from __future__ import annotations

from typing import Optional

import jax
import numpy as np

from .... import nn
from ... import env
from .distributed_strategy import DistributedStrategy
from .topology import CommunicateTopology, HybridCommunicateGroup

__all__ = ["Fleet", "fleet"]


class Fleet:
    def __init__(self):
        self._strategy: Optional[DistributedStrategy] = None
        self._hcg: Optional[HybridCommunicateGroup] = None
        self._topology: Optional[CommunicateTopology] = None
        self._is_collective = True
        self._mesh = None

    # -- init ----------------------------------------------------------------
    def init(self, role_maker=None, is_collective=True, strategy=None):
        if not is_collective:
            # Parameter-server mode (reference fleet_base.py:170 with
            # is_collective=False → brpc PS, paddle/fluid/distributed/
            # service/ps_client.h:55). DECISION (documented in README):
            # sparse/async PS training has no TPU-native analog — TPU
            # training is dense SPMD over ICI/DCN meshes; a brpc-style
            # CPU parameter server is out of the TPU critical path.
            raise NotImplementedError(
                "parameter-server mode (is_collective=False) is not "
                "supported by the TPU backend: use collective mode "
                "(is_collective=True) with hybrid_configs "
                "(dp/mp/pp/sharding) instead — see README 'Parameter "
                "server decision'")
        self._is_collective = is_collective
        if isinstance(strategy, dict):
            # `fleet.init(strategy={"auto": True})` shorthand (ISSUE 9):
            # a plain dict of DistributedStrategy attribute overrides
            d = dict(strategy)
            strategy = DistributedStrategy()
            for k, v in d.items():
                setattr(strategy, k, v)
        self._strategy = strategy or DistributedStrategy()
        if getattr(self._strategy, "a_sync", False):
            raise NotImplementedError(
                "DistributedStrategy.a_sync (async parameter server) is "
                "not supported on TPU — see README 'Parameter server "
                "decision'")
        if getattr(self._strategy, "auto", False) and \
                not getattr(self._strategy, "semi_auto", False):
            # full-auto (fleet.auto planner): the mesh depends on the
            # MODEL, which init has not seen — defer it to the first
            # engine build (FleetEngine._make_plan installs the planned
            # mesh and re-registers topology/hcg through the plan)
            from ...auto_parallel import get_default_mesh

            pm = get_default_mesh()
            if pm is not None:
                self._mesh = pm.install()
                ms = dict(self._mesh.shape)
                dims = (ms["data"], ms["pipe"], ms["sharding"], ms["model"])
            else:
                self._mesh = None
                from ....parallel.mesh import set_mesh

                set_mesh(None)
                dims = (1, 1, 1, 1)
            dp, pp, sh, mp = dims
        elif getattr(self._strategy, "semi_auto", False) or \
                getattr(self._strategy, "auto", False):
            # semi-auto route (reference fleet_base.py:1423-1430): the mesh
            # comes from the user's ProcessMesh annotations, not
            # hybrid_configs; GSPMD is the parallelizer
            from ...auto_parallel import get_default_mesh

            pm = get_default_mesh()
            if pm is not None:
                self._mesh = pm.install()
            else:
                devs = np.array(jax.devices())
                self._mesh = jax.sharding.Mesh(
                    devs.reshape(len(devs), 1, 1, 1),
                    ("data", "pipe", "sharding", "model"))
                from ....parallel.mesh import set_mesh

                set_mesh(self._mesh)
            ms = dict(self._mesh.shape)
            dp, pp = ms["data"], ms["pipe"]
            sh, mp = ms["sharding"], ms["model"]
        else:
            hc = self._strategy.hybrid_configs
            dp = int(hc.get("dp_degree", 1))
            mp = int(hc.get("mp_degree", 1))
            pp = int(hc.get("pp_degree", 1))
            sh = int(hc.get("sharding_degree", 1))
            # strategy.tensor_parallel (reference tensor_parallel
            # meta-optimizer, static-graph mp): sets the "model" mesh axis
            # when hybrid_configs hasn't already
            if getattr(self._strategy, "tensor_parallel", False) and mp <= 1:
                tp_cfg = getattr(self._strategy, "tensor_parallel_configs",
                                 {}) or {}
                mp = int(tp_cfg.get("tensor_parallel_degree", 1))
            n_needed = dp * mp * pp * sh
            devs = np.array(jax.devices())
            if n_needed <= 1:
                # pure DP over all devices
                dp = len(devs)
                n_needed = dp
            if len(devs) < n_needed:
                raise RuntimeError(
                    f"hybrid_configs needs {n_needed} devices, have {len(devs)}")
            devs = devs[:n_needed].reshape(dp, pp, sh, mp)
            self._mesh = jax.sharding.Mesh(devs, ("data", "pipe", "sharding", "model"))
            from ....parallel.mesh import set_mesh

            set_mesh(self._mesh)
        self._topology = CommunicateTopology(("data", "pipe", "sharding", "model"),
                                             (dp, pp, sh, mp))
        self._hcg = HybridCommunicateGroup(self._topology, env.get_rank())
        env.set_state(initialized=True, mesh=self._mesh, topology=self._topology,
                      hcg=self._hcg, rank=env.get_rank(),
                      world_size=self._topology.world_size())
        return self

    def get_hybrid_communicate_group(self):
        return self._hcg

    def get_mesh(self):
        return self._mesh

    @property
    def worker_num(self):
        return self._topology.world_size() if self._topology else 1

    def worker_index(self):
        return env.get_rank()

    def is_first_worker(self):
        return env.get_rank() == 0

    def barrier_worker(self):
        from ... import collective as C

        C.barrier()

    # -- model/optimizer wrapping --------------------------------------------
    def distributed_model(self, model):
        """Pick the parallel wrapper (reference fleet_base.py:883).

        pp>1 PipelineLayer → PipelineParallel (train_batch compiles the
        SPMD pipeline via fleet/engine.py); mp>1 → TensorParallel;
        sharding>1 → ShardingParallel (train_batch compiles a ZeRO-1
        sharded step); else eager DataParallel."""
        from ..meta_parallel.pp_layers import PipelineLayer
        from ..meta_parallel.pipeline_parallel import PipelineParallel
        from ..meta_parallel.tensor_parallel import (SemiAutoParallel,
                                                     ShardingParallel,
                                                     TensorParallel)
        from ...parallel import DataParallel

        if self._hcg is None:
            self.init()
        if getattr(self._strategy, "semi_auto", False) or \
                getattr(self._strategy, "auto", False):
            return SemiAutoParallel(model, self._hcg, self._strategy)
        if self._hcg.get_pipe_parallel_world_size() > 1 and isinstance(model, PipelineLayer):
            return PipelineParallel(model, self._hcg, self._strategy)
        if self._hcg.get_model_parallel_world_size() > 1:
            return TensorParallel(model, self._hcg, self._strategy)
        if self._hcg.get_sharding_parallel_world_size() > 1:
            return ShardingParallel(model, self._hcg, self._strategy)
        return DataParallel(model)

    def distributed_optimizer(self, optimizer, strategy=None):
        from ..meta_optimizers.dygraph_optimizer.hybrid_parallel_optimizer import (
            HybridParallelOptimizer,
        )
        from ..meta_optimizers.gradient_merge import GradientMergeOptimizer

        if strategy is not None:
            self._strategy = strategy
        if self._hcg is None:
            self.init()
        if getattr(self._strategy, "gradient_merge", False):
            cfg = self._strategy.gradient_merge_configs or {}
            optimizer = GradientMergeOptimizer(
                optimizer, k_steps=int(cfg.get("k_steps", 1)),
                avg=bool(cfg.get("avg", True)))
        if self._topology and self._topology.world_size() > 1:
            return HybridParallelOptimizer(optimizer, self._hcg, self._strategy)
        return optimizer

    def minimize(self, loss, startup_program=None, parameter_list=None, no_grad_set=None):
        from ....framework.core import backward

        backward(loss)
        return None, []

    # -- save/load (reference fleet_base.py:701-828) -------------------------
    def save_inference_model(self, executor, dirname, feeded_var_names,
                             target_vars, main_program=None, export_for_deployment=True):
        raise NotImplementedError("use paddle_tpu.jit.save")

    def save_persistables(self, executor, dirname, main_program=None, mode=0):
        raise NotImplementedError("use paddle_tpu.save on state_dict")

    # role info
    def is_server(self):
        return False

    def is_worker(self):
        return True

    def stop_worker(self):
        pass

    @property
    def worker_endpoints(self):
        """Per-PROCESS endpoints from the launcher env. Note the unit
        difference from worker_num, which counts mesh devices: one process
        drives worker_num/len(endpoints) devices."""
        import os

        eps = os.environ.get("PADDLE_TRAINER_ENDPOINTS", "")
        return eps.split(",") if eps else ["127.0.0.1:0"]

    @property
    def server_num(self):
        return 0  # no parameter servers: README PS decision

    @property
    def server_index(self):
        return -1

    @property
    def server_endpoints(self):
        return []

    @property
    def util(self):
        if getattr(self, "_util", None) is None:
            self._util = UtilBase(self)
        return self._util

    def init_worker(self):
        raise NotImplementedError(
            "init_worker belongs to parameter-server mode; see the README "
            "parameter-server decision (collective mode needs no worker "
            "bring-up beyond fleet.init)")

    def init_server(self, *args, **kwargs):
        raise NotImplementedError(
            "init_server: no parameter servers (README PS decision)")

    def run_server(self):
        raise NotImplementedError(
            "run_server: no parameter servers (README PS decision)")

    def state_dict(self):
        """PS-mode table snapshot in the reference; collective mode's
        training state lives in the model/optimizer state_dicts."""
        return {}

    def set_state_dict(self, state):
        return None

    def shrink(self, threshold=None):
        raise NotImplementedError(
            "shrink compacts PS sparse tables (README PS decision)")


fleet = Fleet()


class Role:
    """Reference role_maker.Role enum (WORKER/SERVER/HETER_WORKER/ALL)."""

    WORKER = 1
    SERVER = 2
    HETER_WORKER = 3
    ALL = 4


def _proc_world():
    """(process_rank, process_count) of the LAUNCHER world.

    The util surface operates on host PYTHON values across trainer
    PROCESSES (the reference's gloo world), not mesh devices: on this
    runtime one process drives many devices (Fleet.worker_num counts
    devices for topology math), so file sharding and host reductions must
    use the process world or a single-host multi-device run would
    silently drop data.
    """
    import os

    # jax-native multi-process launches (jax.distributed) first, then the
    # launcher's PADDLE_* env, else single process
    if jax.process_count() > 1:
        return jax.process_index(), jax.process_count()
    eps = os.environ.get("PADDLE_TRAINER_ENDPOINTS", "")
    n = max(len(eps.split(",")) if eps else 1, 1)
    return int(os.environ.get("PADDLE_TRAINER_ID", 0)), n


class UtilBase:
    """Cross-worker utility surface (reference fleet/base/util_factory.py)
    over the PROCESS world (see _proc_world): host-side helpers, not the
    compiled-step device collectives.
    """

    def __init__(self, fleet_obj=None):
        self._fleet = fleet_obj

    def all_reduce(self, input, mode="sum", comm_world="worker"):  # noqa: A002
        arr = np.asarray(input)
        _rank, n = _proc_world()
        if n == 1:
            return arr  # one process: every mode reduces to identity
        raise NotImplementedError(
            "UtilBase.all_reduce across launcher processes needs a host "
            "store; reduce inside the compiled step "
            "(paddle.distributed.all_reduce) instead")

    def all_gather(self, input, comm_world="worker"):  # noqa: A002
        _rank, n = _proc_world()
        if n == 1:
            return [input]
        raise NotImplementedError(
            "UtilBase.all_gather across launcher processes needs a host "
            "store; gather inside the compiled step instead")

    def barrier(self, comm_world="worker"):
        from ... import collective

        collective.barrier()

    def get_file_shard(self, files):
        """Split a file list evenly over trainer PROCESSES (reference
        util_factory.get_file_shard): each process feeds all its local
        devices from its stripe."""
        i, n = _proc_world()
        per, rem = divmod(len(files), n)
        start = i * per + min(i, rem)
        return files[start: start + per + (1 if i < rem else 0)]

    def print_on_rank(self, message, rank_id=0):
        if _proc_world()[0] == rank_id:
            print(message)
