"""paddle_tpu.distributed.fleet (mirrors paddle.distributed.fleet)."""
from .base.distributed_strategy import DistributedStrategy  # noqa: F401
from .base.fleet_base import Fleet, fleet  # noqa: F401
from .base.topology import CommunicateTopology, HybridCommunicateGroup, ParallelMode  # noqa: F401
from . import meta_parallel  # noqa: F401
from . import auto  # noqa: F401  (fleet.auto — hybrid-parallel planner)
from .utils.recompute import recompute  # noqa: F401
from .utils.fs import HDFSClient, LocalFS  # noqa: F401
from .base.fleet_base import Role, UtilBase  # noqa: F401
from .data_generator import (  # noqa: F401
    DataGenerator, MultiSlotDataGenerator, MultiSlotStringDataGenerator,
)

# module-level facade functions (reference fleet/__init__.py re-exports)
init = fleet.init
distributed_model = fleet.distributed_model
distributed_optimizer = fleet.distributed_optimizer
get_hybrid_communicate_group = fleet.get_hybrid_communicate_group
get_mesh = fleet.get_mesh
minimize = fleet.minimize
worker_index = fleet.worker_index
is_first_worker = fleet.is_first_worker
barrier_worker = fleet.barrier_worker
is_server = fleet.is_server
is_worker = fleet.is_worker
stop_worker = fleet.stop_worker
init_worker = fleet.init_worker
init_server = fleet.init_server
run_server = fleet.run_server
state_dict = fleet.state_dict
set_state_dict = fleet.set_state_dict
shrink = fleet.shrink


def worker_endpoints(to_string=False):
    eps = fleet.worker_endpoints
    return ",".join(eps) if to_string else eps


def server_num():
    return fleet.server_num


def server_index():
    return fleet.server_index


def server_endpoints(to_string=False):
    eps = fleet.server_endpoints
    return ",".join(eps) if to_string else eps


util = fleet.util  # instance attribute, reference spelling fleet.util.xxx


def worker_num():
    return fleet.worker_num


class UserDefinedRoleMaker:
    def __init__(self, *args, **kwargs):
        pass


class PaddleCloudRoleMaker:
    def __init__(self, is_collective=True, **kwargs):
        self._is_collective = is_collective
