"""paddle_tpu.distributed.fleet (mirrors paddle.distributed.fleet)."""
from .base.distributed_strategy import DistributedStrategy  # noqa: F401
from .base.fleet_base import Fleet, fleet  # noqa: F401
from .base.topology import CommunicateTopology, HybridCommunicateGroup, ParallelMode  # noqa: F401
from . import meta_parallel  # noqa: F401
from .utils.recompute import recompute  # noqa: F401

# module-level facade functions (reference fleet/__init__.py re-exports)
init = fleet.init
distributed_model = fleet.distributed_model
distributed_optimizer = fleet.distributed_optimizer
get_hybrid_communicate_group = fleet.get_hybrid_communicate_group
get_mesh = fleet.get_mesh
minimize = fleet.minimize
worker_index = fleet.worker_index
is_first_worker = fleet.is_first_worker
barrier_worker = fleet.barrier_worker
is_server = fleet.is_server
is_worker = fleet.is_worker
stop_worker = fleet.stop_worker


def worker_num():
    return fleet.worker_num


class UserDefinedRoleMaker:
    def __init__(self, *args, **kwargs):
        pass


class PaddleCloudRoleMaker:
    def __init__(self, is_collective=True, **kwargs):
        self._is_collective = is_collective
