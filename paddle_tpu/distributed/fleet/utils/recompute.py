"""Activation recomputation.

Parity: reference fleet/utils/recompute.py:63 (RecomputeFunction PyLayer —
stash inputs, replay rng, re-forward in backward). TPU-native: this is
exactly jax.checkpoint (rematerialization), which XLA schedules better than
a hand-rolled replay. The eager path wraps it through apply_op so
`loss.backward()` sees one fused node whose vjp recomputes the forward.

When ``function`` is a Layer, its parameters are threaded through the
checkpointed function as differentiable arguments (a closure constant would
be invisible to the tape's vjp).
"""
from __future__ import annotations

import jax

from ....framework.core import Tensor, apply_op, is_grad_enabled
from ....nn.layer.layers import Layer

__all__ = ["recompute"]


def recompute(function, *args, **kwargs):
    kwargs.pop("preserve_rng_state", True)  # jax PRNG keys are explicit

    if not is_grad_enabled():
        return function(*args)

    owner = getattr(function, "__self__", None)
    layer = function if isinstance(function, Layer) else (
        owner if isinstance(owner, Layer) else None)
    # partial-bound layer (SharedLayerDesc forward_func)
    if layer is None and hasattr(function, "func") and hasattr(function, "args"):
        for a in getattr(function, "args", ()):
            if isinstance(a, Layer):
                layer = a
                break

    if layer is None:
        def pure(*arrays):
            tensors = [Tensor(a) for a in arrays]
            out = function(*tensors)
            if isinstance(out, (tuple, list)):
                return tuple(o._data if isinstance(o, Tensor) else o for o in out)
            return out._data if isinstance(out, Tensor) else out

        return apply_op(jax.checkpoint(pure), *args, op_name="recompute")

    param_items = list(layer.named_parameters())
    param_tensors = [p for _, p in param_items]
    n_args = len(args)

    def pure(*arrays):
        arg_arrays = arrays[:n_args]
        param_arrays = arrays[n_args:]
        tensors = [Tensor(a) for a in arg_arrays]
        saved = []
        try:
            for p, arr in zip(param_tensors, param_arrays):
                saved.append(p._data)
                p._data = arr
            out = function(*tensors)
        finally:
            for p, old in zip(param_tensors, saved):
                p._data = old
        if isinstance(out, (tuple, list)):
            return tuple(o._data if isinstance(o, Tensor) else o for o in out)
        return out._data if isinstance(out, Tensor) else out

    return apply_op(jax.checkpoint(pure), *args, *param_tensors, op_name="recompute")
