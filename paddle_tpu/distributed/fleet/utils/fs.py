"""Filesystem abstraction (reference fleet/utils/fs.py:119 LocalFS,
HDFSClient): checkpoint managers and dataset file lists go through this
interface. LocalFS is fully implemented; HDFSClient shells out to a
configured ``hadoop`` binary exactly like the reference, and raises a
clear error when none exists (zero-egress environments have no HDFS).
"""
from __future__ import annotations

import os
import shutil
import subprocess

__all__ = ["FS", "LocalFS", "HDFSClient", "FSFileExistsError",
           "FSFileNotExistsError"]


class FSFileExistsError(Exception):
    pass


class FSFileNotExistsError(Exception):
    pass


class FS:
    def ls_dir(self, fs_path):
        raise NotImplementedError

    def is_file(self, fs_path):
        raise NotImplementedError

    def is_dir(self, fs_path):
        raise NotImplementedError

    def is_exist(self, fs_path):
        raise NotImplementedError

    def upload(self, local_path, fs_path):
        raise NotImplementedError

    def download(self, fs_path, local_path):
        raise NotImplementedError

    def mkdirs(self, fs_path):
        raise NotImplementedError

    def delete(self, fs_path):
        raise NotImplementedError

    def need_upload_download(self):
        raise NotImplementedError

    def rename(self, fs_src_path, fs_dst_path):
        raise NotImplementedError

    def mv(self, fs_src_path, fs_dst_path, overwrite=False,
           test_exists=False):
        raise NotImplementedError

    def list_dirs(self, fs_path):
        raise NotImplementedError

    def touch(self, fs_path, exist_ok=True):
        raise NotImplementedError

    def cat(self, fs_path=None):
        raise NotImplementedError


class LocalFS(FS):
    """Local filesystem (reference fs.py:119): same (dirs, files) ls_dir
    contract, exist-checked mv, recursive delete."""

    def ls_dir(self, fs_path):
        if not self.is_exist(fs_path):
            return [], []
        dirs, files = [], []
        for entry in os.listdir(fs_path):
            if os.path.isdir(os.path.join(fs_path, entry)):
                dirs.append(entry)
            else:
                files.append(entry)
        return dirs, files

    def is_file(self, fs_path):
        return os.path.isfile(fs_path)

    def is_dir(self, fs_path):
        return os.path.isdir(fs_path)

    def is_exist(self, fs_path):
        return os.path.exists(fs_path)

    def mkdirs(self, fs_path):
        os.makedirs(fs_path, exist_ok=True)

    def delete(self, fs_path):
        if self.is_dir(fs_path):
            shutil.rmtree(fs_path)
        elif self.is_file(fs_path):
            os.remove(fs_path)

    def need_upload_download(self):
        return False

    def rename(self, fs_src_path, fs_dst_path):
        os.rename(fs_src_path, fs_dst_path)

    def mv(self, src_path, dst_path, overwrite=False, test_exists=False):
        if not self.is_exist(src_path):
            raise FSFileNotExistsError(src_path)
        if not overwrite and self.is_exist(dst_path):
            raise FSFileExistsError(dst_path)
        if overwrite and self.is_exist(dst_path):
            self.delete(dst_path)
        os.rename(src_path, dst_path)

    def list_dirs(self, fs_path):
        return self.ls_dir(fs_path)[0]

    def touch(self, fs_path, exist_ok=True):
        if self.is_exist(fs_path):
            if not exist_ok:
                raise FSFileExistsError(fs_path)
            return
        with open(fs_path, "a"):
            pass

    def cat(self, fs_path=None):
        with open(fs_path, "r") as f:
            return f.read()

    def upload(self, local_path, fs_path):
        shutil.copy(local_path, fs_path)

    def download(self, fs_path, local_path):
        shutil.copy(fs_path, local_path)


class HDFSClient(FS):
    """HDFS client with two transports:

    1. hadoop CLI (reference fs.py HDFSClient shells `hadoop fs -<cmd>`
       the same way) when a hadoop binary is available, and
    2. the WebHDFS REST API (public Hadoop spec, /webhdfs/v1) when only
       an endpoint is configured — `configs={"webhdfs_url":
       "http://namenode:9870", "user": "..."}`. This is the TPU-native
       path: pod workers usually have network reach to the namenode but
       no hadoop JRE install, so state-of-the-cluster queries and
       checkpoint upload/download ride plain HTTP.

    With neither transport configured, every call raises with that
    reason (zero-egress environments have no HDFS).
    """

    def __init__(self, hadoop_home=None, configs=None, time_out=5 * 60 * 1000,
                 sleep_inter=1000):
        self._hadoop = os.path.join(hadoop_home, "bin/hadoop") \
            if hadoop_home else shutil.which("hadoop")
        self._configs = configs or {}
        self._webhdfs = (self._configs.get("webhdfs_url") or "").rstrip("/")
        self._user = self._configs.get("user")
        self._timeout = max(1.0, float(time_out) / 1000.0)

    # -- transport selection ------------------------------------------------
    def _use_rest(self) -> bool:
        if self._hadoop and os.path.exists(self._hadoop):
            return False
        if self._webhdfs:
            return True
        # distinct type: predicate methods must NOT swallow this into
        # a False answer (a checkpoint manager would silently restart)
        raise FileNotFoundError(
            "HDFSClient needs a hadoop binary (hadoop_home=...) or a "
            "WebHDFS endpoint (configs={'webhdfs_url': ...}); neither is "
            "available in this environment — use LocalFS, or mount the "
            "checkpoint directory")

    def _run(self, *args):
        self._use_rest()  # raises when no transport at all
        cfg = []
        for k, v in self._configs.items():
            if k in ("webhdfs_url", "user"):
                continue
            cfg += ["-D", f"{k}={v}"]
        out = subprocess.run([self._hadoop, "fs", *cfg, *args],
                             capture_output=True, text=True)
        if out.returncode != 0:
            raise RuntimeError(out.stderr.strip())
        return out.stdout

    # -- WebHDFS REST -------------------------------------------------------
    def _rest_url(self, fs_path, op, **params):
        from urllib.parse import quote, urlencode

        if not fs_path.startswith("/"):
            fs_path = "/" + fs_path
        q = {"op": op}
        if self._user:
            q["user.name"] = self._user
        q.update(params)
        return (f"{self._webhdfs}/webhdfs/v1{quote(fs_path)}"
                f"?{urlencode(q)}")

    def _rest(self, method, fs_path, op, data=None, ok404=False,
              expect_true=False, data_len=None, **params):
        """One WebHDFS call; returns the parsed JSON body (or raw bytes
        for OPEN). 404 returns None when ok404 (existence probes).
        expect_true: ops whose success signal is a {"boolean": true} BODY
        (RENAME/MKDIRS/DELETE) raise on false — HTTP 200 alone does NOT
        mean the operation happened (a silently-failed checkpoint rename
        would otherwise report success)."""
        import json as _json
        import urllib.error
        import urllib.request

        url = self._rest_url(fs_path, op, **params)
        # CREATE two-step per the spec: the FIRST namenode PUT carries no
        # body (it only fetches the datanode redirect); the data goes
        # once, to the redirect target
        first_data = None if (method == "PUT" and op == "CREATE") else data
        req = urllib.request.Request(url, data=first_data, method=method)
        try:
            with urllib.request.urlopen(req, timeout=self._timeout) as r:
                body = r.read()
        except urllib.error.HTTPError as e:
            if e.code == 404 and ok404:
                return None
            if e.code == 307 and method == "PUT":
                # urllib does not auto-redirect PUTs. data may be a
                # file-like object (streamed upload) — then data_len sets
                # an explicit Content-Length so the body is not buffered
                loc = e.headers.get("Location")
                req2 = urllib.request.Request(
                    loc, data=b"" if data is None else data, method="PUT")
                if data_len is not None:
                    req2.add_header("Content-Length", str(data_len))
                with urllib.request.urlopen(req2,
                                            timeout=self._timeout) as r2:
                    body = r2.read()
                return _json.loads(body) if body else {}
            raise self._rest_error(op, fs_path, e) from e
        if method == "PUT" and op == "CREATE" and data:
            # the server consumed CREATE WITHOUT redirecting (HttpFS/Knox
            # gateway front-ends do this) — our body-free first PUT means
            # the bytes were never sent; resend WITH the body rather than
            # silently leaving a 0-byte file
            if hasattr(data, "seek"):
                data.seek(0)
            req3 = urllib.request.Request(url, data=data, method="PUT")
            if data_len is not None:
                req3.add_header("Content-Length", str(data_len))
            with urllib.request.urlopen(req3, timeout=self._timeout) as r3:
                body = r3.read()
        if op == "OPEN":
            return body
        out = _json.loads(body) if body else {}
        if expect_true and out.get("boolean") is False:
            raise RuntimeError(
                f"WebHDFS {op} {fs_path}: server answered boolean=false "
                f"(operation did not happen)")
        return out

    @staticmethod
    def _rest_error(op, fs_path, e):
        """Structured WebHDFS error: carries the HTTP code and the parsed
        RemoteException class so callers can classify exactly instead of
        substring-matching the message."""
        import json as _json

        raw = e.read()[:500]
        exc_name = ""
        try:
            exc_name = _json.loads(raw)["RemoteException"]["exception"]
        except Exception:  # noqa: BLE001 — non-JSON error page
            pass
        err = RuntimeError(
            f"WebHDFS {op} {fs_path}: HTTP {e.code} {exc_name or raw!r}")
        err.http_code = e.code
        err.remote_exception = exc_name
        return err

    def _rest_status(self, fs_path):
        out = self._rest("GET", fs_path, "GETFILESTATUS", ok404=True)
        return None if out is None else out["FileStatus"]

    def ls_dir(self, fs_path):
        if self._use_rest():
            # NO ok404: the CLI transport raises for a missing path — the
            # two transports must agree, or misconfigured checkpoint dirs
            # read as "no checkpoints" and auto-resume silently restarts
            out = self._rest("GET", fs_path, "LISTSTATUS")
            dirs, files = [], []
            for st in out["FileStatuses"]["FileStatus"]:
                (dirs if st["type"] == "DIRECTORY"
                 else files).append(st["pathSuffix"])
            return dirs, files
        lines = self._run("-ls", fs_path).splitlines()
        dirs, files = [], []
        for ln in lines:
            parts = ln.split()
            if len(parts) < 8:
                continue
            name = os.path.basename(parts[-1])
            (dirs if parts[0].startswith("d") else files).append(name)
        return dirs, files

    def is_exist(self, fs_path):
        if self._use_rest():
            return self._rest_status(fs_path) is not None
        try:
            self._run("-test", "-e", fs_path)
            return True
        except RuntimeError:
            return False

    def is_dir(self, fs_path):
        if self._use_rest():
            st = self._rest_status(fs_path)
            return st is not None and st["type"] == "DIRECTORY"
        try:
            self._run("-test", "-d", fs_path)
            return True
        except RuntimeError:
            return False

    def is_file(self, fs_path):
        return self.is_exist(fs_path) and not self.is_dir(fs_path)

    def mkdirs(self, fs_path):
        if self._use_rest():
            self._rest("PUT", fs_path, "MKDIRS", expect_true=True)
            return
        self._run("-mkdir", "-p", fs_path)

    def delete(self, fs_path):
        if self._use_rest():
            self._rest("DELETE", fs_path, "DELETE", recursive="true")
            return
        self._run("-rm", "-r", "-f", fs_path)

    def upload(self, local_path, fs_path):
        if self._use_rest():
            # streamed: the namenode PUT carries no body (spec step 1);
            # the redirected datanode PUT takes the open FILE OBJECT with
            # an explicit Content-Length, so a multi-GB checkpoint never
            # sits in host memory (mirrors download()'s copyfileobj)
            size = os.path.getsize(local_path)
            with open(local_path, "rb") as f:
                self._rest("PUT", fs_path, "CREATE", data=f,
                           data_len=size, overwrite="true")
            return
        self._run("-put", local_path, fs_path)

    def download(self, fs_path, local_path):
        if self._use_rest():
            import shutil as _sh
            import urllib.request

            req = urllib.request.Request(
                self._rest_url(fs_path, "OPEN"), method="GET")
            with urllib.request.urlopen(req, timeout=self._timeout) as r, \
                    open(local_path, "wb") as f:
                _sh.copyfileobj(r, f)          # streamed, not buffered
            return
        self._run("-get", fs_path, local_path)

    def need_upload_download(self):
        return True

    def mv(self, fs_src_path, fs_dst_path, overwrite=False,
           test_exists=False):
        if test_exists and not self.is_exist(fs_src_path):
            raise FSFileNotExistsError(fs_src_path)
        if overwrite and self.is_exist(fs_dst_path):
            self.delete(fs_dst_path)
        elif not overwrite and self.is_exist(fs_dst_path):
            raise FSFileExistsError(fs_dst_path)
        if self._use_rest():
            dst = fs_dst_path if fs_dst_path.startswith("/") \
                else "/" + fs_dst_path
            self._rest("PUT", fs_src_path, "RENAME", destination=dst,
                       expect_true=True)
            return
        self._run("-mv", fs_src_path, fs_dst_path)

    def list_dirs(self, fs_path):
        return self.ls_dir(fs_path)[0]

    def touch(self, fs_path, exist_ok=True):
        # mirror LocalFS.touch: -touchz would truncate an existing
        # zero-length file (and error on a non-empty one), so an existing
        # path returns or raises per exist_ok instead
        if self.is_exist(fs_path):
            if exist_ok:
                return
            raise FSFileExistsError(fs_path)
        if self._use_rest():
            try:
                self._rest("PUT", fs_path, "CREATE", data=b"",
                           overwrite="false")
            except RuntimeError as e:
                # check-then-create race: another worker created the file
                # between our probe and the CREATE — with exist_ok that IS
                # the requested end state. Classified STRUCTURALLY (the
                # parsed RemoteException class / HTTP 403), never by
                # message substring.
                if exist_ok and (
                        getattr(e, "remote_exception", "")
                        == "FileAlreadyExistsException"
                        or getattr(e, "http_code", None) == 403):
                    return
                raise
            return
        self._run("-touchz", fs_path)

    def cat(self, fs_path=None):
        if self._use_rest():
            return self._rest("GET", fs_path, "OPEN").decode(
                "utf-8", errors="replace")
        return self._run("-cat", fs_path)
