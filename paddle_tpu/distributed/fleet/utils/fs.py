"""Filesystem abstraction (reference fleet/utils/fs.py:119 LocalFS,
HDFSClient): checkpoint managers and dataset file lists go through this
interface. LocalFS is fully implemented; HDFSClient shells out to a
configured ``hadoop`` binary exactly like the reference, and raises a
clear error when none exists (zero-egress environments have no HDFS).
"""
from __future__ import annotations

import os
import shutil
import subprocess

__all__ = ["FS", "LocalFS", "HDFSClient", "FSFileExistsError",
           "FSFileNotExistsError"]


class FSFileExistsError(Exception):
    pass


class FSFileNotExistsError(Exception):
    pass


class FS:
    def ls_dir(self, fs_path):
        raise NotImplementedError

    def is_file(self, fs_path):
        raise NotImplementedError

    def is_dir(self, fs_path):
        raise NotImplementedError

    def is_exist(self, fs_path):
        raise NotImplementedError

    def upload(self, local_path, fs_path):
        raise NotImplementedError

    def download(self, fs_path, local_path):
        raise NotImplementedError

    def mkdirs(self, fs_path):
        raise NotImplementedError

    def delete(self, fs_path):
        raise NotImplementedError

    def need_upload_download(self):
        raise NotImplementedError

    def rename(self, fs_src_path, fs_dst_path):
        raise NotImplementedError

    def mv(self, fs_src_path, fs_dst_path, overwrite=False,
           test_exists=False):
        raise NotImplementedError

    def list_dirs(self, fs_path):
        raise NotImplementedError

    def touch(self, fs_path, exist_ok=True):
        raise NotImplementedError

    def cat(self, fs_path=None):
        raise NotImplementedError


class LocalFS(FS):
    """Local filesystem (reference fs.py:119): same (dirs, files) ls_dir
    contract, exist-checked mv, recursive delete."""

    def ls_dir(self, fs_path):
        if not self.is_exist(fs_path):
            return [], []
        dirs, files = [], []
        for entry in os.listdir(fs_path):
            if os.path.isdir(os.path.join(fs_path, entry)):
                dirs.append(entry)
            else:
                files.append(entry)
        return dirs, files

    def is_file(self, fs_path):
        return os.path.isfile(fs_path)

    def is_dir(self, fs_path):
        return os.path.isdir(fs_path)

    def is_exist(self, fs_path):
        return os.path.exists(fs_path)

    def mkdirs(self, fs_path):
        os.makedirs(fs_path, exist_ok=True)

    def delete(self, fs_path):
        if self.is_dir(fs_path):
            shutil.rmtree(fs_path)
        elif self.is_file(fs_path):
            os.remove(fs_path)

    def need_upload_download(self):
        return False

    def rename(self, fs_src_path, fs_dst_path):
        os.rename(fs_src_path, fs_dst_path)

    def mv(self, src_path, dst_path, overwrite=False, test_exists=False):
        if not self.is_exist(src_path):
            raise FSFileNotExistsError(src_path)
        if not overwrite and self.is_exist(dst_path):
            raise FSFileExistsError(dst_path)
        if overwrite and self.is_exist(dst_path):
            self.delete(dst_path)
        os.rename(src_path, dst_path)

    def list_dirs(self, fs_path):
        return self.ls_dir(fs_path)[0]

    def touch(self, fs_path, exist_ok=True):
        if self.is_exist(fs_path):
            if not exist_ok:
                raise FSFileExistsError(fs_path)
            return
        with open(fs_path, "a"):
            pass

    def cat(self, fs_path=None):
        with open(fs_path, "r") as f:
            return f.read()

    def upload(self, local_path, fs_path):
        shutil.copy(local_path, fs_path)

    def download(self, fs_path, local_path):
        shutil.copy(fs_path, local_path)


class HDFSClient(FS):
    """HDFS via the hadoop CLI (reference fs.py HDFSClient shells
    `hadoop fs -<cmd>` the same way). Requires a hadoop binary; absent
    one (this zero-egress image), every call raises with that reason.
    """

    def __init__(self, hadoop_home=None, configs=None, time_out=5 * 60 * 1000,
                 sleep_inter=1000):
        self._hadoop = os.path.join(hadoop_home, "bin/hadoop") \
            if hadoop_home else shutil.which("hadoop")
        self._configs = configs or {}

    def _run(self, *args):
        if not self._hadoop or not os.path.exists(self._hadoop):
            # distinct type: predicate methods must NOT swallow this into
            # a False answer (a checkpoint manager would silently restart)
            raise FileNotFoundError(
                "HDFSClient needs a hadoop binary (hadoop_home=...); none "
                "is available in this environment — use LocalFS, or mount "
                "the checkpoint directory")
        cfg = []
        for k, v in self._configs.items():
            cfg += ["-D", f"{k}={v}"]
        out = subprocess.run([self._hadoop, "fs", *cfg, *args],
                             capture_output=True, text=True)
        if out.returncode != 0:
            raise RuntimeError(out.stderr.strip())
        return out.stdout

    def ls_dir(self, fs_path):
        lines = self._run("-ls", fs_path).splitlines()
        dirs, files = [], []
        for ln in lines:
            parts = ln.split()
            if len(parts) < 8:
                continue
            name = os.path.basename(parts[-1])
            (dirs if parts[0].startswith("d") else files).append(name)
        return dirs, files

    def is_exist(self, fs_path):
        try:
            self._run("-test", "-e", fs_path)
            return True
        except RuntimeError:
            return False

    def is_dir(self, fs_path):
        try:
            self._run("-test", "-d", fs_path)
            return True
        except RuntimeError:
            return False

    def is_file(self, fs_path):
        return self.is_exist(fs_path) and not self.is_dir(fs_path)

    def mkdirs(self, fs_path):
        self._run("-mkdir", "-p", fs_path)

    def delete(self, fs_path):
        self._run("-rm", "-r", "-f", fs_path)

    def upload(self, local_path, fs_path):
        self._run("-put", local_path, fs_path)

    def download(self, fs_path, local_path):
        self._run("-get", fs_path, local_path)

    def need_upload_download(self):
        return True

    def mv(self, fs_src_path, fs_dst_path, overwrite=False,
           test_exists=False):
        if test_exists and not self.is_exist(fs_src_path):
            raise FSFileNotExistsError(fs_src_path)
        if overwrite and self.is_exist(fs_dst_path):
            self.delete(fs_dst_path)
        elif not overwrite and self.is_exist(fs_dst_path):
            raise FSFileExistsError(fs_dst_path)
        self._run("-mv", fs_src_path, fs_dst_path)

    def list_dirs(self, fs_path):
        return self.ls_dir(fs_path)[0]

    def touch(self, fs_path, exist_ok=True):
        # mirror LocalFS.touch: -touchz would truncate an existing
        # zero-length file (and error on a non-empty one), so an existing
        # path returns or raises per exist_ok instead
        if self.is_exist(fs_path):
            if exist_ok:
                return
            raise FSFileExistsError(fs_path)
        self._run("-touchz", fs_path)

    def cat(self, fs_path=None):
        return self._run("-cat", fs_path)
