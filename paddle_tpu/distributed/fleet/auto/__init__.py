"""fleet.auto — cost-model hybrid-parallel planner (ISSUE 9).

The reference's headline Fleet capability is hybrid parallelism from one
config: dp x mp x pp x ZeRO (PAPER.md layer map `distributed/fleet`,
`auto_parallel`). This package is the subsystem that PICKS the config:

- :mod:`.cost_model` — closed-form per-candidate estimates: per-device HBM
  (params/grads/optimizer state under ZeRO-0/1/2/3, pipeline/TP splits,
  activation working set), pipeline bubble fraction ``(S-1)/T``, and
  collective bytes per step; plus the legal-candidate enumerator.
- :mod:`.planner` — :func:`plan` ranks the candidates (fastest estimated
  step among those that fit per-chip HBM) into a :class:`ParallelPlan`
  (mesh dims over ("data","sharding","pipe","model"), microbatch count,
  ZeRO level, remat/schedule policy); :func:`explain` prints the ranked
  table of the latest plan.
- :mod:`.zero` — :class:`ShardedOptimizer`: ZeRO-2/3 as a first-class
  optimizer wrapper consumed by ``parallel.DistributedTrainStep``
  (reduce-scatter grads / 1-Nth-sharded moments and params expressed as
  PartitionSpecs; XLA inserts the collectives).

Activation: ``fleet.init(strategy={"auto": True})`` defers the mesh to the
first engine build, where the planner sees the model; unmodified hapi /
fleet scripts then train under the chosen plan (pipeline microbatching
runs the in-jit 1F1B schedule of ``parallel.pipeline.pipeline_1f1b``).

Everything in this package runs at trace-build time on the host — no
device arrays, no jit sinks (pinned by tests/test_fleet_auto.py).
"""
from .cost_model import (HardwareSpec, ModelStats, PlanCandidate,  # noqa: F401
                         enumerate_plans, estimate)
from .planner import ParallelPlan, explain, last_plan, plan  # noqa: F401
from .resize import replan_for_devices  # noqa: F401
from .zero import ShardedOptimizer  # noqa: F401

__all__ = ["HardwareSpec", "ModelStats", "PlanCandidate", "enumerate_plans",
           "estimate", "ParallelPlan", "plan", "explain", "last_plan",
           "ShardedOptimizer", "replan_for_devices"]
