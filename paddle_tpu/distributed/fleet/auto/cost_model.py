"""Analytic cost model for hybrid-parallel plan selection.

The reference's auto-parallel planner searches dist-attr assignments with a
cluster cost model (reference python/paddle/distributed/auto_parallel/
cost_model.py — op FLOPs + tensor-transfer times over the cluster graph).
Here the search space is the four Fleet mesh axes plus the ZeRO level and
microbatch count, and every estimate is a closed-form expression over the
model's byte/FLOP totals — the whole model runs at TRACE-BUILD time on the
host (no device work, no jax arrays), so the planner can evaluate hundreds
of candidates in microseconds before the first program is compiled.

Per-candidate estimates (all per DEVICE, the binding resource):

- HBM bytes. Params split over "pipe" (layer-stacked leaves / pp) and
  "model" (the TP-annotated fraction / mp); ZeRO-3 additionally splits
  storage over "sharding". Gradients mirror params, ZeRO-2 splits them.
  Optimizer state (AdamW m+v, fp32) mirrors params and splits at ZeRO-1+
  (Rajbhandari et al., ZeRO 2020: levels 1/2/3 = optimizer state /
  +gradients / +parameters partitioned 1/Nth).
- Pipeline bubble fraction ``(S-1)/T`` with ``T = n_micro + S - 1``
  schedule ticks (GPipe fill/drain and the lockstep 1F1B variant share
  the same tick count per pass; Narayanan et al. 2021 eq. 1).
- Collective bytes per step: dp gradient all-reduce (ring: 2(N-1)/N of
  the replica's grad bytes), ZeRO-2/3 reduce-scatter + all-gather, TP
  per-layer activation all-reduces, pipeline stage-boundary transfers.
- A scalar time score — compute seconds inflated by the bubble, plus
  collective seconds — used ONLY for ranking candidates that fit.
"""
from __future__ import annotations

import dataclasses
from typing import Any, Dict, List, Optional, Sequence, Tuple

__all__ = ["ModelStats", "PlanCandidate", "HardwareSpec", "enumerate_plans",
           "estimate"]


@dataclasses.dataclass
class HardwareSpec:
    """Per-chip capabilities used to turn bytes/FLOPs into a rank score.

    Defaults describe a TPU v5e chip; the values only order candidates —
    any figures of the right magnitude rank dp-vs-pp-vs-ZeRO trade-offs
    correctly on any recent accelerator.
    """

    hbm_bytes: int = 16 * 2 ** 30          # 16 GB
    peak_flops: float = 197e12             # bf16 MXU
    ici_bandwidth: float = 4.5e10          # bytes/s per link, all-reduce eff.
    hbm_fudge: float = 0.90                # usable fraction (XLA reserves)


@dataclasses.dataclass
class ModelStats:
    """Byte/FLOP totals the cost model needs — derivable from any param
    pytree; no device arrays are touched."""

    param_bytes: int                # total parameter storage bytes
    n_params: int                   # scalar parameter count
    layer_bytes: int                # bytes in layer-stackable leaves (pp-splittable)
    tp_bytes: int = 0               # bytes annotated over "model" (mp-splittable)
    layers: int = 1                 # pipeline-stackable depth
    hidden: int = 0                 # activation width (0 = unknown)
    seq_len: int = 1                # tokens per sample
    act_dtype_bytes: int = 4
    opt_state_bytes_per_param: int = 8   # AdamW fp32 m+v
    grad_dtype_bytes: int = 4
    # embedding-table placement term (paddle_tpu.sparse): the table is
    # NOT part of param_bytes — it follows its own rules (replicates, or
    # row-shards over "model"; sparse grads are SelectedRows-bounded, so
    # no dense grad or full-row optimizer traffic). Zero rows = no table.
    table_rows: int = 0             # logical rows of the sharded table(s)
    table_dim: int = 0              # embedding width
    table_dtype_bytes: int = 4
    table_lookups_per_sample: int = 0   # ids resolved per sample per step
    # mixture-of-experts placement term (ISSUE 18, nn/moe.py): expert
    # weights follow the table precedent — NOT part of param_bytes, they
    # ride their own fields and either replicate (ep=1) or shard over
    # the ep slice of the "model" axis. moe_expert_params counts every
    # expert FFN scalar across all MoE layers; the router (H·E per
    # layer — noise at this resolution) is not counted anywhere.
    # Zero experts = dense model.
    moe_experts: int = 0            # experts per MoE layer (E)
    moe_expert_params: int = 0      # expert FFN scalars, all MoE layers
    moe_expert_dtype_bytes: int = 4
    moe_layers: int = 0             # number of MoE blocks
    moe_top_k: int = 2
    moe_capacity_factor: float = 1.25

    @classmethod
    def from_params(cls, params, specs=None, layers: Optional[int] = None,
                    hidden: int = 0, seq_len: int = 1) -> "ModelStats":
        """Derive stats from a param pytree (+ optional PartitionSpec tree).

        Layer-stackable bytes: leaves whose leading dim equals ``layers``
        (explicit, or inferred as the most common leading dim > 1 among
        multi-dim leaves — the gpt_init "blocks" layout). TP bytes: leaves
        whose spec mentions the "model" axis. A gpt-layout ``"moe"``
        subtree (w_in (Lm, E, H, M), …) is pulled OUT of param_bytes into
        the moe_expert_* fields (expert weights place like the embedding
        table: their own rules, their own term).
        """
        import jax
        import numpy as np

        moe_kw: Dict[str, Any] = {}
        if isinstance(params, dict) and isinstance(params.get("moe"), dict) \
                and hasattr(params["moe"].get("w_in"), "shape"):
            moe = params["moe"]
            params = {k: v for k, v in params.items() if k != "moe"}
            if specs is not None and isinstance(specs, dict):
                specs = {k: v for k, v in specs.items() if k != "moe"}
            expert = [v for k, v in moe.items() if k != "router_w"
                      and hasattr(v, "shape")]
            moe_kw = dict(
                moe_experts=int(moe["w_in"].shape[1]),
                moe_layers=int(moe["w_in"].shape[0]),
                moe_expert_params=sum(
                    int(np.prod(v.shape) or 1) for v in expert),
                moe_expert_dtype_bytes=int(
                    np.dtype(moe["w_in"].dtype).itemsize))

        leaves = [x for x in jax.tree_util.tree_leaves(params)
                  if hasattr(x, "shape")]
        shapes = [tuple(x.shape) for x in leaves]
        sizes = [int(np.prod(s) or 1) for s in shapes]
        itemsize = [int(getattr(getattr(x, "dtype", np.float32), "itemsize",
                                None) or np.dtype(x.dtype).itemsize)
                    for x in leaves]
        total = sum(n * b for n, b in zip(sizes, itemsize))
        n_params = sum(sizes)
        if layers is None:
            lead = [s[0] for s in shapes if len(s) >= 2 and s[0] > 1]
            layers = max(set(lead), key=lead.count) if lead else 1
        layer_bytes = sum(n * b for s, n, b in zip(shapes, sizes, itemsize)
                          if s and s[0] == layers and layers > 1)
        tp_bytes = 0
        if specs is not None:
            spec_leaves = jax.tree_util.tree_leaves(
                specs, is_leaf=lambda s: hasattr(s, "index") and not
                hasattr(s, "shape"))
            if len(spec_leaves) == len(leaves):
                tp_bytes = sum(
                    n * b for sp, n, b in zip(spec_leaves, sizes, itemsize)
                    if "model" in str(sp))
        if not hidden:
            # widest trailing dim of a 2-D+ leaf approximates the stream width
            cand = [s[-1] for s in shapes if len(s) >= 2]
            hidden = max(cand) if cand else 0
        return cls(param_bytes=total, n_params=n_params,
                   layer_bytes=layer_bytes, tp_bytes=tp_bytes,
                   layers=int(layers), hidden=int(hidden),
                   seq_len=int(seq_len), **moe_kw)


@dataclasses.dataclass
class PlanCandidate:
    dp: int
    sharding: int
    pp: int
    mp: int
    n_micro: int
    zero: int
    # expert parallelism (ISSUE 18): experts shard over the "model" axis
    # alongside TP, so the physical axis degree is max(mp, ep) and ep>1
    # is legal only when mp is 1 or equal to ep (one axis, one degree)
    ep: int = 1
    remat: bool = True
    # filled by estimate():
    hbm_bytes: int = 0
    hbm_detail: Dict[str, int] = dataclasses.field(default_factory=dict)
    bubble_frac: float = 0.0
    coll_bytes: int = 0
    a2a_bytes: int = 0              # MoE dispatch AllToAll share of coll
    score: float = float("inf")
    fits: bool = False
    why: str = ""

    @property
    def model_degree(self) -> int:
        """Physical size of the "model" mesh axis (TP and EP share it)."""
        return max(self.mp, self.ep)

    @property
    def dims(self) -> Dict[str, int]:
        return {"data": self.dp, "sharding": self.sharding,
                "pipe": self.pp, "model": self.model_degree}

    def describe(self) -> str:
        ep = f" ep={self.ep}" if self.ep > 1 else ""
        return (f"dp={self.dp} sh={self.sharding} pp={self.pp} "
                f"mp={self.mp}{ep} micro={self.n_micro} zero={self.zero}")


def _divisors(n: int) -> List[int]:
    return [d for d in range(1, n + 1) if n % d == 0]


def enumerate_plans(n_devices: int, global_batch: int,
                    stats: ModelStats,
                    zero_levels: Sequence[int] = (0, 1, 2, 3),
                    allow_mp: bool = False,
                    max_micro: int = 64,
                    constraints: Optional[Dict[str, int]] = None
                    ) -> List[PlanCandidate]:
    """All LEGAL (dp, sharding, pp, mp, n_micro, zero) tuples for the
    device count.

    Legality (the reference's topology checks, fleet_base
    _init_hybrid_parallel_env):
    - dp * sharding * pp * mp == n_devices;
    - layers % pp == 0 (SegmentLayers uniform split);
    - global_batch % (dp * sharding * n_micro) == 0 (integral microbatch);
    - n_micro >= pp (fewer microbatches than stages idles the pipe);
    - mp > 1 only with TP-annotated params (allow_mp) and hidden % mp == 0;
    - ep > 1 only with experts (stats.moe_experts), ep | moe_experts
      (whole experts per shard), and mp in {1, ep} — TP and EP ride the
      SAME "model" axis (degree max(mp, ep)), so mixed degrees would
      need a fifth axis this mesh does not have;
    - zero > 0 only when the "sharding" axis exists (degree > 1).
    ``constraints`` pins any of dp/sharding/pp/mp/ep/n_micro/zero.
    """
    cons = dict(constraints or {})
    out: List[PlanCandidate] = []
    for pp in _divisors(n_devices):
        if cons.get("pp", pp) != pp:
            continue
        if stats.layers % pp != 0 or (pp > 1 and stats.layers < pp):
            continue
        for mp in _divisors(n_devices // pp):
            if cons.get("mp", mp) != mp:
                continue
            if mp > 1 and not allow_mp:
                continue
            # hidden divisibility binds only the TP-annotated matmuls;
            # a row-sharded embedding table has no such constraint
            if mp > 1 and stats.tp_bytes and stats.hidden \
                    and stats.hidden % mp != 0:
                continue
            if mp > 1 and stats.table_rows and stats.table_rows < mp:
                continue  # fewer rows than shards: empty shards
            ep_choices = [1]
            if stats.moe_experts > 0:
                ep_choices = [e for e in _divisors(n_devices // pp)
                              if e == 1 or (stats.moe_experts % e == 0
                                            and mp in (1, e))]
            for ep in ep_choices:
                if cons.get("ep", ep) != ep:
                    continue
                md = max(mp, ep)
                if (n_devices // pp) % md != 0:
                    continue
                _emit(out, cons, n_devices, global_batch, stats,
                      zero_levels, max_micro, pp, mp, ep)
    return out


def _emit(out, cons, n_devices, global_batch, stats, zero_levels,
          max_micro, pp, mp, ep):
    """Inner dp/sharding/micro/zero loops for one (pp, mp, ep) shape."""
    rest = n_devices // (pp * max(mp, ep))
    for sh in _divisors(rest):
        if cons.get("sharding", sh) != sh:
            continue
        dp = rest // sh
        if cons.get("dp", dp) != dp:
            continue
        if global_batch % (dp * sh) != 0:
            continue
        per_replica = global_batch // (dp * sh)
        for n_micro in _divisors(min(per_replica, max_micro)):
            if cons.get("n_micro", n_micro) != n_micro:
                continue
            if pp > 1 and n_micro < pp:
                continue
            if pp == 1 and n_micro > 1:
                continue  # microbatching buys nothing without pipe
            for zero in zero_levels:
                if cons.get("zero", zero) != zero:
                    continue
                if zero > 0 and sh <= 1:
                    continue
                out.append(PlanCandidate(
                    dp=dp, sharding=sh, pp=pp, mp=mp, ep=ep,
                    n_micro=n_micro, zero=zero))


def estimate(c: PlanCandidate, stats: ModelStats, global_batch: int,
             hw: HardwareSpec,
             hidden_comm_frac: float = None) -> PlanCandidate:
    """Fill the candidate's HBM/bubble/collective estimates and rank score
    (see module docstring for the formulas). Returns the same object.

    ``hidden_comm_frac``: measured fraction of the grad collective hidden
    inside the backward (``DistributedTrainStep.measure_overlap()``'s
    ``hidden_frac``). None keeps the historical assumption (0.5 credit on
    the dp all-reduce, none on the sharding collective). A measured value
    replaces the dp credit, and — because FLAGS_overlap_zero2 issues the
    ZeRO-2 reduce-scatter in-backward too — credits the reduce-scatter
    HALF of the sharding collective at zero >= 2 (the update-boundary
    all-gather half still cannot hide)."""
    edge_bytes = stats.param_bytes - stats.layer_bytes
    tp_frac = stats.tp_bytes / stats.param_bytes if stats.param_bytes else 0.0

    def split(total_bytes: int) -> float:
        """Per-device share after pipe + model splits (pre-ZeRO)."""
        layer_share = (stats.layer_bytes / stats.param_bytes
                       if stats.param_bytes else 0.0)
        b = total_bytes * (layer_share / c.pp + (1 - layer_share))
        # the TP-annotated fraction additionally splits over "model"
        return b * (1 - tp_frac) + b * tp_frac / c.mp

    params = split(stats.param_bytes)
    if c.zero >= 3:
        params /= c.sharding
    grads = split(stats.n_params * stats.grad_dtype_bytes)
    if c.zero >= 2:
        grads /= c.sharding
    opt = split(stats.n_params * stats.opt_state_bytes_per_param)
    if c.zero >= 1:
        opt /= c.sharding

    # activations: per-device microbatch tokens x hidden, with the 1F1B
    # in-flight ring (2S-1 stage inputs per stage, see pipeline.py) and a
    # remat working-set factor (~2 live layer activations) — coarse on
    # purpose; HBM headroom below absorbs the slack
    micro_bs = max(global_batch // (c.dp * c.sharding * max(c.n_micro, 1)), 1)
    act_token_bytes = max(stats.hidden, 1) * stats.act_dtype_bytes
    in_flight = (2 * c.pp - 1) if c.pp > 1 else 1
    act = micro_bs * stats.seq_len * act_token_bytes * in_flight
    act += micro_bs * stats.seq_len * act_token_bytes * \
        (2 if c.remat else max(stats.layers // c.pp, 1))

    # embedding-table placement (paddle_tpu.sparse): storage + moments
    # row-shard over "model" (mod-sharding — mp=1 means fully
    # replicated); the gradient never densifies, it is bounded by the
    # batch's touched rows (SelectedRows semantics)
    table = 0.0
    batch_ids = stats.table_lookups_per_sample * \
        max(global_batch // (c.dp * c.sharding), 1)
    if stats.table_rows and stats.table_dim:
        table_bytes = stats.table_rows * stats.table_dim * \
            stats.table_dtype_bytes
        table = table_bytes / c.mp
        table += (stats.table_rows * stats.table_dim *
                  stats.opt_state_bytes_per_param) / c.mp
        touched = min(batch_ids, stats.table_rows)
        table += touched * stats.table_dim * stats.grad_dtype_bytes

    # mixture-of-experts placement (ISSUE 18): expert weights + their
    # grads and AdamW moments shard over ep — THE expert-parallel HBM
    # credit (ep=1 replicates, so expert-heavy models that cannot fit
    # replicated experts only fit at ep>1). Experts are deliberately
    # outside the ZeRO terms: the optimizer shards them over "model",
    # not "sharding" (zero.py composes with ep at the axis level).
    moe = 0.0
    if stats.moe_experts and stats.moe_expert_params:
        per_dev = stats.moe_expert_params / c.ep
        moe = per_dev * (stats.moe_expert_dtype_bytes
                         + stats.grad_dtype_bytes
                         + stats.opt_state_bytes_per_param)

    hbm = int(params + grads + opt + act + table + moe)
    c.hbm_detail = {"params": int(params), "grads": int(grads),
                    "opt_state": int(opt), "activations": int(act),
                    "table": int(table), "moe_experts": int(moe)}
    c.hbm_bytes = hbm
    budget = int(hw.hbm_bytes * hw.hbm_fudge)
    c.fits = hbm <= budget
    if not c.fits:
        c.why = f"needs {hbm / 2**20:.2f}M > {budget / 2**20:.2f}M"

    # pipeline bubble: (S-1)/T, T = n_micro + S - 1 ticks per pass
    c.bubble_frac = ((c.pp - 1) / (c.n_micro + c.pp - 1)) if c.pp > 1 else 0.0

    # collective bytes per step (per device)
    replica_grad = split(stats.n_params * stats.grad_dtype_bytes)
    # visible (non-hidden) fraction of the in-backward grad collective:
    # 0.5 assumed historically; a MEASURED hidden_comm_frac (ISSUE 17,
    # measure_overlap) replaces the assumption
    visible = (0.5 if hidden_comm_frac is None
               else 1.0 - max(0.0, min(1.0, float(hidden_comm_frac))))
    coll = 0.0
    if c.dp > 1:
        # ring all-reduce; the hidden share overlaps the remaining
        # backward (FLAGS_overlap_grads; PR-6 measured ~0.5+), which
        # ZeRO's update-boundary all-gather cannot
        coll += visible * 2.0 * replica_grad * (c.dp - 1) / c.dp
    if c.sharding > 1:
        # ZeRO-0/1 all-reduce over the sharding group; 2/3 reduce-scatter
        # + param all-gather (same wire bytes, half the HBM traffic).
        # With a MEASURED overlap and zero >= 2 (FLAGS_overlap_zero2
        # issues the reduce-scatter in-backward), the scatter half earns
        # the same hidden credit; the all-gather half never does.
        shard_bytes = 2.0 * replica_grad * (c.sharding - 1) / c.sharding
        if hidden_comm_frac is not None and c.zero >= 2:
            coll += shard_bytes * (0.5 * visible + 0.5)
        else:
            coll += shard_bytes
        if c.zero >= 3:
            coll += split(stats.param_bytes) * (c.sharding - 1) / c.sharding
    if c.mp > 1 and stats.hidden:
        # Megatron: 2 activation all-reduces per layer per micro pass,
        # forward + backward
        per_layer = micro_bs * stats.seq_len * stats.hidden * \
            stats.act_dtype_bytes
        coll += 4.0 * (stats.layers // c.pp) * c.n_micro * per_layer \
            * (c.mp - 1) / c.mp
    if c.pp > 1:
        # stage-boundary activation rotate, fwd + bwd, per microbatch tick
        coll += 2.0 * c.n_micro * micro_bs * stats.seq_len * act_token_bytes
    if c.mp > 1 and stats.table_rows and stats.table_dim:
        # sharded-lookup all-to-all: each off-shard id ships 4 bytes of
        # id out and dim * dtype bytes of vector back (sparse/embedding.
        # exchange_bytes), twice per step (forward lookup + the grad
        # rows routed home)
        coll += 2.0 * batch_ids * \
            (4 + stats.table_dim * stats.table_dtype_bytes) * \
            (c.mp - 1) / c.mp
    a2a = 0.0
    if c.ep > 1 and stats.moe_layers:
        # MoE dispatch AllToAll (GShard): 2 dispatches (tokens out,
        # expert outputs back) × routed rows × d_model per MoE layer.
        # Routed rows = cf·k·T — the capacity grid ships PADDED, so the
        # capacity factor IS the imbalance term: a perfectly balanced
        # router still pays cf·k copies of every token on the wire.
        # (fwd only: the bwd AllToAll pair overlaps the expert grads
        # the same way the dp all-reduce hides — coarse, rank-stable.)
        tokens = stats.seq_len * max(global_batch // (c.dp * c.sharding), 1)
        routed = stats.moe_capacity_factor * stats.moe_top_k * tokens
        a2a = 2.0 * stats.moe_layers * routed * max(stats.hidden, 1) \
            * stats.act_dtype_bytes * (c.ep - 1) / c.ep
        coll += a2a
    c.a2a_bytes = int(a2a)
    c.coll_bytes = int(coll)

    # mp splits dense compute only when matmuls are TP-annotated; a
    # table-only "model" axis (row-sharded embeddings) leaves the dense
    # math replicated
    mp_compute = c.mp if stats.tp_bytes else 1
    flops = 6.0 * stats.n_params * (global_batch * stats.seq_len) \
        / (c.dp * c.sharding * mp_compute * c.pp)
    if stats.moe_experts and stats.moe_expert_params:
        # expert FFN compute: every routed (capacity-padded) row runs ONE
        # expert's FFN — 6 · (expert_params_all_layers / E) FLOPs per row
        # summed over the MoE layers — and ep splits the capacity grid,
        # so expert compute scales 1/ep exactly like the HBM term
        routed = stats.moe_capacity_factor * stats.moe_top_k \
            * (global_batch * stats.seq_len) \
            / (c.dp * c.sharding * c.ep * c.pp)
        flops += 6.0 * stats.moe_expert_params \
            / max(stats.moe_experts, 1) * routed
    t_compute = flops / hw.peak_flops
    t = t_compute / max(1e-9, 1.0 - c.bubble_frac) + coll / hw.ici_bandwidth
    c.score = t
    return c
