"""fleet.auto planner — pick a complete hybrid-parallel plan from
(model, batch, topology) and make it runnable.

The reference's ``strategy.auto`` routes through its auto-parallel
completion/partitioner stack; here the equivalent artifact is a
:class:`ParallelPlan`: the 4-axis mesh shape, the ZeRO level, the
microbatch count and the remat/schedule policy, all chosen by ranking the
legal candidates of :mod:`.cost_model` — fastest estimated step among the
ones that fit per-chip HBM. The plan then installs the process mesh
(parallel.mesh.create_mesh + fleet/env registration), and FleetEngine /
DistributedTrainStep consume its fields (zero level, n_micro, 1F1B
schedule) when ``fleet.init(strategy={"auto": True})`` is active.

The whole planner runs at TRACE-BUILD time on the host: nothing here may
touch device values (pinned by the GL001 host-sync taint test in
tests/test_fleet_auto.py).
"""
from __future__ import annotations

import dataclasses
from typing import Any, Dict, List, Optional

from ....monitor import stats as _mstats
from .cost_model import (HardwareSpec, ModelStats, PlanCandidate,
                         enumerate_plans, estimate)

__all__ = ["ParallelPlan", "plan", "explain", "last_plan"]

_LAST_PLAN: Optional["ParallelPlan"] = None


def _fmt_bytes(n: float) -> str:
    neg = "-" if n < 0 else ""
    n = abs(float(n))
    for unit, div in (("G", 2 ** 30), ("M", 2 ** 20), ("K", 2 ** 10)):
        if n >= div:
            return f"{neg}{n / div:.1f}{unit}"
    return f"{neg}{n:.0f}B"


@dataclasses.dataclass
class ParallelPlan:
    """A complete, installable hybrid-parallel execution plan."""

    dp: int
    sharding: int
    pp: int
    mp: int
    n_micro: int
    zero: int
    remat: bool
    schedule: str                       # "1f1b" | "fill_drain"
    stats: ModelStats
    hardware: HardwareSpec
    chosen: PlanCandidate
    candidates: List[PlanCandidate]     # ranked, fitting first
    global_batch: int
    ep: int = 1                         # expert parallelism (ISSUE 18)

    @property
    def model_degree(self) -> int:
        """Physical "model" axis size — TP and EP share the axis."""
        return max(self.mp, self.ep)

    @property
    def mesh_dims(self) -> Dict[str, int]:
        return {"data": self.dp, "sharding": self.sharding,
                "pipe": self.pp, "model": self.model_degree}

    def create_mesh(self):
        """Build + install the 4-axis Fleet mesh for this plan and
        register it with the fleet facade/env (so facade calls and hcg
        queries agree with the planner's choice)."""
        from ....parallel.mesh import create_mesh

        mesh = create_mesh(dp=self.dp, sharding=self.sharding, pp=self.pp,
                           mp=self.model_degree)
        try:
            from ... import env as _env
            from ..base.fleet_base import fleet as _fleet
            from ..base.topology import (CommunicateTopology,
                                         HybridCommunicateGroup)

            topo = CommunicateTopology(("data", "pipe", "sharding", "model"),
                                       (self.dp, self.pp, self.sharding,
                                        self.model_degree))
            hcg = HybridCommunicateGroup(topo, _env.get_rank())
            _fleet._mesh = mesh
            _fleet._topology = topo
            _fleet._hcg = hcg
            _env.set_state(initialized=True, mesh=mesh, topology=topo,
                           hcg=hcg)
        except Exception:  # standalone use without the facade initialised
            pass
        return mesh

    # -- reporting -----------------------------------------------------------
    def table(self, top: int = 10) -> str:
        """Ranked candidate table (the ``explain`` payload)."""
        moe = any(c.ep > 1 for c in self.candidates)
        hdr = (f"{'rank':<5}{'dp':>4}{'sh':>4}{'pp':>4}{'mp':>4}"
               + (f"{'ep':>4}" if moe else "")
               + f"{'micro':>6}{'zero':>5}{'hbm/dev':>10}{'bubble':>8}"
               + f"{'coll':>10}"
               + (f"{'a2a':>10}" if moe else "")
               + f"{'score':>11}  fit")
        lines = [hdr, "-" * len(hdr)]
        for i, c in enumerate(self.candidates[:top]):
            mark = " <== chosen" if c is self.chosen else ""
            lines.append(
                f"{i:<5}{c.dp:>4}{c.sharding:>4}{c.pp:>4}{c.mp:>4}"
                + (f"{c.ep:>4}" if moe else "")
                + f"{c.n_micro:>6}{c.zero:>5}"
                + f"{_fmt_bytes(c.hbm_bytes):>10}{c.bubble_frac:>8.3f}"
                + f"{_fmt_bytes(c.coll_bytes):>10}"
                + (f"{_fmt_bytes(c.a2a_bytes):>10}" if moe else "")
                + f"{c.score * 1e3:>9.4f}ms"
                + f"  {'yes' if c.fits else 'NO (' + c.why + ')'}{mark}")
        return "\n".join(lines)

    def explain(self, top: int = 10, file=None) -> str:
        budget = int(self.hardware.hbm_bytes * self.hardware.hbm_fudge)
        head = (f"fleet.auto plan over "
                f"{self.dp * self.sharding * self.pp * self.model_degree} "
                f"device(s), global_batch={self.global_batch}, "
                f"params={_fmt_bytes(self.stats.param_bytes)}, "
                f"HBM budget={_fmt_bytes(budget)}/device\n"
                f"chosen: {self.chosen.describe()} schedule={self.schedule} "
                f"remat={self.remat} (headroom "
                f"{_fmt_bytes(budget - self.chosen.hbm_bytes)})")
        text = head + "\n" + self.table(top)
        print(text, file=file)
        return text


def plan(params=None, *, stats: Optional[ModelStats] = None,
         global_batch: int, n_devices: Optional[int] = None,
         hardware: Optional[HardwareSpec] = None,
         param_specs=None, layers: Optional[int] = None,
         seq_len: int = 1, hidden: int = 0,
         table_rows: int = 0, table_dim: int = 0,
         table_lookups_per_sample: int = 0,
         moe_experts: int = 0, moe_expert_params: int = 0,
         moe_layers: int = 0, moe_top_k: int = 2,
         moe_capacity_factor: float = 1.25,
         allow_mp: Optional[bool] = None,
         zero_levels=(0, 1, 2, 3), max_micro: int = 64,
         constraints: Optional[Dict[str, int]] = None,
         schedule: str = "1f1b",
         hidden_comm_frac: Optional[float] = None) -> ParallelPlan:
    """Enumerate legal candidates, estimate each, pick the fastest that
    fits per-chip HBM.

    ``hidden_comm_frac``: measured grad-collective overlap fraction from
    ``DistributedTrainStep.measure_overlap()["hidden_frac"]`` — feeds the
    cost model's overlap credit (see :func:`cost_model.estimate`) so the
    plan score uses the MEASURED value instead of the assumed 0.5.

    Raises ``ValueError`` when NO candidate fits (the error carries the
    closest candidate's shortfall — the actionable number).
    """
    import jax

    if stats is None:
        if params is None:
            raise ValueError("plan() needs `params` or `stats`")
        stats = ModelStats.from_params(params, specs=param_specs,
                                       layers=layers, hidden=hidden,
                                       seq_len=seq_len)
    if table_rows:
        # embedding-table placement term (paddle_tpu.sparse): the table
        # rides its own ModelStats fields, never param_bytes
        stats = dataclasses.replace(
            stats, table_rows=int(table_rows), table_dim=int(table_dim),
            table_lookups_per_sample=int(table_lookups_per_sample))
    if moe_experts:
        # expert placement term (ISSUE 18, nn/moe.py): same pattern —
        # expert weights ride their own fields, legalising the ep search
        stats = dataclasses.replace(
            stats, moe_experts=int(moe_experts),
            moe_expert_params=int(moe_expert_params),
            moe_layers=int(moe_layers), moe_top_k=int(moe_top_k),
            moe_capacity_factor=float(moe_capacity_factor))
    if n_devices is None:
        n_devices = len(jax.devices())
    hw = hardware or HardwareSpec()
    if allow_mp is None:
        # TP-annotated matmuls or a row-shardable table both legalise mp
        allow_mp = stats.tp_bytes > 0 or stats.table_rows > 0

    cands = enumerate_plans(n_devices, global_batch, stats,
                            zero_levels=zero_levels, allow_mp=allow_mp,
                            max_micro=max_micro, constraints=constraints)
    if not cands:
        raise ValueError(
            f"no legal (dp, sharding, pp, mp, n_micro) factorisation for "
            f"{n_devices} devices / global_batch={global_batch} / "
            f"layers={stats.layers} (constraints={constraints})")
    for c in cands:
        estimate(c, stats, global_batch, hw,
                 hidden_comm_frac=hidden_comm_frac)
    # fastest fitting plan first. Scores are bucketed at 2% of the best —
    # the model's resolution ends well before that — and ties within a
    # bucket resolve to the simpler topology (less pipe, less tp, less
    # sharding, more dp: fewer moving parts for the same speed).
    # Non-fitting candidates rank after every fitting one, by smallest
    # HBM overshoot (the explain() table then reads as "what was close").
    fitting = [c for c in cands if c.fits]
    eps = 0.02 * min((c.score for c in fitting), default=1.0)

    def key(c):
        rank = (int(c.score / eps) if eps > 0 else 0) if c.fits \
            else c.hbm_bytes
        return (not c.fits, rank, c.pp, c.mp, c.ep, c.sharding, -c.dp)

    cands.sort(key=key)
    chosen = cands[0]
    if not chosen.fits:
        raise ValueError(
            "fleet.auto: no plan fits per-device HBM "
            f"({int(hw.hbm_bytes * hw.hbm_fudge) / 2**30:.2f} GiB usable); "
            f"closest is {chosen.describe()} needing "
            f"{chosen.hbm_bytes / 2**30:.2f} GiB — add devices, raise the "
            "ZeRO level ceiling, or shrink the per-replica batch")

    p = ParallelPlan(
        dp=chosen.dp, sharding=chosen.sharding, pp=chosen.pp, mp=chosen.mp,
        ep=chosen.ep,
        n_micro=chosen.n_micro, zero=chosen.zero, remat=chosen.remat,
        schedule=schedule if chosen.pp > 1 else "none",
        stats=stats, hardware=hw, chosen=chosen, candidates=cands,
        global_batch=global_batch)

    budget = int(hw.hbm_bytes * hw.hbm_fudge)
    _mstats.PLAN_CANDIDATES_CONSIDERED.add(len(cands))
    _mstats.ZERO_LEVEL.set(chosen.zero)
    _mstats.PIPELINE_BUBBLE_FRAC.set(int(chosen.bubble_frac * 1e6))
    _mstats.PLANNER_HBM_HEADROOM_BYTES.set(budget - chosen.hbm_bytes)

    global _LAST_PLAN
    _LAST_PLAN = p
    return p


def last_plan() -> Optional[ParallelPlan]:
    return _LAST_PLAN


def explain(top: int = 10, file=None) -> str:
    """Print the ranked candidate table of the most recent plan()."""
    if _LAST_PLAN is None:
        msg = "fleet.auto: no plan computed yet (call fleet.auto.plan first)"
        print(msg, file=file)
        return msg
    return _LAST_PLAN.explain(top=top, file=file)
