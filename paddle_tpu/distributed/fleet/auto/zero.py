"""ZeRO-2/3 optimizer-state sharding as a first-class optimizer wrapper.

Parity: the reference's DygraphShardingOptimizer / sharding stage 2-3
(fleet/meta_optimizers/dygraph_optimizer/dygraph_sharding_optimizer.py:90
greedy param partition + hand-inserted reduce-scatter / all-gather /
broadcast ops). TPU-native, the partition IS a PartitionSpec: moments (and
at level 3 the params themselves) carry the "sharding" mesh axis in their
storage specs, and XLA derives the exact collective sequence the reference
coded by hand — gradients hit a reduce-scatter at the spec boundary
(level >= 2), the updated shards are all-gathered where the next forward
consumes them (level 3). Rajbhandari et al. (ZeRO, 2020) levels:

  1: optimizer state 1/Nth per device      (DistributedTrainStep default)
  2: + gradients reduce-scattered           (grads pinned to the shard spec)
  3: + parameters stored 1/Nth per device   (param storage spec sharded)

``ShardedOptimizer`` bundles a pure optimizer (init_fn, update_fn) with the
level; ``DistributedTrainStep(optimizer=ShardedOptimizer("adamw", level=3))``
applies the spec policy. State-dicts round-trip through the existing
checkpoint paths unchanged: sharded arrays gather on host read and a load
device_puts them back through the sharded NamedSharding (layout, not
content).
"""
from __future__ import annotations

from typing import Callable, Optional, Tuple

__all__ = ["ShardedOptimizer"]

_KNOWN = ("adamw", "sgd", "momentum", "lamb", "lars")


class ShardedOptimizer:
    """Wrap a pure optimizer with a ZeRO partition level.

    Args:
      inner: optimizer name ("adamw", "lamb", ...) or an
        ``(init_fn, update_fn)`` pair with the pure-optimizer signature of
        parallel.train_step.
      level: ZeRO stage, 0..3 (see module docstring).
      axis: mesh axis the states shard over (default "sharding").
      **opt_kwargs: hyperparameters forwarded to every update call
        (beta1, weight_decay, ...).
    """

    def __init__(self, inner="adamw", level: int = 2,
                 axis: str = "sharding", **opt_kwargs):
        if level not in (0, 1, 2, 3):
            raise ValueError(f"ZeRO level must be 0..3, got {level}")
        self.level = int(level)
        self.axis = axis
        self.opt_kwargs = dict(opt_kwargs)
        if isinstance(inner, str):
            from ....parallel.train_step import _OPTS

            if inner not in _OPTS:
                raise ValueError(
                    f"unknown optimizer {inner!r}; known: {_KNOWN}")
            self.name = inner
            self._fns: Tuple[Callable, Callable] = _OPTS[inner]
        else:
            init_fn, update_fn = inner
            self.name = getattr(update_fn, "__name__", "custom")
            self._fns = (init_fn, update_fn)

    @property
    def init_fn(self) -> Callable:
        return self._fns[0]

    @property
    def update_fn(self) -> Callable:
        return self._fns[1]

    def fns(self) -> Tuple[Callable, Callable]:
        return self._fns

    def __repr__(self):
        return (f"ShardedOptimizer({self.name}, level={self.level}, "
                f"axis={self.axis!r})")
