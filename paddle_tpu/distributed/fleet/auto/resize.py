"""Elastic resize: re-plan a job over a SURVIVING device set.

fleet.auto made "what mesh fits N-k hosts" a solved query — the planner
already enumerates and ranks every legal (dp, sharding, pp, mp, micro,
zero) factorisation for an arbitrary device count. This module is the
thin bridge the resilience stack drives on host loss: take the devices
that are still alive, re-run :func:`~.planner.plan` over exactly that
many, and install the chosen mesh over exactly those devices. The
TrainGuardian then reshards the pod-agreed snapshot onto the new plan
via the ZeRO sharded<->unsharded checkpoint round-trip (snapshots hold
full unsharded host arrays; ``device_put`` under the new step's
NamedShardings is the reshard) and resumes.
"""
from __future__ import annotations

from typing import Optional, Sequence, Tuple

from .cost_model import HardwareSpec, ModelStats
from .planner import ParallelPlan, plan

__all__ = ["replan_for_devices"]


def replan_for_devices(devices: Sequence, *, global_batch: int,
                       params=None, stats: Optional[ModelStats] = None,
                       hardware: Optional[HardwareSpec] = None,
                       install: bool = True,
                       **plan_kw) -> Tuple[ParallelPlan, "object"]:
    """Re-run the planner over ``devices`` (the survivors of a host
    loss) and build the 4-axis mesh over exactly those devices.

    Returns ``(plan, mesh)``. ``install=True`` (default) also registers
    the mesh with the parallel/fleet state, so a subsequently-built
    DistributedTrainStep picks it up. Raises ``ValueError`` when no
    legal candidate fits the shrunken pod — the caller's last rung.
    """
    devices = list(devices)
    if not devices:
        raise ValueError("replan_for_devices: no surviving devices")
    p = plan(params=params, stats=stats, global_batch=global_batch,
             n_devices=len(devices), hardware=hardware, **plan_kw)
    from ....parallel.mesh import create_mesh, set_mesh

    mesh = create_mesh(dp=p.dp, sharding=p.sharding, pp=p.pp, mp=p.mp,
                       devices=devices)
    if not install:
        set_mesh(None)
    return p, mesh
