"""Elastic training manager — membership, scale-in/out, rank remap, relaunch.

Parity: reference fleet elastic (python/paddle/distributed/fleet/elastic/
manager.py:103 ElasticManager — etcd node registry with watches, :176-225
host registration + np consistency, :247-270 _match on live host count,
:268-292 _update_hosts rank preservation, :317 watch/relaunch loop).

TPU-native translation:
- the etcd cluster becomes a :class:`FileKVStore` — a shared directory
  (NFS/GCS-fuse on real pods, tmpdir in tests) with atomic-rename writes
  and mtime heartbeats. Same contract: registry of alive nodes, a np
  target, completion flag. (On Cloud TPU the scheduler usually owns
  membership; the kv store is what makes the manager self-contained and
  testable.)
- a "node" is a host driving a TPU slice-chunk (one process per host, jax
  process model), not one process per GPU.
- scale-in/out within [min_np, max_np]: the supervising agent relaunches
  the pod whenever the alive-node set stops matching the running pod, with
  ranks regenerated but PRESERVED for surviving nodes (reference
  _update_hosts swap logic).
- fault recovery composes with CheckpointManager auto-resume
  (framework/checkpoint.py): workers restore_latest() on start, so a
  relaunch continues from the newest snapshot.
"""
from __future__ import annotations

import json
import os
import struct
import time
import zlib
from typing import Dict, List, Optional, Tuple

from ..monitor import stats as _mstats
from ..resilience import faults as _faults

__all__ = ["FileKVStore", "ElasticManager", "ElasticStatus"]


def _partition_check() -> None:
    """kv_partition fault-injection point (resilience.faults): raise the
    OSError a partitioned NFS/GCS-fuse mount would, for the injected
    window. One list-index check when no faults are configured."""
    if _faults.ENABLED[0] and _faults.kv_partition_active():
        raise OSError("injected kv partition: shared store unreachable")


class ElasticStatus:
    COMPLETED = "completed"
    RESTART = "restart"
    ERROR = "error"
    EXIT = "exit"


class FileKVStore:
    """etcd-analog over a shared directory. Keys are '/'-separated paths;
    values bytes. Writes are atomic (tmp + rename); watches are polls."""

    def __init__(self, root: str):
        self.root = os.path.abspath(root)
        os.makedirs(self.root, exist_ok=True)

    def _path(self, key: str) -> str:
        key = key.strip("/")
        if not key or ".." in key.split("/"):
            raise ValueError(f"bad key {key!r}")
        return os.path.join(self.root, key)

    # transient-OSError retry budget for put(): the store lives on
    # NFS/GCS-fuse on real pods, where EIO/ESTALE blips are routine — a
    # heartbeat that dies on one would scale a healthy node in
    PUT_RETRIES = 3
    PUT_BACKOFF = 0.02

    def put(self, key: str, value) -> None:
        if isinstance(value, str):
            value = value.encode()
        path = self._path(key)
        last: Optional[OSError] = None
        for attempt in range(self.PUT_RETRIES + 1):
            tmp = path + f".tmp.{os.getpid()}.{time.monotonic_ns()}"
            try:
                _partition_check()   # injected partitions ride the retry path
                os.makedirs(os.path.dirname(path), exist_ok=True)
                with open(tmp, "wb") as f:
                    f.write(value)
                os.replace(tmp, path)
                return
            except OSError as e:
                last = e
                try:
                    os.remove(tmp)
                except OSError:
                    pass
                if attempt < self.PUT_RETRIES:
                    time.sleep(self.PUT_BACKOFF * (2 ** attempt))
        raise last

    def get(self, key: str) -> Optional[bytes]:
        _partition_check()
        try:
            with open(self._path(key), "rb") as f:
                return f.read()
        except FileNotFoundError:
            return None

    # binary-safe framed values (ISSUE 19): KV-block payload manifests and
    # large registration records carry bytes that must not ride the text
    # path unguarded — a torn NFS read or a truncated GCS-fuse flush would
    # otherwise hand the reader silently-corrupt block rows. Frame: magic,
    # crc32, payload length, payload. The size guard bounds what one
    # heartbeat-path writer can park on the shared store.
    BYTES_MAGIC = b"KVB1"
    MAX_BYTES = 256 * 1024 * 1024

    def put_bytes(self, key: str, value: bytes,
                  max_bytes: Optional[int] = None) -> None:
        """Atomic checksummed binary write; retry discipline identical to
        :meth:`put` (the frame is built once, then rides the same
        transient-OSError budget)."""
        value = bytes(value)
        cap = self.MAX_BYTES if max_bytes is None else int(max_bytes)
        if len(value) > cap:
            raise ValueError(
                f"put_bytes({key!r}): payload {len(value)} bytes exceeds "
                f"the {cap}-byte size guard")
        frame = (self.BYTES_MAGIC
                 + struct.pack("<IQ", zlib.crc32(value) & 0xFFFFFFFF,
                               len(value))
                 + value)
        self.put(key, frame)

    def get_bytes(self, key: str) -> Optional[bytes]:
        """Read a :meth:`put_bytes` frame back, verifying length and
        checksum. None when the key is absent; ValueError when the frame
        is torn or corrupt (the caller retries or treats the record as
        missing — never consumes garbage)."""
        raw = self.get(key)
        if raw is None:
            return None
        head = len(self.BYTES_MAGIC) + 12
        if len(raw) < head or not raw.startswith(self.BYTES_MAGIC):
            raise ValueError(f"get_bytes({key!r}): not a framed binary "
                             "record (bad magic)")
        crc, size = struct.unpack("<IQ", raw[len(self.BYTES_MAGIC):head])
        payload = raw[head:]
        if len(payload) != size:
            raise ValueError(
                f"get_bytes({key!r}): torn frame — header says {size} "
                f"bytes, file holds {len(payload)}")
        if zlib.crc32(payload) & 0xFFFFFFFF != crc:
            raise ValueError(f"get_bytes({key!r}): checksum mismatch "
                             "(corrupt payload)")
        return payload

    def delete(self, key: str) -> None:
        _partition_check()
        try:
            os.remove(self._path(key))
        except FileNotFoundError:
            pass

    def get_prefix(self, prefix: str) -> Dict[str, bytes]:
        """{key: value} for every key under prefix (one directory level)."""
        _partition_check()
        base = self._path(prefix)
        out = {}
        try:
            names = sorted(os.listdir(base))
        except FileNotFoundError:
            return out
        for n in names:
            if n.endswith(("~",)) or ".tmp." in n:
                continue
            p = os.path.join(base, n)
            if os.path.isfile(p):
                with open(p, "rb") as f:
                    out[f"{prefix.strip('/')}/{n}"] = f.read()
        return out

    def mtime(self, key: str) -> Optional[float]:
        try:
            return os.path.getmtime(self._path(key))
        except FileNotFoundError:
            return None


class ElasticManager:
    """Membership + rank-map + relaunch decisions for one job.

    One instance runs inside each host's launcher agent (and, in the
    single-host test rig, inside the one agent supervising all workers).
    """

    def __init__(self, kv: FileKVStore, job_id: str, min_np: int,
                 max_np: Optional[int] = None, heartbeat_ttl: float = 10.0):
        if min_np < 1:
            raise ValueError("min_np must be >= 1")
        self.kv = kv
        self.job_id = job_id
        self.min_np = int(min_np)
        self.max_np = int(max_np or min_np)
        if self.max_np < self.min_np:
            raise ValueError("max_np must be >= min_np")
        self.ttl = float(heartbeat_ttl)
        self.prefix = f"jobs/{job_id}"
        self.node_prefix = f"{self.prefix}/nodes"
        # monotonic staleness tracking: host -> (last heartbeat payload
        # ts, local time.monotonic() when that payload was first seen)
        self._hb_seen: Dict[str, Tuple[float, float]] = {}

    # -- node registry (reference manager.py:176-225) ------------------------
    def register(self, host: str, status: str = "alive") -> None:
        self.kv.put(f"{self.node_prefix}/{host}",
                    json.dumps({"host": host, "status": status,
                                "ts": time.time()}))

    def heartbeat(self, host: str) -> None:
        self.register(host)

    def mark_dead(self, host: str) -> None:
        """Permanent scale-in signal. A TOMBSTONE key, not a node-record
        status: the supervising agent heartbeats nodes whose process is
        still alive, and a worker calls mark_dead shortly BEFORE exiting —
        a status field would race with that heartbeat and get resurrected.
        Tombstones win over any registration until readmit()."""
        self.kv.put(f"{self.prefix}/dead/{host}", b"1")

    def readmit(self, host: str) -> None:
        """Clear a tombstone so the host may rejoin (scale-out)."""
        self.kv.delete(f"{self.prefix}/dead/{host}")

    def dead_hosts(self) -> List[str]:
        return sorted(k.rsplit("/", 1)[1]
                      for k in self.kv.get_prefix(f"{self.prefix}/dead"))

    def deregister(self, host: str) -> None:
        self.kv.delete(f"{self.node_prefix}/{host}")

    def alive_hosts(self) -> List[str]:
        """Hosts with a fresh, non-tombstoned registration (etcd lease
        analog).

        Staleness is a MONOTONIC-clock delta, not a raw heartbeat-ts /
        mtime comparison: each manager notes the local
        ``time.monotonic()`` at which it first observed a given heartbeat
        payload, and a host goes stale only once the SAME payload has
        been observed for longer than the ttl. Wall-clock skew between
        hosts, NTP steps, and NFS server time drift therefore cannot
        kill a live node (or resurrect a dead one) — the cost is that a
        pre-existing stale record counts as alive for one ttl after this
        manager first sees it.

        A host whose record VANISHES (deregistration, or a partition that
        wiped the lease) has its ``_hb_seen`` entry pruned, so a later
        re-registration — even one carrying an identical heartbeat
        payload (frozen/coarse clock, a stale NFS cache replaying the old
        file) — is a fresh observation, not "the same payload seen a ttl
        ago": without the prune, a host re-registering after a transient
        KV partition would come back permanently stale, and the stale
        bookkeeping row would shadow (double-count against) its live
        registration."""
        now_m = time.monotonic()
        dead = set(self.dead_hosts())
        alive = []
        present = set()
        for key, raw in self.kv.get_prefix(self.node_prefix).items():
            try:
                rec = json.loads(raw.decode())
            except (ValueError, UnicodeDecodeError):
                continue
            host = rec.get("host")
            present.add(host)
            if host in dead or rec.get("status") == "dead":
                continue
            ts = float(rec.get("ts", 0))
            seen = self._hb_seen.get(host)
            if seen is None or seen[0] != ts:
                self._hb_seen[host] = (ts, now_m)
            elif now_m - seen[1] > self.ttl:
                continue
            alive.append(host)
        for host in [h for h in self._hb_seen if h not in present]:
            del self._hb_seen[host]
        _mstats.POD_HOSTS_ALIVE.set(len(alive))
        return sorted(alive)

    def last_seen_age(self, host: str) -> Optional[float]:
        """Seconds of LOCAL monotonic time since this manager last
        observed a NEW heartbeat payload from ``host`` (None = never
        observed). This is the staleness input to :meth:`alive_hosts` —
        a host is declared stale once its age exceeds the ttl."""
        seen = self._hb_seen.get(host)
        if seen is None:
            return None
        return time.monotonic() - seen[1]

    def host_ages(self) -> Dict[str, float]:
        """{host: last-seen age in seconds} for every registered host
        (tombstoned hosts included — the caller filters). Refreshes the
        observation bookkeeping first, so ages reflect the current store
        contents."""
        self.alive_hosts()
        now_m = time.monotonic()
        return {h: now_m - first_m
                for h, (_, first_m) in self._hb_seen.items()}

    # -- quorum / scale (reference _match :247, np watch :205) ---------------
    def match(self) -> Tuple[bool, List[str]]:
        hosts = self.alive_hosts()
        return (self.min_np <= len(hosts) <= self.max_np, hosts)

    def wait_for_quorum(self, timeout: float = 60.0,
                        poll: float = 0.2) -> List[str]:
        """Block until the alive set sits inside [min_np, max_np] and is
        stable for one extra poll (reference wait() loop)."""
        # monotonic deadline: an NTP step mid-wait must not stretch or
        # collapse the quorum window (graftlint GL008, same class as the
        # PR-5 heartbeat-staleness fix)
        deadline = time.monotonic() + timeout
        prev: Optional[List[str]] = None
        while time.monotonic() < deadline:
            ok, hosts = self.match()
            if ok and hosts == prev:
                return hosts
            prev = hosts if ok else None
            time.sleep(poll)
        raise TimeoutError(
            f"elastic quorum not reached: need [{self.min_np}, "
            f"{self.max_np}] alive nodes, have {self.alive_hosts()}")

    # -- rank map (reference _update_hosts :268-292) -------------------------
    def rank_map(self, hosts: List[str],
                 previous: Optional[Dict[str, int]] = None) -> Dict[str, int]:
        """host -> rank. Surviving hosts keep their previous rank when it
        is still inside the new world size; vacated ranks are filled by the
        new/displaced hosts in sorted order — the reference's host-swap
        logic generalized to arbitrary membership changes."""
        n = len(hosts)
        taken: Dict[int, str] = {}
        if previous:
            for h in sorted(hosts):
                r = previous.get(h)
                if r is not None and 0 <= r < n and r not in taken:
                    taken[r] = h
        placed = set(taken.values())
        free_ranks = [r for r in range(n) if r not in taken]
        for h in sorted(hosts):
            if h in placed:
                continue
            taken[free_ranks.pop(0)] = h
        result = {h: r for r, h in taken.items()}
        self.kv.put(f"{self.prefix}/rank_map", json.dumps(result))
        return result

    def last_rank_map(self) -> Optional[Dict[str, int]]:
        raw = self.kv.get(f"{self.prefix}/rank_map")
        return json.loads(raw.decode()) if raw else None

    # -- completion flag (reference exit() :229) -----------------------------
    def set_completed(self) -> None:
        self.kv.put(f"{self.prefix}/completed", b"1")

    def completed(self) -> bool:
        return self.kv.get(f"{self.prefix}/completed") == b"1"

    # -- job status (ElasticStatus) ------------------------------------------
    def set_status(self, status: str) -> None:
        """Publish a job status (e.g. ElasticStatus.RESTART from the
        TrainGuardian's preemption handler — the supervising agent reads
        it and relaunches instead of treating the exit as terminal)."""
        self.kv.put(f"{self.prefix}/status", status)

    def status(self) -> Optional[str]:
        raw = self.kv.get(f"{self.prefix}/status")
        return raw.decode() if raw is not None else None
