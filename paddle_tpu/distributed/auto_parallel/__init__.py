"""Semi-auto parallel API — ProcessMesh / shard_tensor / shard_op.

Parity: reference python/paddle/distributed/auto_parallel/interface.py:71
(ProcessMesh), :295 (shard_tensor), :383 (shard_op), :331/:440/:468
(set_shard_mask / set_offload_device / set_pipeline_stage), routed through
``strategy.semi_auto`` (reference fleet_base.py:1423-1430).

TPU-native design: the reference's whole auto-parallel stack — dist-attr
completion (completion.py), partitioner.py program rewriting, reshard.py
send/recv insertion — IS the GSPMD partitioner. Here an annotation becomes
a ``jax.sharding.PartitionSpec``:

- ``ProcessMesh`` wraps the topology as a 4-axis ``jax.sharding.Mesh``
  (singleton axes padded), so every existing engine path (ZeRO, TP,
  pipeline, DP batch split) works unchanged on top of it.
- ``shard_tensor(x, mesh, dim_mapping)`` stores the PartitionSpec on the
  tensor (``x.sharding``); eager Parameters carry it into
  DistributedTrainStep/FleetEngine, and traced arrays get a
  ``with_sharding_constraint`` so XLA inserts exactly the collectives the
  reference's reshard pass would have coded by hand.
- ``shard_op(op_fn, mesh, dim_mapping_dict)`` constrains the op's inputs /
  outputs; the "completion" of every unannotated tensor is GSPMD's sharding
  propagation, which is the same fixed-point algorithm completion.py
  approximates.
"""
from __future__ import annotations

import warnings
import weakref
from typing import Dict, List, Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P

from ...framework.core import Tensor
from ...parallel.mesh import AXES, get_mesh, set_mesh

__all__ = ["ProcessMesh", "shard_tensor", "shard_op", "set_shard_mask",
           "set_offload_device", "set_pipeline_stage", "get_default_mesh",
           "plan", "explain"]


def plan(*args, **kwargs):
    """Bridge to the fleet.auto cost-model planner (ISSUE 9) — the
    reference exposes its planner under auto_parallel; ours lives in
    distributed/fleet/auto (one implementation, two entry points)."""
    from ..fleet import auto as _auto

    return _auto.plan(*args, **kwargs)


def explain(*args, **kwargs):
    """Print the ranked candidate table of the latest fleet.auto plan."""
    from ..fleet import auto as _auto

    return _auto.explain(*args, **kwargs)

# dim-name defaults by mesh arity; chosen so the data axis always exists
# (DistributedTrainStep shards batches over ("data", "sharding")) and a 2-D
# mesh matches the common dp x mp usage of the reference examples
_DEFAULT_DIM_NAMES = {
    1: ("data",),
    2: ("data", "model"),
    3: ("data", "sharding", "model"),
    4: ("data", "sharding", "pipe", "model"),
}

# the root (first-created) ProcessMesh — what fleet's semi_auto init adopts
_root_mesh: Optional["ProcessMesh"] = None

# id(tensor) -> {"mesh": ProcessMesh, "dim_mapping": [...], ...}; Tensor has
# __slots__ (no attr bag) and elementwise __eq__ (no WeakKeyDictionary), so
# dist attrs live here keyed by id with a weakref finalizer for cleanup
_dist_attrs: Dict[int, dict] = {}


def _attrs_for(x: Tensor) -> dict:
    key = id(x)
    if key not in _dist_attrs:
        _dist_attrs[key] = {}
        try:
            weakref.finalize(x, _dist_attrs.pop, key, None)
        except TypeError:
            pass
    return _dist_attrs[key]


def get_dist_attr(x: Tensor) -> dict:
    """Distributed attributes previously attached by shard_tensor & co."""
    return dict(_dist_attrs.get(id(x), {}))


class ProcessMesh:
    """Topology of logical processes (reference interface.py:71).

    ``mesh`` is an N-D nested list of unique process ids, e.g.
    ``ProcessMesh([[0, 1, 2, 3], [4, 5, 6, 7]])`` is a [2, 4] topology.
    ``dim_names`` (TPU extension, matches later reference versions) names
    each topology dim with one of the Fleet mesh axes
    ("data"/"sharding"/"pipe"/"model"); defaults by arity so dim 0 is
    always the data axis.
    """

    def __init__(self, mesh, dim_names: Optional[Sequence[str]] = None,
                 parent: Optional["ProcessMesh"] = None):
        global _root_mesh
        if mesh is None or not isinstance(mesh, (list, tuple)):
            raise ValueError("mesh must be a (nested) list of process ids")
        arr = np.array(mesh)
        self._topology: List[int] = list(arr.shape)
        self._processes: List[int] = [int(v) for v in arr.flatten()]
        if min(self._processes) < 0:
            raise ValueError("all elements of mesh must be >= 0")
        if len(set(self._processes)) != len(self._processes):
            raise ValueError("all elements of mesh must be unique")
        self.parent = parent
        if parent is None and min(self._processes) == 0 and \
                max(self._processes) != len(self._processes) - 1:
            raise ValueError(
                "for a root ProcessMesh, process ids must be a permutation "
                "of range(N)")
        if dim_names is None:
            dim_names = _DEFAULT_DIM_NAMES.get(len(self._topology))
            if dim_names is None:
                raise ValueError(f"mesh rank {len(self._topology)} > 4; "
                                 "pass dim_names explicitly")
        if len(dim_names) != len(self._topology):
            raise ValueError("dim_names must match mesh rank")
        bad = [d for d in dim_names if d not in AXES]
        if bad:
            raise ValueError(f"dim_names must be from {AXES}, got {bad}")
        if len(set(dim_names)) != len(dim_names):
            raise ValueError("dim_names must be unique")
        self._dim_names = tuple(dim_names)
        self._jax_mesh: Optional[Mesh] = None
        if _root_mesh is None and parent is None:
            _root_mesh = self

    # -- reference surface ---------------------------------------------------
    @property
    def topology(self) -> List[int]:
        return list(self._topology)

    shape = topology

    @property
    def process_group(self) -> List[int]:
        return list(self._processes)

    processes = process_group

    @property
    def dim_names(self):
        return self._dim_names

    @property
    def ndim(self) -> int:
        return len(self._topology)

    def set_placement(self, order: Sequence[int]):
        """Map logical process ids to physical device indices (reference
        interface.py set_placement): order[i] is the physical device for
        logical process i."""
        if sorted(order) != sorted(self._processes):
            raise ValueError("placement must be a permutation of the mesh's "
                             "process ids")
        self._placement = list(order)
        self._jax_mesh = None

    def __eq__(self, other):
        return (isinstance(other, ProcessMesh)
                and self._topology == other._topology
                and self._processes == other._processes)

    def __hash__(self):
        return hash((tuple(self._topology), tuple(self._processes)))

    def __repr__(self):
        return (f"ProcessMesh(topology={self._topology}, "
                f"dim_names={self._dim_names})")

    # -- jax bridge ----------------------------------------------------------
    def as_jax_mesh(self, devices=None) -> Mesh:
        """The 4-axis Fleet jax Mesh (singleton axes padded) over the
        devices selected by this mesh's process ids."""
        if self._jax_mesh is not None and devices is None:
            return self._jax_mesh
        all_devs = list(devices if devices is not None else jax.devices())
        placement = getattr(self, "_placement", None)
        ids = [placement[p] for p in self._processes] if placement \
            else self._processes
        if max(ids) >= len(all_devs):
            raise RuntimeError(
                f"ProcessMesh needs device id {max(ids)} but only "
                f"{len(all_devs)} devices are available")
        sel = np.array([all_devs[i] for i in ids]).reshape(self._topology)
        # expand to the canonical 4-axis order with singleton padding
        full_shape = [1] * len(AXES)
        src_axes = []
        for name, size in zip(self._dim_names, self._topology):
            full_shape[AXES.index(name)] = size
            src_axes.append(AXES.index(name))
        # transpose source dims into AXES order, then pad
        order = np.argsort(src_axes)
        sel = sel.transpose(order).reshape(full_shape)
        mesh = Mesh(sel, AXES)
        if devices is None:
            self._jax_mesh = mesh
        return mesh

    def install(self, devices=None) -> Mesh:
        """Make this the process-global mesh (parallel.mesh.set_mesh)."""
        mesh = self.as_jax_mesh(devices)
        set_mesh(mesh)
        return mesh


def get_default_mesh() -> Optional[ProcessMesh]:
    """The root ProcessMesh (first created), if any."""
    return _root_mesh


def reset_auto_parallel_state():
    """Test hook: forget the root mesh and all dist attrs."""
    global _root_mesh
    _root_mesh = None
    _dist_attrs.clear()


def _spec_from_mapping(mesh: ProcessMesh, dim_mapping: Sequence[int],
                       ndim: int) -> P:
    if len(dim_mapping) != ndim:
        raise ValueError(
            f"dim_mapping {list(dim_mapping)} must have one entry per "
            f"tensor dim ({ndim})")
    entries = []
    used = set()
    for m in dim_mapping:
        if m == -1:
            entries.append(None)
            continue
        if not (0 <= m < mesh.ndim):
            raise ValueError(f"dim_mapping entry {m} out of range for "
                             f"mesh rank {mesh.ndim}")
        name = mesh.dim_names[m]
        if name in used:
            raise ValueError(f"mesh dim {m} used for more than one tensor "
                             "dim")
        used.add(name)
        entries.append(name)
    while entries and entries[-1] is None:
        entries.pop()
    return P(*entries)


def shard_tensor(x, mesh: ProcessMesh, dim_mapping: Sequence[int]):
    """Annotate tensor ``x``: tensor dim i is split over mesh dim
    ``dim_mapping[i]`` (-1 = replicated on that dim). Reference
    interface.py:295.

    Eager Tensors/Parameters keep the PartitionSpec on ``.sharding`` (the
    engine reads it); arrays inside a jit trace additionally get a
    ``with_sharding_constraint`` so GSPMD pins the layout at this point.
    """
    if not isinstance(x, Tensor):
        x = Tensor(x)
    spec = _spec_from_mapping(mesh, dim_mapping, x.ndim)
    attrs = _attrs_for(x)
    attrs["mesh"] = mesh
    attrs["dim_mapping"] = list(dim_mapping)
    x.sharding = spec
    data = x._data
    if isinstance(data, jax.core.Tracer):
        jmesh = get_mesh() or mesh.as_jax_mesh()
        x._data = jax.lax.with_sharding_constraint(
            data, jax.sharding.NamedSharding(jmesh, spec))
    return x


def shard_op(op_fn, mesh: ProcessMesh, dim_mapping_dict=None, **kwargs):
    """Run ``op_fn(**kwargs)`` with sharding annotations (reference
    interface.py:383).

    ``dim_mapping_dict`` maps *kwarg names* to dim_mappings (annotating the
    op's inputs) and/or integer output indices to dim_mappings (annotating
    the op's outputs). With None, the op runs unannotated and GSPMD
    propagates shardings through it — the analog of the reference's
    completion pass filling in unspecified dist attrs.
    """
    dim_mapping_dict = dict(dim_mapping_dict or {})
    for name, arg in list(kwargs.items()):
        if name in dim_mapping_dict and isinstance(arg, Tensor):
            kwargs[name] = shard_tensor(arg, mesh, dim_mapping_dict[name])
    out = op_fn(**kwargs)
    outs = list(out) if isinstance(out, (tuple, list)) else [out]
    for i, o in enumerate(outs):
        if i in dim_mapping_dict and isinstance(o, Tensor):
            outs[i] = shard_tensor(o, mesh, dim_mapping_dict[i])
    if isinstance(out, tuple):
        return tuple(outs)
    if isinstance(out, list):
        return outs
    return outs[0]


def set_shard_mask(x, mask):
    """Reference interface.py:331 keeps a tensor off some processes of its
    mesh. GSPMD has no per-device placement mask — a PartitionSpec either
    shards or replicates a dim — so the mask is recorded as metadata and
    placement stays with the partitioner. Recorded, advisory only."""
    if not isinstance(x, Tensor):
        raise TypeError("set_shard_mask expects a Tensor")
    attrs = _attrs_for(x)
    if "mesh" not in attrs:
        raise RuntimeError("set process mesh for the tensor first "
                           "(shard_tensor)")
    np_mask = np.array(mask)
    if list(np_mask.shape) != attrs["mesh"].topology:
        raise ValueError("mask shape must equal the mesh topology")
    if not np.isin(np_mask, (0, 1)).all():
        raise ValueError("mask values must be 0 or 1")
    attrs["mask"] = np_mask.tolist()
    warnings.warn("set_shard_mask is advisory on TPU: GSPMD decides "
                  "physical placement; the mask is recorded in the "
                  "tensor's dist attrs only")
    return x


def set_offload_device(x, device):
    """Reference interface.py:440 pins a tensor to an offload device
    ("cpu"). Recorded as metadata; the TPU runtime keeps persistent state
    in HBM (host offload is a jax.device_put decision at checkpoint time,
    framework/checkpoint.py)."""
    if not isinstance(x, Tensor):
        raise TypeError("set_offload_device expects a Tensor")
    _attrs_for(x)["offload_device"] = str(device)
    return x


def set_pipeline_stage(stage):
    """Reference interface.py:468 sets the current pipeline stage for
    subsequently created ops. Here it tags the global context; PipelineLayer
    / LayerDesc stage assignment is the mechanism that actually places
    layers (fleet/meta_parallel/pp_layers.py)."""
    global _current_pipeline_stage
    _current_pipeline_stage = int(stage)


_current_pipeline_stage = 0


def get_pipeline_stage() -> int:
    return _current_pipeline_stage
