"""Sharding helpers shared by the parallel layers and train-step builders."""
from __future__ import annotations

from typing import Optional

import jax
from jax.sharding import NamedSharding, PartitionSpec

from ..framework.core import Tensor
from . import env

__all__ = ["P", "shard_constraint", "named_sharding", "current_mesh"]

P = PartitionSpec


def current_mesh():
    return env.global_mesh()


def named_sharding(*spec):
    mesh = current_mesh()
    if mesh is None:
        return None
    return NamedSharding(mesh, PartitionSpec(*spec))


def shard_constraint(x, *spec):
    """Annotate x (Tensor or array) with a PartitionSpec on the global mesh.

    Inside a jit trace this becomes a GSPMD sharding constraint (the
    TPU-native replacement for the reference's explicit c_identity /
    _c_split collective ops, collective.py:747-920). Outside a trace, or
    with no mesh initialized, it is a no-op.
    """
    mesh = current_mesh()
    if mesh is None:
        return x
    ns = NamedSharding(mesh, PartitionSpec(*spec))
    if isinstance(x, Tensor):
        if isinstance(x._data, jax.core.Tracer):
            x._data = jax.lax.with_sharding_constraint(x._data, ns)
        return x
    if isinstance(x, jax.core.Tracer):
        return jax.lax.with_sharding_constraint(x, ns)
    return x
