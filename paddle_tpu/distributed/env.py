"""Process/device environment for distribution.

Replaces the reference's rank/env plumbing (PaddleCloudRoleMaker env vars,
fleet/base/role_maker.py:530). TPU-native model: ONE process drives N local
devices (or multi-host via jax.distributed); "rank" maps to a mesh
coordinate, not a process. For API parity we expose rank/world_size in
terms of the data-parallel axis of the active global mesh.
"""
from __future__ import annotations

import os
from typing import Optional

import jax
import numpy as np

_state = {
    "initialized": False,
    "mesh": None,          # jax.sharding.Mesh, the global hybrid mesh
    "topology": None,      # CommunicateTopology
    "hcg": None,           # HybridCommunicateGroup
    "rank": 0,
    "world_size": 1,
}


def _devices():
    return jax.devices()


def is_initialized() -> bool:
    return _state["initialized"]


def get_rank() -> int:
    if not _state["initialized"]:
        return int(os.environ.get("PADDLE_TRAINER_ID", 0))
    return _state["rank"]


def get_world_size() -> int:
    if not _state["initialized"]:
        return int(os.environ.get("PADDLE_TRAINERS_NUM", 1))
    return _state["world_size"]


def set_state(**kwargs):
    _state.update(kwargs)


def get_state():
    return _state


def global_mesh():
    return _state["mesh"]


def init_parallel_env(mesh_shape=None, axis_names=None):
    """paddle.distributed.init_parallel_env parity.

    Reference (parallel.py:69) bootstraps NCCL rings over TCP; here we build
    the global device mesh. Default: 1-D "data" mesh over all local devices.
    Multi-host: the launcher exports JAX_COORDINATOR_ADDRESS/JAX_PROCESS_ID
    and jax.distributed.initialize is called here before touching devices.
    """
    n_procs = int(os.environ.get("JAX_NUM_PROCESSES", 1))
    if n_procs > 1 and not _state["initialized"]:
        try:
            jax.distributed.initialize(
                coordinator_address=os.environ["JAX_COORDINATOR_ADDRESS"],
                num_processes=n_procs,
                process_id=int(os.environ.get("JAX_PROCESS_ID", 0)))
        except RuntimeError as e:
            # Only the double-init case is benign; a genuine bootstrap
            # failure (bad coordinator address, bind failure) must not
            # silently degrade to a wrong single-process world view.
            # jax 0.9 phrases double-init as "should only be called once".
            msg = str(e).lower()
            if ("already initialized" not in msg
                    and "only be called once" not in msg):
                raise
    devs = np.array(_devices())
    if mesh_shape is None:
        mesh_shape = (len(devs),)
        axis_names = axis_names or ("data",)
    mesh = jax.sharding.Mesh(devs.reshape(mesh_shape), axis_names)
    _state.update({
        "initialized": True,
        "mesh": mesh,
        "rank": jax.process_index(),
        "world_size": max(jax.process_count(), 1),
    })
    from ..parallel.mesh import set_mesh

    set_mesh(mesh)
    return ParallelEnv()


class ParallelEnv:
    """Reference python/paddle/fluid/dygraph/parallel.py ParallelEnv parity."""

    @property
    def rank(self):
        return get_rank()

    @property
    def local_rank(self):
        return get_rank()

    @property
    def world_size(self):
        return get_world_size()

    @property
    def nranks(self):
        return get_world_size()

    @property
    def device_id(self):
        return 0

    @property
    def current_endpoint(self):
        return os.environ.get("PADDLE_CURRENT_ENDPOINT", "127.0.0.1:6170")

    @property
    def trainer_endpoints(self):
        eps = os.environ.get("PADDLE_TRAINER_ENDPOINTS", "127.0.0.1:6170")
        return eps.split(",")
