"""DataParallel wrapper — eager SPMD data parallelism.

Parity: reference python/paddle/fluid/dygraph/parallel.py:389 (DataParallel)
+ C++ Reducer (imperative/reducer.cc:648-971). The reference makes each
process compute on its batch shard and bucket-allreduces gradients over
NCCL. TPU-native redesign: ONE process drives all devices of the mesh's
"data" axis; DataParallel

1. replicates parameters across the mesh at construction (the analog of
   the reference's startup param broadcast, hybrid_parallel_util.py:111),
2. shards each forward input's leading (batch) dim over the data axis,
3. lets GSPMD propagate shardings through every eager op — where an op
   contracts the sharded batch dim (loss reductions, weight gradients),
   XLA inserts the cross-device reduction that the Reducer did by hand.

So after ``loss.backward()`` each parameter's ``grad`` is already the
full-batch gradient, replicated on every device: ``apply_collective_grads``
verifies this instead of communicating. ``scale_loss`` is identity because
the mean over the globally sharded batch is already the global mean.

Multi-process eager DDP is not supported — use the launcher + compiled
DistributedTrainStep (fleet.distributed_model routes there).
"""
from __future__ import annotations

from contextlib import contextmanager
from typing import Optional

import jax
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from ..framework.core import Tensor
from ..nn.layer.layers import Layer
from . import env

__all__ = ["DataParallel"]


def _data_mesh():
    from ..parallel.mesh import get_mesh

    mesh = get_mesh()
    if mesh is None or "data" not in mesh.shape:
        return None
    return mesh


class DataParallel(Layer):
    """``find_unused_parameters`` is accepted for API parity and is a
    DOCUMENTED NO-OP: the reference Reducer needs unused-variable
    detection (imperative/reducer.cc:972) because its per-grad allreduce
    hooks would wait forever on grads that never arrive; here the grads
    are produced by whole-graph autodiff and reduced in one pass over
    whatever grads exist, so unused parameters simply contribute nothing
    — there is no hook to unblock (README 'find_unused_parameters')."""

    def __init__(self, layers, strategy=None, comm_buffer_size=25,
                 last_comm_buffer_size=1, find_unused_parameters=False,
                 group=None):
        super().__init__()
        self._layers = layers
        self.find_unused_parameters = find_unused_parameters
        self._group = group
        if jax.process_count() > 1:
            raise NotImplementedError(
                "multi-process eager DataParallel is not supported on TPU; "
                "launch one process and let DataParallel shard over the "
                "local mesh, or use fleet.distributed_model (compiled "
                "DistributedTrainStep) for multi-host training.")
        self._mesh = _data_mesh()
        if self._mesh is not None:
            self._replicate_params()

    # -- setup ----------------------------------------------------------

    def _replicate_params(self):
        """Startup broadcast analog: place every parameter replicated on
        the mesh so each device holds the same copy."""
        repl = NamedSharding(self._mesh, P())
        for p in self._layers.parameters():
            p._data = jax.device_put(p._data, repl)

    def _shard_batch(self, x):
        """Shard an input tensor's leading dim over the data axis."""
        if self._mesh is None:
            return x
        n = self._mesh.shape["data"]
        arr = x._data if isinstance(x, Tensor) else x
        if not hasattr(arr, "ndim") or arr.ndim == 0 or arr.shape[0] % n != 0:
            return x  # unshardable input passes through replicated
        sh = NamedSharding(self._mesh, P("data"))
        arr = jax.device_put(arr, sh)
        if isinstance(x, Tensor):
            x._data = arr
            return x
        return Tensor(arr)

    # -- forward --------------------------------------------------------

    def forward(self, *inputs, **kwargs):
        inputs = tuple(self._shard_batch(x) for x in inputs)
        kwargs = {k: self._shard_batch(v) for k, v in kwargs.items()}
        return self._layers(*inputs, **kwargs)

    # passthrough the wrapped module's state (reference behavior)
    def state_dict(self, *args, **kwargs):
        return self._layers.state_dict(*args, **kwargs)

    def set_state_dict(self, state_dict, *args, **kwargs):
        return self._layers.set_state_dict(state_dict, *args, **kwargs)

    set_dict = set_state_dict
    load_dict = set_state_dict

    def parameters(self, *args, **kwargs):
        return self._layers.parameters(*args, **kwargs)

    def scale_loss(self, loss):
        """Identity: the loss mean over the sharded global batch already
        is the global-batch mean (reference scales by 1/nranks because
        each process only saw 1/nranks of the batch)."""
        return loss

    def apply_collective_grads(self):
        """Reducer.FusedAllReduceSchedule analog. Under GSPMD the weight
        gradients come out of backward already reduced across the data
        axis; this re-asserts the replicated placement (a no-op collective
        when XLA already replicated them, the reduction otherwise)."""
        if self._mesh is None:
            return
        repl = NamedSharding(self._mesh, P())
        for p in self._layers.parameters():
            g = getattr(p, "grad", None)
            if g is not None and isinstance(g, Tensor):
                g._data = jax.device_put(g._data, repl)

    @contextmanager
    def no_sync(self):
        """Gradients are produced reduced under GSPMD; there is no deferred
        communication to skip, so no_sync is the identity (kept for API
        parity with reference parallel.py:656)."""
        yield
