"""DataParallel wrapper.

Parity: reference python/paddle/fluid/dygraph/parallel.py:389 (DataParallel)
+ C++ Reducer (imperative/reducer.cc). TPU-native: there is no per-process
NCCL ring to bucket gradients for — XLA fuses the grad all-reduce into the
compiled step. Eager semantics:

- world_size==1 (single process driving N devices): passthrough; the
  multi-device speedup comes from the jit'd TrainStep over the mesh (data
  axis sharding replaces the Reducer entirely).
- multi-process (jax.distributed): gradient sync happens inside the jit'd
  step via psum; the eager hook path averages grads across processes lazily
  on backward completion for API parity with `loss.backward()` + `opt.step()`.
"""
from __future__ import annotations

from typing import Optional

from ..framework.core import Tensor
from ..nn.layer.layers import Layer
from . import env

__all__ = ["DataParallel"]


class DataParallel(Layer):
    def __init__(self, layers, strategy=None, comm_buffer_size=25,
                 last_comm_buffer_size=1, find_unused_parameters=False,
                 group=None):
        super().__init__()
        self._layers = layers
        self.find_unused_parameters = find_unused_parameters
        self._group = group

    def forward(self, *inputs, **kwargs):
        return self._layers(*inputs, **kwargs)

    # passthrough the wrapped module's state (reference behavior)
    def state_dict(self, *args, **kwargs):
        return self._layers.state_dict(*args, **kwargs)

    def set_state_dict(self, state_dict, *args, **kwargs):
        return self._layers.set_state_dict(state_dict, *args, **kwargs)

    set_dict = set_state_dict
    load_dict = set_state_dict

    def scale_loss(self, loss):
        return loss

    def apply_collective_grads(self):
        # grads are synchronized inside the compiled step on TPU
        pass

    from contextlib import contextmanager

    @contextmanager
    def no_sync(self):
        yield
