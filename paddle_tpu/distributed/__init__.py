"""paddle_tpu.distributed (mirrors paddle.distributed).

The NCCL-ring world of the reference (collective.py + fleet) rebuilt on the
jax.sharding Mesh + XLA collectives. See SURVEY.md §2.3 / §5 for the
correspondence table.
"""
from .env import (  # noqa: F401
    init_parallel_env, get_rank, get_world_size, ParallelEnv, is_initialized,
    global_mesh,
)
from .collective import (  # noqa: F401
    ReduceOp, Group, new_group, get_group, all_reduce, reduce, broadcast,
    all_gather, scatter, alltoall, send, recv, sendrecv, barrier, wait,
    destroy_process_group, split, psum, pmax, pmin, pmean,
)
from .parallel import DataParallel  # noqa: F401
from .sharding_utils import P, shard_constraint, named_sharding, current_mesh  # noqa: F401
from .auto_parallel import (  # noqa: F401
    ProcessMesh, shard_tensor, shard_op, set_shard_mask, set_offload_device,
    set_pipeline_stage)
from . import auto_parallel  # noqa: F401
from . import fleet  # noqa: F401
from . import launch  # noqa: F401
from .fleet.dataset import (  # noqa: F401
    InMemoryDataset, QueueDataset, CountFilterEntry, ProbabilityEntry,
)


def spawn(func, args=(), nprocs=-1, join=True, daemon=False, **options):
    """reference spawn.py:394 — process-per-device launch. On TPU one
    process drives all local devices, so spawn degenerates to a direct call
    (multi-host uses the launcher + jax.distributed)."""
    func(*args)


def get_device_count():
    import jax

    return jax.device_count()

def gloo_init_parallel_env(rank_id, rank_num, server_endpoint):
    """Reference parallel.py gloo CPU bootstrap. The mesh runtime needs no
    TCP rendezvous (jax.distributed owns multi-host init), so this only
    validates arguments and marks the env initialized."""
    from .env import init_parallel_env

    init_parallel_env()


def gloo_barrier():
    from .collective import barrier

    barrier()


def gloo_release():
    """No gloo store to tear down — kept for API parity."""
