"""Collective communication API.

Parity: reference python/paddle/distributed/collective.py (all_reduce,
broadcast, all_gather, ...) over NCCL ring communicators
(paddle/fluid/operators/collective/, platform/collective_helper.h:68).

TPU-native redesign: a "group" is a named mesh axis (or tuple of axes), not
a ring_id. Collectives have two execution regimes:

1. **Traced** (inside shard_map over the global mesh — the performance
   path): lower directly to lax.psum/all_gather/ppermute; XLA emits ICI
   collectives.
2. **Eager, single process**: the reference's "one process per rank"
   becomes "one mesh-axis slot per rank". Eager collectives take the
   **rank-major layout**: ``tensor.shape[0] == group.nranks``, slice ``i``
   being rank i's tensor. The op executes on the devices through a jitted
   ``shard_map`` over the group's axis (XLA emits the real collective),
   and every rank's result comes back in the same layout. A group of size
   1 is the identity, as in the reference. Anything else raises — a
   collective must never silently return its input.
"""
from __future__ import annotations

import functools
from typing import List, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from ..framework.core import Tensor
from ..monitor import stats as _mstats
from . import env

__all__ = [
    "ReduceOp", "Group", "new_group", "get_group", "all_reduce", "reduce",
    "broadcast", "all_gather", "scatter", "alltoall", "send", "recv",
    "sendrecv", "barrier", "split", "wait", "destroy_process_group",
]


class ReduceOp:
    SUM = 0
    MAX = 1
    MIN = 2
    PROD = 3
    AVG = 4


class Group:
    """A communicator: names a mesh axis (traced) / rank list (bookkeeping)."""

    def __init__(self, rank, nranks, id=0, ranks=None, axis_name=None):  # noqa: A002
        self.rank = rank
        self.nranks = nranks
        self.id = id
        self.ranks = ranks or list(range(nranks))
        self.axis_name = axis_name  # mesh axis this group maps onto

    @property
    def world_size(self):
        return self.nranks

    def get_group_rank(self, rank):
        return self.ranks.index(rank) if rank in self.ranks else -1

    def __repr__(self):
        return f"Group(rank={self.rank}, nranks={self.nranks}, axis={self.axis_name})"


_default_group: List[Optional[Group]] = [None]
_groups = {}
_next_gid = [1]


def _get_default_group() -> Group:
    if _default_group[0] is None:
        _default_group[0] = Group(env.get_rank(), max(env.get_world_size(), 1),
                                  id=0, axis_name="data")
        _groups[0] = _default_group[0]
    return _default_group[0]


def get_group(gid=0):
    return _groups.get(gid, _get_default_group())


def new_group(ranks=None, backend=None, axis_name=None):
    """reference collective.py:209 — creates a ring; here: names a sub-axis."""
    gid = _next_gid[0]
    _next_gid[0] += 1
    myrank = env.get_rank()
    ranks = ranks if ranks is not None else list(range(env.get_world_size()))
    g = Group(ranks.index(myrank) if myrank in ranks else -1, len(ranks),
              id=gid, ranks=ranks, axis_name=axis_name)
    _groups[gid] = g
    return g


def _axis_in_trace(x) -> bool:
    """True if x is a tracer inside shard_map (axis names bound)."""
    return isinstance(x, jax.core.Tracer)


def _count(opname: str) -> None:
    """Collective launch counters (monitor.h STAT_ADD analog): the
    aggregate ``collective_calls`` plus a per-op ``collective_<name>``."""
    _mstats.COLLECTIVE_CALLS.add()
    _mstats.stat_add("collective_" + opname)


def _axis_name(group: Optional[Group]):
    g = group or _get_default_group()
    return g.axis_name or "data"


# -- eager execution over the mesh ------------------------------------------

def _eager_setup(arr, group, opname):
    """Resolve (mesh, axis, nranks) for an eager collective; validate the
    rank-major layout. Raises instead of silently passing data through."""
    from ..parallel.mesh import get_mesh

    g = group or _get_default_group()
    axis = g.axis_name or "data"
    mesh = get_mesh()
    if mesh is None or axis not in mesh.shape:
        raise RuntimeError(
            f"distributed.{opname}: no device mesh with axis '{axis}' is "
            f"active. Create one (paddle_tpu.parallel.create_mesh or "
            f"init_parallel_env) before eager collectives, or call the op "
            f"inside shard_map.")
    n = mesh.shape[axis]
    if env.get_world_size() > 1:
        raise NotImplementedError(
            f"distributed.{opname}: eager collectives across processes are "
            f"not supported; use the compiled path (DistributedTrainStep) "
            f"or in-trace collectives under shard_map.")
    if g.nranks not in (1, n):
        raise RuntimeError(
            f"distributed.{opname}: group has {g.nranks} ranks but mesh "
            f"axis '{axis}' has {n} slots.")
    if arr.ndim == 0 or arr.shape[0] != n:
        raise RuntimeError(
            f"distributed.{opname}: eager single-process collectives use "
            f"the rank-major layout — tensor.shape[0] must equal the group "
            f"size ({n}); got shape {tuple(arr.shape)}. Each slice [i] is "
            f"rank i's tensor.")
    return mesh, axis, n


@functools.lru_cache(maxsize=256)
def _eager_fn(kind, axis, mesh, extra=None):
    """Build + cache the jitted shard_map program for an eager collective.
    The mesh itself is part of the cache key — two meshes with the same
    axis name/size but different device layouts must not share programs."""
    from jax.experimental.shard_map import shard_map

    spec = P(axis)

    if kind == "all_reduce":
        red = {ReduceOp.SUM: jax.lax.psum, ReduceOp.MAX: jax.lax.pmax,
               ReduceOp.MIN: jax.lax.pmin, ReduceOp.AVG: jax.lax.pmean}[extra]
        body = lambda x: red(x, axis)
    elif kind == "reduce":
        dst, op = extra
        red = {ReduceOp.SUM: jax.lax.psum, ReduceOp.MAX: jax.lax.pmax,
               ReduceOp.MIN: jax.lax.pmin, ReduceOp.AVG: jax.lax.pmean}[op]

        def body(x):
            total = red(x, axis)
            idx = jax.lax.axis_index(axis)
            keep = (idx == dst)
            return jnp.where(keep, total, x)
    elif kind == "broadcast":
        src = extra

        def body(x):
            idx = jax.lax.axis_index(axis)
            return jax.lax.psum(jnp.where(idx == src, x, jnp.zeros_like(x)),
                                axis)
    elif kind == "all_gather":
        body = lambda x: jax.lax.all_gather(x, axis, tiled=True)
    elif kind == "alltoall":
        body = lambda x: jax.lax.all_to_all(x, axis, split_axis=0,
                                            concat_axis=0, tiled=True)
    elif kind == "ppermute":
        perm = extra
        body = lambda x: jax.lax.ppermute(x, axis, list(perm))
    else:  # pragma: no cover
        raise ValueError(kind)

    return jax.jit(shard_map(body, mesh=mesh, in_specs=spec, out_specs=spec,
                             check_rep=False))


def _run_eager(kind, arr, group, opname, extra=None):
    mesh, axis, n = _eager_setup(arr, group, opname)
    if n == 1:
        return arr
    with mesh:
        return _eager_fn(kind, axis, mesh, extra)(arr)


def _unwrap(t):
    return t._data if isinstance(t, Tensor) else t


def _rewrap(tensor, out):
    if isinstance(tensor, Tensor):
        tensor._data = out
        return tensor
    return out


# Pure collective fns usable on arrays inside shard_map --------------------

def psum(x, axis_name):
    return jax.lax.psum(x, axis_name)


def pmax(x, axis_name):
    return jax.lax.pmax(x, axis_name)


def pmin(x, axis_name):
    return jax.lax.pmin(x, axis_name)


def pmean(x, axis_name):
    return jax.lax.pmean(x, axis_name)


# Cross-process eager path (reference imperative/nccl_context.cc: eager
# collectives work per-process over NCCL rings). TPU-native analog: the
# multi-controller runtime's process_allgather (host-driven, rides the
# same ICI/DCN transport jax.distributed set up). Covers the utility uses
# the reference's eager path serves — metric all-reduce, eval-loop
# broadcast, checkpoint-decision gathers; send/recv/alltoall stay
# compiled-only (README 'eager collectives decision').

def _multihost_eager(kind, arr, group, extra=None):
    from jax.experimental import multihost_utils

    g = group or _get_default_group()
    if g.nranks != env.get_world_size():
        raise NotImplementedError(
            "cross-process eager collectives support only the full-world "
            "group (subgroup rings need the compiled path)")
    gathered = multihost_utils.process_allgather(np.asarray(arr))
    if kind == "all_gather":
        return gathered
    if kind == "broadcast":
        return jnp.asarray(gathered[int(extra)])
    op = extra
    if op == ReduceOp.SUM:
        return jnp.asarray(gathered.sum(axis=0))
    if op == ReduceOp.MAX:
        return jnp.asarray(gathered.max(axis=0))
    if op == ReduceOp.MIN:
        return jnp.asarray(gathered.min(axis=0))
    if op == ReduceOp.AVG:
        return jnp.asarray(gathered.mean(axis=0))
    raise ValueError(f"unsupported ReduceOp {op}")


def _multi_process() -> bool:
    return env.get_world_size() > 1


# Tensor-level API ---------------------------------------------------------

def all_reduce(tensor, op=ReduceOp.SUM, group=None, sync_op=True,
               use_calc_stream=True):
    _count("all_reduce")
    arr = _unwrap(tensor)
    if _axis_in_trace(arr):
        fn = {ReduceOp.SUM: jax.lax.psum, ReduceOp.MAX: jax.lax.pmax,
              ReduceOp.MIN: jax.lax.pmin, ReduceOp.AVG: jax.lax.pmean}[op]
        return _rewrap(tensor, fn(arr, _axis_name(group)))
    if _multi_process():
        return _rewrap(tensor, _multihost_eager("all_reduce", arr, group, op))
    return _rewrap(tensor, _run_eager("all_reduce", arr, group,
                                      "all_reduce", op))


def reduce(tensor, dst=0, op=ReduceOp.SUM, group=None, sync_op=True):
    _count("reduce")
    arr = _unwrap(tensor)
    if _axis_in_trace(arr):
        axis = _axis_name(group)
        fn = {ReduceOp.SUM: jax.lax.psum, ReduceOp.MAX: jax.lax.pmax,
              ReduceOp.MIN: jax.lax.pmin, ReduceOp.AVG: jax.lax.pmean}[op]
        total = fn(arr, axis)
        idx = jax.lax.axis_index(axis)
        return _rewrap(tensor, jnp.where(idx == dst, total, arr))
    if _multi_process():
        # every process computes the reduction; non-dst ranks keeping the
        # value is harmless (reference leaves their buffers undefined)
        return _rewrap(tensor, _multihost_eager("reduce", arr, group, op))
    return _rewrap(tensor, _run_eager("reduce", arr, group, "reduce",
                                      (int(dst), op)))


def broadcast(tensor, src=0, group=None, sync_op=True):
    _count("broadcast")
    arr = _unwrap(tensor)
    if _axis_in_trace(arr):
        axis = _axis_name(group)
        idx = jax.lax.axis_index(axis)
        out = jax.lax.psum(jnp.where(idx == src, arr, jnp.zeros_like(arr)),
                           axis)
        return _rewrap(tensor, out)
    if _multi_process():
        return _rewrap(tensor, _multihost_eager("broadcast", arr, group,
                                                int(src)))
    return _rewrap(tensor, _run_eager("broadcast", arr, group, "broadcast",
                                      int(src)))


def all_gather(tensor_list, tensor, group=None, sync_op=True, axis=0):
    _count("all_gather")
    arr = _unwrap(tensor)
    if _axis_in_trace(arr):
        ax = _axis_name(group)
        out = jax.lax.all_gather(arr, ax)
        n = out.shape[0]
        if isinstance(tensor_list, list):
            tensor_list.extend(Tensor(out[i]) for i in range(n))
            return tensor_list
        return out
    if _multi_process():
        gathered = _multihost_eager("all_gather", arr, group)
        if isinstance(tensor_list, list):
            tensor_list.extend(Tensor(jnp.asarray(g))
                               for g in gathered)
            return tensor_list
        return jnp.asarray(gathered)
    mesh, ax, n = _eager_setup(arr, group, "all_gather")
    # rank-major input already holds every rank's tensor; still run the
    # real collective so the mesh path is exercised, then unstack. Each
    # device's tiled gather contributes a full copy — take the first.
    if n > 1:
        with mesh:
            gathered = _eager_fn("all_gather", ax, mesh)(arr)
        out_rows = [gathered[i] for i in range(n)]
    else:
        out_rows = [arr[0]]
    if isinstance(tensor_list, list):
        tensor_list.extend(Tensor(r) for r in out_rows)
        return tensor_list
    return jnp.stack(out_rows)


def scatter(tensor, tensor_list=None, src=0, group=None, sync_op=True):
    _count("scatter")
    if tensor_list is None or not len(tensor_list):
        raise ValueError("distributed.scatter needs tensor_list on src")
    arrs = [_unwrap(t) for t in tensor_list]
    if _axis_in_trace(arrs[0]):
        ax = _axis_name(group)
        stacked = jnp.stack(arrs)
        idx = jax.lax.axis_index(ax)
        picked = jnp.take(stacked, idx, axis=0)
        return _rewrap(tensor, picked)
    if _multi_process():
        # README 'eager collectives decision': scatter across processes is
        # compiled-path only — fail loudly, never return local-only data
        raise NotImplementedError(
            "distributed.scatter: eager cross-process scatter is not "
            "supported; use the compiled path (shard_map) — see README "
            "'Eager-mode collective semantics'")
    # eager rank-major: rank i receives tensor_list[i]
    out = jnp.stack(arrs)
    return _rewrap(tensor, out)


def alltoall(in_tensor_list, out_tensor_list, group=None, sync_op=True):
    _count("alltoall")
    arrs = [_unwrap(t) for t in in_tensor_list]
    if arrs and _axis_in_trace(arrs[0]):
        ax = _axis_name(group)
        stacked = jnp.stack(arrs)
        out = jax.lax.all_to_all(stacked, ax, split_axis=0, concat_axis=0,
                                 tiled=False)
        out_tensor_list.extend(Tensor(out[i]) for i in range(out.shape[0]))
        return out_tensor_list
    # eager rank-major: in_tensor_list[i] has leading dim nranks;
    # out[j] slice i = in[i] slice j  (transpose ranks <-> chunks)
    stacked = jnp.stack(arrs)  # [n_in, n, ...]
    mesh, ax, n = _eager_setup(stacked[0], group, "alltoall")
    if stacked.shape[0] != n:
        raise RuntimeError(
            f"alltoall: need one input tensor per rank ({n}); got "
            f"{stacked.shape[0]}")
    out = jnp.swapaxes(stacked, 0, 1)
    out_tensor_list.extend(Tensor(out[i]) for i in range(out.shape[0]))
    return out_tensor_list


def sendrecv(tensor, perm, group=None):
    """SPMD point-to-point: CollectivePermute with explicit (src, dst)
    pairs — the mesh-native form of the reference's send_v2/recv_v2 pair
    (operators/collective/send_v2_op.cc). Works in-trace and eagerly
    (rank-major layout)."""
    _count("sendrecv")
    arr = _unwrap(tensor)
    perm = tuple((int(s), int(d)) for s, d in perm)
    if _axis_in_trace(arr):
        return _rewrap(tensor, jax.lax.ppermute(arr, _axis_name(group), list(perm)))
    return _rewrap(tensor, _run_eager("ppermute", arr, group, "sendrecv", perm))


def send(tensor, dst=0, group=None, sync_op=True, src=None):
    """P2P send. In SPMD every device runs the same program, so the
    (src, dst) pair must be explicit: pass src= or use sendrecv()."""
    _count("send")
    arr = _unwrap(tensor)
    if _axis_in_trace(arr):
        if src is None:
            raise ValueError(
                "distributed.send inside a trace needs an explicit src rank "
                "(SPMD programs are identical on every device; the process "
                "rank is meaningless here). Use send(tensor, dst, src=s) or "
                "sendrecv(tensor, [(s, d)]).")
        return _rewrap(tensor, jax.lax.ppermute(
            arr, _axis_name(group), [(int(src), int(dst))]))
    if src is None:
        raise NotImplementedError(
            "distributed.send: one-sided eager p2p has no single-process "
            "SPMD meaning; use sendrecv(tensor, [(src, dst)]).")
    return sendrecv(tensor, [(int(src), int(dst))], group)


def recv(tensor, src=0, group=None, sync_op=True, dst=None):
    """P2P recv — the receiving half of sendrecv. See send()."""
    _count("recv")
    arr = _unwrap(tensor)
    if _axis_in_trace(arr):
        if dst is None:
            raise ValueError(
                "distributed.recv inside a trace needs an explicit dst rank; "
                "use recv(tensor, src, dst=d) or sendrecv(tensor, [(s, d)]).")
        return _rewrap(tensor, jax.lax.ppermute(
            arr, _axis_name(group), [(int(src), int(dst))]))
    if dst is None:
        raise NotImplementedError(
            "distributed.recv: one-sided eager p2p has no single-process "
            "SPMD meaning; use sendrecv(tensor, [(src, dst)]).")
    return sendrecv(tensor, [(int(src), int(dst))], group)


def barrier(group=None):
    _count("barrier")
    if _multi_process():
        from jax.experimental import multihost_utils

        multihost_utils.sync_global_devices("paddle_tpu.distributed.barrier")
        return
    jax.effects_barrier() if hasattr(jax, "effects_barrier") else None
    (jnp.zeros(()) + 0).block_until_ready()


def wait(tensor, group=None, use_calc_stream=True):
    if isinstance(tensor, Tensor):
        tensor.block_until_ready()
    return tensor


def destroy_process_group(group=None):
    _groups.clear()
    _default_group[0] = None
    _eager_fn.cache_clear()


def split(x, size, operation, axis=0, num_partitions=1, gather_out=True,
          weight_attr=None, bias_attr=None, name=None):
    """reference collective.py split — sharded layer factory; provided via
    fleet.meta_parallel Parallel layers instead."""
    raise NotImplementedError(
        "use paddle_tpu.distributed.fleet.meta_parallel ColumnParallelLinear/"
        "RowParallelLinear/VocabParallelEmbedding")
