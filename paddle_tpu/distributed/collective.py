"""Collective communication API.

Parity: reference python/paddle/distributed/collective.py (all_reduce,
broadcast, all_gather, ...) over NCCL ring communicators
(paddle/fluid/operators/collective/, platform/collective_helper.h:68).

TPU-native redesign: a "group" is a named mesh axis (or tuple of axes), not
a ring_id. Collectives have two execution regimes:

1. **Traced** (inside shard_map over the global mesh — the performance
   path): lower directly to lax.psum/all_gather/ppermute; XLA emits ICI
   collectives.
2. **Eager single-process**: the world is this process; ops are identity
   (world_size 1 per process) matching reference semantics where each
   process holds one shard. Cross-device eager work is done by jit'ing a
   shard_map over the group's mesh.
"""
from __future__ import annotations

from typing import List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from ..framework.core import Tensor, apply_op
from . import env

__all__ = [
    "ReduceOp", "Group", "new_group", "get_group", "all_reduce", "reduce",
    "broadcast", "all_gather", "scatter", "alltoall", "send", "recv",
    "barrier", "split", "wait", "destroy_process_group",
]


class ReduceOp:
    SUM = 0
    MAX = 1
    MIN = 2
    PROD = 3
    AVG = 4


class Group:
    """A communicator: names a mesh axis (traced) / rank list (bookkeeping)."""

    def __init__(self, rank, nranks, id=0, ranks=None, axis_name=None):  # noqa: A002
        self.rank = rank
        self.nranks = nranks
        self.id = id
        self.ranks = ranks or list(range(nranks))
        self.axis_name = axis_name  # mesh axis this group maps onto

    @property
    def world_size(self):
        return self.nranks

    def get_group_rank(self, rank):
        return self.ranks.index(rank) if rank in self.ranks else -1

    def __repr__(self):
        return f"Group(rank={self.rank}, nranks={self.nranks}, axis={self.axis_name})"


_default_group: List[Optional[Group]] = [None]
_groups = {}
_next_gid = [1]


def _get_default_group() -> Group:
    if _default_group[0] is None:
        _default_group[0] = Group(env.get_rank(), max(env.get_world_size(), 1),
                                  id=0, axis_name="data")
        _groups[0] = _default_group[0]
    return _default_group[0]


def get_group(gid=0):
    return _groups.get(gid, _get_default_group())


def new_group(ranks=None, backend=None, axis_name=None):
    """reference collective.py:209 — creates a ring; here: names a sub-axis."""
    gid = _next_gid[0]
    _next_gid[0] += 1
    myrank = env.get_rank()
    ranks = ranks if ranks is not None else list(range(env.get_world_size()))
    g = Group(ranks.index(myrank) if myrank in ranks else -1, len(ranks),
              id=gid, ranks=ranks, axis_name=axis_name)
    _groups[gid] = g
    return g


def _axis_in_trace(x) -> bool:
    """True if x is a tracer inside shard_map (axis names bound)."""
    return isinstance(x, jax.core.Tracer)


def _axis_name(group: Optional[Group]):
    g = group or _get_default_group()
    return g.axis_name or "data"


# Pure collective fns usable on arrays inside shard_map --------------------

def psum(x, axis_name):
    return jax.lax.psum(x, axis_name)


def pmax(x, axis_name):
    return jax.lax.pmax(x, axis_name)


def pmin(x, axis_name):
    return jax.lax.pmin(x, axis_name)


def pmean(x, axis_name):
    return jax.lax.pmean(x, axis_name)


# Tensor-level API ---------------------------------------------------------

def all_reduce(tensor, op=ReduceOp.SUM, group=None, sync_op=True, use_calc_stream=True):
    axis = _axis_name(group)
    arr = tensor._data if isinstance(tensor, Tensor) else tensor
    if _axis_in_trace(arr):
        fn = {ReduceOp.SUM: jax.lax.psum, ReduceOp.MAX: jax.lax.pmax,
              ReduceOp.MIN: jax.lax.pmin, ReduceOp.AVG: jax.lax.pmean}[op]
        out = fn(arr, axis)
        if isinstance(tensor, Tensor):
            tensor._data = out
            return tensor
        return out
    # eager single process: identity (world of one per process)
    return tensor


def reduce(tensor, dst=0, op=ReduceOp.SUM, group=None, sync_op=True):
    return all_reduce(tensor, op, group, sync_op)


def broadcast(tensor, src=0, group=None, sync_op=True):
    arr = tensor._data if isinstance(tensor, Tensor) else tensor
    if _axis_in_trace(arr):
        axis = _axis_name(group)
        idx = jax.lax.axis_index(axis)
        src_val = jax.lax.psum(jnp.where(idx == src, arr, jnp.zeros_like(arr)), axis)
        if isinstance(tensor, Tensor):
            tensor._data = src_val
            return tensor
        return src_val
    return tensor


def all_gather(tensor_list, tensor, group=None, sync_op=True, axis=0):
    arr = tensor._data if isinstance(tensor, Tensor) else tensor
    if _axis_in_trace(arr):
        ax = _axis_name(group)
        out = jax.lax.all_gather(arr, ax)
        n = out.shape[0]
        if isinstance(tensor_list, list):
            tensor_list.extend(Tensor(out[i]) for i in range(n))
            return tensor_list
        return out
    if isinstance(tensor_list, list):
        tensor_list.append(tensor.clone() if isinstance(tensor, Tensor) else Tensor(arr))
        return tensor_list
    return tensor


def scatter(tensor, tensor_list=None, src=0, group=None, sync_op=True):
    if tensor_list is not None and len(tensor_list):
        g = group or _get_default_group()
        tensor.set_value(tensor_list[g.rank if g.rank >= 0 else 0])
    return tensor


def alltoall(in_tensor_list, out_tensor_list, group=None, sync_op=True):
    arrs = [t._data if isinstance(t, Tensor) else t for t in in_tensor_list]
    if arrs and _axis_in_trace(arrs[0]):
        ax = _axis_name(group)
        stacked = jnp.stack(arrs)
        out = jax.lax.all_to_all(stacked, ax, split_axis=0, concat_axis=0, tiled=False)
        out_tensor_list.extend(Tensor(out[i]) for i in range(out.shape[0]))
        return out_tensor_list
    out_tensor_list.extend(in_tensor_list)
    return out_tensor_list


def send(tensor, dst=0, group=None, sync_op=True):
    arr = tensor._data if isinstance(tensor, Tensor) else tensor
    if _axis_in_trace(arr):
        ax = _axis_name(group)
        # point-to-point on a mesh axis = ppermute to dst
        src = jax.lax.axis_index(ax)
        del src
        return jax.lax.ppermute(arr, ax, [(env.get_rank(), dst)])
    return tensor


def recv(tensor, src=0, group=None, sync_op=True):
    return tensor


def barrier(group=None):
    jax.effects_barrier() if hasattr(jax, "effects_barrier") else None
    (jnp.zeros(()) + 0).block_until_ready()


def wait(tensor, group=None, use_calc_stream=True):
    if isinstance(tensor, Tensor):
        tensor.block_until_ready()
    return tensor


def destroy_process_group(group=None):
    _groups.clear()
    _default_group[0] = None


def split(x, size, operation, axis=0, num_partitions=1, gather_out=True,
          weight_attr=None, bias_attr=None, name=None):
    """reference collective.py split — sharded layer factory; provided via
    fleet.meta_parallel Parallel layers instead."""
    raise NotImplementedError(
        "use paddle_tpu.distributed.fleet.meta_parallel ColumnParallelLinear/"
        "RowParallelLinear/VocabParallelEmbedding")
