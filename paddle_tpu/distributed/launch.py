"""Distributed launcher — ``python -m paddle_tpu.distributed.launch``.

Parity: reference fleet launcher (python/paddle/distributed/fleet/
launch.py:250 launch_collective — builds a Cluster/Pod, spawns one worker
process per device with PADDLE_* env, watches children, aborts the pod on
failure) and the elastic relaunch loop (fleet/elastic/manager.py:103).

TPU-native process model: ONE worker process per HOST drives all local
chips (the reference's one-proc-per-GPU maps to jax's one-proc-per-host);
``--nproc_per_node`` exists for CPU rehearsal and multi-host emulation.
Workers get the jax.distributed coordinator env (the TCP bootstrap that
replaces the reference's gen_comm_id_helper NCCL-id rendezvous) plus the
PADDLE_* variables reference role-makers read. ``--elastic`` enables
supervised restarts: a failed worker pod is relaunched up to
``--max_restarts`` times, picking up from the newest checkpoint (see
framework/checkpoint.py CheckpointManager.restore_latest).
"""
from __future__ import annotations

import argparse
import os
import signal
import subprocess
import sys
import time
from typing import List, Optional

__all__ = ["launch", "elastic_launch", "main", "get_cluster_env", "wait_pod"]


def _free_port() -> int:
    import socket

    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def get_cluster_env(rank: int, nproc: int, coordinator: str,
                    endpoints: List[str]) -> dict:
    """Env block for one worker (reference launch_utils.py pod env)."""
    env = dict(os.environ)
    env.update({
        # reference PaddleCloudRoleMaker reads these (role_maker.py:692)
        "PADDLE_TRAINER_ID": str(rank),
        "PADDLE_TRAINERS_NUM": str(nproc),
        "PADDLE_CURRENT_ENDPOINT": endpoints[rank],
        "PADDLE_TRAINER_ENDPOINTS": ",".join(endpoints),
        # jax.distributed bootstrap (replaces NCCL-id TCP rendezvous)
        "JAX_COORDINATOR_ADDRESS": coordinator,
        "JAX_NUM_PROCESSES": str(nproc),
        "JAX_PROCESS_ID": str(rank),
    })
    return env


class Pod:
    """Local worker group (reference launch_utils.py:144 Pod)."""

    def __init__(self, procs: List[subprocess.Popen], log_files: List[str]):
        self.procs = procs
        self.log_files = log_files

    def poll(self) -> Optional[int]:
        """None while all alive; else the first non-zero exit code (0 when
        all exited cleanly)."""
        codes = [p.poll() for p in self.procs]
        if any(c is None for c in codes):
            for c in codes:
                if c not in (None, 0):
                    return c  # fail fast while others still run
            return None
        bad = [c for c in codes if c != 0]
        return bad[0] if bad else 0

    def terminate(self):
        for p in self.procs:
            if p.poll() is None:
                p.send_signal(signal.SIGTERM)
        # monotonic: a wall-clock step here would stretch/starve the
        # shared kill budget across workers (graftlint GL008)
        deadline = time.monotonic() + 10
        for p in self.procs:
            try:
                p.wait(timeout=max(0.1, deadline - time.monotonic()))
            except subprocess.TimeoutExpired:
                p.kill()


def start_pod(script: List[str], nproc: int, log_dir: Optional[str] = None,
              extra_env_of_rank=None) -> Pod:
    """Spawn nproc workers with cluster env (reference
    start_local_trainers)."""
    coordinator = f"127.0.0.1:{_free_port()}"
    endpoints = [f"127.0.0.1:{_free_port()}" for _ in range(nproc)]
    procs, logs = [], []
    for rank in range(nproc):
        env = get_cluster_env(rank, nproc, coordinator, endpoints)
        if extra_env_of_rank is not None:
            env.update(extra_env_of_rank(rank))
        stdout = None
        log_path = ""
        if log_dir:
            os.makedirs(log_dir, exist_ok=True)
            log_path = os.path.join(log_dir, f"workerlog.{rank}")
            stdout = open(log_path, "w")
        p = subprocess.Popen([sys.executable] + script, env=env,
                             stdout=stdout,
                             stderr=subprocess.STDOUT if stdout else None)
        procs.append(p)
        logs.append(log_path)
    return Pod(procs, logs)


def wait_pod(pod: Pod, poll_interval: float = 0.5) -> int:
    """Watch children; abort the pod when any worker fails (reference
    launch_utils.py watch_local_trainers)."""
    while True:
        code = pod.poll()
        if code is None:
            time.sleep(poll_interval)
            continue
        if code != 0:
            pod.terminate()
        return code


def launch(script: List[str], nproc: int = 1, log_dir: Optional[str] = None,
           elastic: bool = False, max_restarts: int = 3,
           poll_interval: float = 0.5) -> int:
    """Run the pod (optionally under elastic supervision). Returns the
    final exit code."""
    restarts = 0
    while True:
        pod = start_pod(script, nproc, log_dir)
        code = wait_pod(pod, poll_interval)
        if code == 0:
            return 0
        if not elastic or restarts >= max_restarts:
            return code
        restarts += 1
        sys.stderr.write(
            f"[paddle_tpu.launch] pod failed (exit {code}); elastic restart "
            f"{restarts}/{max_restarts}\n")


def elastic_launch(script: List[str], kv_dir: str, job_id: str,
                   min_np: int, max_np: Optional[int] = None,
                   initial_np: Optional[int] = None,
                   log_dir: Optional[str] = None, max_restarts: int = 10,
                   quorum_timeout: float = 60.0,
                   poll_interval: float = 0.2) -> int:
    """Elastic supervision (reference fleet/elastic/manager.py:317 watch
    loop): maintain a pod matching the job's live membership.

    - Membership lives in a FileKVStore; logical node ``n{i}``'s liveness
      is heartbeated by this agent while worker i runs. A worker that
      fails transiently keeps its node (same-np restart); a worker whose
      script marks its node dead (``ElasticManager.mark_dead``) is scaled
      IN — the pod relaunches with np-1 (down to min_np) and ranks
      remapped, surviving workers keeping theirs. Externally registered
      nodes scale the pod OUT (up to max_np) at the next membership check.
    - Every relaunch starts workers that auto-resume from the newest
      checkpoint (CheckpointManager.restore_latest) — the reference pairs
      its relaunch with --auto_checkpoint the same way.

    Returns the final exit code (0 = pod completed).
    """
    from .elastic import ElasticManager, FileKVStore

    kv = FileKVStore(kv_dir)
    mgr = ElasticManager(kv, job_id, min_np, max_np)
    # a fresh launch is a new incarnation of the job: clear the previous
    # run's completion flag and tombstones, else a reused job_id/kv_dir
    # silently starts scaled-in
    kv.delete(f"{mgr.prefix}/completed")
    for h in mgr.dead_hosts():
        mgr.readmit(h)
    n0 = initial_np or mgr.max_np
    for i in range(n0):
        mgr.register(f"n{i}")

    prev_map = None
    restarts = 0
    while True:
        hosts = mgr.wait_for_quorum(quorum_timeout, poll=poll_interval)
        rank_of = mgr.rank_map(hosts, prev_map)
        prev_map = rank_of
        node_of_rank = {r: h for h, r in rank_of.items()}

        def extra_env(rank):
            return {
                "PADDLE_ELASTIC_NODE": node_of_rank[rank],
                "PADDLE_ELASTIC_KV_DIR": kv_dir,
                "PADDLE_ELASTIC_JOB_ID": job_id,
            }

        pod = start_pod(script, nproc=len(hosts), log_dir=log_dir,
                        extra_env_of_rank=extra_env)
        sys.stderr.write(
            f"[paddle_tpu.elastic] pod up np={len(hosts)} "
            f"ranks={rank_of}\n")
        code = None
        scale_event = False
        while code is None:
            code = pod.poll()
            # heartbeat nodes whose worker is alive
            for rank, proc in enumerate(pod.procs):
                if proc.poll() is None:
                    mgr.heartbeat(node_of_rank[rank])
            if code is None:
                # scale-out/in watch: membership vs running pod
                ok, now = mgr.match()
                if ok and set(now) - set(hosts):
                    sys.stderr.write(
                        f"[paddle_tpu.elastic] membership grew to {now}; "
                        "relaunching\n")
                    scale_event = True
                    break
                time.sleep(poll_interval)
        # stop every surviving worker before relaunching: a half-dead pod
        # left running would race the new one on checkpoints and linger on
        # a dead coordinator
        pod.terminate()
        if code == 0:
            mgr.set_completed()
            return 0
        if scale_event:
            # voluntary resize, not a failure — doesn't consume the budget
            continue
        restarts += 1
        if restarts > max_restarts:
            sys.stderr.write(
                f"[paddle_tpu.elastic] giving up after {max_restarts} "
                "restarts\n")
            return code if code else 1
        sys.stderr.write(
            f"[paddle_tpu.elastic] pod exited {code}; restart "
            f"{restarts}/{max_restarts}\n")


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m paddle_tpu.distributed.launch",
        description="paddle_tpu distributed launcher")
    ap.add_argument("--nproc_per_node", type=int, default=1,
                    help="worker processes on this host (TPU: usually 1 — "
                         "one process drives all local chips)")
    ap.add_argument("--log_dir", default=None)
    ap.add_argument("--elastic", action="store_true",
                    help="supervised restarts on worker failure")
    ap.add_argument("--max_restarts", type=int, default=3)
    ap.add_argument("--np", default=None,
                    help="elastic size or range 'min:max' (enables the "
                         "membership manager; reference --elastic_server "
                         "np syntax)")
    ap.add_argument("--elastic_kv_dir", default=None,
                    help="shared directory backing the membership store")
    ap.add_argument("--job_id", default="default")
    ap.add_argument("script", help="training script")
    ap.add_argument("script_args", nargs=argparse.REMAINDER)
    args = ap.parse_args(argv)
    if args.np:
        lo, _, hi = args.np.partition(":")
        min_np, max_np = int(lo), int(hi or lo)
        kv_dir = args.elastic_kv_dir or os.path.join(
            args.log_dir or ".", f"elastic_{args.job_id}")
        return elastic_launch([args.script] + args.script_args,
                              kv_dir=kv_dir, job_id=args.job_id,
                              min_np=min_np, max_np=max_np,
                              log_dir=args.log_dir,
                              max_restarts=args.max_restarts)
    return launch([args.script] + args.script_args,
                  nproc=args.nproc_per_node, log_dir=args.log_dir,
                  elastic=args.elastic, max_restarts=args.max_restarts)


if __name__ == "__main__":
    sys.exit(main())
