"""Distributed launcher — ``python -m paddle_tpu.distributed.launch``.

Parity: reference fleet launcher (python/paddle/distributed/fleet/
launch.py:250 launch_collective — builds a Cluster/Pod, spawns one worker
process per device with PADDLE_* env, watches children, aborts the pod on
failure) and the elastic relaunch loop (fleet/elastic/manager.py:103).

TPU-native process model: ONE worker process per HOST drives all local
chips (the reference's one-proc-per-GPU maps to jax's one-proc-per-host);
``--nproc_per_node`` exists for CPU rehearsal and multi-host emulation.
Workers get the jax.distributed coordinator env (the TCP bootstrap that
replaces the reference's gen_comm_id_helper NCCL-id rendezvous) plus the
PADDLE_* variables reference role-makers read. ``--elastic`` enables
supervised restarts: a failed worker pod is relaunched up to
``--max_restarts`` times, picking up from the newest checkpoint (see
framework/checkpoint.py CheckpointManager.restore_latest).
"""
from __future__ import annotations

import argparse
import os
import signal
import subprocess
import sys
import time
from typing import List, Optional

__all__ = ["launch", "main", "get_cluster_env", "wait_pod"]


def _free_port() -> int:
    import socket

    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def get_cluster_env(rank: int, nproc: int, coordinator: str,
                    endpoints: List[str]) -> dict:
    """Env block for one worker (reference launch_utils.py pod env)."""
    env = dict(os.environ)
    env.update({
        # reference PaddleCloudRoleMaker reads these (role_maker.py:692)
        "PADDLE_TRAINER_ID": str(rank),
        "PADDLE_TRAINERS_NUM": str(nproc),
        "PADDLE_CURRENT_ENDPOINT": endpoints[rank],
        "PADDLE_TRAINER_ENDPOINTS": ",".join(endpoints),
        # jax.distributed bootstrap (replaces NCCL-id TCP rendezvous)
        "JAX_COORDINATOR_ADDRESS": coordinator,
        "JAX_NUM_PROCESSES": str(nproc),
        "JAX_PROCESS_ID": str(rank),
    })
    return env


class Pod:
    """Local worker group (reference launch_utils.py:144 Pod)."""

    def __init__(self, procs: List[subprocess.Popen], log_files: List[str]):
        self.procs = procs
        self.log_files = log_files

    def poll(self) -> Optional[int]:
        """None while all alive; else the first non-zero exit code (0 when
        all exited cleanly)."""
        codes = [p.poll() for p in self.procs]
        if any(c is None for c in codes):
            for c in codes:
                if c not in (None, 0):
                    return c  # fail fast while others still run
            return None
        bad = [c for c in codes if c != 0]
        return bad[0] if bad else 0

    def terminate(self):
        for p in self.procs:
            if p.poll() is None:
                p.send_signal(signal.SIGTERM)
        deadline = time.time() + 10
        for p in self.procs:
            try:
                p.wait(timeout=max(0.1, deadline - time.time()))
            except subprocess.TimeoutExpired:
                p.kill()


def start_pod(script: List[str], nproc: int, log_dir: Optional[str] = None) -> Pod:
    """Spawn nproc workers with cluster env (reference
    start_local_trainers)."""
    coordinator = f"127.0.0.1:{_free_port()}"
    endpoints = [f"127.0.0.1:{_free_port()}" for _ in range(nproc)]
    procs, logs = [], []
    for rank in range(nproc):
        env = get_cluster_env(rank, nproc, coordinator, endpoints)
        stdout = None
        log_path = ""
        if log_dir:
            os.makedirs(log_dir, exist_ok=True)
            log_path = os.path.join(log_dir, f"workerlog.{rank}")
            stdout = open(log_path, "w")
        p = subprocess.Popen([sys.executable] + script, env=env,
                             stdout=stdout,
                             stderr=subprocess.STDOUT if stdout else None)
        procs.append(p)
        logs.append(log_path)
    return Pod(procs, logs)


def wait_pod(pod: Pod, poll_interval: float = 0.5) -> int:
    """Watch children; abort the pod when any worker fails (reference
    launch_utils.py watch_local_trainers)."""
    while True:
        code = pod.poll()
        if code is None:
            time.sleep(poll_interval)
            continue
        if code != 0:
            pod.terminate()
        return code


def launch(script: List[str], nproc: int = 1, log_dir: Optional[str] = None,
           elastic: bool = False, max_restarts: int = 3,
           poll_interval: float = 0.5) -> int:
    """Run the pod (optionally under elastic supervision). Returns the
    final exit code."""
    restarts = 0
    while True:
        pod = start_pod(script, nproc, log_dir)
        code = wait_pod(pod, poll_interval)
        if code == 0:
            return 0
        if not elastic or restarts >= max_restarts:
            return code
        restarts += 1
        sys.stderr.write(
            f"[paddle_tpu.launch] pod failed (exit {code}); elastic restart "
            f"{restarts}/{max_restarts}\n")


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m paddle_tpu.distributed.launch",
        description="paddle_tpu distributed launcher")
    ap.add_argument("--nproc_per_node", type=int, default=1,
                    help="worker processes on this host (TPU: usually 1 — "
                         "one process drives all local chips)")
    ap.add_argument("--log_dir", default=None)
    ap.add_argument("--elastic", action="store_true",
                    help="supervised restarts on worker failure")
    ap.add_argument("--max_restarts", type=int, default=3)
    ap.add_argument("script", help="training script")
    ap.add_argument("script_args", nargs=argparse.REMAINDER)
    args = ap.parse_args(argv)
    return launch([args.script] + args.script_args,
                  nproc=args.nproc_per_node, log_dir=args.log_dir,
                  elastic=args.elastic, max_restarts=args.max_restarts)


if __name__ == "__main__":
    sys.exit(main())
