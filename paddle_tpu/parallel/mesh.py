"""Device mesh management — the TPU-native replacement for NCCL rings.

Parity: reference fleet topology (python/paddle/distributed/fleet/base/
topology.py:36 CommunicateTopology dims ["data","pipe","sharding","model"])
and the ring-id based comm contexts (paddle/fluid/platform/
collective_helper.h:68). One jax.sharding.Mesh with the four Fleet axes
replaces both: a "group" is a mesh axis name, collective placement is
decided by GSPMD, and the TCP unique-id bootstrap (gen_comm_id_helper.cc)
is replaced by jax.distributed's coordinator (multi-host) or nothing at
all (single-host slices).

Axis order is ("data", "sharding", "pipe", "model"): the innermost axis
("model") maps to the most tightly coupled devices so TP collectives ride
the fastest ICI links; "data" is outermost so DP gradient reductions can
cross DCN on multi-slice topologies.
"""
from __future__ import annotations

import threading
from typing import Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh

AXES = ("data", "sharding", "pipe", "model")

_state = threading.local()


def factorize_devices(n: int, dp: int = -1, sharding: int = 1, pp: int = 1,
                      mp: int = 1) -> Tuple[int, int, int, int]:
    """Resolve mesh dims; a -1 dim absorbs the remaining devices."""
    dims = [dp, sharding, pp, mp]
    fixed = int(np.prod([d for d in dims if d != -1]))
    free = [i for i, d in enumerate(dims) if d == -1]
    if len(free) > 1:
        raise ValueError("at most one mesh dim may be -1")
    if free:
        if n % fixed != 0:
            raise ValueError(f"{n} devices not divisible by fixed dims {dims}")
        dims[free[0]] = n // fixed
    if int(np.prod(dims)) != n:
        raise ValueError(f"mesh dims {dims} != device count {n}")
    return tuple(dims)


def create_mesh(dp: int = -1, sharding: int = 1, pp: int = 1, mp: int = 1,
                devices: Optional[Sequence] = None) -> Mesh:
    """Build the 4-axis Fleet mesh over the available devices.

    Like fleet._init_hybrid_parallel_env (reference fleet_base.py:338) but
    the result is a jax Mesh, not a set of NCCL rings.
    """
    devices = list(devices if devices is not None else jax.devices())
    dims = factorize_devices(len(devices), dp, sharding, pp, mp)
    arr = np.array(devices).reshape(dims)
    mesh = Mesh(arr, AXES)
    set_mesh(mesh)
    return mesh


def set_mesh(mesh: Optional[Mesh]):
    _state.mesh = mesh


def get_mesh() -> Optional[Mesh]:
    return getattr(_state, "mesh", None)


def mesh_shape(mesh: Optional[Mesh] = None) -> dict:
    mesh = mesh or get_mesh()
    if mesh is None:
        return {a: 1 for a in AXES}
    return dict(mesh.shape)


class MeshGuard:
    """Context manager installing a mesh as current."""

    def __init__(self, mesh: Mesh):
        self._mesh = mesh

    def __enter__(self):
        self._prev = get_mesh()
        set_mesh(self._mesh)
        self._ctx = self._mesh
        self._ctx.__enter__()
        return self._mesh

    def __exit__(self, *exc):
        self._ctx.__exit__(*exc)
        set_mesh(self._prev)
        return False
