"""DistributedTrainStep — the whole training step as one sharded XLA program.

Replaces the reference's hybrid-parallel step choreography
(fleet/meta_optimizers/dygraph_optimizer/hybrid_parallel_optimizer.py:207:
sharding_reduce_gradients → fused_allreduce_gradients(dp) → inner step, plus
HybridParallelClipGrad's cross-group allreduced global norm :45) with a
single jit: value_and_grad + global-norm clip + a pure optimizer update,
compiled with NamedShardings so XLA emits every reduction the reference
inserted by hand — dp/sharding grad psum, ZeRO reduce-scatter/all-gather,
TP activation collectives.

Optimizer state is sharded by :func:`zero_shard_specs` (ZeRO-1): the update
math runs 1/Nth per device along "sharding"; XLA all-gathers fresh params.
"""
from __future__ import annotations

import functools
from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from ..analysis import sanitizers as _san
from ..core import native as _native
from ..core.native import fast_step as _fast_step
from ..core.native import sanitize as _sanitize
from ..framework.core import AsyncLoss as _AsyncLoss
from ..monitor import benchmark as _bench
from ..monitor import stats as _mstats
from ..monitor.trace import span as _trace_span
from ..resilience import faults as _faults
from ..resilience import sentinel as _sentinel
from .mesh import get_mesh, mesh_shape
from .ring_attention import _shard_map_call
from .sharding import zero_shard_specs

__all__ = ["DistributedTrainStep", "pure_adamw_init", "pure_adamw_update",
           "pure_sgd_init", "pure_sgd_update", "pure_momentum_init",
           "pure_momentum_update", "pure_lamb_init", "pure_lamb_update",
           "pure_lars_init", "pure_lars_update", "global_norm_clip"]


# -- pure optimizers (tree-level) ------------------------------------------

def pure_adamw_init(params, mv_dtype=jnp.float32):
    # m/v default to fp32 regardless of the param dtype (the update math is
    # always fp32). mv_dtype=bf16 halves optimizer-state HBM footprint AND
    # per-step optimizer traffic — bf16 keeps fp32's exponent range, so
    # m/v never over/underflow, only lose mantissa; at LLM scale the freed
    # memory buys a larger batch, which dominates the precision cost (the
    # update still computes in fp32 and stores back rounded). Pass the same
    # mv_dtype to pure_adamw_update so the scan carry dtype is stable.
    zeros = lambda t: jax.tree_util.tree_map(
        lambda x: jnp.zeros(jnp.shape(x), mv_dtype), t)
    return {"m": zeros(params), "v": zeros(params),
            "count": jnp.zeros((), jnp.int32)}


def pure_adamw_update(params, grads, state, lr, beta1=0.9, beta2=0.999,
                      eps=1e-8, weight_decay=0.01, l2_coeff=0.0,
                      mv_dtype=None, decay_mask=None):
    """weight_decay is AdamW's decoupled decay; l2_coeff is classic Adam's
    grad-side L2 (added before the moments, reference Optimizer
    _regularized_grad path). mv_dtype: storage dtype for the moments (None
    = keep whatever pure_adamw_init allocated); math is fp32 either way.
    decay_mask: optional pytree of bools matching params — False leaves
    skip the decoupled decay (reference AdamW apply_decay_param_fun,
    python/paddle/optimizer/adamw.py _append_decoupled_weight_decay)."""
    count = state["count"] + 1
    c = count.astype(jnp.float32)
    bc1 = 1.0 - beta1 ** c
    bc2 = 1.0 - beta2 ** c

    def upd(p, g, m, v, wd):
        g32 = g.astype(jnp.float32)
        store = m.dtype if mv_dtype is None else mv_dtype
        m, v = m.astype(jnp.float32), v.astype(jnp.float32)
        if l2_coeff:
            g32 = g32 + l2_coeff * p.astype(jnp.float32)
        m = beta1 * m + (1 - beta1) * g32
        v = beta2 * v + (1 - beta2) * (g32 * g32)
        step = (m / bc1) / (jnp.sqrt(v / bc2) + eps)
        # decay BEFORE the adam step, matching the reference op order
        # (adamw.py _append_decoupled_weight_decay scales the param first)
        p32 = p.astype(jnp.float32) * (1.0 - lr * wd)
        p32 = p32 - lr * step
        return p32.astype(p.dtype), m.astype(store), v.astype(store)

    flat_p, treedef = jax.tree_util.tree_flatten(params)
    flat_g = treedef.flatten_up_to(grads)
    flat_m = treedef.flatten_up_to(state["m"])
    flat_v = treedef.flatten_up_to(state["v"])
    flat_wd = ([weight_decay] * len(flat_p) if decay_mask is None else
               [weight_decay if dm else 0.0
                for dm in treedef.flatten_up_to(decay_mask)])
    out = [upd(p, g, m, v, wd) for p, g, m, v, wd
           in zip(flat_p, flat_g, flat_m, flat_v, flat_wd)]
    new_p = jax.tree_util.tree_unflatten(treedef, [o[0] for o in out])
    new_m = jax.tree_util.tree_unflatten(treedef, [o[1] for o in out])
    new_v = jax.tree_util.tree_unflatten(treedef, [o[2] for o in out])
    return new_p, {"m": new_m, "v": new_v, "count": count}


def pure_lamb_init(params):
    return pure_adamw_init(params)


def pure_lamb_update(params, grads, state, lr, beta1=0.9, beta2=0.999,
                     eps=1e-6, weight_decay=0.01, decay_mask=None, **_):
    """LAMB (reference operators/optimizers/lamb_op.h
    LambMomentREGUpdateFunctor + LambParamUpateFunctor): Adam moments →
    trust_ratio_div r = m̂/(√v̂+ε) + λp, then a PER-PARAMETER trust ratio
    ‖p‖/‖r‖ (1 when either norm is 0) rescales lr. decay_mask=False
    leaves λ=0 for that leaf (exclude_from_weight_decay_fn)."""
    count = state["count"] + 1
    c = count.astype(jnp.float32)
    bc1 = 1.0 - beta1 ** c
    bc2 = 1.0 - beta2 ** c

    def upd(p, g, m, v, wd):
        g32 = g.astype(jnp.float32)
        p32 = p.astype(jnp.float32)
        m = beta1 * m + (1 - beta1) * g32
        v = beta2 * v + (1 - beta2) * (g32 * g32)
        r = (m / bc1) / (jnp.sqrt(v / bc2) + eps) + wd * p32
        p_norm = jnp.sqrt(jnp.sum(jnp.square(p32)))
        r_norm = jnp.sqrt(jnp.sum(jnp.square(r)))
        trust = jnp.where((p_norm > 0) & (r_norm > 0), p_norm / r_norm, 1.0)
        p32 = p32 - lr * trust * r
        return p32.astype(p.dtype), m, v

    flat_p, treedef = jax.tree_util.tree_flatten(params)
    flat_g = treedef.flatten_up_to(grads)
    flat_m = treedef.flatten_up_to(state["m"])
    flat_v = treedef.flatten_up_to(state["v"])
    flat_wd = ([weight_decay] * len(flat_p) if decay_mask is None else
               [weight_decay if dm else 0.0
                for dm in treedef.flatten_up_to(decay_mask)])
    out = [upd(p, g, m, v, wd) for p, g, m, v, wd
           in zip(flat_p, flat_g, flat_m, flat_v, flat_wd)]
    new_p = jax.tree_util.tree_unflatten(treedef, [o[0] for o in out])
    new_m = jax.tree_util.tree_unflatten(treedef, [o[1] for o in out])
    new_v = jax.tree_util.tree_unflatten(treedef, [o[2] for o in out])
    return new_p, {"m": new_m, "v": new_v, "count": count}


def pure_lars_init(params):
    return pure_momentum_init(params)


def pure_lars_update(params, grads, state, lr, momentum=0.9,
                     lars_coeff=0.001, lars_weight_decay=0.0005,
                     epsilon=0.0, **_):
    """LARS momentum (reference operators/optimizers/lars_momentum_op.h):
    per-parameter local_lr = lr·coeff·‖p‖ / (‖g‖ + λ‖p‖ + ε) when
    λ>0 and both norms >0, else the global lr; velocity over the
    L2-regularized gradient."""

    def upd(p, g, v):
        g32 = g.astype(jnp.float32)
        p32 = p.astype(jnp.float32)
        p_norm = jnp.sqrt(jnp.sum(jnp.square(p32)))
        g_norm = jnp.sqrt(jnp.sum(jnp.square(g32)))
        local_lr = jnp.where(
            (lars_weight_decay > 0) & (p_norm > 0) & (g_norm > 0),
            lr * lars_coeff * p_norm
            / (g_norm + lars_weight_decay * p_norm + epsilon),
            lr)
        nv = momentum * v + local_lr * (g32 + lars_weight_decay * p32)
        p32 = p32 - nv
        return p32.astype(p.dtype), nv

    flat_p, treedef = jax.tree_util.tree_flatten(params)
    flat_g = treedef.flatten_up_to(grads)
    flat_v = treedef.flatten_up_to(state["velocity"])
    out = [upd(p, g, v) for p, g, v in zip(flat_p, flat_g, flat_v)]
    new_p = jax.tree_util.tree_unflatten(treedef, [o[0] for o in out])
    new_v = jax.tree_util.tree_unflatten(treedef, [o[1] for o in out])
    return new_p, {"velocity": new_v, "count": state["count"] + 1}


def pure_sgd_init(params):
    return {"count": jnp.zeros((), jnp.int32)}


def pure_sgd_update(params, grads, state, lr, weight_decay=0.0, **_):
    def upd(p, g):
        g32 = g.astype(jnp.float32)
        if weight_decay:
            g32 = g32 + weight_decay * p.astype(jnp.float32)
        return (p.astype(jnp.float32) - lr * g32).astype(p.dtype)

    new_p = jax.tree_util.tree_map(upd, params, grads)
    return new_p, {"count": state["count"] + 1}


def pure_momentum_init(params):
    # velocity in fp32, like adamw's m/v (see pure_adamw_init)
    return {"velocity": jax.tree_util.tree_map(
        lambda x: jnp.zeros(jnp.shape(x), jnp.float32), params),
        "count": jnp.zeros((), jnp.int32)}


def pure_momentum_update(params, grads, state, lr, momentum=0.9,
                         use_nesterov=False, weight_decay=0.0):
    """SGD with (Nesterov) momentum — matches Momentum._pure_update
    (reference operators/optimizers/momentum_op.h velocity recurrence)."""

    def upd(p, g, v):
        g32 = g.astype(jnp.float32)
        p32 = p.astype(jnp.float32)
        if weight_decay:
            g32 = g32 + weight_decay * p32
        nv = momentum * v + g32
        if use_nesterov:
            p32 = p32 - lr * (g32 + momentum * nv)
        else:
            p32 = p32 - lr * nv
        return p32.astype(p.dtype), nv

    flat_p, treedef = jax.tree_util.tree_flatten(params)
    flat_g = treedef.flatten_up_to(grads)
    flat_v = treedef.flatten_up_to(state["velocity"])
    out = [upd(p, g, v) for p, g, v in zip(flat_p, flat_g, flat_v)]
    new_p = jax.tree_util.tree_unflatten(treedef, [o[0] for o in out])
    new_v = jax.tree_util.tree_unflatten(treedef, [o[1] for o in out])
    return new_p, {"velocity": new_v, "count": state["count"] + 1}


def global_norm_clip(grads, clip_norm: float):
    """Global-norm clip across the WHOLE param set — inside the sharded
    program the partial norms are combined by XLA, which is exactly the
    reference HybridParallelClipGrad's allreduce-across-groups (:45-170)."""
    leaves = jax.tree_util.tree_leaves(grads)
    sq = sum(jnp.sum(jnp.square(g.astype(jnp.float32))) for g in leaves)
    norm = jnp.sqrt(sq)
    scale = jnp.minimum(1.0, clip_norm / (norm + 1e-6))
    return jax.tree_util.tree_map(lambda g: (g * scale).astype(g.dtype), grads), norm


_OPTS = {
    "adamw": (pure_adamw_init, pure_adamw_update),
    "sgd": (pure_sgd_init, pure_sgd_update),
    "momentum": (pure_momentum_init, pure_momentum_update),
    "lamb": (pure_lamb_init, pure_lamb_update),
    "lars": (pure_lars_init, pure_lars_update),
}


def _san_batch_sig(sig):
    """Batch aval sig -> sanitizers leaf-signature format."""
    return tuple((str(i), shape, dtype, False)
                 for i, (shape, dtype) in enumerate(sig))


def _pmean_in_bwd(axes):
    """Identity whose BACKWARD all-reduces the cotangent over ``axes`` —
    applied per param bucket inside shard_map, it issues the dp-grad
    pmean at the exact point the backward produces that bucket's grad,
    so XLA's async collectives overlap it with the REMAINING backward
    compute (the ring-attention per-hop overlap idea applied to the
    gradient all-reduce; FLAGS_overlap_grads)."""

    @jax.custom_vjp
    def ident(x):
        return x

    def fwd(x):
        return x, None

    def bwd(_, g):
        return (jax.lax.pmean(g, axes),)

    ident.defvjp(fwd, bwd)
    return ident


def _spec_shard_dim(spec, axis="sharding"):
    """Index of the dim ``axis`` shards in a PartitionSpec, else None."""
    if not isinstance(spec, P):
        return None
    for d, e in enumerate(tuple(spec)):
        if e == axis or (isinstance(e, (tuple, list)) and axis in e):
            return d
    return None


def _rs_in_bwd(data_axes, shard_axis, dim, deg):
    """Identity whose BACKWARD reduce-scatters the cotangent over
    ``shard_axis`` (and pmeans over ``data_axes``) — the ZeRO-2 form of
    :func:`_pmean_in_bwd` (FLAGS_overlap_zero2): each device keeps only
    ITS 1/deg shard of the bucket's grad, issued in-backward so the
    scatter overlaps remaining backward compute, and the full-size
    reduced gradient never materializes. The cotangent must match the
    primal (full) shape inside shard_map, so the shard lands in a zero
    buffer at this device's offset; the caller slices it back out before
    the shard_map boundary."""

    @jax.custom_vjp
    def ident(x):
        return x

    def fwd(x):
        return x, None

    def bwd(_, g):
        shard = jax.lax.psum_scatter(g, shard_axis, scatter_dimension=dim,
                                     tiled=True)
        if data_axes:
            shard = jax.lax.pmean(shard, data_axes)
        # psum_scatter SUMS over the shard group; match pmean semantics
        shard = shard / deg
        size = shard.shape[dim]
        idx = jax.lax.axis_index(shard_axis)
        buf = jnp.zeros_like(g)
        buf = jax.lax.dynamic_update_slice_in_dim(buf, shard, idx * size,
                                                  dim)
        return (buf,)

    ident.defvjp(fwd, bwd)
    return ident


class DistributedTrainStep:
    """jit(value_and_grad(loss) + clip + optimizer) with Fleet shardings.

    Args:
      loss_fn: pure ``(params, batch) -> scalar loss``.
      params: param pytree (jax arrays).
      param_specs: matching pytree of PartitionSpec (TP/PP placement).
      optimizer: "adamw" | "sgd" | (init_fn, update_fn) pair.
      lr: learning rate — a float, or a callable ``step_index -> float``
        (schedule); either way it enters the compiled step as a traced
        scalar, so schedules do not trigger recompilation.
      batch_spec: PartitionSpec for each batch leaf; default shards the
        leading dim over ("data", "sharding") — the sharding group doubles
        as extra data parallelism, as in reference sharding_optimizer
        hybrid-dp mode (sharding_optimizer.py, hybrid with dp).
      clip_norm: optional global-norm clip.
      zero: ZeRO stage over the "sharding" axis (Rajbhandari et al. 2020).
        ``True``/1 shards optimizer state (the historical default);
        2 additionally pins gradients to the sharded layout (XLA's grad
        reduction becomes a reduce-scatter and the full-size gradient
        never materializes); 3 additionally stores the PARAMETERS
        1/Nth-sharded (all-gathered where the forward consumes them).
        ``False``/0 disables. A ``fleet.auto.ShardedOptimizer`` passed as
        ``optimizer`` carries its own level (and hyperparameters), which
        wins over this argument.
      zero_min_size: parameters smaller than this stay replicated under
        ZeRO (the reference's greedy partition likewise skips tiny
        tensors).
      aux: optional non-trainable state pytree (buffers: BatchNorm running
        stats, quant scales) threaded through the step. When given,
        ``loss_fn`` is ``(params, aux, batch) -> (loss, new_aux)`` and the
        step keeps ``self.aux`` updated — the functional analog of the
        reference's in-place persistable-variable mutation. Default
        replicated; pass aux_specs to shard.
      dynamic_scale: optional dict enabling COMPILED dynamic loss scaling
        (fp16 training) — the in-jit analog of the reference's
        check_finite_and_unscale + update_loss_scaling op pair
        (operators/amp/check_finite_and_unscale_op.cc,
        update_loss_scaling_op.cc): the loss is scaled before the
        backward, grads unscaled, a single all-reduced finite flag gates
        the whole parameter/optimizer update with ``where`` (a skipped
        step costs nothing), and the scale/good/bad counters update in the
        same program. Keys (GradScaler names): init_scale, incr_ratio,
        decr_ratio, incr_every_n_steps, decr_every_n. State lives in
        ``self.scaler_state`` {"scale","good","bad"} (host-readable).
      sentinel: optional resilience.sentinel config (True for defaults):
        a per-step health verdict (loss/grad-norm finiteness + EMA
        z-score spike) computed INSIDE the compiled step; the whole
        update is gated on it (a tripped step is a no-op,
        GradScaler-style) and a device trip counter is carried in
        ``self.sentinel_state`` — no host syncs are added; TrainGuardian
        reads the counter at its own cadence.
    """

    def __init__(self, loss_fn: Callable, params, param_specs,
                 optimizer="adamw", lr: float = 1e-3,
                 batch_spec: P = P(("data", "sharding")),
                 clip_norm: Optional[float] = None, zero=True,
                 mesh=None, opt_kwargs: Optional[dict] = None,
                 aux=None, aux_specs=None,
                 dynamic_scale: Optional[dict] = None,
                 sentinel=None, zero_min_size: int = 2 ** 12):
        self.mesh = mesh or get_mesh()
        if self.mesh is None:
            raise RuntimeError("DistributedTrainStep needs a mesh "
                               "(parallel.create_mesh)")
        if hasattr(optimizer, "fns") and hasattr(optimizer, "level"):
            # fleet.auto.ShardedOptimizer: carries (init, update), the
            # ZeRO level and its hyperparameters
            zero = optimizer.level
            opt_kwargs = {**optimizer.opt_kwargs, **(opt_kwargs or {})}
            optimizer = optimizer.fns()
        if isinstance(optimizer, str):
            init_fn, update_fn = _OPTS[optimizer]
            if _native.fused_optimizer[0] and optimizer in ("adamw",
                                                            "lamb"):
                # FLAGS_fused_optimizer: same init/state layout, the
                # update math as flat-bucket passes (Pallas on TPU)
                from ..ops.fused_optimizer import (fused_adamw_update,
                                                   fused_lamb_update)

                update_fn = (fused_adamw_update if optimizer == "adamw"
                             else fused_lamb_update)
        else:
            init_fn, update_fn = optimizer
        self._update_fn = update_fn
        self._loss_fn = loss_fn
        self._lr = lr
        self._clip = clip_norm
        self._opt_kwargs = dict(opt_kwargs or {})
        self.param_specs = param_specs

        shard_deg = mesh_shape(self.mesh).get("sharding", 1)
        zero_level = (1 if zero is True else 0 if zero is False
                      else int(zero))
        if shard_deg <= 1:
            zero_level = 0
        self.zero_level = zero_level
        opt_state = init_fn(params)
        shapes = jax.tree_util.tree_map(lambda x: tuple(x.shape), params)
        if zero_level >= 1:
            zspecs = zero_shard_specs(param_specs, shapes, shard_deg,
                                      min_size=zero_min_size)
        else:
            zspecs = param_specs
        self._zspecs = zspecs
        # ZeRO-3: parameter STORAGE is 1/Nth-sharded — the jit boundary
        # shardings do the partitioning, XLA all-gathers at first use
        storage_specs = zspecs if zero_level >= 3 else param_specs
        # per-param moment trees (m/v/velocity/...) mirror the
        # (zero-)sharded param layout; scalars (count) replicated
        param_treedef = jax.tree_util.tree_structure(params)

        def _state_spec(v):
            try:
                if jax.tree_util.tree_structure(v) == param_treedef:
                    return zspecs
            except Exception:
                pass
            return jax.tree_util.tree_map(lambda _: P(), v)

        self.opt_specs = {k: _state_spec(v) for k, v in opt_state.items()}

        ns = lambda tree: jax.tree_util.tree_map(
            lambda s: NamedSharding(self.mesh, s), tree,
            is_leaf=lambda x: isinstance(x, P))
        self._param_sh = ns(storage_specs)
        # ZeRO-2: gradients pinned to the sharded layout — the dp/sharding
        # grad reduction lowers to a reduce-scatter at this boundary and
        # the full-size grad buffer never materializes
        self._grad_sh = ns(zspecs) if zero_level >= 2 else self._param_sh
        self._opt_sh = ns(self.opt_specs)
        self._batch_spec = batch_spec

        # defensive copy: device_put may alias caller buffers, and our jit
        # donates params/opt_state — without the copy the caller's arrays
        # would be deleted on the first step.
        params_copy = jax.tree_util.tree_map(lambda x: jnp.array(x), params)
        self.params = jax.device_put(params_copy, self._param_sh)
        self.opt_state = jax.device_put(opt_state, self._opt_sh)

        self._has_aux = aux is not None
        if self._has_aux:
            if aux_specs is None:
                aux_specs = jax.tree_util.tree_map(lambda _: P(), aux)
            self._aux_sh = ns(aux_specs)
            aux_copy = jax.tree_util.tree_map(lambda x: jnp.array(x), aux)
            self.aux = jax.device_put(aux_copy, self._aux_sh)
        else:
            self.aux = None

        batch_sh = NamedSharding(self.mesh, batch_spec)
        self._batch_sh = batch_sh

        self._dyn = dict(dynamic_scale) if dynamic_scale else None
        if self._dyn is not None:
            self.scaler_state = {
                "scale": jnp.float32(self._dyn.get("init_scale", 2.0 ** 15)),
                "good": jnp.int32(0),
                "bad": jnp.int32(0),
            }
        else:
            self.scaler_state = None

        self._sentinel_cfg = (_sentinel.normalize_config(sentinel)
                              if sentinel else None)
        self.sentinel_state = (_sentinel.init_state()
                               if self._sentinel_cfg is not None else None)

        # FLAGS_overlap_grads (read at construction): grads computed
        # under shard_map with a per-bucket pmean issued INSIDE the
        # backward (_pmean_in_bwd), overlapping the dp all-reduce with
        # the remaining backward compute. Only sound when every param is
        # replicated (pure data/sharding mesh, no aux) — other
        # topologies keep the GSPMD path.
        self._overlap_axes = None
        if _native.overlap_grads[0]:
            shape = mesh_shape(self.mesh)
            spec_leaves = jax.tree_util.tree_leaves(
                param_specs, is_leaf=lambda x: isinstance(x, P))
            replicated = all(
                isinstance(s, P) and all(e is None for e in tuple(s))
                for s in spec_leaves)
            if (shape.get("model", 1) == 1 and shape.get("pipe", 1) == 1
                    and not self._has_aux and replicated):
                self._overlap_axes = tuple(
                    a for a in ("data", "sharding") if shape.get(a, 1) > 0)
                n_buckets = len(jax.tree_util.tree_leaves(params))
                _mstats.GRAD_OVERLAP_BUCKETS.add(n_buckets)
        # FLAGS_overlap_zero2 (ISSUE 17): at ZeRO-2+ the in-backward
        # pmean becomes an in-backward reduce-scatter over "sharding" —
        # each bucket's grad leaves the backward already 1/Nth-sharded
        # (the layout ZeRO-2 pins grads to) and the scatter overlaps the
        # remaining backward compute. Off, the overlap path keeps the
        # full pmean exactly as before.
        self._overlap_zero2 = bool(
            _native.overlap_zero2[0] and self._overlap_axes is not None
            and zero_level >= 2 and shard_deg > 1)
        self._shard_deg = shard_deg
        # zspec leaves aligned with the params-tree leaf order (zspecs is
        # built by tree_map over param_specs, so orders agree)
        self._zspec_leaves = jax.tree_util.tree_leaves(
            zspecs, is_leaf=lambda x: isinstance(x, P))

        def step(params, opt_state, aux, batch, lr, scaler_state,
                 sent_state):
            scale = (scaler_state["scale"] if scaler_state is not None
                     else jnp.float32(1.0))

            if self._overlap_axes is not None:
                axes = self._overlap_axes
                ident = _pmean_in_bwd(axes)
                rs2 = self._overlap_zero2
                deg = self._shard_deg
                data_axes = tuple(a for a in axes if a != "sharding")
                zleaves = self._zspec_leaves

                def leaf_ident(spec):
                    d = _spec_shard_dim(spec)
                    if rs2 and d is not None:
                        return _rs_in_bwd(data_axes, "sharding", d, deg)
                    return ident

                def local_step(p, b, sc):
                    def run_local(pp):
                        # per-bucket in-backward collective: each leaf's
                        # grad pmean (or, under FLAGS_overlap_zero2, its
                        # reduce-scatter) launches as soon as the
                        # backward produces it
                        flat, td = jax.tree_util.tree_flatten(pp)
                        flat = [leaf_ident(s)(x)
                                for x, s in zip(flat, zleaves)]
                        pp = jax.tree_util.tree_unflatten(td, flat)
                        loss = self._loss_fn(pp, b)
                        return loss * sc.astype(loss.dtype), loss

                    (_, loss), g = jax.value_and_grad(
                        run_local, has_aux=True)(p)
                    if rs2:
                        # keep only this device's shard of each sharded
                        # bucket (the rest of the zero buffer is dead);
                        # the zspec out_specs reassemble the global grad
                        # in the ZeRO-2 sharded layout
                        idx = jax.lax.axis_index("sharding")
                        flat, td = jax.tree_util.tree_flatten(g)
                        out = []
                        for x, s in zip(flat, zleaves):
                            d = _spec_shard_dim(s)
                            if d is None:
                                out.append(x)
                            else:
                                size = x.shape[d] // deg
                                out.append(jax.lax.dynamic_slice_in_dim(
                                    x, idx * size, size, d))
                        g = jax.tree_util.tree_unflatten(td, out)
                    return jax.lax.pmean(loss, axes), g

                g_specs = self._zspecs if rs2 else P()
                loss, grads = _shard_map_call(
                    local_step, self.mesh,
                    in_specs=(P(), self._batch_spec, P()),
                    out_specs=(P(), g_specs))(params, batch, scale)
                new_aux = aux
            else:
                def run_loss(p):
                    if self._has_aux:
                        loss, new_aux = self._loss_fn(p, aux, batch)
                    else:
                        loss, new_aux = self._loss_fn(p, batch), aux
                    return loss * scale.astype(loss.dtype), (loss, new_aux)

                (_, (loss, new_aux)), grads = jax.value_and_grad(
                    run_loss, has_aux=True)(params)
                # pin grads to the PARAM layout (ZeRO-0/1: the m/v
                # reshard happens here as a reduce-scatter instead of
                # GSPMD propagating the opt-state sharding backward
                # through the loss) or, at ZeRO-2+, directly to the
                # SHARDED layout so the full-size gradient never exists
                grads = jax.tree_util.tree_map(
                    lambda g, s: jax.lax.with_sharding_constraint(g, s),
                    grads, self._grad_sh)
            if scaler_state is not None:
                inv = (1.0 / scale)
                grads = jax.tree_util.tree_map(
                    lambda g: (g.astype(jnp.float32) * inv).astype(g.dtype),
                    grads)
                finite = jnp.array(True)
                for g in jax.tree_util.tree_leaves(grads):
                    finite &= jnp.all(jnp.isfinite(g.astype(jnp.float32)))
            # raw (pre-clip) global grad norm: clipping would cap exactly
            # the spikes the sentinel exists to catch
            sent_gnorm = (_sentinel.global_grad_norm(grads)
                          if sent_state is not None else None)
            if self._clip is not None:
                grads, _ = global_norm_clip(grads, self._clip)
            new_params, new_opt = self._update_fn(
                params, grads, opt_state, lr, **self._opt_kwargs)
            if scaler_state is not None:
                # gate the whole update on the finite flag (reference
                # check_finite_and_unscale semantics: a skipped step leaves
                # params and optimizer state untouched)
                pick = lambda new, old: jax.tree_util.tree_map(
                    lambda a, b: jnp.where(finite, a, b), new, old)
                new_params = pick(new_params, params)
                new_opt = pick(new_opt, opt_state)
                # update_loss_scaling_op counters
                d = self._dyn
                good = jnp.where(finite, scaler_state["good"] + 1, 0)
                bad = jnp.where(finite, 0, scaler_state["bad"] + 1)
                incr = good >= int(d.get("incr_every_n_steps", 1000))
                decr = bad >= int(d.get("decr_every_n", 2))
                new_scale = jnp.where(
                    incr, scale * float(d.get("incr_ratio", 2.0)), scale)
                new_scale = jnp.where(
                    decr,
                    jnp.maximum(scale * float(d.get("decr_ratio", 0.5)), 1.0),
                    new_scale)
                scaler_state = {"scale": new_scale,
                                "good": jnp.where(incr, 0, good),
                                "bad": jnp.where(decr, 0, bad)}
            if sent_state is not None:
                # in-jit health verdict (resilience.sentinel): finiteness
                # + EMA z-spike on the raw global grad norm, then the
                # GradScaler-style gate — a tripped step leaves params,
                # optimizer state and buffers untouched
                sent_state = _sentinel.update(sent_state, loss, sent_gnorm,
                                              self._sentinel_cfg)
                trip = sent_state["last_trip"]
                new_params = _sentinel.gate(trip, new_params, params)
                new_opt = _sentinel.gate(trip, new_opt, opt_state)
                if self._has_aux:
                    new_aux = _sentinel.gate(trip, new_aux, aux)
            return new_params, new_opt, new_aux, loss, scaler_state, \
                sent_state

        repl = NamedSharding(self.mesh, P())
        aux_sh = self._aux_sh if self._has_aux else None
        scaler_sh = ({"scale": repl, "good": repl, "bad": repl}
                     if self._dyn is not None else None)
        sent_sh = (jax.tree_util.tree_map(lambda _: repl,
                                          self.sentinel_state)
                   if self.sentinel_state is not None else None)
        self._step = jax.jit(
            step,
            in_shardings=(self._param_sh, self._opt_sh, aux_sh, batch_sh,
                          repl, scaler_sh, sent_sh),
            out_shardings=(self._param_sh, self._opt_sh, aux_sh, repl,
                           scaler_sh, sent_sh),
            donate_argnums=(0, 1, 2) if self._has_aux else (0, 1),
        )
        self._step_count = 0
        # batch aval signatures already compiled for: keeps the jit
        # cache-hit/compile gauges honest for the compiled-step path (a
        # shape-churning data loader shows up as a jit_compile storm here
        # exactly like an eager recompile storm does in grad_jit_compile)
        self._seen_batch_avals: set = set()
        # FLAGS_fast_step: device-cache the lr scalar between steps — a
        # fresh jnp.float32 per call is a host->device transfer per step
        # that the compiled program then waits on
        self._lr_cache = (None, None)
        # guardian lr_backoff multiplier (scale_lr); 1.0 = untouched
        self._lr_scale = 1.0

    def current_lr(self) -> float:
        if callable(self._lr):
            return float(self._lr(self._step_count)) * self._lr_scale
        return float(self._lr) * self._lr_scale

    def scale_lr(self, scale: float) -> None:
        """Set the ABSOLUTE learning-rate multiplier (TrainGuardian's
        post-rollback backoff). The lr enters the compiled step as a
        traced scalar, so rescaling never recompiles; schedules keep
        their shape, scaled."""
        self._lr_scale = float(scale)

    def __call__(self, batch):
        if _faults.ENABLED[0]:
            # fault-injection hook (FLAGS_fault_inject): may corrupt the
            # batch (nan_grad), sleep (stall), raise (crash), or SIGTERM
            # ourselves (preempt); one list-index check when idle
            batch = _faults.FAULTS.on_train_step(self._step_count, batch)
        lrf = self.current_lr()
        if _fast_step[0]:
            if self._lr_cache[0] != lrf:
                self._lr_cache = (lrf, jnp.float32(lrf))
            lr = self._lr_cache[1]
        else:
            lr = jnp.float32(lrf)
        sig = tuple(
            (tuple(getattr(x, "shape", ())), str(getattr(x, "dtype", "?")))
            for x in jax.tree_util.tree_leaves(batch))
        if sig in self._seen_batch_avals:
            _mstats.JIT_CACHE_HIT.add()
        else:
            if _sanitize[0] and self._seen_batch_avals:
                # recompile explainer (FLAGS_sanitize): name the batch
                # leaf whose aval churned vs the nearest compiled sig
                _san.note_recompile(
                    "DistributedTrainStep", _san_batch_sig(sig),
                    [_san_batch_sig(s) for s in self._seen_batch_avals])
            self._seen_batch_avals.add(sig)
            _mstats.JIT_CACHE_MISS.add()
            _mstats.JIT_COMPILE.add()
        donated = (self.params, self.opt_state,
                   self.aux if self._has_aux else None) \
            if _sanitize[0] else None
        with _trace_span("DistributedTrainStep.step", cat="step",
                         args={"step": self._step_count}):
            with self.mesh:
                (self.params, self.opt_state, self.aux, loss,
                 self.scaler_state, self.sentinel_state) = self._step(
                    self.params, self.opt_state, self.aux, batch, lr,
                    self.scaler_state, self.sentinel_state)
        if donated is not None:
            _san.tombstone_tree(donated)
        self._step_count += 1
        _mstats.TRAIN_STEPS.add()
        if _fast_step[0]:
            # async handle: params/opt-state stay device-resident and the
            # dispatch is not awaited; the first host read of the loss is
            # the sync point (step_async_syncs gauge)
            out = _AsyncLoss(loss)
            if self.sentinel_state is not None:
                out.health = {"trip": self.sentinel_state["last_trip"],
                              "trips": self.sentinel_state["trips"]}
            return out
        return loss

    def loss_scale(self) -> Optional[float]:
        """Current dynamic loss scale (None when scaling is off)."""
        if self.scaler_state is None:
            return None
        return float(self.scaler_state["scale"])

    def state_dict(self) -> dict:
        """Host snapshot {params, opt_state, step}. Sharded leaves
        (ZeRO m/v, ZeRO-3 params) GATHER on the host read, so the
        checkpoint layout is identical to an unsharded run's — sharding
        is placement, not content."""
        import numpy as np

        host = lambda tree: jax.tree_util.tree_map(
            lambda x: np.asarray(x), tree)
        return {"params": host(self.params),
                "opt_state": host(self.opt_state),
                "step": self._step_count}

    def set_state_dict(self, state: dict) -> None:
        """Restore a state_dict (this run's or an unsharded one's): full
        arrays are device_put back through the step's NamedShardings, so
        a ZeRO-sharded step resumes from any checkpoint and vice versa."""
        self.params = jax.device_put(state["params"], self._param_sh)
        self.opt_state = jax.device_put(state["opt_state"], self._opt_sh)
        self._step_count = int(state.get("step", self._step_count))

    def lower(self, batch):
        """Expose the lowered/compiled artifact (assert-on-HLO testing —
        the TPU analog of the reference's assert-on-op-list meta-optimizer
        tests, SURVEY.md §4.6)."""
        return self._step.lower(self.params, self.opt_state, self.aux, batch,
                                jnp.float32(self.current_lr()),
                                self.scaler_state, self.sentinel_state)

    def measure_overlap(self, batch, reps: int = 2) -> dict:
        """Comm-vs-compute overlap diagnostic (FLAGS_overlap_grads).

        Times three programs over the real mesh/batch: (a) the full
        loss+grads including the dp all-reduce, (b) backward COMPUTE
        only (shard_map local grads, no grad collective), (c) the grad
        all-reduce COMM alone over grad-shaped buffers. Overlap quality
        = how much of (c) hides inside (a):
        ``hidden_frac = clamp((compute + comm - step) / comm, 0, 1)``.
        Emits ``overlap.step`` / ``overlap.compute`` / ``overlap.comm``
        trace spans (tools/trace_report.py turns them into a verdict)
        and FLAGS_benchmark rows. Does NOT touch training state."""
        import time as _time

        axes = self._overlap_axes or tuple(
            a for a in ("data", "sharding")
            if mesh_shape(self.mesh).get(a, 1) > 0)
        loss_fn = self._loss_fn
        if self._has_aux:
            aux = self.aux
            loss_fn = lambda p, b: self._loss_fn(p, aux, b)[0]  # noqa: E731

        def full(p, b):
            return jax.grad(lambda pp: loss_fn(pp, b))(p)

        def compute_only(p, b):
            g = jax.grad(lambda pp: loss_fn(pp, b))(p)
            # cheap scalar reduce so nothing is all-gathered: the grad
            # collectives themselves are what (c) measures
            return jax.lax.pmean(
                sum(jnp.sum(jnp.abs(t.astype(jnp.float32)))
                    for t in jax.tree_util.tree_leaves(g)), axes)

        rs2 = getattr(self, "_overlap_zero2", False)
        deg = getattr(self, "_shard_deg", 1)
        data_axes = tuple(a for a in axes if a != "sharding")
        zleaves = getattr(self, "_zspec_leaves", None)

        def comm_only(g):
            if not rs2:
                return jax.tree_util.tree_map(
                    lambda t: jax.lax.pmean(t, axes), g)
            # the EXACT collectives the ZeRO-2 overlap backward issues:
            # reduce-scatter for sharded buckets, pmean for the rest;
            # reduced to a replicated scalar so shapes stay uniform
            flat, _ = jax.tree_util.tree_flatten(g)
            acc = jnp.float32(0.0)
            for x, s in zip(flat, zleaves):
                d = _spec_shard_dim(s)
                if d is None:
                    r = jax.lax.pmean(x, axes)
                else:
                    r = jax.lax.psum_scatter(
                        x, "sharding", scatter_dimension=d, tiled=True)
                    if data_axes:
                        r = jax.lax.pmean(r, data_axes)
                    r = r / deg
                acc += jnp.sum(jnp.abs(r.astype(jnp.float32)))
            return jax.lax.pmean(acc, axes)

        param_sh = self._param_sh
        full_j = jax.jit(full, in_shardings=(param_sh, self._batch_sh),
                         out_shardings=param_sh)
        comp_j = jax.jit(_shard_map_call(
            compute_only, self.mesh, in_specs=(P(), self._batch_spec),
            out_specs=P()))
        comm_j = jax.jit(_shard_map_call(
            comm_only, self.mesh, in_specs=(P(),), out_specs=P()))
        zeros = jax.tree_util.tree_map(
            lambda p: jnp.zeros(p.shape, p.dtype), self.params)

        from ..monitor import trace as _trace

        def timed(name, fn, *args):
            with self.mesh:
                jax.block_until_ready(fn(*args))          # compile+warm
                best = float("inf")
                for _ in range(max(1, reps)):
                    t0 = _time.perf_counter()
                    jax.block_until_ready(fn(*args))
                    best = min(best, _time.perf_counter() - t0)
            if _trace.is_tracing():
                # span duration == measured device time (not re-run)
                _trace.get_writer().add_complete(
                    "overlap.%s" % name, _time.perf_counter() - best,
                    best, cat="overlap", args={"ms": best * 1e3})
            if _bench.enabled():
                _bench.record_op("grad_overlap@%s" % name, best)
            return best * 1e3

        step_ms = timed("step", full_j, self.params, batch)
        compute_ms = timed("compute", comp_j, self.params, batch)
        comm_ms = timed("comm", comm_j, zeros)
        out = {"step_ms": step_ms, "compute_ms": compute_ms,
               "comm_ms": comm_ms, "buckets": len(
                   jax.tree_util.tree_leaves(self.params)),
               "overlap_enabled": self._overlap_axes is not None}
        if comm_ms > 0:
            out["hidden_frac"] = max(
                0.0, min(1.0, (compute_ms + comm_ms - step_ms) / comm_ms))
        return out
