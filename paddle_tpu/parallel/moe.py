"""Mixture-of-Experts with expert parallelism, GSPMD-style.

Parity target: the reference's MoE expert-parallel ops
``global_scatter``/``global_gather`` (reference operators/collective/
global_scatter_op.cc:63-80 — ragged NCCL alltoall routing each token to
its expert's rank) plus the gating that drives them.

TPU-native design (GShard lineage): instead of ragged alltoalls, routing
is expressed as dense dispatch/combine einsums over a FIXED per-expert
capacity, and the expert dim is sharded over a mesh axis — GSPMD then
emits the AllToAll over ICI. Static shapes keep XLA happy; over-capacity
tokens are dropped (their combine weight is 0), which is the standard
capacity-factor trade.

Top-2 gating with the GShard auxiliary load-balance loss
(mean(fraction_tokens_per_expert · mean_gate_prob_per_expert) · E²).
"""
from __future__ import annotations

from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from .sharding import constraint

__all__ = ["top2_gating", "moe_ffn", "moe_init", "moe_param_specs"]


def top2_gating(logits, capacity: int):
    """logits (T, E) → dispatch (T, E, C) float, combine (T, E, C) float,
    aux_loss scalar. Position-in-expert computed with a cumsum rank; tokens
    beyond capacity get zero weight (dropped)."""
    T, E = logits.shape
    probs = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)

    # top-1
    idx1 = jnp.argmax(probs, axis=-1)                      # (T,)
    mask1 = jax.nn.one_hot(idx1, E, dtype=jnp.float32)     # (T, E)
    # top-2: mask out top-1 and take argmax again
    probs_wo1 = probs * (1.0 - mask1)
    idx2 = jnp.argmax(probs_wo1, axis=-1)
    mask2 = jax.nn.one_hot(idx2, E, dtype=jnp.float32)

    # aux load-balance loss (GShard eq. 4) on top-1 assignments
    density = jnp.mean(mask1, axis=0)                      # fraction per expert
    density_proxy = jnp.mean(probs, axis=0)
    aux_loss = jnp.mean(density * density_proxy) * (E * E)

    # positions within each expert's buffer (top-1 ranks first, then top-2)
    pos1 = jnp.cumsum(mask1, axis=0) * mask1 - mask1       # 0-based
    pos2 = (jnp.cumsum(mask2, axis=0) - mask2 +
            jnp.sum(mask1, axis=0, keepdims=True)) * mask2

    keep1 = mask1 * (pos1 < capacity)
    keep2 = mask2 * (pos2 < capacity)

    g1 = jnp.sum(probs * keep1, axis=-1)                   # (T,)
    g2 = jnp.sum(probs * keep2, axis=-1)
    denom = jnp.maximum(g1 + g2, 1e-9)
    g1, g2 = g1 / denom, g2 / denom

    cap1 = jax.nn.one_hot(jnp.sum(pos1, axis=-1).astype(jnp.int32), capacity,
                          dtype=jnp.float32)               # (T, C)
    cap2 = jax.nn.one_hot(jnp.sum(pos2, axis=-1).astype(jnp.int32), capacity,
                          dtype=jnp.float32)

    combine = (g1[:, None, None] * keep1[:, :, None] * cap1[:, None, :] +
               g2[:, None, None] * keep2[:, :, None] * cap2[:, None, :])
    dispatch = (combine > 0).astype(jnp.float32)
    return dispatch, combine, aux_loss


def moe_init(key, n_experts: int, d_model: int, d_ff: int,
             dtype=jnp.float32) -> Dict[str, Any]:
    k1, k2, k3 = jax.random.split(key, 3)
    std = d_model ** -0.5
    return {
        "router_w": (std * jax.random.normal(k1, (d_model, n_experts))).astype(dtype),
        "w_in": (std * jax.random.normal(k2, (n_experts, d_model, d_ff))).astype(dtype),
        "w_out": (std * jax.random.normal(k3, (n_experts, d_ff, d_model))).astype(dtype),
    }


def moe_param_specs(expert_axis: str = "model") -> Dict[str, P]:
    """Experts sharded over ``expert_axis`` — each device group owns
    n_experts / axis_size experts, the EP layout of the reference's
    global_scatter world."""
    return {
        "router_w": P(),
        "w_in": P(expert_axis, None, None),
        "w_out": P(expert_axis, None, None),
    }


def moe_ffn(params, x, capacity_factor: float = 1.25,
            expert_axis: Optional[str] = "model",
            compute_dtype=None,
            groups: Optional[int] = None) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """MoE feed-forward. x (B, S, D) → (y (B, S, D), aux_loss).

    The dispatch einsum + expert-sharded compute + combine einsum is the
    dense equivalent of global_scatter → local expert FFN → global_gather
    (reference global_scatter_op.cc:63-80, global_gather_op.cc).

    ``groups``: tokens are gated in G independent groups (GShard's group
    dim) so dispatch/combine stay (G, Tg, E, C) with C ∝ Tg/E — linear,
    not quadratic, in total token count. Default: smallest G dividing T
    with Tg ≤ 4096.
    """
    B, S, D = x.shape
    E = params["router_w"].shape[-1]
    cd = compute_dtype or x.dtype
    T = B * S
    if groups is None:
        groups = 1
        while T // groups > 4096 and T % (groups * 2) == 0:
            groups *= 2
    if T % groups != 0:
        raise ValueError(f"token count {T} not divisible by groups {groups}")
    Tg = T // groups
    # top-2 routing → up to 2Tg assignments; balanced load is 2Tg/E per expert
    capacity = max(1, int(2 * capacity_factor * Tg / E))

    tokens = x.reshape(groups, Tg, D)
    logits = jnp.einsum("gtd,de->gte", tokens.astype(jnp.float32),
                        params["router_w"].astype(jnp.float32))
    dispatch, combine, aux = jax.vmap(
        lambda lg: top2_gating(lg, capacity))(logits)
    aux = jnp.mean(aux)

    # scatter tokens to (G, E, C, D) expert buffers — GSPMD AllToAll happens
    # here when the expert dim is sharded and tokens are data-sharded
    expert_in = jnp.einsum("gtec,gtd->gecd", dispatch.astype(cd), tokens)
    if expert_axis:
        expert_in = constraint(expert_in, None, expert_axis, None, None)

    h = jnp.einsum("gecd,edf->gecf", expert_in, params["w_in"].astype(cd))
    h = jax.nn.gelu(h)
    expert_out = jnp.einsum("gecf,efd->gecd", h, params["w_out"].astype(cd))
    if expert_axis:
        expert_out = constraint(expert_out, None, expert_axis, None, None)

    y = jnp.einsum("gtec,gecd->gtd", combine.astype(cd), expert_out)
    return y.reshape(B, S, D), aux
