"""Sharding rules: param-name patterns → PartitionSpec.

This replaces the reference's program-rewriting parallel optimizers:
- TensorParallelOptimizer / mp_layers (reference fleet/meta_parallel/
  parallel_layers/mp_layers.py:30-300) hand-inserted c_identity/c_allreduce
  around column/row-split matmuls. Here a rule like
  ``("*.qkv.weight", P(None, "model"))`` makes GSPMD derive the same
  collectives.
- ShardingOptimizer ZeRO (reference fleet/meta_optimizers/
  sharding_optimizer.py:45, dygraph_sharding_optimizer.py:90 greedy param
  partition) → :func:`zero_shard_specs`, which extends each param's spec
  with the "sharding" axis on the first evenly divisible unsharded dim, so
  optimizer slots (and optionally master weights) are stored 1/Nth per
  device — XLA inserts the reduce-scatter/all-gather pair the reference
  built by hand.
"""
from __future__ import annotations

import fnmatch
import re
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from .mesh import get_mesh

__all__ = ["ShardingRules", "apply_rules", "zero_shard_specs", "shard_params",
           "constraint", "named_sharding"]


class ShardingRules:
    """Ordered (glob-pattern → PartitionSpec) table.

    First match wins; unmatched names get the default spec (replicated).
    Patterns match against '/'-joined pytree paths or '.'-joined param
    names — both separators are normalised to '.'.
    """

    def __init__(self, rules: Sequence[Tuple[str, P]] = (),
                 default: P = P()):
        self.rules: List[Tuple[str, P]] = list(rules)
        self.default = default

    def add(self, pattern: str, spec: P):
        self.rules.append((pattern, spec))
        return self

    def spec_for(self, name: str) -> P:
        name = name.replace("/", ".")
        for pat, spec in self.rules:
            if fnmatch.fnmatch(name, pat):
                return spec
        return self.default

    def __repr__(self):
        return f"ShardingRules({self.rules!r}, default={self.default!r})"


def _tree_paths(tree):
    flat, treedef = jax.tree_util.tree_flatten_with_path(tree)
    names = []
    for path, _ in flat:
        parts = []
        for k in path:
            if hasattr(k, "key"):
                parts.append(str(k.key))
            elif hasattr(k, "idx"):
                parts.append(str(k.idx))
            else:
                parts.append(str(k))
        names.append(".".join(parts))
    leaves = [v for _, v in flat]
    return names, leaves, treedef


def apply_rules(tree, rules: ShardingRules):
    """Map a param pytree → pytree of PartitionSpec by name."""
    names, leaves, treedef = _tree_paths(tree)
    specs = [rules.spec_for(n) for n in names]
    return jax.tree_util.tree_unflatten(treedef, specs)


def _spec_axes(spec: P) -> set:
    used = set()
    for entry in spec:
        if entry is None:
            continue
        if isinstance(entry, (tuple, list)):
            used.update(entry)
        else:
            used.add(entry)
    return used


def zero_shard_specs(specs_tree, shapes_tree, degree: int,
                     axis: str = "sharding", min_size: int = 2 ** 12):
    """ZeRO extension: add the sharding axis to each spec on the first
    unsharded dim whose size divides evenly. Small params stay replicated
    (the reference's greedy partition likewise skips tiny tensors by
    grouping on size, dygraph_sharding_optimizer.py:90-114)."""
    if degree <= 1:
        return specs_tree

    def one(spec, shape):
        shape = tuple(shape) if not hasattr(shape, "shape") else tuple(shape.shape)
        if int(np.prod(shape) or 0) < min_size:
            return spec
        used = _spec_axes(spec)
        if axis in used:
            return spec
        entries = list(spec) + [None] * (len(shape) - len(spec))
        for i, (dim, entry) in enumerate(zip(shape, entries)):
            if entry is None and dim % degree == 0:
                entries[i] = axis
                return P(*entries)
        return spec

    return jax.tree_util.tree_map(one, specs_tree, shapes_tree,
                                  is_leaf=lambda x: isinstance(x, P))


def named_sharding(spec: P, mesh: Optional[Mesh] = None) -> NamedSharding:
    mesh = mesh or get_mesh()
    if mesh is None:
        raise RuntimeError("no mesh — call parallel.create_mesh first")
    return NamedSharding(mesh, spec)


def shard_params(tree, specs_tree, mesh: Optional[Mesh] = None):
    """device_put the param pytree with its specs (init-time placement)."""
    mesh = mesh or get_mesh()
    return jax.tree_util.tree_map(
        lambda x, s: jax.device_put(x, NamedSharding(mesh, s)),
        tree, specs_tree,
        is_leaf=lambda x: not isinstance(x, (dict, list, tuple)))


def constraint(x, *spec_entries, mesh: Optional[Mesh] = None):
    """with_sharding_constraint shorthand usable on arrays inside jit.

    The analog of the reference's c_identity/c_split markers: it pins an
    intermediate's layout so GSPMD materialises the intended collective.
    """
    mesh = mesh or get_mesh()
    spec = spec_entries[0] if (len(spec_entries) == 1 and
                               isinstance(spec_entries[0], P)) else P(*spec_entries)
    if mesh is None:
        return x
    return jax.lax.with_sharding_constraint(x, NamedSharding(mesh, spec))
