"""Ring attention — sequence/context parallelism over the mesh.

NEW capability relative to the reference (SURVEY.md §5: the 2021-era
reference has no sequence/context parallelism or ring attention — its
longest-sequence answer is fused attention kernels + TP head splitting).
This is the TPU-native long-context design:

- the sequence dim of Q/K/V is sharded over a mesh axis (any of the Fleet
  axes; by convention "sharding" doubles as the context axis the way
  Megatron-CP reuses a dp subgroup);
- each device computes blockwise attention of its local Q chunk against a
  rotating K/V chunk, accumulating with the online-softmax recurrence (the
  flash-attention update), while K/V hop device-to-device with
  lax.ppermute — XLA lowers the hop to a CollectivePermute over ICI, and
  the [S, S] score matrix never exists globally NOR locally beyond one
  (S_loc × S_loc) block pair;
- the whole ring is a lax.scan, so jax.grad differentiates it (the
  transpose of ppermute is the reverse ring) — no hand-written backward
  schedule.

Causality is enforced per block pair from global chunk indices: a device's
Q chunk attends fully to earlier chunks, triangularly to its own, not at
all to later ones (compute is masked, not skipped — the ring must rotate
anyway; a skip-ahead schedule is a later optimisation).
"""
from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from .mesh import get_mesh

# jax.shard_map is top-level only from 0.5; 0.4.x ships it under
# jax.experimental (same signature)
_shard_map = getattr(jax, "shard_map", None)
if _shard_map is None:
    from jax.experimental.shard_map import shard_map as _shard_map


def _shard_map_call(fn, mesh, in_specs, out_specs):
    """check_rep=False on 0.4.x (its replication checker rejects the
    lax.switch hop branches; the newer vma typing path needs no flag and
    has no such kwarg)."""
    try:
        return _shard_map(fn, mesh=mesh, in_specs=in_specs,
                          out_specs=out_specs, check_rep=False)
    except TypeError:
        return _shard_map(fn, mesh=mesh, in_specs=in_specs,
                          out_specs=out_specs)

__all__ = ["ring_attention", "ring_attention_sharded"]

_NEG_INF = -1e30

def _axis_size(axis_name):
    """jax.lax.axis_size compat (added in jax 0.5): psum of the literal 1
    is evaluated statically from the axis env on 0.4.x."""
    try:
        return jax.lax.axis_size(axis_name)
    except AttributeError:
        return jax.lax.psum(1, axis_name)




def _block_attn(q, k, v, mask, scale):
    """One (S_q × S_k) block: scores + masked logits, returns
    (unnormalised out, rowmax, rowsum) for the online-softmax merge.

    Matmuls run at the INPUT dtype's MXU rate (bf16 in training) with f32
    accumulation (preferred_element_type); softmax statistics and the
    running accumulator stay f32 — same numerics contract as the Pallas
    flash kernel."""
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k,
                   preferred_element_type=jnp.float32) * scale
    s = jnp.where(mask, s, _NEG_INF)
    m = jnp.max(s, axis=-1, keepdims=True)           # (b,h,q,1)
    # guard fully-masked rows (m = -inf → exp(nan))
    m_safe = jnp.maximum(m, _NEG_INF / 2)
    p = jnp.exp(s - m_safe)
    l = jnp.sum(p, axis=-1, keepdims=True)
    o = jnp.einsum("bhqk,bhkd->bhqd", p.astype(v.dtype), v,
                   preferred_element_type=jnp.float32)
    return o, m_safe, l


def ring_attention(q, k, v, axis_name: str, causal: bool = True,
                   scale: Optional[float] = None):
    """Blockwise ring attention; call INSIDE shard_map with the seq dim of
    q/k/v sharded over ``axis_name``. Shapes: (B, H, S_local, D)."""
    n = _axis_size(axis_name)
    idx = jax.lax.axis_index(axis_name)
    s_loc = q.shape[2]
    if scale is None:
        scale = q.shape[-1] ** -0.5

    q_pos = idx * s_loc + jnp.arange(s_loc)          # global q positions

    def tick(carry, step):
        o, m, l, kc, vc = carry
        # the chunk we currently hold started at device (idx - step) % n
        k_chunk = (idx - step) % n
        k_pos = k_chunk * s_loc + jnp.arange(s_loc)
        if causal:
            mask = q_pos[:, None] >= k_pos[None, :]
        else:
            mask = jnp.ones((s_loc, s_loc), bool)
        ob, mb, lb = _block_attn(q, kc, vc, mask[None, None], scale)
        # online-softmax merge of (o,m,l) with the new block
        m_new = jnp.maximum(m, mb)
        alpha = jnp.exp(m - m_new)
        beta = jnp.exp(mb - m_new)
        o = o * alpha + ob * beta
        l = l * alpha + lb * beta
        # rotate K/V one hop around the ring
        perm = [(i, (i + 1) % n) for i in range(n)]
        kc = jax.lax.ppermute(kc, axis_name, perm)
        vc = jax.lax.ppermute(vc, axis_name, perm)
        return (o, m_new, l, kc, vc), None

    b, h, _, d = q.shape
    # mark the zero-init carries as device-varying over the same manual
    # axes as the inputs so the scan carry type matches its output
    # (shard_map vma typing; older jax has neither typeof().vma nor pcast
    # and needs no cast at all)
    try:
        vma = (set(jax.typeof(q).vma) | set(jax.typeof(k).vma)
               | set(jax.typeof(v).vma))
        pcast = jax.lax.pcast
        pv = lambda x: pcast(x, tuple(vma), to="varying")
    except (AttributeError, TypeError):
        pv = lambda x: x
    o0 = pv(jnp.zeros((b, h, s_loc, d), jnp.float32))
    m0 = pv(jnp.full((b, h, s_loc, 1), _NEG_INF, jnp.float32))
    l0 = pv(jnp.zeros((b, h, s_loc, 1), jnp.float32))
    (o, m, l, _, _), _ = jax.lax.scan(
        tick, (o0, m0, l0, k, v), jnp.arange(n))
    out = o / jnp.maximum(l, 1e-30)
    return out.astype(q.dtype)


def ring_attention_sharded(q, k, v, causal: bool = True,
                           seq_axis: str = "sharding",
                           batch_axis: Optional[str] = "data",
                           head_axis: Optional[str] = "model",
                           mesh: Optional[Mesh] = None,
                           scale: Optional[float] = None):
    """shard_map wrapper: q/k/v are global (B, H, S, D) arrays; seq dim
    sharded over ``seq_axis``, batch over ``batch_axis``, heads over
    ``head_axis`` (pass None to keep an axis replicated)."""
    mesh = mesh or get_mesh()
    if mesh is None:
        raise RuntimeError("ring_attention_sharded needs a mesh")
    spec = P(batch_axis, head_axis, seq_axis, None)

    fn = functools.partial(ring_attention, axis_name=seq_axis,
                           causal=causal, scale=scale)
    mapped = _shard_map_call(fn, mesh, (spec, spec, spec), spec)
    return mapped(q, k, v)
