"""Ring attention with FLASH-KERNEL blocks — the full Ring Attention
design (context parallelism whose per-hop block computation is the fused
online-softmax kernel, not a materialized S_loc x S_loc einsum).

This supersedes ring_attention.py's jnp blockwise path for performance:
- per hop, the local Q chunk attends to the visiting K/V chunk through
  the Pallas flash kernel (ops/flash_attention.py) — bf16 MXU matmuls,
  f32 softmax stats, no S^2 buffer even locally;
- hops merge via the (out, lse) log-sum-exp recurrence;
- the BACKWARD is the hand-written ring-attention backward (the
  published algorithm): the forward saves only (out, lse); the backward
  re-rotates K/V and calls the flash BACKWARD kernel per hop with the
  GLOBAL lse/delta — p = exp(s - lse_global) makes every per-hop ds
  exact without storing per-hop probabilities — while dK/dV partial sums
  ride the same ring and arrive home after n hops.

Causality per hop is the chunk relation (earlier = full attention,
own = triangular, later = dead) dispatched by lax.switch over three
statically-compiled block variants — compile-time control flow, not a
runtime mask over dead work.

Off-TPU the block computation falls back to a jnp reference with
identical (out, lse) semantics, so the same code path is testable on the
virtual CPU mesh.

Reference relation: the 2021-era reference has NO sequence/context
parallelism (SURVEY §5) — this is a new capability; the kernel reuse
mirrors how its fused ops share CUDA kernels between fwd/bwd
(operators/fused/fmha_ref.h).
"""
from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

# jax.shard_map is top-level only from 0.5; 0.4.x ships it under
# jax.experimental (same signature)
_shard_map = getattr(jax, "shard_map", None)
if _shard_map is None:
    from jax.experimental.shard_map import shard_map as _shard_map


def _shard_map_call(fn, mesh, in_specs, out_specs):
    """check_rep=False on 0.4.x (its replication checker rejects the
    lax.switch hop branches; the newer vma typing path needs no flag and
    has no such kwarg)."""
    try:
        return _shard_map(fn, mesh=mesh, in_specs=in_specs,
                          out_specs=out_specs, check_rep=False)
    except TypeError:
        return _shard_map(fn, mesh=mesh, in_specs=in_specs,
                          out_specs=out_specs)

from ..ops.flash_attention import (_attention_reference, _flash_backward,
                                   _flash_forward, _on_tpu)
from .mesh import get_mesh

__all__ = ["ring_flash_attention", "ring_flash_attention_sharded"]

_NEG = -1e30

def _axis_size(axis_name):
    """jax.lax.axis_size compat (added in jax 0.5): psum of the literal 1
    is evaluated statically from the axis env on 0.4.x."""
    try:
        return jax.lax.axis_size(axis_name)
    except AttributeError:
        return jax.lax.psum(1, axis_name)



# chunk relations (lax.switch branch indices)
_FULL, _DIAG, _DEAD = 0, 1, 2


def _pick_block(s, cap):
    """Largest multiple of 128 <= cap that tiles s exactly, or None.

    The flash kernels floor-divide the sequence into a grid of
    ``s // block`` blocks — a chunk length that is NOT a multiple of the
    block size (S_local = 640/768/896 with the default 512/1024 blocks)
    would silently compute only the first ``n * block`` rows."""
    for b in range(min(cap, s), 127, -128):
        if s % b == 0:
            return b
    return None


def _supported_by_kernel(q):
    b, h, s, d = q.shape
    return _on_tpu() and s >= 128 and s % 128 == 0 and \
        (d == 64 or d % 128 == 0) and \
        _pick_block(s, 512) is not None and _pick_block(s, 1024) is not None


# -- per-hop forward blocks: (q, k, v) -> (out, lse) -----------------------

def _ref_block_fwd(q, k, v, causal, scale):
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k,
                   preferred_element_type=jnp.float32) * scale
    if causal:
        sl = s.shape[-1]
        mask = jnp.tril(jnp.ones((sl, sl), bool))
        s = jnp.where(mask[None, None], s, _NEG)
    m = jnp.max(s, axis=-1, keepdims=True)
    m = jnp.maximum(m, _NEG / 2)
    p = jnp.exp(s - m)
    l = jnp.sum(p, axis=-1, keepdims=True)
    out = jnp.einsum("bhqk,bhkd->bhqd", p.astype(v.dtype), v,
                     preferred_element_type=jnp.float32) / l
    lse = (m + jnp.log(l))[..., 0]
    return out.astype(q.dtype), lse


def _block_fwd(q, k, v, causal, scale):
    """One block: normalized out + log-sum-exp, both per query row."""
    if _supported_by_kernel(q):
        b, h, s, _ = q.shape
        out, lse = _flash_forward(q, k, v, causal=causal, scale=scale,
                                  block_q=_pick_block(s, 512),
                                  block_k=_pick_block(k.shape[2], 1024))
        return out, lse.reshape(b, h, s)
    return _ref_block_fwd(q, k, v, causal, scale)


# -- per-hop backward blocks -----------------------------------------------

def _ref_block_bwd(q, k, v, out, lse, g, delta, causal, scale):
    """Gradients of one hop given GLOBAL lse/delta (ring-attn backward):
    p = exp(s - lse) is each entry's GLOBAL softmax weight, so per-hop
    contributions sum exactly to the full-attention gradient."""
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k,
                   preferred_element_type=jnp.float32) * scale
    if causal:
        sl = s.shape[-1]
        mask = jnp.tril(jnp.ones((sl, sl), bool))
        s = jnp.where(mask[None, None], s, _NEG)
    p = jnp.exp(s - lse[..., None])
    dv = jnp.einsum("bhqk,bhqd->bhkd", p.astype(g.dtype), g,
                    preferred_element_type=jnp.float32)
    dp = jnp.einsum("bhqd,bhkd->bhqk", g, v,
                    preferred_element_type=jnp.float32)
    ds = p * (dp - delta[..., None]) * scale
    dq = jnp.einsum("bhqk,bhkd->bhqd", ds.astype(k.dtype), k,
                    preferred_element_type=jnp.float32)
    dk = jnp.einsum("bhqk,bhqd->bhkd", ds.astype(q.dtype), q,
                    preferred_element_type=jnp.float32)
    return (dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype))


def _block_bwd(q, k, v, out, lse, g, causal, scale):
    """(dq, dk, dv) for one hop. The TPU path is the Pallas backward
    kernel with the GLOBAL lse (it computes delta = rowsum(g*out)
    internally from the global out, which equals the global delta)."""
    if _supported_by_kernel(q):
        b, h, sq = q.shape[0], q.shape[1], q.shape[2]
        return _flash_backward(q, k, v, out,
                               lse.reshape(b * h, sq, 1), g,
                               causal=causal, scale=scale,
                               block_q=_pick_block(sq, 512),
                               block_k=_pick_block(k.shape[2], 1024))
    delta = jnp.sum(g.astype(jnp.float32) * out.astype(jnp.float32),
                    axis=-1)
    return _ref_block_bwd(q, k, v, out, lse, g, delta, causal, scale)


# -- ring forward/backward (inside shard_map, axis bound) ------------------

def _rel_of(step, idx, n, causal):
    """Chunk relation for the hop holding chunk (idx - step) % n.
    Non-causal attention has no dead hops — every chunk attends fully."""
    k_chunk = (idx - step) % n
    if not causal:
        return jnp.where(k_chunk == idx, _DIAG, _FULL)
    return jnp.where(k_chunk == idx, _DIAG,
                     jnp.where(k_chunk < idx, _FULL, _DEAD))


def _merge(o1, lse1, o2, lse2):
    lse = jnp.logaddexp(lse1, lse2)
    w1 = jnp.exp(lse1 - lse)[..., None]
    w2 = jnp.exp(lse2 - lse)[..., None]
    return (o1.astype(jnp.float32) * w1
            + o2.astype(jnp.float32) * w2), lse


def _ring_fwd_impl(q, k, v, axis_name, causal, scale):
    n = _axis_size(axis_name)
    idx = jax.lax.axis_index(axis_name)
    b, h, s_loc, d = q.shape
    perm = [(i, (i + 1) % n) for i in range(n)]

    def full_b(q, kc, vc):
        return _block_fwd(q, kc, vc, False, scale)

    def diag_b(q, kc, vc):
        return _block_fwd(q, kc, vc, causal, scale)

    def dead_b(q, kc, vc):
        # fresh constants need the same varying manual axes as the live
        # branches' outputs (shard_map vma typing)
        return _pv_like((jnp.zeros_like(q),
                         jnp.full((b, h, s_loc), _NEG, jnp.float32)),
                        (q, kc, vc))

    def tick(carry, step):
        o, lse, kc, vc = carry
        rel = _rel_of(step, idx, n, causal)
        ob, lseb = jax.lax.switch(rel, (full_b, diag_b, dead_b), q, kc, vc)
        o, lse = _merge(o, lse, ob, lseb)
        kc = jax.lax.ppermute(kc, axis_name, perm)
        vc = jax.lax.ppermute(vc, axis_name, perm)
        return (o, lse, kc, vc), None

    o0 = jnp.zeros((b, h, s_loc, d), jnp.float32)
    lse0 = jnp.full((b, h, s_loc), _NEG, jnp.float32)
    o0, lse0 = _pv_like((o0, lse0), (q, k, v))
    (o, lse, _, _), _ = jax.lax.scan(tick, (o0, lse0, k, v),
                                     jnp.arange(n))
    return o.astype(q.dtype), lse


def _ring_bwd_impl(q, k, v, out, lse, g, axis_name, causal, scale):
    n = _axis_size(axis_name)
    idx = jax.lax.axis_index(axis_name)
    perm = [(i, (i + 1) % n) for i in range(n)]

    def full_b(q, kc, vc):
        return _block_bwd(q, kc, vc, out, lse, g, False, scale)

    def diag_b(q, kc, vc):
        return _block_bwd(q, kc, vc, out, lse, g, causal, scale)

    def dead_b(q, kc, vc):
        return _pv_like((jnp.zeros_like(q), jnp.zeros_like(kc),
                         jnp.zeros_like(vc)), (q, kc, vc))

    def tick(carry, step):
        dq, kc, vc, dkc, dvc = carry
        rel = _rel_of(step, idx, n, causal)
        dqb, dkb, dvb = jax.lax.switch(rel, (full_b, diag_b, dead_b),
                                       q, kc, vc)
        dq = dq + dqb.astype(jnp.float32)
        dkc = dkc + dkb.astype(jnp.float32)
        dvc = dvc + dvb.astype(jnp.float32)
        # rotate K/V AND their gradient accumulators together: after n
        # hops the accumulators arrive back at the chunk's owner with
        # every hop's contribution summed
        kc, vc, dkc, dvc = (jax.lax.ppermute(x, axis_name, perm)
                            for x in (kc, vc, dkc, dvc))
        return (dq, kc, vc, dkc, dvc), None

    dq0 = jnp.zeros(q.shape, jnp.float32)
    dk0 = jnp.zeros(k.shape, jnp.float32)
    dv0 = jnp.zeros(v.shape, jnp.float32)
    dq0, dk0, dv0 = _pv_like((dq0, dk0, dv0), (q, k, v))
    (dq, _, _, dk, dv), _ = jax.lax.scan(
        tick, (dq0, k, v, dk0, dv0), jnp.arange(n))
    return dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype)


def _pv_like(zeros_trees, ref_trees):
    """Mark fresh zero carries device-varying over the same manual axes
    as the real inputs (shard_map vma typing; no-op on older jax)."""
    try:
        vma = set()
        for r in ref_trees:
            vma |= set(jax.typeof(r).vma)
        pcast = jax.lax.pcast
        out = []
        for z in zeros_trees:
            need = tuple(vma - set(jax.typeof(z).vma))
            out.append(pcast(z, need, to="varying") if need else z)
        return tuple(out)
    except (AttributeError, TypeError):
        return zeros_trees


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5))
def ring_flash_attention(q, k, v, axis_name, causal=True,
                         scale: Optional[float] = None):
    """Call INSIDE shard_map with the seq dim of q/k/v sharded over
    ``axis_name``. Shapes (B, H, S_local, D); returns (B, H, S_local, D).
    """
    out, _ = _ring_fwd_rule(q, k, v, axis_name, causal, scale)
    return out


def _ring_fwd_rule(q, k, v, axis_name, causal, scale):
    if scale is None:
        scale = q.shape[-1] ** -0.5
    out, lse = _ring_fwd_impl(q, k, v, axis_name, causal, float(scale))
    return out, (q, k, v, out, lse)


def _ring_bwd_rule(axis_name, causal, scale, res, g):
    q, k, v, out, lse = res
    if scale is None:
        scale = q.shape[-1] ** -0.5
    return _ring_bwd_impl(q, k, v, out, lse, g, axis_name, causal,
                          float(scale))


ring_flash_attention.defvjp(_ring_fwd_rule, _ring_bwd_rule)


def ring_flash_attention_sharded(q, k, v, causal: bool = True,
                                 seq_axis: str = "sharding",
                                 batch_axis: Optional[str] = "data",
                                 head_axis: Optional[str] = "model",
                                 mesh: Optional[Mesh] = None,
                                 scale: Optional[float] = None):
    """shard_map wrapper mirroring ring_attention_sharded: global
    (B, H, S, D) arrays, seq dim sharded over ``seq_axis``."""
    mesh = mesh or get_mesh()
    if mesh is None:
        raise RuntimeError("ring_flash_attention_sharded needs a mesh")
    if dict(mesh.shape).get(seq_axis, 1) == 1 and _on_tpu():
        # degenerate ring (context degree 1): no hop to take — the block
        # computation IS full flash attention; skip the shard_map wrapper
        from ..ops.flash_attention import flash_attention_arrays

        return flash_attention_arrays(q, k, v, causal=causal, scale=scale)
    spec = P(batch_axis, head_axis, seq_axis, None)
    fn = functools.partial(ring_flash_attention, axis_name=seq_axis,
                           causal=causal, scale=scale)
    mapped = _shard_map_call(fn, mesh, (spec, spec, spec), spec)
    return mapped(q, k, v)
