"""SPMD pipeline parallelism compiled into one XLA program.

This is the TPU-native answer to the reference's TWO pipeline runtimes:
- static SectionWorker 1F1B (reference paddle/fluid/framework/
  section_worker.cc:61-142: per-stage process runs F then B per microbatch,
  p2p via send_v2/recv_v2 ops), and
- dygraph PipelineParallel (reference fleet/meta_parallel/
  pipeline_parallel.py:80-150: warmup/steady/cooldown loop with NCCL
  isend/irecv pairs).

Design: all stages live in ONE jitted program. Block params are stacked
with a leading stage dim sharded over the "pipe" mesh axis; each schedule
tick applies every stage's layer-stack in parallel (a vmap over the stage
dim — zero cross-stage communication because params and activations are
both pipe-sharded), then rotates the activation buffer one stage forward
with a roll that XLA lowers to a CollectivePermute over ICI. Differentiation
through the schedule gives the backward pipeline for free (the transpose of
a CollectivePermute is the reverse permute), so the 1F1B process choreography
collapses into a lax.scan the compiler software-pipelines.

Schedule (GPipe-style fill/drain, T = n_micro + n_stages - 1 ticks):
  tick t: stage 0 ingests microbatch t (t < n_micro); stage s processes the
  activation it received at tick t-1; stage S-1 emits microbatch t-(S-1).
Bubble fraction = (S-1)/T, same as the reference's F-then-B schedule
(section_worker.cc:139-142); increase n_micro to amortise.

Memory: each tick body runs under jax.checkpoint, so backward saves only
the inter-stage carry per tick and rematerialises the per-layer internals
— peak live activation memory is O(n_stages · act) + O(T · carry), not
O(n_micro · layer_internals). This is the memory property 1F1B exists for
(reference pipeline_parallel.py:80-150 holds ≤ n_stages in-flight
microbatches); the remat trades one extra forward per tick for it, the
standard TPU-side bargain (HBM is the binding constraint, MXU is not).
"""
from __future__ import annotations

from typing import Callable, Optional

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

__all__ = ["stack_stages", "pipeline_forward"]


def stack_stages(block_params, n_stages: int):
    """Reshape leading layer dim L → (n_stages, L // n_stages).

    The analog of the reference's SegmentLayers uniform split
    (fleet/meta_parallel/pp_layers.py:63-130).
    """

    def one(x):
        L = x.shape[0]
        if L % n_stages != 0:
            raise ValueError(f"{L} layers not divisible by {n_stages} stages")
        return x.reshape(n_stages, L // n_stages, *x.shape[1:])

    return jax.tree_util.tree_map(one, block_params)


def pipeline_forward(stage_fn: Callable, stage_params, x_micro,
                     n_stages: int, remat: bool = True,
                     batch_spec=P(("data", "sharding"))):
    """Run the pipeline schedule; returns per-microbatch outputs.

    Args:
      stage_fn: ``(params_one_stage, x) -> y`` applying one stage's layer
        stack; x and y share shape (the inter-stage activation).
      stage_params: pytree with leading dims (n_stages, layers_per_stage,
        ...) — shard dim 0 over the "pipe" mesh axis.
      x_micro: (n_micro, micro_batch, ...) stage-0 inputs.
      n_stages: pipeline depth (mesh "pipe" size).
      batch_spec: sharding of the per-microbatch batch dim. The scan CARRY
        is pinned to P("pipe", batch, ...) — without that, the
        batch→microbatch reshape leaves the data/sharding tiling on the
        time axis and every scan-boundary transition forces the
        partitioner's "involuntary full rematerialization"
        replicate-and-repartition fallback. (Only the carry is pinned:
        constraining x_micro/ys too injects transpose-side constraints
        that conflict with the backward scan's layouts and reintroduce
        the fallback.)

    Returns: (n_micro, micro_batch, ...) final-stage outputs.
    """
    from .mesh import get_mesh
    from .sharding import constraint

    have_mesh = get_mesh() is not None
    batch_entry = tuple(batch_spec)[0] if len(batch_spec) else None
    trailing = (None,) * (x_micro.ndim - 2)
    act_spec = P("pipe", batch_entry, *trailing)        # stage dim on "pipe"

    def pin(x, spec):
        # constraints only make sense inside a jit trace over the mesh;
        # eager/pure-numpy use (tests, CPU debugging) passes through
        if not have_mesh or not isinstance(x, jax.core.Tracer):
            return x
        return constraint(x, spec)

    n_micro = x_micro.shape[0]
    if n_stages == 1:
        return jax.vmap(lambda x: stage_fn(
            jax.tree_util.tree_map(lambda p: p[0], stage_params), x))(x_micro)

    T = n_micro + n_stages - 1
    act_shape = (n_stages,) + x_micro.shape[1:]

    # axis_name lets a stage_fn recover ITS stage index with
    # lax.axis_index("pipe_stage") — the padded non-uniform engine path
    # uses it to mask dead (padding) units per stage
    vstage = jax.vmap(stage_fn, axis_name="pipe_stage")

    # Microbatches ride the scan's xs, zero-padded to T for the drain
    # ticks. Concatenate is used (not a clamped gather): its transpose is
    # a plain slice, so the backward keeps scan-native layouts — a gather
    # here left a scatter-add cotangent whose sharding GSPMD could only
    # fix with the replicate-and-repartition fallback.
    pad = jnp.zeros((n_stages - 1,) + x_micro.shape[1:], x_micro.dtype)
    xs = jnp.concatenate([x_micro, pad], axis=0)

    def tick(acts, xt):
        xt = pin(xt, P(batch_entry, *trailing))
        acts = acts.at[0].set(xt.astype(acts.dtype))
        acts = pin(acts, act_spec)
        # all stages compute in parallel on their held activation
        y = vstage(stage_params, acts)
        # rotate activations one stage forward (XLA: CollectivePermute);
        # emit the last stage's output as this tick's y (scan-stacked, NOT
        # part of the carry — keeps the carry O(n_stages)). The emitted
        # slice leaves the pipe-sharded buffer: pin it to the batch layout
        # so the partitioner reshards directly instead of via its
        # replicate-and-repartition fallback.
        out = pin(y[-1], P(batch_entry, *trailing))
        return pin(jnp.roll(y, shift=1, axis=0), act_spec), out

    acts0 = pin(jnp.zeros(act_shape, x_micro.dtype), act_spec)
    body = jax.checkpoint(tick) if remat else tick
    _, ys = jax.lax.scan(body, acts0, xs)
    # drain: tick t >= n_stages-1 emitted microbatch t-(n_stages-1)
    return ys[n_stages - 1:].astype(x_micro.dtype)
